"""GBDT boosting engine and the `Booster` class.

TPU-native replacement for LightGBM's ``GBDT::TrainOneIter`` driver
(SURVEY.md §3.1): one boosting round = one jitted device program
(grad/hess -> bagging-masked stats -> best-first tree growth -> train-score
update), driven by a host loop that only syncs for early stopping / logging.

Compilation strategy: the round step is cached per *static* configuration
(objective, num_leaves, num_bins, ...) at module level, while every
continuous hyper-parameter (learning_rate, lambda_l1/l2, min_data_in_leaf,
fractions, max_depth) is a traced scalar.  A 108-config sweep with three
distinct ``num_leaves`` values therefore compiles exactly three programs
(SURVEY.md §3.3 TPU mapping), and configs can later be vmapped.
"""

from __future__ import annotations

import functools
from collections.abc import MutableSequence as _MutableSequence
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..config import Params, default_metric_for_objective, parse_params
from ..dataset import Dataset
from ..metrics import get_metric
from ..objectives import Objective, create_objective
from ..ops.lookup import lookup_values
from ..ops.predict import predict_forest_binned, predict_tree_binned
from ..ops.split import SplitContext
from .tree import Tree, grow_tree, pad_tree, renew_leaf_values


class HyperScalars(NamedTuple):
    """Traced per-config scalars fed to the jitted round step."""

    learning_rate: jnp.ndarray
    lambda_l1: jnp.ndarray
    lambda_l2: jnp.ndarray
    min_data_in_leaf: jnp.ndarray
    min_sum_hessian: jnp.ndarray
    min_gain_to_split: jnp.ndarray
    max_depth: jnp.ndarray
    feature_fraction_bynode: jnp.ndarray
    top_rate: jnp.ndarray        # GOSS a (used only when boosting="goss")
    other_rate: jnp.ndarray      # GOSS b
    max_delta_step: jnp.ndarray = 0.0   # |leaf output| cap (<=0 = off)
    path_smooth: jnp.ndarray = 0.0      # child-output smoothing (0 = off)
    linear_lambda: jnp.ndarray = 0.0    # linear-leaf ridge (linear_tree)

    @staticmethod
    def from_params(p: Params) -> "HyperScalars":
        return HyperScalars(
            learning_rate=jnp.float32(p.learning_rate),
            lambda_l1=jnp.float32(p.lambda_l1),
            lambda_l2=jnp.float32(p.lambda_l2),
            min_data_in_leaf=jnp.float32(p.min_data_in_leaf),
            min_sum_hessian=jnp.float32(p.min_sum_hessian_in_leaf),
            min_gain_to_split=jnp.float32(p.min_gain_to_split),
            max_depth=jnp.int32(p.max_depth),
            feature_fraction_bynode=jnp.float32(p.feature_fraction_bynode),
            top_rate=jnp.float32(p.top_rate),
            other_rate=jnp.float32(p.other_rate),
            max_delta_step=jnp.float32(p.max_delta_step),
            path_smooth=jnp.float32(p.path_smooth),
            linear_lambda=jnp.float32(p.linear_lambda),
        )

    def ctx(self) -> SplitContext:
        return SplitContext(
            lambda_l1=self.lambda_l1,
            lambda_l2=self.lambda_l2,
            min_data_in_leaf=self.min_data_in_leaf,
            min_sum_hessian=self.min_sum_hessian,
            min_gain_to_split=self.min_gain_to_split,
            max_delta_step=self.max_delta_step,
            path_smooth=self.path_smooth,
        )


def resolve_hist_dtype(p: Params, n_rows: int) -> str:
    """Histogram matmul precision (static).

    "auto" picks bf16 one-hot matmuls (full-rate MXU, f32 accumulation) once
    the data is large enough that (a) the histogram pass dominates wall time
    and (b) per-bin sums average over enough rows that the ~0.4% bf16
    quantization of per-row grad/hess washes out of the split scores
    (validated against f32 AUC on the Higgs bench).  Small data under
    "auto" resolves to "f32", which the fused TPU kernel serves as a hi/lo
    bf16 split (2 passes, ~1e-5 relative).  An EXPLICIT
    ``hist_dtype="f32"`` request is a contract for exactness (ADVICE r3):
    it resolves to "f32x", which bypasses the fused kernel for the true
    Precision.HIGHEST path unless ``hist_impl="pallas"`` is also forced.
    """
    if p.use_quantized_grad:
        # upstream's quantized-gradient training: reduced-precision
        # histogram accumulation.  bf16 MXU inputs are the FAST reduced
        # mode on this chip: a true int8 path exists (hist_dtype="int8",
        # stochastic rounding + exact int32 accumulation) but Mosaic's
        # int8 relayouts force a 4x smaller row chunk and it measured
        # 17.8 ms/pass vs bf16's 10.5 at the Higgs shape
        return "bf16"
    d = p.extra.get("hist_dtype", "auto")
    if d != "auto":
        return "f32x" if d == "f32" else d
    return "bf16" if n_rows >= (1 << 19) else "f32"


def check_int8_row_limit(p: Params, n_rows: int, n_shards: int = 1) -> None:
    """Fail fast when ``hist_dtype='int8'`` cannot accumulate exactly.

    The kernel-level guard (``hist_fused_pallas``) catches this too, but
    only at trace time inside the compiled round — by which point the
    user has paid dataset binning and sharding.  This check runs once per
    ``update()`` with the Booster's own shard count, so oversized int8
    configs die with a clear message before any lowering.
    """
    if resolve_hist_dtype(p, n_rows) != "int8":
        return
    from ..ops.histogram_pallas import INT8_ACC_ROW_LIMIT

    per_shard = -(-n_rows // max(int(n_shards), 1))
    if per_shard > INT8_ACC_ROW_LIMIT:
        raise ValueError(
            f"hist_dtype='int8' with {per_shard:,} rows per device shard "
            f"(n={n_rows:,} over {n_shards} shard(s)) exceeds the exact "
            f"int32 accumulation limit of {INT8_ACC_ROW_LIMIT:,} rows — "
            f"histograms would silently wrap.  Use hist_dtype='bf16' or "
            f"train on more devices.")


def _exact_overgrow_target(num_leaves: int, width: int, over: float) -> int:
    """Wave-aligned overgrowth target for the exact tail.

    Every histogram pass costs the same whether it retires 2 or ``width``
    splits, so an overgrowth target that lands mid-wave buys its last few
    candidate nodes at the price of a full pass.  Walk the greedy wave
    schedule (same recurrence as the grower: wave size = min(frontier
    doubling, width)) and pick the wave boundary closest to
    ``num_leaves * over`` in log space, bounded to (num_leaves, 2.5x].
    """
    import math

    target = max(num_leaves * over, num_leaves + 1)
    leaves, cand = 1, 1
    best = None
    while leaves < 2.5 * num_leaves:
        s = min(cand, width)
        leaves += s
        cand = min(cand * 2, leaves)
        if leaves > num_leaves:
            if best is None or (abs(math.log(leaves / target))
                                < abs(math.log(best / target))):
                best = leaves
    return best or int(math.ceil(target))


def resolve_wave_width(p: Params, n_rows: int) -> int:
    """Pick the grower's splits-per-histogram-pass (static).

    ``grow_policy="leafwise"`` forces strict best-first (1) — use it when
    LightGBM-exact split ORDER matters (wave growth picks each wave's split
    set before scoring that wave's children, which can allocate the leaf
    budget differently when it binds mid-wave; predictive quality is
    equivalent in tests).  "frontier" forces wave growth.  "auto" defaults
    to waves for any non-toy workload (>= 4096 rows and >= 16 leaves):
    every histogram pass has a large fixed cost on the TPU runtime, and a
    wave retires up to ``width`` splits per pass instead of one (the strict
    grower's ``num_leaves - 1`` passes are the round-time ceiling — VERDICT
    r1 item 3).  Default width 42 keeps the segment-folded one-hot matmul
    at 3*42=126 lanes — inside one 128-lane MXU tile, so a wave costs about
    the same as a single strict trip.
    """
    if p.grow_policy == "leafwise":
        return 1
    width = int(p.extra.get("wave_width", 0)) or min(42, p.num_leaves - 1)
    # clamp below the exact-mode encoding base (1024): an unclamped user
    # width would collide with the overgrow_leaves*1024 encoding and
    # silently misroute the grower (code review r5); >512 lanes is far
    # past the MXU tile sweet spot anyway
    width = max(1, min(width, 512))
    # wave_tail — how the wave schedule spends the tail of the leaf
    # budget, where wave and strict best-first order can diverge:
    #   "exact"  — overgrow greedily ~2x past num_leaves, then replay
    #     strict best-first selection over the realized gains and prune
    #     (models/tree.py _exact_prune).  LightGBM-exact split ORDER at
    #     ~one extra histogram pass over greedy; r4's gap decomposition
    #     proved split order was the ENTIRE residual quality gap of the
    #     old near-strict tail (PERF.md), so this is the default
    #     wherever order can matter: large data (the AUC-parity north
    #     star), budget-saturating small data, and every ranking
    #     objective (rank lambdas are tail-order-sensitive: the greedy
    #     tail costs ~6e-2 NDCG@10 on the MSLR bench).
    #   "greedy" — whole remaining budget per wave, fewest passes.
    #     Default only for mid-size pointwise tasks whose budget is far
    #     from saturating the rows — r4 measured the diamonds shape
    #     (46k rows, nl=31, ~1.5k rows/leaf) quality-NEUTRAL across
    #     half/greedy/strict while greedy is 1.44x faster.
    #   "half"   — at most half the remaining budget per wave
    #     (near-strict tail, r3's compromise; kept for compatibility).
    # Encoding (static width int, rides all existing plumbing): negative
    # = greedy; >= 1024 = exact (overgrow_leaves * 1024 + width).
    rows_per_leaf = n_rows // max(p.num_leaves, 1)
    # objective "none" = user-supplied fobj whose tail-order sensitivity
    # is unknown (a custom ranking loss would silently eat the greedy
    # tail's ~6e-2 NDCG cost) — classify it conservatively (ADVICE r4)
    pointwise = p.objective not in ("lambdarank", "rank_xendcg", "none")
    default_tail = ("greedy" if pointwise and rows_per_leaf >= 1024
                    and n_rows < (1 << 19) else "exact")
    tail = str(p.extra.get("wave_tail", default_tail))
    if tail == "greedy":
        width = -width
    elif tail == "exact":
        # default overgrowth 2.0: the r5 on-chip gap-vs-overgrow sweep
        # converged at ~2x (Higgs-1M: 1.5x -> +8.6e-4 vs oracle, 2.0x ->
        # +0.3..2.1e-4 across oracle draws, 2.5x no better), and at 2x
        # the 11M throughput still clears the 5x north star with the
        # partition-fused kernel (PERF.md r5)
        over = float(p.extra.get("wave_overgrow", 2.0))
        l_over = _exact_overgrow_target(p.num_leaves, width, over)
        width = l_over * 1024 + width
    if p.grow_policy == "frontier":
        return width
    return width if (n_rows >= 4096 and p.num_leaves >= 16) else 1


def _objective_static_key(obj: Objective, p: Params) -> tuple:
    """Hashable key identifying the objective for the jit-compile cache.

    The custom-loss callable rides in the key itself (callables hash by
    identity), so user fobj objectives get their own cached program instead
    of crashing the rebuild path.

    Group-based objectives (lambdarank) carry per-training packed group
    tensors that cannot be rebuilt from scalars, so the prepared instance
    itself IS the key (hashes by identity — one compiled program per
    training, which is inevitable anyway since the [Q, G] layout is shape-
    defining).
    """
    if getattr(obj, "needs_group", False):
        return ("__group_objective__", obj)
    return (
        obj.name,
        p.sigmoid,
        getattr(obj, "pos_weight", 1.0),
        p.alpha,
        p.fair_c,
        p.poisson_max_delta_step,
        p.lambdarank_truncation_level,
        p.lambdarank_norm,
        p.num_class,
        p.extra.get("fobj"),
        p.tweedie_variance_power,
    )


def _build_cat_info(cat_key, num_features: int):
    """Static cat_key -> traced CatInfo (None passthrough).

    cat_key = (tuple of categorical column indices, cat_smooth, cat_l2,
    max_cat_threshold) — static so the compiled program specializes on
    WHICH columns take subset splits.
    """
    if cat_key is None:
        return None
    from ..ops.split import CatInfo

    idx, smooth, l2, mct = cat_key
    is_cat = jnp.zeros(num_features, bool).at[jnp.asarray(idx)].set(True)
    return CatInfo(is_cat=is_cat, cat_smooth=jnp.float32(smooth),
                   cat_l2=jnp.float32(l2), max_cat_threshold=int(mct))


def _rebuild_objective(key: tuple) -> Objective:
    if key and key[0] == "__group_objective__":
        return key[1]
    (name, sigmoid, pos_weight, alpha, fair_c, pmd, trunc, norm, num_class,
     fobj, tvp) = (key + (None, 1.5))[:11]
    p = Params(
        objective="none" if fobj is not None else name,
        sigmoid=sigmoid, alpha=alpha, fair_c=fair_c,
        poisson_max_delta_step=pmd, lambdarank_truncation_level=trunc,
        lambdarank_norm=norm, num_class=max(num_class, 1),
        tweedie_variance_power=tvp,
    )
    if fobj is not None:
        p.extra["fobj"] = fobj
    obj = create_objective(p)
    if hasattr(obj, "pos_weight"):
        obj.pos_weight = pos_weight
    return obj


def _goss_compact_round(bins, y, w, bag, pred, fmask, hyper: HyperScalars,
                        key, g, h, goss_k, num_leaves, num_bins, hist_impl,
                        row_chunk, hist_dtype, wave_width, cat_info,
                        renew_alpha, axis_name=None, sample_key=None,
                        mono=None, extra_trees=False, col_bins=None,
                        renew_scale=None, ic_member=None,
                        bynode_off=False, hist_merge="psum", n_shards=1,
                        voting_k=0, hist_wire="f32", merge_chunks=4):
    """One compacted GOSS round (shared by the per-round and scanned paths
    — the two MUST stay in RNG lockstep for fused == host training).

    Unlike CPU LightGBM (where skipping rows is free), a TPU histogram pass
    costs the same for masked rows as for live ones — so the sampled subset
    is GATHERED into a dense [k_top + k_other, F] matrix and the tree grown
    on that, cutting histogram cost by ~(top_rate + other_rate).  Train
    scores for ALL rows then come from one traversal pass."""
    from ..ops.sampling import approx_top_mask

    k_top, k_other = goss_k
    n = bins.shape[0]
    if sample_key is None:
        sample_key = key  # sampling and growth share one stream (serial)
    valid = bag > 0
    # sort-free selection (a 1M-row lax.top_k is a ~7 s device sort and
    # long fused GOSS programs crashed the runtime watchdog): histogram-
    # threshold masks, then prefix-sum compaction into the static buffers
    is_top = approx_top_mask(jnp.where(valid, jnp.abs(g), 0.0), valid,
                             k_top)
    rest = valid & ~is_top
    u = jax.random.uniform(jax.random.fold_in(sample_key, 0x7FFFFFFF), (n,))
    sampled = approx_top_mask(jnp.where(rest, 1.0 - u, 0.0), rest, k_other)

    def compact_idx(mask, k):
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        idx = jnp.zeros(k, jnp.int32).at[
            jnp.where(mask, pos, k)].set(lax.iota(jnp.int32, n),
                                         mode="drop")
        filled = lax.iota(jnp.int32, k) < jnp.sum(mask.astype(jnp.int32))
        return idx, filled.astype(jnp.float32)

    top_idx, top_fill = compact_idx(is_top, k_top)
    other_idx, other_fill = compact_idx(sampled, k_other)
    idx = jnp.concatenate([top_idx, other_idx])         # [k]
    amp = (1.0 - hyper.top_rate) / jnp.maximum(hyper.other_rate, 1e-12)
    wt = jnp.concatenate([top_fill, other_fill * amp])
    # when live rows < the static k (small or heavily padded shards), the
    # unfilled buffer slots point at row 0 with weight 0 — mask their count
    # (their g/h are already zero via the sample weights) so they cannot
    # pollute min_data_in_leaf gating
    live = (bag[idx] > 0).astype(jnp.float32) * (wt > 0)
    wt = wt * live
    bins_c = jnp.take(bins, idx, axis=0)
    stats = jnp.stack([g[idx] * wt, h[idx] * wt, live], axis=-1)
    tree, rl_c = grow_tree(
        bins_c, stats, fmask, hyper.ctx(), num_leaves, num_bins,
        hyper.max_depth, ff_bynode=(None if bynode_off else hyper.feature_fraction_bynode), key=key,
        hist_impl=hist_impl, row_chunk=row_chunk, hist_dtype=hist_dtype,
        wave_width=wave_width, cat_info=cat_info, axis_name=axis_name,
        mono=mono, extra_trees=extra_trees, col_bins=col_bins,
        ic_member=ic_member, fuse_partition=True, hist_merge=hist_merge,
        n_shards=n_shards, voting_k=voting_k, hist_wire=hist_wire,
        merge_chunks=merge_chunks)
    if renew_alpha is not None:
        rw = w[idx] * wt
        if renew_scale is not None:
            rw = rw * renew_scale(y[idx])
        tree = renew_leaf_values(tree, rl_c, y[idx] - pred[idx],
                                 rw, renew_alpha)
    # convergence-checked traversal (depth_cap=None): iterates the tree's
    # ACTUAL depth — the num_leaves-deep static scan was 3.7 s/round at
    # 500k rows (r5 trace), ~10x the whole histogram work, and any
    # optimistic static bound is unsound under stalled waves
    new_pred = pred + hyper.learning_rate * predict_tree_binned(
        tree, bins, None)
    return tree, new_pred



def mc_round_update(grow_one, g, h, keys, pred, learning_rate):
    """Shared multiclass round: one tree per class via a vmapped grower.

    The class axis vmaps over ``grow_one`` (per-class histogram psums /
    split-exchange all_gathers batch into one collective under mesh
    learners), and the prediction update is one batched
    ``leaf_value[row_leaf]`` lookup.  Callers own their RNG chain: the
    ``keys`` argument must already match the host loop's fold/split
    sequence, or fused/mesh training would diverge from serial."""
    trees, row_leafs = jax.vmap(grow_one, in_axes=(1, 1, 0))(g, h, keys)
    deltas = jax.vmap(lambda t, rl: lookup_values(
        rl, t.leaf_value))(trees, row_leafs)            # [K, n]
    return trees, pred + learning_rate * deltas.T


@functools.lru_cache(maxsize=None)
def _round_fn(obj_key: tuple, num_leaves: int, num_bins: int,
              hist_impl: str, row_chunk: int, is_rf: bool,
              num_class: int = 1, hist_dtype: str = "f32",
              wave_width: int = 1, goss_k: Optional[Tuple[int, int]] = None,
              cat_key: Optional[tuple] = None,
              mono_key: Optional[tuple] = None, extra_trees: bool = False,
              nbins_key: Optional[tuple] = None,
              linear_k: Optional[int] = None,
              ic_key: Optional[tuple] = None,
              bynode_off: bool = False):
    """goss_k: static (k_top, k_other) row counts enabling the compacted
    GOSS path; None = plain gbdt/rf.  cat_key: static categorical-split
    configuration (see _build_cat_info).  mono_key: static per-feature
    monotone constraints tuple (upstream ``monotone_constraints``).
    bynode_off: statically true when feature_fraction_bynode == 1.0 — the
    growers then skip the per-node threefry draw entirely (kernel-count
    savings at small shapes)."""
    obj = _rebuild_objective(obj_key)
    is_goss = goss_k is not None
    renew_alpha = getattr(obj, "renew_alpha", None)
    renew_scale = getattr(obj, "renew_scale", None)
    mono_arr = (None if mono_key is None
                else jnp.asarray(mono_key, jnp.int32))
    colb = (None if nbins_key is None
            else jnp.asarray(nbins_key, jnp.int32))
    ic_member = (None if ic_key is None else jnp.asarray(ic_key, bool))

    def goss_bag(key, g, bag, hyper):
        """GOSS as row re-weighting (multiclass path): top-|g| keep +
        amplified sample of the rest (SURVEY.md §2C; VERDICT r1 item 5)."""
        from ..ops.sampling import goss_weights
        g_abs = jnp.abs(g) if g.ndim == 1 else jnp.sum(jnp.abs(g), axis=-1)
        return goss_weights(key, g_abs, bag, hyper.top_rate,
                            hyper.other_rate, jnp.sum(bag))

    if num_class > 1:
        # one tree per class per round, grown simultaneously: the class axis
        # is a vmapped batch over the grower (SURVEY.md §7 batching design)
        @jax.jit
        def round_fn_mc(bins, y, w, bag, pred, feature_mask,
                        hyper: HyperScalars, key):
            g, h = obj.grad_hess(pred, y, w)          # [n, K]
            if is_goss:
                bag = goss_bag(jax.random.fold_in(key, 0x7FFFFFFF), g, bag, hyper)

            def grow_one(gc, hc, kc):
                stats = jnp.stack([gc * bag, hc * bag,
                                   (bag > 0).astype(jnp.float32)], axis=-1)
                return grow_tree(
                    bins, stats, feature_mask, hyper.ctx(), num_leaves,
                    num_bins, hyper.max_depth,
                    ff_bynode=(None if bynode_off else hyper.feature_fraction_bynode), key=kc,
                    hist_impl=hist_impl, row_chunk=row_chunk,
                    hist_dtype=hist_dtype, wave_width=wave_width,
                    cat_info=_build_cat_info(cat_key, bins.shape[1]),
                    mono=mono_arr, extra_trees=extra_trees, col_bins=colb,
                    ic_member=ic_member)

            return mc_round_update(grow_one, g, h,
                                   jax.random.split(key, num_class), pred,
                                   hyper.learning_rate)

        return round_fn_mc

    if is_goss:  # single-class: compacted GOSS (mc handled above, masked)

        @jax.jit
        def round_fn_goss(bins, y, w, bag, pred, feature_mask,
                          hyper: HyperScalars, key):
            g, h = obj.grad_hess(pred, y, w)
            return _goss_compact_round(
                bins, y, w, bag, pred, feature_mask, hyper, key, g, h,
                goss_k, num_leaves, num_bins, hist_impl, row_chunk,
                hist_dtype, wave_width,
                _build_cat_info(cat_key, bins.shape[1]), renew_alpha,
                mono=mono_arr, extra_trees=extra_trees, col_bins=colb,
                renew_scale=renew_scale, ic_member=ic_member,
                bynode_off=bynode_off)

        return round_fn_goss

    if linear_k is not None:
        from .tree import fit_linear_leaves

        @jax.jit
        def round_fn_linear(bins, y, w, bag, pred, feature_mask,
                            hyper: HyperScalars, key, xraw):
            """linear_tree round: constant-leaf growth on binned codes,
            then every leaf refits a ridge model over its path features on
            the RAW values (tree.fit_linear_leaves) — the Newton constant
            remains the fallback for degenerate leaves."""
            g, h = obj.grad_hess(pred, y, w)
            stats = jnp.stack(
                [g * bag, h * bag, (bag > 0).astype(jnp.float32)], axis=-1)
            tree, row_leaf = grow_tree(
                bins, stats, feature_mask, hyper.ctx(), num_leaves,
                num_bins, hyper.max_depth,
                ff_bynode=(None if bynode_off else hyper.feature_fraction_bynode),
                key=key, hist_impl=hist_impl, row_chunk=row_chunk,
                hist_dtype=hist_dtype, wave_width=wave_width,
                cat_info=_build_cat_info(cat_key, bins.shape[1]),
                mono=mono_arr, extra_trees=extra_trees, col_bins=colb,
                ic_member=ic_member, fuse_partition=True)
            tree, delta = fit_linear_leaves(
                tree, row_leaf, xraw, g, h, bag, hyper.linear_lambda,
                linear_k, row_chunk)
            new_pred = pred + hyper.learning_rate * delta
            return tree, new_pred

        return round_fn_linear

    @jax.jit
    def round_fn(bins, y, w, bag, pred, feature_mask, hyper: HyperScalars,
                 key):
        g, h = obj.grad_hess(pred, y, w)
        stats = jnp.stack([g * bag, h * bag, (bag > 0).astype(jnp.float32)],
                          axis=-1)
        tree, row_leaf = grow_tree(
            bins, stats, feature_mask, hyper.ctx(), num_leaves, num_bins,
            hyper.max_depth, ff_bynode=(None if bynode_off else hyper.feature_fraction_bynode),
            key=key, hist_impl=hist_impl, row_chunk=row_chunk,
            hist_dtype=hist_dtype, wave_width=wave_width,
            cat_info=_build_cat_info(cat_key, bins.shape[1]),
            mono=mono_arr, extra_trees=extra_trees, col_bins=colb,
            ic_member=ic_member, fuse_partition=True)
        if renew_alpha is not None:
            rw = w * bag if renew_scale is None else w * bag * renew_scale(y)
            tree = renew_leaf_values(tree, row_leaf, y - pred, rw,
                                     renew_alpha)
        shrink = jnp.where(is_rf, 1.0, hyper.learning_rate)
        new_pred = pred + shrink * lookup_values(row_leaf, tree.leaf_value)
        return tree, new_pred

    return round_fn


@functools.lru_cache(maxsize=None)
def _multi_round_fn(obj_key: tuple, num_leaves: int, num_bins: int,
                    hist_impl: str, row_chunk: int, is_rf: bool,
                    hist_dtype: str, wave_width: int, n_rounds: int,
                    bagging_freq: int, use_ff: bool,
                    cat_key: Optional[tuple] = None,
                    goss_k: Optional[Tuple[int, int]] = None,
                    mono_key: Optional[tuple] = None,
                    extra_trees: bool = False,
                    nbins_key: Optional[tuple] = None,
                    ic_key: Optional[tuple] = None,
                    bynode_off: bool = False):
    """``n_rounds`` boosting rounds as ONE device program (`lax.scan`).

    The host round loop pays a dispatch round-trip per boosting round —
    ~20 ms through the remote-TPU tunnel, which dominates wall time on
    reference-sized data (the diamonds bench spends 30 strict histogram
    trips of microseconds each per round).  Scanning rounds on device
    removes that entirely; trees come back stacked with a leading
    [n_rounds] axis.  RNG streams match the host loop exactly (same
    fold_in(key, round_index) chain), so fused and host training produce
    identical models.
    """
    obj = _rebuild_objective(obj_key)
    renew_alpha = getattr(obj, "renew_alpha", None)
    renew_scale = getattr(obj, "renew_scale", None)
    mono_arr = (None if mono_key is None
                else jnp.asarray(mono_key, jnp.int32))
    colb = (None if nbins_key is None
            else jnp.asarray(nbins_key, jnp.int32))
    ic_member = (None if ic_key is None else jnp.asarray(ic_key, bool))

    @jax.jit
    def multi(bins, y, w, bag0, pred0, hyper: HyperScalars, round_key,
              bag_key, ff_key, row_mask, num_data, start_iter, bag_frac, ff):
        num_features = bins.shape[1]

        def body(carry, i):
            pred, bag = carry
            if bagging_freq > 0:
                from ..ops.sampling import sample_bag

                bag = lax.cond(
                    i % bagging_freq == 0,
                    lambda _: sample_bag(
                        jax.random.fold_in(bag_key, i), row_mask,
                        bag_frac, num_data),
                    lambda _: bag, None)
            if use_ff:
                from .feature_mask import compose_tree_mask

                fmask = compose_tree_mask(
                    jax.random.fold_in(ff_key, i), ff, num_features)
            else:
                fmask = jnp.ones(num_features, jnp.float32)
            rkey = jax.random.fold_in(round_key, i)
            cat_info = _build_cat_info(cat_key, bins.shape[1])
            g, h = obj.grad_hess(pred, y, w)
            if goss_k is not None:
                tree, new_pred = _goss_compact_round(
                    bins, y, w, bag, pred, fmask, hyper, rkey, g, h,
                    goss_k, num_leaves, num_bins, hist_impl, row_chunk,
                    hist_dtype, wave_width, cat_info, renew_alpha,
                    mono=mono_arr, extra_trees=extra_trees, col_bins=colb,
                    renew_scale=renew_scale, ic_member=ic_member,
                    bynode_off=bynode_off)
                return (new_pred, bag), tree
            stats = jnp.stack(
                [g * bag, h * bag, (bag > 0).astype(jnp.float32)], axis=-1)
            tree, row_leaf = grow_tree(
                bins, stats, fmask, hyper.ctx(), num_leaves, num_bins,
                hyper.max_depth, ff_bynode=(None if bynode_off else hyper.feature_fraction_bynode),
                key=rkey, hist_impl=hist_impl,
                row_chunk=row_chunk, hist_dtype=hist_dtype,
                wave_width=wave_width,
                cat_info=cat_info, mono=mono_arr, extra_trees=extra_trees,
                col_bins=colb, ic_member=ic_member, fuse_partition=True)
            if renew_alpha is not None:
                rw = (w * bag if renew_scale is None
                      else w * bag * renew_scale(y))
                tree = renew_leaf_values(tree, row_leaf, y - pred, rw,
                                         renew_alpha)
            if is_rf:
                new_pred = pred
            else:
                new_pred = pred + hyper.learning_rate * \
                    lookup_values(row_leaf, tree.leaf_value)
            return (new_pred, bag), tree

        (pred, bag), trees = lax.scan(
            body, (pred0, bag0), start_iter + jnp.arange(n_rounds))
        return pred, bag, trees

    return multi


@functools.lru_cache(maxsize=None)
def _tree_pred_fn(depth_cap: int, num_class: int = 1):
    if num_class > 1:
        @jax.jit
        def add_tree_mc(pred, tree, bins, shrink):   # pred [n, K]
            vals = jax.vmap(
                lambda t: predict_tree_binned(t, bins, depth_cap))(tree)
            return pred + shrink * vals.T

        return add_tree_mc

    @jax.jit
    def add_tree(pred, tree, bins, shrink):
        return pred + shrink * predict_tree_binned(tree, bins, depth_cap)

    return add_tree


def _predict_forest_mc(forest, bins, shrink, inits, n_trees, depth_cap,
                       start_iteration=0):
    """Per-class forest replay for multiclass tree stacks ([T, K, M]
    fields) -> raw scores [n, K].  The single shared implementation of the
    class-sliced predict_forest_binned loop (used by predict, the lazy rf
    train-pred reconstruction, and DART's dropped-tree sums)."""
    k = forest.leaf_value.shape[1]
    cols = [predict_forest_binned(
        jax.tree.map(lambda a, c=c: a[:, c], forest), bins,
        jnp.float32(shrink),
        float(inits[c]) if np.ndim(inits) else float(inits),
        jnp.int32(n_trees), depth_cap,
        start_iteration=jnp.int32(start_iteration))
        for c in range(k)]
    return jnp.stack(cols, axis=1)


@functools.lru_cache(maxsize=None)
def _linear_tree_pred_fn(depth_cap: int):
    """pred += shrink * (leaf_const + coef . raw_pathfeats) for ONE linear
    tree (traversal on binned codes, evaluation on raw values)."""

    @jax.jit
    def add(pred, tree, bins, xraw, shrink):
        n = bins.shape[0]
        b32 = bins.astype(jnp.int32)

        def step(node, _):
            feat = tree.split_feature[node]
            thr = tree.split_bin[node]
            code = jnp.take_along_axis(b32, feat[:, None], axis=1)[:, 0]
            go_left = code <= thr
            if tree.is_cat_split is not None:
                go_left = jnp.where(tree.is_cat_split[node],
                                    tree.cat_mask[node, code], go_left)
            nxt = jnp.where(go_left, tree.left[node], tree.right[node])
            return jnp.where(tree.is_leaf[node], node, nxt), None

        node, _ = lax.scan(step, jnp.zeros(n, jnp.int32), None,
                           length=depth_cap)
        feats = tree.linear_feat[node]                    # [n, K]
        xg = jnp.take_along_axis(xraw, jnp.maximum(feats, 0), axis=1)
        xg = jnp.where((feats >= 0) & jnp.isfinite(xg), xg, 0.0)
        val = tree.leaf_value[node] + jnp.sum(
            tree.linear_coef[node] * xg, axis=1)
        return pred + shrink * val

    return add


@functools.lru_cache(maxsize=None)
def _eval_fn(obj_key: tuple, metric_names: tuple, metric_cfg: tuple):
    obj = _rebuild_objective(obj_key)
    p = (Params(alpha=metric_cfg[0],
                tweedie_variance_power=(metric_cfg[1] if len(metric_cfg) > 1
                                        else 1.5))
         if metric_cfg else Params())
    metrics = [get_metric(m, p) for m in metric_names]

    @jax.jit
    def evaluate(pred_raw, y, w):
        t = obj.transform(pred_raw)
        return tuple(m.fn(t, y, w) for m in metrics)

    return evaluate


@functools.lru_cache(maxsize=None)
def _bag_fn():
    from ..ops.sampling import sample_bag

    return jax.jit(sample_bag)


@functools.lru_cache(maxsize=None)
def _feature_mask_fn(num_features: int, with_base: bool = False):
    from .feature_mask import compose_tree_mask

    if with_base:
        # screening composition (r20): feature_fraction samples WITHIN
        # the screener's active-set mask, so the two maskers can never
        # double-mask into an empty usable set
        @jax.jit
        def sample_features_within(key, fraction, base_mask):
            return compose_tree_mask(key, fraction, num_features,
                                     base_mask)

        return sample_features_within

    @jax.jit
    def sample_features(key, fraction):
        return compose_tree_mask(key, fraction, num_features)

    return sample_features


class _SegView:
    """Placeholder for round ``j`` of a stacked k-round tree segment."""

    __slots__ = ("seg", "j")

    def __init__(self, seg, j):
        self.seg = seg
        self.j = j


class _TreeStore(_MutableSequence):
    """Per-round tree list that keeps fused-segment output STACKED.

    ``update_many`` produces k rounds of trees as one stacked pytree per
    segment; slicing each round out eagerly enqueues a tiny device gather
    per pytree field per round — hundreds of remote-tunnel ops over a
    200-round reference run, which is exactly the fixed per-op cost that
    made the diamonds wall clock lose to the CPU baseline (r3 verdict).
    The store records (segment, round) placeholders instead: a per-tree
    view materializes lazily on first access, and ``stacked_runs`` hands
    intact segments straight to the predict-time forest with ONE slice
    per run.
    """

    def __init__(self, items=()):
        self._items = list(items)

    # -- segment-aware entry points --------------------------------------
    def append_stacked(self, seg, n: int) -> None:
        self._items.extend(_SegView(seg, j) for j in range(n))

    def cap_set(self) -> set:
        """Distinct node-capacities across the forest, without
        materializing any per-tree view."""
        caps = set()
        for it in self._items:
            t = it.seg if isinstance(it, _SegView) else it
            caps.add(int(t.split_feature.shape[-1]))
        return caps

    def stacked_runs(self) -> list:
        """Pytrees with a leading tree axis that concatenate into the
        forest: contiguous rounds of one segment come out as a single
        slice of it; materialized singles get a length-1 axis."""
        runs, items, i = [], self._items, 0
        while i < len(items):
            it = items[i]
            if isinstance(it, _SegView):
                k = i + 1
                while (k < len(items) and isinstance(items[k], _SegView)
                       and items[k].seg is it.seg
                       and items[k].j == items[k - 1].j + 1):
                    k += 1
                j0, j1 = it.j, items[k - 1].j + 1
                runs.append(jax.tree.map(
                    lambda a, j0=j0, j1=j1: a[j0:j1], it.seg))
                i = k
            else:
                runs.append(jax.tree.map(lambda a: a[None], it))
                i += 1
        return runs

    # -- MutableSequence -------------------------------------------------
    def _mat(self, i: int):
        it = self._items[i]
        if isinstance(it, _SegView):
            it = jax.tree.map(lambda a, j=it.j: a[j], it.seg)
            self._items[i] = it
        return it

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._mat(j)
                    for j in range(*i.indices(len(self._items)))]
        return self._mat(i)

    def __setitem__(self, i, v):
        self._items[i] = v

    def __delitem__(self, i):
        del self._items[i]

    def __len__(self):
        return len(self._items)

    def insert(self, i, v):
        self._items.insert(i, v)


class Booster:
    """LightGBM-compatible Booster driving the jitted TPU round step.

    Reference API surface exercised: construction via ``lgb.train`` with a
    Dataset (r/gridsearchCV.R:57), ``predict`` over all or first-k trees
    (r/gridsearchCV.R:63, bagging_boosting.ipynb:136).
    """

    def __init__(self, params: Optional[Union[Dict[str, Any], Params]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        if model_file is not None or model_str is not None:
            from ..utils.serialize import load_booster_into
            load_booster_into(self, model_file=model_file, model_str=model_str)
            return
        if isinstance(params, Params):
            self.params = params
        else:
            self.params = parse_params(params)
        self.train_set = train_set
        self.obj = create_objective(self.params)
        self.trees: List[Tree] = _TreeStore()
        self._forest_cache: Optional[Tree] = None
        self.best_iteration: int = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._valid: List[Tuple[str, Dataset, Any]] = []  # (name, dataset, pred)
        self._iter = 0
        self.init_score_ = 0.0
        self._pred_train = None
        self._bag = None
        self._key = jax.random.PRNGKey(self.params.seed)

        if train_set is not None:
            self._setup_training()

    # ------------------------------------------------------------------
    @property
    def _num_class(self) -> int:
        if self.params.objective in ("multiclass", "multiclassova"):
            return self.params.num_class
        return 1

    def _setup_training(self) -> None:
        ds = self.train_set
        ds.construct()
        if ds.y is None:
            raise ValueError("training Dataset requires a label")
        p = self.params
        y_host = ds.get_label()
        w_host = (ds.get_weight() if ds.get_weight() is not None
                  else np.ones(ds.num_data_))
        if hasattr(self.obj, "prepare"):
            self.obj.prepare(y_host, w_host)
        if getattr(self.obj, "needs_group", False):
            gs = ds.get_group()
            if gs is None:
                raise ValueError(
                    f"objective '{self.obj.name}' requires query group "
                    "information: Dataset(X, label=y, group=sizes)")
            self.obj.set_group(gs, y_host, int(ds.row_mask.shape[0]))
        k = self._num_class
        if k > 1:  # every boosting mode (gbdt/goss/rf/dart) supports K>1
            self.init_score_ = np.asarray(
                self.obj.init_score(y_host, w_host), np.float32)  # [K]
            if ds.get_init_score() is not None:
                raise NotImplementedError(
                    "per-row init_score with multiclass is not supported")
            self._pred_train = jnp.broadcast_to(
                jnp.asarray(self.init_score_)[None, :],
                (int(ds.row_mask.shape[0]), k))
        elif ds.get_init_score() is not None:
            base = np.concatenate([
                np.asarray(ds.get_init_score(), np.float32),
                np.zeros(int(ds.row_mask.shape[0]) - ds.num_data_, np.float32)])
            self._pred_train = jnp.asarray(base)
            self.init_score_ = 0.0
        else:
            self.init_score_ = float(self.obj.init_score(y_host, w_host))
            self._pred_train = jnp.full(
                ds.row_mask.shape, self.init_score_, jnp.float32)
        self._bag = ds.row_mask
        self._hyper = HyperScalars.from_params(p)
        # predict-time shrinkage base: stored leaf values are normalized to
        # THIS rate, so reset_parameter learning-rate schedules stay exact
        # (round i's tree is rescaled by lr_i / base at append time)
        self._base_lr = float(p.learning_rate)
        self._obj_key = _objective_static_key(self.obj, p)
        self._num_bins = ds.num_bins
        self._w_eff = ds.w  # 0 on padding rows already
        cats = np.flatnonzero(ds.col_is_categorical)
        self._cat_key = (
            (tuple(int(c) for c in cats), float(p.cat_smooth),
             float(p.cat_l2), int(p.max_cat_threshold))
            if len(cats) else None)
        self._mono_key = self._resolve_monotone_constraints()
        self._ic_key = self._resolve_interaction_constraints()
        # per-training-column used-bin counts bound the extra_trees draw
        # (code-review r2: a global [0, num_bins) draw starves
        # low-cardinality features of valid thresholds)
        if p.extra_trees:
            bmm = ds.bin_mapper
            colb = (bmm.bundler.col_bins if bmm.bundler is not None
                    else [int(x) for x in bmm.n_bins])
            self._nbins_key = tuple(int(x) for x in colb)
        else:
            self._nbins_key = None
        self._streamed = bool(getattr(ds, "is_streamed", False))
        if self._streamed:
            self._check_streamed_scope()
        self._xraw = None
        self._linear_k = None
        if p.linear_tree:
            self._setup_linear_tree()
        # r20 gain-informed feature screening: the host-side EWMA
        # screener plans a compacted active set per round (None on
        # refresh rounds); a checkpoint restore that arrived before this
        # setup re-applies its stashed EWMA state here
        self._screener = None
        self._screen_bins_cache = None
        if p.feature_screen == "ema":
            self._check_screen_scope()
            from .feature_mask import FeatureScreener

            self._screener = FeatureScreener(
                int(ds.num_feature_), p.screen_keep_ratio,
                p.screen_ema_decay, p.screen_refresh_rounds)
            stash = getattr(self, "_screen_restore", None)
            if stash is not None:
                self._screener.restore(*stash)
                self._screen_restore = None
        self._dp_mesh = None
        self._fp_mesh = None
        if self._streamed:
            ds.block_store.prefetch_blocks = int(
                p.extra.get("stream_prefetch_blocks", 1))
            if p.tree_learner == "data":
                # r19: streamed × data-parallel — per-shard BlockStores
                # on the dp mesh with per-block-round merges
                self._maybe_setup_stream_dp()
            elif p.tree_learner != "serial":
                import warnings

                warnings.warn(
                    f"tree_learner='{p.tree_learner}' is not routed under "
                    "streamed (from_blocks) training — only 'data' "
                    "composes with the block loop (r19); falling back to "
                    "serial")
        elif p.tree_learner == "feature":
            self._maybe_setup_fp()
        elif p.tree_learner in ("data", "voting"):
            self._maybe_setup_dp()

    def _check_streamed_scope(self) -> None:
        """Out-of-core training covers the PLAIN numeric path (ISSUE 7):
        the per-block grower kernels replicate the fused strict/wave
        bodies without the categorical / monotone / extra-trees /
        interaction / bynode machinery, and multiclass & ranking need
        per-round state the streamed round functions don't carry.  Each
        fence raises :class:`~lightgbm_tpu.faults.StreamScopeError`
        naming the EXACT offending key (r19 satellite) rather than a
        generic message — train something subtly different, never."""
        from ..faults import StreamScopeError

        p = self.params
        bad = key = None
        if self._num_class > 1:
            bad, key = "multiclass objectives", "num_class"
        elif getattr(self.obj, "needs_group", False):
            bad, key = f"ranking objective '{self.obj.name}'", "objective"
        elif p.linear_tree:
            bad = key = "linear_tree"
        elif p.extra_trees:
            bad = key = "extra_trees"
        elif self._mono_key is not None:
            bad = key = "monotone_constraints"
        elif self._ic_key is not None:
            bad = key = "interaction_constraints"
        elif self._cat_key is not None:
            bad, key = "categorical features", "categorical_feature"
        elif p.feature_fraction_bynode < 1.0:
            bad, key = ("feature_fraction_bynode < 1",
                        "feature_fraction_bynode")
        elif p.boosting == "dart":
            bad, key = "boosting='dart'", "boosting"
        if bad is not None:
            raise StreamScopeError(
                f"streamed (from_blocks) training does not support {bad} "
                f"(unsupported key: {key})", key=key)

    def _check_screen_scope(self) -> None:
        """Feature screening covers the plain gbdt/rf/goss growers (the
        serial, streamed, and data-parallel row meshes).  Configs whose
        static per-column state is indexed by GLOBAL feature id —
        categorical sets, monotone signs, interaction groups, per-column
        bin counts (extra_trees), linear leaf designs, the
        feature-sharded learner, DART's per-round replay — would need a
        remap per structure to grow in compacted space; each fence
        raises :class:`~lightgbm_tpu.faults.ScreenScopeError` naming the
        exact offending key, mirroring ``_check_streamed_scope``."""
        from ..faults import ScreenScopeError

        p = self.params
        bad = key = None
        if self._num_class > 1:
            bad, key = "multiclass objectives", "num_class"
        elif getattr(self.obj, "needs_group", False):
            bad, key = f"ranking objective '{self.obj.name}'", "objective"
        elif p.linear_tree:
            bad = key = "linear_tree"
        elif p.boosting == "dart":
            bad, key = "boosting='dart'", "boosting"
        elif p.extra_trees:
            bad = key = "extra_trees"
        elif self._mono_key is not None:
            bad = key = "monotone_constraints"
        elif self._ic_key is not None:
            bad = key = "interaction_constraints"
        elif self._cat_key is not None:
            bad, key = "categorical features", "categorical_feature"
        elif p.tree_learner == "feature":
            bad, key = "tree_learner='feature'", "tree_learner"
        if bad is not None:
            raise ScreenScopeError(
                f"feature_screen='ema' does not support {bad} "
                f"(unsupported key: {key})", key=key)

    def _resolve_monotone_constraints(self) -> Optional[tuple]:
        """Map user ``monotone_constraints`` (per ORIGINAL feature) onto the
        TRAINING columns (post-EFB), validating LightGBM's rules: the list
        must cover every feature and categorical features cannot be
        constrained (a category set has no order to be monotone in).

        Returns a static tuple for the jit-compile cache, or None when no
        constraint is active.
        """
        p = self.params
        mc = p.monotone_constraints
        if mc is None or not any(int(c) != 0 for c in mc):
            return None
        bm = self.train_set.bin_mapper
        if len(mc) != bm.num_features:
            raise ValueError(
                f"monotone_constraints has {len(mc)} entries for "
                f"{bm.num_features} features")
        for f, c in enumerate(mc):
            if c != 0 and bm.is_categorical[f]:
                raise ValueError(
                    f"monotone constraint on categorical feature {f} is "
                    "not supported (matching lightgbm)")
        b = bm.bundler
        if b is None:
            return tuple(int(c) for c in mc)
        train_mc = []
        for g in b.groups:
            if len(g) == 1:
                train_mc.append(int(mc[g[0]]))
            elif any(int(mc[f]) != 0 for f in g):
                raise ValueError(
                    "monotone constraint on an EFB-bundled feature "
                    f"(bundle members {g}); pass enable_bundle=False "
                    "when constraining sparse features")
            else:
                train_mc.append(0)
        return tuple(train_mc)

    @staticmethod
    def _raw_to_device(raw, n_pad: int):
        """Raw feature matrix -> padded f32 device array (linear_tree)."""
        from ..dataset import _to_2d_float_array

        X = _to_2d_float_array(raw).astype(np.float32)
        if X.shape[0] < n_pad:
            X = np.concatenate(
                [X, np.zeros((n_pad - X.shape[0], X.shape[1]), np.float32)])
        return jnp.asarray(X)

    def _setup_linear_tree(self) -> None:
        """Device-resident raw feature matrix for linear leaves (upstream
        ``linear_tree``): the ridge fit and linear prediction read RAW
        values, which the binned pipeline otherwise never ships to the
        device.  EFB must be off (a merged bundle column has no single raw
        value; upstream LightGBM likewise forbids linear trees with EFB).
        """
        ds = self.train_set
        p = self.params
        if ds.bin_mapper.bundler is not None:
            raise ValueError(
                "linear_tree with EFB bundling is not supported; construct "
                "the Dataset with params={'enable_bundle': False}")
        raw = ds.raw_data
        if raw is None or isinstance(raw, str):
            raise ValueError(
                "linear_tree needs the raw feature values: keep "
                "free_raw_data=False and build the Dataset from an "
                "in-memory matrix (not a saved binary)")
        self._xraw = self._raw_to_device(raw, int(ds.row_mask.shape[0]))
        self._linear_k = max(1, min(int(p.extra.get("linear_k", 8)),
                                    int(ds.num_feature_)))

    def _resolve_interaction_constraints(self) -> Optional[tuple]:
        """interaction_constraints (original-feature groups) -> static
        group-membership over TRAINING columns.

        sklearn-HistGBDT convention: features in no listed group become
        singleton groups (they can still split, alone).  An EFB bundle
        column belongs to a group only if ALL its members do (a split on
        the merged axis involves every member's default/non-default
        structure)."""
        p = self.params
        ic = p.interaction_constraints
        if not ic:
            return None
        bm = self.train_set.bin_mapper
        f_orig = bm.num_features
        groups = [set(g) for g in ic]
        listed = set().union(*groups) if groups else set()
        bad = sorted(f for f in listed if not (0 <= f < f_orig))
        if bad:
            raise ValueError(
                f"interaction_constraints reference feature indices {bad} "
                f"but the dataset has {f_orig} features")
        for f in sorted(set(range(f_orig)) - listed):
            groups.append({f})
        b = bm.bundler
        cols = ([tuple(g) for g in getattr(b, "groups", [])] if b is not None
                else [(f,) for f in range(f_orig)])
        member = [[1 if all(f in g for f in col_members) else 0
                   for col_members in cols] for g in groups]
        # EFB fallout: a multi-member bundle column whose members span
        # groups belongs to no group and would be silently unsplittable
        # (code-review r2).  If any member is LISTED the semantics are
        # genuinely mixed -> reject; if all members are unlisted, the
        # bundle becomes its own singleton group (its members are
        # mutually-exclusive sparse features).
        for c, col_members in enumerate(cols):
            if any(member[g][c] for g in range(len(member))):
                continue
            if any(f in listed for f in col_members):
                raise ValueError(
                    "interaction_constraints split an EFB bundle "
                    f"(members {list(col_members)}); pass "
                    "params={'enable_bundle': False} on the Dataset "
                    "when constraining sparse features")
            member.append([1 if i == c else 0 for i in range(len(cols))])
        return tuple(tuple(row) for row in member)

    def _dp_merge_mode(self):
        """Resolve the row-sharded learners' histogram merge topology.

        Returns static ``(merge_mode, voting_k)`` for the dp step builders:
        ``tree_learner="data"`` routes to ``reduce_scatter_pipelined``
        since r10 (LightGBM's data-parallel Reduce-Scatter realized as a
        chunked ppermute ring — each shard receives its F/D feature
        slice in sub-chunks whose ring hops overlap the per-chunk split
        scans; 1/D the comm bytes AND the transfer hidden behind
        compute, serial-parity-exact trees) and ``"voting"`` to the
        PV-Tree voting merge (``top_k`` ballots, approximate) — distinct
        topologies since r9, not aliases of the full psum.
        ``params={'histogram_merge': ...}`` overrides the routing (e.g.
        ``"psum"`` to A/B the r0 baseline, ``"reduce_scatter"`` for the
        fused single-collective scatter, or ``"reduce_scatter_ring"``
        for the unchunked ring).  Voting needs a numeric-threshold
        ballot, so categorical datasets fall back to reduce-scatter with
        a warning.
        """
        import warnings

        p = self.params
        override = p.extra.get("histogram_merge")
        if override is not None:
            valid = ("psum", "reduce_scatter", "reduce_scatter_ring",
                     "reduce_scatter_pipelined", "voting")
            if override not in valid:
                raise ValueError(
                    f"histogram_merge must be one of {valid}, "
                    f"got {override!r}")
            mode = override
        elif p.tree_learner == "voting":
            mode = "voting"
        else:
            mode = "reduce_scatter_pipelined"
        if mode == "voting" and self._cat_key is not None:
            warnings.warn(
                "tree_learner='voting' does not support categorical "
                "features (the local ballot scans numeric thresholds "
                "only); using the reduce_scatter merge instead",
                stacklevel=3)
            mode = "reduce_scatter"
        return mode, int(p.top_k)

    def _dp_wire(self, merge_mode: str, eff_rows: int):
        """Resolve the ring merge's static ``(wire_dtype, merge_chunks)``.

        ``params={'histogram_wire': 'f32'|'bf16'|'int8'}`` compresses
        ring-hop messages (2x / 4x fewer wire bytes); ``merge_chunks``
        (default 4) sets the pipelined mode's sub-chunk count.  Non-f32
        wire needs explicit hop boundaries, so it rejects the fused
        ``psum`` / ``reduce_scatter`` collectives.

        int8 wire exactness gate: hop messages carry partial-sum COUNT
        columns, so the quantization step grows with the per-shard row
        count; past the r9 int8-accumulator bound (``2^31/127`` rows per
        shard, ``ops.histogram_pallas.INT8_ACC_ROW_LIMIT`` — the same
        exact-accumulation cliff ``check_int8_row_limit`` guards) the
        wire's documented tolerance can no longer be honored and the
        Booster falls back to f32 wire with a warning instead of
        training silently degraded.  Within the bound, int8 wire is
        approximate-by-contract (bench quality gate: AUC drift <= 1e-4),
        NOT parity-exact — only f32 wire keeps the bit-identity bar.
        """
        import warnings

        p = self.params
        wire = str(p.extra.get("histogram_wire", "f32"))
        from ..ops.histogram import WIRE_DTYPES

        if wire not in WIRE_DTYPES:
            raise ValueError(
                f"histogram_wire must be one of {WIRE_DTYPES}, "
                f"got {wire!r}")
        chunks = int(p.extra.get("merge_chunks", 4))
        if chunks < 1:
            raise ValueError(
                f"merge_chunks must be >= 1, got {chunks}")
        if wire == "f32":
            return wire, chunks
        if merge_mode not in ("reduce_scatter_ring",
                              "reduce_scatter_pipelined"):
            raise ValueError(
                f"histogram_wire={wire!r} compresses ring-hop messages "
                f"and needs histogram_merge='reduce_scatter_ring' or "
                f"'reduce_scatter_pipelined', not {merge_mode!r}")
        if wire == "int8":
            from ..ops.histogram_pallas import INT8_ACC_ROW_LIMIT

            mesh = getattr(self, "_dp_mesh", None)
            n_shards = (int(mesh.shape["data"]) if mesh is not None
                        else 1)
            per_shard = -(-int(eff_rows) // max(n_shards, 1))
            if per_shard > INT8_ACC_ROW_LIMIT:
                warnings.warn(
                    f"histogram_wire='int8' with {per_shard:,} rows per "
                    f"shard exceeds the exact-accumulation bound "
                    f"({INT8_ACC_ROW_LIMIT:,}); falling back to f32 "
                    "wire", stacklevel=3)
                return "f32", chunks
        return wire, chunks

    def _dp2_shape(self, n_dev: int, n_features: int):
        """Resolve the data learner's mesh topology: ``None`` for the 1-D
        row mesh or ``(rows, cols)`` for the 2-D rows x features mesh.

        ``params={'mesh_shape': ...}`` controls it: ``"auto"`` (default)
        promotes to ``(n_dev//2, 2)`` when ``n_dev >= 8`` and
        ``n_features >= 64`` — wide-enough data that halving each
        shard's histogram width beats the wider row slice — ``"1d"``
        forces the row mesh, and an explicit ``"RxC"`` (e.g. ``"4x2"``)
        pins the shape.  The 2-D step psum-merges over the data axis
        (``grow_tree`` rejects ring merges composed with a feature
        axis), so explicit ``histogram_merge`` / ``histogram_wire``
        overrides keep the 1-D topology, as do configurations the 2-D
        step does not trace (multiclass, goss, linear, constraints,
        categoricals, per-feature bins, per-node sampling).
        """
        p = self.params
        spec = str(p.extra.get("mesh_shape", "auto"))
        if spec == "1d":
            return None
        plain = (p.tree_learner == "data"
                 and p.boosting in ("gbdt", "rf")
                 and self._num_class == 1
                 and not p.linear_tree and not p.extra_trees
                 and self._mono_key is None and self._ic_key is None
                 and self._cat_key is None and self._nbins_key is None
                 and p.feature_fraction_bynode >= 1.0
                 and p.feature_screen == "off"  # screening compacts the
                 # column axis per round; the 2-D mesh pins a static
                 # column shard width — keep the 1-D row mesh instead
                 and p.extra.get("histogram_merge") is None
                 and p.extra.get("histogram_wire", "f32") == "f32")
        if spec == "auto":
            if plain and n_dev >= 8 and n_dev % 2 == 0 \
                    and n_features >= 64:
                return n_dev // 2, 2
            return None
        try:
            rows, cols = (int(t) for t in spec.lower().split("x"))
            if rows < 1 or cols < 1:
                raise ValueError
        except ValueError:
            raise ValueError(
                f"mesh_shape must be 'auto', '1d', or 'RxC' "
                f"(e.g. '4x2'), got {spec!r}") from None
        if cols == 1:
            return None
        if not plain:
            import warnings
            warnings.warn(
                f"mesh_shape={spec!r} needs the plain single-class "
                "gbdt/rf data learner with the default psum-over-rows "
                "merge; using the 1-D row mesh", stacklevel=4)
            return None
        if rows * cols != n_dev:
            raise ValueError(
                f"mesh_shape={spec!r} wants {rows * cols} devices but "
                f"the row-divisible device count is {n_dev}")
        return rows, cols

    def _maybe_setup_dp(self) -> None:
        """Shard the training arrays over the local device mesh when the
        user asks for a row-sharded parallel tree learner (LightGBM
        ``tree_learner=data`` / ``voting`` — SURVEY.md §2C / VERDICT r1
        item 6).  The histogram merge topology each learner uses is
        resolved separately by :meth:`_dp_merge_mode`.
        """
        import warnings

        p = self.params
        ranking = getattr(self.obj, "needs_group", False)
        if (p.boosting == "dart"
                or getattr(self.obj, "renew_alpha", None) is not None
                # linear leaves under the mesh since r5: plain
                # single-class gbdt (the ridge psum path,
                # parallel.make_dp_linear_train_step)
                or (p.linear_tree and (p.boosting != "gbdt"
                                       or self._num_class > 1 or ranking
                                       or self._mono_key is not None
                                       or self._ic_key is not None
                                       or self._cat_key is not None
                                       or p.extra_trees))
                or (ranking and (p.boosting != "gbdt"
                                 or self._mono_key is not None
                                 or self._ic_key is not None
                                 or self._cat_key is not None
                                 or p.extra_trees))):
            warnings.warn(
                f"tree_learner='{p.tree_learner}' currently supports "
                "gbdt/rf/goss boosting without leaf renewal "
                "(ranking: plain gbdt only; linear_tree: plain "
                "single-class gbdt); training serially",
                stacklevel=3)
            return
        n_pad = int(self.train_set.row_mask.shape[0])
        n_dev = len(jax.devices())
        while n_dev > 1 and n_pad % n_dev != 0:
            n_dev -= 1
        if n_dev <= 1:
            if len(jax.devices()) <= 1:
                warnings.warn(
                    f"tree_learner='{p.tree_learner}' requested but only one "
                    "device is visible; training serially", stacklevel=3)
            return
        from ..parallel.data_parallel import make_mesh, shard_rows

        shape2 = (None if ranking else self._dp2_shape(
            n_dev, int(self.train_set.X_binned.shape[1])))
        if shape2 is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.feature_parallel import (
                FEATURE_AXIS, make_mesh_2d, pad_features)

            rows, cols = shape2
            self._dp_mesh = make_mesh_2d(rows, cols)
            self._dp2 = True
            ds = self.train_set
            padded = pad_features(np.asarray(ds.X_binned), cols)
            self._dp2_width = padded.shape[1]
            self._dp_bins = jax.device_put(
                jnp.asarray(padded),
                NamedSharding(self._dp_mesh, P("data", FEATURE_AXIS)))
            (self._dp_y, self._dp_w, self._pred_train,
             self._bag) = shard_rows(
                self._dp_mesh, ds.y, self._w_eff, self._pred_train,
                self._bag)
            return
        self._dp_mesh = make_mesh(n_dev)
        ds = self.train_set
        if ranking:
            # LambdaRank lambdas need whole queries: the [Q, G] pairwise
            # pass runs REPLICATED (cheap next to histogram work) and only
            # the grower is sharded — see make_dp_grow_step.
            self._dp_stats_only = True
            self._dp_bins = shard_rows(self._dp_mesh, ds.X_binned)
            self._dp_grad_jit = jax.jit(self.obj.grad_hess)
            return
        (self._dp_bins, self._dp_y, self._dp_w, self._pred_train,
         self._bag) = shard_rows(
            self._dp_mesh, ds.X_binned, ds.y, self._w_eff,
            self._pred_train, self._bag)
        if self._xraw is not None:   # linear_tree under the mesh (r5)
            self._dp_xraw = shard_rows(self._dp_mesh, self._xraw)

    def _maybe_setup_fp(self) -> None:
        """Shard the FEATURE axis over the local mesh (LightGBM
        ``tree_learner=feature`` — per-shard histograms over a column
        slice, split exchange via all_gather; parallel.feature_parallel).
        Falls back to data-parallel-style serial training when the
        configuration needs capabilities the fp step does not trace."""
        import warnings

        p = self.params
        # (multiclass and categorical are fp-supported since r4: the class
        # axis vmaps inside the shard_map and the static is_cat mask
        # slices per shard — make_fp_train_step)
        if (p.boosting in ("goss", "dart")
                or p.linear_tree
                or getattr(self.obj, "needs_group", False)
                or getattr(self.obj, "renew_alpha", None) is not None
                or self._mono_key is not None or p.extra_trees
                or self._ic_key is not None
                or p.feature_fraction_bynode < 1.0):
            warnings.warn(
                "tree_learner='feature' currently supports gbdt/rf "
                "(single or multiclass, with categoricals) without "
                "monotone/interaction constraints, extra_trees, goss, "
                "dart, linear_tree, ranking, or per-node feature "
                "sampling (bynode would sample per SHARD and diverge "
                "from serial); training serially", stacklevel=3)
            return
        n_dev = len(jax.devices())
        if n_dev <= 1:
            warnings.warn(
                "tree_learner='feature' requested but only one device is "
                "visible; training serially", stacklevel=3)
            return
        from ..parallel.feature_parallel import (
            make_feature_mesh, pad_features, shard_features)

        ds = self.train_set
        codes = np.asarray(ds.X_binned)
        padded = pad_features(codes, n_dev)
        base_mask = np.zeros(padded.shape[1], np.float32)
        base_mask[: codes.shape[1]] = 1.0
        self._fp_mesh = make_feature_mesh(n_dev)
        self._fp_bins, _ = shard_features(
            self._fp_mesh, jnp.asarray(padded), jnp.asarray(base_mask))
        self._fp_width = padded.shape[1]

    def _maybe_setup_stream_dp(self) -> None:
        """Compose out-of-core streaming with the dp mesh (r19 tentpole):
        split the block store into per-shard stores over contiguous block
        ranges, pin each to its own device, and shard the O(n) resident
        vectors row-wise so every device streams + scores ONLY its own
        row range.  Falls back to serial streaming (with a warning) when
        the mesh cannot be used, mirroring ``_maybe_setup_dp``."""
        import warnings

        from ..faults import StreamScopeError

        p = self.params
        if getattr(self.obj, "renew_alpha", None) is not None:
            warnings.warn(
                "tree_learner='data' under streamed training supports "
                "gbdt/rf/goss without leaf renewal (the renewal pass "
                "needs an extra full stream per round); training with "
                "the serial block loop", stacklevel=3)
            return
        if p.extra.get("histogram_merge") == "voting":
            # voting is a grower-level ballot, not a histogram merge the
            # per-block-round collective can express
            raise StreamScopeError(
                "streamed (from_blocks) dp training does not support "
                "histogram_merge='voting' — the PV-Tree ballot needs "
                "in-memory per-shard split scans (unsupported key: "
                "histogram_merge)", key="histogram_merge")
        store = self.train_set.block_store
        n_dev = len(jax.devices())
        cap = int(p.extra.get("stream_dp_devices", 0))
        if cap > 0:
            n_dev = min(n_dev, cap)
        from ..data.stream_dp import (choose_stream_dp_devices,
                                      setup_stream_shards)

        n_dev = choose_stream_dp_devices(store.num_blocks, n_dev)
        if n_dev <= 1:
            if len(jax.devices()) <= 1:
                warnings.warn(
                    "tree_learner='data' requested but only one device "
                    "is visible; streaming serially", stacklevel=3)
            else:
                warnings.warn(
                    f"tree_learner='data' requested but {store.num_blocks}"
                    " block(s) admit no >1-device lockstep shard split; "
                    "streaming serially", stacklevel=3)
            return
        from ..parallel.data_parallel import make_mesh, shard_rows

        self._dp_mesh = make_mesh(n_dev)
        self._stream_dp = True
        self._stream_shards = setup_stream_shards(store, self._dp_mesh)
        ds = self.train_set
        (self._dp_y, self._dp_w, self._pred_train,
         self._bag) = shard_rows(
            self._dp_mesh, ds.y, self._w_eff, self._pred_train, self._bag)

    # -- continuation ----------------------------------------------------
    @property
    def _depth_cap(self) -> int:
        """Static traversal depth bound covering every tree in the forest.

        Equals ``num_leaves`` for a homogeneous forest; an ``init_model``
        continuation may carry deeper ingested trees, whose own capacity
        then sets the bound.
        """
        caps = (self.trees.cap_set() if isinstance(self.trees, _TreeStore)
                else {int(t.split_feature.shape[-1]) for t in self.trees})
        cap = max([2 * self.params.num_leaves - 1, *caps])
        return (cap + 1) // 2

    def ingest_init_model(self, prev: "Booster") -> None:
        """Continue training from ``prev``'s forest (lgb.train init_model).

        The stored leaf values are raw (shrinkage applied at predict time by
        the CURRENT learning_rate), so ingested trees are rescaled by
        ``prev_lr / cur_lr`` — the uniform shrink then reproduces each
        ingested tree's original contribution exactly.
        """
        p = self.params
        if p.boosting == "rf" or prev.params.boosting == "rf":
            raise NotImplementedError(
                "init_model continuation is not supported for rf boosting "
                "(averaged forests have no additive continuation)")
        if prev.num_model_per_iteration() != self._num_class:
            raise ValueError(
                "init_model has a different number of classes "
                f"({prev.num_model_per_iteration()} vs {self._num_class})")
        if not prev.trees:
            return
        # the ingested trees' split_bin codes only mean something under the
        # bin mapper they were trained with — require an identical binning
        # (pass reference= to reuse the original Dataset's bins)
        if not self._same_binning(self.train_set.bin_mapper,
                                  prev._bin_mapper_for_predict()):
            raise ValueError(
                "init_model was trained with different feature binning than "
                "this Dataset; rebuild the Dataset with "
                "reference=<original training Dataset> (or identical data) "
                "before continuing training")
        prev_linear = bool(prev.trees
                           and prev.trees[0].linear_feat is not None)
        if prev_linear != bool(p.linear_tree):
            raise ValueError(
                "init_model and the continuation must agree on linear_tree "
                f"(init_model linear={prev_linear}, params "
                f"linear_tree={p.linear_tree}) — a forest cannot mix "
                "constant and linear leaves")
        prev_lr = float(getattr(prev, "_base_lr",
                                prev.params.learning_rate))
        scale = jnp.float32(prev_lr / self._base_lr)
        self.trees = [t._replace(
            leaf_value=t.leaf_value * scale,
            linear_coef=(None if t.linear_coef is None
                         else t.linear_coef * scale))
            for t in prev.trees]
        self._iter = len(self.trees)
        self._forest_cache = None
        # restart from the PREVIOUS model's base score and replay its trees
        # into the train predictions so gradients continue where it left off
        self._rebase_and_replay(prev.init_score_)

    @staticmethod
    def _same_binning(cur_m, prev_m) -> bool:
        """Whether two bin mappers describe the SAME training column
        space — identical bounds AND identical EFB bundling (bundling
        remaps training columns without touching ``upper_bounds``)."""
        same = (len(cur_m.upper_bounds) == len(prev_m.upper_bounds) and all(
            len(a) == len(b) and np.allclose(a, b)
            for a, b in zip(cur_m.upper_bounds, prev_m.upper_bounds)))
        cur_b = getattr(cur_m, "bundler", None)
        prev_b = getattr(prev_m, "bundler", None)
        if (cur_b is None) != (prev_b is None):
            return False
        if cur_b is not None and (
                cur_b.groups != prev_b.groups
                or not np.array_equal(cur_b.default_bins,
                                      prev_b.default_bins)):
            return False
        return same

    def _rebase_and_replay(self, init_score) -> None:
        """Rebuild ``_pred_train`` from ``init_score`` and replay the
        current forest into it, so continued-training gradients pick up
        exactly where the source model stopped (shared by init_model
        ingest and the ``Booster(model_file=...)`` + ``update()`` path)."""
        ds = self.train_set
        p = self.params
        self.init_score_ = init_score
        if self._num_class > 1:
            self._pred_train = jnp.broadcast_to(
                jnp.asarray(self.init_score_, jnp.float32)[None, :],
                (int(ds.row_mask.shape[0]), self._num_class))
        else:
            self._pred_train = jnp.full(
                ds.row_mask.shape, float(self.init_score_), jnp.float32)
            if ds.get_init_score() is not None:
                # dataset per-row offsets apply ON TOP of the ingested
                # model's scores (upstream GBDT::ResetTrainingData keeps both)
                base = np.concatenate([
                    np.asarray(ds.get_init_score(), np.float32),
                    np.zeros(int(ds.row_mask.shape[0]) - ds.num_data_,
                             np.float32)])
                self._pred_train = self._pred_train + jnp.asarray(base)
        shrink = jnp.float32(self._base_lr)
        if getattr(self, "_streamed", False):
            # no resident X_binned on a streamed Dataset: replay each
            # tree with one traversal pass over the block store, then
            # apply the SAME jitted update shape the live streamed
            # rounds use — under jit XLA:CPU contracts the mul+add into
            # an FMA; an eager update would round differently and every
            # continued round would see 1-ulp-different gradients
            from ..data.stream_grow import _block_pred_fn, _replay_add_fn
            pred_fn = _block_pred_fn()
            store = ds.block_store
            for tree in self.trees:
                deltas = [pred_fn(tree, bins_b)
                          for _, bins_b in store.device_blocks()]
                delta = (deltas[0] if len(deltas) == 1
                         else jnp.concatenate(deltas))
                self._pred_train = _replay_add_fn()(
                    self._pred_train, shrink, delta)
            return
        if p.linear_tree:
            add_lin = _linear_tree_pred_fn(self._depth_cap)
            for tree in self.trees:
                self._pred_train = add_lin(
                    self._pred_train, tree, ds.X_binned, self._xraw, shrink)
        else:
            add = _tree_pred_fn(self._depth_cap, self._num_class)
            for tree in self.trees:
                self._pred_train = add(self._pred_train, tree, ds.X_binned,
                                       shrink)

    def _attach_continuation(self, ds: Dataset) -> None:
        """Attach a training Dataset to a deserialized Booster so
        ``update()`` continues the saved model (r13 satellite).

        Validates that the Dataset was binned identically to the saved
        model (targeted error otherwise), runs the normal training setup,
        then replays the loaded forest into the train predictions.  For
        deterministic configs the continued rounds are bit-identical to
        an uninterrupted run; mid-``bagging_freq`` bag state is NOT in
        the model file — resume from a training checkpoint
        (``lightgbm_tpu.training``) when that matters.
        """
        ds.construct()
        prev_m = self._bin_mapper_for_predict()
        if prev_m is not None and not self._same_binning(
                ds.bin_mapper, prev_m):
            raise ValueError(
                "this Booster was saved under a different feature binning "
                "than the offered Dataset (bin bounds / EFB bundling "
                "differ); rebuild the Dataset with reference=<original "
                "training Dataset> (or identical data) before continuing "
                "training")
        loaded_init = self.init_score_
        loaded_iter = self._iter
        self.train_set = ds
        self._setup_training()
        if getattr(self, "_streamed", False) and prev_m is not None:
            # streamed continuation (r15): the split_bin codes in the
            # loaded forest only mean something under the binning they
            # were trained with — enforce via the checkpoint-grade
            # schema digest (covers bounds, nan bin, bin counts,
            # categorical flags, EFB bundling), same contract as
            # training.checkpoint.resume_booster
            from ..data.sketch import schema_digest
            got = schema_digest(ds.bin_mapper)
            want = schema_digest(prev_m)
            if got != want:
                raise ValueError(
                    "this Booster was saved under a different binning "
                    f"schema (digest {want[:12]}… vs the streamed "
                    f"Dataset's {got[:12]}…); rebuild the blocks with "
                    "Dataset.from_blocks(..., reference=<original "
                    "training Dataset>) before continuing training")
        self._iter = loaded_iter
        self._forest_cache = None
        self._rebase_and_replay(loaded_init)

    def _screen_finite(self, i: int) -> None:
        """Gradient/hessian finiteness screen (r13 streaming hardening):
        one non-finite raw prediction makes every objective's g/h
        non-finite and the round would grow a garbage tree out of NaN
        stats that silently poisons the rest of the run.  Costs one
        scalar host sync — the streamed block loop it guards is a host
        loop already.  Disable with ``finite_screen=false``."""
        from ..faults import NonFiniteGradientError

        if not bool(jnp.all(jnp.isfinite(self._pred_train))):
            raise NonFiniteGradientError(
                f"non-finite raw predictions entering round {i}: the "
                "gradient/hessian stats would be non-finite and the grown "
                "tree garbage — inspect labels/objective, or resume from "
                "the last good checkpoint (lightgbm_tpu.training)",
                round_index=i)

    # -- checkpoint state (r13) ------------------------------------------
    def checkpoint_state(self) -> tuple:
        """Complete training state as ``(arrays, meta)`` host payloads.

        Everything a bit-identical resume needs beyond the params:
        the forest (raw f32 buffers — NOT the decimal JSON codec), the
        train predictions and current bagging mask exactly as the next
        round would consume them, the base PRNG key, round counters, and
        the shrinkage base.  All other per-round randomness (bagging /
        feature-fraction / GOSS keys) is re-derived from params + round
        index by ``_sample_bag_and_fmask`` and the round functions, so
        no raw RNG stream state beyond the base key exists.  Sharded
        arrays gather to host here; resume re-shards lazily exactly like
        a fresh run does.
        """
        if self.train_set is None or self._pred_train is None:
            raise ValueError(
                "checkpoint_state() needs an attached training Dataset — "
                "this booster holds no round state")
        import dataclasses

        from ..data.sketch import schema_digest
        from .tree import tree_to_arrays

        p = self.params
        params_dict = dataclasses.asdict(p)
        extra = dict(params_dict.pop("extra", None) or {})
        params_dict.update(extra)
        arrays = {
            "pred_train": np.asarray(self._pred_train),
            "bag": np.asarray(self._bag),
            "key": np.asarray(self._key),
        }
        init_meta = None
        if isinstance(self.init_score_, np.ndarray):
            arrays["init_score"] = np.asarray(self.init_score_, np.float32)
        else:
            init_meta = float(self.init_score_)
        trees = list(self.trees)   # materializes stacked-segment views
        for t_idx, t in enumerate(trees):
            for fname, arr in tree_to_arrays(t).items():
                arrays[f"tree{t_idx:05d}/{fname}"] = arr
        parallel = {"tree_learner": p.tree_learner}
        if getattr(self, "_dp_mesh", None) is not None:
            parallel["n_devices"] = int(self._dp_mesh.devices.size)
            if getattr(self, "_dp2", False):
                parallel["mesh"] = "dp2"
            else:
                merge_mode, voting_k = self._dp_merge_mode()
                parallel["merge_mode"] = merge_mode
                parallel["voting_k"] = int(voting_k)
        elif getattr(self, "_fp_mesh", None) is not None:
            parallel["n_devices"] = int(self._fp_mesh.devices.size)
        meta = {
            "params": params_dict,
            "iter": int(self._iter),
            "num_trees": len(trees),
            "base_lr": float(self._base_lr),
            "init_score": init_meta,
            "best_iteration": int(self.best_iteration),
            "streamed": bool(getattr(self, "_streamed", False)),
            "parallel": parallel,
            "schema_digest": schema_digest(self.train_set.bin_mapper),
        }
        if getattr(self, "_screener", None) is not None:
            # r20: the EWMA vector + refresh counter ARE the screener's
            # whole state — with them restored, plan() reproduces the
            # identical active set every remaining round
            ema, rounds_since = self._screener.state()
            arrays["screen_ema"] = ema
            meta["screen_rounds_since_refresh"] = rounds_since
        return arrays, meta

    def restore_checkpoint_state(self, arrays, meta) -> None:
        """Inverse of :meth:`checkpoint_state` onto a booster already
        constructed with the SAME params and an equivalently-binned
        training Dataset (``training.checkpoint.resume_booster`` wraps
        the construction + schema validation)."""
        from .tree import tree_from_arrays

        trees = []
        for t_idx in range(int(meta["num_trees"])):
            prefix = f"tree{t_idx:05d}/"
            fields = {k[len(prefix):]: v for k, v in arrays.items()
                      if k.startswith(prefix)}
            trees.append(tree_from_arrays(fields))
        self.trees = _TreeStore(trees)
        self._forest_cache = None
        self._iter = int(meta["iter"])
        self._base_lr = float(meta["base_lr"])
        self.best_iteration = int(meta["best_iteration"])
        self.init_score_ = (
            float(meta["init_score"]) if meta.get("init_score") is not None
            else np.asarray(arrays["init_score"], np.float32))
        self._pred_train = jnp.asarray(arrays["pred_train"])
        self._bag = jnp.asarray(arrays["bag"])
        self._key = jnp.asarray(arrays["key"])
        if "screen_ema" in arrays:
            state = (np.asarray(arrays["screen_ema"], np.float32),
                     int(meta.get("screen_rounds_since_refresh", 0)))
            if getattr(self, "_screener", None) is not None:
                self._screener.restore(*state)
            else:
                # restore arrived before _setup_training (continuation
                # flows attach the Dataset later) — stash for it
                self._screen_restore = state
        if getattr(self, "_dp_mesh", None) is not None and \
                not getattr(self, "_dp_stats_only", False):
            # elastic resume (r19): the checkpoint gathered these to host
            # under the WRITER's device count; re-shard onto THIS run's
            # row mesh — values are unchanged, only placement moves, so a
            # D=8 checkpoint resumes bit-identically at D=4 (and back)
            from ..parallel.data_parallel import shard_rows

            self._pred_train, self._bag = shard_rows(
                self._dp_mesh, self._pred_train, self._bag)

    def _screen_view(self, bins, active_ids):
        """Compacted ``[N, F_active]`` gather of the binned matrix for a
        screened round, cached on (matrix identity, active-id bytes) so
        consecutive rounds with an unchanged active set reuse the device
        gather instead of re-materializing it."""
        ck = active_ids.tobytes()
        c = self._screen_bins_cache
        if c is not None and c[0] is bins and c[1] == ck:
            return c[2]
        out = jnp.take(bins, jnp.asarray(active_ids, jnp.int32), axis=1)
        self._screen_bins_cache = (bins, ck, out)
        return out

    def _sample_bag_and_fmask(self, i: int, screen_ids=None):
        """Per-round stochasticity shared by plain and DART rounds: resample
        the bagging mask on schedule (updating ``self._bag``, kept
        mesh-sharded under DP) and return this round's feature mask.  RNG
        streams are keyed by round index so any round path reproduces the
        same draws.  ``screen_ids`` (r20) threads the screener's active
        set in as the BASE mask, so ``feature_fraction`` samples within
        it — composition through the one mask layer, never a second
        masking pass."""
        ds = self.train_set
        p = self.params
        if p.bagging_freq > 0 and p.bagging_fraction < 1.0 and \
                i % p.bagging_freq == 0:
            bkey = jax.random.fold_in(
                jax.random.PRNGKey(p.bagging_seed + p.seed), i)
            self._bag = _bag_fn()(
                bkey, ds.row_mask, jnp.float32(p.bagging_fraction),
                jnp.float32(ds.num_data_))
            if getattr(self, "_dp_mesh", None) is not None:
                # keep the bag mesh-sharded: sampling ran on the default
                # device, and leaving it there would reshard every round
                from ..parallel.data_parallel import shard_rows
                self._bag = shard_rows(self._dp_mesh, self._bag)
        n_cols = int(ds.num_feature_)  # == X_binned.shape[1]; X_binned is
        # None under streaming (the codes live in ds.block_store)
        base = None
        if screen_ids is not None:
            bm = np.zeros(n_cols, np.float32)
            bm[screen_ids] = 1.0
            base = jnp.asarray(bm)
        if p.feature_fraction < 1.0:
            fkey = jax.random.fold_in(
                jax.random.PRNGKey(p.feature_fraction_seed + p.seed), i)
            if base is not None:
                return _feature_mask_fn(n_cols, True)(
                    fkey, jnp.float32(p.feature_fraction), base)
            return _feature_mask_fn(n_cols)(
                fkey, jnp.float32(p.feature_fraction))
        return base if base is not None else jnp.ones(n_cols, jnp.float32)

    # -- round step ------------------------------------------------------
    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """Run one boosting round (LightGBM Booster.update)."""
        if train_set is not None and train_set is not self.train_set:
            if self.train_set is None and len(self.trees) > 0:
                # a Booster(model_file=...) continuing training: attach
                # the dataset AND replay the loaded forest into the train
                # predictions so the gradients continue where the saved
                # run left off (r13 satellite — _setup_training alone
                # resets predictions to the init score and the next round
                # would re-learn the forest's contribution)
                self._attach_continuation(train_set)
            else:
                self.train_set = train_set
                self._setup_training()
        if self.params.boosting == "dart":
            return self._dart_round()
        ds = self.train_set
        p = self.params
        i = self._iter

        screener = getattr(self, "_screener", None)
        active_ids = None
        if screener is not None:
            active_ids, _ = screener.plan()   # None on refresh rounds
        fmask = self._sample_bag_and_fmask(i, screen_ids=active_ids)
        if active_ids is not None:
            # screened round: compact the mask to [F_active] — bins and
            # comms compact below per branch; exactly two program shapes
            # per config (full F on refresh rounds, F_active otherwise)
            fmask = jnp.take(fmask, jnp.asarray(active_ids, jnp.int32))

        goss_k = None
        eff_rows = int(ds.row_mask.shape[0])
        if p.boosting == "goss":
            goss_k = (int(p.top_rate * ds.num_data_),
                      int(p.other_rate * ds.num_data_))
            if self._num_class == 1:  # mc uses the masked (non-compacted) path
                eff_rows = goss_k[0] + goss_k[1]
        _dp_m = getattr(self, "_dp_mesh", None)
        check_int8_row_limit(
            p, eff_rows,
            int(_dp_m.shape["data"]) if _dp_m is not None else 1)
        round_key = jax.random.fold_in(self._key, i)
        if getattr(self, "_streamed", False):
            from ..data.stream_grow import (stream_goss_round,
                                            stream_plain_round)

            if p.extra.get("finite_screen", True):
                self._screen_finite(i)

            renew_alpha = getattr(self.obj, "renew_alpha", None)
            renew_scale = getattr(self.obj, "renew_scale", None)
            hist_impl = p.extra.get("hist_impl", "auto")
            hist_dtype = resolve_hist_dtype(p, eff_rows)
            wave_width = resolve_wave_width(p, eff_rows)
            store = ds.block_store
            if active_ids is not None:
                # screened round out-of-core: only the active columns
                # cross PCIe (the screener doubling as the hot-feature
                # prior for GOSS-at-the-source row gathers)
                from ..data.block_store import ColumnViewStore

                store = ColumnViewStore(store, active_ids)
            if getattr(self, "_stream_dp", False):
                # r19: streamed × dp — per-shard stores, per-block-round
                # merges; GOSS samples per shard at the source
                from ..data.stream_dp import (drain_shard_odometers,
                                              stream_dp_goss_round,
                                              stream_dp_plain_round)

                merge_mode, _ = self._dp_merge_mode()
                wire_dtype, merge_chunks = self._dp_wire(
                    merge_mode, eff_rows)
                shards = self._stream_shards
                if active_ids is not None:
                    from ..data.block_store import ColumnViewStore

                    shards = [ColumnViewStore(sh, active_ids)
                              for sh in shards]
                if goss_k is not None:
                    n_sh = len(self._stream_shards)
                    goss_k_shard = (max(goss_k[0] // n_sh, 1),
                                    max(goss_k[1] // n_sh, 1))
                    tree, new_pred = stream_dp_goss_round(
                        shards, self._dp_mesh,
                        self._obj_key, self._dp_y, self._dp_w,
                        self._bag, self._pred_train, fmask, self._hyper,
                        round_key, goss_k_shard, float(p.top_rate),
                        float(p.other_rate), p.seed * 1_000_003 + i,
                        p.num_leaves, self._num_bins, hist_impl,
                        hist_dtype, wave_width, merge_mode, wire_dtype,
                        merge_chunks)
                else:
                    tree, new_pred = stream_dp_plain_round(
                        shards, self._dp_mesh,
                        self._obj_key, self._dp_y, self._dp_w,
                        self._bag, self._pred_train, fmask, self._hyper,
                        p.num_leaves, self._num_bins, hist_impl,
                        hist_dtype, wave_width, p.boosting == "rf",
                        merge_mode, wire_dtype, merge_chunks)
                drain_shard_odometers(ds.block_store,
                                      self._stream_shards)
            elif goss_k is not None:
                tree, new_pred = stream_goss_round(
                    store, self._obj_key, ds.y, self._w_eff,
                    self._bag, self._pred_train, fmask, self._hyper,
                    round_key, goss_k, float(p.top_rate),
                    float(p.other_rate), p.seed * 1_000_003 + i,
                    p.num_leaves, self._num_bins, hist_impl, hist_dtype,
                    wave_width, renew_alpha, renew_scale)
            else:
                tree, new_pred = stream_plain_round(
                    store, self._obj_key, ds.y, self._w_eff,
                    self._bag, self._pred_train, fmask, self._hyper,
                    p.num_leaves, self._num_bins, hist_impl, hist_dtype,
                    wave_width, p.boosting == "rf", renew_alpha,
                    renew_scale)
        elif getattr(self, "_fp_mesh", None) is not None:
            from ..parallel.feature_parallel import make_fp_train_step

            fn = make_fp_train_step(
                self._fp_mesh, self._obj_key, p.num_leaves, self._num_bins,
                p.extra.get("hist_impl", "auto"),
                int(p.extra.get("row_chunk", 131072)), p.boosting == "rf",
                resolve_hist_dtype(p, eff_rows), self._num_class,
                self._cat_key, resolve_wave_width(p, eff_rows))
            from .feature_mask import pad_feature_mask

            fmask_p = pad_feature_mask(fmask, self._fp_width)
            tree, new_pred = fn(self._fp_bins, ds.y, self._w_eff, self._bag,
                                self._pred_train, fmask_p, self._hyper,
                                round_key)
        elif getattr(self, "_dp2", False):
            # 2-D rows x features mesh (r10 default at D>=8, F>=64):
            # per-block histograms psum over rows, split exchange over
            # columns — see parallel.feature_parallel.make_dp_fp_train_step
            from ..parallel.feature_parallel import make_dp_fp_train_step

            fn = make_dp_fp_train_step(
                self._dp_mesh, self._obj_key, p.num_leaves, self._num_bins,
                p.extra.get("hist_impl", "auto"),
                int(p.extra.get("row_chunk", 131072)), p.boosting == "rf",
                resolve_hist_dtype(p, eff_rows),
                resolve_wave_width(p, eff_rows))
            from .feature_mask import pad_feature_mask

            fmask_p = pad_feature_mask(fmask, self._dp2_width)
            tree, new_pred = fn(self._dp_bins, self._dp_y, self._dp_w,
                                self._bag, self._pred_train, fmask_p,
                                self._hyper, round_key)
        elif getattr(self, "_dp_mesh", None) is not None and \
                getattr(self, "_dp_stats_only", False):
            from ..parallel.data_parallel import (make_dp_grow_step,
                                                  shard_rows)

            g, h = self._dp_grad_jit(self._pred_train, ds.y, self._w_eff)
            bag = self._bag
            stats = jnp.stack(
                [g * bag, h * bag, (bag > 0).astype(jnp.float32)], axis=-1)
            stats = shard_rows(self._dp_mesh, stats)
            merge_mode, voting_k = self._dp_merge_mode()
            wire_dtype, merge_chunks = self._dp_wire(merge_mode, eff_rows)
            fn = make_dp_grow_step(
                self._dp_mesh, p.num_leaves, self._num_bins,
                p.extra.get("hist_impl", "auto"),
                int(p.extra.get("row_chunk", 131072)),
                resolve_wave_width(p, eff_rows),
                resolve_hist_dtype(p, eff_rows),
                merge_mode, voting_k, wire_dtype, merge_chunks)
            dp_bins = (self._dp_bins if active_ids is None
                       else self._screen_view(self._dp_bins, active_ids))
            tree, row_leaf = fn(dp_bins, stats, fmask, self._hyper,
                                round_key)
            new_pred = self._pred_train + jnp.float32(p.learning_rate) \
                * lookup_values(row_leaf, tree.leaf_value)
        elif getattr(self, "_dp_mesh", None) is not None and \
                self._linear_k is not None:
            from ..parallel.data_parallel import make_dp_linear_train_step

            merge_mode, voting_k = self._dp_merge_mode()
            wire_dtype, merge_chunks = self._dp_wire(merge_mode, eff_rows)
            fn = make_dp_linear_train_step(
                self._dp_mesh, self._obj_key, p.num_leaves, self._num_bins,
                p.extra.get("hist_impl", "auto"),
                int(p.extra.get("row_chunk", 131072)),
                resolve_hist_dtype(p, eff_rows),
                resolve_wave_width(p, eff_rows), self._linear_k,
                merge_mode, voting_k, wire_dtype, merge_chunks)
            tree, new_pred = fn(self._dp_bins, self._dp_y, self._dp_w,
                                self._bag, self._pred_train, self._dp_xraw,
                                fmask, self._hyper, round_key)
        elif getattr(self, "_dp_mesh", None) is not None:
            from ..parallel.data_parallel import make_dp_train_step

            goss_k_shard = None
            if goss_k is not None:
                # per-shard compaction (upstream's data-parallel GOSS
                # samples per machine); multiclass GOSS re-weights without
                # compacting, so its static sizing keeps the full rows
                n_dev = self._dp_mesh.devices.size
                goss_k_shard = (max(goss_k[0] // n_dev, 1),
                                max(goss_k[1] // n_dev, 1))
                if self._num_class == 1:
                    eff_rows = sum(goss_k_shard)
            merge_mode, voting_k = self._dp_merge_mode()
            wire_dtype, merge_chunks = self._dp_wire(merge_mode, eff_rows)
            fn = make_dp_train_step(
                self._dp_mesh, self._obj_key, p.num_leaves, self._num_bins,
                p.extra.get("hist_impl", "auto"),
                int(p.extra.get("row_chunk", 131072)), p.boosting == "rf",
                resolve_wave_width(p, eff_rows),
                resolve_hist_dtype(p, eff_rows), goss_k_shard,
                self._mono_key, p.extra_trees, self._nbins_key,
                self._num_class, self._ic_key, self._cat_key,
                merge_mode, voting_k, wire_dtype, merge_chunks)
            dp_bins = (self._dp_bins if active_ids is None
                       else self._screen_view(self._dp_bins, active_ids))
            tree, new_pred = fn(dp_bins, self._dp_y, self._dp_w,
                                self._bag, self._pred_train, fmask,
                                self._hyper, round_key)
        else:
            fn = _round_fn(self._obj_key, p.num_leaves, self._num_bins,
                           p.extra.get("hist_impl", "auto"),
                           int(p.extra.get("row_chunk", 131072)),
                           p.boosting == "rf", self._num_class,
                           resolve_hist_dtype(p, eff_rows),
                           resolve_wave_width(p, eff_rows), goss_k,
                           self._cat_key, self._mono_key, p.extra_trees,
                           self._nbins_key, self._linear_k, self._ic_key,
                           bynode_off=p.feature_fraction_bynode >= 1.0)
            if self._linear_k is not None:
                tree, new_pred = fn(ds.X_binned, ds.y, self._w_eff,
                                    self._bag, self._pred_train, fmask,
                                    self._hyper, round_key, self._xraw)
            else:
                bins = (ds.X_binned if active_ids is None
                        else self._screen_view(ds.X_binned, active_ids))
                tree, new_pred = fn(bins, ds.y, self._w_eff,
                                    self._bag, self._pred_train, fmask,
                                    self._hyper, round_key)
        if active_ids is not None:
            # the tree grew in compacted space — gather the winner ids
            # back to GLOBAL features before anything downstream
            # (predict, valid eval, checkpoints, the screener) sees it
            from .feature_mask import remap_split_features

            tree = remap_split_features(tree, active_ids)
        if screener is not None:
            # refresh rounds observe too — that is exactly how a feature
            # whose gain appears late re-enters the active set
            screener.observe(np.asarray(tree.split_feature),
                             np.asarray(tree.split_gain))
        if p.boosting != "rf":
            self._pred_train = new_pred
        if p.boosting != "rf" and p.learning_rate != self._base_lr:
            # reset_parameter schedule: bake lr_i/base into stored values so
            # the uniform predict-time shrink (base) reproduces lr_i exactly
            scale = jnp.float32(p.learning_rate / self._base_lr)
            tree = tree._replace(
                leaf_value=tree.leaf_value * scale,
                linear_coef=(None if tree.linear_coef is None
                             else tree.linear_coef * scale))
        self.trees.append(tree)
        self._forest_cache = None
        # incremental valid-set predictions
        shrink = 1.0 if p.boosting == "rf" else self._base_lr
        if self._linear_k is not None:
            add_lin = _linear_tree_pred_fn(self._depth_cap)
            for idx, (name, vds, vpred) in enumerate(self._valid):
                self._valid[idx] = (
                    name, vds, add_lin(vpred, tree, vds.X_binned,
                                       vds._xraw_dev, jnp.float32(shrink)))
        else:
            add_tree = _tree_pred_fn(p.num_leaves, self._num_class)
            for idx, (name, vds, vpred) in enumerate(self._valid):
                self._valid[idx] = (
                    name, vds, add_tree(vpred, tree, vds.X_binned,
                                        jnp.float32(shrink)))
        self._iter += 1
        return False

    def can_fuse_rounds(self) -> bool:
        """Whether update_many can run rounds as one scanned device program
        (matching the host loop's RNG streams exactly)."""
        p = self.params
        return (self._num_class == 1
                and getattr(self, "_dp_mesh", None) is None
                and getattr(self, "_fp_mesh", None) is None
                and not getattr(self, "_streamed", False)
                and p.boosting in ("gbdt", "rf", "goss")
                and not p.linear_tree
                and p.feature_screen == "off"  # screener plans per round
                and not self._valid)

    def update_many(self, k: int) -> None:
        """Run ``k`` boosting rounds fused into scanned device programs.

        Falls back to per-round update() when the configuration needs
        host-side work between rounds (valid-set eval, multiclass,
        DP/FP mesh, DART's dropout bookkeeping).  Segments of at most
        ``fused_segment_rounds`` (default 25) bound per-dispatch runtime —
        one very long device execution can trip the TPU runtime watchdog —
        and keep the compile cache small (one program per segment length).
        """
        if k <= 0:
            return
        if not self.can_fuse_rounds():
            for _ in range(k):
                self.update()
            return
        ds = self.train_set
        p = self.params
        # default segment length scales inversely with row count so one
        # dispatch stays a few device-seconds at most (very long single
        # executions crash/restart the remote TPU worker); big data pays
        # per-dispatch overhead rarely anyway — compute dominates there.
        # TINY shapes (rows x features <= 2^20 cells — the diamonds
        # regime) fuse up to 200 rounds into ONE dispatch: device time
        # stays well under a second, and per-dispatch round trips are the
        # entire wall-clock story there (~100 ms each through a sick
        # tunnel x 8 segments was most of the r4 diamonds budget)
        n_pad = int(ds.row_mask.shape[0])
        cells = n_pad * max(int(ds.X_binned.shape[1]), 1)
        if cells <= (1 << 20):
            seg_default = max(1, min(200, (1 << 24) // max(n_pad, 1)))
        else:
            seg_default = max(1, min(25, (1 << 22) // max(n_pad, 1)))
        seg = max(1, int(p.extra.get("fused_segment_rounds", seg_default)))
        use_bagging = p.bagging_freq > 0 and p.bagging_fraction < 1.0
        use_ff = p.feature_fraction < 1.0
        bag_key = jax.random.PRNGKey(p.bagging_seed + p.seed)
        ff_key = jax.random.PRNGKey(p.feature_fraction_seed + p.seed)
        eff_rows = int(ds.row_mask.shape[0])
        goss_k = None
        if p.boosting == "goss":
            goss_k = (int(p.top_rate * ds.num_data_),
                      int(p.other_rate * ds.num_data_))
            eff_rows = goss_k[0] + goss_k[1]
        while k > 0:
            n_rounds = min(k, seg)
            fn = _multi_round_fn(
                self._obj_key, p.num_leaves, self._num_bins,
                p.extra.get("hist_impl", "auto"),
                int(p.extra.get("row_chunk", 131072)), p.boosting == "rf",
                resolve_hist_dtype(p, eff_rows),
                resolve_wave_width(p, eff_rows), n_rounds,
                p.bagging_freq if use_bagging else 0, use_ff,
                self._cat_key, goss_k, self._mono_key, p.extra_trees,
                self._nbins_key, self._ic_key,
                bynode_off=p.feature_fraction_bynode >= 1.0)
            pred, bag, trees = fn(
                ds.X_binned, ds.y, self._w_eff, self._bag, self._pred_train,
                self._hyper, self._key, bag_key, ff_key, ds.row_mask,
                jnp.float32(ds.num_data_), jnp.int32(self._iter),
                jnp.float32(p.bagging_fraction),
                jnp.float32(p.feature_fraction))
            self._pred_train = pred
            self._bag = bag
            if not isinstance(self.trees, _TreeStore):
                self.trees = _TreeStore(self.trees)   # e.g. loaded model
            self.trees.append_stacked(trees, n_rounds)
            self._iter += n_rounds
            self._forest_cache = None
            k -= n_rounds

    def _dart_round(self) -> bool:
        """One DART boosting round (upstream dart.hpp semantics).

        A random subset of existing trees is "dropped": the new tree fits
        gradients of the ensemble WITHOUT them, then (non-xgboost mode) the
        new tree is scaled by 1/(k+1) and each dropped tree rescaled to
        k/(k+1) so the expected ensemble output is preserved (MART's
        shrinkage-induced over-specialization fix — Rashmi &
        Gilad-Bachrach 2015).  Stored leaf values carry the DART scales
        directly, so the uniform learning-rate shrink at predict time stays
        correct; with probability ``skip_drop`` a round degenerates to
        plain gbdt.
        """
        ds = self.train_set
        p = self.params
        i = self._iter
        fmask = self._sample_bag_and_fmask(i)

        rng = np.random.default_rng(p.drop_seed + p.seed + i * 7919)
        n_t = len(self.trees)
        dropped: List[int] = []
        if n_t > 0 and p.drop_rate > 0 and rng.random() >= p.skip_drop:
            m = rng.random(n_t) < p.drop_rate
            dropped = [int(t) for t in np.flatnonzero(m)]
            if p.max_drop > 0 and len(dropped) > p.max_drop:
                dropped = sorted(
                    int(t) for t in rng.choice(dropped, p.max_drop,
                                               replace=False))
        k = len(dropped)
        nc = self._num_class
        lr = jnp.float32(p.learning_rate)
        add = _tree_pred_fn(self._depth_cap, nc)

        drop_sum = None
        if k > 0:
            # ONE stacked forest pass computes the dropped trees' summed raw
            # values per dataset (not k separate single-tree dispatches)
            caps = {int(self.trees[t].split_feature.shape[-1])
                    for t in dropped}
            cap = max(caps)
            stack = jax.tree.map(
                lambda *xs: jnp.stack(xs),
                *[pad_tree(self.trees[t], cap) for t in dropped])

            def dropped_sum(bins):
                if nc > 1:  # [k, K, M] stacked trees -> [n, K] summed raw
                    return _predict_forest_mc(stack, bins, 1.0, 0.0, k,
                                              self._depth_cap)
                return predict_forest_binned(
                    stack, bins, 1.0, 0.0, jnp.int32(k), self._depth_cap)

            drop_sum = dropped_sum(ds.X_binned)

        pred = self._pred_train
        if k > 0:
            pred = pred - lr * drop_sum

        eff_rows = int(ds.row_mask.shape[0])
        fn = _round_fn(self._obj_key, p.num_leaves, self._num_bins,
                       p.extra.get("hist_impl", "auto"),
                       int(p.extra.get("row_chunk", 131072)), False, nc,
                       resolve_hist_dtype(p, eff_rows),
                       resolve_wave_width(p, eff_rows), None, self._cat_key,
                       self._mono_key, p.extra_trees, self._nbins_key,
                       None, self._ic_key,
                       bynode_off=p.feature_fraction_bynode >= 1.0)
        round_key = jax.random.fold_in(self._key, i)
        tree, new_pred = fn(ds.X_binned, ds.y, self._w_eff, self._bag, pred,
                            fmask, self._hyper, round_key)

        if k > 0:
            # upstream Normalize(): on drop rounds the new tree's weight is
            # 1/(k+1) (xgboost mode: lr/(k+lr)) INSTEAD of the learning
            # rate, and dropped trees rescale to k/(k+1) (resp. k/(k+lr)).
            # Stored values are raw (uniform lr applied at predict), so the
            # baked factor divides lr back out.
            lr_f = float(p.learning_rate)
            if p.xgboost_dart_mode:
                new_scale = 1.0 / (k + lr_f)
                drop_scale = k / (k + lr_f)
            else:
                new_scale = 1.0 / ((k + 1.0) * lr_f)
                drop_scale = k / (k + 1.0)
            tree = tree._replace(
                leaf_value=tree.leaf_value * jnp.float32(new_scale))
            new_pred = pred + (new_pred - pred) * jnp.float32(new_scale)
            # valid-set deltas from rescaling dropped trees — one stacked
            # forest pass per valid set, using the OLD leaf values
            for idx, (name, vds, vpred) in enumerate(self._valid):
                vsum = dropped_sum(vds.X_binned)
                self._valid[idx] = (
                    name, vds,
                    vpred + lr * jnp.float32(drop_scale - 1.0) * vsum)
            for t in dropped:
                self.trees[t] = self.trees[t]._replace(
                    leaf_value=self.trees[t].leaf_value
                    * jnp.float32(drop_scale))
            # re-add the (now rescaled) dropped trees' contribution
            new_pred = new_pred + lr * jnp.float32(drop_scale) * drop_sum

        self._pred_train = new_pred
        self.trees.append(tree)
        self._forest_cache = None
        for idx, (name, vds, vpred) in enumerate(self._valid):
            self._valid[idx] = (name, vds,
                                add(vpred, tree, vds.X_binned, lr))
        self._iter += 1
        return False

    # -- evaluation ------------------------------------------------------
    def _metric_names(self) -> List[str]:
        names = [m for m in self.params.metric if m != "none"]
        if not names:
            default = default_metric_for_objective(self.params.objective)
            if default != "none":
                names = [default]
        return names

    def _eval_on(self, pred_raw, ds: Dataset, name: str):
        metric_names = tuple(self._metric_names())
        if not metric_names:
            return []
        out = []
        # ranking metrics need the query grouping — they bypass the plain
        # (pred, y, w) metric signature via the grouped eval path
        plain = tuple(m for m in metric_names if m not in ("ndcg", "map"))
        if plain:
            fn = _eval_fn(self._obj_key, plain,
                          (self.params.alpha,
                           self.params.tweedie_variance_power))
            vals = fn(pred_raw, ds.y, ds.w)
            for mname, v in zip(plain, vals):
                m = get_metric(mname, self.params)
                out.append((name, mname, float(v), m.higher_better))
        grouped = tuple(m for m in metric_names if m in ("ndcg", "map"))
        if grouped:
            from ..ranking import eval_ranking
            for mname, val, hib in eval_ranking(
                    pred_raw, ds, self.params.eval_at,
                    self.params.label_gain, metrics=grouped):
                out.append((name, mname, val, hib))
        return out

    def eval_train(self, feval=None):
        pred = self._pred_train_effective()
        res = self._eval_on(pred, self.train_set, "training")
        return res + self._feval_results(feval, pred, self.train_set,
                                         "training")

    def eval_valid(self, feval=None):
        out = []
        for name, vds, vpred in self._valid:
            vp = self._rf_scale(vpred)
            out.extend(self._eval_on(vp, vds, name))
            out.extend(self._feval_results(feval, vp, vds, name))
        return out

    def _feval_results(self, feval, pred_raw, ds, name):
        if feval is None:
            return []
        fevals = feval if isinstance(feval, (list, tuple)) else [feval]
        out = []
        n = ds.num_data_
        pred_host = np.asarray(self.obj.transform(pred_raw))[:n]
        for f in fevals:
            mname, val, hib = f(pred_host, ds)
            out.append((name, mname, float(val), bool(hib)))
        return out

    def _rf_scale(self, pred_raw):
        if self.params.boosting == "rf" and self._iter > 0:
            return (pred_raw - self.init_score_) / self._iter + self.init_score_
        return pred_raw

    def _pred_train_effective(self):
        if self.params.boosting == "rf":
            # rf keeps _pred_train at init; reconstruct mean over trees lazily
            if not self.trees:
                return self._pred_train
            forest = self._stacked_forest()
            if self._num_class > 1:
                return _predict_forest_mc(
                    forest, self.train_set.X_binned, 1.0 / self._iter,
                    self.init_score_, self._iter, self.params.num_leaves)
            pred = predict_forest_binned(
                forest, self.train_set.X_binned, 1.0 / self._iter,
                self.init_score_, jnp.int32(self._iter), self.params.num_leaves)
            return pred
        return self._pred_train

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.construct()
        if getattr(data, "is_streamed", False):
            raise ValueError(
                f"valid set '{name}' is a streamed (from_blocks) dataset — "
                "incremental valid-set scoring needs a resident binned "
                "matrix; bin the valid set in memory with "
                "reference=<streamed train set> instead")
        if data.y is None:
            raise ValueError(f"valid set '{name}' requires a label")
        k = self._num_class
        if k > 1:
            vpred = jnp.broadcast_to(
                jnp.asarray(self.init_score_)[None, :],
                (int(data.row_mask.shape[0]), k))
        else:
            vpred = jnp.full(data.row_mask.shape, self.init_score_,
                             jnp.float32)
        # replay existing trees (valid sets are usually added before round 0)
        shrink = (1.0 if self.params.boosting == "rf"
                  else getattr(self, "_base_lr", self.params.learning_rate))
        if getattr(self, "_linear_k", None) is not None:
            raw = data.raw_data
            if raw is None or isinstance(raw, str):
                raise ValueError(
                    "linear_tree valid sets need raw feature values "
                    "(free_raw_data=False, in-memory matrix)")
            data._xraw_dev = self._raw_to_device(
                raw, int(data.row_mask.shape[0]))
            add_lin = _linear_tree_pred_fn(self._depth_cap)
            for tree in self.trees:
                vpred = add_lin(vpred, tree, data.X_binned, data._xraw_dev,
                                jnp.float32(shrink))
        else:
            add_tree = _tree_pred_fn(self._depth_cap, k)
            for tree in self.trees:
                vpred = add_tree(vpred, tree, data.X_binned,
                                 jnp.float32(shrink))
        self._valid.append((name, data, vpred))
        return self

    # -- prediction ------------------------------------------------------
    def _stacked_forest(self) -> Tree:
        if self._forest_cache is None or \
                getattr(self, "_forest_count", -1) != len(self.trees):
            if not self.trees:
                raise ValueError("no trees trained yet")
            trees = self.trees
            caps = (trees.cap_set() if isinstance(trees, _TreeStore)
                    else {int(t.split_feature.shape[-1]) for t in trees})
            if len(caps) > 1:  # init_model continuation, different num_leaves
                cap = max(caps)
                trees = [pad_tree(t, cap) for t in trees]
            if isinstance(trees, _TreeStore):
                runs = trees.stacked_runs()
                forest = (runs[0] if len(runs) == 1 else jax.tree.map(
                    lambda *xs: jnp.concatenate(xs), *runs))
            else:
                forest = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)
            from ..ops.predict import DEFAULT_TREE_CHUNK, forest_depth_cap
            self._forest_depth = forest_depth_cap(forest)
            # pad the tree axis to a chunk multiple so predict() compiles
            # once per forest-size bucket, not once per forest size (padded
            # trees are zeroed and excluded by the traced round mask)
            t_real = forest.leaf_value.shape[0]
            t_pad = -(-t_real // DEFAULT_TREE_CHUNK) * DEFAULT_TREE_CHUNK
            if t_pad != t_real:
                forest = jax.tree.map(
                    lambda a: jnp.concatenate(
                        [a, jnp.zeros((t_pad - t_real,) + a.shape[1:],
                                      a.dtype)]), forest)
            self._forest_cache = forest
            self._forest_count = len(self.trees)
        return self._forest_cache

    def predict(
        self,
        data,
        num_iteration: Optional[int] = None,
        raw_score: bool = False,
        pred_leaf: bool = False,
        pred_contrib: bool = False,
        start_iteration: int = 0,
        ntree_limit: Optional[int] = None,  # xgboost-style alias
        **kwargs,
    ) -> np.ndarray:
        """Predict on raw (unbinned) features.

        ``num_iteration``/``ntree_limit`` truncate to the first k trees —
        the staged-prediction contract of bagging_boosting.ipynb:136.
        ``pred_contrib`` returns exact path-dependent TreeSHAP values
        ``[n, F+1]`` (``[n, K*(F+1)]`` multiclass) in raw-score space with
        the expected value in the last column, matching LightGBM's
        ``predict(..., pred_contrib=True)`` contract (ops/shap.py).
        """
        if num_iteration is None:
            num_iteration = ntree_limit
        if num_iteration is None:
            # None -> best_iteration when early stopping found one
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else len(self.trees))
        elif num_iteration <= 0:
            # explicit <= 0 -> ALL trees (LightGBM contract)
            num_iteration = len(self.trees)
        start_iteration = max(int(start_iteration), 0)
        num_iteration = min(num_iteration, len(self.trees) - start_iteration)
        if isinstance(data, Dataset):
            raise TypeError(
                "predict() expects a raw feature matrix, not a Dataset "
                "(matching lightgbm)")
        from ..dataset import _to_2d_float_array
        X = _to_2d_float_array(data)
        codes = self._bin_mapper_for_predict().transform(X)
        bins = jnp.asarray(codes)
        if pred_leaf:
            forest = self._stacked_forest()
            # LightGBM contract: [n, num_iteration * num_class], iteration-
            # major, values are per-tree leaf ordinals in [0, num_leaves)
            # — not node-array slots (ADVICE r1): rank leaf slots by node id
            leaves = []
            for t in range(start_iteration, start_iteration + num_iteration):
                for c in range(self._num_class):
                    tree = jax.tree.map(
                        (lambda a: a[t]) if self._num_class == 1
                        else (lambda a: a[t, c]), forest)
                    node = self._leaf_index(tree, bins)
                    ordinal = jnp.cumsum(tree.is_leaf.astype(jnp.int32)) - 1
                    leaves.append(np.asarray(ordinal[node]))
            return np.stack(leaves, axis=1)
        if pred_contrib:
            if self.trees and self.trees[0].linear_feat is not None:
                raise NotImplementedError(
                    "pred_contrib with linear_tree is not supported")
            return self._pred_contrib(bins, start_iteration, num_iteration)
        shrink = (1.0 if self.params.boosting == "rf"
                  else getattr(self, "_base_lr", self.params.learning_rate))
        if self.trees and self.trees[0].linear_feat is not None:
            xr = np.ascontiguousarray(X, dtype=np.float32)
            add_lin = _linear_tree_pred_fn(self._depth_cap)
            raw = jnp.full(bins.shape[0], float(self.init_score_),
                           jnp.float32)
            xr_dev = jnp.asarray(xr)
            for t in range(start_iteration,
                           start_iteration + num_iteration):
                raw = add_lin(raw, self.trees[t], bins, xr_dev,
                              jnp.float32(shrink))
            if raw_score:
                return np.asarray(raw)
            return np.asarray(self.obj.transform(raw))
        forest = self._stacked_forest()
        k = self._num_class
        if k > 1:
            raw = _predict_forest_mc(
                forest, bins, shrink, self.init_score_, num_iteration,
                min(self._depth_cap, self._forest_depth),
                start_iteration=start_iteration)          # [n, K]
            if self.params.boosting == "rf" and num_iteration > 0:
                raw = ((raw - jnp.asarray(self.init_score_)[None, :])
                       / num_iteration
                       + jnp.asarray(self.init_score_)[None, :])
        else:
            raw = predict_forest_binned(
                forest, bins, jnp.float32(shrink), self.init_score_,
                jnp.int32(num_iteration),
                min(self._depth_cap, self._forest_depth),
                start_iteration=jnp.int32(start_iteration))
            if self.params.boosting == "rf" and num_iteration > 0:
                raw = (raw - self.init_score_) / num_iteration \
                    + self.init_score_
        if raw_score:
            return np.asarray(raw)
        return np.asarray(self.obj.transform(raw))

    def _pred_contrib(self, bins, start: int, num: int) -> np.ndarray:
        """Exact TreeSHAP contributions over the selected trees.

        Reported per ORIGINAL feature (EFB bundle splits resolved through
        the bundle map); the bias column carries the per-tree expected
        values plus the init score, so rows sum to the raw prediction.
        """
        from ..ops.shap import forest_pred_contrib

        bm = self._bin_mapper_for_predict()
        f_orig = bm.num_features
        bundler = bm.bundler
        p = self.params
        k = self._num_class
        sel = self.trees[start:start + num]
        caps = {int(t.split_feature.shape[-1]) for t in sel}
        if len(caps) > 1:  # init_model continuation with mixed num_leaves
            sel = [pad_tree(t, max(caps)) for t in sel]
        fields = [f for f in Tree._fields
                  if getattr(sel[0], f, None) is not None] if sel else []

        def to_np(t, c=None):
            return {f: np.asarray(getattr(t, f) if c is None
                                  else getattr(t, f)[c]) for f in fields}

        is_rf = p.boosting == "rf"
        shrink = np.full(
            len(sel),
            1.0 if is_rf else getattr(self, "_base_lr", p.learning_rate),
            np.float32)
        outs = []
        for c in range(k):
            tree_dicts = [to_np(t, c if k > 1 else None) for t in sel]
            phi = forest_pred_contrib(tree_dicts, bins, f_orig, shrink,
                                      bundler=bundler)
            if is_rf and len(sel) > 0:
                phi /= len(sel)
            init = (float(self.init_score_[c]) if k > 1
                    else float(np.float32(self.init_score_)))
            phi[:, -1] += init
            outs.append(phi)
        return np.concatenate(outs, axis=1) if k > 1 else outs[0]

    def _leaf_index(self, tree: Tree, bins) -> jnp.ndarray:
        from jax import lax

        n = bins.shape[0]
        b32 = bins.astype(jnp.int32)

        def step(node, _):
            feat = tree.split_feature[node]
            thr = tree.split_bin[node]
            code = jnp.take_along_axis(b32, feat[:, None], axis=1)[:, 0]
            left = code <= thr
            if tree.is_cat_split is not None:
                left = jnp.where(tree.is_cat_split[node],
                                 tree.cat_mask[node, code], left)
            nxt = jnp.where(left, tree.left[node], tree.right[node])
            return jnp.where(tree.is_leaf[node], node, nxt), None

        node, _ = lax.scan(step, jnp.zeros(n, jnp.int32), None,
                           length=self._depth_cap)
        return node

    def _bin_mapper_for_predict(self):
        if self.train_set is not None:
            return self.train_set.bin_mapper
        return self._bin_mapper  # loaded from a model file

    # -- introspection ---------------------------------------------------
    def current_iteration(self) -> int:
        return self._iter

    def num_trees(self) -> int:
        return len(self.trees)

    def num_feature(self) -> int:
        if self.train_set is not None:
            return self.train_set.num_feature()
        return self._bin_mapper.num_features

    def feature_name(self) -> List[str]:
        if self.train_set is not None:
            return list(self.train_set.feature_names)
        return list(self._feature_names or [])

    def num_model_per_iteration(self) -> int:
        return self._num_class

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        """Per-feature split counts or total gains.

        ``iteration`` counts boosting ROUNDS (for multiclass each round holds
        ``num_class`` trees); ``None`` or <= 0 means all rounds (ADVICE r1:
        no falsy-zero conflation).  Vectorized over the stacked forest — no
        Python double loop at 1000 trees (VERDICT r1 weak #9).
        """
        k = len(self.trees) if (iteration is None or iteration <= 0) \
            else min(int(iteration), len(self.trees))
        out = np.zeros(self.num_feature(), dtype=np.float64)
        if k == 0:
            return (out.astype(np.int64) if importance_type == "split"
                    else out)
        forest = jax.tree.map(lambda a: a[:k], self._stacked_forest())
        feats = np.asarray(forest.split_feature).ravel()
        gains = np.asarray(forest.split_gain).ravel()
        # internal nodes = slots that were actually split: not a leaf AND
        # have a child written (unused slots keep left == -1)
        used = (~np.asarray(forest.is_leaf).ravel()
                & (np.asarray(forest.left).ravel() >= 0))
        bundler = getattr(self._bin_mapper_for_predict(), "bundler", None)
        if bundler is not None:
            # splits reference EFB bundle columns; attribute each to the
            # original feature whose bin range holds the threshold
            bins_thr = np.asarray(forest.split_bin).ravel()
            feats = bundler.split_to_original(feats, bins_thr)
        vals = (np.ones_like(gains) if importance_type == "split" else gains)
        np.add.at(out, feats[used], vals[used])
        if importance_type == "split":
            return out.astype(np.int64)
        return out

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """Update trace-dynamic hyper-parameters mid-training (LightGBM
        ``Booster.reset_parameter``, driven by the ``reset_parameter``
        callback).  Continuous knobs (learning_rate, lambdas, fractions,
        min_data_in_leaf, ...) are traced scalars, so NO recompilation
        happens; shape-static parameters cannot change on a live booster.
        """
        newp = parse_params(params, base=self.params)
        static = ["num_leaves", "max_bin", "objective", "boosting",
                  "num_class", "tree_learner", "grow_policy",
                  "max_cat_threshold", "extra_trees", "linear_tree"]
        if self.params.boosting == "goss":
            # GOSS sampling counts are compile-time constants (goss_k)
            static += ["top_rate", "other_rate"]
        for f in static:
            if getattr(newp, f) != getattr(self.params, f):
                raise ValueError(
                    f"cannot reset shape-static parameter '{f}' on a "
                    "trained booster (it changes the compiled program)")
        self.params = newp
        self._hyper = HyperScalars.from_params(newp)
        return self

    def rollback_one_iter(self) -> "Booster":
        if self.trees:
            tree = self.trees.pop()
            self._forest_cache = None
            self._iter -= 1
            is_rf = self.params.boosting == "rf"
            shrink = jnp.float32(
                1.0 if is_rf
                else getattr(self, "_base_lr", self.params.learning_rate))
            if tree.linear_feat is not None:
                add_lin = _linear_tree_pred_fn(self._depth_cap)
                if not is_rf:
                    self._pred_train = add_lin(
                        self._pred_train, tree, self.train_set.X_binned,
                        self._xraw, -shrink)
                for idx, (name, vds, vpred) in enumerate(self._valid):
                    self._valid[idx] = (
                        name, vds, add_lin(vpred, tree, vds.X_binned,
                                           vds._xraw_dev, -shrink))
                return self
            add = _tree_pred_fn(self._depth_cap, self._num_class)
            if not is_rf:  # rf keeps _pred_train at init score
                self._pred_train = add(
                    self._pred_train, tree, self.train_set.X_binned, -shrink)
            for idx, (name, vds, vpred) in enumerate(self._valid):
                self._valid[idx] = (
                    name, vds, add(vpred, tree, vds.X_binned, -shrink))
        return self

    # -- persistence (full model dump lands with utils.serialize) --------
    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> "Booster":
        from ..utils.serialize import save_booster
        save_booster(self, filename, num_iteration=num_iteration,
                     start_iteration=start_iteration)
        return self

    def refit(self, data, label, decay_rate: float = 0.9,
              weight=None, group=None, **kwargs) -> "Booster":
        """Refit leaf values on new data, keeping every tree's structure
        (LightGBM ``Booster.refit``): sequentially per tree, the new leaf
        value is ``decay_rate * old + (1 - decay_rate) * newton`` where the
        Newton step comes from the new data's grad/hess at the ensemble's
        running prediction.  Returns a NEW booster; self is untouched.

        Ranking models pass ``group=`` (query sizes of the NEW data) — a
        fresh lambda layout is packed for it and the pairwise gradients
        drive the same Newton renewal.
        """
        import copy as _copy

        if self.params.boosting in ("rf", "dart"):
            raise NotImplementedError(
                "refit supports additive boosting (gbdt/goss); rf averages "
                "trees and dart bakes dropout scales into leaf values")
        if self.trees and self.trees[0].linear_feat is not None:
            raise NotImplementedError(
                "refit with linear_tree is not supported (leaf models need "
                "re-solving, not Newton-constant renewal)")
        if kwargs:
            raise TypeError(f"refit got unsupported arguments: "
                            f"{sorted(kwargs)}")
        from ..dataset import _to_2d_float_array

        X = _to_2d_float_array(data)
        y = jnp.asarray(np.asarray(label, np.float32))
        w = (jnp.ones_like(y) if weight is None
             else jnp.asarray(np.asarray(weight, np.float32)))
        codes = jnp.asarray(self._bin_mapper_for_predict().transform(X))
        p = self.params
        lam = jnp.float32(p.lambda_l2)
        decay = jnp.float32(decay_rate)
        lr = jnp.float32(getattr(self, "_base_lr", p.learning_rate))
        obj = self.obj
        if getattr(obj, "needs_group", False):
            if group is None:
                raise ValueError(
                    "refit with a ranking objective requires group= "
                    "(query sizes of the refit data)")
            # fresh lambda layout packed for the NEW data
            obj = create_objective(p)
            obj.set_group(np.asarray(group, np.int64).reshape(-1),
                          np.asarray(label, np.float32),
                          int(np.asarray(label).reshape(-1).shape[0]))
        elif group is not None:
            raise TypeError("refit got group= for a non-ranking objective")
        depth_cap = self._depth_cap

        def leaf_of(tree):
            n = codes.shape[0]
            b32 = codes.astype(jnp.int32)

            def step(node, _):
                feat = tree.split_feature[node]
                thr = tree.split_bin[node]
                code = jnp.take_along_axis(b32, feat[:, None], axis=1)[:, 0]
                left = code <= thr
                if tree.is_cat_split is not None:
                    left = jnp.where(tree.is_cat_split[node],
                                     tree.cat_mask[node, code], left)
                nxt = jnp.where(left, tree.left[node], tree.right[node])
                return jnp.where(tree.is_leaf[node], node, nxt), None

            leafs, _ = lax.scan(step, jnp.zeros(n, jnp.int32), None,
                                length=depth_cap)
            return leafs

        def renew(tree, leafs, g, h):
            m = tree.leaf_value.shape[0]
            gs = jnp.zeros(m, jnp.float32).at[leafs].add(g)
            hs = jnp.zeros(m, jnp.float32).at[leafs].add(h)
            cnt = jnp.zeros(m, jnp.float32).at[leafs].add(1.0)
            newton = -gs / (hs + lam + 1e-15)
            vals = jnp.where(tree.is_leaf & (cnt > 0),
                             decay * tree.leaf_value
                             + (1.0 - decay) * newton,
                             tree.leaf_value)
            return tree._replace(leaf_value=vals), vals[leafs]

        @jax.jit
        def one_tree(tree, pred):
            g, h = obj.grad_hess(pred, y, w)
            new_tree, delta = renew(tree, leaf_of(tree), g, h)
            return new_tree, pred + lr * delta

        @jax.jit
        def one_round_mc(tree, pred):   # tree fields [K, M]; pred [n, K]
            g, h = obj.grad_hess(pred, y, w)            # [n, K]
            leafs = jax.vmap(leaf_of)(tree)             # [K, n]
            new_tree, delta = jax.vmap(renew)(tree, leafs, g.T, h.T)
            return new_tree, pred + lr * delta.T

        if self._num_class > 1:
            pred = jnp.broadcast_to(
                jnp.asarray(self.init_score_, jnp.float32)[None, :],
                (codes.shape[0], self._num_class))
            step_fn = one_round_mc
        else:
            pred = jnp.full(codes.shape[0], float(self.init_score_),
                            jnp.float32)
            step_fn = one_tree
        new_trees = []
        for t in self.trees:
            nt, pred = step_fn(t, pred)
            new_trees.append(nt)
        out = _copy.copy(self)
        out.trees = new_trees
        out._forest_cache = None
        out._valid = []
        # the refit booster is predict-only: its training-state caches
        # (_pred_train/_bag) reflect the OLD leaf values, so continuing
        # training on it would fit wrong residuals
        out.train_set = None
        out._bin_mapper = self._bin_mapper_for_predict()
        out._feature_names = list(self.feature_name())
        out._pred_train = None
        out._bag = None
        return out

    def trees_to_dataframe(self):
        """Flat per-node pandas DataFrame (LightGBM ``trees_to_dataframe``):
        one row per node with tree_index / node_depth / node_index /
        children / parent / split_feature / split_gain / threshold /
        decision_type / value / count, node names in LightGBM's
        ``{tree}-S{split}`` / ``{tree}-L{leaf}`` convention."""
        import pandas as pd

        names = self.feature_name()
        rows: List[Dict[str, Any]] = []

        def walk(node: Dict[str, Any], tree_idx: int, depth: int,
                 parent: Optional[str]) -> str:
            is_leaf = "leaf_index" in node
            nid = (f"{tree_idx}-L{node['leaf_index']}" if is_leaf
                   else f"{tree_idx}-S{node['split_index']}")
            row = {
                "tree_index": tree_idx, "node_depth": depth,
                "node_index": nid, "left_child": None, "right_child": None,
                "parent_index": parent, "split_feature": None,
                "split_gain": None, "threshold": None,
                "decision_type": None,
                "value": node.get("leaf_value"),
                "count": int(node.get("leaf_count",
                                      node.get("internal_count", 0))),
            }
            rows.append(row)
            if not is_leaf:
                row["split_feature"] = names[node["split_feature"]]
                row["split_gain"] = node["split_gain"]
                row["threshold"] = node["threshold"]
                row["decision_type"] = node.get("decision_type", "<=")
                row["value"] = None
                row["left_child"] = walk(node["left_child"], tree_idx,
                                         depth + 1, nid)
                row["right_child"] = walk(node["right_child"], tree_idx,
                                          depth + 1, nid)
            return nid

        dump = self.dump_model()
        for ti, tinfo in enumerate(dump["tree_info"]):
            walk(tinfo["tree_structure"], ti, 1, None)
        return pd.DataFrame(rows)

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> Dict[str, Any]:
        """Nested-dict model dump (LightGBM ``dump_model`` contract)."""
        from ..utils.serialize import dump_booster_dict
        return dump_booster_dict(self, num_iteration=num_iteration,
                                 start_iteration=start_iteration)

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> str:
        from ..utils.serialize import booster_to_string
        return booster_to_string(self, num_iteration=num_iteration,
                                 start_iteration=start_iteration)

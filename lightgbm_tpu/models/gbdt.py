"""GBDT boosting engine and the `Booster` class.

TPU-native replacement for LightGBM's ``GBDT::TrainOneIter`` driver
(SURVEY.md §3.1): one boosting round = one jitted device program
(grad/hess -> bagging-masked stats -> best-first tree growth -> train-score
update), driven by a host loop that only syncs for early stopping / logging.

Compilation strategy: the round step is cached per *static* configuration
(objective, num_leaves, num_bins, ...) at module level, while every
continuous hyper-parameter (learning_rate, lambda_l1/l2, min_data_in_leaf,
fractions, max_depth) is a traced scalar.  A 108-config sweep with three
distinct ``num_leaves`` values therefore compiles exactly three programs
(SURVEY.md §3.3 TPU mapping), and configs can later be vmapped.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp

from ..config import Params, default_metric_for_objective, parse_params
from ..dataset import Dataset
from ..metrics import get_metric
from ..objectives import Objective, create_objective
from ..ops.predict import predict_forest_binned, predict_tree_binned
from ..ops.split import SplitContext
from .tree import Tree, grow_tree


class HyperScalars(NamedTuple):
    """Traced per-config scalars fed to the jitted round step."""

    learning_rate: jnp.ndarray
    lambda_l1: jnp.ndarray
    lambda_l2: jnp.ndarray
    min_data_in_leaf: jnp.ndarray
    min_sum_hessian: jnp.ndarray
    min_gain_to_split: jnp.ndarray
    max_depth: jnp.ndarray
    feature_fraction_bynode: jnp.ndarray
    top_rate: jnp.ndarray        # GOSS a (used only when boosting="goss")
    other_rate: jnp.ndarray      # GOSS b

    @staticmethod
    def from_params(p: Params) -> "HyperScalars":
        return HyperScalars(
            learning_rate=jnp.float32(p.learning_rate),
            lambda_l1=jnp.float32(p.lambda_l1),
            lambda_l2=jnp.float32(p.lambda_l2),
            min_data_in_leaf=jnp.float32(p.min_data_in_leaf),
            min_sum_hessian=jnp.float32(p.min_sum_hessian_in_leaf),
            min_gain_to_split=jnp.float32(p.min_gain_to_split),
            max_depth=jnp.int32(p.max_depth),
            feature_fraction_bynode=jnp.float32(p.feature_fraction_bynode),
            top_rate=jnp.float32(p.top_rate),
            other_rate=jnp.float32(p.other_rate),
        )

    def ctx(self) -> SplitContext:
        return SplitContext(
            lambda_l1=self.lambda_l1,
            lambda_l2=self.lambda_l2,
            min_data_in_leaf=self.min_data_in_leaf,
            min_sum_hessian=self.min_sum_hessian,
            min_gain_to_split=self.min_gain_to_split,
        )


def resolve_wave_width(p: Params, n_rows: int) -> int:
    """Pick the grower's splits-per-histogram-pass (static).

    ``grow_policy="leafwise"`` forces strict best-first (1).  "frontier"
    forces wave growth.  "auto" uses frontier when row count makes the
    per-split full-data pass the dominant cost (the strict grower's
    ``num_leaves - 1`` passes cap Higgs-scale throughput — VERDICT r1
    item 3) and strict growth on small data, where it is both fast enough
    and LightGBM-exact.  Default width 42 keeps the segment-folded one-hot
    matmul at 3*42=126 lanes — inside one 128-lane MXU tile, so a wave
    costs about the same as a single strict trip.
    """
    if p.grow_policy == "leafwise":
        return 1
    width = int(p.extra.get("wave_width", 0)) or min(42, p.num_leaves - 1)
    width = max(1, width)
    if p.grow_policy == "frontier":
        return width
    return width if (n_rows >= (1 << 19) and p.num_leaves >= 8) else 1


def _objective_static_key(obj: Objective, p: Params) -> tuple:
    """Hashable key identifying the objective for the jit-compile cache.

    The custom-loss callable rides in the key itself (callables hash by
    identity), so user fobj objectives get their own cached program instead
    of crashing the rebuild path.

    Group-based objectives (lambdarank) carry per-training packed group
    tensors that cannot be rebuilt from scalars, so the prepared instance
    itself IS the key (hashes by identity — one compiled program per
    training, which is inevitable anyway since the [Q, G] layout is shape-
    defining).
    """
    if getattr(obj, "needs_group", False):
        return ("__group_objective__", obj)
    return (
        obj.name,
        p.sigmoid,
        getattr(obj, "pos_weight", 1.0),
        p.alpha,
        p.fair_c,
        p.poisson_max_delta_step,
        p.lambdarank_truncation_level,
        p.lambdarank_norm,
        p.num_class,
        p.extra.get("fobj"),
    )


def _rebuild_objective(key: tuple) -> Objective:
    if key and key[0] == "__group_objective__":
        return key[1]
    (name, sigmoid, pos_weight, alpha, fair_c, pmd, trunc, norm, num_class,
     fobj) = (key + (None,))[:10]
    p = Params(
        objective="none" if fobj is not None else name,
        sigmoid=sigmoid, alpha=alpha, fair_c=fair_c,
        poisson_max_delta_step=pmd, lambdarank_truncation_level=trunc,
        lambdarank_norm=norm, num_class=max(num_class, 1),
    )
    if fobj is not None:
        p.extra["fobj"] = fobj
    obj = create_objective(p)
    if hasattr(obj, "pos_weight"):
        obj.pos_weight = pos_weight
    return obj


@functools.lru_cache(maxsize=None)
def _round_fn(obj_key: tuple, num_leaves: int, num_bins: int,
              hist_impl: str, row_chunk: int, is_rf: bool,
              num_class: int = 1, hist_dtype: str = "f32",
              wave_width: int = 1, goss_k: Optional[Tuple[int, int]] = None):
    """goss_k: static (k_top, k_other) row counts enabling the compacted
    GOSS path; None = plain gbdt/rf."""
    obj = _rebuild_objective(obj_key)
    is_goss = goss_k is not None

    def goss_bag(key, g, bag, hyper):
        """GOSS as row re-weighting (multiclass path): top-|g| keep +
        amplified sample of the rest (SURVEY.md §2C; VERDICT r1 item 5)."""
        from ..ops.sampling import goss_weights
        g_abs = jnp.abs(g) if g.ndim == 1 else jnp.sum(jnp.abs(g), axis=-1)
        return goss_weights(key, g_abs, bag, hyper.top_rate,
                            hyper.other_rate, jnp.sum(bag))

    if num_class > 1:
        # one tree per class per round, grown simultaneously: the class axis
        # is a vmapped batch over the grower (SURVEY.md §7 batching design)
        @jax.jit
        def round_fn_mc(bins, y, w, bag, pred, feature_mask,
                        hyper: HyperScalars, key):
            g, h = obj.grad_hess(pred, y, w)          # [n, K]
            if is_goss:
                bag = goss_bag(jax.random.fold_in(key, -1), g, bag, hyper)

            def grow_one(gc, hc, kc):
                stats = jnp.stack([gc * bag, hc * bag,
                                   (bag > 0).astype(jnp.float32)], axis=-1)
                return grow_tree(
                    bins, stats, feature_mask, hyper.ctx(), num_leaves,
                    num_bins, hyper.max_depth,
                    ff_bynode=hyper.feature_fraction_bynode, key=kc,
                    hist_impl=hist_impl, row_chunk=row_chunk,
                    hist_dtype=hist_dtype, wave_width=wave_width)

            keys = jax.random.split(key, num_class)
            trees, row_leafs = jax.vmap(grow_one, in_axes=(1, 1, 0))(
                g, h, keys)                            # leading [K] axis
            deltas = jax.vmap(lambda t, rl: t.leaf_value[rl])(
                trees, row_leafs)                      # [K, n]
            new_pred = pred + hyper.learning_rate * deltas.T
            return trees, new_pred

        return round_fn_mc

    if is_goss:  # single-class: compacted GOSS (mc handled above, masked)
        k_top, k_other = goss_k

        @jax.jit
        def round_fn_goss(bins, y, w, bag, pred, feature_mask,
                          hyper: HyperScalars, key):
            """Compacted GOSS round: unlike CPU LightGBM (where skipping
            rows is free), a TPU histogram pass costs the same for masked
            rows as for live ones — so the sampled subset is GATHERED into
            a dense [k_top + k_other, F] matrix and the tree grown on that,
            cutting histogram cost by ~(top_rate + other_rate).  Train
            scores for ALL rows then come from one traversal pass."""
            n = bins.shape[0]
            g, h = obj.grad_hess(pred, y, w)
            g_abs = jnp.where(bag > 0, jnp.abs(g), -1.0)
            _, top_idx = jax.lax.top_k(g_abs, k_top)
            is_top = jnp.zeros(n, bool).at[top_idx].set(True)
            rest = (bag > 0) & ~is_top
            u = jax.random.uniform(jax.random.fold_in(key, -1), (n,))
            _, other_idx = jax.lax.top_k(jnp.where(rest, u, -1.0), k_other)
            idx = jnp.concatenate([top_idx, other_idx])         # [k]
            amp = ((1.0 - hyper.top_rate)
                   / jnp.maximum(hyper.other_rate, 1e-12))
            wt = jnp.concatenate([jnp.ones(k_top, jnp.float32),
                                  jnp.full(k_other, 1.0, jnp.float32) * amp])
            bins_c = jnp.take(bins, idx, axis=0)
            stats = jnp.stack([g[idx] * wt, h[idx] * wt,
                               jnp.ones(k_top + k_other, jnp.float32)],
                              axis=-1)
            tree, _ = grow_tree(
                bins_c, stats, feature_mask, hyper.ctx(), num_leaves,
                num_bins, hyper.max_depth,
                ff_bynode=hyper.feature_fraction_bynode, key=key,
                hist_impl=hist_impl, row_chunk=row_chunk,
                hist_dtype=hist_dtype, wave_width=wave_width)
            new_pred = pred + hyper.learning_rate * predict_tree_binned(
                tree, bins, num_leaves)
            return tree, new_pred

        return round_fn_goss

    @jax.jit
    def round_fn(bins, y, w, bag, pred, feature_mask, hyper: HyperScalars,
                 key):
        g, h = obj.grad_hess(pred, y, w)
        stats = jnp.stack([g * bag, h * bag, (bag > 0).astype(jnp.float32)],
                          axis=-1)
        tree, row_leaf = grow_tree(
            bins, stats, feature_mask, hyper.ctx(), num_leaves, num_bins,
            hyper.max_depth, ff_bynode=hyper.feature_fraction_bynode,
            key=key, hist_impl=hist_impl, row_chunk=row_chunk,
            hist_dtype=hist_dtype, wave_width=wave_width)
        shrink = jnp.where(is_rf, 1.0, hyper.learning_rate)
        new_pred = pred + shrink * tree.leaf_value[row_leaf]
        return tree, new_pred

    return round_fn


@functools.lru_cache(maxsize=None)
def _tree_pred_fn(depth_cap: int, num_class: int = 1):
    if num_class > 1:
        @jax.jit
        def add_tree_mc(pred, tree, bins, shrink):   # pred [n, K]
            vals = jax.vmap(
                lambda t: predict_tree_binned(t, bins, depth_cap))(tree)
            return pred + shrink * vals.T

        return add_tree_mc

    @jax.jit
    def add_tree(pred, tree, bins, shrink):
        return pred + shrink * predict_tree_binned(tree, bins, depth_cap)

    return add_tree


@functools.lru_cache(maxsize=None)
def _eval_fn(obj_key: tuple, metric_names: tuple, metric_cfg: tuple):
    obj = _rebuild_objective(obj_key)
    p = Params(alpha=metric_cfg[0]) if metric_cfg else Params()
    metrics = [get_metric(m, p) for m in metric_names]

    @jax.jit
    def evaluate(pred_raw, y, w):
        t = obj.transform(pred_raw)
        return tuple(m.fn(t, y, w) for m in metrics)

    return evaluate


@functools.lru_cache(maxsize=None)
def _bag_fn():
    from ..ops.sampling import sample_bag

    return jax.jit(sample_bag)


@functools.lru_cache(maxsize=None)
def _feature_mask_fn(num_features: int):
    from ..ops.sampling import sample_feature_mask

    @jax.jit
    def sample_features(key, fraction):
        return sample_feature_mask(key, fraction, num_features)

    return sample_features


class Booster:
    """LightGBM-compatible Booster driving the jitted TPU round step.

    Reference API surface exercised: construction via ``lgb.train`` with a
    Dataset (r/gridsearchCV.R:57), ``predict`` over all or first-k trees
    (r/gridsearchCV.R:63, bagging_boosting.ipynb:136).
    """

    def __init__(self, params: Optional[Union[Dict[str, Any], Params]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        if model_file is not None or model_str is not None:
            from ..utils.serialize import load_booster_into
            load_booster_into(self, model_file=model_file, model_str=model_str)
            return
        if isinstance(params, Params):
            self.params = params
        else:
            self.params = parse_params(params)
        self.train_set = train_set
        self.obj = create_objective(self.params)
        self.trees: List[Tree] = []
        self._forest_cache: Optional[Tree] = None
        self.best_iteration: int = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._valid: List[Tuple[str, Dataset, Any]] = []  # (name, dataset, pred)
        self._iter = 0
        self.init_score_ = 0.0
        self._pred_train = None
        self._bag = None
        self._key = jax.random.PRNGKey(self.params.seed)

        if train_set is not None:
            self._setup_training()

    # ------------------------------------------------------------------
    @property
    def _num_class(self) -> int:
        if self.params.objective in ("multiclass", "multiclassova"):
            return self.params.num_class
        return 1

    def _setup_training(self) -> None:
        ds = self.train_set
        ds.construct()
        if ds.y is None:
            raise ValueError("training Dataset requires a label")
        p = self.params
        y_host = ds.get_label()
        w_host = (ds.get_weight() if ds.get_weight() is not None
                  else np.ones(ds.num_data_))
        if hasattr(self.obj, "prepare"):
            self.obj.prepare(y_host, w_host)
        if getattr(self.obj, "needs_group", False):
            gs = ds.get_group()
            if gs is None:
                raise ValueError(
                    f"objective '{self.obj.name}' requires query group "
                    "information: Dataset(X, label=y, group=sizes)")
            self.obj.set_group(gs, y_host, int(ds.row_mask.shape[0]))
        k = self._num_class
        if k > 1:
            if p.boosting == "rf":
                raise NotImplementedError("rf boosting with multiclass is "
                                          "not supported yet")
            self.init_score_ = np.asarray(
                self.obj.init_score(y_host, w_host), np.float32)  # [K]
            if ds.get_init_score() is not None:
                raise NotImplementedError(
                    "per-row init_score with multiclass is not supported")
            self._pred_train = jnp.broadcast_to(
                jnp.asarray(self.init_score_)[None, :],
                (int(ds.row_mask.shape[0]), k))
        elif ds.get_init_score() is not None:
            base = np.concatenate([
                np.asarray(ds.get_init_score(), np.float32),
                np.zeros(int(ds.row_mask.shape[0]) - ds.num_data_, np.float32)])
            self._pred_train = jnp.asarray(base)
            self.init_score_ = 0.0
        else:
            self.init_score_ = float(self.obj.init_score(y_host, w_host))
            self._pred_train = jnp.full(
                ds.row_mask.shape, self.init_score_, jnp.float32)
        self._bag = ds.row_mask
        self._hyper = HyperScalars.from_params(p)
        self._obj_key = _objective_static_key(self.obj, p)
        self._num_bins = ds.num_bins
        self._w_eff = ds.w  # 0 on padding rows already

    # -- round step ------------------------------------------------------
    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """Run one boosting round (LightGBM Booster.update)."""
        if train_set is not None and train_set is not self.train_set:
            self.train_set = train_set
            self._setup_training()
        ds = self.train_set
        p = self.params
        i = self._iter

        if p.bagging_freq > 0 and p.bagging_fraction < 1.0 and \
                i % p.bagging_freq == 0:
            bkey = jax.random.fold_in(
                jax.random.PRNGKey(p.bagging_seed + p.seed), i)
            self._bag = _bag_fn()(
                bkey, ds.row_mask, jnp.float32(p.bagging_fraction),
                jnp.float32(ds.num_data_))
        if p.feature_fraction < 1.0:
            fkey = jax.random.fold_in(
                jax.random.PRNGKey(p.feature_fraction_seed + p.seed), i)
            fmask = _feature_mask_fn(ds.num_feature_)(
                fkey, jnp.float32(p.feature_fraction))
        else:
            fmask = jnp.ones(ds.num_feature_, jnp.float32)

        goss_k = None
        eff_rows = int(ds.row_mask.shape[0])
        if p.boosting == "goss":
            goss_k = (int(p.top_rate * ds.num_data_),
                      int(p.other_rate * ds.num_data_))
            if self._num_class == 1:  # mc uses the masked (non-compacted) path
                eff_rows = goss_k[0] + goss_k[1]
        fn = _round_fn(self._obj_key, p.num_leaves, self._num_bins,
                       p.extra.get("hist_impl", "auto"),
                       int(p.extra.get("row_chunk", 131072)),
                       p.boosting == "rf", self._num_class,
                       p.extra.get("hist_dtype", "f32"),
                       resolve_wave_width(p, eff_rows), goss_k)
        round_key = jax.random.fold_in(self._key, i)
        tree, new_pred = fn(ds.X_binned, ds.y, self._w_eff, self._bag,
                            self._pred_train, fmask, self._hyper, round_key)
        if p.boosting != "rf":
            self._pred_train = new_pred
        self.trees.append(tree)
        self._forest_cache = None
        # incremental valid-set predictions
        shrink = 1.0 if p.boosting == "rf" else p.learning_rate
        add_tree = _tree_pred_fn(p.num_leaves, self._num_class)
        for idx, (name, vds, vpred) in enumerate(self._valid):
            self._valid[idx] = (
                name, vds, add_tree(vpred, tree, vds.X_binned,
                                    jnp.float32(shrink)))
        self._iter += 1
        return False

    # -- evaluation ------------------------------------------------------
    def _metric_names(self) -> List[str]:
        names = [m for m in self.params.metric if m != "none"]
        if not names:
            default = default_metric_for_objective(self.params.objective)
            if default != "none":
                names = [default]
        return names

    def _eval_on(self, pred_raw, ds: Dataset, name: str):
        metric_names = tuple(self._metric_names())
        if not metric_names:
            return []
        out = []
        # ranking metrics need the query grouping — they bypass the plain
        # (pred, y, w) metric signature via the grouped eval path
        plain = tuple(m for m in metric_names if m not in ("ndcg", "map"))
        if plain:
            fn = _eval_fn(self._obj_key, plain, (self.params.alpha,))
            vals = fn(pred_raw, ds.y, ds.w)
            for mname, v in zip(plain, vals):
                m = get_metric(mname, self.params)
                out.append((name, mname, float(v), m.higher_better))
        if any(m == "ndcg" for m in metric_names):
            from ..ranking import eval_ranking
            for mname, val, hib in eval_ranking(
                    pred_raw, ds, self.params.eval_at,
                    self.params.label_gain):
                out.append((name, mname, val, hib))
        return out

    def eval_train(self, feval=None):
        pred = self._pred_train_effective()
        res = self._eval_on(pred, self.train_set, "training")
        return res + self._feval_results(feval, pred, self.train_set,
                                         "training")

    def eval_valid(self, feval=None):
        out = []
        for name, vds, vpred in self._valid:
            vp = self._rf_scale(vpred)
            out.extend(self._eval_on(vp, vds, name))
            out.extend(self._feval_results(feval, vp, vds, name))
        return out

    def _feval_results(self, feval, pred_raw, ds, name):
        if feval is None:
            return []
        fevals = feval if isinstance(feval, (list, tuple)) else [feval]
        out = []
        n = ds.num_data_
        pred_host = np.asarray(self.obj.transform(pred_raw))[:n]
        for f in fevals:
            mname, val, hib = f(pred_host, ds)
            out.append((name, mname, float(val), bool(hib)))
        return out

    def _rf_scale(self, pred_raw):
        if self.params.boosting == "rf" and self._iter > 0:
            return (pred_raw - self.init_score_) / self._iter + self.init_score_
        return pred_raw

    def _pred_train_effective(self):
        if self.params.boosting == "rf":
            # rf keeps _pred_train at init; reconstruct mean over trees lazily
            if not self.trees:
                return self._pred_train
            forest = self._stacked_forest()
            pred = predict_forest_binned(
                forest, self.train_set.X_binned, 1.0 / self._iter,
                self.init_score_, jnp.int32(self._iter), self.params.num_leaves)
            return pred
        return self._pred_train

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.construct()
        if data.y is None:
            raise ValueError(f"valid set '{name}' requires a label")
        k = self._num_class
        if k > 1:
            vpred = jnp.broadcast_to(
                jnp.asarray(self.init_score_)[None, :],
                (int(data.row_mask.shape[0]), k))
        else:
            vpred = jnp.full(data.row_mask.shape, self.init_score_,
                             jnp.float32)
        # replay existing trees (valid sets are usually added before round 0)
        shrink = 1.0 if self.params.boosting == "rf" else self.params.learning_rate
        add_tree = _tree_pred_fn(self.params.num_leaves, k)
        for tree in self.trees:
            vpred = add_tree(vpred, tree, data.X_binned, jnp.float32(shrink))
        self._valid.append((name, data, vpred))
        return self

    # -- prediction ------------------------------------------------------
    def _stacked_forest(self) -> Tree:
        if self._forest_cache is None or \
                self._forest_cache.leaf_value.shape[0] != len(self.trees):
            if not self.trees:
                raise ValueError("no trees trained yet")
            self._forest_cache = jax.tree.map(
                lambda *xs: jnp.stack(xs), *self.trees)
        return self._forest_cache

    def predict(
        self,
        data,
        num_iteration: Optional[int] = None,
        raw_score: bool = False,
        pred_leaf: bool = False,
        start_iteration: int = 0,
        ntree_limit: Optional[int] = None,  # xgboost-style alias
        **kwargs,
    ) -> np.ndarray:
        """Predict on raw (unbinned) features.

        ``num_iteration``/``ntree_limit`` truncate to the first k trees —
        the staged-prediction contract of bagging_boosting.ipynb:136.
        """
        if num_iteration is None:
            num_iteration = ntree_limit
        if num_iteration is None:
            # None -> best_iteration when early stopping found one
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else len(self.trees))
        elif num_iteration <= 0:
            # explicit <= 0 -> ALL trees (LightGBM contract)
            num_iteration = len(self.trees)
        start_iteration = max(int(start_iteration), 0)
        num_iteration = min(num_iteration, len(self.trees) - start_iteration)
        if isinstance(data, Dataset):
            raise TypeError(
                "predict() expects a raw feature matrix, not a Dataset "
                "(matching lightgbm)")
        from ..dataset import _to_2d_float_array
        X = _to_2d_float_array(data)
        codes = self._bin_mapper_for_predict().transform(X)
        bins = jnp.asarray(codes)
        forest = self._stacked_forest()
        if pred_leaf:
            if self._num_class > 1:
                raise NotImplementedError("pred_leaf with multiclass")
            leaves = []
            for t in range(start_iteration, start_iteration + num_iteration):
                tree = jax.tree.map(lambda a: a[t], forest)
                node = self._leaf_index(tree, bins)
                leaves.append(np.asarray(node))
            return np.stack(leaves, axis=1)
        shrink = 1.0 if self.params.boosting == "rf" else self.params.learning_rate
        k = self._num_class
        if k > 1:
            cols = []
            for c in range(k):
                forest_c = jax.tree.map(lambda a: a[:, c], forest)
                cols.append(predict_forest_binned(
                    forest_c, bins, jnp.float32(shrink),
                    float(self.init_score_[c]), jnp.int32(num_iteration),
                    self.params.num_leaves,
                    start_iteration=jnp.int32(start_iteration)))
            raw = jnp.stack(cols, axis=1)                 # [n, K]
        else:
            raw = predict_forest_binned(
                forest, bins, jnp.float32(shrink), self.init_score_,
                jnp.int32(num_iteration), self.params.num_leaves,
                start_iteration=jnp.int32(start_iteration))
            if self.params.boosting == "rf" and num_iteration > 0:
                raw = (raw - self.init_score_) / num_iteration \
                    + self.init_score_
        if raw_score:
            return np.asarray(raw)
        return np.asarray(self.obj.transform(raw))

    def _leaf_index(self, tree: Tree, bins) -> jnp.ndarray:
        from jax import lax

        n = bins.shape[0]
        b32 = bins.astype(jnp.int32)

        def step(node, _):
            feat = tree.split_feature[node]
            thr = tree.split_bin[node]
            code = jnp.take_along_axis(b32, feat[:, None], axis=1)[:, 0]
            nxt = jnp.where(code <= thr, tree.left[node], tree.right[node])
            return jnp.where(tree.is_leaf[node], node, nxt), None

        node, _ = lax.scan(step, jnp.zeros(n, jnp.int32), None,
                           length=self.params.num_leaves)
        return node

    def _bin_mapper_for_predict(self):
        if self.train_set is not None:
            return self.train_set.bin_mapper
        return self._bin_mapper  # loaded from a model file

    # -- introspection ---------------------------------------------------
    def current_iteration(self) -> int:
        return self._iter

    def num_trees(self) -> int:
        return len(self.trees)

    def num_feature(self) -> int:
        if self.train_set is not None:
            return self.train_set.num_feature()
        return self._bin_mapper.num_features

    def feature_name(self) -> List[str]:
        if self.train_set is not None:
            return list(self.train_set.feature_names)
        return list(self._feature_names or [])

    def num_model_per_iteration(self) -> int:
        return self._num_class

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        k = iteration or len(self.trees)
        out = np.zeros(self.num_feature(), dtype=np.float64)
        for tree in self.trees[:k]:
            feats = np.asarray(tree.split_feature).ravel()
            gains = np.asarray(tree.split_gain).ravel()
            internal = np.asarray(~tree.is_leaf).ravel() & (feats >= 0)
            for f, g, used in zip(feats, gains, internal):
                if used:
                    out[f] += 1.0 if importance_type == "split" else float(g)
        if importance_type == "split":
            return out.astype(np.int64)
        return out

    def rollback_one_iter(self) -> "Booster":
        if self.trees:
            tree = self.trees.pop()
            self._forest_cache = None
            self._iter -= 1
            is_rf = self.params.boosting == "rf"
            shrink = jnp.float32(1.0 if is_rf else self.params.learning_rate)
            add = _tree_pred_fn(self.params.num_leaves, self._num_class)
            if not is_rf:  # rf keeps _pred_train at init score
                self._pred_train = add(
                    self._pred_train, tree, self.train_set.X_binned, -shrink)
            for idx, (name, vds, vpred) in enumerate(self._valid):
                self._valid[idx] = (
                    name, vds, add(vpred, tree, vds.X_binned, -shrink))
        return self

    # -- persistence (full model dump lands with utils.serialize) --------
    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> "Booster":
        from ..utils.serialize import save_booster
        save_booster(self, filename, num_iteration=num_iteration,
                     start_iteration=start_iteration)
        return self

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> str:
        from ..utils.serialize import booster_to_string
        return booster_to_string(self, num_iteration=num_iteration,
                                 start_iteration=start_iteration)

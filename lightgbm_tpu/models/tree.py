"""Tensorized leaf-wise (best-first) tree grower.

TPU-native replacement for LightGBM's ``SerialTreeLearner::Train`` (SURVEY.md
§3.1): no leaf objects, no row-index vectors, no OpenMP — the tree is a
struct-of-arrays with a static node capacity ``2*num_leaves - 1``, rows carry a
leaf-id vector updated by gathered split decisions, and growth is a
``lax.fori_loop`` with exactly ``num_leaves - 1`` trips where exhausted trees
execute masked no-ops (SURVEY.md §7 "Dynamic tree growth under static
shapes").

Best-first semantics match LightGBM: each trip splits the single active leaf
with the highest cached split gain.  When a leaf is split, both children's
histograms are built in **one** pass over all rows (segments = {left child,
right child}; other rows contribute nothing), so no per-node histogram storage
and no subtraction trick is needed — under static shapes a one-child pass
costs the same as a two-child pass, and dropping stored histograms keeps
memory at O(num_leaves) scalars per node, which is what lets folds × configs
be vmapped later.

Everything data-dependent stays on device; all regularization thresholds are
traced scalars (vmap-able across hyper-parameter configs).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.histogram import compute_histograms, histogram_merge, histogram_psum
from ..ops.lookup import lookup_rows, lookup_values
from ..ops.split import (
    BestSplit,
    SplitContext,
    constrained_leaf_output,
    find_best_split,
    leaf_output,
)

# tools/hlo_counts.py flips this to compile the fused strict grower with
# the split-iteration kernel replaced by an optimization barrier, so the
# CPU HLO counts only the XLA-side launches (the kernel is one TPU
# custom-call but inlines under interpret mode).  Never set in production.
_SPLIT_ITER_OPCOUNT_STUB = False


class Tree(NamedTuple):
    """One tensorized decision tree (node arrays of length 2*num_leaves-1).

    Traversal rule at internal node i: go left iff
    ``bin_code[row, split_feature[i]] <= split_bin[i]`` for numeric splits;
    for categorical k-vs-rest splits (``is_cat_split[i]``) go left iff
    ``cat_mask[i, bin_code[row, split_feature[i]]]``.
    Unused slots have ``is_leaf=False`` and are unreachable.
    """

    split_feature: jnp.ndarray  # i32[M]
    split_bin: jnp.ndarray      # i32[M]
    left: jnp.ndarray           # i32[M]
    right: jnp.ndarray          # i32[M]
    leaf_value: jnp.ndarray     # f32[M] (raw, no shrinkage)
    is_leaf: jnp.ndarray        # bool[M]
    count: jnp.ndarray          # f32[M] rows that reached the node (bagged)
    split_gain: jnp.ndarray     # f32[M] gain of the split at internal nodes
    num_leaves: jnp.ndarray     # i32[] leaves actually grown
    # categorical subset splits — None for datasets without categoricals
    is_cat_split: Optional[jnp.ndarray] = None  # bool[M]
    cat_mask: Optional[jnp.ndarray] = None      # bool[M, B] bins going LEFT
    # linear leaves (upstream linear_tree) — None for constant-leaf models.
    # Prediction at a linear leaf: leaf_value[l] + sum_k coef[l,k] *
    # raw[linear_feat[l,k]] (feat -1 = unused slot; NaN raw imputes 0).
    linear_feat: Optional[jnp.ndarray] = None   # i32[M, K] training columns
    linear_coef: Optional[jnp.ndarray] = None   # f32[M, K]

    @property
    def capacity(self) -> int:
        return self.split_feature.shape[-1]


class _PK:
    """Column layout of the strict grower's packed per-node table.

    The strict grower's per-split bookkeeping used to live in 22 separate
    ``[capacity]`` arrays; at small n the fused-cv sweep is bound by KERNEL
    COUNT, not FLOPs (PERF.md r4 finding 3), and the 15 tiny per-field
    gathers plus ~44 per-field masked scatters per split iteration were
    most of its while-body kernels.  One f32 ``[capacity, NC]`` table makes
    that ONE row gather and THREE row scatters per iteration.  Integer
    fields (node ids <= capacity, feature ids, bin ids <= 256, depth) are
    all exactly representable in f32.
    """

    SPLIT_FEAT = 0    # init -1
    SPLIT_BIN = 1
    LEFT = 2          # init -1
    RIGHT = 3         # init -1
    LEAF_VALUE = 4
    IS_LEAF = 5       # 0/1
    COUNT = 6
    SPLIT_GAIN = 7
    DEPTH = 8
    CAND_GAIN = 9     # init -inf
    CAND_FEAT = 10
    CAND_BIN = 11
    CAND_LG = 12
    CAND_LH = 13
    CAND_LC = 14
    CAND_RG = 15
    CAND_RH = 16
    CAND_RC = 17
    CAND_WL = 18
    CAND_WR = 19
    BOUND_LO = 20     # init -inf
    BOUND_HI = 21     # init +inf
    CAND_CAT = 22     # 0/1 (unused when the dataset has no categoricals)
    PM = 23           # pathmin: min candidate gain over ancestors-or-self
    NC = 24           # (set at creation; drives exact-tail selection)


class _GrowState(NamedTuple):
    nodes: jnp.ndarray          # f32[M, _PK.NC] packed per-node table
    row_leaf: jnp.ndarray       # i32[n]
    n_nodes: jnp.ndarray        # i32[]
    n_leaves: jnp.ndarray       # i32[]
    done: jnp.ndarray           # bool[]
    # categorical candidate split masks (None when the dataset has none)
    cand_catmask: Optional[jnp.ndarray] = None  # bool[M, B]
    # interaction constraints: surviving group set per node (None = off)
    ic_sets: Optional[jnp.ndarray] = None       # bool[M, NG]


def decode_wave_width(wave_width: int):
    """Decode the static wave-width int into (width, tail, overgrow_leaves).

    SINGLE SOURCE for the encoding produced by ``gbdt.resolve_wave_width``
    (negative = greedy tail; >= 1024 = exact tail, ``overgrow_leaves *
    1024 + width``; else half) — the grower, the profiling report, and
    the bench FLOP model all decode through here.
    """
    if wave_width < 0:
        return -wave_width, "greedy", None
    if wave_width >= 1024:
        return wave_width % 1024, "exact", wave_width // 1024
    return wave_width, "half", None


def _write(arr, idx, val, active):
    """Masked scalar write arr[idx] = val if active."""
    return arr.at[idx].set(jnp.where(active, val, arr[idx]))


def _empty_packed_table(capacity: int) -> jnp.ndarray:
    """All-sentinel packed [capacity, _PK.NC] node table (unused slots:
    no children, no candidate, unbounded)."""
    K = _PK
    nodes0 = jnp.zeros((capacity, K.NC), jnp.float32)
    nodes0 = nodes0.at[:, K.SPLIT_FEAT].set(-1.0)
    nodes0 = nodes0.at[:, K.LEFT].set(-1.0)
    nodes0 = nodes0.at[:, K.RIGHT].set(-1.0)
    nodes0 = nodes0.at[:, K.CAND_GAIN].set(-jnp.inf)
    nodes0 = nodes0.at[:, K.BOUND_LO].set(-jnp.inf)
    nodes0 = nodes0.at[:, K.BOUND_HI].set(jnp.inf)
    nodes0 = nodes0.at[:, K.PM].set(-jnp.inf)
    return nodes0


def _packed_root_table(capacity, root_out, root_tot, root_best,
                       cat_info) -> jnp.ndarray:
    """Initial packed [capacity, _PK.NC] node table with the root's row set
    (shared by the strict and frontier growers)."""
    K = _PK
    nodes0 = _empty_packed_table(capacity)
    root_row = jnp.zeros((K.NC,), jnp.float32)
    root_row = root_row.at[jnp.array([
        K.SPLIT_FEAT, K.LEFT, K.RIGHT, K.LEAF_VALUE, K.IS_LEAF, K.COUNT,
        K.CAND_GAIN, K.CAND_FEAT, K.CAND_BIN, K.CAND_LG, K.CAND_LH,
        K.CAND_LC, K.CAND_RG, K.CAND_RH, K.CAND_RC, K.CAND_WL, K.CAND_WR,
        K.BOUND_LO, K.BOUND_HI, K.CAND_CAT, K.PM])].set(jnp.stack([
            jnp.float32(-1.0), jnp.float32(-1.0), jnp.float32(-1.0),
            root_out, jnp.float32(1.0), root_tot[2],
            root_best.gain, root_best.feature.astype(jnp.float32),
            root_best.bin.astype(jnp.float32), root_best.left_g,
            root_best.left_h, root_best.left_c, root_best.right_g,
            root_best.right_h, root_best.right_c, root_best.left_out,
            root_best.right_out, jnp.float32(-jnp.inf),
            jnp.float32(jnp.inf),
            (root_best.cat.astype(jnp.float32) if cat_info is not None
             else jnp.float32(0.0)),
            root_best.gain]))
    return nodes0.at[0].set(root_row)


def _tree_from_packed(P, n_leaves, cat_info, cand_catmask) -> Tree:
    """Unpack the packed node table into the public Tree struct."""
    K = _PK
    is_leaf = P[:, K.IS_LEAF] > 0.5
    left = P[:, K.LEFT].astype(jnp.int32)
    internal = (~is_leaf) & (left >= 0)
    return Tree(
        split_feature=P[:, K.SPLIT_FEAT].astype(jnp.int32),
        split_bin=P[:, K.SPLIT_BIN].astype(jnp.int32),
        left=left,
        right=P[:, K.RIGHT].astype(jnp.int32),
        leaf_value=P[:, K.LEAF_VALUE],
        is_leaf=is_leaf,
        count=P[:, K.COUNT],
        split_gain=P[:, K.SPLIT_GAIN],
        num_leaves=n_leaves,
        is_cat_split=(None if cat_info is None
                      else internal & (P[:, K.CAND_CAT] > 0.5)),
        cat_mask=(None if cat_info is None else cand_catmask),
    )


def _rand_bins_for_node(key, node_id, num_features, num_bins, col_bins):
    """ExtraTrees: one random threshold position per feature per node
    (upstream ``extra_trees``), drawn WITHIN each feature's own used-bin
    range (``col_bins``, the per-training-column bin counts) so
    low-cardinality features keep their full split chance — a global
    [0, num_bins) draw would almost always land outside a binary feature's
    single valid threshold.  Distinct stream from the bynode sampler.
    """
    k = jax.random.fold_in(jax.random.fold_in(key, 0x0EF7), node_id)
    u = jax.random.uniform(k, (num_features,))
    hi = (jnp.asarray(col_bins, jnp.float32) - 1.0 if col_bins is not None
          else jnp.float32(max(num_bins - 1, 1)))
    return jnp.floor(u * jnp.maximum(hi, 1.0)).astype(jnp.int32)


def _ic_allowed(group_sets, member):
    """Interaction constraints: allowed-feature mask for nodes.

    ``group_sets`` bool [..., NG] — which constraint groups the node's
    path-used feature set still fits inside (upstream col_sampler's
    interaction-constraint tracking, re-derived as a set recurrence:
    ``S_child = {G in S_node : split_feature in G}``).  ``member`` bool
    [NG, F].  Allowed features = union of the surviving groups — one
    boolean matmul."""
    return (group_sets.astype(jnp.float32) @ member.astype(jnp.float32)
            > 0.5).astype(jnp.float32)


def _mono_child_bounds(mono, feat, wl, wr, lo, hi):
    """Basic-method monotone bounds for a split's children (upstream
    LeafConstraintsBase 'basic'): descendants on the low side of an
    increasing split are capped at the split's output mid-point, and vice
    versa.  Shapes follow (feat, wl, wr, lo, hi) — scalar in the strict
    grower, [W] vectors in the frontier grower."""
    if mono is None:
        return lo, hi, lo, hi
    mval = mono[feat]
    mid = 0.5 * (wl + wr)
    hi_l = jnp.where(mval > 0, jnp.minimum(hi, mid), hi)
    lo_l = jnp.where(mval < 0, jnp.maximum(lo, mid), lo)
    lo_r = jnp.where(mval > 0, jnp.maximum(lo, mid), lo)
    hi_r = jnp.where(mval < 0, jnp.minimum(hi, mid), hi)
    return lo_l, hi_l, lo_r, hi_r


def _fp_reduce_best(bs: BestSplit, axis_name: str,
                    f_local: int) -> BestSplit:
    """Feature-parallel combine: each shard found the best split over its
    OWN feature slice; all-gather the per-shard winners, take the global
    argmax, and globalize the winning feature index (upstream
    FeatureParallelTreeLearner's split exchange — one tiny allgather
    instead of allreducing full histograms).  Shared with the data-parallel
    reduce-scatter/voting merge modes — single source lives in
    parallel.feature_parallel (imported lazily: that module imports
    models.gbdt at load time)."""
    from ..parallel.feature_parallel import reduce_best_split

    return reduce_best_split(bs, axis_name, f_local)


def _fp_column(bins_local: jnp.ndarray, feat_global, axis_name: str,
               f_local: int) -> jnp.ndarray:
    """Fetch the GLOBAL feature column under feature sharding: only the
    owning shard has it, so it contributes the codes and a psum broadcasts
    them (the [n] bitmap exchange of upstream's feature-parallel split)."""
    from ..parallel.feature_parallel import broadcast_feature_column

    return broadcast_feature_column(bins_local, feat_global, axis_name,
                                    f_local)


def _make_dist_scorer(axis_name: str, hist_merge: str, n_shards: int,
                      num_features: int, ctx, cat_info, mono, voting_k: int,
                      merge_chunks: int = 1):
    """Build the batched split scorer for the distributed histogram-merge
    modes (``reduce_scatter`` / ``reduce_scatter_ring`` /
    ``reduce_scatter_pipelined`` / ``voting``).

    Returns ``score(hist_s, masks, depth_ok_s, lo_s, hi_s, po_s, rand_s)
    -> BestSplit`` batched over the leading segment axis, with GLOBAL
    feature ids (the per-shard winners are combined through the same
    all-gather + argmax exchange the feature-parallel learner uses —
    :func:`~lightgbm_tpu.parallel.feature_parallel.reduce_best_split`).

    ``hist_s`` is the merged ``[S, F_pad/D, B, 3]`` feature SLICE under
    reduce-scatter, or the LOCAL unmerged ``[S, F, B, 3]`` partials under
    voting (the ballot and the candidate-union merge both happen here).
    All other per-feature arguments stay GLOBAL ``[.., F]`` — the scorer
    slices them to match, so monotone/categorical/extra-trees/interaction
    masks need no caller-side changes.  Because every shard holds
    contiguous ascending feature ranges, the cross-shard argmax preserves
    the serial scan's first-occurrence tie-break (lowest shard = lowest
    global feature id), which is what makes reduce-scatter mode
    serial-parity-exact.

    Under ``reduce_scatter_pipelined`` the scorer consumes the slice in
    ``merge_chunks`` static sub-chunks (the units the chunked ring lands):
    each chunk is scanned by its own ``find_best_split`` call the moment
    the slice-of-concat dataflow makes it available — XLA's async
    scheduler can then run chunk ``k``'s ring hops behind chunk ``k−1``'s
    scan — and the per-chunk winners combine with a first-occurrence
    argmax over the chunk axis (lowest chunk = lowest feature id, so the
    serial tie-break survives chunking too).
    """
    from ..ops.histogram import merge_slice_width
    from ..ops.split import feature_best_gains
    from ..parallel.feature_parallel import reduce_best_split

    rs = hist_merge in ("reduce_scatter", "reduce_scatter_ring",
                        "reduce_scatter_pipelined")
    chunks = (max(int(merge_chunks), 1)
              if hist_merge == "reduce_scatter_pipelined" else 1)
    f_loc = merge_slice_width(num_features, n_shards, hist_merge, chunks)
    f_pad = f_loc * n_shards

    def pad_f(a, axis, value):
        if f_pad == num_features:
            return a
        pads = [(0, 0)] * a.ndim
        pads[axis] = (0, f_pad - num_features)
        return jnp.pad(a, pads, constant_values=value)

    def fslice(a, axis, value=0):
        start = lax.axis_index(axis_name) * f_loc
        return lax.dynamic_slice_in_dim(pad_f(a, axis, value), start, f_loc,
                                        axis=axis)

    if rs:
        # static per-feature config arrays slice ONCE; padded tail columns
        # carry mask 0 / mono 0 / is_cat False so a ragged last shard (or a
        # fully-padded shard when D > F) scores every pad slot -inf
        cat_l = (None if cat_info is None else cat_info._replace(
            is_cat=fslice(cat_info.is_cat, 0, False)))
        mono_l = None if mono is None else fslice(mono, 0, 0)
        sub = f_loc // chunks           # divisible by construction

        def csl(a, axis, c):            # static chunk window c of a slice
            return lax.slice_in_dim(a, c * sub, (c + 1) * sub, axis=axis)

        def score(hist_s, masks, depth_ok_s, lo_s, hi_s, po_s, rand_s=None):
            masks_l = fslice(masks, 1, 0.0)
            rand_l = None if rand_s is None else fslice(rand_s, 1, 0)
            per_chunk = []
            for c in range(chunks):
                cat_c = (None if cat_l is None else cat_l._replace(
                    is_cat=csl(cat_l.is_cat, 0, c)))
                mono_c = None if mono_l is None else csl(mono_l, 0, c)
                if rand_l is None:
                    def one(h, m, d, lo, hi, po,
                            cat_c=cat_c, mono_c=mono_c):
                        return find_best_split(h, ctx, m, d, cat_c, mono_c,
                                               lo, hi, po)

                    bs = jax.vmap(one)(csl(hist_s, 1, c), csl(masks_l, 1, c),
                                       depth_ok_s, lo_s, hi_s, po_s)
                else:
                    def one(h, m, d, lo, hi, po, rb,
                            cat_c=cat_c, mono_c=mono_c):
                        return find_best_split(h, ctx, m, d, cat_c, mono_c,
                                               lo, hi, po, rb)

                    bs = jax.vmap(one)(csl(hist_s, 1, c), csl(masks_l, 1, c),
                                       depth_ok_s, lo_s, hi_s, po_s,
                                       csl(rand_l, 1, c))
                if c:
                    bs = bs._replace(feature=bs.feature + c * sub)
                per_chunk.append(bs)
            if chunks == 1:
                bs = per_chunk[0]
            else:
                # first-occurrence argmax over the chunk axis: gain ties
                # resolve to the lowest chunk, hence the lowest global
                # feature id — the serial scan's tie-break, preserved
                stacked = jax.tree.map(lambda *xs: jnp.stack(xs),
                                       *per_chunk)
                win = jnp.argmax(stacked.gain, axis=0)
                bs = jax.tree.map(
                    lambda x: jax.vmap(lambda xc, w: xc[w],
                                       in_axes=(1, 0))(x, win), stacked)
            return jax.vmap(
                lambda b: reduce_best_split(b, axis_name, f_loc))(bs)

        return score

    # ---- voting merge (PV-Tree / upstream VotingParallelTreeLearner) ----
    # Each shard nominates its local top-k features by LOCAL gain; the
    # global candidate set is the top-(2k) by vote count, and only those
    # columns are reduce-scattered.  Approximate by construction (a
    # feature strong globally but nowhere locally top-k is never merged);
    # when 2k >= F the union is exact and the result matches reduce-scatter
    # (minus candidate ORDER, so the exact-union short-circuit below keeps
    # ascending ids for strict parity).
    k_top = max(1, min(int(voting_k) if voting_k else 20, num_features))
    kc = min(2 * k_top, num_features)
    kc_pad = -(-kc // n_shards) * n_shards
    kc_loc = kc_pad // n_shards
    exact_union = kc == num_features

    def one_vote(h_local, m, d, lo, hi, po, rb):
        if exact_union:
            cand_ids = lax.iota(jnp.int32, kc)
        else:
            g_loc = feature_best_gains(h_local, ctx, m, d, mono=mono,
                                       bound_lo=lo, bound_hi=hi,
                                       parent_out=po, rand_bins=rb)
            kth = -jnp.sort(-g_loc)[k_top - 1]
            local_top = jnp.isfinite(g_loc) & (g_loc >= kth)
            votes = lax.psum(local_top.astype(jnp.float32), axis_name)
            # stable argsort of -votes: vote ties resolve to the lower
            # feature id on every shard identically
            cand_ids = jnp.argsort(-votes, stable=True)[:kc].astype(
                jnp.int32)
        cand_hist = jnp.take(h_local, cand_ids, axis=0)       # [kc, B, 3]
        if kc_pad != kc:
            cand_hist = jnp.pad(cand_hist,
                                ((0, kc_pad - kc), (0, 0), (0, 0)))
            cand_ids = jnp.pad(cand_ids, (0, kc_pad - kc))
        merged = lax.psum_scatter(cand_hist, axis_name,
                                  scatter_dimension=0, tiled=True)
        shard = lax.axis_index(axis_name)
        ids_l = lax.dynamic_slice_in_dim(cand_ids, shard * kc_loc, kc_loc)
        slot = shard * kc_loc + lax.iota(jnp.int32, kc_loc)
        valid = slot < kc               # pad slots: zero hist, masked out
        m_l = jnp.where(valid, m[ids_l], 0.0)
        mono_l2 = None if mono is None else jnp.where(valid, mono[ids_l], 0)
        rb_l = None if rb is None else rb[ids_l]
        bs = find_best_split(merged, ctx, m_l, d, None, mono_l2, lo, hi,
                             po, rb_l)
        return reduce_best_split(bs, axis_name, kc_loc, feature_map=ids_l)

    def score(hist_s, masks, depth_ok_s, lo_s, hi_s, po_s, rand_s=None):
        if rand_s is None:
            def onev(h, m, d, lo, hi, po):
                return one_vote(h, m, d, lo, hi, po, None)

            return jax.vmap(onev)(hist_s, masks, depth_ok_s, lo_s, hi_s,
                                  po_s)
        return jax.vmap(one_vote)(hist_s, masks, depth_ok_s, lo_s, hi_s,
                                  po_s, rand_s)

    return score


def renew_leaf_values(tree: Tree, row_leaf: jnp.ndarray, residual: jnp.ndarray,
                      weight: jnp.ndarray, alpha) -> Tree:
    """Refit leaf values as weighted alpha-quantiles of the residuals.

    TPU-native equivalent of LightGBM's ``RegressionL1loss::RenewTreeOutput``
    (and the quantile variant): the Newton step is a poor leaf estimator for
    L1/quantile losses, so after the tree structure is fixed each leaf's
    value is replaced by the weighted alpha-quantile (alpha=0.5 -> weighted
    median) of ``residual`` over its rows.

    Formulation without per-leaf loops: one global sort of rows by residual,
    one stable sort by leaf id, then every leaf's quantile is found with a
    vectorized ``searchsorted`` on the global cumulative-weight vector.
    Zero-weight rows (padding, bagged-out) advance no cumulative weight and
    therefore never become a quantile.  O(n log n) VPU work, off the MXU
    hot loop, only traced in when the objective requests renewal.
    """
    capacity = tree.leaf_value.shape[-1]
    alpha = jnp.float32(alpha)
    order = jnp.argsort(residual)
    leaf_o = row_leaf[order]
    order2 = jnp.argsort(leaf_o, stable=True)
    perm = order[order2]
    leaf_s = row_leaf[perm]
    r_s = residual[perm]
    w_s = weight[perm]
    cw = jnp.cumsum(w_s)
    # per-leaf row spans via binary search on the (sorted) leaf ids — no
    # [n, capacity] one-hot materialization
    ids = lax.iota(jnp.int32, capacity)
    starts = jnp.searchsorted(leaf_s, ids, side="left")
    ends = jnp.searchsorted(leaf_s, ids, side="right")
    cw0 = jnp.concatenate([jnp.zeros(1), cw])
    w_before = cw0[starts]
    totals = cw0[ends] - w_before
    target = w_before + alpha * totals
    idx = jnp.clip(jnp.searchsorted(cw, target, side="left"), 0,
                   r_s.shape[0] - 1)
    quant = r_s[idx]
    new_vals = jnp.where((totals > 0) & tree.is_leaf, quant,
                         tree.leaf_value)
    return tree._replace(leaf_value=new_vals)


def pad_tree(tree: Tree, capacity: int) -> Tree:
    """Pad a tree's node arrays (last axis) up to ``capacity`` slots.

    Used when stacking forests of mixed ``num_leaves`` — e.g. an
    ``init_model`` continuation trained with a different leaf budget.  Padded
    slots are unreachable (no node points at them) and carry the grower's
    unused-slot sentinels: is_leaf=False, children=-1, zero values — so
    downstream used-node masks (``~is_leaf & (left >= 0)``) stay correct.
    """
    m = tree.split_feature.shape[-1]
    if m == capacity:
        return tree
    if m > capacity:
        raise ValueError(f"cannot shrink tree capacity {m} -> {capacity}")
    pad = [(0, 0)] * (tree.split_feature.ndim - 1) + [(0, capacity - m)]

    def p(a, val=0):
        return jnp.pad(a, pad, constant_values=val)

    def p_node2(a, val=False):
        """Pad the NODE axis of a [..., M, B] array (cat_mask)."""
        pads = [(0, 0)] * a.ndim
        pads[-2] = (0, capacity - m)
        return jnp.pad(a, pads, constant_values=val)

    return Tree(
        split_feature=p(tree.split_feature), split_bin=p(tree.split_bin),
        left=p(tree.left, -1), right=p(tree.right, -1),
        leaf_value=p(tree.leaf_value), is_leaf=p(tree.is_leaf, False),
        count=p(tree.count), split_gain=p(tree.split_gain),
        num_leaves=tree.num_leaves,
        is_cat_split=(None if tree.is_cat_split is None
                      else p(tree.is_cat_split, False)),
        cat_mask=(None if tree.cat_mask is None
                  else p_node2(tree.cat_mask)),
        linear_feat=(None if tree.linear_feat is None
                     else p_node2(tree.linear_feat, -1)),
        linear_coef=(None if tree.linear_coef is None
                     else p_node2(tree.linear_coef, 0.0)))


def grow_tree(
    bins: jnp.ndarray,
    stats: jnp.ndarray,
    feature_mask: jnp.ndarray,
    ctx: SplitContext,
    num_leaves: int,
    num_bins: int,
    max_depth,
    ff_bynode=None,
    key: Optional[jnp.ndarray] = None,
    axis_name: Optional[str] = None,
    hist_impl: str = "auto",
    row_chunk: int = 131072,
    hist_dtype: str = "f32",
    wave_width: int = 1,
    cat_info=None,
    fp_axis: Optional[str] = None,
    mono=None,
    extra_trees: bool = False,
    col_bins=None,
    ic_member=None,
    wave_tail: str = "half",
    fuse_partition: bool = False,
    fuse_split: bool = True,
    hist_merge: str = "psum",
    n_shards: int = 1,
    voting_k: int = 0,
    hist_wire: str = "f32",
    merge_chunks: int = 4,
) -> Tuple[Tree, jnp.ndarray]:
    """Grow one best-first tree.

    Args:
      bins: uint8/int32 ``[n, F]`` binned features (full, static shape; rows
        not in this tree's bag simply carry zero stats).
      stats: f32 ``[n, 3]`` of (grad, hess, in-bag indicator).  grad/hess must
        already include sample weights and bagging mask; padding rows all-zero.
      feature_mask: f32 ``[F]`` — 1 for features usable this tree.
      ctx: traced regularization scalars.
      num_leaves: static leaf budget (r/gridsearchCV.R:96 grid axis).
      num_bins: static histogram bin-axis size.
      max_depth: traced i32; <= 0 means unlimited (LightGBM default -1).
      ff_bynode: traced per-node feature-sampling fraction (LightGBM
        ``feature_fraction_bynode`` — sklearn RandomForest's per-split
        ``max_features``); None/1.0 disables sampling.
      key: PRNG key for per-node sampling (folded with the node id, so the
        sampled set differs per node but is deterministic under the seed).
      axis_name: if set, per-shard histograms are psum-merged over this mesh
        axis — the data-parallel tree learner (SURVEY.md §2C).
      mono: optional i32 ``[F]`` monotone constraints in {-1, 0, +1}
        (upstream ``monotone_constraints``, basic method: violating splits
        rejected, descendants clipped at the split's output mid-point).
      extra_trees: ExtraTrees randomization (upstream ``extra_trees``) —
        each node considers ONE random threshold per feature, drawn
        deterministically from ``key`` and the node id within the
        feature's own used-bin range (``col_bins``).
      col_bins: optional i32 ``[F]`` per-training-column used-bin counts
        (BinMapper.n_bins / EFB col_bins) bounding the extra_trees draw.
      fuse_split: run each strict split iteration as ONE Pallas call
        (:func:`~lightgbm_tpu.ops.histogram_pallas.split_iter_pallas`:
        cumsum gain scan + argmax + winner gather + packed-table update
        in VMEM) instead of the ~49-fusion XLA body.  Engages only on
        the plain numeric path (no categorical/monotone/extra-trees/
        interaction/bynode-sampling/feature-parallel); numerics are
        bitwise identical (tests/test_split_iter_fused.py).
      hist_merge: how per-shard histogram partials combine under
        ``axis_name`` (see :func:`~lightgbm_tpu.ops.histogram.
        histogram_merge`): ``"psum"`` (full allreduce, the r0 baseline),
        ``"reduce_scatter"`` / ``"reduce_scatter_ring"`` (each shard
        receives only its ``F/D`` feature slice and scans splits over it
        — LightGBM's data-parallel Reduce-Scatter topology, 1/D the comm
        bytes, serial-parity-exact), or ``"voting"`` (PV-Tree: shards
        nominate local top-k features, only the voted candidate union is
        merged — approximate, cheapest).  ``n_shards`` must give the
        static mesh-axis size for the non-psum modes; ``voting_k`` is
        the per-shard ballot size (top-2k candidates merge globally).
        ``"reduce_scatter_pipelined"`` splits the ring into
        ``merge_chunks`` sub-rings whose hops interleave with the
        per-chunk split scans (r10 comm/compute overlap); ``hist_wire``
        (``"f32"``/``"bf16"``/``"int8"``) compresses ring-hop messages —
        f32 keeps the exactness bar, bf16/int8 are quality-gated.

    Returns:
      (Tree, row_leaf) — row_leaf gives each training row's final leaf node id
      so the boosting loop can update train predictions with one gather.

    ``|wave_width| > 1`` dispatches to :func:`grow_tree_frontier` (multiple
    splits per histogram pass via the subtraction trick — the large-data
    fast path).  ``wave_width`` carries the wave TAIL policy in its
    encoding so the policy rides every existing static plumbing path
    (compile-cache keys, mesh learners) untouched:

      * NEGATIVE — "greedy" tail (spend the whole remaining leaf budget
        per wave, fewest histogram passes);
      * ``>= 1024`` — "exact" mode, encoded ``overgrow_leaves * 1024 +
        width``: overgrow greedily to ``overgrow_leaves``, then replay
        strict best-first selection over the realized gains and prune
        back to ``num_leaves`` (LightGBM-exact split ORDER at near-greedy
        pass counts — see :func:`_exact_prune`);
      * otherwise — "half" tail (near-strict tail ordering).
    """
    raw_wave_width = wave_width
    wave_width, decoded_tail, overgrow_leaves = decode_wave_width(wave_width)
    if decoded_tail == "exact" and (
            wave_width > 512 or overgrow_leaves <= num_leaves):
        # ints >= 1024 are RESERVED for resolve_wave_width's exact-tail
        # encoding (overgrow_leaves * 1024 + width, width <= 512, overgrow
        # strictly past num_leaves).  A direct caller passing a genuine
        # width (e.g. 2000) would otherwise be silently misrouted into
        # exact mode with a nonsense overgrow target (ADVICE r5) — reject
        # it instead; widths beyond 512 are past the MXU tile sweet spot
        # and are clamped by the encoder anyway.
        raise ValueError(
            f"wave_width={raw_wave_width} decodes to exact-tail "
            f"(width={wave_width}, overgrow_leaves={overgrow_leaves}) but "
            f"is not a valid resolve_wave_width encoding for "
            f"num_leaves={num_leaves}; raw widths must be < 1024 — use "
            "gbdt.resolve_wave_width to encode the exact tail")
    if decoded_tail != "half" or wave_tail == "half":
        wave_tail = decoded_tail
    if wave_width > 1 and not (fp_axis is not None and cat_info is not None):
        # (frontier + feature-parallel since r5; categorical k-vs-rest
        # splits under fp keep the strict grower's psum-broadcast path)
        return grow_tree_frontier(
            bins, stats, feature_mask, ctx, num_leaves, num_bins, max_depth,
            wave_width, ff_bynode=ff_bynode, key=key, axis_name=axis_name,
            hist_impl=hist_impl, row_chunk=row_chunk, hist_dtype=hist_dtype,
            cat_info=cat_info, mono=mono, extra_trees=extra_trees,
            col_bins=col_bins, ic_member=ic_member, wave_tail=wave_tail,
            overgrow_leaves=overgrow_leaves, fp_axis=fp_axis,
            fuse_partition=fuse_partition, hist_merge=hist_merge,
            n_shards=n_shards, voting_k=voting_k, hist_wire=hist_wire,
            merge_chunks=merge_chunks)
    n, num_features = bins.shape
    capacity = 2 * num_leaves - 1
    max_depth = jnp.asarray(max_depth, jnp.int32)
    neg_inf = jnp.float32(-jnp.inf)
    if key is None:
        key = jax.random.PRNGKey(0)
    bynode_off = ff_bynode is None   # static: skip the per-node RNG draw

    if axis_name is None:
        hist_merge = "psum"          # single-shard: nothing to merge
    dist_mode = hist_merge != "psum"
    if dist_mode and fp_axis is not None:
        raise ValueError(
            f"hist_merge={hist_merge!r} is a data-parallel merge topology "
            "and cannot compose with feature sharding (fp_axis) — the 2-D "
            "dp x fp mesh keeps the psum merge")
    if hist_merge == "voting" and cat_info is not None:
        raise ValueError(
            "hist_merge='voting' does not support categorical splits (the "
            "local ballot scans numeric thresholds only) — use "
            "'reduce_scatter' or 'psum'")
    score_dist = (_make_dist_scorer(axis_name, hist_merge, n_shards,
                                    num_features, ctx, cat_info, mono,
                                    voting_k, merge_chunks)
                  if dist_mode else None)

    # Split-iteration mega-kernel gate (ops.histogram_pallas
    # ._split_iter_kernel): the ~49-fusion tail of each split iteration —
    # gain scan, argmax, winner gather, three node-table row writes, and
    # the NEXT iteration's leaf pick — collapses into one pallas call.
    # Static eligibility mirrors what the kernel traces: no categorical
    # subset scan, no monotone bounds, no per-node RNG (bynode sampling /
    # extra_trees), no interaction-constraint set recurrence, and no
    # feature sharding (the winner must be globalized OUTSIDE the kernel).
    # Numerics are bitwise identical to the XLA body by construction (the
    # shared ops.split.split_gain_scan helper + first-occurrence argmax);
    # ``fuse_split=False`` keeps the reference XLA body for debugging.
    fuse_si = (fuse_split and cat_info is None and mono is None
               and not extra_trees and ic_member is None and bynode_off
               and fp_axis is None and not dist_mode)

    # per-node column subsample: the ONE shared mask-composition layer
    # (models.feature_mask, r20) — bynode draws WITHIN the tree mask,
    # which under screening is already compacted to the active set
    from .feature_mask import node_mask_fn

    node_feature_mask = node_mask_fn(key, ff_bynode, num_features,
                                     feature_mask, bynode_off)

    def node_rand_bins(node_id):
        if not extra_trees:
            return None
        return _rand_bins_for_node(key, node_id, num_features, num_bins,
                                   col_bins)

    def hist_fn(seg_id, num_segments):
        # custom-vmap op: under fold/config/class batching, calls sharing
        # this binned matrix collapse into ONE wide-matmul pass instead of
        # per-element skinny matmuls (memory-bound otherwise)
        from ..ops.histogram import batched_histogram_op

        op = batched_histogram_op(num_segments, num_bins, row_chunk,
                                  hist_impl, hist_dtype)
        h = op(bins, stats, seg_id)
        if hist_merge == "voting":
            return h       # local partials; the scorer merges candidates
        return histogram_merge(h, axis_name, mode=hist_merge,
                               n_shards=n_shards, wire_dtype=hist_wire,
                               n_chunks=merge_chunks)

    # ---- root -------------------------------------------------------------
    # under rs the merged root_hist is this shard's [F_pad/D, B, 3] slice;
    # under voting the LOCAL unmerged partial
    root_hist = hist_fn(jnp.zeros(n, jnp.int32), 1)[0]          # [F, B, 3]
    if dist_mode:
        # global totals without the full histogram: stats rows sum to the
        # histogram totals by construction, so one [3]-element psum
        # replaces reading bins of feature 0 from a (now sliced) histogram
        root_tot = lax.psum(jnp.sum(stats, axis=0), axis_name)
    else:
        root_tot = jnp.sum(root_hist[0], axis=0)                 # (g, h, c)
    # root output: unsmoothed (no parent), but still max_delta_step-capped
    root_out = constrained_leaf_output(
        root_tot[0], root_tot[1], root_tot[2],
        ctx._replace(path_smooth=jnp.float32(0.0)),
        jnp.float32(-jnp.inf), jnp.float32(jnp.inf), jnp.float32(0.0))
    if ic_member is not None:
        ng = ic_member.shape[0]
        root_sets = jnp.ones((ng,), bool)
        root_mask = node_feature_mask(0) * _ic_allowed(root_sets, ic_member)
    else:
        root_mask = node_feature_mask(0)
    # LightGBM convention: max_depth <= 0 means unlimited, so the root
    # (depth 0) is always splittable — if a limit exists it is >= 1.
    if dist_mode:
        rb0 = node_rand_bins(0)
        root_best = jax.tree.map(lambda x: x[0], score_dist(
            root_hist[None], root_mask[None], jnp.ones((1,), bool),
            jnp.full((1,), -jnp.inf, jnp.float32),
            jnp.full((1,), jnp.inf, jnp.float32), root_out[None],
            None if rb0 is None else rb0[None]))
    else:
        root_best = find_best_split(root_hist, ctx, root_mask,
                                    jnp.bool_(True), cat_info, mono=mono,
                                    parent_out=root_out,
                                    rand_bins=node_rand_bins(0))
    if fp_axis is not None:
        root_best = _fp_reduce_best(root_best, fp_axis, num_features)

    K = _PK
    st = _GrowState(
        nodes=_packed_root_table(capacity, root_out, root_tot, root_best,
                                 cat_info),
        row_leaf=jnp.zeros(n, jnp.int32),
        n_nodes=jnp.int32(1),
        n_leaves=jnp.int32(1),
        done=jnp.bool_(False),
        cand_catmask=(None if cat_info is None else
                      jnp.zeros((capacity, num_bins), jnp.bool_)
                      .at[0].set(root_best.cat_mask)),
        ic_sets=(None if ic_member is None else
                 jnp.zeros((capacity, ic_member.shape[0]), bool)
                 .at[0].set(True)),
    )

    bins_i32 = bins.astype(jnp.int32)

    if fuse_si:
        from ..ops.histogram_pallas import split_iter_pallas  # noqa: F401

        f32 = jnp.float32
        zero = jnp.float32(0.0)
        # aux carries the pick the NEXT iteration acts on; the root pick
        # reproduces iteration 0's argmax (only node 0 is a leaf, so the
        # picked leaf is 0 and its gain is the root candidate's)
        aux0 = jnp.stack([
            zero, root_best.feature.astype(f32), root_best.bin.astype(f32),
            jnp.isfinite(root_best.gain).astype(f32),
            zero, zero, zero, zero]).reshape(1, 8)
        fmask_row = feature_mask.astype(f32).reshape(1, num_features)
        md_f = max_depth.astype(f32)

        def body_f(_, carry):
            P, row_leaf_c, n_nodes, n_leaves, aux = carry
            leaf = aux[0, 0].astype(jnp.int32)
            feat = aux[0, 1].astype(jnp.int32)
            thr = aux[0, 2].astype(jnp.int32)
            active = aux[0, 3] > 0
            nl, nr = n_nodes, n_nodes + 1
            # partition + segment select stay in XLA (they touch the [n]
            # row axis); everything table-sized moves into the kernel
            col = jnp.take(bins_i32, feat, axis=1)
            go_left = col <= thr
            new_rl = jnp.where(row_leaf_c == leaf,
                               jnp.where(go_left, nl, nr), row_leaf_c)
            row_leaf2 = jnp.where(active, new_rl, row_leaf_c)
            seg = jnp.where(row_leaf2 == nl, 0,
                            jnp.where(row_leaf2 == nr, 1, 2)).astype(
                                jnp.int32)
            hist2 = hist_fn(seg, 2)                      # [2, F, B, 3]
            scal = jnp.stack([
                jnp.asarray(ctx.lambda_l1, f32),
                jnp.asarray(ctx.lambda_l2, f32),
                jnp.asarray(ctx.min_data_in_leaf, f32),
                jnp.asarray(ctx.min_sum_hessian, f32),
                jnp.asarray(ctx.min_gain_to_split, f32),
                jnp.asarray(ctx.max_delta_step, f32),
                jnp.asarray(ctx.path_smooth, f32),
                md_f, n_nodes.astype(f32),
                zero, zero, zero, zero, zero, zero, zero]).reshape(1, 16)
            if _SPLIT_ITER_OPCOUNT_STUB:
                # op-count probe (tools/hlo_counts.py): swap the kernel
                # for a pure_callback so a CPU compile shows the same
                # launch structure a TPU build has — XLA-side fusions
                # plus ONE custom-call (interpret mode would inline the
                # kernel instead).  Compile-only; never executed.
                P2, aux2 = jax.pure_callback(
                    lambda h, p, a: (p, a),
                    (jax.ShapeDtypeStruct(P.shape, P.dtype),
                     jax.ShapeDtypeStruct(aux.shape, aux.dtype)),
                    hist2.transpose(0, 1, 3, 2), P, aux,
                    vmap_method="legacy_vectorized")
            else:
                P2, aux2 = split_iter_pallas(
                    hist2.transpose(0, 1, 3, 2), P, fmask_row, aux, scal,
                    pk=_PK)
            grew = jnp.where(active, 1, 0).astype(jnp.int32)
            return (P2, row_leaf2, n_nodes + 2 * grew, n_leaves + grew,
                    aux2)

        P_f, row_leaf_f, _, n_leaves_f, _ = lax.fori_loop(
            0, num_leaves - 1, body_f,
            (st.nodes, st.row_leaf, st.n_nodes, st.n_leaves, aux0))
        return (_tree_from_packed(P_f, n_leaves_f, None, None), row_leaf_f)

    def body(_, st: _GrowState) -> _GrowState:
        P = st.nodes
        # 1. pick the active leaf with the best cached gain (best-first).
        gains = jnp.where(P[:, K.IS_LEAF] > 0.5, P[:, K.CAND_GAIN], neg_inf)
        leaf = jnp.argmax(gains).astype(jnp.int32)
        gain = gains[leaf]
        active = (~st.done) & jnp.isfinite(gain)

        nl = st.n_nodes
        nr = st.n_nodes + 1
        row = P[leaf]                       # [NC] — ONE gather for every
        feat = row[K.CAND_FEAT].astype(jnp.int32)   # cached scalar below
        thr = row[K.CAND_BIN].astype(jnp.int32)

        # 2. partition rows of the split leaf (gather, no pointer chasing).
        if fp_axis is not None:
            col = _fp_column(bins_i32, feat, fp_axis, num_features)
        else:
            col = jnp.take(bins_i32, feat, axis=1)
        if cat_info is None:
            go_left = col <= thr
        else:
            go_left = jnp.where(row[K.CAND_CAT] > 0.5,
                                st.cand_catmask[leaf][col], col <= thr)
        new_rl = jnp.where(
            st.row_leaf == leaf, jnp.where(go_left, nl, nr), st.row_leaf)
        row_leaf = jnp.where(active, new_rl, st.row_leaf)

        # 3. both children's histograms in one pass (others -> segment 2).
        seg = jnp.where(row_leaf == nl, 0,
                        jnp.where(row_leaf == nr, 1, 2)).astype(jnp.int32)
        hist2 = hist_fn(seg, 2)                                  # [2, F, B, 3]

        # 4. child output bounds (monotone basic method).
        wl_v, wr_v = row[K.CAND_WL], row[K.CAND_WR]
        lo, hi = row[K.BOUND_LO], row[K.BOUND_HI]
        lo_l, hi_l, lo_r, hi_r = _mono_child_bounds(mono, feat, wl_v, wr_v,
                                                    lo, hi)

        # 5. candidate splits for the children (each child samples its own
        # per-node feature subset when feature_fraction_bynode < 1).
        child_depth = row[K.DEPTH] + 1.0
        depth_ok = (max_depth <= 0) | \
            (child_depth < max_depth.astype(jnp.float32))
        child_masks = jnp.stack([node_feature_mask(nl), node_feature_mask(nr)])
        if ic_member is not None:
            child_sets = st.ic_sets[leaf] & ic_member[:, feat]   # [NG]
            child_masks = child_masks * _ic_allowed(child_sets,
                                                    ic_member)[None, :]
        child_lo = jnp.stack([lo_l, lo_r])
        child_hi = jnp.stack([hi_l, hi_r])
        child_out = jnp.stack([wl_v, wr_v])
        if dist_mode:
            child_rand = (jnp.stack([node_rand_bins(nl), node_rand_bins(nr)])
                          if extra_trees else None)
            bs = score_dist(hist2, child_masks, jnp.stack([depth_ok,
                                                           depth_ok]),
                            child_lo, child_hi, child_out, child_rand)
        elif extra_trees:
            child_rand = jnp.stack([node_rand_bins(nl), node_rand_bins(nr)])

            def score(h, m, lo_, hi_, po, rb):
                return find_best_split(h, ctx, m, depth_ok, cat_info, mono,
                                       lo_, hi_, po, rb)

            bs: BestSplit = jax.vmap(score)(hist2, child_masks, child_lo,
                                            child_hi, child_out, child_rand)
        else:

            def score(h, m, lo_, hi_, po):
                return find_best_split(h, ctx, m, depth_ok, cat_info, mono,
                                       lo_, hi_, po)

            bs = jax.vmap(score)(hist2, child_masks, child_lo, child_hi,
                                 child_out)
        if fp_axis is not None:
            bs = jax.vmap(
                lambda b: _fp_reduce_best(b, fp_axis, num_features))(bs)

        # 6. three packed row writes: the split leaf becomes internal, the
        # two children arrive with their cached candidate splits.
        leaf_row = row.at[jnp.array([
            K.SPLIT_FEAT, K.SPLIT_BIN, K.LEFT, K.RIGHT, K.IS_LEAF,
            K.SPLIT_GAIN])].set(jnp.stack([
                feat.astype(jnp.float32), thr.astype(jnp.float32),
                nl.astype(jnp.float32), nr.astype(jnp.float32),
                jnp.float32(0.0), gain]))
        two = lambda a, b: jnp.stack([a, b])
        child_rows = jnp.stack([
            jnp.full((2,), -1.0),                        # SPLIT_FEAT
            jnp.zeros((2,)),                             # SPLIT_BIN
            jnp.full((2,), -1.0),                        # LEFT
            jnp.full((2,), -1.0),                        # RIGHT
            two(wl_v, wr_v),                             # LEAF_VALUE
            jnp.ones((2,)),                              # IS_LEAF
            two(row[K.CAND_LC], row[K.CAND_RC]),         # COUNT
            jnp.zeros((2,)),                             # SPLIT_GAIN
            jnp.full((2,), child_depth),                 # DEPTH
            bs.gain,                                     # CAND_GAIN
            bs.feature.astype(jnp.float32),              # CAND_FEAT
            bs.bin.astype(jnp.float32),                  # CAND_BIN
            bs.left_g, bs.left_h, bs.left_c,
            bs.right_g, bs.right_h, bs.right_c,
            bs.left_out,                                 # CAND_WL
            bs.right_out,                                # CAND_WR
            two(lo_l, lo_r),                             # BOUND_LO
            two(hi_l, hi_r),                             # BOUND_HI
            (bs.cat.astype(jnp.float32) if cat_info is not None
             else jnp.zeros((2,))),                      # CAND_CAT
            jnp.minimum(row[K.PM], bs.gain),             # PM
        ], axis=-1)                                      # [2, NC]
        oob = jnp.int32(capacity)
        P = P.at[jnp.where(active, leaf, oob)].set(leaf_row, mode="drop")
        kid_idx = jnp.where(active, jnp.stack([nl, nr]), oob)
        P = P.at[kid_idx].set(child_rows, mode="drop")

        return st._replace(
            nodes=P,
            row_leaf=row_leaf,
            n_nodes=st.n_nodes + jnp.where(active, 2, 0).astype(jnp.int32),
            n_leaves=st.n_leaves + jnp.where(active, 1, 0).astype(jnp.int32),
            done=st.done | ~jnp.isfinite(gain),
            cand_catmask=(None if cat_info is None else
                          st.cand_catmask.at[kid_idx].set(
                              bs.cat_mask, mode="drop")),
            ic_sets=(None if ic_member is None else
                     st.ic_sets.at[kid_idx].set(
                         jnp.stack([child_sets, child_sets]), mode="drop")),
        )

    st = lax.fori_loop(0, num_leaves - 1, body, st)
    tree = _tree_from_packed(st.nodes, st.n_leaves, cat_info,
                             st.cand_catmask)
    return tree, st.row_leaf


def _scatter(arr, idx, val, active):
    """Masked vector scatter: arr[idx[i]] = val[i] where active[i].

    Inactive lanes are redirected to an out-of-bounds index and dropped
    (positive OOB, because negative indices wrap in JAX).
    """
    oob = arr.shape[0]
    safe = jnp.where(active, idx, oob)
    return arr.at[safe].set(val, mode="drop")


def _exact_prune(P, cand_catmask, row_leaf, num_leaves: int,
                 cat_info):
    """Replay strict best-first selection over an OVERGROWN wave tree and
    prune it back to ``num_leaves`` — LightGBM-exact split order at wave
    cost.

    Every node's candidate split (gain, feature, bin, child outputs)
    depends only on its OWN rows, so the overgrown tree's realized gains
    are exactly the gains strict growth would have scored, and strict
    best-first growth is priority-first extraction over that gain tree
    (a node becomes extractable when its parent is extracted).  The
    selection below replays the extraction literally on the packed node
    table; the pruning and row remap are vectorized.

    Coverage caveat: if strict would have split a node the overgrowth
    never expanded (an overgrown LEAF with competitive gain), that node
    stays a leaf and its budget goes to the next-best candidate — the
    only divergence from true strict order.  The overgrowth waves
    select by PATHMIN (= priority-first extraction order between
    distinct priorities), which expands nodes in near-strict order and
    makes misses rare at the ~2x default overgrowth (validated vs the strict
    grower in tests/test_exact_wave.py; quality impact measured in the
    bench's parity section).

    Returns (packed table [2*num_leaves-1, NC], pruned cand_catmask,
    remapped row_leaf, n_leaves).
    """
    K = _PK
    m_over = P.shape[0]
    capacity = 2 * num_leaves - 1
    ids = lax.iota(jnp.int32, m_over)
    left = P[:, K.LEFT].astype(jnp.int32)
    right = P[:, K.RIGHT].astype(jnp.int32)
    # parent pointers (root: parent = self = 0)
    parent = jnp.zeros(m_over, jnp.int32)
    parent = _scatter(parent, left, ids, left >= 0)
    parent = _scatter(parent, right, ids, right >= 0)

    expandable = left >= 0            # children exist in the overgrown tree
    # Sequential priority-first replay of strict extraction.  A single
    # (pathmin desc, id asc) sort selects the right SET between distinct
    # pathmin values, but inside a pathmin TIE GROUP (structural: every
    # chain capped by one weak ancestor shares its pm) strict extraction
    # dives into high-gain descendants while any static id order is
    # breadth-first — and the budget boundary lands exactly in the
    # low-gain region where those groups are widest.  So the selection
    # replays extraction literally: num_leaves-1 trips of (argmax over
    # available candidate gains -> keep -> activate children), all on
    # [m_over]-sized arrays (~6 tiny fused kernels per trip; a few ms per
    # round at production shapes).  Overgrown leaves with no scored
    # children (coverage misses — rare under pathmin-ordered overgrowth)
    # are skipped in favor of the next-best candidate.
    gain_c = P[:, K.CAND_GAIN]
    avail0 = jnp.zeros(m_over, bool).at[0].set(True)
    kept0 = jnp.zeros(m_over, bool)

    def extract(_, carry):
        avail, kept = carry
        g_av = jnp.where(avail & expandable, gain_c, -jnp.inf)
        i = jnp.argmax(g_av).astype(jnp.int32)
        ok = jnp.isfinite(g_av[i])
        oob = jnp.int32(m_over)
        kept = kept.at[jnp.where(ok, i, oob)].set(True, mode="drop")
        avail = avail.at[jnp.where(ok, i, oob)].set(False, mode="drop")
        kids = jnp.where(ok, jnp.stack([left[i], right[i]]), oob)
        avail = avail.at[kids].set(True, mode="drop")
        return avail, kept

    _, kept = lax.fori_loop(0, num_leaves - 1, extract, (avail0, kept0))
    n_kept = jnp.sum(kept.astype(jnp.int32))

    # final leaves = children of kept splits that are not themselves kept
    # (plus the root when nothing was kept at all).  Gate on REAL nodes:
    # when growth stalls below the overgrowth target, unused table slots
    # keep parent=0, and once the root is kept they would masquerade as
    # its children — ghost IS_LEAF rows in the output (code review r5).
    real = (P[:, K.IS_LEAF] > 0.5) | expandable
    final_leaf = real & (~kept) & ((kept[parent] & (ids != 0))
                                   | ((ids == 0) & (n_kept == 0)))
    surv = kept | final_leaf
    newid = jnp.cumsum(surv.astype(jnp.int32)) - 1

    # rewrite rows: kept nodes stay internal with remapped children; final
    # leaves revert to leaf sentinels (their LEAF_VALUE / COUNT were set at
    # creation from the parent's candidate — identical to strict growth)
    f32 = jnp.float32
    P_mod = P
    P_mod = P_mod.at[:, K.LEFT].set(
        jnp.where(kept, newid[jnp.maximum(left, 0)], -1).astype(f32))
    P_mod = P_mod.at[:, K.RIGHT].set(
        jnp.where(kept, newid[jnp.maximum(right, 0)], -1).astype(f32))
    P_mod = P_mod.at[:, K.IS_LEAF].set(jnp.where(kept, 0.0, 1.0))
    P_mod = P_mod.at[:, K.SPLIT_FEAT].set(
        jnp.where(kept, P[:, K.SPLIT_FEAT], -1.0))
    P_mod = P_mod.at[:, K.SPLIT_BIN].set(
        jnp.where(kept, P[:, K.SPLIT_BIN], 0.0))
    P_mod = P_mod.at[:, K.SPLIT_GAIN].set(
        jnp.where(kept, P[:, K.SPLIT_GAIN], 0.0))
    target = jnp.where(surv, newid, capacity)
    newP = _empty_packed_table(capacity).at[target].set(P_mod, mode="drop")
    new_cat = (None if cat_info is None else
               jnp.zeros((capacity, cand_catmask.shape[1]), jnp.bool_)
               .at[target].set(cand_catmask, mode="drop"))

    # rows point at overgrown leaves — map each to its unique final-leaf
    # ancestor-or-self (pointer doubling: k squarings cover chains of
    # 2^k nodes, and any ancestor chain is < m_over long), then newid
    f = jnp.where(final_leaf, ids, parent)
    for _ in range(max(4, int(m_over).bit_length())):
        f = f[f]
    node_to_new = jnp.where(final_leaf[f], newid[f], 0).astype(f32)
    row_leaf_new = lookup_values(
        row_leaf, node_to_new,
        precision=(lax.Precision.DEFAULT if capacity <= 256
                   else lax.Precision.HIGHEST)).astype(jnp.int32)
    return newP, new_cat, row_leaf_new, n_kept + 1


class _WaveState(NamedTuple):
    nodes: jnp.ndarray          # f32[M, _PK.NC] packed per-node table
    # frontier extras
    hist_cache: jnp.ndarray     # f32[num_leaves, F, B, 3] per-active-leaf
    node_slot: jnp.ndarray      # i32[M] node id -> hist_cache slot
    # dynamic growth state
    row_leaf: jnp.ndarray
    n_nodes: jnp.ndarray
    n_leaves: jnp.ndarray
    # categorical candidate split masks (None when the dataset has none)
    cand_catmask: Optional[jnp.ndarray] = None  # bool[M, B]
    # interaction constraints: surviving group set per node (None = off)
    ic_sets: Optional[jnp.ndarray] = None       # bool[M, NG]


def grow_tree_frontier(
    bins: jnp.ndarray,
    stats: jnp.ndarray,
    feature_mask: jnp.ndarray,
    ctx: SplitContext,
    num_leaves: int,
    num_bins: int,
    max_depth,
    wave_width: int,
    ff_bynode=None,
    key: Optional[jnp.ndarray] = None,
    axis_name: Optional[str] = None,
    hist_impl: str = "auto",
    row_chunk: int = 131072,
    hist_dtype: str = "f32",
    cat_info=None,
    mono=None,
    extra_trees: bool = False,
    col_bins=None,
    ic_member=None,
    wave_tail: str = "half",
    overgrow_leaves: Optional[int] = None,
    fp_axis: Optional[str] = None,
    fuse_partition: bool = False,
    hist_merge: str = "psum",
    n_shards: int = 1,
    voting_k: int = 0,
    hist_wire: str = "f32",
    merge_chunks: int = 4,
) -> Tuple[Tree, jnp.ndarray]:
    """Best-first growth in WAVES: up to ``wave_width`` splits per data pass.

    The strict grower (:func:`grow_tree`) re-scans all rows once per split —
    ``num_leaves - 1`` full-data histogram passes per tree, which caps
    large-``num_leaves`` training at Higgs scale (VERDICT r1 item 3).  This
    variant is the TPU analogue of LightGBM's histogram-subtraction trick
    (upstream ``ConstructHistogram`` computes the smaller child and derives
    the sibling as parent − child; SURVEY.md §3.1 hot-loop trace):

      * per wave, the top-``W`` active leaves by cached candidate gain are
        split TOGETHER; one histogram pass computes each split's *smaller*
        child directly (W segments folded into one one-hot matmul — MXU
        lanes below 128 are padded anyway, so batching W splits into one
        pass costs roughly the same as one strict trip);
      * the sibling histogram is ``parent − child`` from a per-leaf
        histogram cache (f32 ``[num_leaves, F, B, 3]``);
      * fresh children get their candidate splits scored from the cached
        histograms with no extra data pass.

    A balanced 127-leaf tree takes ~8 passes instead of 126.  Semantics:
    with ``wave_width=1`` the split order equals strict best-first; with
    larger widths the wave's split set is chosen before the wave's children
    are scored, so when the leaf budget binds mid-wave the tree can spend
    budget on wave-start leaves that strict growth would have skipped in
    favor of higher-gain fresh children.  Predictive quality is equivalent
    in practice (tests compare both modes); LightGBM-exact split order
    needs either the strict grower or ``wave_tail="exact"`` — overgrow
    greedily to ``overgrow_leaves``, then :func:`_exact_prune` replays
    strict best-first selection over the realized gains and prunes back
    to ``num_leaves`` (the budget-binding tail is the ONLY place wave and
    strict order diverge, so recovering it recovers strict order at
    roughly one extra histogram pass — PERF.md r4 gap decomposition).
    """
    n, num_features = bins.shape
    exact = wave_tail == "exact"
    grow_leaves = (max(num_leaves + 1, int(overgrow_leaves or 0))
                   if exact else num_leaves)
    capacity = 2 * grow_leaves - 1
    w_width = min(int(wave_width), grow_leaves - 1)

    # partition-fused wave kernel (histogram + row routing in one pallas
    # call — r5 trace: ~22 ms/wave of XLA-side partition work at 11M rows
    # reads data the kernel already holds in VMEM).  Static eligibility:
    # single-model growth (callers opt in; vmapped/batched growth keeps
    # the custom-vmap wide-segment route), no feature sharding, no
    # categorical subset splits, and a pallas-routed dtype.  Since r7 the
    # feature axis may span multiple VMEM blocks — routing then reads the
    # wave-gathered split-feature code rows instead of the resident bins
    # tile (_fused_part_kernel_mb), so MSLR-class shapes (F=136) get the
    # in-kernel partition too.
    exact_dtype = hist_dtype == "f32x"
    route_pallas = (hist_impl == "pallas"
                    or (hist_impl == "auto" and not exact_dtype
                        and jax.default_backend() == "tpu"))
    fuse_part = (fuse_partition and fp_axis is None and cat_info is None
                 and hist_dtype != "int8" and route_pallas
                 and w_width > 1
                 # the per-row field lookup runs at bf16 DEFAULT
                 # precision — every table value (feature id, bin,
                 # 2*rank child offset) must be an exact bf16 integer
                 and max(num_features, 2 * w_width, num_bins) <= 256)
    max_depth = jnp.asarray(max_depth, jnp.int32)
    neg_inf = jnp.float32(-jnp.inf)
    if key is None:
        key = jax.random.PRNGKey(0)
    bynode_off = ff_bynode is None   # static: skip the per-node RNG draw

    if axis_name is None:
        hist_merge = "psum"          # single-shard: nothing to merge
    dist_mode = hist_merge != "psum"
    if dist_mode and fp_axis is not None:
        raise ValueError(
            f"hist_merge={hist_merge!r} is a data-parallel merge topology "
            "and cannot compose with feature sharding (fp_axis) — the 2-D "
            "dp x fp mesh keeps the psum merge")
    if hist_merge == "voting" and cat_info is not None:
        raise ValueError(
            "hist_merge='voting' does not support categorical splits (the "
            "local ballot scans numeric thresholds only) — use "
            "'reduce_scatter' or 'psum'")
    score_dist = (_make_dist_scorer(axis_name, hist_merge, n_shards,
                                    num_features, ctx, cat_info, mono,
                                    voting_k, merge_chunks)
                  if dist_mode else None)
    # per-leaf histogram cache feature extent: the merged SLICE under
    # reduce-scatter (a D-fold cache memory drop — the subtraction trick is
    # linear, so parent - child on slices is the slice of the subtraction);
    # under voting the cache keeps LOCAL unmerged partials (additive too —
    # the candidate-union merge happens at scoring time).  The pipelined
    # mode pads to a D*chunks multiple, so the slice width comes from the
    # shared merge_slice_width helper, not ceil(F/D).
    if dist_mode and hist_merge != "voting":
        from ..ops.histogram import merge_slice_width

        f_hist = merge_slice_width(num_features, n_shards, hist_merge,
                                   merge_chunks)
    else:
        f_hist = num_features

    # shared mask-composition layer (models.feature_mask, r20): same
    # fold_in(key, node_id)-within-tree-mask draw as the strict grower
    from .feature_mask import node_mask_fn

    node_feature_mask = node_mask_fn(key, ff_bynode, num_features,
                                     feature_mask, bynode_off)

    def node_rand_bins(node_id):
        if not extra_trees:
            return None
        return _rand_bins_for_node(key, node_id, num_features, num_bins,
                                   col_bins)

    def hist_fn(seg_id, num_segments):
        from ..ops.histogram import batched_histogram_op

        op = batched_histogram_op(num_segments, num_bins, row_chunk,
                                  hist_impl, hist_dtype)
        h = op(bins, stats, seg_id)
        if hist_merge == "voting":
            return h       # local partials; the scorer merges candidates
        return histogram_merge(h, axis_name, mode=hist_merge,
                               n_shards=n_shards, wire_dtype=hist_wire,
                               n_chunks=merge_chunks)

    # ---- root -------------------------------------------------------------
    root_hist = hist_fn(jnp.zeros(n, jnp.int32), 1)[0]      # [f_hist, B, 3]
    if dist_mode:
        # global totals from the stats rows (they sum to the histogram
        # totals by construction) — one [3]-element psum instead of
        # reading feature 0's bins from a sliced/unmerged histogram
        root_tot = lax.psum(jnp.sum(stats, axis=0), axis_name)
    else:
        root_tot = jnp.sum(root_hist[0], axis=0)                 # (g, h, c)
    root_out = constrained_leaf_output(
        root_tot[0], root_tot[1], root_tot[2],
        ctx._replace(path_smooth=jnp.float32(0.0)),
        jnp.float32(-jnp.inf), jnp.float32(jnp.inf), jnp.float32(0.0))
    if ic_member is not None:
        root_mask_f = (node_feature_mask(0)
                       * _ic_allowed(jnp.ones((ic_member.shape[0],), bool),
                                     ic_member))
    else:
        root_mask_f = node_feature_mask(0)
    if dist_mode:
        rb0 = node_rand_bins(0)
        root_best = jax.tree.map(lambda x: x[0], score_dist(
            root_hist[None], root_mask_f[None], jnp.ones((1,), bool),
            jnp.full((1,), -jnp.inf, jnp.float32),
            jnp.full((1,), jnp.inf, jnp.float32), root_out[None],
            None if rb0 is None else rb0[None]))
    else:
        root_best = find_best_split(root_hist, ctx, root_mask_f,
                                    jnp.bool_(True), cat_info, mono=mono,
                                    parent_out=root_out,
                                    rand_bins=node_rand_bins(0))
    if fp_axis is not None:
        # feature-parallel: each shard scanned its own column slice; one
        # tiny all_gather + argmax globalizes the winner (the same split
        # exchange the strict grower uses — upstream's
        # FeatureParallelTreeLearner, SURVEY.md §2C)
        root_best = _fp_reduce_best(root_best, fp_axis, num_features)

    def full(val, dtype):
        return jnp.full((capacity,), val, dtype)

    K = _PK
    st = _WaveState(
        nodes=_packed_root_table(capacity, root_out, root_tot, root_best,
                                 cat_info),
        hist_cache=jnp.zeros((grow_leaves, f_hist, num_bins, 3),
                             jnp.float32).at[0].set(root_hist),
        node_slot=full(0, jnp.int32),
        row_leaf=jnp.zeros(n, jnp.int32),
        n_nodes=jnp.int32(1),
        n_leaves=jnp.int32(1),
        cand_catmask=(None if cat_info is None else
                      jnp.zeros((capacity, num_bins), jnp.bool_)
                      .at[0].set(root_best.cat_mask)),
        ic_sets=(None if ic_member is None else
                 jnp.zeros((capacity, ic_member.shape[0]), bool)
                 .at[0].set(True)),
    )

    bins_i32 = bins.astype(jnp.int32)
    iota_w = lax.iota(jnp.int32, w_width)

    if fuse_part:
        # loop-invariant kernel operands prepared ONCE (the in-call
        # pad/convert re-ran per wave, ~2.7 ms each at 11M — r5 trace)
        from ..ops.histogram_pallas import (hist_partition_fused_pallas,
                                            prepare_wave_operands)

        stats_prep_src = stats
        if hist_dtype == "bf16sr":
            # the opt-in SR variant must quantize here too — the fused
            # path bypasses compute_histograms where SR normally applies
            from ..ops.histogram import sr_round_bf16

            stats_prep_src = sr_round_bf16(stats)
        bins_t_prep, stats_t_prep, part_chunk = prepare_wave_operands(
            bins, stats_prep_src, num_bins, w_width)
        n_pad_rows = bins_t_prep.shape[1]

    def cond(st: _WaveState):
        P = st.nodes
        gains = jnp.where(P[:, K.IS_LEAF] > 0.5, P[:, K.CAND_GAIN], neg_inf)
        return (st.n_leaves < grow_leaves) & jnp.any(jnp.isfinite(gains))

    def body(st: _WaveState) -> _WaveState:
        m = capacity
        P = st.nodes
        # 1. rank active leaves by cached candidate gain (desc, stable).
        # Exact mode ranks by PATHMIN instead: priority-first extraction
        # order on a tree IS descending pathmin (see _exact_prune), so
        # pm-ordered waves expand nodes in the same order strict growth
        # would — the overgrown tree then CONTAINS the strict selection
        # (no coverage misses at the replay), instead of greedy-by-gain
        # overgrowth hoping to have covered it.
        gains = jnp.where(P[:, K.IS_LEAF] > 0.5, P[:, K.CAND_GAIN], neg_inf)
        sel_key = (jnp.where(P[:, K.IS_LEAF] > 0.5, P[:, K.PM], neg_inf)
                   if exact else gains)
        order = jnp.argsort(-sel_key, stable=True)        # [M]
        rank = jnp.zeros(m, jnp.int32).at[order].set(
            lax.iota(jnp.int32, m))
        budget = grow_leaves - st.n_leaves
        n_cand = jnp.sum(jnp.isfinite(gains)).astype(jnp.int32)
        # Wave size: every histogram pass costs the same (the one-hot
        # matmul pads the segment lanes to a full MXU tile), so wave count
        # IS tree cost.  Greedy (s = min(budget, W)) closes a 127-leaf tree
        # in 8 passes; spending at most HALF the remaining budget per wave
        # allocates the tail splits near-strict-best-first at ~5 extra
        # passes.  The tail refinement is what preserves strict-growth
        # quality when the leaf budget nearly saturates the data (small-n /
        # large-num_leaves); ``wave_tail`` picks the tradeoff.  "exact"
        # overgrows with the greedy schedule (the post-hoc replay, not the
        # wave order, is what restores strict allocation).
        if wave_tail == "half":
            alloc = jnp.maximum(jnp.int32(1), budget // 2)
        else:  # "greedy" / "exact"
            alloc = budget
        s = jnp.minimum(jnp.minimum(n_cand, alloc),
                        jnp.int32(w_width))               # splits this wave
        sel = jnp.isfinite(gains) & (rank < s)            # [M]

        # 2. partition rows of all splitting leaves at once.  Per-row state
        # comes from ONE one-hot-matmul table lookup (ops.lookup): XLA's
        # native [n]-from-[capacity] gathers cost ~7 ms each at 1M rows on
        # TPU, and this block needs six of them — more than the histogram
        # kernel itself.
        parent_r = order[:w_width]                        # [W] node ids
        active_r = iota_w < s
        prow = P[parent_r]            # [W, NC] — ONE gather for all the
        direct_left = prow[:, K.CAND_LC] <= prow[:, K.CAND_RC]  # per-parent
        nl_r = st.n_nodes + 2 * iota_w                          # scalars
        nr_r = nl_r + 1
        dl_of = _scatter(full(m, jnp.bool_), parent_r, direct_left,
                         active_r)                        # node -> direct side
        p = st.row_leaf
        f32 = jnp.float32
        if fuse_part:
            # 2+3 FUSED: one transposed per-row lookup of the wave's node
            # fields, then the pallas kernel routes rows AND builds the
            # direct-child histograms in a single pass (phase-1 feature
            # select + phase-2 folded dots — _fused_part_kernel).  The
            # one-hot compares against the W SPLITTING PARENTS only, not
            # the full node table (rows in any other leaf produce an
            # all-zero column = sel 0, exactly the wanted semantics) —
            # the full-table compare was ~6 ms/wave at 11M rows.  Table
            # values (sel/feat/thr/rank2/dl) are all <= 256 under the
            # single-f-block gate, so the dot stays bf16-exact.
            zw = jnp.zeros(w_width)
            tbl_w = jnp.stack([active_r.astype(f32),
                               prow[:, K.CAND_FEAT], prow[:, K.CAND_BIN],
                               (2 * iota_w).astype(f32),
                               direct_left.astype(f32), zw, zw, zw],
                              axis=1)                        # [W, 8]
            oh_w = (parent_r[:, None] == p[None, :])         # [W, n]
            pv_t = lax.dot_general(
                tbl_w.astype(f32).T, oh_w.astype(f32),
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=lax.Precision.DEFAULT)             # [8, n]
            if n_pad_rows != n:
                pv_t = jnp.pad(pv_t, ((0, 0), (0, n_pad_rows - n)))
            direct_hist, enc = hist_partition_fused_pallas(
                bins_t_prep, stats_t_prep, pv_t, w_width, num_bins,
                part_chunk,
                hist_dtype=("f32" if hist_dtype in ("f32", "f32x")
                            else "bf16"),
                # multi-f-block routing gathers the wave split features'
                # code rows; ignored on single-block shapes
                wfeat=prow[:, K.CAND_FEAT].astype(jnp.int32),
                num_features=num_features)
            # the kernel's direct_hist is the LOCAL pre-merge [W, F, B, 3]
            # partial, so every merge topology applies after it unchanged
            # (voting keeps it unmerged for the scorer's candidate union)
            if hist_merge != "voting":
                direct_hist = histogram_merge(direct_hist, axis_name,
                                              mode=hist_merge,
                                              n_shards=n_shards,
                                              wire_dtype=hist_wire,
                                              n_chunks=merge_chunks)
            enc = enc[:n]
            row_leaf = jnp.where(enc > 0, st.n_nodes + enc - 1, p)
        else:
            # child ids ride as WAVE-RELATIVE offsets (2*rank <= 2W <=
            # 256), not absolute node ids: absolute ids exceed 256
            # whenever the (overgrown) capacity does, which would force
            # the HIGHEST-precision dot below.  child = n_nodes + offset
            # reconstructs the absolute id after the lookup.
            cols = [sel.astype(f32), P[:, K.CAND_FEAT],
                    P[:, K.CAND_BIN], (2 * rank).astype(f32),
                    dl_of.astype(f32)]
            if cat_info is not None:
                cols.append(P[:, K.CAND_CAT])
            # DEFAULT precision (native-rate bf16 dot) is exact only while
            # every table value is an integer <= 256 (bf16 has an 8-bit
            # significand); feature ids beyond 256 need the full-precision
            # dot or rows partition on corrupted ids.  (The one-hot INDEX
            # side is exact at any capacity — only table VALUES are
            # constrained.)  Under feature sharding the table carries
            # GLOBAL feature ids whose range this shard cannot bound
            # statically — always exact there.
            exact_in_bf16 = (fp_axis is None
                             and max(num_features, 2 * w_width,
                                     num_bins) <= 256)
            pv = lookup_rows(p, jnp.stack(cols, axis=1),
                             precision=(lax.Precision.DEFAULT
                                        if exact_in_bf16
                                        else lax.Precision.HIGHEST))
            psel = pv[:, 0] > 0
            feat_r = pv[:, 1].astype(jnp.int32)
            thr_r = pv[:, 2]
            # per-row split value WITHOUT take_along_axis (same gather
            # problem): masked lane-reduction over the feature axis.
            # Under feature sharding the ids are global: match against
            # this shard's global column range and psum — the owning
            # shard contributes the codes (the [n] bitmap exchange of
            # upstream's feature-parallel split, batched over the wave)
            if fp_axis is not None:
                gids = (lax.axis_index(fp_axis) * num_features
                        + lax.iota(jnp.int32, num_features))
                fmatch = feat_r[:, None] == gids[None, :]
                v = lax.psum(
                    jnp.sum(jnp.where(fmatch, bins_i32, 0), axis=1),
                    fp_axis)
            else:
                fmatch = (feat_r[:, None]
                          == lax.iota(jnp.int32, num_features)[None, :])
                v = jnp.sum(jnp.where(fmatch, bins_i32, 0), axis=1)
            if cat_info is None:
                go_left = v.astype(f32) <= thr_r
            else:
                # category-subset membership: one-hot lookup of the row's
                # mask row, then select bit v — both stay fused
                mrow = lookup_rows(p, st.cand_catmask.astype(f32),
                                   precision=lax.Precision.DEFAULT)
                bit = jnp.sum(
                    jnp.where(v[:, None]
                              == lax.iota(jnp.int32, num_bins)[None, :],
                              mrow, 0.0), axis=1)
                go_left = jnp.where(pv[:, 5] > 0, bit > 0,
                                    v.astype(f32) <= thr_r)
            rank2_r = pv[:, 3].astype(jnp.int32)
            child = st.n_nodes + rank2_r + jnp.where(go_left, 0, 1)
            row_leaf = jnp.where(psel, child, p)

            # 3. one histogram pass over the SMALLER child of every
            # split: a row participates iff its leaf splits this wave AND
            # it went to the direct (smaller) side; its segment is the
            # leaf's wave rank.
            to_direct = psel & (go_left == (pv[:, 4] > 0))
            seg_id = jnp.where(to_direct, rank2_r >> 1, w_width)
            direct_hist = hist_fn(seg_id, w_width)        # [W, F, B, 3]

        # 4. sibling = parent - child (the subtraction trick).  The cache
        # gather and update are ONE-HOT MATMULS, not gather/scatter ops:
        # the r5 trace showed XLA materializing wholesale copies of the
        # [grow_leaves, F, B, 3] cache around the scatter (two ~59 ms
        # async copies per wave at the 11M o2.0 shape, co-critical with
        # the kernel stream), while the matmul form reads the cache once
        # and commits a pure += the while-carry can alias in place.
        # Exactness: one-hot factors are exact at every precision and
        # HIGHEST keeps the f32 cache values bit-exact.
        fb3 = f_hist * num_bins * 3
        cache_flat = st.hist_cache.reshape(grow_leaves, fb3)
        parent_slot = st.node_slot[parent_r]              # [W]
        oh_p = (parent_slot[:, None]
                == lax.iota(jnp.int32, grow_leaves)[None, :])
        parent_hist = lax.dot_general(
            oh_p.astype(f32), cache_flat,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        ).reshape(w_width, f_hist, num_bins, 3)
        other_hist = parent_hist - direct_hist
        dl = direct_left[:, None, None, None]
        left_hist = jnp.where(dl, direct_hist, other_hist)
        right_hist = jnp.where(dl, other_hist, direct_hist)

        left_slot = parent_slot                           # reuse parent slot
        right_slot = st.n_leaves + iota_w
        # mask-and-add: zero the overwritten rows, matmul-add the EXACT
        # new values (a delta formulation would set left = parent +
        # (left - parent), off by ~ulp(parent) in f32 — an error the old
        # scatter never had, compounding through future subtractions)
        slot2 = jnp.concatenate([left_slot, right_slot])  # [2W]
        act2w = jnp.concatenate([active_r, active_r])
        slot2m = jnp.where(act2w, slot2, -1)
        q = (lax.iota(jnp.int32, grow_leaves)[:, None]
             == slot2m[None, :])                          # [L, 2W]
        keep = 1.0 - jnp.any(q, axis=1).astype(f32)       # [L]
        newvals = jnp.concatenate([left_hist, right_hist])
        cache = (cache_flat * keep[:, None] + lax.dot_general(
            q.astype(f32), newvals.reshape(2 * w_width, fb3),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )).reshape(st.hist_cache.shape)
        node_slot = _scatter(st.node_slot, nl_r, left_slot, active_r)
        node_slot = _scatter(node_slot, nr_r, right_slot, active_r)

        # 5. child output bounds (monotone basic method, per splitting leaf).
        pf = prow[:, K.CAND_FEAT].astype(jnp.int32)
        wl_w, wr_w = prow[:, K.CAND_WL], prow[:, K.CAND_WR]       # [W]
        lo_w, hi_w = prow[:, K.BOUND_LO], prow[:, K.BOUND_HI]
        lo_l, hi_l, lo_r, hi_r = _mono_child_bounds(mono, pf, wl_w, wr_w,
                                                    lo_w, hi_w)

        # 6. score candidates for all 2W fresh children from the cache.
        child_nodes = jnp.concatenate([nl_r, nr_r])       # [2W]
        child_hists = jnp.concatenate([left_hist, right_hist])
        child_depth1 = prow[:, K.DEPTH] + 1.0             # [W]
        child_depth = jnp.concatenate([child_depth1, child_depth1])
        depth_ok = (max_depth <= 0) | \
            (child_depth < max_depth.astype(jnp.float32))
        child_masks = jax.vmap(node_feature_mask)(child_nodes)
        if ic_member is not None:
            child_sets = (st.ic_sets[parent_r]
                          & ic_member[:, pf].T)              # [W, NG]
            allowed_w = _ic_allowed(child_sets, ic_member)   # [W, F]
            child_masks = child_masks * jnp.concatenate(
                [allowed_w, allowed_w])
        child_lo = jnp.concatenate([lo_l, lo_r])
        child_hi = jnp.concatenate([hi_l, hi_r])
        child_vals = jnp.concatenate([wl_w, wr_w])        # actual outputs
        if dist_mode:
            child_rand = (jax.vmap(node_rand_bins)(child_nodes)
                          if extra_trees else None)
            bs = score_dist(child_hists, child_masks, depth_ok, child_lo,
                            child_hi, child_vals, child_rand)
        elif extra_trees:
            child_rand = jax.vmap(node_rand_bins)(child_nodes)

            def score(h, m, d, lo_, hi_, po, rb):
                return find_best_split(h, ctx, m, d, cat_info, mono,
                                       lo_, hi_, po, rb)

            bs: BestSplit = jax.vmap(score)(
                child_hists, child_masks, depth_ok, child_lo, child_hi,
                child_vals, child_rand)
        else:

            def score(h, m, d, lo_, hi_, po):
                return find_best_split(h, ctx, m, d, cat_info, mono,
                                       lo_, hi_, po)

            bs = jax.vmap(score)(child_hists, child_masks, depth_ok,
                                 child_lo, child_hi, child_vals)
        if fp_axis is not None:
            # globalize all 2W child winners in one batched all_gather
            bs = jax.vmap(
                lambda b: _fp_reduce_best(b, fp_axis, num_features))(bs)
        active_2 = jnp.concatenate([active_r, active_r])

        # 7. commit with TWO packed row scatters: the W split parents
        # become internal (their rows keep every cached field and gain
        # the split bookkeeping), the 2W fresh children arrive with
        # their scored candidate splits.
        parent_rows = prow.at[:, jnp.array([
            K.SPLIT_FEAT, K.SPLIT_BIN, K.LEFT, K.RIGHT, K.IS_LEAF,
            K.SPLIT_GAIN])].set(jnp.stack([
                prow[:, K.CAND_FEAT], prow[:, K.CAND_BIN],
                nl_r.astype(jnp.float32), nr_r.astype(jnp.float32),
                jnp.zeros(w_width), gains[parent_r]], axis=-1))
        child_rows = jnp.stack([
            jnp.full((2 * w_width,), -1.0),              # SPLIT_FEAT
            jnp.zeros((2 * w_width,)),                   # SPLIT_BIN
            jnp.full((2 * w_width,), -1.0),              # LEFT
            jnp.full((2 * w_width,), -1.0),              # RIGHT
            child_vals,                                  # LEAF_VALUE
            jnp.ones((2 * w_width,)),                    # IS_LEAF
            jnp.concatenate([prow[:, K.CAND_LC],
                             prow[:, K.CAND_RC]]),       # COUNT
            jnp.zeros((2 * w_width,)),                   # SPLIT_GAIN
            child_depth,                                 # DEPTH
            bs.gain,                                     # CAND_GAIN
            bs.feature.astype(jnp.float32),              # CAND_FEAT
            bs.bin.astype(jnp.float32),                  # CAND_BIN
            bs.left_g, bs.left_h, bs.left_c,
            bs.right_g, bs.right_h, bs.right_c,
            bs.left_out,                                 # CAND_WL
            bs.right_out,                                # CAND_WR
            child_lo,                                    # BOUND_LO
            child_hi,                                    # BOUND_HI
            (bs.cat.astype(jnp.float32) if cat_info is not None
             else jnp.zeros((2 * w_width,))),            # CAND_CAT
            jnp.minimum(jnp.concatenate([prow[:, K.PM], prow[:, K.PM]]),
                        bs.gain),                        # PM
        ], axis=-1)                                      # [2W, NC]
        oob = jnp.int32(capacity)
        P2 = P.at[jnp.where(active_r, parent_r, oob)].set(
            parent_rows, mode="drop")
        kid_idx = jnp.where(active_2, child_nodes, oob)
        P2 = P2.at[kid_idx].set(child_rows, mode="drop")

        return st._replace(
            nodes=P2,
            hist_cache=cache,
            node_slot=node_slot,
            row_leaf=row_leaf,
            n_nodes=st.n_nodes + 2 * s,
            n_leaves=st.n_leaves + s,
            cand_catmask=(None if cat_info is None else
                          st.cand_catmask.at[kid_idx].set(
                              bs.cat_mask, mode="drop")),
            ic_sets=(None if ic_member is None else
                     st.ic_sets.at[kid_idx].set(
                         jnp.concatenate([child_sets, child_sets]),
                         mode="drop")),
        )

    st = lax.while_loop(cond, body, st)
    if exact:
        newP, new_cat, row_leaf_new, n_leaves_f = _exact_prune(
            st.nodes, st.cand_catmask, st.row_leaf, num_leaves, cat_info)
        return (_tree_from_packed(newP, n_leaves_f, cat_info, new_cat),
                row_leaf_new)
    tree = _tree_from_packed(st.nodes, st.n_leaves, cat_info,
                             st.cand_catmask)
    return tree, st.row_leaf


# ---------------------------------------------------------------------------
# Streamed (out-of-core) grower helpers — ISSUE 7.
#
# The in-memory growers trace the whole tree as ONE device program (fori/
# while loops over a resident [n, F] matrix).  Under out-of-core training
# the matrix lives host-side in a data.BlockStore and each histogram pass
# is a HOST loop over prefetched blocks, so the growers decompose into
# jitted pieces: per-block partition+histogram kernels (row-axis work,
# called once per block) and per-iteration table updates (node-table-sized
# work, called once per split/wave).  Every piece replicates the
# corresponding in-memory computation VERBATIM on the plain numeric path
# (no categorical/monotone/extra-trees/interaction/bynode/distributed) —
# combined with the BlockStore's chunk-replicating layout rules, streamed
# trees are BIT-IDENTICAL to `grow_tree(..., row_chunk=block_rows)`
# (tests/test_streaming.py).  The host drivers live in data/stream_grow.py.
# ---------------------------------------------------------------------------


def _stream_root_core(root_hist, ctx, feature_mask):
    """Root output + candidate from an accumulated [F, B, 3] histogram
    (the streamed analogue of the growers' shared root block)."""
    root_tot = jnp.sum(root_hist[0], axis=0)                 # (g, h, c)
    root_out = constrained_leaf_output(
        root_tot[0], root_tot[1], root_tot[2],
        ctx._replace(path_smooth=jnp.float32(0.0)),
        jnp.float32(-jnp.inf), jnp.float32(jnp.inf), jnp.float32(0.0))
    root_best = find_best_split(root_hist, ctx, feature_mask,
                                jnp.bool_(True), None, mono=None,
                                parent_out=root_out, rand_bins=None)
    return root_out, root_tot, root_best


@functools.partial(jax.jit, static_argnames=("capacity",))
def stream_strict_init(root_hist, ctx, feature_mask, capacity):
    """Packed root table + the fused strict grower's aux pick row."""
    root_out, root_tot, root_best = _stream_root_core(root_hist, ctx,
                                                      feature_mask)
    P0 = _packed_root_table(capacity, root_out, root_tot, root_best, None)
    f32 = jnp.float32
    zero = jnp.float32(0.0)
    aux0 = jnp.stack([
        zero, root_best.feature.astype(f32), root_best.bin.astype(f32),
        jnp.isfinite(root_best.gain).astype(f32),
        zero, zero, zero, zero]).reshape(1, 8)
    return P0, aux0


@functools.partial(jax.jit, static_argnames=("capacity", "grow_leaves"))
def stream_wave_init(root_hist, ctx, feature_mask, capacity, grow_leaves):
    """Packed root table + per-leaf histogram cache for the wave grower."""
    root_out, root_tot, root_best = _stream_root_core(root_hist, ctx,
                                                      feature_mask)
    P0 = _packed_root_table(capacity, root_out, root_tot, root_best, None)
    cache0 = jnp.zeros((grow_leaves,) + root_hist.shape,
                       jnp.float32).at[0].set(root_hist)
    slot0 = jnp.full((capacity,), 0, jnp.int32)
    return P0, cache0, slot0


@functools.lru_cache(maxsize=None)
def _stream_root_block_fn(num_bins: int, block_rows: int, hist_impl: str,
                          hist_dtype: str):
    """Per-block root histogram partial [1, F, B, 3].

    ``row_chunk`` is pinned to ``block_rows`` so each block takes the
    single-chunk direct path of ``_hist_from_segstats`` — the SAME dot the
    in-memory op's scan body runs per chunk, which is what makes the
    block-wise partial sum bit-identical to the in-memory accumulation.
    """
    from ..ops.histogram import batched_histogram_op

    op = batched_histogram_op(1, num_bins, block_rows, hist_impl,
                              hist_dtype)

    @jax.jit
    def blk(bins_b, stats_full, off):
        nb = bins_b.shape[0]
        stats_b = lax.dynamic_slice(stats_full, (off, jnp.int32(0)),
                                    (nb, 3))
        return op(bins_b, stats_b, jnp.zeros((nb,), jnp.int32))

    return blk


@functools.lru_cache(maxsize=None)
def _stream_strict_block_fn(num_bins: int, block_rows: int, hist_impl: str,
                            hist_dtype: str):
    """One strict split iteration's ROW-AXIS work for one block: partition
    the split leaf's rows and build the {left, right, other} histogram
    partial — a verbatim per-block restatement of the fused strict body's
    XLA prologue (grow_tree's ``body_f``)."""
    from ..ops.histogram import batched_histogram_op

    op = batched_histogram_op(2, num_bins, block_rows, hist_impl,
                              hist_dtype)

    @jax.jit
    def blk(bins_b, stats_full, row_leaf_full, off, aux, n_nodes):
        nb = bins_b.shape[0]
        leaf = aux[0, 0].astype(jnp.int32)
        feat = aux[0, 1].astype(jnp.int32)
        thr = aux[0, 2].astype(jnp.int32)
        active = aux[0, 3] > 0
        nl, nr = n_nodes, n_nodes + 1
        rl_b = lax.dynamic_slice(row_leaf_full, (off,), (nb,))
        stats_b = lax.dynamic_slice(stats_full, (off, jnp.int32(0)),
                                    (nb, 3))
        col = jnp.take(bins_b.astype(jnp.int32), feat, axis=1)
        go_left = col <= thr
        new_rl = jnp.where(rl_b == leaf,
                           jnp.where(go_left, nl, nr), rl_b)
        rl2 = jnp.where(active, new_rl, rl_b)
        seg = jnp.where(rl2 == nl, 0,
                        jnp.where(rl2 == nr, 1, 2)).astype(jnp.int32)
        h = op(bins_b, stats_b, seg)                     # [2, F, B, 3]
        return lax.dynamic_update_slice(row_leaf_full, rl2, (off,)), h

    return blk


@jax.jit
def stream_strict_update(hist2, P, aux, feature_mask, ctx, max_depth,
                         n_nodes, n_leaves):
    """One strict split iteration's TABLE work: the split-iteration
    mega-kernel on the block-accumulated histogram (same call the fused
    in-memory body makes)."""
    from ..ops.histogram_pallas import split_iter_pallas

    f32 = jnp.float32
    zero = jnp.float32(0.0)
    num_features = feature_mask.shape[0]
    fmask_row = feature_mask.astype(f32).reshape(1, num_features)
    md_f = jnp.asarray(max_depth, jnp.int32).astype(f32)
    scal = jnp.stack([
        jnp.asarray(ctx.lambda_l1, f32),
        jnp.asarray(ctx.lambda_l2, f32),
        jnp.asarray(ctx.min_data_in_leaf, f32),
        jnp.asarray(ctx.min_sum_hessian, f32),
        jnp.asarray(ctx.min_gain_to_split, f32),
        jnp.asarray(ctx.max_delta_step, f32),
        jnp.asarray(ctx.path_smooth, f32),
        md_f, n_nodes.astype(f32),
        zero, zero, zero, zero, zero, zero, zero]).reshape(1, 16)
    P2, aux2 = split_iter_pallas(hist2.transpose(0, 1, 3, 2), P, fmask_row,
                                 aux, scal, pk=_PK)
    grew = jnp.where(aux[0, 3] > 0, 1, 0).astype(jnp.int32)
    return P2, aux2, n_nodes + 2 * grew, n_leaves + grew


@functools.lru_cache(maxsize=None)
def _stream_wave_block_fn(w_width: int, num_bins: int, num_features: int,
                          block_rows: int, hist_impl: str, hist_dtype: str):
    """One wave's ROW-AXIS work for one block: table-lookup routing of the
    wave's splitting leaves + the direct-child histogram partial — the
    non-fused wave body's steps 2–3 restated per block."""
    from ..ops.histogram import batched_histogram_op

    op = batched_histogram_op(w_width, num_bins, block_rows, hist_impl,
                              hist_dtype)
    # same gate as the in-memory wave body (fp_axis is None here):
    # DEFAULT-precision (bf16) lookups are exact only while every table
    # value is an integer <= 256
    exact_in_bf16 = max(num_features, 2 * w_width, num_bins) <= 256

    @jax.jit
    def blk(bins_b, stats_full, row_leaf_full, off, tbl, n_nodes):
        f32 = jnp.float32
        nb = bins_b.shape[0]
        p = lax.dynamic_slice(row_leaf_full, (off,), (nb,))
        stats_b = lax.dynamic_slice(stats_full, (off, jnp.int32(0)),
                                    (nb, 3))
        bins_i32 = bins_b.astype(jnp.int32)
        pv = lookup_rows(p, tbl,
                         precision=(lax.Precision.DEFAULT if exact_in_bf16
                                    else lax.Precision.HIGHEST))
        psel = pv[:, 0] > 0
        feat_r = pv[:, 1].astype(jnp.int32)
        thr_r = pv[:, 2]
        fmatch = (feat_r[:, None]
                  == lax.iota(jnp.int32, num_features)[None, :])
        v = jnp.sum(jnp.where(fmatch, bins_i32, 0), axis=1)
        go_left = v.astype(f32) <= thr_r
        rank2_r = pv[:, 3].astype(jnp.int32)
        child = n_nodes + rank2_r + jnp.where(go_left, 0, 1)
        row_leaf = jnp.where(psel, child, p)
        to_direct = psel & (go_left == (pv[:, 4] > 0))
        seg_id = jnp.where(to_direct, rank2_r >> 1, w_width)
        h = op(bins_b, stats_b, seg_id)                  # [W, F, B, 3]
        return lax.dynamic_update_slice(row_leaf_full, row_leaf, (off,)), h

    return blk


@functools.lru_cache(maxsize=None)
def _stream_wave_fns(capacity: int, w_width: int, grow_leaves: int,
                     num_features: int, num_bins: int, wave_tail: str):
    """(plan, update, cond) for the streamed wave grower.

    ``plan`` emits the [capacity, 5] routing table the per-block kernel
    consumes; ``update`` re-derives the wave plan from the SAME packed
    table (deterministic — identical jitted ops on identical inputs) and
    then runs the in-memory wave body's steps 4–7 verbatim; ``cond`` is
    the while-loop predicate, synced to host once per wave by the driver.
    """
    exact = wave_tail == "exact"
    neg_inf = jnp.float32(-jnp.inf)
    m = capacity
    iota_w = lax.iota(jnp.int32, w_width)
    K = _PK

    def _plan(P, n_leaves):
        gains = jnp.where(P[:, K.IS_LEAF] > 0.5, P[:, K.CAND_GAIN], neg_inf)
        sel_key = (jnp.where(P[:, K.IS_LEAF] > 0.5, P[:, K.PM], neg_inf)
                   if exact else gains)
        order = jnp.argsort(-sel_key, stable=True)
        rank = jnp.zeros(m, jnp.int32).at[order].set(lax.iota(jnp.int32, m))
        budget = grow_leaves - n_leaves
        n_cand = jnp.sum(jnp.isfinite(gains)).astype(jnp.int32)
        if wave_tail == "half":
            alloc = jnp.maximum(jnp.int32(1), budget // 2)
        else:  # "greedy" / "exact"
            alloc = budget
        s = jnp.minimum(jnp.minimum(n_cand, alloc), jnp.int32(w_width))
        sel = jnp.isfinite(gains) & (rank < s)
        parent_r = order[:w_width]
        active_r = iota_w < s
        prow = P[parent_r]
        direct_left = prow[:, K.CAND_LC] <= prow[:, K.CAND_RC]
        dl_of = _scatter(jnp.full((m,), True), parent_r, direct_left,
                         active_r)
        return (gains, rank, s, sel, parent_r, active_r, prow, direct_left,
                dl_of)

    @jax.jit
    def plan(P, n_leaves):
        f32 = jnp.float32
        _, rank, _, sel, _, _, _, _, dl_of = _plan(P, n_leaves)
        return jnp.stack([sel.astype(f32), P[:, K.CAND_FEAT],
                          P[:, K.CAND_BIN], (2 * rank).astype(f32),
                          dl_of.astype(f32)], axis=1)       # [M, 5]

    @jax.jit
    def update(P, hist_cache, node_slot, n_nodes, n_leaves, direct_hist,
               feature_mask, ctx, max_depth):
        f32 = jnp.float32
        (gains, _, s, _, parent_r, active_r, prow, direct_left,
         _) = _plan(P, n_leaves)
        nl_r = n_nodes + 2 * iota_w
        nr_r = nl_r + 1

        # step 4: sibling = parent - child from the per-leaf cache
        fb3 = num_features * num_bins * 3
        cache_flat = hist_cache.reshape(grow_leaves, fb3)
        parent_slot = node_slot[parent_r]
        oh_p = (parent_slot[:, None]
                == lax.iota(jnp.int32, grow_leaves)[None, :])
        parent_hist = lax.dot_general(
            oh_p.astype(f32), cache_flat,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        ).reshape(w_width, num_features, num_bins, 3)
        other_hist = parent_hist - direct_hist
        dl = direct_left[:, None, None, None]
        left_hist = jnp.where(dl, direct_hist, other_hist)
        right_hist = jnp.where(dl, other_hist, direct_hist)
        left_slot = parent_slot
        right_slot = n_leaves + iota_w
        slot2 = jnp.concatenate([left_slot, right_slot])
        act2w = jnp.concatenate([active_r, active_r])
        slot2m = jnp.where(act2w, slot2, -1)
        q = (lax.iota(jnp.int32, grow_leaves)[:, None] == slot2m[None, :])
        keep = 1.0 - jnp.any(q, axis=1).astype(f32)
        newvals = jnp.concatenate([left_hist, right_hist])
        cache = (cache_flat * keep[:, None] + lax.dot_general(
            q.astype(f32), newvals.reshape(2 * w_width, fb3),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )).reshape(hist_cache.shape)
        node_slot2 = _scatter(node_slot, nl_r, left_slot, active_r)
        node_slot2 = _scatter(node_slot2, nr_r, right_slot, active_r)

        # step 5: child bounds (plain path: mono is None -> pass-through)
        wl_w, wr_w = prow[:, K.CAND_WL], prow[:, K.CAND_WR]
        lo_w, hi_w = prow[:, K.BOUND_LO], prow[:, K.BOUND_HI]
        lo_l, hi_l, lo_r, hi_r = lo_w, hi_w, lo_w, hi_w

        # step 6: score the 2W fresh children from the cache
        child_nodes = jnp.concatenate([nl_r, nr_r])
        child_hists = jnp.concatenate([left_hist, right_hist])
        child_depth1 = prow[:, K.DEPTH] + 1.0
        child_depth = jnp.concatenate([child_depth1, child_depth1])
        md = jnp.asarray(max_depth, jnp.int32)
        depth_ok = (md <= 0) | (child_depth < md.astype(f32))
        child_masks = jnp.broadcast_to(feature_mask,
                                       (2 * w_width, num_features))
        child_lo = jnp.concatenate([lo_l, lo_r])
        child_hi = jnp.concatenate([hi_l, hi_r])
        child_vals = jnp.concatenate([wl_w, wr_w])

        def score(h, mm, d, lo_, hi_, po):
            return find_best_split(h, ctx, mm, d, None, None, lo_, hi_, po)

        bs = jax.vmap(score)(child_hists, child_masks, depth_ok, child_lo,
                             child_hi, child_vals)
        active_2 = jnp.concatenate([active_r, active_r])

        # step 7: commit (two packed row scatters)
        parent_rows = prow.at[:, jnp.array([
            K.SPLIT_FEAT, K.SPLIT_BIN, K.LEFT, K.RIGHT, K.IS_LEAF,
            K.SPLIT_GAIN])].set(jnp.stack([
                prow[:, K.CAND_FEAT], prow[:, K.CAND_BIN],
                nl_r.astype(f32), nr_r.astype(f32),
                jnp.zeros(w_width), gains[parent_r]], axis=-1))
        child_rows = jnp.stack([
            jnp.full((2 * w_width,), -1.0),              # SPLIT_FEAT
            jnp.zeros((2 * w_width,)),                   # SPLIT_BIN
            jnp.full((2 * w_width,), -1.0),              # LEFT
            jnp.full((2 * w_width,), -1.0),              # RIGHT
            child_vals,                                  # LEAF_VALUE
            jnp.ones((2 * w_width,)),                    # IS_LEAF
            jnp.concatenate([prow[:, K.CAND_LC],
                             prow[:, K.CAND_RC]]),       # COUNT
            jnp.zeros((2 * w_width,)),                   # SPLIT_GAIN
            child_depth,                                 # DEPTH
            bs.gain,                                     # CAND_GAIN
            bs.feature.astype(f32),                      # CAND_FEAT
            bs.bin.astype(f32),                          # CAND_BIN
            bs.left_g, bs.left_h, bs.left_c,
            bs.right_g, bs.right_h, bs.right_c,
            bs.left_out,                                 # CAND_WL
            bs.right_out,                                # CAND_WR
            child_lo,                                    # BOUND_LO
            child_hi,                                    # BOUND_HI
            jnp.zeros((2 * w_width,)),                   # CAND_CAT
            jnp.minimum(jnp.concatenate([prow[:, K.PM], prow[:, K.PM]]),
                        bs.gain),                        # PM
        ], axis=-1)                                      # [2W, NC]
        oob = jnp.int32(capacity)
        P2 = P.at[jnp.where(active_r, parent_r, oob)].set(
            parent_rows, mode="drop")
        kid_idx = jnp.where(active_2, child_nodes, oob)
        P2 = P2.at[kid_idx].set(child_rows, mode="drop")
        return (P2, cache, node_slot2, n_nodes + 2 * s, n_leaves + s)

    @jax.jit
    def cond(P, n_leaves):
        gains = jnp.where(P[:, K.IS_LEAF] > 0.5, P[:, K.CAND_GAIN], neg_inf)
        return (n_leaves < grow_leaves) & jnp.any(jnp.isfinite(gains))

    return plan, update, cond


@functools.partial(jax.jit, static_argnames=("num_leaves",))
def stream_exact_prune(P, row_leaf, num_leaves):
    """Exact-tail replay for the streamed wave grower (plain numeric path:
    no categorical masks)."""
    newP, _, row_leaf_new, n_leaves_f = _exact_prune(P, None, row_leaf,
                                                     num_leaves, None)
    return newP, row_leaf_new, n_leaves_f


def empty_forest(num_trees: int, num_leaves: int) -> Tree:
    """Stacked all-stump forest used as a fixed-capacity accumulator."""
    capacity = 2 * num_leaves - 1

    def full(val, dtype):
        return jnp.full((num_trees, capacity), val, dtype)

    return Tree(
        split_feature=full(-1, jnp.int32),
        split_bin=full(0, jnp.int32),
        left=full(-1, jnp.int32),
        right=full(-1, jnp.int32),
        leaf_value=full(0.0, jnp.float32),
        is_leaf=full(False, jnp.bool_).at[:, 0].set(True),
        count=full(0.0, jnp.float32),
        split_gain=full(0.0, jnp.float32),
        num_leaves=jnp.ones((num_trees,), jnp.int32),
    )


def fit_linear_leaves(tree: Tree, row_leaf: jnp.ndarray, xraw: jnp.ndarray,
                      g: jnp.ndarray, h: jnp.ndarray, bag: jnp.ndarray,
                      linear_lambda, k_feats: int,
                      row_chunk: int = 131072,
                      axis_name: Optional[str] = None
                      ) -> Tuple[Tree, jnp.ndarray]:
    """Fit ridge-regularized linear models in every leaf (upstream
    ``linear_tree``, src/treelearner/linear_tree_learner.cpp re-derived
    tensor-first).

    Upstream solves one small normal-equations system per leaf over the
    leaf's path features, serially with Eigen.  Here all leaves solve at
    once: per-leaf path feature lists come from one structure sweep, the
    per-leaf Gram matrices ``A_l = Z^T H Z`` and moments ``b_l = Z^T g``
    accumulate via a one-hot matmul over row chunks (the histogram trick,
    MXU-friendly), and a single batched ``jnp.linalg.solve`` finishes.
    The Newton objective ``sum_i [g_i f(x_i) + 0.5 h_i f(x_i)^2]`` with
    ridge ``linear_lambda`` gives ``(Z^T H Z + lam I) beta = -Z^T g``.

    Leaves where the solve is singular/non-finite or with fewer than
    ``k_feats + 2`` rows keep their constant Newton value (upstream's
    fallback).  The first ``k_feats`` distinct path features participate
    (upstream uses all; deep paths truncate — documented divergence).
    NaN raw values impute 0 for both fit and predict.

    Returns (tree with linear_feat/linear_coef/leaf_value set,
    per-row prediction delta f(x_i) of THIS tree).
    """
    n, num_features = xraw.shape
    capacity = tree.capacity
    kp1 = k_feats + 1
    lam = jnp.asarray(linear_lambda, jnp.float32)

    # 1. per-leaf path feature lists: one forward sweep (children are
    # created after parents, so parents resolve first).
    flist0 = jnp.full((capacity, k_feats), -1, jnp.int32)
    fcnt0 = jnp.zeros((capacity,), jnp.int32)

    def sweep(i, carry):
        flist, fcnt = carry
        internal = (~tree.is_leaf[i]) & (tree.left[i] >= 0)
        f = tree.split_feature[i]
        present = jnp.any(flist[i] == f)
        can_add = (~present) & (fcnt[i] < k_feats)
        child_list = jnp.where(
            can_add,
            flist[i].at[jnp.clip(fcnt[i], 0, k_feats - 1)].set(f),
            flist[i])
        child_cnt = fcnt[i] + can_add.astype(jnp.int32)

        def put(dst_l, dst_c, child):
            ok = internal & (child >= 0)
            safe = jnp.where(ok, child, capacity)
            return (dst_l.at[safe].set(child_list, mode="drop"),
                    dst_c.at[safe].set(child_cnt, mode="drop"))

        flist, fcnt = put(flist, fcnt, tree.left[i])
        flist, fcnt = put(flist, fcnt, tree.right[i])
        return flist, fcnt

    flist, _ = lax.fori_loop(0, capacity, sweep, (flist0, fcnt0))

    # 2. per-row design Z = [x_pathfeats, 1] with NaN->0 and pad-slot->0.
    feats = flist[row_leaf]                              # [n, K]
    xg = jnp.take_along_axis(xraw, jnp.maximum(feats, 0), axis=1)
    xg = jnp.where((feats >= 0) & jnp.isfinite(xg), xg, 0.0)
    z = jnp.concatenate([xg, jnp.ones((n, 1), jnp.float32)], axis=1)

    # 3. accumulate A = Z^T H Z and b = Z^T g per leaf, chunked one-hot
    # matmuls (histogram formulation).  Rows are padded up to a chunk
    # multiple with zero g/h so every chunk slice is in-bounds and padded
    # rows contribute exactly nothing (code-review r2: a clamped
    # dynamic_slice double-counts the tail).
    gb = g * bag
    hb = h * bag
    n_chunks = max(-(-n // row_chunk), 1)
    n_fit = n_chunks * row_chunk if n > row_chunk else n
    if n_fit != n:
        pad = n_fit - n
        z = jnp.pad(z, ((0, pad), (0, 0)))
        row_leaf_f = jnp.pad(row_leaf, (0, pad))
        gb = jnp.pad(gb, (0, pad))
        hb = jnp.pad(hb, (0, pad))
    else:
        row_leaf_f = row_leaf

    def chunk(ci, acc):
        A, bvec = acc
        s = ci * (row_chunk if n > row_chunk else n)
        c = row_chunk if n > row_chunk else n
        zc = lax.dynamic_slice_in_dim(z, s, c, 0)
        rlc = lax.dynamic_slice_in_dim(row_leaf_f, s, c, 0)
        gc = lax.dynamic_slice_in_dim(gb, s, c, 0)
        hc = lax.dynamic_slice_in_dim(hb, s, c, 0)
        onehot = (rlc[:, None]
                  == lax.iota(jnp.int32, capacity)[None]).astype(jnp.float32)
        zz = zc[:, :, None] * zc[:, None, :]             # [c, K+1, K+1]
        A = A + jnp.einsum("cm,cij,c->mij", onehot, zz, hc)
        bvec = bvec + jnp.einsum("cm,ci,c->mi", onehot, zc, gc)
        return A, bvec

    A0 = jnp.zeros((capacity, kp1, kp1), jnp.float32)
    b0 = jnp.zeros((capacity, kp1), jnp.float32)
    if n <= row_chunk:
        A, bvec = chunk(0, (A0, b0))
    else:
        A, bvec = lax.fori_loop(0, n_chunks, chunk, (A0, b0))
    if axis_name is not None:
        # data-parallel linear leaves: per-shard Gram/moment partials
        # merge with one psum (the same allreduce shape as the histogram
        # merge), then every shard solves the identical batched system —
        # coefficients replicated by construction
        A = lax.psum(A, axis_name)
        bvec = lax.psum(bvec, axis_name)

    eye = jnp.eye(kp1, dtype=jnp.float32)
    beta = jnp.linalg.solve(A + (lam + 1e-6) * eye[None],
                            -bvec[..., None])[..., 0]    # [M, K+1]

    ok = (tree.is_leaf
          & jnp.all(jnp.isfinite(beta), axis=-1)
          & (tree.count >= kp1 + 1))
    coef = jnp.where(ok[:, None], beta[:, :k_feats], 0.0)
    intercept = jnp.where(ok, beta[:, k_feats], tree.leaf_value)
    new_tree = tree._replace(leaf_value=intercept, linear_feat=flist,
                             linear_coef=coef)
    delta = intercept[row_leaf] + jnp.sum(coef[row_leaf] * xg, axis=1)
    return new_tree, delta


# ---------------------------------------------------------------------------
# Checkpoint codec (r13): a Tree as a flat dict of host arrays and back.
# Unlike the JSON model format (utils/serialize.py) this is BIT-EXACT —
# float fields round-trip as raw f32 buffers, never through decimal — so
# resumed training replays the identical forest the interrupted run held.
# Handles single-class [M] and stacked multiclass [K, M] field layouts
# uniformly (np.asarray carries whatever rank the field has).
# ---------------------------------------------------------------------------

_TREE_OPTIONAL_FIELDS = ("is_cat_split", "cat_mask", "linear_feat",
                         "linear_coef")


def tree_to_arrays(tree: Tree) -> dict:
    """Tree -> ``{field: np.ndarray}`` (optional None fields omitted)."""
    import numpy as np

    out = {}
    for name, val in zip(Tree._fields, tree):
        if val is None:
            continue
        out[name] = np.asarray(val)
    return out


def tree_from_arrays(arrays: dict) -> Tree:
    """Inverse of :func:`tree_to_arrays` (device arrays, lazily put)."""
    kw = {}
    for name in Tree._fields:
        if name in arrays:
            kw[name] = jnp.asarray(arrays[name])
        elif name in _TREE_OPTIONAL_FIELDS:
            kw[name] = None
        else:
            raise KeyError(f"tree checkpoint missing field {name!r}")
    return Tree(**kw)

"""Deterministic fault injection shared by training and serving.

Resilience claims — serving's "sheds instead of missing" and "rollback
on a bad artifact" (r12), training's "retry absorbs a transient block
read" and "a torn checkpoint never loses the run" (r13) — are only
testable if the failures themselves are reproducible.  This module is
the one injection mechanism both stacks consult, driven the same way
the injectable clock drives the deadline tests: armed specs fire on
exact hit counts, never on wall-clock or randomness.

Injection sites (:data:`SITES`):

Serving (consulted by ``serving/runtime.py`` and ``serving/bank.py``;
``lightgbm_tpu.serving.faults`` re-exports this module for backward
compatibility):

* ``device_predict`` — raises :class:`FaultError` inside
  ``PredictorRuntime._dispatch`` before the compiled program runs.
* ``artifact_load`` — raises inside ``ModelBank`` artifact ingest.
* ``compile`` — returns a stall duration (seconds) added to the
  measured warm/compile time in ``ModelBank.deploy``.
* ``clock`` — :meth:`FaultInjector.wrap_clock` adds a skew offset to an
  injectable time source.

Training (consulted by ``data/block_store.py`` and ``training/``):

* ``block_read`` — raises inside ``BlockStore.device_blocks`` when a
  host block is fetched, modeling a transient host/file read error;
  absorbed by the bounded retry, surfaced as
  :class:`~lightgbm_tpu.data.block_store.OOCBlockError` on exhaustion.
* ``device_put`` — raises around the host->HBM transfer of a block
  (a PCIe/runtime transfer fault); retried the same way.
* ``checkpoint_write`` — raises inside ``training.checkpoint`` before
  the atomic rename, modeling a failed/partial checkpoint write; the
  tmp+rename protocol guarantees the prior checkpoint stays intact.
* ``gradient`` — consulted once per round by the resumable training
  loop; a firing poisons the round's input predictions with NaN so the
  gradient/hessian finiteness screen (:class:`NonFiniteGradientError`)
  is exercised end to end.

Pipeline (consulted by ``pipeline/daemon.py`` — the r15 refresh loop):

* ``data_arrival`` — raises while the daemon polls its block feed
  (a watch/listing outage); the poll is retried next tick, arrivals
  are never lost.
* ``continue_train`` — raises at a round boundary of the continuation
  training run, modeling a mid-refresh preemption; the daemon resumes
  the SAME generation from its last checkpoint and still converges to
  the same flip.
* ``artifact_push`` — fires during the versioned-artifact publish,
  modeling a torn/corrupted push; the written artifact is corrupted so
  ModelBank ingest/canary rejects it and the prior version keeps
  serving, with a clean re-push next tick.
* ``flip`` — raises immediately after a successful atomic flip,
  modeling a post-flip health alarm; the daemon rolls the bank back to
  the prior version and re-anchors continuation on it.

Sweep (consulted by ``sweep/service.py`` and ``pipeline/daemon.py`` —
the r17 sweep-as-a-service loop):

* ``sweep_segment`` — raises between fused-CV hyper-batch segments (or
  before a host-engine config), modeling a preemption at an arbitrary
  config/round of the grid; the service returns ``preempted`` and a
  rerun resumes from the per-hyper-batch checkpoint bit-identically.
* ``sweep_record`` — raises after a hyper-batch finishes, BEFORE its
  results are committed to the ledger; the completed carry is already
  checkpointed, so the resume replays only the final segment and lands
  the identical ledger rows.
* ``sweep_promote`` — raises between a completed sweep and the winning
  config's promotion training, modeling a crash in the tune->serve
  handoff; the daemon retries next tick, the finished ledger makes the
  re-run a fast no-op, and the same winner promotes.

A ``FaultInjector`` with no armed specs is a cheap no-op, so the hooks
stay wired in production configurations.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

SERVING_SITES = ("device_predict", "artifact_load", "compile", "clock")
TRAINING_SITES = ("block_read", "device_put", "checkpoint_write", "gradient")
PIPELINE_SITES = ("data_arrival", "continue_train", "artifact_push", "flip")
SWEEP_SITES = ("sweep_segment", "sweep_record", "sweep_promote")
SITES = SERVING_SITES + TRAINING_SITES + PIPELINE_SITES + SWEEP_SITES


class FaultError(RuntimeError):
    """A deterministically injected fault."""


class StreamScopeError(ValueError):
    """A parameter the streamed (out-of-core) trainer does not cover.

    The per-block grower kernels replicate the fused strict/wave bodies
    without the categorical / monotone / extra-trees / interaction /
    bynode machinery — training anyway would be subtly DIFFERENT, not
    slower, so the fence is a hard typed error.  ``key`` names the exact
    offending parameter so callers (and tests) can assert on the field
    rather than parse prose.
    """

    def __init__(self, message: str, key: str = ""):
        super().__init__(message)
        self.key = key


class ScreenScopeError(ValueError):
    """A parameter gain-informed feature screening does not cover (r20).

    Screened rounds grow trees in COMPACTED feature space and remap the
    winners; configs whose static per-column state (categorical sets,
    monotone signs, per-column bin counts, interaction groups, linear
    leaf designs, the feature-sharded learner) is indexed by GLOBAL
    column would train subtly differently, not merely slower — so the
    fence is a hard typed error.  ``key`` names the exact offending
    parameter, mirroring :class:`StreamScopeError`.
    """

    def __init__(self, message: str, key: str = ""):
        super().__init__(message)
        self.key = key


class NonFiniteGradientError(RuntimeError):
    """Diagnostic raised by the training finiteness screen.

    Non-finite raw predictions make every downstream gradient/hessian
    non-finite, and a tree grown from NaN stats silently poisons the
    whole forest — the screen raises THIS before the round runs instead
    of growing a garbage tree.  Carries the failing round index so the
    operator knows which checkpoint still precedes the corruption.
    """

    def __init__(self, message: str, round_index: int = -1):
        super().__init__(message)
        self.round_index = int(round_index)


@dataclass
class FaultSpec:
    """One armed failure: fire at ``site`` after ``after`` clean hits.

    ``times`` bounds how many consecutive hits fire (-1 = every hit
    forever).  ``stall_s`` is only meaningful at the ``compile`` site
    (returned, not raised); ``skew_s`` only at the ``clock`` site
    (applied by :meth:`FaultInjector.wrap_clock` while the spec has
    firings left).
    """

    site: str
    after: int = 0
    times: int = 1
    message: str = "injected fault"
    stall_s: float = 0.0
    skew_s: float = 0.0
    _fired: int = field(default=0, repr=False)

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site {self.site!r} (known: {SITES})")

    def _active(self, site_hits: int) -> bool:
        if site_hits <= self.after:
            return False
        return self.times < 0 or self._fired < self.times


class FaultInjector:
    """Holds armed :class:`FaultSpec`s and counts every site hit.

    ``check(site)`` is the one call the stacks make: it counts the hit,
    fires the first matching armed spec, and either raises
    :class:`FaultError` (error sites) or returns a stall duration in
    seconds (the ``compile`` site; 0.0 when nothing fires).
    """

    def __init__(self, specs=()):
        self._specs: List[FaultSpec] = []
        self.hits: Dict[str, int] = {s: 0 for s in SITES}
        self.fired: Dict[str, int] = {s: 0 for s in SITES}
        for s in specs:
            self.arm(s)

    def arm(self, spec, **kw) -> FaultSpec:
        """Arm a spec (or build one from ``site=...`` keywords)."""
        if not isinstance(spec, FaultSpec):
            spec = FaultSpec(spec, **kw)
        self._specs.append(spec)
        return spec

    def disarm_all(self) -> None:
        self._specs.clear()

    def check(self, site: str) -> float:
        """Count one hit at ``site``; fire the first matching armed spec.

        Raises :class:`FaultError` for error sites; returns the stall
        seconds for the ``compile`` site (0.0 when no spec fires).
        """
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r} (known: {SITES})")
        self.hits[site] += 1
        for spec in self._specs:
            if spec.site != site or not spec._active(self.hits[site]):
                continue
            spec._fired += 1
            self.fired[site] += 1
            if site == "compile":
                return float(spec.stall_s)
            raise FaultError(f"{site}: {spec.message}")
        return 0.0

    def wrap_clock(self, clock):
        """A clock that adds the skew of every armed clock spec with
        firings left.  Each read counts a ``clock`` site hit, so
        ``after``/``times`` select exactly which reads see the skew."""

        def skewed() -> float:
            self.hits["clock"] += 1
            t = clock()
            for spec in self._specs:
                if spec.site == "clock" and spec._active(
                        self.hits["clock"]):
                    spec._fired += 1
                    self.fired["clock"] += 1
                    t += float(spec.skew_s)
            return t

        return skewed

    def snapshot(self) -> dict:
        return {
            "armed": len(self._specs),
            "hits": dict(self.hits),
            "fired": dict(self.fired),
        }


def null_injector() -> Optional[FaultInjector]:
    """Explicit 'no faults' for call sites that want a real object."""
    return None

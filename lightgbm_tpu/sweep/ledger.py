"""Crash-safe, resumable sweep ledger (moved from utils/sweep.py, r17).

The reference checkpoints its 108x9 ``paramGrid`` data.frame after every
config with ``save(paramGrid, file=...)`` "if lgb crashes"
(r/gridsearchCV.R:118) and resumes with ``load(...)``.  This module is
the TPU side of that contract — with the durability the reference never
had:

* **atomic saves** — every write goes to a ``.tmp-`` sibling in the
  SAME directory, is fsynced, then ``os.replace``d into place (the r13
  checkpoint protocol), so a kill mid-save can never corrupt the ledger
  a resume depends on;
* **sentinel-proof leaderboard** — rows still carrying the -1 "crashed/
  unfinished" sentinel are excluded from ranking, so an interrupted
  config can never be handed to auto-promotion as the "winner";
* **codec by suffix** — ``.RData`` paths read/write R's actual
  serialization (byte-compatible with the reference's ``save()`` /
  ``load()`` checkpoint, utils.rdata), anything else is JSON.

Ledger writes are byte-deterministic for a given row state (the JSON
``saved_at`` stamp comes from the injectable ``clock``; the RData gzip
wrapper pins mtime=0), which is what lets the kill-anywhere chaos tests
compare interrupted-and-resumed ledgers to uninterrupted ones as files,
not just as parsed rows.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

RESULT_COLUMNS = ("iteration", "score")
SENTINEL = -1.0  # paramGrid.RData's marker for crashed/unfinished rows


def expand_grid(**axes: Sequence[Any]) -> List[Dict[str, Any]]:
    """R ``expand.grid`` equivalent: cartesian product, first axis fastest
    (R's column-major convention, so row order matches the reference grid)."""
    names = list(axes.keys())
    values = [list(axes[n]) for n in names]
    rows = []
    for combo in itertools.product(*reversed(values)):
        row = dict(zip(reversed(names), combo))
        rows.append({n: row[n] for n in names})
    return rows


def grid_digest(grid: List[Dict[str, Any]], **extra: Any) -> str:
    """Stable content hash of a config grid (+ run statics like nfold /
    seed / rounds) — the compatibility key hyper-batch checkpoints carry
    so a resume against a DIFFERENT sweep definition restarts cleanly
    instead of restoring foreign state."""
    doc = {"grid": [{k: row[k] for k in sorted(row)} for row in grid]}
    doc.update({k: extra[k] for k in sorted(extra)})
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True, default=float).encode()
    ).hexdigest()


class SweepLedger:
    """Resumable grid ledger: one record per config with status + results.

    ``clock`` stamps the JSON codec's ``saved_at`` field; the default is
    a bare wall-clock reference, injectable for deterministic runs.
    """

    def __init__(self, grid: List[Dict[str, Any]], path: Optional[str] = None,
                 *, clock: Callable[[], float] = time.time):
        self.path = path
        self.clock = clock
        self.rows: List[Dict[str, Any]] = []
        for cfg in grid:
            row = {c: SENTINEL for c in RESULT_COLUMNS}
            row.update(cfg)
            self.rows.append(row)
        if path and os.path.exists(path):
            self._merge_existing(path)

    @staticmethod
    def _is_rdata(path: str) -> bool:
        return path.lower().endswith(".rdata")

    def _merge_existing(self, path: str) -> None:
        if self._is_rdata(path):
            from ..utils.rdata import read_rdata
            dfs = read_rdata(path)
            df = dfs.get("paramGrid") or next(iter(dfs.values()), {})
            cols = list(df.keys())
            nrow = len(df[cols[0]]) if cols else 0
            saved_rows = [{c: df[c][i] for c in cols} for i in range(nrow)]
        else:
            with open(path) as f:
                saved = json.load(f)
            saved_rows = saved.get("rows", [])
        for i, srow in enumerate(saved_rows):
            if i >= len(self.rows):
                break
            mine = {k: v for k, v in self.rows[i].items()
                    if k not in RESULT_COLUMNS}
            theirs = {k: v for k, v in srow.items() if k not in RESULT_COLUMNS}
            if self._cfg_equal(mine, theirs) and \
                    srow.get("iteration", SENTINEL) != SENTINEL:
                merged = dict(self.rows[i])
                merged.update({c: srow[c] for c in RESULT_COLUMNS
                               if c in srow})
                self.rows[i] = merged

    @staticmethod
    def _cfg_equal(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
        """Config equality across serializations (R numerics come back as
        floats: num_leaves 31 vs 31.0 must still match)."""
        if set(a) != set(b):
            return False
        for k in a:
            x, y = a[k], b[k]
            if isinstance(x, (int, float)) and isinstance(y, (int, float)):
                if abs(float(x) - float(y)) > 1e-9 * max(1.0, abs(float(x))):
                    return False
            elif x != y:
                return False
        return True

    def done(self, i: int) -> bool:
        return self.rows[i]["iteration"] != SENTINEL

    def pending(self) -> List[int]:
        """Indices still carrying the sentinel (the resume work list)."""
        return [i for i in range(len(self.rows)) if not self.done(i)]

    def record(self, i: int, best_iter: int, best_score: float) -> None:
        self.rows[i]["iteration"] = int(best_iter)
        self.rows[i]["score"] = float(best_score)
        self.save()

    def save(self) -> None:
        """Atomic, durable write: tmp sibling -> fsync -> ``os.replace``
        (the training/checkpoint.py protocol) — a kill at any byte of
        the save leaves the previous ledger intact."""
        if not self.path:
            return
        tmp = os.path.join(
            os.path.dirname(self.path) or ".",
            f".tmp-{os.path.basename(self.path)}")
        try:
            if self._is_rdata(self.path):
                from ..utils.rdata import write_rdata
                cols = list(self.rows[0].keys()) if self.rows else []
                write_rdata(tmp, "paramGrid",
                            {c: [r[c] for r in self.rows] for c in cols})
                fd = os.open(tmp, os.O_RDONLY)
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
            else:
                with open(tmp, "w") as f:
                    json.dump({"rows": self.rows,
                               "saved_at": self.clock()}, f, indent=1)
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, self.path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def leaderboard(self) -> List[Dict[str, Any]]:
        """COMPLETED rows ordered by score descending (scores are
        sign-flipped so higher is better — the R convention;
        r/gridsearchCV.R:122).  Rows still carrying a sentinel in EITHER
        result column are excluded: a crashed/unfinished config must
        never rank as the winning configuration handed to
        auto-promotion."""
        return sorted((r for r in self.rows
                       if r["iteration"] != SENTINEL
                       and r["score"] != SENTINEL),
                      key=lambda r: -r["score"])

    def to_numpy(self):
        cols = list(self.rows[0].keys())
        return cols, np.array([[r[c] for c in cols] for r in self.rows],
                              dtype=np.float64)

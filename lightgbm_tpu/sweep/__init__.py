"""Sweep-as-a-service: distributed, preemptible grid search (ISSUE r17).

The source paper's central artifact is a 108-config GridSearchCV sweep
with a per-config crash checkpoint (r/gridsearchCV.R:104-119,
paramGrid.RData).  Earlier rounds built the pieces — the r7 fused-CV
hyper-batch runs a bucket of configs x folds as ONE XLA program, the
r13 checkpoint protocol makes any round state durable, the r15 refresh
daemon owns canary -> atomic flip — and this package is the service
layer that composes them:

* :class:`~.scheduler.SweepScheduler` shards the config grid over a
  configs x devices 2-D mesh: configs pack into fused-CV hyper-batches
  (bucketed by compile-time statics), hyper-batches spread over device
  groups;
* :class:`~.service.SweepService` executes the plan segment by segment,
  checkpointing each hyper-batch's full carry through the r13 protocol
  so a SIGTERM or injected fault at ANY config/round resumes
  bit-identically (kill-anywhere sweep parity, JSON and RData ledger
  codecs both);
* :class:`~.ledger.SweepLedger` is the crash-safe resumable result
  ledger (atomic fsync+rename saves; unfinished sentinels can never
  rank on the leaderboard);
* the r15 :class:`~lightgbm_tpu.pipeline.daemon.RefreshDaemon` drives
  the whole thing as ``task=sweep``: a completed sweep auto-promotes
  its winning config through canary -> atomic flip, closing the loop
  from "hyperparameters drifted stale" to "re-tuned model serving".

``lightgbm_tpu.utils.sweep`` remains as a thin compat surface over this
package (``expand_grid`` / ``SweepLedger`` / ``run_grid_search``).
"""

from .ledger import RESULT_COLUMNS, SENTINEL, SweepLedger, expand_grid
from .scheduler import SweepPlan, SweepScheduler, SweepUnit, fused_bucket_key
from .service import SweepResult, SweepService, run_grid_search

__all__ = [
    "RESULT_COLUMNS", "SENTINEL", "SweepLedger", "expand_grid",
    "SweepPlan", "SweepScheduler", "SweepUnit", "fused_bucket_key",
    "SweepResult", "SweepService", "run_grid_search",
]

"""SweepService: distributed, preemptible grid-search execution (r17).

The execution half of sweep-as-a-service: take a config grid, a
Dataset, and a mesh shape; run the :class:`~.scheduler.SweepScheduler`
plan hyper-batch by hyper-batch on the fused-CV engine (or config by
config on the host ``engine.cv`` loop); checkpoint every hyper-batch's
full carry through the r13 protocol between segments; commit results
into the crash-safe :class:`~.ledger.SweepLedger`.

**Kill-anywhere parity** is the load-bearing contract: a SIGTERM (the
reentrant r13 :class:`PreemptionGuard`, polled at segment and unit
boundaries) or an injected fault at ANY config/round —
``sweep_segment`` between device dispatches, ``sweep_record`` in the
window after a hyper-batch finishes but before its ledger commit,
``checkpoint_write`` inside the checkpoint itself — leaves durable
state (per-unit carry checkpoints + the atomically-saved ledger) from
which a rerun converges to a ledger bit-identical to the uninterrupted
run, on both the JSON and RData codecs.  Three properties make that
true: per-round RNG is keyed by round index (replay from any segment
boundary reproduces the stream), the carry round-trips through numpy
exactly (f32/i32/bool fields), and unit identity is content-derived
(the same remaining work re-plans to the same checkpoint directory).

``run_grid_search`` at the bottom is the r2-era entry point, preserved
verbatim as a thin wrapper (``utils.sweep`` re-exports it).
"""

from __future__ import annotations

import os
import shutil
import time
import warnings
from typing import (Any, Callable, Dict, List, NamedTuple, Optional,
                    Sequence, Tuple)

import numpy as np

from ..faults import FaultError, FaultInjector
from ..training.checkpoint import load_latest, save_state_checkpoint
from ..training.loop import PreemptionGuard
from .ledger import RESULT_COLUMNS, SweepLedger, grid_digest
from .scheduler import SweepPlan, SweepScheduler, SweepUnit

SWEEP_ENGINES = ("auto", "fused", "host")


class SweepResult(NamedTuple):
    """Outcome of one :meth:`SweepService.run` invocation."""

    ledger: SweepLedger
    completed: bool            # every grid row recorded
    preempted: bool            # SIGTERM drain or injected fault mid-sweep
    error: Optional[str]       # the fault message when preempted by one
    engine: str                # "fused" or "host", post-eligibility
    units_total: int           # hyper-batches planned this run
    units_done: int            # hyper-batches committed this run
    resumed_units: int         # units restored from a carry checkpoint
    checkpoint_failures: int   # carry writes lost to injected/real faults
    stats: Dict[str, Any]      # bucket timings (the r2 sweep_stats shape)


class SweepService:
    """Execute a config grid as a scheduled, checkpointed sweep.

    Parameters
    ----------
    grid : list of config dicts (``expand_grid`` rows)
    train_set : Dataset
    base_params : dict, optional
        Params shared by every config (each grid row overlays it).
    num_boost_round / nfold / early_stopping_rounds / seed
        The ``engine.cv`` contract per config.  ``seed`` also fixes the
        fold assignment, so resumes re-derive identical folds.
    engine : "auto" | "fused" | "host"
        "fused"/"auto" run eligible grids as hyper-batched device
        programs and fall back to the host loop otherwise; "host"
        forces the serial per-config loop (the reference's shape).
    ledger_path : str, optional
        Resumable ledger location (codec by suffix: .RData or JSON).
    checkpoint_dir : str, optional
        Root for per-hyper-batch carry checkpoints (``unit_<uid>/``
        subdirectories, r13 file protocol).  Without it the sweep is
        still per-unit resumable through the ledger, but an interrupted
        unit restarts from round 0.
    n_devices / group_size / hyper_batch
        The configs x devices mesh shape handed to the scheduler.
    injector : FaultInjector, optional
        Consults ``sweep_segment`` / ``sweep_record`` here (and
        ``checkpoint_write`` inside the checkpoint writer).
    clock : callable, optional
        Injectable time source for the stats (and the ledger's
        ``saved_at``) — deterministic runs inject a sim clock.
    cv_fn : callable, optional
        Host-engine cv override (tests); forces the host path.
    """

    def __init__(self, grid: List[Dict[str, Any]], train_set, *,
                 base_params: Optional[Dict[str, Any]] = None,
                 num_boost_round: int = 1000,
                 nfold: int = 5,
                 early_stopping_rounds: int = 5,
                 seed: int = 0,
                 engine: str = "auto",
                 ledger_path: Optional[str] = None,
                 checkpoint_dir: Optional[str] = None,
                 keep_last: int = 2,
                 n_devices: int = 1,
                 group_size: int = 1,
                 hyper_batch: int = 36,
                 injector: Optional[FaultInjector] = None,
                 clock: Callable[[], float] = time.monotonic,
                 verbose: bool = False,
                 cv_fn: Optional[Callable] = None):
        if engine not in SWEEP_ENGINES:
            raise ValueError(f"engine must be one of {SWEEP_ENGINES}, "
                             f"got {engine!r}")
        if nfold < 2:
            raise ValueError(f"nfold must be >= 2, got {nfold}")
        if not grid:
            raise ValueError("empty config grid")
        self.grid = [dict(cfg) for cfg in grid]
        self.train_set = train_set
        self.base_params = dict(base_params or {})
        self.num_boost_round = int(num_boost_round)
        self.nfold = int(nfold)
        self.early_stopping_rounds = int(early_stopping_rounds)
        self.seed = int(seed)
        self.engine = engine
        self.checkpoint_dir = checkpoint_dir
        self.keep_last = int(keep_last)
        self.n_devices = int(n_devices)
        self.group_size = int(group_size)
        self.injector = injector
        self.clock = clock
        self.verbose = verbose
        self.cv_fn = cv_fn
        self.scheduler = SweepScheduler(hyper_batch=hyper_batch)
        self.ledger = SweepLedger(self.grid, ledger_path, clock=clock)
        self._digest = grid_digest(
            self.grid, nfold=self.nfold, seed=self.seed,
            num_boost_round=self.num_boost_round,
            early_stopping_rounds=self.early_stopping_rounds)

    # -- driving -------------------------------------------------------------
    def run(self, guard: Optional[PreemptionGuard] = None) -> SweepResult:
        """Execute (or resume) the sweep under a preemption guard.

        ``guard`` shares an outer reentrant guard (the daemon's); by
        default the service scopes its own.  Returns instead of raising
        on preemption/faults — rerunning converges bit-identically.
        """
        g = guard if guard is not None else PreemptionGuard()
        with g:
            return self._run(g)

    def _fold_masks(self) -> np.ndarray:
        n = self.train_set.num_data()
        rng = np.random.default_rng(self.seed)
        assign = rng.permutation(n) % self.nfold
        return np.stack([assign != k for k in range(self.nfold)])

    def _parsed(self) -> list:
        from ..config import parse_params

        parsed = []
        for cfg in self.grid:
            params = dict(self.base_params)
            params.update(cfg)
            parsed.append(parse_params(params, warn_unknown=False))
        return parsed

    def _run(self, g: PreemptionGuard) -> SweepResult:
        from ..models.fused import fused_cv_eligible

        self.train_set.construct()
        parsed = self._parsed()
        use_fused = (self.engine in ("auto", "fused")
                     and self.cv_fn is None
                     and all(fused_cv_eligible(p, None, None,
                                               self.train_set)
                             for p in parsed))
        if not use_fused and self.engine == "fused" and self.cv_fn is None \
                and self.verbose:
            print("fused engine ineligible for this grid; "
                  "falling back to host loop")
        if use_fused:
            return self._run_fused(g, parsed)
        return self._run_host(g)

    def _result(self, *, preempted: bool, error: Optional[str], engine: str,
                units_total: int, units_done: int, resumed: int,
                ckpt_failures: int, stats: Dict[str, Any]) -> SweepResult:
        completed = not self.ledger.pending()
        if completed and self.checkpoint_dir:
            # every unit is committed; the carry checkpoints are spent
            shutil.rmtree(self.checkpoint_dir, ignore_errors=True)
        return SweepResult(
            ledger=self.ledger, completed=completed, preempted=preempted,
            error=error, engine=engine, units_total=units_total,
            units_done=units_done, resumed_units=resumed,
            checkpoint_failures=ckpt_failures, stats=stats)

    # -- host engine ---------------------------------------------------------
    def _run_host(self, g: PreemptionGuard) -> SweepResult:
        from ..engine import cv as _cv

        cv_fn = self.cv_fn or _cv
        stats: Dict[str, Any] = {"buckets": [], "compile_s": 0.0,
                                 "exec_s": 0.0, "rounds_total": 0}
        done_now = 0
        pending = self.ledger.pending()
        for i, cfg in enumerate(self.grid):
            if self.ledger.done(i):
                if self.verbose:
                    print(f"[{i + 1}/{len(self.grid)}] already done, "
                          "skipping")
                continue
            try:
                if self.injector is not None:
                    self.injector.check("sweep_segment")
            except FaultError as e:
                return self._result(
                    preempted=True, error=str(e), engine="host",
                    units_total=len(pending), units_done=done_now,
                    resumed=0, ckpt_failures=0, stats=stats)
            if self.verbose:
                print(f"[{i + 1}/{len(self.grid)}]")
            params = dict(self.base_params)
            params.update(cfg)
            fit = cv_fn(params, self.train_set,
                        num_boost_round=self.num_boost_round,
                        nfold=self.nfold,
                        early_stopping_rounds=self.early_stopping_rounds,
                        seed=self.seed, stratified=False)
            try:
                if self.injector is not None:
                    self.injector.check("sweep_record")
            except FaultError as e:
                return self._result(
                    preempted=True, error=str(e), engine="host",
                    units_total=len(pending), units_done=done_now,
                    resumed=0, ckpt_failures=0, stats=stats)
            self.ledger.record(i, fit.best_iter, fit.best_score)
            done_now += 1
            if g.requested:
                return self._result(
                    preempted=True, error="SIGTERM drain mid-sweep",
                    engine="host", units_total=len(pending),
                    units_done=done_now, resumed=0, ckpt_failures=0,
                    stats=stats)
        return self._result(
            preempted=False, error=None, engine="host",
            units_total=len(pending), units_done=done_now, resumed=0,
            ckpt_failures=0, stats=stats)

    # -- fused engine --------------------------------------------------------
    def _unit_dir(self, unit: SweepUnit) -> Optional[str]:
        if not self.checkpoint_dir:
            return None
        return os.path.join(self.checkpoint_dir, f"unit_{unit.uid}")

    def _save_unit_ckpt(self, prog, carry, unit_dir: str,
                        unit: SweepUnit) -> int:
        arrays = prog.carry_arrays(carry)
        meta = {"iter": int(arrays["r"]), "kind": "sweep_unit",
                "uid": unit.uid, "grid_digest": self._digest,
                "configs": [int(i) for i in unit.config_indices]}
        try:
            save_state_checkpoint(arrays, meta, unit_dir,
                                  injector=self.injector,
                                  keep_last=self.keep_last)
        except (FaultError, OSError) as e:
            # same contract as the training loop: the tmp+rename
            # protocol kept the prior checkpoint; losing one write
            # costs redo rounds, never the sweep
            warnings.warn(f"sweep checkpoint write failed (prior "
                          f"checkpoint kept): {e}")
            return 1
        return 0

    def _restore_unit(self, prog, unit: SweepUnit, unit_dir: str):
        path, found = load_latest(unit_dir)
        for rej_path, why in found["rejected"]:
            warnings.warn(f"skipping corrupt sweep checkpoint "
                          f"{rej_path}: {why}")
        if path is None:
            return None
        meta = found["meta"]
        if meta.get("kind") != "sweep_unit" or meta.get("uid") != unit.uid \
                or meta.get("grid_digest") != self._digest:
            warnings.warn(
                f"discarding sweep checkpoint {path}: it belongs to a "
                "different sweep definition (grid/nfold/seed/rounds "
                "drift); restarting this hyper-batch from round 0")
            return None
        return prog.restore_carry(found["arrays"])

    def _run_fused(self, g: PreemptionGuard, parsed: list) -> SweepResult:
        import jax

        from ..metrics import get_metric
        from ..models.fused import FusedCVProgram

        fold_masks = self._fold_masks()
        plan = self.scheduler.plan(
            parsed, self.train_set, done=[i for i in range(len(self.grid))
                                          if self.ledger.done(i)],
            n_devices=self.n_devices, group_size=self.group_size)
        stats: Dict[str, Any] = {"buckets": [], "compile_s": 0.0,
                                 "exec_s": 0.0, "rounds_total": 0,
                                 "plan": {"units": len(plan.units),
                                          "n_groups": plan.n_groups,
                                          "group_size": plan.group_size}}
        units_done = 0
        resumed_units = 0
        ckpt_failures = 0

        def bail(err: str) -> SweepResult:
            return self._result(
                preempted=True, error=err, engine="fused",
                units_total=len(plan.units), units_done=units_done,
                resumed=resumed_units, ckpt_failures=ckpt_failures,
                stats=stats)

        for unit in plan.units:
            key = unit.bucket_key
            if self.verbose:
                print(f"fused bucket num_leaves={key[0]} "
                      f"bagging_freq={key[1]}: "
                      f"{len(unit.config_indices)} configs x "
                      f"{self.nfold} folds (group {unit.group})")
            t0 = self.clock()
            prog = FusedCVProgram(
                self.train_set, [parsed[i] for i in unit.config_indices],
                fold_masks, self.num_boost_round,
                self.early_stopping_rounds, self.seed)
            unit_dir = self._unit_dir(unit)
            carry = None
            if unit_dir:
                carry = self._restore_unit(prog, unit, unit_dir)
                if carry is not None:
                    resumed_units += 1
            if carry is None:
                carry = prog.init()
            # compile isolation (the run_fused_cv_batch trick): a
            # seg_end=r dispatch compiles the program but runs no rounds
            carry = prog.step(carry, int(carry.r))
            jax.block_until_ready(carry.r)
            compile_s = self.clock() - t0
            t_exec = self.clock()

            seg = prog.segment_rounds
            while not prog.done(carry):
                try:
                    if self.injector is not None:
                        self.injector.check("sweep_segment")
                except FaultError as e:
                    return bail(str(e))
                seg_end = min((int(carry.r) // seg + 1) * seg,
                              self.num_boost_round)
                carry = prog.step(carry, seg_end)
                if unit_dir:
                    ckpt_failures += self._save_unit_ckpt(
                        prog, carry, unit_dir, unit)
                if g.requested:
                    return bail("SIGTERM drain mid-sweep")

            try:
                if self.injector is not None:
                    self.injector.check("sweep_record")
            except FaultError as e:
                return bail(str(e))
            res = prog.finalize(carry)
            best_iters = np.asarray(res.best_iter)
            best_raw = np.asarray(res.best_score)
            hib = get_metric(prog.metric_name).higher_better
            for j, i in enumerate(unit.config_indices):
                raw = float(best_raw[j])
                self.ledger.rows[i]["iteration"] = int(best_iters[j])
                self.ledger.rows[i]["score"] = raw if hib else -raw
            self.ledger.save()
            if unit_dir:
                shutil.rmtree(unit_dir, ignore_errors=True)
            units_done += 1

            el = self.clock() - t0
            exec_s = self.clock() - t_exec
            rounds = int(res.rounds_run)
            stats["buckets"].append(
                {"num_leaves": key[0],
                 "configs": len(unit.config_indices),
                 "group": unit.group, "uid": unit.uid,
                 "s": round(el, 2), "rounds": rounds,
                 "compile_s": round(compile_s, 2),
                 "exec_s": round(exec_s, 2)})
            stats["compile_s"] += compile_s
            stats["exec_s"] += exec_s
            stats["rounds_total"] += rounds
            if self.verbose:
                print(f"  bucket done in {el:.1f}s ({rounds} rounds "
                      f"run, compile {compile_s:.1f}s)")
            if g.requested:
                return bail("SIGTERM drain mid-sweep")

        return self._result(
            preempted=False, error=None, engine="fused",
            units_total=len(plan.units), units_done=units_done,
            resumed=resumed_units, ckpt_failures=ckpt_failures,
            stats=stats)


def run_grid_search(
    grid: List[Dict[str, Any]],
    train_set,
    base_params: Optional[Dict[str, Any]] = None,
    num_boost_round: int = 1000,
    nfold: int = 5,
    early_stopping_rounds: int = 5,
    ledger_path: Optional[str] = None,
    seed: int = 0,
    verbose: bool = True,
    cv_fn: Optional[Callable] = None,
    engine: str = "fused",
) -> SweepLedger:
    """Execute the reference's sweep loop (r/gridsearchCV.R:104-119).

    Per config: 5-fold CV with early stopping; ``best_iter``/``best_score``
    written back into the ledger; ledger checkpointed each iteration.
    Re-running with the same ledger_path skips completed rows.

    ``engine="fused"`` (default) buckets configs sharing the shape-static
    params (num_leaves, bagging_freq) and runs each bucket's cv trainings as
    ONE on-device batched program (folds × configs vmapped, rounds in a
    `lax.while_loop` with on-device early stopping) — this is the headline
    TPU win over the reference's 30-minute serial sweep (SURVEY.md §3.3).
    ``engine="host"`` reproduces the serial per-config loop.

    Since r17 this drives a single-device :class:`SweepService`; the
    returned ledger carries the service timing stats as ``sweep_stats``.
    """
    service = SweepService(
        grid, train_set, base_params=base_params,
        num_boost_round=num_boost_round, nfold=nfold,
        early_stopping_rounds=early_stopping_rounds, seed=seed,
        engine="host" if engine == "host" else "auto",
        ledger_path=ledger_path, verbose=verbose, cv_fn=cv_fn)
    result = service.run()
    ledger = result.ledger
    ledger.sweep_stats = result.stats
    return ledger

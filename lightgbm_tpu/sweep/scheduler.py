"""Sweep scheduler: config grid -> hyper-batches -> device groups (r17).

The fused-CV engine (models/fused.py, r7) runs one BUCKET of configs —
everything sharing the compile-time statics — as a single XLA program
with a configs x folds batch axis.  The scheduler turns a whole grid
into an executable plan over a **configs x devices 2-D mesh**:

* axis 1 (configs): pending configs bucket by :func:`fused_bucket_key`
  and pack into hyper-batches of at most ``hyper_batch`` configs (the
  36-config x 5-fold shape the r7 bench validated as one program);
* axis 2 (devices): the ``n_devices`` mesh splits into
  ``n_devices // group_size`` device groups; each hyper-batch is
  assigned whole to one group (configs never straddle groups — a
  bucket's early stopping is collective), greedily balancing total
  configs per group.

On the CPU dryrun mesh the groups execute serially in unit order — the
plan is still what the configs/hour time model
(``analysis.budgets.sweep_time_model``) prices, and unit identity
(``uid``) is what the per-hyper-batch checkpoints key on, so a resumed
sweep re-plans the SAME remaining units and finds its own state.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple


def fused_bucket_key(p, train_set) -> tuple:
    """Everything the fused program treats as compile-time static,
    INCLUDING objective scalars (a grid axis over e.g. quantile alpha
    must not share one objective instance).  learning_rate also buckets
    — not for compilation (it is traced) but because a bucket runs until
    its SLOWEST config early-stops, and stopping round is dominated by
    lr (mixing lr=0.1 with lr=0.01 makes the fast configs idle-run ~5x
    their needed rounds)."""
    return (p.num_leaves, p.bagging_freq if p.bagging_fraction < 1 else 0,
            p.objective, p.num_class, train_set.num_bins, p.alpha,
            p.sigmoid, p.scale_pos_weight, p.is_unbalance, p.fair_c,
            p.poisson_max_delta_step, p.learning_rate)


class SweepUnit(NamedTuple):
    """One schedulable hyper-batch: a bucket slice bound to a device
    group.  ``uid`` is content-derived (bucket key + config indices), so
    the same remaining work always maps to the same checkpoint
    directory across a kill/resume boundary."""

    uid: str
    bucket_key: tuple
    config_indices: Tuple[int, ...]
    group: int


class SweepPlan(NamedTuple):
    """The full mesh assignment for one sweep execution."""

    units: Tuple[SweepUnit, ...]
    n_devices: int
    group_size: int
    n_groups: int

    def units_for_group(self, group: int) -> List[SweepUnit]:
        return [u for u in self.units if u.group == group]

    def n_configs(self) -> int:
        return sum(len(u.config_indices) for u in self.units)


def _unit_uid(bucket_key: tuple, config_indices: Sequence[int]) -> str:
    doc = repr((tuple(bucket_key), tuple(int(i) for i in config_indices)))
    return hashlib.sha256(doc.encode()).hexdigest()[:16]


class SweepScheduler:
    """Pack pending configs into hyper-batches and spread them over the
    device mesh.

    Parameters
    ----------
    hyper_batch : int
        Max configs per fused hyper-batch (x nfold batch elements on
        device).  36 is the r7-validated shape at the reference sweep.
    """

    def __init__(self, hyper_batch: int = 36):
        if hyper_batch < 1:
            raise ValueError(
                f"hyper_batch must be >= 1, got {hyper_batch}")
        self.hyper_batch = int(hyper_batch)

    def plan(self, parsed: Sequence, train_set, *,
             done: Optional[Sequence[int]] = None,
             n_devices: int = 1, group_size: int = 1) -> SweepPlan:
        """Build the mesh plan for the configs not yet in the ledger.

        ``parsed`` is the full grid as Params (index-aligned with the
        ledger rows); ``done`` lists row indices to skip.  Deterministic:
        the same pending set always yields the same units, the same
        uids, and the same group assignment.
        """
        if n_devices < 1:
            raise ValueError(f"n_devices must be >= 1, got {n_devices}")
        if group_size < 1 or n_devices % group_size:
            raise ValueError(
                f"group_size must be >= 1 and divide n_devices "
                f"(got group_size={group_size}, n_devices={n_devices})")
        n_groups = n_devices // group_size
        skip = set(done or ())

        buckets: Dict[tuple, List[int]] = {}
        for i, p in enumerate(parsed):
            if i in skip:
                continue
            buckets.setdefault(fused_bucket_key(p, train_set), []).append(i)

        chunks: List[Tuple[tuple, Tuple[int, ...]]] = []
        for key, idxs in sorted(buckets.items()):
            for lo in range(0, len(idxs), self.hyper_batch):
                chunks.append((key, tuple(idxs[lo:lo + self.hyper_batch])))

        # largest chunks first onto the least-loaded group (greedy LPT;
        # ties break on group index so the plan stays deterministic)
        order = sorted(range(len(chunks)),
                       key=lambda c: (-len(chunks[c][1]), c))
        load = [0] * n_groups
        group_of = {}
        for c in order:
            g = min(range(n_groups), key=lambda gi: (load[gi], gi))
            group_of[c] = g
            load[g] += len(chunks[c][1])

        units = tuple(
            SweepUnit(uid=_unit_uid(key, idxs), bucket_key=key,
                      config_indices=idxs, group=group_of[c])
            for c, (key, idxs) in enumerate(chunks))
        return SweepPlan(units=units, n_devices=int(n_devices),
                         group_size=int(group_size), n_groups=n_groups)

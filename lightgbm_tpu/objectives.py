"""Objective functions: gradients/hessians + init score + output transform.

TPU-native replacement for LightGBM's ``src/objective/`` (exercised via
``objective="regression"`` at r/gridsearchCV.R:59,74,111 and xgboost's
``reg:linear`` at bagging_boosting.ipynb:121; SURVEY.md §2C "Boosting loop +
objectives/metrics").  Each objective is a stateless class whose
``grad_hess`` runs inside the jitted round step.

Conventions:
  * ``pred`` is always the raw (untransformed) score.
  * gradients/hessians are already multiplied by the effective row weight.
  * ``init_score`` runs on host once per training (numpy).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from .config import Params


class Objective:
    name = "none"
    higher_better = False
    needs_group = False

    def __init__(self, params: Params):
        self.params = params

    def init_score(self, y: np.ndarray, w: np.ndarray) -> float:
        return 0.0

    def grad_hess(self, pred, y, w):
        raise NotImplementedError

    def transform(self, raw):
        """Raw score -> user-facing prediction (e.g. sigmoid for binary)."""
        return raw


class RegressionL2(Objective):
    name = "regression"

    def init_score(self, y, w):
        if not self.params.boost_from_average:
            return 0.0
        return float(np.average(y, weights=np.maximum(w, 0)))

    def grad_hess(self, pred, y, w):
        return (pred - y) * w, w


def _weighted_quantile(y: np.ndarray, w: np.ndarray, alpha: float) -> float:
    """Host-side weighted alpha-quantile (alpha=0.5 -> weighted median):
    the BoostFromScore base for L1/quantile objectives."""
    order = np.argsort(y)
    cw = np.cumsum(w[order])
    idx = np.searchsorted(cw, alpha * cw[-1])
    return float(y[order][min(idx, len(y) - 1)])


class RegressionL1(Objective):
    """MAE: constant-hessian surrogate gradients + leaf renewal.

    Matching upstream ``RegressionL1loss``: the grower uses sign gradients,
    then each grown tree's leaf values are refit to the weighted MEDIAN of
    the leaf's residuals (``RenewTreeOutput``; see
    models.tree.renew_leaf_values for the TPU formulation)."""

    name = "regression_l1"

    @property
    def renew_alpha(self):
        """Leaf renewal quantile: weighted median (RenewTreeOutput)."""
        return 0.5

    def init_score(self, y, w):
        if not self.params.boost_from_average:
            return 0.0
        return _weighted_quantile(y, w, 0.5)

    def grad_hess(self, pred, y, w):
        return jnp.sign(pred - y) * w, w


class Huber(Objective):
    name = "huber"

    def grad_hess(self, pred, y, w):
        delta = jnp.float32(self.params.alpha)
        r = pred - y
        g = jnp.clip(r, -delta, delta)
        return g * w, w

    def init_score(self, y, w):
        if not self.params.boost_from_average:
            return 0.0
        return float(np.average(y, weights=np.maximum(w, 0)))


class Fair(Objective):
    name = "fair"

    def grad_hess(self, pred, y, w):
        c = jnp.float32(self.params.fair_c)
        r = pred - y
        g = c * r / (jnp.abs(r) + c)
        h = c * c / (jnp.abs(r) + c) ** 2
        return g * w, h * w


class Poisson(Objective):
    name = "poisson"

    def init_score(self, y, w):
        mean = max(np.average(y, weights=np.maximum(w, 0)), 1e-9)
        return float(np.log(mean))

    def grad_hess(self, pred, y, w):
        mu = jnp.exp(pred)
        h = jnp.exp(pred + jnp.float32(self.params.poisson_max_delta_step))
        return (mu - y) * w, h * w

    def transform(self, raw):
        return jnp.exp(raw)


class Quantile(Objective):
    name = "quantile"

    @property
    def renew_alpha(self):
        """Leaf renewal quantile = alpha (RegressionQuantileloss)."""
        return float(self.params.alpha)

    def init_score(self, y, w):
        """Weighted alpha-quantile of the labels (upstream
        RegressionQuantileloss::BoostFromScore) — starting from 0.0 costs
        rounds on shifted targets (ADVICE r1)."""
        if not self.params.boost_from_average:
            return 0.0
        return _weighted_quantile(y, w, float(self.params.alpha))

    def grad_hess(self, pred, y, w):
        alpha = jnp.float32(self.params.alpha)
        g = jnp.where(y > pred, -alpha, 1.0 - alpha)
        return g * w, w


class MAPE(Objective):
    """Mean absolute percentage error (upstream ``RegressionMAPELOSS``):
    L1 on residuals scaled by ``1/max(1, |y|)`` — gradients are signs
    carrying that scale as an extra weight, and leaf values renew to the
    weighted median like L1."""

    name = "mape"

    @property
    def renew_alpha(self):
        return 0.5

    @staticmethod
    def renew_scale(y):
        """Leaf renewal weights carry the MAPE 1/max(1,|y|) scale
        (upstream RegressionMAPELOSS label_weight_) — a plain weighted
        median would let large-|y| rows dominate leaf values."""
        return 1.0 / jnp.maximum(jnp.abs(y), 1.0)

    def init_score(self, y, w):
        if not self.params.boost_from_average:
            return 0.0
        return _weighted_quantile(y, w / np.maximum(np.abs(y), 1.0), 0.5)

    def grad_hess(self, pred, y, w):
        scale = 1.0 / jnp.maximum(jnp.abs(y), 1.0)
        return jnp.sign(pred - y) * scale * w, scale * w


class Gamma(Objective):
    """Gamma deviance with log link (upstream ``RegressionGammaLoss``):
    raw score is log(mu); grad = 1 - y*exp(-s), hess = y*exp(-s)."""

    name = "gamma"

    def init_score(self, y, w):
        mean = max(np.average(y, weights=np.maximum(w, 0)), 1e-9)
        return float(np.log(mean))

    def grad_hess(self, pred, y, w):
        e = jnp.exp(-pred)
        return (1.0 - y * e) * w, jnp.maximum(y * e, 1e-16) * w

    def transform(self, raw):
        return jnp.exp(raw)


class Tweedie(Objective):
    """Tweedie deviance, variance power rho in (1, 2) (upstream
    ``RegressionTweedieLoss``): raw score is log(mu);
    grad = -y*exp((1-rho)s) + exp((2-rho)s)."""

    name = "tweedie"

    def __init__(self, params: Params):
        super().__init__(params)
        self.rho = float(params.tweedie_variance_power)

    def init_score(self, y, w):
        mean = max(np.average(y, weights=np.maximum(w, 0)), 1e-9)
        return float(np.log(mean))

    def grad_hess(self, pred, y, w):
        rho = jnp.float32(self.rho)
        a = jnp.exp((1.0 - rho) * pred)
        b = jnp.exp((2.0 - rho) * pred)
        g = -y * a + b
        h = -y * (1.0 - rho) * a + (2.0 - rho) * b
        return g * w, jnp.maximum(h, 1e-16) * w

    def transform(self, raw):
        return jnp.exp(raw)


class CrossEntropy(Objective):
    """Cross-entropy on CONTINUOUS labels in [0, 1] (upstream
    ``CrossEntropy`` / objective="xentropy"): logistic link without the
    sigmoid-scale knob; unlike ``binary`` the label need not be 0/1."""

    name = "cross_entropy"

    def init_score(self, y, w):
        if not self.params.boost_from_average:
            return 0.0
        pbar = float(np.average(y, weights=np.maximum(w, 1e-12)))
        pbar = min(max(pbar, 1e-12), 1 - 1e-12)
        return float(np.log(pbar / (1 - pbar)))

    def grad_hess(self, pred, y, w):
        p = jax_sigmoid(pred)
        return (p - y) * w, jnp.maximum(p * (1.0 - p), 1e-16) * w

    def transform(self, raw):
        return jax_sigmoid(raw)


class Binary(Objective):
    """Binary logloss on labels {0,1}; raw score is a logit.

    Supports ``sigmoid`` scaling, ``scale_pos_weight`` and ``is_unbalance``
    (positive-class reweighting) like upstream binary_objective.hpp.
    """

    name = "binary"

    def __init__(self, params: Params):
        super().__init__(params)
        self.pos_weight = float(params.scale_pos_weight)

    def prepare(self, y: np.ndarray, w: np.ndarray) -> None:
        if self.params.is_unbalance:
            pos = float(np.sum(w * (y > 0.5)))
            neg = float(np.sum(w * (y <= 0.5)))
            self.pos_weight = neg / max(pos, 1.0) if pos > 0 else 1.0

    def init_score(self, y, w):
        self.prepare(y, np.asarray(w))
        if not self.params.boost_from_average:
            return 0.0
        pw = self.pos_weight
        sw = w * np.where(y > 0.5, pw, 1.0)
        pbar = np.average(y, weights=np.maximum(sw, 1e-12))
        pbar = min(max(pbar, 1e-12), 1 - 1e-12)
        return float(np.log(pbar / (1 - pbar)) / self.params.sigmoid)

    def grad_hess(self, pred, y, w):
        sig = jnp.float32(self.params.sigmoid)
        p = jax_sigmoid(sig * pred)
        wy = w * jnp.where(y > 0.5, jnp.float32(self.pos_weight), 1.0)
        g = sig * (p - y)
        h = jnp.maximum(sig * sig * p * (1.0 - p), 1e-16)
        return g * wy, h * wy

    def transform(self, raw):
        return jax_sigmoid(jnp.float32(self.params.sigmoid) * raw)


def jax_sigmoid(x):
    return 1.0 / (1.0 + jnp.exp(-x))


class CustomObjective(Objective):
    """Wraps a user fobj(preds, train_data)-style callable (lgb custom loss)."""

    name = "custom"

    def __init__(self, params: Params, fobj: Callable):
        super().__init__(params)
        self.fobj = fobj

    def grad_hess(self, pred, y, w):
        g, h = self.fobj(pred, y)
        return g * w, h * w


_REGISTRY: Dict[str, type] = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": Huber,
    "fair": Fair,
    "poisson": Poisson,
    "quantile": Quantile,
    "mape": MAPE,
    "gamma": Gamma,
    "tweedie": Tweedie,
    "cross_entropy": CrossEntropy,
    "binary": Binary,
}


def create_objective(params: Params) -> Objective:
    fobj = params.extra.get("fobj")
    if fobj is not None or params.objective == "none":
        if fobj is None:
            raise ValueError("objective='none' requires a custom fobj")
        return CustomObjective(params, fobj)
    if params.objective in ("multiclass", "multiclassova"):
        from .multiclass import Multiclass, MulticlassOVA
        cls = MulticlassOVA if params.objective == "multiclassova" else \
            Multiclass
        return cls(params)
    if params.objective == "lambdarank":
        from .ranking import LambdaRank
        return LambdaRank(params)
    cls = _REGISTRY.get(params.objective)
    if cls is None:
        raise ValueError(f"Unsupported objective: {params.objective}")
    return cls(params)

"""Config-file CLI (LightGBM's original interface, ``lightgbm config=...``).

Upstream LightGBM ships a C++ CLI driven by ``key=value`` config files with
``task=train|predict`` (src/main.cpp + io/config.cpp).  The snippets repo
never uses it, but it is the library's historical front door, so the same
contract is exposed here over the TPU engine:

    python -m lightgbm_tpu config=train.conf
    python -m lightgbm_tpu task=train data=train.csv valid=valid.csv \
        objective=regression num_trees=100 output_model=model.txt
    python -m lightgbm_tpu task=predict data=test.csv \
        input_model=model.txt output_result=preds.txt

Config format (upstream io/config semantics): one ``key = value`` per line,
``#`` comments; command-line ``key=value`` pairs override file entries.
Data files are CSV/TSV (auto-sniffed) with ``label_column=<int>`` (default
0, upstream default) or ``label_column=name:<col>``; ``header=true|false``
(default false, matching upstream).
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional, Tuple

import numpy as np


def parse_config_text(text: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        if "=" not in line:
            raise ValueError(f"config line without '=': {line!r}")
        k, v = line.split("=", 1)
        out[k.strip()] = v.strip()
    return out


def parse_argv(argv: List[str]) -> Dict[str, str]:
    """``key=value`` pairs; a ``config=`` file loads first, CLI overrides."""
    pairs: Dict[str, str] = {}
    for a in argv:
        if "=" not in a:
            raise ValueError(f"expected key=value, got {a!r}")
        k, v = a.split("=", 1)
        pairs[k.strip()] = v.strip()
    cfg: Dict[str, str] = {}
    if "config" in pairs:
        with open(pairs.pop("config")) as f:
            cfg = parse_config_text(f.read())
    cfg.update(pairs)
    return cfg


def _load_table(path: str, header: bool) -> Tuple[np.ndarray, List[str]]:
    import csv

    with open(path) as f:
        sample = f.read(4096)
        f.seek(0)
        delim = "\t" if "\t" in sample.split("\n", 1)[0] else ","
        rows = list(csv.reader(f, delimiter=delim))
    names: List[str] = []
    if header:
        names = rows[0]
        rows = rows[1:]
    data = np.asarray(
        [[np.nan if c in ("", "NA", "na", "NaN") else float(c) for c in r]
         for r in rows if r], dtype=np.float64)
    return data, names


def _split_label(data: np.ndarray, names: List[str],
                 label_spec: str) -> Tuple[np.ndarray, np.ndarray]:
    if label_spec.startswith("name:"):
        col = names.index(label_spec[5:])
    else:
        col = int(label_spec)
    y = data[:, col]
    X = np.delete(data, col, axis=1)
    return X, y


def main(argv: Optional[List[str]] = None) -> int:
    cfg = parse_argv(list(sys.argv[1:] if argv is None else argv))
    task = cfg.pop("task", "train")
    header = cfg.pop("header", "false").lower() in ("true", "1", "yes")
    label_spec = cfg.pop("label_column", "0")
    data_path = cfg.pop("data", None)
    valid_path = cfg.pop("valid", cfg.pop("valid_data", None))
    output_model = cfg.pop("output_model", "LightGBM_model.txt")
    input_model = cfg.pop("input_model", None)
    output_result = cfg.pop("output_result", "LightGBM_predict_result.txt")

    import lightgbm_tpu as lgb

    if task == "train":
        if data_path is None:
            raise SystemExit("task=train requires data=<file>")
        data, names = _load_table(data_path, header)
        X, y = _split_label(data, names, label_spec)
        params = dict(cfg)  # remaining keys ARE the LightGBM params;
        # train() resolves every num-rounds alias from them itself
        dtrain = lgb.Dataset(X, label=y)
        valid_sets = None
        if valid_path:
            valid_sets = []
            for vp in valid_path.split(","):  # upstream: comma-separated
                vdata, vnames = _load_table(vp.strip(), header)
                Xv, yv = _split_label(vdata, vnames, label_spec)
                valid_sets.append(dtrain.create_valid(Xv, label=yv))
        booster = lgb.train(params, dtrain, valid_sets=valid_sets)
        booster.save_model(output_model)
        print(f"[lightgbm_tpu] finished training; model -> {output_model}")
        return 0
    if task == "predict":
        if data_path is None or input_model is None:
            raise SystemExit(
                "task=predict requires data=<file> input_model=<model>")
        data, names = _load_table(data_path, header)
        booster = lgb.Booster(model_file=input_model)
        if data.shape[1] == booster.num_feature() + 1:
            # labelled file: drop the label column like upstream predict
            X, _ = _split_label(data, names, label_spec)
        else:
            X = data
        pred = booster.predict(X)
        np.savetxt(output_result, pred, fmt="%.10g")
        print(f"[lightgbm_tpu] predictions -> {output_result}")
        return 0
    raise SystemExit(f"unknown task {task!r} (train|predict)")


if __name__ == "__main__":
    sys.exit(main())

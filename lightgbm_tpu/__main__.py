"""Config-file CLI (LightGBM's original interface, ``lightgbm config=...``).

Upstream LightGBM ships a C++ CLI driven by ``key=value`` config files with
``task=train|predict`` (src/main.cpp + io/config.cpp).  The snippets repo
never uses it, but it is the library's historical front door, so the same
contract is exposed here over the TPU engine:

    python -m lightgbm_tpu config=train.conf
    python -m lightgbm_tpu task=train data=train.csv valid=valid.csv \
        objective=regression num_trees=100 output_model=model.txt
    python -m lightgbm_tpu task=predict data=test.csv \
        input_model=model.txt output_result=preds.txt

Config format (upstream io/config semantics): one ``key = value`` per line,
``#`` comments; command-line ``key=value`` pairs override file entries.
Data files are CSV/TSV (auto-sniffed) with ``label_column=<int>`` (default
0, upstream default) or ``label_column=name:<col>``; ``header=true|false``
(default false, matching upstream).

``task=serve`` (alias ``predict-server``) is the serving front end: it
loads a model (JSON text or packed ``.npz``), builds the compiled
PredictorRuntime + micro-batching queue (lightgbm_tpu.serving), and
serves newline-delimited requests from stdin to stdout — one CSV row (or
JSON array) of features in, one prediction out, no network dependency:

    python -m lightgbm_tpu task=serve input_model=model.npz \
        max_batch=256 max_delay_ms=2 < requests.csv > preds.txt

Keys: ``output_format=csv|json`` (csv), ``raw_score=true|false`` (false),
``num_iteration`` (staged truncation), ``request_timeout_ms`` (per-request
queue deadline), ``show_stats=true`` (serving counters as JSON on stderr
at shutdown), ``max_bucket``/``max_cache_entries`` (runtime knobs),
``warm_buckets=true`` (precompile the bucket ladder before the first
request so no size class pays its compile on live traffic).

r12 resilience keys (validated at startup; unknown keys are rejected):
``max_queue_depth`` (admission-control bound on live queued requests;
default ``none`` = unbounded), ``shed_policy=off|depth|deadline``
(default ``deadline``: reject requests predicted to miss their deadline
with a typed ``Overloaded`` error instead of letting p99 blow out),
``canary_rows`` (post-swap canary batch size, default 8),
``compile_cache_dir`` (jax persistent compilation cache, so restarts
serve warm).  The model is ModelBank-backed: ``!swap <model.npz>`` /
``!rollback`` / ``!stats`` request lines are control commands (acks on
stderr), and SIGTERM drains gracefully — stop admitting, flush
in-flight, final stats snapshot on stderr.

r14 pod-scale serving keys: ``mesh_devices`` (power of two; shard
dispatches across a device mesh, default 1), ``shard_policy=auto|dp|tp``
(data-parallel row sharding — bit-identical to single-device at f32 —
vs tree-parallel psum splitting vs the automatic batch-size x
forest-depth chooser; default ``auto``), ``forest_precision=f32|bf16|
int8`` (quantized resident forest with per-tree scales — ~2.3x models
per HBM byte at int8; structural fields must narrow exactly or the
deploy is rejected, and the canary gates quantization drift against its
arithmetic bound).  Swaps stay mesh-wide atomic: one runtime owns all
mesh programs, so ``!swap``/``!rollback`` remain one attribute flip.

r13 fault-tolerant training keys (``task=train``): ``checkpoint_dir=``
turns on the resumable loop — atomic checkpoints every
``checkpoint_rounds`` (default 10), ``checkpoint_keep`` generations
retained (default 2), and ``resume=true|false`` (default true: pick up
the newest valid checkpoint, bit-identical continuation).  SIGTERM
finishes the in-flight round, checkpoints, and exits 0, so a preempted
job resumes by rerunning the same command line.

``task=refresh`` (r15) runs the freshness pipeline: watch a directory
for ``*.npz`` row-block files (``X`` + ``y`` arrays), continue training
the live model ``refresh_rounds`` rounds per generation, and push each
versioned artifact through canary + atomic hot swap, reporting the
measured model staleness per flip:

    python -m lightgbm_tpu task=refresh watch_dir=blocks/ \
        state_dir=state/ refresh_rounds=5 staleness_slo_ms=60000 \
        objective=binary num_leaves=31

Keys (validated up front; unknown keys are rejected like ``serve``):
``watch_dir``/``state_dir`` (required), ``refresh_rounds`` (default 5),
``initial_rounds`` (generation 1; defaults to refresh_rounds),
``checkpoint_rounds`` (default 5), ``canary_rows`` (default 8),
``staleness_slo_ms`` (optional SLO; breaches are reported on stderr),
``model_name`` (default "model"), ``max_ticks`` (default 64 — the CLI
drains the watch directory and exits; schedulers rerun it).  Remaining
keys are LightGBM training params, checked against the known parameter
vocabulary.  r17 adds the closed tune->serve loop: ``sweep_grid=<json>``
+ ``sweep_every=N`` makes every Nth data-bearing generation sweep the
grid first and promote the winning config through the same
canary->flip path (``sweep_rounds``/``sweep_nfold``/
``sweep_early_stopping``/``sweep_devices`` bound the sweep).

``task=sweep`` (r17) runs a standalone distributed sweep over a
CSV/TSV training file: the grid (JSON — ``{"axes": {...}}`` expands the
cartesian product, ``{"rows": [...]}`` or a bare list is explicit)
shards into fused-CV hyper-batches over a configs x devices mesh, every
hyper-batch checkpoints between segments, and the ledger is crash-safe
and resumable — a preempted sweep exits 0 and the SAME command line
resumes bit-identically:

    python -m lightgbm_tpu task=sweep data=train.csv \
        sweep_grid=grid.json ledger=sweep.json \
        sweep_checkpoint_dir=ck/ sweep_devices=8 num_trees=500

Keys (typed validation, unknown keys rejected): ``sweep_grid``
(required), ``ledger`` (path; ``.RData`` suffix selects the reference's
codec), ``sweep_checkpoint_dir``, ``sweep_devices``/
``sweep_group_size`` (mesh shape), ``nfold`` (default 5),
``early_stopping_rounds`` (5), ``hyper_batch`` (36),
``engine=auto|fused|host``, ``seed``, ``top`` (leaderboard rows
printed, default 10).  Remaining keys are the shared base params.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional, Tuple

import numpy as np


def parse_config_text(text: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        if "=" not in line:
            raise ValueError(f"config line without '=': {line!r}")
        k, v = line.split("=", 1)
        out[k.strip()] = v.strip()
    return out


def parse_argv(argv: List[str]) -> Dict[str, str]:
    """``key=value`` pairs; a ``config=`` file loads first, CLI overrides."""
    pairs: Dict[str, str] = {}
    for a in argv:
        if "=" not in a:
            raise ValueError(f"expected key=value, got {a!r}")
        k, v = a.split("=", 1)
        pairs[k.strip()] = v.strip()
    cfg: Dict[str, str] = {}
    if "config" in pairs:
        with open(pairs.pop("config")) as f:
            cfg = parse_config_text(f.read())
    cfg.update(pairs)
    return cfg


def _load_table(path: str, header: bool) -> Tuple[np.ndarray, List[str]]:
    import csv

    with open(path) as f:
        sample = f.read(4096)
        f.seek(0)
        delim = "\t" if "\t" in sample.split("\n", 1)[0] else ","
        rows = list(csv.reader(f, delimiter=delim))
    names: List[str] = []
    if header:
        names = rows[0]
        rows = rows[1:]
    data = np.asarray(
        [[np.nan if c in ("", "NA", "na", "NaN") else float(c) for c in r]
         for r in rows if r], dtype=np.float64)
    return data, names


def _split_label(data: np.ndarray, names: List[str],
                 label_spec: str) -> Tuple[np.ndarray, np.ndarray]:
    if label_spec.startswith("name:"):
        col = names.index(label_spec[5:])
    else:
        col = int(label_spec)
    y = data[:, col]
    X = np.delete(data, col, axis=1)
    return X, y


def main(argv: Optional[List[str]] = None) -> int:
    raw = list(sys.argv[1:] if argv is None else argv)
    if raw and raw[0] == "lint":
        # graftlint front end — flag-style argv, not key=value config
        from .analysis.cli import main as lint_main

        return lint_main(raw[1:])
    try:
        cfg = parse_argv(raw)
    except (ValueError, OSError) as e:
        # `python -m lightgbm_tpu refresh --help`-style misuse: a typed
        # usage error, never a traceback
        raise SystemExit(
            f"lightgbm_tpu: {e}\nusage: python -m lightgbm_tpu "
            "task=train|predict|serve|refresh|sweep key=value ... "
            "(or config=<file>; see module docs)") from None
    task = cfg.pop("task", "train")
    header = cfg.pop("header", "false").lower() in ("true", "1", "yes")
    label_spec = cfg.pop("label_column", "0")
    data_path = cfg.pop("data", None)
    valid_path = cfg.pop("valid", cfg.pop("valid_data", None))
    output_model = cfg.pop("output_model", "LightGBM_model.txt")
    input_model = cfg.pop("input_model", None)
    output_result = cfg.pop("output_result", "LightGBM_predict_result.txt")

    import lightgbm_tpu as lgb

    if task == "train":
        if data_path is None:
            raise SystemExit("task=train requires data=<file>")
        data, names = _load_table(data_path, header)
        X, y = _split_label(data, names, label_spec)
        ckpt_dir = cfg.pop("checkpoint_dir", None)
        params = dict(cfg)  # remaining keys ARE the LightGBM params;
        # train() resolves every num-rounds alias from them itself
        dtrain = lgb.Dataset(X, label=y)
        if ckpt_dir:
            # fault-tolerant path (r13): auto-checkpoint + SIGTERM drain
            # + resume; a preempted run exits 0 with the checkpoint noted
            # so schedulers can simply relaunch the same command line
            from .engine import _resolve_num_rounds
            from .training import train_resumable

            ckpt_rounds = int(params.pop("checkpoint_rounds", 10))
            keep_last = int(params.pop("checkpoint_keep", 2))
            resume = str(params.pop("resume", "true")).lower() \
                in ("true", "1", "yes")
            rounds = _resolve_num_rounds(params, 100)
            result = train_resumable(
                params, dtrain, rounds, checkpoint_dir=ckpt_dir,
                checkpoint_rounds=ckpt_rounds, keep_last=keep_last,
                resume=resume)
            booster = result.booster
            if result.resumed_from:
                print(f"[lightgbm_tpu] resumed from "
                      f"{result.resumed_from}")
            if result.preempted:
                print(f"[lightgbm_tpu] preempted at round "
                      f"{result.rounds_done}/{rounds}; state -> "
                      f"{result.last_checkpoint} (rerun to resume)")
                return 0
        else:
            valid_sets = None
            if valid_path:
                valid_sets = []
                for vp in valid_path.split(","):  # upstream: comma-sep
                    vdata, vnames = _load_table(vp.strip(), header)
                    Xv, yv = _split_label(vdata, vnames, label_spec)
                    valid_sets.append(dtrain.create_valid(Xv, label=yv))
            booster = lgb.train(params, dtrain, valid_sets=valid_sets)
        booster.save_model(output_model)
        print(f"[lightgbm_tpu] finished training; model -> {output_model}")
        return 0
    if task == "predict":
        if data_path is None or input_model is None:
            raise SystemExit(
                "task=predict requires data=<file> input_model=<model>")
        data, names = _load_table(data_path, header)
        booster = lgb.Booster(model_file=input_model)
        if data.shape[1] == booster.num_feature() + 1:
            # labelled file: drop the label column like upstream predict
            X, _ = _split_label(data, names, label_spec)
        else:
            X = data
        pred = booster.predict(X)
        np.savetxt(output_result, pred, fmt="%.10g")
        print(f"[lightgbm_tpu] predictions -> {output_result}")
        return 0
    if task in ("serve", "predict-server"):
        if input_model is None:
            raise SystemExit(
                "task=serve requires input_model=<model.txt|model.npz>")
        return _serve(input_model, cfg)
    if task == "refresh":
        return _refresh(cfg)
    if task == "sweep":
        return _sweep(cfg, data_path, header, label_spec)
    raise SystemExit(
        f"unknown task {task!r} (train|predict|serve|refresh|sweep)")


def _parse_request_line(line: str) -> Optional[np.ndarray]:
    """One request: CSV floats or a JSON array; blank/comment -> None."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    if line.startswith("["):
        import json

        return np.asarray(json.loads(line), dtype=np.float64)
    return np.asarray(
        [np.nan if c.strip() in ("", "NA", "na", "NaN") else float(c)
         for c in line.split(",")], dtype=np.float64)


_SERVE_MODEL = "default"        # single-tenant CLI name in the ModelBank


def _serve(input_model: str, cfg: Dict[str, str],
           stdin=None, stdout=None, stderr=None) -> int:
    """Micro-batched stdin/stdout serving loop (no network dependency).

    Reads one request per line, coalesces through MicroBatcher, answers
    in submission order.  Separated from main() with injectable streams
    so the loop is Tier-1-testable in-process.

    The model lives in a ModelBank, so lines starting with ``!`` are
    control commands (acks on stderr, so the prediction stream stays
    clean): ``!swap <model.npz>`` hot-swaps to a new artifact
    (validate -> warm -> canary -> atomic flip; a rejected swap leaves
    the current version serving), ``!rollback`` flips back to the
    previous resident version, ``!stats`` prints a stats snapshot.

    SIGTERM drains gracefully: stop admitting, flush in-flight requests,
    emit a final stats snapshot on stderr.
    """
    import json
    import signal

    from .serving import SHED_POLICIES, ModelBank, SwapRejected
    from .serving.packed import pack_booster

    stdin = sys.stdin if stdin is None else stdin
    stdout = sys.stdout if stdout is None else stdout
    stderr = sys.stderr if stderr is None else stderr

    def flag(key: str, default: bool = False) -> bool:
        return cfg.pop(key, str(default)).lower() in ("true", "1", "yes")

    def die(msg: str) -> "SystemExit":
        return SystemExit(f"task=serve: {msg}")

    max_batch = int(cfg.pop("max_batch", "128"))
    max_delay_ms = float(cfg.pop("max_delay_ms", "2"))
    max_bucket = int(cfg.pop("max_bucket", "16384"))
    max_cache = int(cfg.pop("max_cache_entries", "12"))
    out_format = cfg.pop("output_format", "csv")
    raw_score = flag("raw_score")
    show_stats = flag("show_stats")
    warm_buckets = flag("warm_buckets")
    tmo = cfg.pop("request_timeout_ms", None)
    timeout_ms = None if tmo is None else float(tmo)
    num_it = cfg.pop("num_iteration", None)
    num_iteration = None if num_it is None else int(num_it)
    # -- r12 resilience knobs, validated up front (a typo'd operating
    # -- point must fail the process at startup, not at 3am under load)
    depth_s = cfg.pop("max_queue_depth", "none").lower()
    try:
        max_queue_depth = None if depth_s in ("none", "") else int(depth_s)
    except ValueError:
        raise die(f"max_queue_depth must be an integer or 'none', "
                  f"got {depth_s!r}") from None
    if max_queue_depth is not None and max_queue_depth < 1:
        raise die(f"max_queue_depth must be >= 1, got {max_queue_depth}")
    shed_policy = cfg.pop("shed_policy", "deadline")
    if shed_policy not in SHED_POLICIES:
        raise die(f"shed_policy must be one of {'|'.join(SHED_POLICIES)},"
                  f" got {shed_policy!r}")
    try:
        canary_rows = int(cfg.pop("canary_rows", "8"))
    except ValueError:
        raise die("canary_rows must be an integer") from None
    if canary_rows < 0:
        raise die(f"canary_rows must be >= 0, got {canary_rows}")
    cache_dir = cfg.pop("compile_cache_dir", None)
    # -- r14 pod-scale knobs, validated up front like the r12 set
    from .serving import FOREST_PRECISIONS, SHARD_POLICIES
    try:
        mesh_devices = int(cfg.pop("mesh_devices", "1"))
    except ValueError:
        raise die("mesh_devices must be an integer") from None
    if mesh_devices < 1 or (mesh_devices & (mesh_devices - 1)):
        raise die(f"mesh_devices must be a power of two >= 1, "
                  f"got {mesh_devices}")
    shard_policy = cfg.pop("shard_policy", "auto")
    if shard_policy not in SHARD_POLICIES:
        raise die(f"shard_policy must be one of "
                  f"{'|'.join(SHARD_POLICIES)}, got {shard_policy!r}")
    forest_precision = cfg.pop("forest_precision", "f32")
    if forest_precision not in FOREST_PRECISIONS:
        raise die(f"forest_precision must be one of "
                  f"{'|'.join(FOREST_PRECISIONS)}, got "
                  f"{forest_precision!r}")
    if cfg:
        raise die(f"unknown key(s): {', '.join(sorted(cfg))}")

    bank = ModelBank(max_bucket=max_bucket, max_cache_entries=max_cache,
                     warm_on_deploy=warm_buckets, canary_rows=canary_rows,
                     cache_dir=cache_dir, mesh_devices=mesh_devices,
                     shard_policy=shard_policy,
                     forest_precision=forest_precision)

    def deploy(path: str) -> dict:
        if path.endswith(".npz"):
            return bank.deploy(_SERVE_MODEL, path, raw_score=raw_score)
        import lightgbm_tpu as lgb

        packed = pack_booster(lgb.Booster(model_file=path))
        return bank.deploy(_SERVE_MODEL, packed, raw_score=raw_score)

    try:
        rep = deploy(input_model)
    except SwapRejected as e:
        raise die(f"input_model rejected: {e}") from None
    if warm_buckets:
        # the ladder precompiled inside deploy(), before the first
        # request — each size class pays dispatch, not compile
        stderr.write(f"[lightgbm_tpu] warmed {rep['warmed']} bucket "
                     f"programs\n")
        stderr.flush()
    batcher = bank.batcher(_SERVE_MODEL, max_batch=max_batch,
                           max_delay_ms=max_delay_ms,
                           timeout_ms=timeout_ms, raw_score=raw_score,
                           max_queue_depth=max_queue_depth,
                           shed_policy=shed_policy)
    stats = batcher.stats

    def emit(pending) -> None:
        try:
            v = pending.result()
        except Exception as e:                    # noqa: BLE001
            stdout.write(f"ERROR: {type(e).__name__}: {e}\n")
            return
        v = np.atleast_1d(np.asarray(v, np.float64))
        if out_format == "json":
            stdout.write(json.dumps(
                v.tolist() if v.size > 1 else float(v[0])) + "\n")
        else:
            stdout.write(",".join(f"{x:.10g}" for x in v) + "\n")

    def control(line: str) -> None:
        parts = line[1:].split()
        cmd = parts[0] if parts else ""
        try:
            if cmd == "swap" and len(parts) == 2:
                r = deploy(parts[1])
                stderr.write(f"[lightgbm_tpu] swapped {_SERVE_MODEL} -> "
                             f"{r['version']}\n")
            elif cmd == "rollback":
                r = bank.rollback(_SERVE_MODEL)
                stderr.write(f"[lightgbm_tpu] rolled back {_SERVE_MODEL} "
                             f"-> {r['version']}\n")
            elif cmd == "stats":
                stderr.write(json.dumps(stats.snapshot()) + "\n")
            else:
                stderr.write(f"[lightgbm_tpu] unknown control "
                             f"{line.strip()!r} (!swap <path> | "
                             f"!rollback | !stats)\n")
        except SwapRejected as e:
            # the old version never stopped serving
            stderr.write(f"[lightgbm_tpu] {e}\n")
        stderr.flush()

    draining = False

    def _on_term(signum, frame):                   # noqa: ARG001
        nonlocal draining
        draining = True

    try:
        prev_handler = signal.signal(signal.SIGTERM, _on_term)
    except ValueError:                             # not the main thread
        prev_handler = None

    pendings = []
    try:
        for line in stdin:
            if draining:
                break                              # stop admitting
            if line.lstrip().startswith("!"):
                control(line)
                continue
            try:
                row = _parse_request_line(line)
            except (ValueError, json.JSONDecodeError) as e:
                pendings.append(_failed_pending(e))
                continue
            if row is None:
                continue
            pendings.append(batcher.submit(row,
                                           num_iteration=num_iteration))
            batcher.pump()
            # stream out everything already resolved, in order
            while pendings and pendings[0].done:
                emit(pendings.pop(0))
        # graceful drain (SIGTERM or EOF): flush in-flight, answer all
        batcher.flush()
        for p in pendings:
            emit(p)
        stdout.flush()
    finally:
        if prev_handler is not None:
            signal.signal(signal.SIGTERM, prev_handler)
    if draining:
        stderr.write(f"[lightgbm_tpu] drained on SIGTERM "
                     f"({len(pendings)} in-flight flushed)\n")
    if show_stats or draining:
        stderr.write(json.dumps(stats.snapshot()) + "\n")
        stderr.flush()
    return 0


def _refresh(cfg: Dict[str, str], stdout=None, stderr=None) -> int:
    """``task=refresh``: drive the r15 freshness pipeline over a watch
    directory.  Every refresh key is validated up front and unknown
    keys are rejected (the r12 ``serve`` contract) — a typo'd operating
    point fails at startup, not mid-refresh; the keys left over after
    the refresh set must belong to the known LightGBM/TPU parameter
    vocabulary.  One invocation drains the watch directory (bounded by
    ``max_ticks``) and exits; schedulers keep the loop alive by
    rerunning the same command line — the daemon re-anchors on the
    newest completed artifact in ``state_dir``."""
    import json

    from .config import _ALIASES, _FRAMEWORK_KEYS
    from .pipeline import DirectoryFeed, RefreshDaemon

    stdout = sys.stdout if stdout is None else stdout
    stderr = sys.stderr if stderr is None else stderr

    def die(msg: str) -> "SystemExit":
        return SystemExit(f"task=refresh: {msg}")

    def intkey(key: str, default: str, minimum: int):
        raw_v = cfg.pop(key, default)
        if raw_v is None:
            return None
        try:
            v = int(raw_v)
        except ValueError:
            raise die(f"{key} must be an integer, got {raw_v!r}") \
                from None
        if v < minimum:
            raise die(f"{key} must be >= {minimum}, got {v}")
        return v

    watch_dir = cfg.pop("watch_dir", None)
    if not watch_dir:
        raise die("requires watch_dir=<directory of X/y .npz blocks>")
    state_dir = cfg.pop("state_dir", None)
    if not state_dir:
        raise die("requires state_dir=<directory for models/checkpoints>")
    refresh_rounds = intkey("refresh_rounds", "5", 1)
    initial_rounds = intkey("initial_rounds", None, 1)
    checkpoint_rounds = intkey("checkpoint_rounds", "5", 1)
    canary_rows = intkey("canary_rows", "8", 0)
    max_ticks = intkey("max_ticks", "64", 1)
    model_name = cfg.pop("model_name", "model")
    # r17 closed tune->serve loop: every sweep_every'th data-bearing
    # generation sweeps the grid and promotes the winner
    grid_path = cfg.pop("sweep_grid", None)
    sweep_grid = None
    if grid_path is not None:
        sweep_grid = _load_grid(grid_path, die)
    sweep_every = intkey("sweep_every", "0", 0)
    if sweep_every > 0 and sweep_grid is None:
        raise die("sweep_every > 0 requires sweep_grid=<grid.json>")
    sweep_rounds = intkey("sweep_rounds", "50", 1)
    sweep_nfold = intkey("sweep_nfold", "3", 2)
    sweep_early_stopping = intkey("sweep_early_stopping", "5", 0)
    sweep_devices = intkey("sweep_devices", "1", 1)
    slo_s = cfg.pop("staleness_slo_ms", None)
    staleness_slo_ms = None
    if slo_s is not None:
        try:
            staleness_slo_ms = float(slo_s)
        except ValueError:
            raise die(f"staleness_slo_ms must be a number, got "
                      f"{slo_s!r}") from None
        if staleness_slo_ms <= 0:
            raise die(f"staleness_slo_ms must be > 0, got "
                      f"{staleness_slo_ms}")
    unknown = sorted(k for k in cfg
                     if k.lower() not in _ALIASES
                     and k.lower() not in _FRAMEWORK_KEYS)
    if unknown:
        raise die(f"unknown key(s): {', '.join(unknown)}")

    daemon = RefreshDaemon(
        dict(cfg), state_dir, feed=DirectoryFeed(watch_dir),
        model_name=model_name, refresh_rounds=refresh_rounds,
        initial_rounds=initial_rounds,
        checkpoint_rounds=checkpoint_rounds,
        staleness_slo_ms=staleness_slo_ms, canary_rows=canary_rows,
        sweep_grid=sweep_grid, sweep_every=sweep_every,
        sweep_rounds=sweep_rounds, sweep_nfold=sweep_nfold,
        sweep_early_stopping=sweep_early_stopping,
        sweep_devices=sweep_devices)
    events = daemon.run_until_idle(max_ticks=max_ticks)
    for ev in events:
        doc = {k: v for k, v in ev.items() if k != "report"}
        stdout.write(json.dumps(doc) + "\n")
    snap = daemon.tracker.snapshot()
    stderr.write(json.dumps({
        "generation": daemon.snapshot()["generation"],
        "served": snap["served"],
        "worst_staleness_ms": snap["worst_staleness_ms"],
        "breaches": snap["breaches"],
    }) + "\n")
    stdout.flush()
    stderr.flush()
    return 0


def _load_grid(path: str, die) -> list:
    """Load a sweep grid from a JSON file: ``{"axes": {...}}`` expands
    the cartesian product (R ``expand.grid`` order), ``{"rows": [...]}``
    or a bare list of objects is the explicit row set.  Every misuse is
    a typed one-line error through ``die``."""
    import json

    from .sweep import expand_grid

    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise die(f"sweep_grid file unreadable: {e}") from None
    except json.JSONDecodeError as e:
        raise die(f"sweep_grid is not valid JSON: {e}") from None
    if isinstance(doc, dict) and "axes" in doc:
        axes = doc["axes"]
        if not isinstance(axes, dict) or not axes or \
                not all(isinstance(v, list) and v for v in axes.values()):
            raise die('sweep_grid "axes" must map param names to '
                      "non-empty lists of values")
        return expand_grid(**axes)
    rows = doc.get("rows") if isinstance(doc, dict) else doc
    if not isinstance(rows, list) or not rows or \
            not all(isinstance(r, dict) for r in rows):
        raise die('sweep_grid must be {"axes": {...}}, {"rows": [...]}, '
                  "or a JSON list of config objects")
    return [dict(r) for r in rows]


def _sweep(cfg: Dict[str, str], data_path: Optional[str], header: bool,
           label_spec: str, stdout=None, stderr=None) -> int:
    """``task=sweep``: run (or resume) a standalone hyperparameter sweep
    over a CSV/TSV training file through the r17 ``SweepService`` —
    scheduled hyper-batches on the fused-CV engine, per-hyper-batch
    checkpoints, a crash-safe resumable ledger, and a leaderboard on
    stdout.  Validation follows the ``serve``/``refresh`` contract:
    every sweep key is checked up front with typed one-line errors,
    unknown keys are rejected against the parameter vocabulary, and a
    preemption exits 0 with the resume instruction — schedulers just
    rerun the same command line."""
    import json

    from .config import _ALIASES, _FRAMEWORK_KEYS
    from .engine import _resolve_num_rounds

    stdout = sys.stdout if stdout is None else stdout
    stderr = sys.stderr if stderr is None else stderr

    def die(msg: str) -> "SystemExit":
        return SystemExit(f"task=sweep: {msg}")

    def intkey(key: str, default, minimum: int):
        raw_v = cfg.pop(key, default)
        if raw_v is None:
            return None
        try:
            v = int(raw_v)
        except ValueError:
            raise die(f"{key} must be an integer, got {raw_v!r}") \
                from None
        if v < minimum:
            raise die(f"{key} must be >= {minimum}, got {v}")
        return v

    if data_path is None:
        raise die("requires data=<train file>")
    grid_path = cfg.pop("sweep_grid", None)
    if not grid_path:
        raise die('requires sweep_grid=<grid.json> ({"axes": {...}}, '
                  '{"rows": [...]}, or a list of config objects)')
    grid = _load_grid(grid_path, die)
    sweep_devices = intkey("sweep_devices", "1", 1)
    sweep_group_size = intkey("sweep_group_size", "1", 1)
    if sweep_devices % sweep_group_size:
        raise die(f"sweep_group_size must divide sweep_devices (got "
                  f"group_size={sweep_group_size}, "
                  f"devices={sweep_devices})")
    ckpt_dir = cfg.pop("sweep_checkpoint_dir", None)
    if ckpt_dir is not None and not str(ckpt_dir).strip():
        raise die("sweep_checkpoint_dir must be a directory path")
    ledger_path = cfg.pop("ledger", None)
    nfold = intkey("nfold", "5", 2)
    early_stopping = intkey("early_stopping_rounds", "5", 0)
    hyper_batch = intkey("hyper_batch", "36", 1)
    seed = intkey("seed", "0", 0)
    top = intkey("top", "10", 1)
    engine = cfg.pop("engine", "auto")
    if engine not in ("auto", "fused", "host"):
        raise die(f"engine must be auto|fused|host, got {engine!r}")
    unknown = sorted(k for k in cfg
                     if k.lower() not in _ALIASES
                     and k.lower() not in _FRAMEWORK_KEYS)
    if unknown:
        raise die(f"unknown key(s): {', '.join(unknown)}")
    params = dict(cfg)
    rounds = _resolve_num_rounds(params, 100)

    import lightgbm_tpu as lgb

    from .sweep import SweepService

    data, names = _load_table(data_path, header)
    X, y = _split_label(data, names, label_spec)
    service = SweepService(
        grid, lgb.Dataset(X, label=y), base_params=params,
        num_boost_round=rounds, nfold=nfold,
        early_stopping_rounds=early_stopping, seed=seed, engine=engine,
        ledger_path=ledger_path, checkpoint_dir=ckpt_dir,
        n_devices=sweep_devices, group_size=sweep_group_size,
        hyper_batch=hyper_batch, verbose=True)
    result = service.run()
    if result.preempted:
        pend = len(result.ledger.pending())
        stderr.write(f"[lightgbm_tpu] sweep preempted ({result.error}); "
                     f"{pend}/{len(grid)} configs pending — rerun the "
                     f"same command line to resume\n")
        stderr.flush()
        return 0
    for row in result.ledger.leaderboard()[:top]:
        stdout.write(json.dumps(row) + "\n")
    stderr.write(json.dumps({
        "engine": result.engine, "units": result.units_total,
        "resumed_units": result.resumed_units,
        "configs": len(grid),
        "rounds_total": result.stats.get("rounds_total", 0),
    }) + "\n")
    stdout.flush()
    stderr.flush()
    return 0


def _failed_pending(e: Exception):
    from .serving import PendingPrediction

    p = PendingPrediction()
    p._set(error=e)
    return p


if __name__ == "__main__":
    sys.exit(main())

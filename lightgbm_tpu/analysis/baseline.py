"""Baseline (accepted-debt) handling for graftlint.

``analysis/baseline.toml`` holds ``[[suppress]]`` tables:

    [[suppress]]
    rule = "GL002"
    path = "lightgbm_tpu/serving/runtime.py"
    count = 1
    reason = "np.asarray at the dispatch boundary IS the host boundary"

Matching is count-based per (rule, path): the first ``count`` findings of
that rule in that file are suppressed, anything beyond is reported.  The
gate therefore starts green and only ratchets down — deleting debt shows
up as a *stale* suppression (count in the file exceeds reality), which the
CLI reports so the baseline can shrink but never silently grow.

Python 3.10 has no ``tomllib``, and the container must not grow deps, so
this module parses exactly the TOML subset the baseline uses: ``[[table]]``
headers, ``key = "string" | integer | true/false`` pairs, ``#`` comments.
Anything fancier is a hard error — the baseline is a ledger, not a config
language.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from .rules import RULE_IDS, Finding

_RULE_ID_RE = re.compile(r"GL\d{3}\Z")


@dataclass
class Suppression:
    rule: str
    path: str
    count: int
    reason: str
    used: int = 0


class BaselineError(ValueError):
    pass


def _parse_value(raw: str, lineno: int):
    raw = raw.strip()
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        return raw[1:-1]
    if raw in ("true", "false"):
        return raw == "true"
    try:
        return int(raw)
    except ValueError:
        raise BaselineError(
            f"baseline line {lineno}: unsupported value {raw!r} "
            f"(strings, ints, booleans only)") from None


def parse_baseline(text: str) -> List[Suppression]:
    """Parse the ``[[suppress]]`` TOML subset (see module docstring)."""
    tables: List[Dict[str, object]] = []
    current: Dict[str, object] = {}
    in_suppress = False
    for lineno, line in enumerate(text.splitlines(), 1):
        # strip comments, but not inside quoted strings
        if '"' in line:
            q = False
            for i, ch in enumerate(line):
                if ch == '"':
                    q = not q
                elif ch == "#" and not q:
                    line = line[:i]
                    break
        else:
            line = line.split("#", 1)[0]
        line = line.strip()
        if not line:
            continue
        if line.startswith("[["):
            if line != "[[suppress]]":
                raise BaselineError(
                    f"baseline line {lineno}: only [[suppress]] tables "
                    f"are allowed, got {line!r}")
            if in_suppress:
                tables.append(current)
            current = {}
            in_suppress = True
            continue
        if line.startswith("["):
            raise BaselineError(
                f"baseline line {lineno}: plain [table] headers are not "
                f"part of the baseline format")
        if "=" not in line:
            raise BaselineError(
                f"baseline line {lineno}: expected key = value, got "
                f"{line!r}")
        if not in_suppress:
            raise BaselineError(
                f"baseline line {lineno}: key outside a [[suppress]] "
                f"table")
        k, v = line.split("=", 1)
        current[k.strip()] = _parse_value(v, lineno)
    if in_suppress:
        tables.append(current)

    out: List[Suppression] = []
    for i, t in enumerate(tables, 1):
        missing = {"rule", "path", "reason"} - set(t)
        if missing:
            raise BaselineError(
                f"baseline [[suppress]] #{i}: missing keys "
                f"{sorted(missing)}")
        count = t.get("count", 1)
        if not isinstance(count, int) or count < 1:
            raise BaselineError(
                f"baseline [[suppress]] #{i}: count must be a positive "
                f"integer")
        if not str(t["reason"]).strip():
            raise BaselineError(
                f"baseline [[suppress]] #{i}: reason must be non-empty — "
                f"accepted debt needs a justification")
        rule = str(t["rule"])
        # r20: a malformed or unknown rule id would suppress NOTHING and
        # sit in the ledger forever looking like accepted debt — reject
        # it at parse time, same as any other format error
        if not _RULE_ID_RE.match(rule):
            raise BaselineError(
                f"baseline [[suppress]] #{i}: malformed rule id {rule!r} "
                f"(expected GLxxx)")
        if rule not in RULE_IDS and rule != "GL000":
            raise BaselineError(
                f"baseline [[suppress]] #{i}: unknown rule id {rule!r} "
                f"(known: {', '.join(RULE_IDS)})")
        if rule == "GL000":
            raise BaselineError(
                f"baseline [[suppress]] #{i}: GL000 (parse failure) is "
                f"never baselineable — a tree that does not parse fails "
                f"the gate, full stop")
        out.append(Suppression(rule=rule, path=str(t["path"]),
                               count=count, reason=str(t["reason"])))
    return out


@dataclass
class BaselineResult:
    unsuppressed: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    stale: List[Suppression] = field(default_factory=list)


def apply_baseline(findings: List[Finding],
                   suppressions: List[Suppression]) -> BaselineResult:
    """Split findings into unsuppressed/suppressed; report stale entries."""
    budget: Dict[Tuple[str, str], List[Suppression]] = {}
    for s in suppressions:
        budget.setdefault((s.rule, s.path), []).append(s)
    res = BaselineResult()
    for f in findings:
        for s in budget.get((f.rule, f.path), []):
            if s.used < s.count:
                s.used += 1
                res.suppressed.append(f)
                break
        else:
            res.unsuppressed.append(f)
    res.stale = [s for s in suppressions if s.used < s.count]
    return res

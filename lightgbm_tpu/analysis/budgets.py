"""graftlint Layer 2 — trace-level invariant checks.

Three invariant families, all declarative so the bench, the tests and the
lint gate consume ONE model instead of three hand-synced copies:

* :data:`LAUNCH_BUDGETS` — per-entry-point kernel-launch budgets.  Each
  spec lowers a public entry point (strict grower split iteration,
  fused-CV round, packed-forest predict) to compiled HLO on this host and
  counts fusion/custom-call instructions in the dominant loop body — the
  r4/r5 lesson that the training floor is launch count, not FLOPs.
* :data:`RECOMPILE_SPECS` — zero-recompile guarantees.  The serving
  runtime must hold at most ``log2(max_bucket)+1`` programs across a
  batch-size sweep, and the fused train step must hold ONE program across
  different hyper-parameter batches and segment bounds (hyperparameters
  are traced values, not static).
* VMEM footprints live in :mod:`lightgbm_tpu.analysis.vmem` (pure math,
  no compilation — they run in the default ``lint`` pass).

The split-iteration HLO machinery moved here from ``tools/hlo_counts.py``
(r7), which is now a thin re-export shim so there is exactly one
launch-count model.

Everything JAX-touching imports lazily: Layer 1 linting must not pay for
an accelerator stack import.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# compiled-HLO op counting (canonical home; tools/hlo_counts.py re-exports)
# ---------------------------------------------------------------------------


def compiled_text(fn, *args):
    import jax

    return jax.jit(fn).lower(*args).compile().as_text()


def fusion_count(txt: str) -> int:
    return len(re.findall(r" fusion\(", txt))


def custom_call_count(txt: str) -> int:
    # instruction form only ("= ... custom-call(...)") — bare
    # "custom-call" also appears in get-tuple-element operand types
    return len(re.findall(r" custom-call\(", txt))


def while_body_counts(txt: str):
    """Per while-body (fusions, custom_calls, chars) from compiled HLO."""
    out = {}
    for b in set(re.findall(r"body=%?([\w.\-]+)", txt)):
        m = re.search(r"(?m)^(%?" + re.escape(b)
                      + r" \([^\n]*\n(?:.*\n)*?)(?=^\}|^%|^ENTRY)", txt)
        if m:
            blk = m.group(1)
            out[b] = (len(re.findall(r" fusion\(", blk)),
                      len(re.findall(r" custom-call\(", blk)), len(blk))
    return out


def main_body_counts(txt: str):
    """(fusions, custom_calls) of the LARGEST while body — the growth
    loop dominates every grower program."""
    bodies = while_body_counts(txt)
    if not bodies:
        return fusion_count(txt), custom_call_count(txt)
    f, c, _ = max(bodies.values(), key=lambda v: v[2])
    return f, c


# ---------------------------------------------------------------------------
# tiny synthetic fixtures (never touch real data; shapes stay cheap on CPU)
# ---------------------------------------------------------------------------


def _grow_fixture(num_features=7, num_bins=16, n=4096, e=None, seed=0):
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.RandomState(seed)
    bins = jnp.asarray(rng.randint(0, num_bins, size=(n, num_features)),
                       jnp.int32)
    shape = (n,) if e is None else (e, n)
    g = jnp.asarray(rng.randn(*shape).astype(np.float32))
    ones = jnp.ones(shape, jnp.float32)
    stats = jnp.stack([g, ones, ones], -1)
    fmask = jnp.ones(num_features, jnp.float32)
    return bins, stats, fmask


def split_iter_counts(fuse_split: bool, e=None, num_leaves=31,
                      num_bins=16, n=4096, stub=False, num_features=7):
    """(fusions, custom_calls) per split iteration of the strict grower
    (``e=None``) or the E-batched fused-CV tree growth (``e=E``).

    ``stub=True`` swaps the Pallas mega-kernel for a pure_callback so the
    body compiles to XLA-side fusions + ONE custom-call — the launch
    structure a TPU build has (interpret-mode Pallas INLINES the kernel
    on CPU, inflating the fused count)."""
    import jax
    import jax.numpy as jnp

    from ..models import tree as tree_mod
    from ..models.tree import grow_tree
    from ..ops.split import SplitContext

    bins, stats, fmask = _grow_fixture(num_features=num_features,
                                       num_bins=num_bins, n=n, e=e)
    ctx = SplitContext(jnp.float32(0.0), jnp.float32(1.0), jnp.float32(3.0),
                       jnp.float32(1e-3), jnp.float32(0.0))

    def grow(s):
        return grow_tree(bins, s, fmask, ctx, num_leaves, num_bins, 0,
                         fuse_split=fuse_split)

    fn = (lambda: grow(stats)) if e is None else (
        lambda: jax.vmap(grow)(stats))
    old = tree_mod._SPLIT_ITER_OPCOUNT_STUB
    tree_mod._SPLIT_ITER_OPCOUNT_STUB = stub and fuse_split
    try:
        txt = compiled_text(fn)
    finally:
        tree_mod._SPLIT_ITER_OPCOUNT_STUB = old
    return main_body_counts(txt)


def tiny_packed_forest(num_trees: int = 3, num_features: int = 2):
    """A hand-built, validated PackedForest: one root split per tree.

    Deterministic and instant — the serving budget/recompile specs must
    not pay a training run to measure a predict program."""
    import numpy as np

    from ..dataset import BinMapper
    from ..serving.packed import PackedForest

    t, m = num_trees, 3
    split_feature = np.zeros((t, m), np.int32)
    split_bin = np.zeros((t, m), np.int32)          # go left on bin 0
    left = np.full((t, m), -1, np.int32)
    right = np.full((t, m), -1, np.int32)
    left[:, 0], right[:, 0] = 1, 2
    is_leaf = np.zeros((t, m), bool)
    is_leaf[:, 1:] = True
    leaf_value = np.zeros((t, m), np.float32)
    leaf_value[:, 1], leaf_value[:, 2] = -0.5, 0.5
    mapper = BinMapper(
        upper_bounds=[np.asarray([0.5]) for _ in range(num_features)],
        nan_bin=np.full(num_features, -1, np.int32),
        n_bins=np.full(num_features, 2, np.int32))
    return PackedForest(
        split_feature=split_feature, split_bin=split_bin,
        left=left, right=right, leaf_value=leaf_value, is_leaf=is_leaf,
        is_cat_split=None, cat_mask=None, shrink=1.0,
        init_score=np.zeros(1, np.float32), num_class=1,
        best_iteration=num_trees, depth_cap=1,
        params={"objective": "regression"},
        bin_mapper_dict=mapper.to_dict()).validate()


def serving_predict_counts(bucket: int = 8, stub: bool = False):
    """(fusions, custom_calls) of one packed-forest predict program at a
    fixed bucket shape — the whole program.

    r18: the device path is the fused predict mega-kernel
    (``ops.predict.predict_forest_pallas``).  ``stub=True`` swaps the
    Pallas call for a pure_callback so the CPU-compiled HLO shows the
    launch structure a TPU build has — XLA-side fusions plus ONE
    custom-call per class (interpret-mode Pallas INLINES the kernel
    body on CPU, inflating the fused count the same way the grower
    stub fixes)."""
    import jax.numpy as jnp

    from ..ops import predict as predict_mod
    from ..serving.runtime import PredictorRuntime

    rt = PredictorRuntime(tiny_packed_forest(), max_bucket=max(bucket, 1),
                          donate=False)
    codes = jnp.zeros((bucket, rt.packed.num_feature()), jnp.int32)
    mask = jnp.ones((bucket,), jnp.float32)
    fn = rt._build_fn(raw_score=False)
    old = predict_mod._PREDICT_OPCOUNT_STUB
    predict_mod._PREDICT_OPCOUNT_STUB = stub
    try:
        txt = fn.lower(codes, mask,
                       jnp.int32(rt.packed.num_trees)).compile().as_text()
    finally:
        predict_mod._PREDICT_OPCOUNT_STUB = old
    return fusion_count(txt), custom_call_count(txt)


def kernels_per_round_summary(e=40, num_leaves=31):
    """The bench-artifact dict: per-split-iteration launch counts for the
    fused-CV bucket shape, CPU-measured plus the TPU launch model —
    cross-referenced against the declarative budgets so BENCH artifacts
    and the lint gate cannot disagree."""
    unf_f, unf_c = split_iter_counts(False, e=e, num_leaves=num_leaves)
    cpu_f, cpu_c = split_iter_counts(True, e=e, num_leaves=num_leaves)
    xla_f, xla_c = split_iter_counts(True, e=e, num_leaves=num_leaves,
                                     stub=True)
    iters = num_leaves - 1
    model = xla_f + xla_c
    # r4's TPU-measured per-split-iteration launch count at this bucket
    # shape (PERF.md "Result: 49 fusions + 1 custom-call per split
    # iteration"; the "~1,500 kernels/round" exec floor)
    r4_per_iter = 50
    budget = budget_by_name("cv_tpu_model").budget
    return {
        "split_iter_kernels_r4_baseline": r4_per_iter,
        "split_iter_kernels_unfused_cpu": unf_f + unf_c,
        "split_iter_kernels_fused_cpu_inlined": cpu_f + cpu_c,
        "split_iter_kernels_tpu_model": model,
        "split_iter_budget_tpu_model": budget,
        "split_iter_within_budget": bool(model <= budget),
        "kernels_per_round_r4_baseline": r4_per_iter * iters,
        "kernels_per_round_unfused_cpu": (unf_f + unf_c) * iters,
        "kernels_per_round": model * iters,
        "kernels_per_round_budget": budget * iters,
        "kernels_per_round_drop_x": round(r4_per_iter / model, 2),
        "kernels_per_round_drop_x_vs_cpu_unfused":
            round((unf_f + unf_c) / model, 2),
    }


# ---------------------------------------------------------------------------
# declarative launch budgets
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LaunchBudget:
    """One entry point, one measured launch count, one ceiling.

    ``kind`` selects the measurement: ``split_iter`` lowers the grower
    (strict when ``e is None``, E-batched fused-CV otherwise, Pallas
    swapped for a pure_callback when ``stub`` — the TPU launch model);
    ``serving_predict`` lowers the packed-forest bucket program.
    Budgets are measured values + ~25% headroom, never aspirations.
    """

    name: str
    budget: int
    kind: str = "split_iter"            # "split_iter" | "serving_predict"
    fuse_split: bool = True
    e: Optional[int] = None
    stub: bool = False
    bucket: int = 8
    num_features: int = 7               # grower fixture column count (r20:
    #   a compacted width proves screening shrinks SHAPES, not launches)
    note: str = ""

    def measure(self) -> int:
        if self.kind == "split_iter":
            f, c = split_iter_counts(self.fuse_split, e=self.e,
                                     stub=self.stub,
                                     num_features=self.num_features)
        elif self.kind == "serving_predict":
            f, c = serving_predict_counts(self.bucket, stub=self.stub)
        else:
            raise ValueError(f"unknown budget kind {self.kind!r}")
        return f + c

    def check(self) -> Dict[str, object]:
        measured = self.measure()
        return {"name": self.name, "kind": self.kind,
                "measured": measured, "budget": self.budget,
                "ok": measured <= self.budget, "note": self.note}


# Measured on the r7 jax pin: strict (23 unfused / 45 fused-inlined /
# 5+1 stub), E-batched (21 / 53 / 5+1); E=8 compiles ~5x faster than the
# production E=40 bucket with IDENTICAL per-iteration body counts
# (vmapped ops don't multiply with batch size) — verified against E=40
# when the budget was set.
LAUNCH_BUDGETS: Tuple[LaunchBudget, ...] = (
    LaunchBudget("strict_unfused", 29, fuse_split=False,
                 note="strict grower, r6 unfused split iteration"),
    LaunchBudget("strict_fused_cpu", 56,
                 note="interpret-mode Pallas inlined; CPU regression pin"),
    LaunchBudget("strict_tpu_model", 8, stub=True,
                 note="XLA fusions + 1 mega-kernel custom-call = TPU "
                      "launches per split iteration"),
    LaunchBudget("strict_screened_tpu_model", 8, stub=True,
                 num_features=2,
                 note="r20 screened round at compacted F_active: the "
                      "SAME launch ceiling as the full-width strict "
                      "model — screening shrinks kernel shapes and "
                      "payloads, never the launch structure"),
    LaunchBudget("cv_unfused", 27, fuse_split=False, e=8,
                 note="fused-CV hyper-batch, unfused split iteration"),
    LaunchBudget("cv_fused_cpu", 66, e=8,
                 note="interpret-mode Pallas inlined; CPU regression pin"),
    LaunchBudget("cv_tpu_model", 8, e=8, stub=True,
                 note="the r7 tentpole: >=3x drop vs the 50/iter r4 "
                      "TPU-measured baseline"),
    LaunchBudget("serving_predict_b8", 12, kind="serving_predict",
                 bucket=8,
                 note="fused predict bucket program, interpret-mode "
                      "Pallas inlined; CPU regression pin (measured 10 "
                      "at the r18 switch to the mega-kernel; the legacy "
                      "per-node program measured 3 on the r8 pin)"),
    LaunchBudget("serving_predict_tpu_model", 5, kind="serving_predict",
                 bucket=8, stub=True,
                 note="XLA fusions + 1 mega-kernel custom-call per "
                      "class = TPU launches per dispatch (measured 3+1 "
                      "at r18); depth-INDEPENDENT — the r14 per-node "
                      "path launched its traversal fusions once per "
                      "depth step"),
)


def budget_by_name(name: str) -> LaunchBudget:
    for b in LAUNCH_BUDGETS:
        if b.name == name:
            return b
    raise KeyError(name)


def check_launch_budgets(names: Optional[List[str]] = None
                         ) -> List[Dict[str, object]]:
    specs = (LAUNCH_BUDGETS if names is None
             else [budget_by_name(n) for n in names])
    return [b.check() for b in specs]


# ---------------------------------------------------------------------------
# zero-recompile guarantees
# ---------------------------------------------------------------------------


def jit_cache_size(fn) -> int:
    """Compiled-program count held by a jax.jit wrapper."""
    size = getattr(fn, "_cache_size", None)
    if callable(size):
        return int(size())
    raise RuntimeError(
        "this jax version exposes no jit cache-size probe; the recompile "
        "specs need jax>=0.4 (PjitFunction._cache_size)")


def serving_recompile_sweep(max_bucket: int = 64) -> Dict[str, object]:
    """Sweep every batch size in [1, max_bucket] through the serving
    runtime; the bucket ladder bounds compiles at log2(max_bucket)+1 and
    a second identical sweep must compile NOTHING."""
    import numpy as np

    rt = None
    try:
        from ..serving.runtime import PredictorRuntime

        rt = PredictorRuntime(tiny_packed_forest(), max_bucket=max_bucket,
                              donate=False)
        rng = np.random.RandomState(0)
        sizes = sorted({1, 2, 3, max_bucket}
                       | {int(x) for x in rng.randint(1, max_bucket + 1,
                                                      size=12)})
        for n in sizes:
            rt.predict(rng.randn(n, rt.packed.num_feature()))
        first = rt.num_compiles
        for n in sizes:
            rt.predict(rng.randn(n, rt.packed.num_feature()))
        second = rt.num_compiles - first
    finally:
        del rt
    limit = max_bucket.bit_length()                # log2(max_bucket) + 1
    return {"name": f"serving_sweep_b{max_bucket}",
            "compiles": first, "recompiles_on_repeat": second,
            "max_compiles": limit,
            "ok": first <= limit and second == 0,
            "note": "bucket ladder: <= log2(max_bucket)+1 programs, "
                    "repeat sweep hits cache only"}


def serving_warm_recompile(max_bucket: int = 16) -> Dict[str, object]:
    """r18 warm-coverage guarantee on a QUANTIZED runtime: ``warm()``
    keys on the FULL compile key ``(bucket, raw_score, route)``, so
    after warming both raw_score settings every traffic-path program
    already exists — a sweep over all buckets and both settings
    compiles NOTHING.  With >=2 devices visible the runtime gets a dp
    mesh so shard programs ride the same contract; on a single-device
    host the spec degrades to the "single" route (the dp/tp coverage
    then lives in tests/test_predict_fused.py under the virtual mesh)."""
    import numpy as np

    rt = None
    try:
        import jax

        from ..serving.runtime import PredictorRuntime

        meshed = jax.local_device_count() >= 2
        kw = ({"mesh_devices": 2, "shard_policy": "dp"} if meshed else {})
        rt = PredictorRuntime(tiny_packed_forest(), max_bucket=max_bucket,
                              donate=False, forest_precision="int8", **kw)
        warmed = rt.warm(raw_score=False) + rt.warm(raw_score=True)
        keys = len(rt.warmed_keys)
        before = rt.num_compiles
        rng = np.random.RandomState(1)
        sizes = sorted({1, 2, max_bucket}
                       | {int(x) for x in rng.randint(1, max_bucket + 1,
                                                      size=8)})
        for n in sizes:
            for raw in (False, True):
                rt.predict(rng.randn(n, rt.packed.num_feature()),
                           raw_score=raw)
        traffic = rt.num_compiles - before
    finally:
        del rt
    limit = 2 * max_bucket.bit_length()     # 2 raw_score x bucket ladder
    return {"name": f"serving_warm_full_key_b{max_bucket}"
                    + ("_dp" if meshed else ""),
            "compiles": warmed, "warmed_keys": keys,
            "recompiles_on_repeat": traffic, "max_compiles": limit,
            "ok": warmed <= limit and keys == warmed and traffic == 0,
            "note": "int8 warm() covers the full (bucket, raw_score, "
                    "route) key: zero traffic-path compiles after warm"}


def fused_train_step_recompiles(n_hyper_batches: int = 3
                                ) -> Dict[str, object]:
    """Drive the fused-CV train step with ``n_hyper_batches`` different
    hyper-parameter batches (and segment bounds) at one data shape: the
    r6 invariant is that hyperparameters and seg_end are TRACED, so the
    program compiles once and every batch reuses it."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..config import parse_params
    from ..models.fused import _fused_cv_fn
    from ..models.gbdt import HyperScalars, _objective_static_key
    from ..objectives import create_objective

    p = parse_params({"objective": "regression"}, warn_unknown=False)
    obj = create_objective(p)
    n, num_features, num_bins, num_leaves = 256, 4, 16, 7
    run_segment, init_carry, _ = _fused_cv_fn(
        _objective_static_key(obj, p), num_leaves, num_bins,
        "l2", float(p.alpha), float(p.tweedie_variance_power),
        t_max=6, bagging_freq=0, n_configs=1, n_folds=1,
        hist_impl="auto", row_chunk=131072)

    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, num_bins, size=(n, num_features)),
                       jnp.int32)
    y = jnp.asarray(rng.randn(n).astype(np.float32))
    w = jnp.ones(n, jnp.float32)
    masks = jnp.ones((1, n), jnp.float32)

    def hyper(lr: float, l2: float) -> HyperScalars:
        one = jnp.ones((1,), jnp.float32)
        return HyperScalars(
            learning_rate=one * lr, lambda_l1=one * 0.0,
            lambda_l2=one * l2, min_data_in_leaf=one * 5.0,
            min_sum_hessian=one * 1e-3, min_gain_to_split=one * 0.0,
            max_depth=jnp.zeros((1,), jnp.int32),
            feature_fraction_bynode=one, top_rate=one * 0.2,
            other_rate=one * 0.1, max_delta_step=one * 0.0,
            path_smooth=one * 0.0, linear_lambda=one * 0.0)

    before = jit_cache_size(run_segment)
    for i in range(n_hyper_batches):
        carry = init_carry(n, jnp.zeros((1,), jnp.float32))
        carry = carry._replace(bag=masks)
        carry = run_segment(
            carry, jnp.int32(2 + i), bins, y, w, masks, masks,
            hyper(0.05 * (i + 1), 0.1 * i), jnp.ones((1,), jnp.float32),
            jnp.ones((1,), jnp.float32),
            jnp.full((1,), float(n), jnp.float32), jnp.int32(0),
            jnp.zeros((1,), jnp.float32), jax.random.PRNGKey(i))
        jax.block_until_ready(carry.r)  # graftlint: GL002 — probe sync
    compiles = jit_cache_size(run_segment) - before
    # `before` can be nonzero when an identical static config already ran
    # in-process (the lru_cached builder shares run_segment) — the
    # invariant is that the SWEEP adds at most one program.
    return {"name": f"fused_train_step_x{n_hyper_batches}",
            "compiles": compiles, "max_compiles": 1,
            "ok": compiles <= 1,
            "note": "hyperparameters + seg_end traced: one program "
                    "across hyper-parameter batches"}


def check_recompile_specs(serving_max_bucket: int = 64,
                          n_hyper_batches: int = 3
                          ) -> List[Dict[str, object]]:
    return [serving_recompile_sweep(serving_max_bucket),
            serving_warm_recompile(),
            fused_train_step_recompiles(n_hyper_batches)]


# ---------------------------------------------------------------------------
# histogram-merge communication budgets (r9)
# ---------------------------------------------------------------------------
#
# Per-round bytes RECEIVED per shard for one merged histogram wave — the
# quantity the r9 reduce-scatter tentpole shrinks.  A full psum
# (allreduce) must deliver the ENTIRE [S, F, B, 3] merged histogram to
# every shard; a reduce-scatter delivers only that shard's F/D feature
# slice, because split finding then runs on the slice and only an O(D)
# BestSplit all-gather follows.  The BestSplit gather is ~64 B/shard and
# is charged to every mode, so it never flatters the ratio.
#
# Ring-transfer view (documented, not budgeted): counting bytes MOVED on
# the wire per shard, allreduce = 2(D-1)/D * H vs reduce-scatter =
# (D-1)/D * H — only a 2x drop.  The received-bytes model is the honest
# one for THIS design because the psum baseline materialises the full
# histogram in every shard's memory and the split iteration there reads
# all of it, while the reduce-scatter path never materialises more than
# the slice.  Both numbers appear in the check result.


_WIRE_BYTES = {"f32": 4, "bf16": 2, "int8": 1}


def hist_merge_comm_bytes(mode: str, n_shards: int, num_features: int,
                          num_bins: int, num_segments: int,
                          top_k: int = 20, dtype_bytes: int = 4,
                          wire_dtype: str = "f32", n_chunks: int = 4
                          ) -> Dict[str, int]:
    """Modeled communication for ONE merged histogram wave.

    Returns received bytes per shard plus the ring wire-transfer bytes
    for the same payload.  ``num_segments`` is the wave width (leaves
    scored per merge); histograms are ``[S, F, B, 3]`` ``dtype_bytes``
    cells.  ``voting`` charges the votes psum (int32 per feature per
    segment) plus the reduce-scatter over the padded candidate union
    ``Kc = min(2*top_k, F)``.

    r10 additions, mirroring ``ops.histogram.histogram_merge``:
    ``"reduce_scatter_pipelined"`` pads the feature axis to a
    ``D * n_chunks`` multiple (slightly wider slice, same asymptotics);
    ``wire_dtype`` shrinks ring-hop cells to 2 B (bf16) or 1 B (int8 —
    plus one 12 B scale sidecar per hop message per chunk) and is only
    meaningful for the ring modes, where per-hop messages exist.
    """
    d = max(int(n_shards), 1)
    cell = num_bins * 3 * dtype_bytes
    full = num_segments * num_features * cell
    bestsplit = d * 16 * dtype_bytes       # O(D) BestSplit all-gather
    ring_modes = ("reduce_scatter_ring", "reduce_scatter_pipelined")
    if wire_dtype not in _WIRE_BYTES:
        raise ValueError(f"unknown wire dtype {wire_dtype!r}")
    if wire_dtype != "f32" and mode not in ring_modes:
        raise ValueError(
            f"wire_dtype={wire_dtype!r} models ring-hop compression and "
            f"needs a ring merge mode, not {mode!r}")
    if mode == "psum":
        recv = full
        wire = (2 * (d - 1) * full) // d
    elif mode == "reduce_scatter" or mode in ring_modes:
        chunks = max(int(n_chunks), 1) \
            if mode == "reduce_scatter_pipelined" else 1
        mult = d * chunks
        f_pad = -(-num_features // mult) * mult
        wcell = num_bins * 3 * _WIRE_BYTES[wire_dtype]
        # int8 hop messages carry a 12 B (3 f32 stats) scale sidecar per
        # FEATURE: (d-1)*chunks messages of f_pad/(d*chunks) features each
        sidecar = ((d - 1) * (f_pad // d) * 12
                   if wire_dtype == "int8" else 0)
        recv = num_segments * (f_pad // d) * wcell + sidecar
        wire = ((d - 1) * num_segments * f_pad * wcell) // d + sidecar
    elif mode == "voting":
        kc = min(2 * max(int(top_k), 1), num_features)
        kc_pad = -(-kc // d) * d
        votes = num_segments * num_features * 4
        recv = votes + num_segments * (kc_pad // d) * cell
        wire = (2 * (d - 1) * votes) // d \
            + ((d - 1) * num_segments * kc_pad * cell) // d
    else:
        raise ValueError(f"unknown histogram merge mode {mode!r}")
    return {"received_bytes_per_shard": recv + bestsplit,
            "ring_wire_bytes_per_shard": wire + bestsplit}


@dataclass(frozen=True)
class CommBudget:
    """One merge mode at one reference shape, one minimum drop vs psum.

    Pure arithmetic — no lowering, no devices — so these run in the
    default ``lint`` pass next to the VMEM estimates.  ``min_drop_x`` is
    the floor on ``psum_received / mode_received`` at the reference
    shape; the r9 acceptance bar is >=4x at D=8.
    """

    name: str
    mode: str
    min_drop_x: float
    n_shards: int = 8
    num_features: int = 136
    num_bins: int = 256
    num_segments: int = 2
    top_k: int = 20
    wire_dtype: str = "f32"
    n_chunks: int = 4
    # When set, drop_x is measured against this fixed byte count instead
    # of the modeled psum at the same shape — used to pin the int8-wire
    # gate to r9's shipped reduce-scatter figure (104,960 B/shard).
    baseline_bytes: Optional[int] = None
    note: str = ""

    def check(self) -> Dict[str, object]:
        base = hist_merge_comm_bytes(
            "psum", self.n_shards, self.num_features, self.num_bins,
            self.num_segments, self.top_k)
        ours = hist_merge_comm_bytes(
            self.mode, self.n_shards, self.num_features, self.num_bins,
            self.num_segments, self.top_k,
            wire_dtype=self.wire_dtype, n_chunks=self.n_chunks)
        ref = (self.baseline_bytes if self.baseline_bytes is not None
               else base["received_bytes_per_shard"])
        drop = ref / ours["received_bytes_per_shard"]
        return {"name": self.name, "mode": self.mode,
                "psum_bytes": ref,
                "measured": ours["received_bytes_per_shard"],
                "ring_wire_bytes": ours["ring_wire_bytes_per_shard"],
                "budget": int(ref / self.min_drop_x),
                "drop_x": round(drop, 2), "min_drop_x": self.min_drop_x,
                "ok": drop >= self.min_drop_x, "note": self.note}


# Reference shape = the r9 acceptance scenario: D=8, ragged F=136
# (17/shard), B=256, wave of 2 leaves.  psum receives 835,584 B/shard
# there; reduce-scatter 104,448 B/shard (the F/D slice) — an 8x drop,
# budgeted at the >=4x acceptance floor so a topology regression (e.g.
# an accidental all-gather after the scatter) trips the gate before it
# ships.
COMM_BUDGETS: Tuple[CommBudget, ...] = (
    CommBudget("hist_rs_d8", "reduce_scatter", 4.0,
               note="r9 tentpole: F/D feature slice per shard"),
    CommBudget("hist_rs_ring_d8", "reduce_scatter_ring", 4.0,
               note="ppermute ring, same received payload as psum_scatter"),
    CommBudget("hist_voting_d8", "voting", 4.0,
               note="PV-Tree: votes psum + 2k-candidate union scatter"),
    # r10: pipelined chunked ring.  C=4 pads F=136 -> 160 (D*C multiple),
    # so the slice widens from 17 to 20 features/shard — still a 6.8x
    # drop vs psum, budgeted at the same >=4x floor.
    CommBudget("hist_rs_pipelined_d8", "reduce_scatter_pipelined", 4.0,
               note="r10 tentpole: chunked ring, f32 wire, C=4"),
    # r10: int8 wire vs the r9 shipped reduce-scatter received figure
    # (104,960 B/shard incl. the BestSplit all-gather).  ISSUE acceptance
    # asks >=2x; the model gives 3.3x (1 B cells + 12 B scale sidecars).
    CommBudget("hist_wire_int8_d8", "reduce_scatter_pipelined", 2.0,
               wire_dtype="int8", baseline_bytes=104_960,
               note="quantized wire vs r9 rs bytes (104,960 B/shard)"),
)


def comm_budget_by_name(name: str) -> CommBudget:
    for b in COMM_BUDGETS:
        if b.name == name:
            return b
    raise KeyError(name)


def check_comm_budgets(names: Optional[List[str]] = None
                       ) -> List[Dict[str, object]]:
    specs = (COMM_BUDGETS if names is None
             else [comm_budget_by_name(n) for n in names])
    return [b.check() for b in specs]


# ---------------------------------------------------------------------------
# Comm TIME model (r10): bytes -> milliseconds, overlap -> hidden fraction
# ---------------------------------------------------------------------------
# Pinned modeling constants.  These are *model* numbers, not measurements
# from this host (the CI harness is a CPU-device proxy; BENCH_SELF_r07 ms
# are CPU wall-clock and say nothing about ICI).  Provenance:
#   ICI_BYTES_PER_S   — order of a single v4/v5 ICI link's usable
#                       bandwidth (~45 GB/s); the model only needs the
#                       order of magnitude since the reference point is
#                       compute-bound by ~100x (see below).
#   ICI_HOP_LATENCY_S — per-ppermute-message launch+flight overhead, 1 us.
#   MXU_EFF_FLOPS     — sustained one-hot-matmul rate used for the
#                       histogram build, 20 TFLOP/s (well under peak;
#                       the r7 self-bench showed the build is the
#                       kernel-bound term of the round).
#   REF_ROWS_PER_SHARD — one row_chunk of the fused kernel (131072 rows),
#                       the per-wave work unit the merge overlaps with.
ICI_BYTES_PER_S = 45e9
ICI_HOP_LATENCY_S = 1e-6
MXU_EFF_FLOPS = 2.0e13
REF_ROWS_PER_SHARD = 131072


def hist_merge_comm_time(mode: str, n_shards: int, num_features: int,
                         num_bins: int, num_segments: int,
                         top_k: int = 20, wire_dtype: str = "f32",
                         n_chunks: int = 4,
                         rows_per_shard: int = REF_ROWS_PER_SHARD
                         ) -> Dict[str, float]:
    """Modeled wall-clock for one merged wave: comm vs overlapped compute.

    Extends :func:`hist_merge_comm_bytes` from a bytes model to a time
    model.  Comm time charges the ring wire bytes at ``ICI_BYTES_PER_S``
    plus ``ICI_HOP_LATENCY_S`` per hop message.  Compute time is the
    wave's kernel-bound work — the one-hot histogram matmul,
    ``2 * rows * B * 3S * F`` FLOPs at ``MXU_EFF_FLOPS`` — which is what
    the pipelined merge interleaves with (ring steps for chunk ``k``
    behind build/scan compute for chunk ``k-1``).

    Non-pipelined modes sit in program order between build and scan, so
    their comm is fully exposed.  The pipelined mode's makespan is

        chunk_comm + (C-1) * max(chunk_comm, chunk_compute) + chunk_compute

    i.e. only the first chunk's wire time is exposed when the reference
    point is compute-bound; ``hidden_frac -> 1 - 1/C``.  At the
    D=8/F=136/B=256 reference the wave matmul is ~2.7 ms vs ~50 us of
    comm, so the verdict is robust to ~10x error in either constant.
    """
    d = max(int(n_shards), 1)
    chunks = (max(int(n_chunks), 1)
              if mode == "reduce_scatter_pipelined" else 1)
    b = hist_merge_comm_bytes(
        mode, n_shards, num_features, num_bins, num_segments,
        top_k=top_k, wire_dtype=wire_dtype, n_chunks=n_chunks)
    if mode == "psum":
        hops = 2 * (d - 1)          # allreduce = scatter + gather phases
    elif mode == "voting":
        hops = 2 * (d - 1) + (d - 1)
    else:
        hops = (d - 1) * chunks     # one ppermute message per hop/chunk
    comm_s = (b["ring_wire_bytes_per_shard"] / ICI_BYTES_PER_S
              + hops * ICI_HOP_LATENCY_S)
    flops = 2.0 * rows_per_shard * num_bins * 3 * num_segments \
        * num_features
    compute_s = flops / MXU_EFF_FLOPS
    if mode == "reduce_scatter_pipelined":
        cc = comm_s / chunks
        ck = compute_s / chunks
        makespan = cc + (chunks - 1) * max(cc, ck) + ck
        exposed_s = max(makespan - compute_s, 0.0)
    else:
        exposed_s = comm_s
    hidden_s = comm_s - exposed_s
    return {"comm_ms": comm_s * 1e3, "compute_ms": compute_s * 1e3,
            "exposed_ms": exposed_s * 1e3, "hidden_ms": hidden_s * 1e3,
            "hidden_frac": hidden_s / comm_s if comm_s > 0 else 0.0,
            "compute_bound": compute_s / max(chunks, 1)
            >= comm_s / max(chunks, 1)}


@dataclass(frozen=True)
class CommTimeBudget:
    """Floor on the hidden fraction of merge comm at a reference shape.

    The r10 acceptance bar: >=60% of per-round merge time hidden behind
    the fused kernels at D=8/F=136/B=256 under the ring-wire time model.
    """

    name: str
    mode: str
    min_hidden_frac: float
    n_shards: int = 8
    num_features: int = 136
    num_bins: int = 256
    num_segments: int = 2
    top_k: int = 20
    wire_dtype: str = "f32"
    n_chunks: int = 4
    rows_per_shard: int = REF_ROWS_PER_SHARD
    note: str = ""

    def check(self) -> Dict[str, object]:
        t = hist_merge_comm_time(
            self.mode, self.n_shards, self.num_features, self.num_bins,
            self.num_segments, top_k=self.top_k,
            wire_dtype=self.wire_dtype, n_chunks=self.n_chunks,
            rows_per_shard=self.rows_per_shard)
        frac = t["hidden_frac"]
        return {"name": self.name, "mode": self.mode,
                "measured": round(frac, 4),
                "budget": self.min_hidden_frac,
                "comm_ms": round(t["comm_ms"], 4),
                "exposed_ms": round(t["exposed_ms"], 4),
                "compute_ms": round(t["compute_ms"], 3),
                "ok": frac >= self.min_hidden_frac, "note": self.note}


COMM_TIME_BUDGETS: Tuple[CommTimeBudget, ...] = (
    CommTimeBudget("merge_hidden_pipelined_d8",
                   "reduce_scatter_pipelined", 0.60,
                   note="r10 acceptance: >=60% of merge time hidden"),
    CommTimeBudget("merge_hidden_pipelined_int8_d8",
                   "reduce_scatter_pipelined", 0.60, wire_dtype="int8",
                   note="int8 wire keeps the same overlap floor"),
)


def comm_time_budget_by_name(name: str) -> CommTimeBudget:
    for b in COMM_TIME_BUDGETS:
        if b.name == name:
            return b
    raise KeyError(name)


def check_comm_time_budgets(names: Optional[List[str]] = None
                            ) -> List[Dict[str, object]]:
    specs = (COMM_TIME_BUDGETS if names is None
             else [comm_time_budget_by_name(n) for n in names])
    return [b.check() for b in specs]


# ---------------------------------------------------------------------------
# Out-of-core streaming: PCIe/host-bandwidth time model (ISSUE 7)
# ---------------------------------------------------------------------------
# Same provenance rules as the ICI constants above — *model* numbers for
# the verdict's order of magnitude, not host measurements:
#   PCIE_BYTES_PER_S  — usable host->HBM bandwidth of a PCIe Gen4 x16-ish
#                       link (~16 GB/s); TPU host attach varies (some
#                       platforms stripe wider) but the reference point is
#                       compute-bound by ~2.5x, robust to that spread.
#   PCIE_PUT_LATENCY_S — per-device_put dispatch+setup overhead, ~20 us
#                       (host-side staging and transfer launch).
PCIE_BYTES_PER_S = 16e9
PCIE_PUT_LATENCY_S = 20e-6


def stream_prefetch_time(block_rows: int = REF_ROWS_PER_SHARD,
                         num_features: int = 136, num_bins: int = 256,
                         num_segments: int = 2, n_blocks: int = 8,
                         code_bytes: int = 1,
                         prefetch_blocks: int = 1) -> Dict[str, float]:
    """Modeled wall-clock for one streamed histogram pass: transfer vs
    overlapped compute under the double-buffered prefetcher.

    Per block the wire moves ``block_rows * F * code_bytes`` at
    ``PCIE_BYTES_PER_S`` (+ one ``device_put`` launch), while the compute
    term is the same per-chunk histogram matmul the merge model charges:
    ``2 * block_rows * B * 3S * F`` FLOPs at ``MXU_EFF_FLOPS``.  The
    prefetcher issues block k+1's put before consuming block k, so with
    async dispatch the makespan is

        transfer + (K-1) * max(transfer, compute) + compute

    — only the FIRST block's wire time is exposed when compute-bound, so
    ``hidden_frac -> 1 - 1/K``.  At the reference shape (131072-row
    uint8 blocks, F=136, B=256, S=2) transfer is ~1.1 ms/block vs
    ~2.7 ms/block of compute: comfortably hidden, and the verdict holds
    down to ~2.5x error in the bandwidth constant.

    ``prefetch_blocks`` (r19 satellite) is the configurable lookahead
    depth (``stream_prefetch_blocks``): with >=2 puts outstanding the
    NEXT put's host-side launch overhead overlaps the in-flight
    transfer's bytes, so steady state serializes only the link's byte
    time; depth 1 (double buffer, the default) exposes the launch
    latency on every block.  Deeper pipelines never hurt under this
    model — the link bandwidth is the invariant floor.
    """
    k = max(int(n_blocks), 1)
    depth = max(int(prefetch_blocks), 1)
    bytes_per_block = float(block_rows) * num_features * code_bytes
    byte_s = bytes_per_block / PCIE_BYTES_PER_S
    fill_s = byte_s + PCIE_PUT_LATENCY_S
    steady_s = byte_s + (PCIE_PUT_LATENCY_S if depth == 1 else 0.0)
    flops = 2.0 * block_rows * num_bins * 3 * num_segments * num_features
    compute_s = flops / MXU_EFF_FLOPS
    total_transfer_s = fill_s + (k - 1) * steady_s
    total_compute_s = k * compute_s
    makespan = (fill_s + (k - 1) * max(steady_s, compute_s)
                + compute_s)
    exposed_s = max(makespan - total_compute_s, 0.0)
    hidden_s = total_transfer_s - exposed_s
    return {"transfer_ms": total_transfer_s * 1e3,
            "compute_ms": total_compute_s * 1e3,
            "exposed_ms": exposed_s * 1e3,
            "hidden_ms": hidden_s * 1e3,
            "hidden_frac": (hidden_s / total_transfer_s
                            if total_transfer_s > 0 else 0.0),
            "compute_bound": compute_s >= steady_s}


@dataclass(frozen=True)
class StreamTimeBudget:
    """Floor on the hidden fraction of streamed-transfer time at a
    reference shape.

    The r11 acceptance bar: >=60% of per-pass PCIe time hidden behind
    the histogram kernels at the 131072x136 uint8 reference under the
    double-buffered prefetch model.
    """

    name: str
    min_hidden_frac: float
    block_rows: int = REF_ROWS_PER_SHARD
    num_features: int = 136
    num_bins: int = 256
    num_segments: int = 2
    n_blocks: int = 8
    code_bytes: int = 1
    prefetch_blocks: int = 1
    note: str = ""

    def check(self) -> Dict[str, object]:
        t = stream_prefetch_time(
            self.block_rows, self.num_features, self.num_bins,
            self.num_segments, n_blocks=self.n_blocks,
            code_bytes=self.code_bytes,
            prefetch_blocks=self.prefetch_blocks)
        frac = t["hidden_frac"]
        return {"name": self.name, "mode": "stream_prefetch",
                "measured": round(frac, 4),
                "budget": self.min_hidden_frac,
                "comm_ms": round(t["transfer_ms"], 4),
                "exposed_ms": round(t["exposed_ms"], 4),
                "compute_ms": round(t["compute_ms"], 3),
                "ok": frac >= self.min_hidden_frac, "note": self.note}


STREAM_TIME_BUDGETS: Tuple[StreamTimeBudget, ...] = (
    StreamTimeBudget("stream_prefetch_hidden_ref", 0.60,
                     note="r11 acceptance: >=60% of PCIe transfer hidden "
                          "behind the per-block histogram pass"),
    StreamTimeBudget("stream_prefetch_hidden_strict_ref", 0.60,
                     num_segments=2, n_blocks=16,
                     note="deeper stores only hide more (1 - 1/K)"),
    StreamTimeBudget("stream_prefetch_hidden_deep_ref", 0.60,
                     prefetch_blocks=2,
                     note="r19 satellite: depth-2 lookahead overlaps the "
                          "put launch latency too — modeled, not guessed"),
)


def stream_budget_by_name(name: str) -> StreamTimeBudget:
    for b in STREAM_TIME_BUDGETS:
        if b.name == name:
            return b
    raise KeyError(name)


def check_stream_budgets(names: Optional[List[str]] = None
                         ) -> List[Dict[str, object]]:
    specs = (STREAM_TIME_BUDGETS if names is None
             else [stream_budget_by_name(n) for n in names])
    return [b.check() for b in specs]


# ---------------------------------------------------------------------------
# Streamed x dp composition (r19): per-block-round merge overlap + the
# GOSS x wire combined byte model
# ---------------------------------------------------------------------------


def stream_dp_time_model(block_rows: int = REF_ROWS_PER_SHARD,
                         num_features: int = 136, num_bins: int = 256,
                         num_segments: int = 2,
                         n_blocks_per_shard: int = 8, n_shards: int = 8,
                         mode: str = "reduce_scatter_pipelined",
                         wire_dtype: str = "f32", n_chunks: int = 4,
                         code_bytes: int = 1,
                         prefetch_blocks: int = 1) -> Dict[str, float]:
    """Modeled wall-clock for ONE streamed-dp histogram pass: the r11
    PCIe prefetch pipeline composed with the r10 per-block-round ICI
    merge (data/stream_dp.py).

    Per block-round every shard (a) receives its next block over PCIe,
    (b) runs the per-block histogram kernel, and (c) ring-merges the
    partial — and the merge of block ``j`` flies while block ``j+1``'s
    prefetch + compute proceed, a three-stage pipeline:

        span = pcie_fill + (K-1) * max(pcie, compute, merge)
               + compute + merge [+ gather]

    Exposed merge time is what the merge ADDS over the merge-free r11
    makespan (``stream_prefetch_time``), plus — under the
    reduce-scatter modes — the ONE per-iteration all-gather of the
    feature-sharded accumulator back to the replicated update
    (``(D-1)/D`` of the f32 histogram; psum pays no gather but ships
    f32 every round).  At D=8/F=136/B=256 the per-block merge is tens
    of microseconds against ~2.7 ms of compute, so
    ``merge_hidden_frac -> 1 - 1/K`` minus the gather term — >=60%
    with margin, robust to ~10x error in either wire constant.
    """
    k = max(int(n_blocks_per_shard), 1)
    d = max(int(n_shards), 1)
    base = stream_prefetch_time(
        block_rows, num_features, num_bins, num_segments, n_blocks=k,
        code_bytes=code_bytes, prefetch_blocks=prefetch_blocks)
    b = hist_merge_comm_bytes(
        mode, d, num_features, num_bins, num_segments,
        wire_dtype=wire_dtype, n_chunks=n_chunks)
    chunks = (max(int(n_chunks), 1)
              if mode == "reduce_scatter_pipelined" else 1)
    if mode == "psum":
        hops = 2 * (d - 1)
    else:
        hops = (d - 1) * chunks
    merge_s = (b["ring_wire_bytes_per_shard"] / ICI_BYTES_PER_S
               + hops * ICI_HOP_LATENCY_S)
    pcie_byte_s = float(block_rows) * num_features * code_bytes \
        / PCIE_BYTES_PER_S
    steady_pcie_s = pcie_byte_s + (
        PCIE_PUT_LATENCY_S if max(int(prefetch_blocks), 1) == 1 else 0.0)
    fill_s = pcie_byte_s + PCIE_PUT_LATENCY_S
    compute_s = (2.0 * block_rows * num_bins * 3 * num_segments
                 * num_features) / MXU_EFF_FLOPS
    span = (fill_s + (k - 1) * max(steady_pcie_s, compute_s, merge_s)
            + compute_s + merge_s)
    # rs modes: ONE gather per split iteration of the (D-1)/D remote
    # f32 slice; psum returns replicated partials every round instead
    hist_f32_bytes = (float(num_features) * num_bins * 3 * num_segments
                      * 4)
    gather_s = (0.0 if mode == "psum"
                else hist_f32_bytes * (d - 1) / d / ICI_BYTES_PER_S
                + (d - 1) * ICI_HOP_LATENCY_S)
    base_span_s = fill_s + (k - 1) * max(steady_pcie_s, compute_s) \
        + compute_s
    exposed_merge_s = max(span - base_span_s, 0.0) + gather_s
    total_merge_s = k * merge_s + gather_s
    hidden_s = max(total_merge_s - exposed_merge_s, 0.0)
    return {"pcie_ms": base["transfer_ms"],
            "compute_ms": base["compute_ms"],
            "merge_ms": total_merge_s * 1e3,
            "gather_ms": gather_s * 1e3,
            "exposed_merge_ms": exposed_merge_s * 1e3,
            "hidden_ms": hidden_s * 1e3,
            "merge_hidden_frac": (hidden_s / total_merge_s
                                  if total_merge_s > 0 else 0.0),
            "span_ms": (span + gather_s) * 1e3,
            "compute_bound": compute_s >= max(merge_s, steady_pcie_s)}


def stream_dp_bytes_model(rows_per_shard: int = REF_ROWS_PER_SHARD,
                          num_features: int = 136, num_bins: int = 256,
                          num_segments: int = 2, n_shards: int = 8,
                          top_rate: float = 0.1, other_rate: float = 0.1,
                          wire_dtype: str = "int8", n_chunks: int = 4,
                          code_bytes: int = 1,
                          iters_per_pass: int = 1) -> Dict[str, float]:
    """GOSS x wire compounding (r19): combined PCIe+ICI bytes one shard
    moves per histogram pass, sampled-int8 vs the full-f32 streamed-dp
    baseline.

    The two reductions act on DIFFERENT links, so they multiply within
    each term rather than saturating one bottleneck: GOSS-at-the-source
    shrinks the PCIe term by ``top_rate + other_rate`` (only sampled
    rows are gathered across the host link, measured by the per-shard
    ``bytes_streamed`` odometers), while the quantized wire shrinks the
    ICI ring-hop term by ~4x (int8 stat columns; the count column rides
    quantized too under the r10 wire codec).  At the
    D=8/F=136/B=256/131072-row reference with 0.1/0.1 GOSS the combined
    reduction is ~4.8x — the >=4x acceptance line with headroom.
    """
    d = max(int(n_shards), 1)
    pcie_full = float(rows_per_shard) * num_features * code_bytes
    sample = min(max(float(top_rate) + float(other_rate), 0.0), 1.0)
    pcie_goss = pcie_full * sample
    full = hist_merge_comm_bytes(
        "reduce_scatter_pipelined", d, num_features, num_bins,
        num_segments, wire_dtype="f32", n_chunks=n_chunks)
    wire = hist_merge_comm_bytes(
        "reduce_scatter_pipelined", d, num_features, num_bins,
        num_segments, wire_dtype=wire_dtype, n_chunks=n_chunks)
    it = max(int(iters_per_pass), 1)
    ici_full = full["ring_wire_bytes_per_shard"] * it
    ici_wire = wire["ring_wire_bytes_per_shard"] * it
    baseline = pcie_full + ici_full
    combined = pcie_goss + ici_wire
    return {"pcie_baseline_bytes": pcie_full,
            "pcie_goss_bytes": pcie_goss,
            "ici_f32_bytes": ici_full,
            "ici_wire_bytes": ici_wire,
            "baseline_bytes": baseline,
            "combined_bytes": combined,
            "reduction_factor": (baseline / combined
                                 if combined > 0 else float("inf")),
            "pcie_factor": (pcie_full / pcie_goss
                            if pcie_goss > 0 else float("inf")),
            "ici_factor": (ici_full / ici_wire
                           if ici_wire > 0 else float("inf"))}


@dataclass(frozen=True)
class StreamDpBudget:
    """One streamed-dp acceptance line (r19): either a floor on the
    merge-hidden fraction of :func:`stream_dp_time_model` (``kind=
    "hidden"``) or a floor on the combined byte-reduction factor of
    :func:`stream_dp_bytes_model` (``kind="bytes"``), both at the
    D=8/F=136/B=256 reference shape."""

    name: str
    kind: str                   # "hidden" | "bytes"
    floor: float
    n_shards: int = 8
    num_features: int = 136
    num_bins: int = 256
    num_segments: int = 2
    block_rows: int = REF_ROWS_PER_SHARD
    n_blocks_per_shard: int = 8
    mode: str = "reduce_scatter_pipelined"
    wire_dtype: str = "f32"
    n_chunks: int = 4
    top_rate: float = 0.1
    other_rate: float = 0.1
    note: str = ""

    def check(self) -> Dict[str, object]:
        if self.kind == "hidden":
            t = stream_dp_time_model(
                self.block_rows, self.num_features, self.num_bins,
                self.num_segments, self.n_blocks_per_shard,
                self.n_shards, self.mode, self.wire_dtype, self.n_chunks)
            measured = t["merge_hidden_frac"]
            detail = {"merge_ms": round(t["merge_ms"], 4),
                      "exposed_ms": round(t["exposed_merge_ms"], 4),
                      "compute_ms": round(t["compute_ms"], 3)}
        else:
            m = stream_dp_bytes_model(
                self.block_rows, self.num_features, self.num_bins,
                self.num_segments, self.n_shards, self.top_rate,
                self.other_rate, self.wire_dtype, self.n_chunks)
            measured = m["reduction_factor"]
            detail = {"baseline_mb": round(m["baseline_bytes"] / 1e6, 3),
                      "combined_mb": round(m["combined_bytes"] / 1e6, 3),
                      "pcie_factor": round(m["pcie_factor"], 2),
                      "ici_factor": round(m["ici_factor"], 2)}
        return {"name": self.name, "mode": f"stream_dp_{self.kind}",
                "measured": round(measured, 4), "budget": self.floor,
                "ok": measured >= self.floor, "note": self.note,
                **detail}


STREAM_DP_BUDGETS: Tuple[StreamDpBudget, ...] = (
    StreamDpBudget(
        "stream_dp_merge_hidden_ref", "hidden", 0.60,
        note="r19 acceptance: >=60% of the per-block-round ring merge "
             "hidden behind block compute at D=8/F=136/B=256"),
    StreamDpBudget(
        "stream_dp_merge_hidden_int8_ref", "hidden", 0.60,
        wire_dtype="int8",
        note="int8 wire shrinks hops 4x — overlap floor unchanged"),
    StreamDpBudget(
        "stream_dp_merge_hidden_psum_ref", "hidden", 0.60,
        mode="psum",
        note="the A/B baseline merge must also stay hidden (no gather "
             "term, 2x the ring bytes)"),
    StreamDpBudget(
        "stream_dp_goss_int8_bytes_ref", "bytes", 4.0,
        wire_dtype="int8",
        note="r19 acceptance: GOSS(0.1/0.1) x int8 wire moves >=4x "
             "fewer combined PCIe+ICI bytes than full-f32 streamed-dp"),
)


def stream_dp_budget_by_name(name: str) -> StreamDpBudget:
    for b in STREAM_DP_BUDGETS:
        if b.name == name:
            return b
    raise KeyError(name)


def check_stream_dp_budgets(names: Optional[List[str]] = None
                            ) -> List[Dict[str, object]]:
    specs = (STREAM_DP_BUDGETS if names is None
             else [stream_dp_budget_by_name(n) for n in names])
    return [b.check() for b in specs]


# ---------------------------------------------------------------------------
# Serving SLO budgets (r12): shed-before-miss + bounded fault inflation
# ---------------------------------------------------------------------------
# Pure arithmetic (fluid-limit queue model, no devices) so these run in
# the default ``lint`` pass like the comm/stream models above.  The same
# model is what the MicroBatcher's admission control implements online
# with an EWMA of measured dispatch time (queue.predicted_wait_s), and
# what tools/bench_loadgen.py replays against measured saturation runs —
# one model, three consumers.
#
# Fluid view of the micro-batched server: capacity is
# ``max_batch / dispatch_s`` rows/s (saturated batches are full).  With
# utilization <= 1 the queue is stable and waits are the coalescing
# delay plus one dispatch.  Past saturation the two policies diverge:
#
# * admission OFF — the queue grows without bound; once the backlog's
#   drain time passes the deadline EVERY admitted request expires in
#   queue, so the steady-state deadline-miss fraction -> 1.  p99 is
#   unbounded (grows with time in saturation).
# * admission ON (``deadline`` policy) — submit-time shedding holds the
#   backlog where predicted wait == deadline, so served requests wait at
#   most one deadline by construction: miss fraction -> 0, shed fraction
#   -> 1 - 1/utilization, and throughput stays at capacity.
#
# That asymmetry IS the r12 invariant: rejections are cheap and typed
# (``Overloaded`` at submit), deadline misses burn a dispatch slot to
# serve nobody.  "Shed before miss."


def serve_queue_model(arrival_rps: float, dispatch_ms: float,
                      max_batch: int = 128, max_delay_ms: float = 5.0,
                      deadline_ms: float = 50.0,
                      shed_policy: str = "deadline"
                      ) -> Dict[str, float]:
    """Steady-state miss/shed fractions for a micro-batched server.

    Returns ``utilization``, ``served_frac``, ``shed_frac``,
    ``miss_frac`` and ``wait_ms`` (queue wait of a served request) under
    the fluid model above.  ``shed_policy`` is "off" or "deadline"
    (matching ``serving.queue.SHED_POLICIES``; "depth" behaves like
    "deadline" here when the depth bound is tuned to the deadline).
    """
    dispatch_s = dispatch_ms / 1e3
    deadline_s = deadline_ms / 1e3
    capacity_rps = max_batch / dispatch_s if dispatch_s > 0 else \
        float("inf")
    util = arrival_rps / capacity_rps if capacity_rps > 0 else \
        float("inf")
    if util <= 1.0:
        # stable: wait = batch fill time (capped by the delay bound) + 1
        # dispatch
        fill_s = (min(max_delay_ms / 1e3, max_batch / arrival_rps)
                  if arrival_rps > 0 else 0.0)
        wait_s = fill_s + dispatch_s
        miss = 0.0 if wait_s <= deadline_s else 1.0
        return {"utilization": util, "served_frac": 1.0 - miss,
                "shed_frac": 0.0, "miss_frac": miss,
                "wait_ms": wait_s * 1e3}
    if shed_policy == "off":
        # unbounded backlog: every admitted request eventually waits past
        # the deadline -> steady-state miss fraction 1, and the server
        # burns dispatches on rows nobody is waiting for
        return {"utilization": util, "served_frac": 0.0,
                "shed_frac": 0.0, "miss_frac": 1.0,
                "wait_ms": float("inf")}
    # admission control pins the backlog at predicted wait == deadline:
    # excess arrivals shed at submit, served requests ride a full queue
    served = 1.0 / util
    return {"utilization": util, "served_frac": served,
            "shed_frac": 1.0 - served, "miss_frac": 0.0,
            "wait_ms": deadline_ms}


def serve_fault_p99_model(deadline_ms: float = 50.0,
                          dispatch_ms: float = 2.0,
                          max_delay_ms: float = 5.0,
                          shedding: bool = True) -> Dict[str, float]:
    """p99 inflation under ONE injected device fault mid-predict.

    Clean p99 is the coalescing delay plus one dispatch.  A fault stalls
    the pipeline (the faulted batch retries through the numpy fallback)
    and the backlog it leaves behind inflates tail latency.  With
    admission control the damage is CAPPED: requests whose predicted
    wait passes the deadline shed at submit, so no served request waits
    longer than ``deadline + dispatch`` — the fault p99 is bounded by
    the SLO itself, not by the stall length.  Without shedding the
    backlog drains at the server's leisure and the tail is open-ended
    (modeled here as one full deadline of backlog ON TOP of the stall).
    """
    clean_p99 = max_delay_ms + dispatch_ms
    if shedding:
        fault_p99 = deadline_ms + dispatch_ms
    else:
        fault_p99 = deadline_ms + clean_p99 + deadline_ms
    return {"clean_p99_ms": clean_p99, "fault_p99_ms": fault_p99,
            "inflation_x": fault_p99 / clean_p99 if clean_p99 > 0
            else float("inf")}


# -- r14 pod-scale serving models --------------------------------------------
#
#   SERVE_DISPATCH_FIXED_S  — per-dispatch fixed cost a sharded program
#       adds over a single-device one: ONE mesh program launch plus the
#       shard bookkeeping (specs resolution, per-device arg slicing).
#       One launch, not D — shard_map lowers to a single SPMD program,
#       which is why dp dispatch overhead AMORTIZES as traversal work
#       per device grows.  20 us is the conservative figure from the
#       LAUNCH_OVERHEAD_US family used by the training-side budgets.
#   SERVE_GATHER_BYTES_PER_S — rate of gathering the row-sharded f32
#       output back to the host-visible buffer (ICI-class, conservative).
SERVE_DISPATCH_FIXED_S = 20e-6
SERVE_GATHER_BYTES_PER_S = 16e9


def serve_mesh_dispatch_model(n_devices: int, dispatch_ms: float = 2.0,
                              bucket: int = 16384,
                              out_bytes_per_row: int = 4
                              ) -> Dict[str, float]:
    """Dispatch time of one dp-sharded bucket on ``n_devices`` devices.

    Traversal is perfectly row-parallel (no collectives in the dp
    route), so compute divides by D; what does NOT divide is the fixed
    program-launch/shard-bookkeeping cost and the output gather.
    Returns ``dispatch_ms_sharded``, ``speedup_x``, ``qps_x`` (same
    thing — full buckets), and ``overhead_frac`` (fixed cost as a
    fraction of the per-device compute slice — the part of the dispatch
    that stops scaling).
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    t1 = dispatch_ms / 1e3
    compute = t1 / n_devices
    fixed = (0.0 if n_devices == 1 else
             SERVE_DISPATCH_FIXED_S
             + bucket * out_bytes_per_row / SERVE_GATHER_BYTES_PER_S)
    td = compute + fixed
    return {"dispatch_ms_sharded": td * 1e3,
            "speedup_x": t1 / td,
            "qps_x": t1 / td,
            "overhead_frac": fixed / compute if compute > 0 else 0.0}


# -- r18 fused-predict kernel model ------------------------------------------
#
#   PREDICT_SOA_NODE_BYTES — HBM bytes per ForestSoA node slot by
#       precision.  INTENTIONALLY equal to ops.quantize.PACKED_NODE_BYTES:
#       the depth-major SoA keeps the compact storage dtypes (i16 feat +
#       u8 threshold + 2x i16 child + i8/bf16 leaf + bool parity byte),
#       so residency cost per node is unchanged by the r18 re-layout —
#       pinned by tests/test_predict_fused.py against the live arrays.
#   R14_PREDICT_STEP_FUSIONS / _EPILOGUE — the r14 per-node path's launch
#       structure: each traversal depth step re-launched its gather/
#       compare/route fusion group (3/step, measured on the r8 pin at
#       depth_cap=1: 3 whole-program fusions) plus a widen/accumulate
#       epilogue.  The fused kernel replaces ALL of it with one
#       custom-call per class — depth runs inside the kernel's
#       fori_loop, so launches stop scaling with depth_cap entirely.

PREDICT_SOA_NODE_BYTES = {"f32": 21, "bf16": 10, "int8": 9}
R14_PREDICT_STEP_FUSIONS = 3
R14_PREDICT_EPILOGUE_FUSIONS = 2


def predict_kernel_time(num_trees: int = 800, node_slots: int = 509,
                        depth_cap: int = 12, num_class: int = 1,
                        precision: str = "int8", bucket: int = 16384,
                        num_features: int = 32) -> Dict[str, float]:
    """Launch/VMEM/HBM model of one fused predict dispatch.

    Reference shape: an 800-tree, 255-leaf (509 node slots) int8 forest
    serving full 16k buckets of 32 features — the PERF.md serving
    reference.  Returns:

    * ``launches_fused`` / ``launches_r14_model`` / ``launch_drop_x`` —
      TPU launches per dispatch, fused (XLA prologue fusions + one
      mega-kernel custom-call per class, depth-independent) vs the r14
      per-node path (its traversal fusion group re-launched every depth
      step);
    * ``vmem_block_mb`` — peak VMEM of one grid step: the widened f32
      table tiles, the bins block, and the dominant [Tc, Mp, Rb] one-hot
      working buffer; must sit under the 16 MB arena;
    * ``hbm_node_table_bytes`` / ``f32_node_table_bytes`` — what the
      resident SoA costs, and how much of it is f32 node data.  For
      int8/bf16 the second number is ZERO — the r18 acceptance that no
      dequantized node table ever lands in HBM (the per-tree f32 scale
      sidecar is charged separately);
    * ``hbm_bytes_per_row`` vs ``r14_hbm_bytes_per_row`` — per-row HBM
      traffic with the table amortized over the bucket; the r14 path
      streamed a widened 21 B/node f32/i32 table regardless of the
      stored precision.
    """
    from ..ops.predict import PREDICT_NODE_PAD, PREDICT_TREE_CHUNKS

    if precision not in PREDICT_SOA_NODE_BYTES:
        raise ValueError(f"precision must be one of "
                         f"{tuple(PREDICT_SOA_NODE_BYTES)}, "
                         f"got {precision!r}")
    chunk = PREDICT_TREE_CHUNKS[precision]
    tp = max(chunk, -(-num_trees // chunk) * chunk)
    mp = max(PREDICT_NODE_PAD,
             -(-node_slots // PREDICT_NODE_PAD) * PREDICT_NODE_PAD)
    fp = max(8, -(-num_features // 8) * 8)
    rb = 128

    # launches per dispatch: fused = prologue fusions + 1 custom-call per
    # class; r14 = the step fusion group x depth_cap + epilogue, per class
    launches_fused = R14_PREDICT_STEP_FUSIONS + num_class
    launches_r14 = num_class * (R14_PREDICT_STEP_FUSIONS * depth_cap
                                + R14_PREDICT_EPILOGUE_FUSIONS)

    # VMEM of one grid step (all tiles widened to f32 in-kernel)
    onehot = chunk * mp * rb * 4            # [Tc, Mp, Rb] working buffer
    tables = 5 * chunk * mp * 4             # feat/thr/left/right/leaf
    bins_blk = fp * rb * 4
    vmem = onehot + tables + bins_blk + chunk * 4 + rb * 4

    node_b = PREDICT_SOA_NODE_BYTES[precision]
    table_bytes = num_class * tp * mp * node_b
    scale_bytes = num_class * tp * 4
    f32_table = table_bytes if precision == "f32" else 0
    per_row = num_features * 4 + (table_bytes + scale_bytes) / bucket
    r14_per_row = (num_features * 4
                   + num_class * num_trees * node_slots * 21 / bucket)
    return {
        "launches_fused": launches_fused,
        "launches_r14_model": launches_r14,
        "launch_drop_x": launches_r14 / launches_fused,
        "vmem_block_bytes": vmem,
        "vmem_block_mb": vmem / 2**20,
        "hbm_node_table_bytes": table_bytes,
        "hbm_scale_bytes": scale_bytes,
        "f32_node_table_bytes": f32_table,
        "hbm_bytes_per_row": per_row,
        "r14_hbm_bytes_per_row": r14_per_row,
        "bytes_per_row_drop_x": r14_per_row / per_row,
    }


def predict_kernels_summary(bucket: int = 8) -> Dict[str, object]:
    """The r18 bench-artifact dict: fused predict launch counts, CPU-
    measured plus the TPU launch model — cross-referenced against the
    declarative budgets so BENCH_SERVE artifacts and the lint gate
    cannot disagree (same contract as ``kernels_per_round_summary``)."""
    cpu_f, cpu_c = serving_predict_counts(bucket)
    xla_f, xla_c = serving_predict_counts(bucket, stub=True)
    m = predict_kernel_time()
    budget = budget_by_name("serving_predict_tpu_model").budget
    return {
        "predict_kernels_fused_cpu_inlined": cpu_f + cpu_c,
        "predict_kernels_tpu_model": xla_f + xla_c,
        "predict_budget_tpu_model": budget,
        "predict_within_budget": bool(xla_f + xla_c <= budget),
        "predict_launches_r14_model": m["launches_r14_model"],
        "predict_launch_drop_x": round(m["launch_drop_x"], 2),
        "predict_launch_drop_floor": 4.0,
        "predict_drop_within_floor": bool(m["launch_drop_x"] >= 4.0),
        "predict_vmem_block_mb": round(m["vmem_block_mb"], 2),
        "predict_f32_node_table_bytes": m["f32_node_table_bytes"],
        "predict_hbm_bytes_per_row": round(m["hbm_bytes_per_row"], 1),
        "predict_r14_hbm_bytes_per_row":
            round(m["r14_hbm_bytes_per_row"], 1),
    }


@dataclass(frozen=True)
class ServeSLOBudget:
    """One serving SLO invariant at a reference operating point.

    ``kind`` selects the measurement:

    * ``queue_miss`` — deadline-miss fraction at ``utilization_x``
      overload with admission control ON (the shed-before-miss bar:
      <= 1%);
    * ``queue_miss_off`` — the same point with admission OFF; budgeted
      from BELOW (miss ~ 1.0) so the model provably separates the
      policies — a "budget" that guards the model, not the code;
    * ``served_frac`` — throughput retained under overload with
      shedding (floor: ~1/utilization);
    * ``fault_inflation`` — p99 inflation under one injected device
      fault with shedding active (ceiling);
    * ``models_per_byte`` — r14: resident models per HBM byte at
      ``precision`` relative to f32 (``ops.quantize.packed_model_bytes``
      — the same layout table the runtime materializes, so the lint
      floor and the device residency cannot drift apart);
    * ``dp_overhead`` — r14: fixed dispatch cost of the dp-sharded
      route as a fraction of the per-device compute slice at
      ``mesh_devices`` (ceiling: the non-scaling part must stay small);
    * ``dp_speedup`` — r14: modeled QPS multiple of the dp route at
      ``mesh_devices`` (floor);
    * ``fused_launch_drop`` — r18: TPU launches per dispatch of the r14
      per-node path over the fused mega-kernel at the reference forest
      shape (``predict_kernel_time``; floor: >= 4x);
    * ``fused_vmem_mb`` — r18: peak VMEM of one fused-kernel grid step
      at ``precision`` (ceiling: the 16 MB arena);
    * ``fused_f32_table_bytes`` — r18: f32 node-table bytes the fused
      path keeps resident in HBM at ``precision`` — ZERO for int8/bf16
      (the no-dequantize-pass acceptance).

    ``cmp`` is "le" (measured <= budget passes) or "ge".
    Reference point: 2 ms dispatches, 128-row batches, 5 ms coalescing
    delay, 50 ms deadlines — the bench_loadgen defaults.
    """

    name: str
    kind: str
    budget: float
    cmp: str = "le"
    utilization_x: float = 2.0
    dispatch_ms: float = 2.0
    max_batch: int = 128
    max_delay_ms: float = 5.0
    deadline_ms: float = 50.0
    precision: str = "int8"
    mesh_devices: int = 8
    note: str = ""

    def measure(self) -> float:
        cap_rps = self.max_batch / (self.dispatch_ms / 1e3)
        arrival = self.utilization_x * cap_rps
        if self.kind in ("queue_miss", "queue_miss_off", "served_frac"):
            m = serve_queue_model(
                arrival, self.dispatch_ms, self.max_batch,
                self.max_delay_ms, self.deadline_ms,
                shed_policy=("off" if self.kind == "queue_miss_off"
                             else "deadline"))
            return m["served_frac"] if self.kind == "served_frac" \
                else m["miss_frac"]
        if self.kind == "fault_inflation":
            return serve_fault_p99_model(
                self.deadline_ms, self.dispatch_ms,
                self.max_delay_ms, shedding=True)["inflation_x"]
        if self.kind == "models_per_byte":
            from ..ops.quantize import models_per_byte_gain

            return models_per_byte_gain(self.precision)
        if self.kind == "dp_overhead":
            return serve_mesh_dispatch_model(
                self.mesh_devices, self.dispatch_ms)["overhead_frac"]
        if self.kind == "dp_speedup":
            return serve_mesh_dispatch_model(
                self.mesh_devices, self.dispatch_ms)["speedup_x"]
        if self.kind == "fused_launch_drop":
            return predict_kernel_time(
                precision=self.precision)["launch_drop_x"]
        if self.kind == "fused_vmem_mb":
            return predict_kernel_time(
                precision=self.precision)["vmem_block_mb"]
        if self.kind == "fused_f32_table_bytes":
            return float(predict_kernel_time(
                precision=self.precision)["f32_node_table_bytes"])
        raise ValueError(f"unknown SLO budget kind {self.kind!r}")

    def check(self) -> Dict[str, object]:
        measured = self.measure()
        ok = (measured <= self.budget if self.cmp == "le"
              else measured >= self.budget)
        return {"name": self.name, "kind": self.kind,
                "measured": round(measured, 4), "budget": self.budget,
                "cmp": self.cmp, "ok": ok, "note": self.note}


SERVE_SLO_BUDGETS: Tuple[ServeSLOBudget, ...] = (
    ServeSLOBudget("serve_shed_before_miss", "queue_miss", 0.01,
                   note="r12 acceptance: <=1% deadline misses at 2x "
                        "overload with admission control on"),
    ServeSLOBudget("serve_miss_without_admission", "queue_miss_off",
                   0.99, cmp="ge",
                   note="counterfactual: admission off at 2x overload "
                        "misses ~everything — the model separates the "
                        "policies"),
    ServeSLOBudget("serve_capacity_under_shed", "served_frac", 0.45,
                   cmp="ge",
                   note="shedding keeps throughput at capacity: "
                        ">=45% of a 2x-overload arrival stream served"),
    ServeSLOBudget("serve_fault_p99_inflation", "fault_inflation", 8.0,
                   note="one device fault inflates p99 <=8x (capped at "
                        "deadline+dispatch by shed-before-miss)"),
    # -- r14 pod-scale entries ------------------------------------------------
    ServeSLOBudget("serve_int8_models_per_byte", "models_per_byte", 1.9,
                   cmp="ge", precision="int8",
                   note="r14 acceptance: int8 PackedForest holds >=1.9x "
                        "models per HBM byte vs f32 (21 B/node -> 9 "
                        "B/node + 4 B/tree scale sidecar)"),
    ServeSLOBudget("serve_bf16_models_per_byte", "models_per_byte", 1.5,
                   cmp="ge", precision="bf16",
                   note="bf16 residency floor: >=1.5x models per HBM "
                        "byte (exact thresholds, rounded leaves, no "
                        "scale sidecar)"),
    ServeSLOBudget("serve_dp_dispatch_overhead", "dp_overhead", 0.10,
                   mesh_devices=8,
                   note="fixed dp-shard dispatch cost (launch + output "
                        "gather) <=10% of the per-device compute slice "
                        "at D=8 — the non-scaling remainder stays "
                        "amortized"),
    ServeSLOBudget("serve_dp_speedup_d4", "dp_speedup", 3.0, cmp="ge",
                   mesh_devices=4,
                   note="r14 acceptance: dp route delivers >=3x QPS at "
                        "D=4 under the dispatch model (near-linear "
                        "minus the fixed launch/gather cost)"),
    # -- r18 fused-predict entries --------------------------------------------
    ServeSLOBudget("serve_fused_launch_drop", "fused_launch_drop", 4.0,
                   cmp="ge", precision="int8",
                   note="r18 acceptance: fused mega-kernel cuts TPU "
                        "launches per dispatch >=4x vs the r14 per-node "
                        "path at the reference forest (depth runs "
                        "inside the kernel, launches stop scaling with "
                        "depth_cap)"),
    ServeSLOBudget("serve_fused_vmem_int8", "fused_vmem_mb", 16.0,
                   precision="int8",
                   note="one fused grid step (widened tiles + one-hot "
                        "working buffer) fits the 16 MB VMEM arena at "
                        "the int8 reference shape (~8.3 MB modeled)"),
    ServeSLOBudget("serve_fused_no_f32_table_int8",
                   "fused_f32_table_bytes", 0.0, precision="int8",
                   note="r18 acceptance: int8 residency keeps ZERO f32 "
                        "node-table bytes in HBM — the SoA ships the "
                        "stored i16/u8/i8 arrays, dequant is one "
                        "per-tree scale inside the kernel"),
    ServeSLOBudget("serve_fused_no_f32_table_bf16",
                   "fused_f32_table_bytes", 0.0, precision="bf16",
                   note="bf16 residency likewise keeps no f32 node "
                        "table resident"),
)


def serve_slo_budget_by_name(name: str) -> ServeSLOBudget:
    for b in SERVE_SLO_BUDGETS:
        if b.name == name:
            return b
    raise KeyError(name)


def check_serve_slo_budgets(names: Optional[List[str]] = None
                            ) -> List[Dict[str, object]]:
    specs = (SERVE_SLO_BUDGETS if names is None
             else [serve_slo_budget_by_name(n) for n in names])
    return [b.check() for b in specs]


# ---------------------------------------------------------------------------
# Checkpoint-overhead budgets (r13): fault-tolerant training must not tax
# throughput — auto-checkpointing at the default cadence stays <=5% of
# round wall clock.
#
#   HOST_WRITE_BYTES_PER_S  — sustained sequential write rate of the
#       checkpoint target (local NVMe-class SSD, conservative 1.5 GB/s).
#   CKPT_DIGEST_BYTES_PER_S — single-core integrity-layer throughput
#       (sha256 over the payload + per-field crc32s); the checksums that
#       make torn-write detection work are charged, not treated as free.
#   CKPT_FIXED_LATENCY_S    — per-checkpoint constant: device->host state
#       gather dispatch, fsync, rename (~10 ms).
#   TRAIN_ROWS_PER_S        — measured training throughput (rows/s/round)
#       at the r5 fused reference (PERF.md); the round denominator is
#       charged from MEASURED wall clock, not the one-hot-matmul flop
#       model, so the overhead fraction means what it says.
# ---------------------------------------------------------------------------

HOST_WRITE_BYTES_PER_S = 1.5e9
CKPT_DIGEST_BYTES_PER_S = 1.5e9
CKPT_FIXED_LATENCY_S = 10e-3
TRAIN_ROWS_PER_S = 7.2e6


def ckpt_overhead_time(n_rows: int = 11_000_000, num_leaves: int = 255,
                       trees_so_far: int = 200, rounds_between: int = 10,
                       num_class: int = 1) -> Dict[str, float]:
    """Checkpoint cost vs training time between checkpoints.

    Checkpoint bytes = the training-state vectors (``pred_train`` [n,K]
    + ``bag`` [n], f32) + the forest so far (per node slot: 4 i32 +
    3 f32 + 1 bool = 29 B across the Tree field arrays) + header/meta.
    The write AND the integrity digest are charged serially (both run on
    the host thread between rounds), plus the fixed fsync/rename cost.
    The denominator is ``rounds_between`` rounds at the measured
    ``TRAIN_ROWS_PER_S``.  Returns bytes, per-leg times, and
    ``overhead_frac``.
    """
    n_pad = -(-int(n_rows) // 256) * 256
    nodes = 2 * int(num_leaves) - 1
    node_bytes = 7 * 4 + 1
    state_bytes = 4 * n_pad * int(num_class) + 4 * n_pad
    forest_bytes = int(trees_so_far) * int(num_class) * nodes * node_bytes
    ckpt_bytes = state_bytes + forest_bytes + 4096
    write_s = ckpt_bytes / HOST_WRITE_BYTES_PER_S
    digest_s = ckpt_bytes / CKPT_DIGEST_BYTES_PER_S
    ckpt_s = write_s + digest_s + CKPT_FIXED_LATENCY_S
    round_s = int(n_rows) / TRAIN_ROWS_PER_S
    span_s = max(int(rounds_between), 1) * round_s
    return {
        "ckpt_bytes": float(ckpt_bytes),
        "ckpt_mb": ckpt_bytes / 1e6,
        "write_ms": write_s * 1e3,
        "digest_ms": digest_s * 1e3,
        "ckpt_ms": ckpt_s * 1e3,
        "round_ms": round_s * 1e3,
        "overhead_frac": ckpt_s / span_s,
    }


@dataclass(frozen=True)
class CkptBudget:
    """One checkpoint-overhead invariant at a reference operating point.

    ``cmp`` is "le" (overhead must stay under the budget — the real
    acceptance bars) or "ge" (budgeted from BELOW: the operating point
    is MEANT to be expensive, proving the model separates cadences —
    the same guard-the-model pattern as ``serve_miss_without_admission``).
    """

    name: str
    budget: float
    cmp: str = "le"
    n_rows: int = 11_000_000
    num_leaves: int = 255
    trees_so_far: int = 200
    rounds_between: int = 10
    num_class: int = 1
    note: str = ""

    def check(self) -> Dict[str, object]:
        t = ckpt_overhead_time(
            self.n_rows, self.num_leaves, self.trees_so_far,
            self.rounds_between, self.num_class)
        frac = t["overhead_frac"]
        ok = frac <= self.budget if self.cmp == "le" else frac >= self.budget
        return {"name": self.name, "mode": "ckpt_overhead",
                "measured": round(frac, 5), "budget": self.budget,
                "cmp": self.cmp, "ckpt_mb": round(t["ckpt_mb"], 2),
                "ckpt_ms": round(t["ckpt_ms"], 2),
                "round_ms": round(t["round_ms"], 2),
                "ok": ok, "note": self.note}


CKPT_BUDGETS: Tuple[CkptBudget, ...] = (
    CkptBudget("ckpt_overhead_ref", 0.05,
               note="r13 acceptance: <=5% throughput overhead at "
                    "checkpoint_rounds=10, Higgs-scale rows, 200-tree "
                    "forest"),
    CkptBudget("ckpt_overhead_deep_forest", 0.05, trees_so_far=2000,
               note="the forest term stays amortized even at 2000 "
                    "trees (state vectors dominate at 11M rows)"),
    CkptBudget("ckpt_overhead_small_shard", 0.05, n_rows=1_048_576,
               trees_so_far=500,
               note="1M-row shard, 500 trees: fixed fsync+digest costs "
                    "still amortize under the default cadence"),
    CkptBudget("ckpt_every_round_uneconomic", 0.05, cmp="ge",
               n_rows=131_072, trees_so_far=500, rounds_between=1,
               note="guard-the-model: checkpointing EVERY round at one "
                    "131k-row shard costs >5% of the round — the "
                    "default cadence is load-bearing, not decorative"),
)


def ckpt_budget_by_name(name: str) -> CkptBudget:
    for b in CKPT_BUDGETS:
        if b.name == name:
            return b
    raise KeyError(name)


def check_ckpt_budgets(names: Optional[List[str]] = None
                       ) -> List[Dict[str, object]]:
    specs = (CKPT_BUDGETS if names is None
             else [ckpt_budget_by_name(n) for n in names])
    return [b.check() for b in specs]


# ---------------------------------------------------------------------------
# Freshness budgets (ISSUE r15): the model-staleness SLO, decomposed
#
# **Model staleness** = seconds from a row block ARRIVING to a model
# trained on it SERVING traffic.  The refresh pipeline
# (lightgbm_tpu.pipeline) measures it; this model BOUNDS it offline:
#
#     staleness <= wait (daemon tick) + train (refresh_rounds rounds)
#                + publish (pack + atomic artifact write)
#                + warm (per-bucket-shape XLA compiles)
#                + canary (device dispatch + host oracle replay)
#                + flip (one attribute assignment)
#
# The SLO is defined at the REFERENCE SHAPE: Higgs-scale rows
# (11M x 28), refresh_rounds=20 continuation rounds, 255-leaf trees, a
# ~220-tree live forest, 4 warmed bucket shapes, 8 canary rows —
# FRESHNESS_SLO_S = 60 s end to end.  The train leg is charged at the
# same MEASURED TRAIN_ROWS_PER_S the checkpoint budgets use, so the two
# models stay mutually consistent; warm is charged per compiled bucket
# shape (the r12 deploy path compiles each padded batch bucket once).
#
# The guard-the-model entry turns the motivation into an invariant: a
# COLD RETRAIN of the full forest at the same shape blows the SLO by
# design (cmp="ge") — continuation is load-bearing, not an
# optimization.  FRESHNESS_BUDGETS runs in the default lint pass
# (analysis.cli, section "freshness") next to the serving/checkpoint
# budgets.
# ---------------------------------------------------------------------------

WARM_COMPILE_S_PER_SHAPE = 0.4
DAEMON_TICK_S = 1.0
CANARY_ORACLE_S_PER_ROW_TREE = 1e-7
FLIP_S = 1e-3
FRESHNESS_SLO_S = 60.0


def staleness_model(n_rows: int = 11_000_000, refresh_rounds: int = 20,
                    num_leaves: int = 255, trees_total: int = 220,
                    num_class: int = 1, warm_shapes: int = 4,
                    canary_rows: int = 8,
                    tick_s: float = DAEMON_TICK_S,
                    screen_round_factor: float = 1.0) -> Dict[str, float]:
    """Closed-form staleness decomposition at one operating point.

    ``trees_total`` is the forest size AFTER the refresh (continuation
    replays + extends; a cold retrain instead sets
    ``refresh_rounds = trees_total``).  Returns per-leg seconds plus
    ``staleness_s`` and ``train_frac`` (train leg / total — the
    quantity that says the pipeline is train-bound, with serving-side
    legs amortized).  ``screen_round_factor`` (r20) scales the train
    leg's per-round cost by EMA-FS screening's amortized round factor
    (``feature_screen_time_model``'s ``avg_round_factor``) — the two
    models stay mutually consistent by construction.
    """
    round_s = int(n_rows) / TRAIN_ROWS_PER_S * float(screen_round_factor)
    train_s = max(int(refresh_rounds), 0) * round_s
    nodes = 2 * int(num_leaves) - 1
    node_bytes = 7 * 4 + 1
    artifact_bytes = (int(trees_total) * int(num_class) * nodes
                      * node_bytes + 4096)
    publish_s = artifact_bytes / HOST_WRITE_BYTES_PER_S \
        + CKPT_FIXED_LATENCY_S
    warm_s = int(warm_shapes) * WARM_COMPILE_S_PER_SHAPE
    canary_s = (2 * SERVE_DISPATCH_FIXED_S
                + int(canary_rows) * int(trees_total) * int(num_class)
                * CANARY_ORACLE_S_PER_ROW_TREE)
    staleness_s = (float(tick_s) + train_s + publish_s + warm_s
                   + canary_s + FLIP_S)
    return {
        "wait_s": float(tick_s),
        "train_s": train_s,
        "publish_s": publish_s,
        "warm_s": warm_s,
        "canary_s": canary_s,
        "flip_s": FLIP_S,
        "artifact_mb": artifact_bytes / 1e6,
        "staleness_s": staleness_s,
        "train_frac": train_s / staleness_s,
    }


@dataclass(frozen=True)
class FreshnessBudget:
    """One staleness invariant at a reference operating point.

    ``metric`` selects what ``staleness_model`` output is compared
    ("staleness_s" for the SLO bars, "train_frac" for the
    decomposition-shape bars).  ``cmp`` is "le" for the acceptance bars
    and "ge" for budgeted-from-below guards (the operating point is
    MEANT to breach — proving the model separates refresh from
    retrain)."""

    name: str
    budget: float
    cmp: str = "le"
    metric: str = "staleness_s"
    n_rows: int = 11_000_000
    refresh_rounds: int = 20
    num_leaves: int = 255
    trees_total: int = 220
    num_class: int = 1
    warm_shapes: int = 4
    canary_rows: int = 8
    tick_s: float = DAEMON_TICK_S
    # r20: a non-None keep ratio prices the train leg under EMA-FS
    # screening (feature_screen_time_model's amortized round factor at
    # this keep/refresh/width operating point)
    screen_keep_ratio: Optional[float] = None
    screen_refresh_rounds: int = 10
    screen_num_features: int = 136
    note: str = ""

    def check(self) -> Dict[str, object]:
        factor = 1.0
        if self.screen_keep_ratio is not None:
            factor = feature_screen_time_model(
                n_rows=self.n_rows,
                num_features=self.screen_num_features,
                keep_ratio=self.screen_keep_ratio,
                refresh_rounds=self.screen_refresh_rounds,
            )["avg_round_factor"]
        t = staleness_model(
            self.n_rows, self.refresh_rounds, self.num_leaves,
            self.trees_total, self.num_class, self.warm_shapes,
            self.canary_rows, self.tick_s,
            screen_round_factor=factor)
        measured = t[self.metric]
        ok = (measured <= self.budget if self.cmp == "le"
              else measured >= self.budget)
        return {"name": self.name, "mode": "freshness",
                "metric": self.metric, "measured": round(measured, 4),
                "budget": self.budget, "cmp": self.cmp,
                "train_s": round(t["train_s"], 3),
                "warm_s": round(t["warm_s"], 3),
                "canary_s": round(t["canary_s"], 5),
                "staleness_s": round(t["staleness_s"], 3),
                "screen_round_factor": round(factor, 4),
                "ok": ok, "note": self.note}


FRESHNESS_BUDGETS: Tuple[FreshnessBudget, ...] = (
    FreshnessBudget("freshness_slo_ref", FRESHNESS_SLO_S,
                    note="r15 acceptance: 20 continuation rounds at "
                         "Higgs-scale rows land a fresh model inside "
                         "the 60 s staleness SLO, warm+canary "
                         "included"),
    FreshnessBudget("freshness_train_warm_canary_ref", FRESHNESS_SLO_S,
                    tick_s=0.0,
                    note="the ISSUE bar verbatim: train + warm + "
                         "canary (+publish/flip) <= SLO with the wait "
                         "leg excluded — the pipeline's own work fits "
                         "the budget even before tick tuning"),
    FreshnessBudget("freshness_small_shard_fast", 5.0, n_rows=1_048_576,
                    refresh_rounds=5, trees_total=120,
                    note="a 1M-row shard refresh of 5 rounds serves "
                         "fresh in under 5 s — the interactive "
                         "operating point"),
    FreshnessBudget("freshness_train_bound_ref", 0.5, cmp="ge",
                    metric="train_frac",
                    note="decomposition shape: the train leg dominates "
                         "staleness at the reference shape — warm, "
                         "canary, publish and flip stay amortized "
                         "overheads, not the bottleneck"),
    FreshnessBudget("freshness_cold_retrain_blows_slo", FRESHNESS_SLO_S,
                    cmp="ge", refresh_rounds=220,
                    note="guard-the-model: retraining the full "
                         "220-tree forest from scratch at the same "
                         "shape CANNOT meet the SLO — continuation is "
                         "load-bearing, not an optimization"),
    FreshnessBudget("freshness_screen_train_leg", 20.0,
                    screen_keep_ratio=0.25,
                    note="r20: EMA-FS screening at keep=0.25/F=136 "
                         "cuts the reference refresh's train leg from "
                         "~30.6 s to ~13 s, landing total staleness "
                         "near 15.5 s — a third of the 60 s SLO, "
                         "headroom the unscreened ~33 s point never "
                         "had"),
)


def freshness_budget_by_name(name: str) -> FreshnessBudget:
    for b in FRESHNESS_BUDGETS:
        if b.name == name:
            return b
    raise KeyError(name)


def check_freshness_budgets(names: Optional[List[str]] = None
                            ) -> List[Dict[str, object]]:
    specs = (FRESHNESS_BUDGETS if names is None
             else [freshness_budget_by_name(n) for n in names])
    return [b.check() for b in specs]


# ---------------------------------------------------------------------------
# gain-informed feature screening budgets (ISSUE r20)
# ---------------------------------------------------------------------------
# EMA-FS screening (models.feature_mask.FeatureScreener) compacts each
# non-refresh round to F_active = max(1, ceil(keep_ratio * F)) columns:
# histograms, split scans, ring merges and PCIe block streaming all run
# over the gathered [N, F_active] view, with winners remapped to global
# ids.  The round-time model splits a training round into an F-scaling
# part (histogram build + split scan + merge, empirically
# ROUND_F_AXIS_FRAC of the round at the 136-feature reference) and an
# F-invariant part (partition, leaf values, prediction update).  Every
# refresh_rounds-th round runs the FULL feature set (exactness +
# cold-feature rediscovery), so the amortized factor is the mean of one
# full round and refresh_rounds-1 screened rounds.  Communication and
# streaming drops reuse hist_merge_comm_bytes — the comm model and the
# screen model price the same wire.

ROUND_F_AXIS_FRAC = 0.85


def feature_screen_time_model(n_rows: int = 11_000_000,
                              num_features: int = 136,
                              keep_ratio: float = 0.25,
                              refresh_rounds: int = 10,
                              n_shards: int = 8, num_bins: int = 256,
                              num_segments: int = 2,
                              wire_dtype: str = "f32"
                              ) -> Dict[str, float]:
    """Closed-form round-time / comm decomposition of EMA-FS screening.

    ``avg_round_factor`` is the amortized per-round cost relative to an
    unscreened round (1 full + ``refresh_rounds - 1`` screened rounds
    per cycle); ``staleness_model`` consumes it so the freshness and
    screening models agree by construction.  ``comm_drop_x`` is the
    ring-merge wire-bytes ratio full/screened from
    ``hist_merge_comm_bytes`` (the feature axis pads to a multiple of
    ``n_shards``, so it is slightly below F / F_active);
    ``stream_drop_x`` is the PCIe block-stream byte ratio, exactly
    F / F_active because ColumnViewStore slices on the host before
    device_put.
    """
    from ..models.feature_mask import active_feature_count
    f = int(num_features)
    f_active = active_feature_count(f, keep_ratio)
    r = max(int(refresh_rounds), 1)
    screened_factor = ((1.0 - ROUND_F_AXIS_FRAC)
                       + ROUND_F_AXIS_FRAC * f_active / f)
    avg_round_factor = (1.0 + (r - 1) * screened_factor) / r
    round_full_s = int(n_rows) / TRAIN_ROWS_PER_S
    full_wire = hist_merge_comm_bytes(
        "reduce_scatter_ring", n_shards, f, num_bins, num_segments,
        wire_dtype=wire_dtype)["ring_wire_bytes_per_shard"]
    screened_wire = hist_merge_comm_bytes(
        "reduce_scatter_ring", n_shards, f_active, num_bins,
        num_segments, wire_dtype=wire_dtype)["ring_wire_bytes_per_shard"]
    return {
        "f_active": float(f_active),
        "screened_factor": screened_factor,
        "avg_round_factor": avg_round_factor,
        "round_full_s": round_full_s,
        "screened_round_s": round_full_s * screened_factor,
        "avg_round_s": round_full_s * avg_round_factor,
        "speedup_x": 1.0 / avg_round_factor,
        "comm_drop_x": full_wire / screened_wire,
        "stream_drop_x": f / f_active,
    }


@dataclass(frozen=True)
class ScreenBudget:
    """One screening invariant at a reference operating point.

    ``metric`` selects a ``feature_screen_time_model`` output; ``cmp``
    is "ge" for the acceptance bars (speedup / drop ratios budgeted
    from below) and "le" for the exactness guards (operating points
    where screening MUST degenerate to a no-op)."""

    name: str
    budget: float
    metric: str = "speedup_x"
    cmp: str = "ge"
    num_features: int = 136
    keep_ratio: float = 0.25
    refresh_rounds: int = 10
    n_shards: int = 8
    note: str = ""

    def check(self) -> Dict[str, object]:
        t = feature_screen_time_model(
            num_features=self.num_features, keep_ratio=self.keep_ratio,
            refresh_rounds=self.refresh_rounds, n_shards=self.n_shards)
        measured = float(t[self.metric])
        ok = (measured >= self.budget if self.cmp == "ge"
              else measured <= self.budget)
        return {"name": self.name, "mode": "screen",
                "metric": self.metric, "measured": round(measured, 4),
                "budget": self.budget, "cmp": self.cmp,
                "f_active": int(t["f_active"]),
                "avg_round_factor": round(t["avg_round_factor"], 4),
                "ok": ok, "note": self.note}


SCREEN_BUDGETS: Tuple[ScreenBudget, ...] = (
    ScreenBudget("screen_speedup_f136", 1.5,
                 note="r20 acceptance: amortized round-time speedup at "
                      "the wide reference (F=136, keep=0.25, refresh "
                      "every 10) clears 1.5x — the modeled point lands "
                      "near 2.35x"),
    ScreenBudget("screen_comm_drop_f136", 3.0, metric="comm_drop_x",
                 note="screened ring merges move >=3x fewer wire bytes "
                      "per shard at D=8 (F pads to a shard multiple, "
                      "so the drop is ~3.4x, not the raw 4x)"),
    ScreenBudget("screen_stream_drop_f136", 3.0, metric="stream_drop_x",
                 note="ColumnViewStore slices host blocks before "
                      "device_put, so streamed PCIe bytes drop by "
                      "exactly F / F_active = 4x at keep=0.25"),
    ScreenBudget("screen_keep1_no_op", 1.001, cmp="le",
                 keep_ratio=1.0,
                 note="guard-the-model: keep_ratio=1 keeps every "
                      "feature, so the modeled speedup MUST collapse "
                      "to 1x — screening never charges a discount it "
                      "did not earn"),
    ScreenBudget("screen_refresh1_exact", 1.001, cmp="le",
                 refresh_rounds=1,
                 note="guard-the-model: refresh_rounds=1 makes every "
                      "round a full-width refresh (the exactness "
                      "limit), so the amortized factor MUST be 1x"),
)


def screen_budget_by_name(name: str) -> ScreenBudget:
    for b in SCREEN_BUDGETS:
        if b.name == name:
            return b
    raise KeyError(name)


def check_screen_budgets(names: Optional[List[str]] = None
                         ) -> List[Dict[str, object]]:
    specs = (SCREEN_BUDGETS if names is None
             else [screen_budget_by_name(n) for n in names])
    return [b.check() for b in specs]


# ---------------------------------------------------------------------------
# sweep throughput + tune->serve staleness budgets (ISSUE r17)
# ---------------------------------------------------------------------------
# Sweep-as-a-service (lightgbm_tpu.sweep) prices hyperparameter search
# in configs/hour: the scheduler packs the grid into fused-CV
# hyper-batches and spreads them over a configs x devices mesh, so the
# serial reference loop's cost model gains two levers — batching (one
# XLA program amortizes B = configs x folds trainings) and the mesh
# (device groups run hyper-batches concurrently; the makespan is the
# slowest group's bucket chain, the scheduler's greedy-LPT quantity).
#
# The REFERENCE SHAPE is the paper's own sweep: 108 configs x 5-fold CV
# on the 46k-row claims table, ~150 boosting rounds to early-stop, 9
# fused buckets of 12 configs (the (num_leaves, lr, bagging) statics of
# the reference grid).  Legs are charged from the SAME measured
# constants the other budget families use (TRAIN_ROWS_PER_S per round,
# HOST_WRITE/CKPT for the ledger) plus three sweep-specific ones
# calibrated against tools/bench_sweep.py on the dryrun mesh: the
# per-bucket compile, the batched-execution efficiency (B elements cost
# B/FUSED_BATCH_EFF serial-equivalents — histogram work vectorizes, the
# while_loop does not), and the straggler factor (a bucket runs until
# its SLOWEST config early-stops).
#
# The tune->serve staleness line extends the r15 freshness model: a
# RETUNE generation's data-arrival -> serving time is the sweep
# makespan plus the winner's cold train plus the unchanged
# publish/warm/canary/flip legs — bounded by TUNE_SERVE_SLO_S at D=8,
# while the guard entry proves the serial ledger loop CANNOT meet it
# (cmp="ge"): the mesh is load-bearing for closed-loop tuning, not an
# optimization.
# ---------------------------------------------------------------------------

SWEEP_COMPILE_S_PER_BUCKET = 12.0   # one fused batch program (measured r7)
HOST_ROUND_LATENCY_S = 1.5e-3       # serial loop's per-round host overhead
FUSED_BATCH_EFF = 3.0               # B batch elements ~ B/3 serial cost
SWEEP_STRAGGLER = 1.3               # bucket runs to its slowest config
GROUP_OVERLAP_EFF = 0.75            # multi-device group scaling efficiency
LEDGER_SAVE_S = 5e-3                # atomic tmp+fsync+rename per commit
TUNE_SERVE_SLO_S = 300.0            # retune data-arrival -> serving bound


def sweep_time_model(n_configs: int = 108, n_rows: int = 46_000,
                     nfold: int = 5, rounds_mean: int = 150,
                     n_buckets: int = 9, n_devices: int = 1,
                     group_size: int = 1) -> Dict[str, float]:
    """Closed-form sweep cost at one operating point.

    ``serial_s`` prices the reference's per-config host loop (every
    fold x round pays the full row pass plus host dispatch latency,
    plus one ledger commit per config).  ``makespan_s`` prices the
    scheduled fused sweep: each bucket pays one compile plus its
    batched execution (straggler-inflated), buckets spread greedily
    over ``n_devices // group_size`` groups, and the makespan is the
    slowest group's chain — ceil(n_buckets / n_groups) buckets when
    buckets are near-uniform, as at the reference shape.
    """
    round_s = int(n_rows) / TRAIN_ROWS_PER_S
    serial_s = (int(n_configs) * int(nfold) * int(rounds_mean)
                * (round_s + HOST_ROUND_LATENCY_S)
                + int(n_configs) * LEDGER_SAVE_S)

    cfg_per_bucket = int(n_configs) / max(int(n_buckets), 1)
    batch = cfg_per_bucket * int(nfold)
    exec_eff = FUSED_BATCH_EFF * (
        1.0 if group_size <= 1 else int(group_size) * GROUP_OVERLAP_EFF)
    bucket_s = (SWEEP_COMPILE_S_PER_BUCKET
                + int(rounds_mean) * round_s * batch / exec_eff
                * SWEEP_STRAGGLER)
    n_groups = max(int(n_devices) // max(int(group_size), 1), 1)
    chain = -(-int(n_buckets) // n_groups)   # ceil: slowest group's load
    makespan_s = chain * bucket_s + int(n_buckets) * LEDGER_SAVE_S
    return {
        "round_s": round_s,
        "serial_s": serial_s,
        "configs_per_hour_serial": int(n_configs) / serial_s * 3600.0,
        "bucket_s": bucket_s,
        "n_groups": float(n_groups),
        "chain_buckets": float(chain),
        "makespan_s": makespan_s,
        "configs_per_hour": int(n_configs) / makespan_s * 3600.0,
        "speedup": serial_s / makespan_s,
    }


def sweep_staleness_model(n_configs: int = 108, n_rows: int = 46_000,
                          nfold: int = 5, rounds_mean: int = 150,
                          n_buckets: int = 9, n_devices: int = 8,
                          group_size: int = 1, num_leaves: int = 127,
                          warm_shapes: int = 4, canary_rows: int = 8,
                          serial: bool = False) -> Dict[str, float]:
    """Tune->serve staleness for a retune generation: the sweep (fused
    mesh, or the serial ledger loop when ``serial=True``) + the
    winner's cold train to its best iteration + the r15 freshness
    legs (publish, warm, canary, flip) charged from the same
    constants ``staleness_model`` uses."""
    t = sweep_time_model(n_configs, n_rows, nfold, rounds_mean,
                         n_buckets, n_devices, group_size)
    sweep_s = t["serial_s"] if serial else t["makespan_s"]
    round_s = t["round_s"]
    train_s = int(rounds_mean) * round_s
    nodes = 2 * int(num_leaves) - 1
    node_bytes = 7 * 4 + 1
    artifact_bytes = int(rounds_mean) * nodes * node_bytes + 4096
    publish_s = artifact_bytes / HOST_WRITE_BYTES_PER_S \
        + CKPT_FIXED_LATENCY_S
    warm_s = int(warm_shapes) * WARM_COMPILE_S_PER_SHAPE
    canary_s = (2 * SERVE_DISPATCH_FIXED_S
                + int(canary_rows) * int(rounds_mean)
                * CANARY_ORACLE_S_PER_ROW_TREE)
    tune_serve_s = sweep_s + train_s + publish_s + warm_s + canary_s \
        + FLIP_S
    return {
        "sweep_s": sweep_s,
        "winner_train_s": train_s,
        "publish_s": publish_s,
        "warm_s": warm_s,
        "canary_s": canary_s,
        "flip_s": FLIP_S,
        "tune_serve_s": tune_serve_s,
        "sweep_frac": sweep_s / tune_serve_s,
    }


@dataclass(frozen=True)
class SweepBudget:
    """One sweep-throughput / tune->serve invariant.

    ``model`` selects the closed form ("time" ->
    :func:`sweep_time_model`, "staleness" ->
    :func:`sweep_staleness_model`); ``metric`` the compared output.
    ``cmp`` is "le" for acceptance bars and "ge" for guard-the-model
    entries (operating points MEANT to breach)."""

    name: str
    budget: float
    metric: str
    cmp: str = "ge"
    model: str = "time"
    n_configs: int = 108
    n_rows: int = 46_000
    nfold: int = 5
    rounds_mean: int = 150
    n_buckets: int = 9
    n_devices: int = 1
    group_size: int = 1
    serial: bool = False
    note: str = ""

    def check(self) -> Dict[str, object]:
        if self.model == "time":
            t = sweep_time_model(
                self.n_configs, self.n_rows, self.nfold,
                self.rounds_mean, self.n_buckets, self.n_devices,
                self.group_size)
        else:
            t = sweep_staleness_model(
                self.n_configs, self.n_rows, self.nfold,
                self.rounds_mean, self.n_buckets, self.n_devices,
                self.group_size, serial=self.serial)
        measured = t[self.metric]
        ok = (measured <= self.budget if self.cmp == "le"
              else measured >= self.budget)
        return {"name": self.name, "mode": "sweep",
                "metric": self.metric, "measured": round(measured, 4),
                "budget": self.budget, "cmp": self.cmp,
                "n_devices": self.n_devices, "ok": ok,
                "note": self.note}


SWEEP_BUDGETS: Tuple[SweepBudget, ...] = (
    SweepBudget("sweep_speedup_d8", 2.0, "speedup", n_devices=8,
                note="r17 acceptance: the 8-device mesh sweeps the "
                     "reference grid >= 2x faster than the serial "
                     "ledger loop (model says ~8.7x: batching x "
                     "mesh, compile amortized per bucket)"),
    SweepBudget("sweep_fused_gain_d1", 1.5, "speedup", n_devices=1,
                note="the fused hyper-batch alone (one device, no "
                     "mesh) beats the serial loop >= 1.5x — batching "
                     "is a win before any scale-out"),
    SweepBudget("sweep_configs_per_hour_d8", 3000.0,
                "configs_per_hour", n_devices=8,
                note="throughput floor the bench reports against: "
                     ">= 3000 configs/hour at D=8 on the reference "
                     "shape (serial manages ~600)"),
    SweepBudget("sweep_tune_serve_slo", TUNE_SERVE_SLO_S,
                "tune_serve_s", cmp="le", model="staleness",
                n_devices=8,
                note="closed-loop bar: a retune generation (full "
                     "sweep + winner train + publish/warm/canary/"
                     "flip) lands inside the 300 s tune->serve SLO "
                     "at D=8"),
    SweepBudget("sweep_serial_blows_tune_slo", TUNE_SERVE_SLO_S,
                "tune_serve_s", cmp="ge", model="staleness",
                serial=True,
                note="guard-the-model: the serial reference loop "
                     "CANNOT meet the tune->serve SLO at the same "
                     "shape — the scheduled mesh is load-bearing "
                     "for closed-loop tuning"),
)


def sweep_budget_by_name(name: str) -> SweepBudget:
    for b in SWEEP_BUDGETS:
        if b.name == name:
            return b
    raise KeyError(name)


def check_sweep_budgets(names: Optional[List[str]] = None
                        ) -> List[Dict[str, object]]:
    specs = (SWEEP_BUDGETS if names is None
             else [sweep_budget_by_name(n) for n in names])
    return [b.check() for b in specs]


# ---------------------------------------------------------------------------
# budget anchors — Layer-2 stale-entry reporting (r16)
# ---------------------------------------------------------------------------
# Every budget family above models a REAL entry point; rename that
# function (or delete its module) and the budget silently keeps passing
# against nothing.  The anchors pin each spec section to the live
# symbols it models, checked with pure ``ast`` in the default lint pass
# (no JAX import, no execution) — a renamed anchor is a lint failure,
# not a silent no-op.

BUDGET_ANCHORS: Dict[str, Tuple[Tuple[str, str], ...]] = {
    # section -> ((repo-relative file, top-level symbol), ...)
    "launch": (
        ("lightgbm_tpu/models/tree.py", "grow_tree"),
        ("lightgbm_tpu/models/fused.py", "run_fused_cv_batch"),
        ("lightgbm_tpu/ops/split.py", "SplitContext"),
    ),
    "comm": (
        ("lightgbm_tpu/parallel/feature_parallel.py",
         "reduce_best_split"),
    ),
    "stream": (
        ("lightgbm_tpu/data/block_store.py", "BlockStore"),
        ("lightgbm_tpu/data/stream_grow.py", "stream_goss_round"),
    ),
    "stream_dp": (
        # r19 streamed x dp: the per-shard store splitter, the lockstep
        # block-round assembler, the round drivers the time/byte models
        # (stream_dp_time_model / stream_dp_bytes_model) charge, and
        # the elastic-resume gate
        ("lightgbm_tpu/data/block_store.py", "shard_block_store"),
        ("lightgbm_tpu/data/stream_dp.py", "dp_block_rounds"),
        ("lightgbm_tpu/data/stream_dp.py", "stream_dp_grow_tree"),
        ("lightgbm_tpu/data/stream_dp.py", "stream_dp_goss_round"),
        ("lightgbm_tpu/analysis/budgets.py", "stream_dp_time_model"),
        ("lightgbm_tpu/analysis/budgets.py", "stream_dp_bytes_model"),
        ("lightgbm_tpu/training/checkpoint.py",
         "validate_parallel_topology"),
    ),
    "serve_slo": (
        ("lightgbm_tpu/serving/runtime.py", "PredictorRuntime"),
        ("lightgbm_tpu/serving/packed.py", "PackedForest"),
        ("lightgbm_tpu/serving/queue.py", "MicroBatcher"),
        ("lightgbm_tpu/serving/mesh.py", "choose_route"),
        ("lightgbm_tpu/serving/mesh.py", "ServingMesh"),
        ("lightgbm_tpu/ops/quantize.py", "wire_transfer"),
        ("lightgbm_tpu/ops/quantize.py", "models_per_byte_gain"),
        ("lightgbm_tpu/ops/quantize.py", "packed_model_bytes"),
    ),
    "predict": (
        # r18 fused predict: the SoA layout, the packer, the mega-kernel
        # entry point, and the tp shard wrapper the launch/VMEM/HBM
        # models (predict_kernel_time) and launch budgets lower or model
        ("lightgbm_tpu/ops/predict.py", "ForestSoA"),
        ("lightgbm_tpu/ops/predict.py", "pack_forest_soa"),
        ("lightgbm_tpu/ops/predict.py", "predict_forest_pallas"),
        ("lightgbm_tpu/serving/mesh.py", "tp_raw_margins_fused"),
    ),
    "ckpt": (
        ("lightgbm_tpu/training/checkpoint.py", "save_checkpoint"),
        ("lightgbm_tpu/training/checkpoint.py", "load_latest"),
    ),
    "freshness": (
        ("lightgbm_tpu/pipeline/daemon.py", "RefreshDaemon"),
        ("lightgbm_tpu/pipeline/staleness.py", "StalenessTracker"),
    ),
    "sweep": (
        ("lightgbm_tpu/sweep/service.py", "SweepService"),
        ("lightgbm_tpu/sweep/scheduler.py", "SweepScheduler"),
        ("lightgbm_tpu/sweep/ledger.py", "SweepLedger"),
    ),
    "screen": (
        # r20 EMA-FS screening: the screener + unified mask-composition
        # layer the growers share, the host-side column view the stream
        # byte model charges, and the round-time model itself
        ("lightgbm_tpu/models/feature_mask.py", "FeatureScreener"),
        ("lightgbm_tpu/models/feature_mask.py", "node_mask_fn"),
        ("lightgbm_tpu/data/block_store.py", "ColumnViewStore"),
        ("lightgbm_tpu/analysis/budgets.py", "feature_screen_time_model"),
    ),
}


def _top_level_symbols(path: str) -> Optional[set]:
    """Top-level def/class names of ``path``, or None when unreadable."""
    import ast as _ast
    import os as _os

    if not _os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        try:
            tree = _ast.parse(f.read())
        except SyntaxError:
            return None
    return {n.name for n in tree.body
            if isinstance(n, (_ast.FunctionDef, _ast.AsyncFunctionDef,
                              _ast.ClassDef))}


def check_budget_anchors(anchors: Optional[Dict[str, Tuple]] = None
                         ) -> List[Dict[str, object]]:
    """One result dict per anchored symbol; ``ok=False`` means the
    budget section references a dead file or renamed symbol."""
    import os as _os

    repo_root = _os.path.dirname(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__))))
    out: List[Dict[str, object]] = []
    cache: Dict[str, Optional[set]] = {}
    for section, pins in sorted((anchors or BUDGET_ANCHORS).items()):
        for rel, symbol in pins:
            path = _os.path.join(repo_root, rel.replace("/", _os.sep))
            if rel not in cache:
                cache[rel] = _top_level_symbols(path)
            syms = cache[rel]
            if syms is None:
                ok, why = False, f"{rel}: file missing or unparseable"
            elif symbol not in syms:
                ok, why = False, (f"`{symbol}` not found at top level of "
                                  f"{rel} — renamed or deleted; update "
                                  f"the budget spec's anchor")
            else:
                ok, why = True, ""
            out.append({"name": f"{section}:{symbol}", "section": section,
                        "path": rel, "symbol": symbol, "ok": ok,
                        "why": why})
    return out

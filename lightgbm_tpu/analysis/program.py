"""graftlint whole-program layer (r16 tentpole).

The r8 engine analyzed one module at a time, so traced/kernel closure
stopped at file boundaries: ``jax.jit(split.best_split)`` in one module
never marked ``best_split`` traced in another.  :class:`Program` fixes
that with a cross-module symbol table + call graph:

* every package module is parsed once into a :class:`ModuleEntry`
  (dotted module name, import table with relative-import resolution,
  the per-module :class:`~.rules._ModuleAnalysis`);
* traced/kernel roots propagate across modules to a global fixed
  point — a bare ``from .split import best_split`` callee, a dotted
  ``split.best_split(...)`` callee, and a reference inside a tracing
  call's arguments all resolve through the import table;
* rules then run per module exactly as before, so every Layer-1
  detector transparently benefits from the wider closure.

GL010 (fault-site registry drift) lives here because it is
whole-program by nature: the registry in :mod:`lightgbm_tpu.faults`,
the consultation sites spread across serving/training/pipeline, and
the chaos tests that must exercise each site are three different sets
of files that have to agree.  :func:`fault_site_findings` checks all
three directions:

1. every site string passed to an injection point exists in
   :data:`~lightgbm_tpu.faults.SITES`;
2. every registered site is consulted somewhere in the package;
3. every registered site is referenced from at least one test module
   (the chaos matrix must not silently stop covering a site).

r20 adds two whole-program families on the same chassis.  GL012's
mesh-context closure rides the exact machinery above: ``shard_map``
references seed meshed functions the way tracing calls seed traced
ones, meshed callers propagate their axis sets across modules, and an
``axis_resolver`` installed per entry lets ``lax.psum(x, DATA_AXIS)``
resolve ``DATA_AXIS`` through the import table to the defining
module's string constant.  GL014 (:func:`parity_anchor_findings`) pins
every bit-identical/tolerance claim in PARITY.md to live ``(file,
symbol)`` pairs — the budgets-layer ``BUDGET_ANCHORS`` discipline,
applied to parity contracts.

Like the rest of Layer 1 this is pure ``ast`` — nothing here imports
JAX or even the package under analysis.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .rules import (Finding, _ModuleAnalysis, apply_waivers, is_kernel_file)

# the shared fault-site registry: module (dotted suffix) and the tuple
# assignments that define it
FAULTS_MODULE_SUFFIX = "faults"
SITE_REGISTRY_NAMES = ("SERVING_SITES", "TRAINING_SITES", "PIPELINE_SITES",
                       "SWEEP_SITES")

# receivers that make a ``.check("site")`` call a fault consultation —
# precision guard: budget specs also have .check() methods (no string
# argument), and unrelated APIs may take string-first .check calls
_INJECTORISH = ("fault", "inject")


def module_name_of(rel_path: str) -> str:
    """Dotted module name for a repo-relative posix path.

    ``lightgbm_tpu/serving/queue.py`` -> ``lightgbm_tpu.serving.queue``;
    ``lightgbm_tpu/__init__.py`` -> ``lightgbm_tpu``.
    """
    p = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    parts = [x for x in p.split("/") if x]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


@dataclass
class ModuleEntry:
    """One parsed module plus its resolved import table."""

    rel: str                                 # repo-relative posix path
    modname: str                             # dotted module name
    src: str
    analysis: Optional[_ModuleAnalysis]      # None when GL000 fired
    parse_finding: Optional[Finding] = None
    # local binding -> absolute dotted module ('split' -> 'pkg.ops.split')
    module_aliases: Dict[str, str] = field(default_factory=dict)
    # local binding -> (absolute module, symbol) for from-imports
    symbol_imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)

    def resolve_imports(self) -> None:
        """Build the absolute import table, resolving relative imports
        against this module's package."""
        if self.analysis is None:
            return
        pkg_parts = self.modname.split(".")
        if not self.rel.endswith("__init__.py"):
            pkg_parts = pkg_parts[:-1]       # containing package
        for node in ast.walk(self.analysis.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.module_aliases[a.asname
                                        or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                base = pkg_parts[:max(0, len(pkg_parts)
                                      - (node.level - 1))] \
                    if node.level else []
                mod = ".".join(base + ([node.module]
                                       if node.module else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.symbol_imports[a.asname or a.name] = (mod, a.name)


class Program:
    """Cross-module symbol table + call graph over a set of modules."""

    def __init__(self, modules: Sequence[Tuple[str, str]]) -> None:
        """``modules`` is a list of (repo-relative posix path, source)."""
        self.entries: List[ModuleEntry] = []
        self.by_module: Dict[str, ModuleEntry] = {}
        for rel, src in modules:
            modname = module_name_of(rel)
            try:
                tree = ast.parse(src)
            except SyntaxError as e:
                entry = ModuleEntry(
                    rel, modname, src, None,
                    Finding("GL000", rel, e.lineno or 1, 0,
                            f"syntax error: {e.msg}"))
            else:
                entry = ModuleEntry(
                    rel, modname, src,
                    _ModuleAnalysis(rel, tree, is_kernel_file(src)))
                entry.resolve_imports()
            self.entries.append(entry)
            self.by_module[modname] = entry
        # GL012: let each module resolve imported axis constants
        # (``from ..parallel.data_parallel import DATA_AXIS``) before the
        # mesh sites are seeded inside the first close_local round
        for e in self.entries:
            if e.analysis is not None:
                e.analysis.axis_resolver = self._axis_resolver_for(e)
        self._close()

    def _axis_resolver_for(self, entry: ModuleEntry):
        def resolve(name: str) -> Optional[str]:
            hit = entry.symbol_imports.get(name)
            if hit is None:
                return None
            target = self.by_module.get(hit[0])
            if target is None or target.analysis is None:
                return None
            return target.analysis.str_constants.get(hit[1])
        return resolve

    # -- cross-module traced/kernel closure ---------------------------------
    def _resolve_chain(self, entry: ModuleEntry,
                       chain: Tuple[str, ...]) -> Optional[
                           Tuple[ModuleEntry, str]]:
        """(target module, symbol) a dotted callee chain refers to, or
        None when it does not land in this program."""
        if not chain:
            return None
        root, rest = chain[0], list(chain[1:])
        if root in entry.symbol_imports:
            mod, sym = entry.symbol_imports[root]
            if not rest:                     # bare imported function
                target = self.by_module.get(mod)
                return (target, sym) if target else None
            # ``from . import split`` then split.best_split(...)
            target = self.by_module.get(f"{mod}.{sym}" if sym else mod) \
                or self.by_module.get(mod)
            if target is not None and len(rest) == 1:
                return target, rest[0]
            return None
        if root in entry.module_aliases:
            base = entry.module_aliases[root]
            # walk intermediate attrs deeper into subpackages
            while len(rest) > 1 and f"{base}.{rest[0]}" in self.by_module:
                base = f"{base}.{rest[0]}"
                rest = rest[1:]
            target = self.by_module.get(base)
            if target is not None and len(rest) == 1:
                return target, rest[0]
        return None

    def _close(self) -> None:
        """Propagate traced/kernel marks across modules to a global
        fixed point (each round re-runs every module's local closure)."""
        for e in self.entries:
            if e.analysis is not None:
                e.analysis.close_local()
        changed = True
        while changed:
            changed = False
            for e in self.entries:
                a = e.analysis
                if a is None:
                    continue
                # references inside tracing-call arguments
                for chain, kern in a.external_traced_refs:
                    hit = self._resolve_chain(e, chain)
                    if hit is not None:
                        target, sym = hit
                        if target.analysis is not None and \
                                target.analysis.seed_traced(sym, kern):
                            changed = True
                # GL012: references inside mesh-entry arguments
                for chain, axes, complete in a.external_mesh_refs:
                    hit = self._resolve_chain(e, chain)
                    if hit is not None:
                        target, sym = hit
                        if target.analysis is not None and \
                                target.analysis.seed_meshed(
                                    sym, axes, complete):
                            changed = True
                # callees of traced/meshed functions
                for info in a.funcs:
                    if not (info.traced or info.meshed):
                        continue
                    for chain in [(c,) for c in info.calls] + \
                            list(info.attr_calls):
                        hit = self._resolve_chain(e, chain)
                        if hit is None:
                            continue
                        target, sym = hit
                        if target.analysis is None:
                            continue
                        if info.traced and target.analysis.seed_traced(
                                sym, info.kernel):
                            changed = True
                        if info.meshed and target.analysis.seed_meshed(
                                sym, info.mesh_axes,
                                not info.mesh_unknown):
                            changed = True
            if changed:
                for e in self.entries:
                    if e.analysis is not None:
                        e.analysis.close_local()

    # -- rule dispatch -------------------------------------------------------
    def run_rules(self) -> List[Finding]:
        out: List[Finding] = []
        for e in self.entries:
            if e.analysis is None:
                out.append(e.parse_finding)
                continue
            out.extend(apply_waivers(e.analysis.run(), e.src))
        out.sort(key=lambda f: (f.path, f.line, f.rule))
        return out


# ---------------------------------------------------------------------------
# GL010 — fault-site registry drift
# ---------------------------------------------------------------------------
def _registry_sites(entry: ModuleEntry) -> Dict[str, int]:
    """site -> registry line, from the ``*_SITES`` tuple assignments."""
    sites: Dict[str, int] = {}
    if entry.analysis is None:
        return sites
    for node in ast.walk(entry.analysis.tree):
        if not isinstance(node, ast.Assign):
            continue
        names = {t.id for t in node.targets if isinstance(t, ast.Name)}
        if not (names & set(SITE_REGISTRY_NAMES)):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            for el in node.value.elts:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, str):
                    sites[el.value] = node.lineno
    return sites


def _is_injectorish(recv: ast.AST) -> bool:
    names: List[str] = []
    while isinstance(recv, ast.Attribute):
        names.append(recv.attr)
        recv = recv.value
    if isinstance(recv, ast.Name):
        names.append(recv.id)
    return any(m in n.lower() for n in names for m in _INJECTORISH)


def _consultation_sites(entry: ModuleEntry) -> List[Tuple[str, ast.AST]]:
    """(site string, node) for every fault-injection consultation:
    ``<injectorish>.check("site")``, ``.arm("site"|site=...)``, and
    ``FaultSpec("site"|site=...)``."""
    out: List[Tuple[str, ast.AST]] = []
    if entry.analysis is None:
        return out

    def const_site(call: ast.Call) -> Optional[str]:
        if call.args and isinstance(call.args[0], ast.Constant) and \
                isinstance(call.args[0].value, str):
            return call.args[0].value
        for kw in call.keywords:
            if kw.arg == "site" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return kw.value.value
        return None

    for node in ast.walk(entry.analysis.tree):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            if meth == "check" and _is_injectorish(node.func.value):
                site = const_site(node)
                if site is not None:
                    out.append((site, node))
            elif meth == "arm":
                site = const_site(node)
                if site is not None:
                    out.append((site, node))
        elif isinstance(node.func, ast.Name) and \
                node.func.id == "FaultSpec":
            site = const_site(node)
            if site is not None:
                out.append((site, node))
    return out


def _string_constants(tree: ast.Module) -> Set[str]:
    return {n.value for n in ast.walk(tree)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)}


def fault_site_findings(
        program: Program,
        test_sources: Sequence[Tuple[str, str]] = ()) -> List[Finding]:
    """GL010: registry <-> usage <-> test coverage, all three directions.

    ``test_sources`` is (path, source) for the chaos/resilience test
    modules; when empty the test-coverage direction is skipped (per-file
    CLI invocations don't see the test tree).
    """
    registry_entry = None
    for e in program.entries:
        if e.modname.endswith("." + FAULTS_MODULE_SUFFIX) or \
                e.modname == FAULTS_MODULE_SUFFIX:
            if _registry_sites(e):
                registry_entry = e
                break
    if registry_entry is None:
        return []                    # nothing to drift against
    registered = _registry_sites(registry_entry)

    findings: List[Finding] = []
    used: Set[str] = set()
    for e in program.entries:
        for site, node in _consultation_sites(e):
            used.add(site)
            if site not in registered:
                findings.append(Finding(
                    "GL010", e.rel, node.lineno, node.col_offset,
                    f"fault site {site!r} is not in the shared SITES "
                    f"registry ({registry_entry.rel}) — FaultSpec "
                    f"construction will raise at runtime; register it "
                    f"or fix the typo"))
    # the registry module itself consults sites through subscripts
    # (hits['clock']) rather than .check() — count its string constants
    # as usage, excluding the registry assignments themselves
    if registry_entry.analysis is not None:
        reg_lines = set(_registry_sites(registry_entry).values())
        for node in ast.walk(registry_entry.analysis.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    node.value in registered and \
                    node.lineno not in reg_lines:
                used.add(node.value)

    for site, line in sorted(registered.items()):
        if site not in used:
            findings.append(Finding(
                "GL010", registry_entry.rel, line, 0,
                f"registered fault site {site!r} is never consulted "
                f"(.check/.arm/FaultSpec) anywhere in the package — "
                f"dead registry entries hide coverage gaps; wire it in "
                f"or remove it"))

    if test_sources:
        covered: Set[str] = set()
        for _, src in test_sources:
            try:
                tree = ast.parse(src)
            except SyntaxError:
                continue
            covered |= _string_constants(tree) & set(registered)
        for site, line in sorted(registered.items()):
            if site not in covered:
                findings.append(Finding(
                    "GL010", registry_entry.rel, line, 0,
                    f"registered fault site {site!r} is not referenced "
                    f"by any chaos/resilience test — the chaos matrix "
                    f"silently stopped covering it"))
    return findings


# ---------------------------------------------------------------------------
# GL014 — parity-contract anchors
# ---------------------------------------------------------------------------
# Every PARITY.md section that makes a bit-identical / tolerance claim
# must be pinned to the live code and tests that carry the claim — the
# BUDGET_ANCHORS discipline (analysis/budgets.py), applied to parity
# contracts.  Keys are PARITY.md `## ` heading texts, values are
# (repo-relative file, top-level symbol) pairs.  Renaming or deleting a
# pinned symbol fails the lint NAMING the stale contract, so the doc
# and the code cannot drift apart silently.
PARITY_DOC = "PARITY.md"
_PARITY_CLAIM_RE = re.compile(r"bit-?identical|bitwise|tolerance", re.I)

PARITY_ANCHORS: Dict[str, Tuple[Tuple[str, str], ...]] = {
    "Quantized-threshold comparison rule (r18 serving)": (
        ("lightgbm_tpu/ops/predict.py", "ForestSoA"),
        ("lightgbm_tpu/ops/predict.py", "pack_forest_soa"),
        ("lightgbm_tpu/ops/predict.py", "predict_forest_pallas"),
        ("lightgbm_tpu/ops/quantize.py", "ThresholdBoundError"),
        ("tests/test_predict_fused.py", "test_bin_edge_routes_left"),
        ("tests/test_predict_fused.py",
         "test_threshold_bound_rejected_at_ingest"),
        ("tests/test_predict_fused.py", "test_runtime_oracle_parity"),
    ),
    "Streamed-dp parity rule: bit-identical vs tolerance-gated (r19)": (
        ("lightgbm_tpu/data/stream_dp.py", "stream_dp_grow_tree"),
        ("lightgbm_tpu/ops/histogram.py", "histogram_merge"),
        ("lightgbm_tpu/ops/quantize.py", "wire_transfer"),
        ("tests/test_stream_dp.py",
         "test_stream_dp_bit_identical_where_exact"),
        ("tests/test_stream_dp.py",
         "test_stream_dp_general_data_dp_parity_bar"),
        ("tests/test_stream_dp.py",
         "test_elastic_resume_first_round_bit_identical_across_d"),
    ),
    "Feature-screening exactness rule (r20)": (
        ("lightgbm_tpu/models/feature_mask.py", "FeatureScreener"),
        ("lightgbm_tpu/models/feature_mask.py", "compose_tree_mask"),
        ("lightgbm_tpu/models/feature_mask.py", "remap_split_features"),
        ("lightgbm_tpu/data/block_store.py", "ColumnViewStore"),
        ("tests/test_screening.py",
         "test_screen_off_bit_identical_strict_and_wave"),
        ("tests/test_screening.py",
         "test_screened_in_memory_matches_streamed"),
        ("tests/test_screening.py",
         "test_refresh_rediscovers_late_gain_feature"),
    ),
}


def _top_level_symbols(path: Path) -> Optional[Set[str]]:
    """Top-level def/class/assignment names of a module; None when the
    file is missing or does not parse (the caller reports that as the
    stale-anchor finding, not a crash)."""
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return None
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            out.add(node.name)
        elif isinstance(node, ast.Assign):
            out |= {t.id for t in node.targets if isinstance(t, ast.Name)}
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            out.add(node.target.id)
    return out


def _parity_sections(text: str) -> Dict[str, Tuple[int, str]]:
    """``## `` heading -> (1-based heading line, section body)."""
    sections: Dict[str, Tuple[int, str]] = {}
    title: Optional[str] = None
    start = 0
    body: List[str] = []
    for i, line in enumerate(text.splitlines(), start=1):
        if line.startswith("## "):
            if title is not None:
                sections[title] = (start, "\n".join(body))
            title = line[3:].strip()
            start = i
            body = []
        elif title is not None:
            body.append(line)
    if title is not None:
        sections[title] = (start, "\n".join(body))
    return sections


def parity_anchor_findings(
        repo_root: Path,
        anchors: Optional[Dict[str, Tuple[Tuple[str, str], ...]]] = None,
        parity_md: Optional[str] = None) -> List[Finding]:
    """GL014: PARITY.md contracts <-> live code, both directions.

    1. every claim-bearing section (matches ``bit-identical``/
       ``bitwise``/``tolerance``) has a PARITY_ANCHORS entry;
    2. every PARITY_ANCHORS key names a section that still exists;
    3. every pinned (file, symbol) resolves to a live top-level symbol.

    ``anchors``/``parity_md`` are injectable for tests; the default pass
    reads ``PARITY_ANCHORS`` and ``<repo_root>/PARITY.md``.
    """
    if anchors is None:
        anchors = PARITY_ANCHORS
    if parity_md is None:
        doc = Path(repo_root) / PARITY_DOC
        if not doc.is_file():
            if anchors:
                return [Finding(
                    "GL014", PARITY_DOC, 1, 0,
                    f"{len(anchors)} parity contract(s) are anchored but "
                    f"{PARITY_DOC} is missing — the contract document "
                    f"moved or was deleted without retiring its anchors")]
            return []
        parity_md = doc.read_text(encoding="utf-8")

    findings: List[Finding] = []
    sections = _parity_sections(parity_md)

    for title, (line, body) in sorted(sections.items(),
                                      key=lambda kv: kv[1][0]):
        # a CLAIM is prose (or the heading itself) — markdown table rows
        # are feature inventories, not parity contracts
        prose = "\n".join(ln for ln in body.splitlines()
                          if not ln.lstrip().startswith("|"))
        if _PARITY_CLAIM_RE.search(title) or _PARITY_CLAIM_RE.search(prose):
            if title not in anchors:
                findings.append(Finding(
                    "GL014", PARITY_DOC, line, 0,
                    f"section {title!r} makes a bit-identical/tolerance "
                    f"claim but has no PARITY_ANCHORS entry — pin the "
                    f"claim to its (file, symbol) pairs in "
                    f"analysis/program.py so renames fail the lint"))

    symcache: Dict[str, Optional[Set[str]]] = {}
    for title in sorted(anchors):
        if title not in sections:
            findings.append(Finding(
                "GL014", PARITY_DOC, 1, 0,
                f"PARITY_ANCHORS pins section {title!r} but {PARITY_DOC} "
                f"has no such heading — the contract was renamed or "
                f"removed; update the anchor key (analysis/program.py) "
                f"in the same change"))
            continue
        line = sections[title][0]
        for rel, sym in anchors[title]:
            if rel not in symcache:
                symcache[rel] = _top_level_symbols(Path(repo_root) / rel)
            syms = symcache[rel]
            if syms is None:
                findings.append(Finding(
                    "GL014", PARITY_DOC, line, 0,
                    f"contract {title!r} is anchored to {rel} which is "
                    f"missing or unparseable — the parity-bearing module "
                    f"moved; re-pin the contract"))
            elif sym not in syms:
                findings.append(Finding(
                    "GL014", PARITY_DOC, line, 0,
                    f"contract {title!r} is anchored to {rel}:{sym} "
                    f"which no longer exists at top level — the "
                    f"parity-bearing symbol was renamed or deleted; "
                    f"update the contract and its anchor together"))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings

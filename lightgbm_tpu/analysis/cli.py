"""``python -m lightgbm_tpu lint`` — the graftlint front end.

Default run: Layer 1 (AST rules + baseline, whole-program in the
no-paths case) plus the pure-arithmetic Layer-2 checks (VMEM estimates,
budget models, budget anchors) — fast, no compilation.  ``--budgets``
adds the HLO launch budgets and the zero-recompile sweeps (lowers real
entry points; ~a minute on CPU).

Exit codes (machine-readable by construction):

* 0 — clean;
* 1 — findings above the baseline / budget violations;
* 2 — usage or baseline-format error (``graftlint: usage-error: ...``);
* 3 — internal analyzer error (``graftlint: internal-error: ...``) —
  the analyzer itself broke, which must never masquerade as "the tree
  has findings" in CI.
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional

from .baseline import BaselineError
from .engine import DEFAULT_BASELINE, run_lint

_USAGE = """\
usage: python -m lightgbm_tpu lint [paths...] [options]

options:
  --budgets         also run HLO launch budgets + recompile sweeps (slow)
  --no-vmem         skip the VMEM footprint estimates
  --no-baseline     report accepted debt too (ratchet view)
  --baseline PATH   alternate baseline file
  --explain GLxxx   print the RULES.md section for a rule id and exit
  --format json     machine-readable report on stdout
  --format github   GitHub workflow-annotation lines (::error file=...)
  -q, --quiet       findings only, no summary
"""


def _explain(rule_id: str) -> int:
    """Print the RULES.md section for one rule id.  Unknown ids exit 2
    with the usage-error one-liner (machine-readable, like every other
    CLI misuse)."""
    import os
    import re

    rid = rule_id.upper()
    rules_md = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "RULES.md")
    with open(rules_md, encoding="utf-8") as f:
        text = f.read()
    match = re.search(rf"^## {re.escape(rid)}\b.*?(?=^## |\Z)",
                      text, re.M | re.S)
    if match is None:
        known = re.findall(r"^## (GL\d{3})\b", text, re.M)
        print(f"graftlint: usage-error: unknown rule id {rule_id!r} "
              f"(known: {', '.join(known)})", file=sys.stderr)
        return 2
    print(match.group(0).rstrip())
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    """Parse args and run; every internal failure becomes exit 3 with a
    typed one-liner (the r15 CLI convention: no tracebacks)."""
    try:
        return _run(argv)
    except SystemExit:
        raise
    except BaselineError as e:
        print(f"graftlint: usage-error: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # noqa: BLE001 — the exit-3 contract boundary
        print(f"graftlint: internal-error: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 3


def _run(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    budgets, vmem = False, True
    use_baseline = True
    fmt = "text"
    quiet = False
    baseline_path = DEFAULT_BASELINE
    paths: List[str] = []
    i = 0
    while i < len(args):
        a = args[i]
        if a in ("-h", "--help"):
            print(_USAGE)
            return 0
        if a == "--budgets":
            budgets = True
        elif a == "--no-vmem":
            vmem = False
        elif a == "--no-baseline":
            use_baseline = False
        elif a == "--baseline":
            i += 1
            if i >= len(args):
                print("--baseline needs a path", file=sys.stderr)
                return 2
            baseline_path = args[i]
        elif a == "--explain":
            i += 1
            if i >= len(args):
                print("graftlint: usage-error: --explain needs a rule id "
                      "(e.g. GL012)", file=sys.stderr)
                return 2
            return _explain(args[i])
        elif a == "--format":
            i += 1
            if i >= len(args) or args[i] not in ("text", "json",
                                                 "github"):
                print("--format takes text|json|github",
                      file=sys.stderr)
                return 2
            fmt = args[i]
        elif a in ("-q", "--quiet"):
            quiet = True
        elif a.startswith("-"):
            print(f"unknown option {a!r}\n{_USAGE}", file=sys.stderr)
            return 2
        else:
            paths.append(a)
        i += 1

    report = run_lint(paths or None,
                      baseline_path if use_baseline else None)

    sections = {"layer1": {
        "files_checked": report.files_checked,
        "unsuppressed": [f.format() for f in report.unsuppressed],
        "suppressed": [f.format() for f in report.suppressed],
        "stale_suppressions": [
            f"{s.rule} {s.path} (count {s.count}, used {s.used}): "
            f"{s.reason}" for s in report.stale],
    }}
    failed = bool(report.unsuppressed)

    if vmem:
        from .vmem import check_vmem_specs

        res = check_vmem_specs()
        sections["vmem"] = res
        failed |= any(not r["ok"] for r in res)

    # pure arithmetic — always on, like the VMEM estimates
    from .budgets import (check_ckpt_budgets, check_comm_budgets,
                          check_comm_time_budgets, check_freshness_budgets,
                          check_screen_budgets, check_serve_slo_budgets,
                          check_stream_budgets, check_stream_dp_budgets,
                          check_sweep_budgets)

    res = check_comm_budgets()
    sections["comm_budgets"] = res
    failed |= any(not r["ok"] for r in res)

    res = check_comm_time_budgets()
    sections["comm_time"] = res
    failed |= any(not r["ok"] for r in res)

    res = check_stream_budgets()
    sections["stream_time"] = res
    failed |= any(not r["ok"] for r in res)

    res = check_stream_dp_budgets()
    sections["stream_dp"] = res
    failed |= any(not r["ok"] for r in res)

    res = check_serve_slo_budgets()
    sections["serve_slo"] = res
    failed |= any(not r["ok"] for r in res)

    res = check_ckpt_budgets()
    sections["ckpt"] = res
    failed |= any(not r["ok"] for r in res)

    res = check_freshness_budgets()
    sections["freshness"] = res
    failed |= any(not r["ok"] for r in res)

    res = check_sweep_budgets()
    sections["sweep"] = res
    failed |= any(not r["ok"] for r in res)

    res = check_screen_budgets()
    sections["screen"] = res
    failed |= any(not r["ok"] for r in res)

    # Layer-2 stale-entry reporting: budget specs must anchor to live
    # symbols — pure ast, so it rides in the default pass
    from .budgets import check_budget_anchors

    res = check_budget_anchors()
    sections["budget_anchors"] = res
    failed |= any(not r["ok"] for r in res)

    if budgets:
        from .budgets import check_launch_budgets, check_recompile_specs

        res = check_launch_budgets()
        sections["launch_budgets"] = res
        failed |= any(not r["ok"] for r in res)
        res = check_recompile_specs()
        sections["recompile"] = res
        failed |= any(not r["ok"] for r in res)

    if fmt == "json":
        sections["ok"] = not failed
        print(json.dumps(sections, indent=1))
        return 1 if failed else 0

    if fmt == "github":
        # workflow-annotation lines: findings anchor file+line, budget /
        # anchor failures annotate without a location
        for f in report.unsuppressed:
            print(f"::error file={f.path},line={f.line},"
                  f"col={f.col + 1},title=graftlint {f.rule}::"
                  f"{f.message}")
        for line in sections["layer1"]["stale_suppressions"]:
            print(f"::warning title=graftlint stale baseline::{line}")
        for key, rs in sections.items():
            if key == "layer1":
                continue
            for r in rs:
                if not r["ok"]:
                    why = r.get("why") or json.dumps(
                        {k: v for k, v in r.items() if k != "name"})
                    print(f"::error title=graftlint {key}::"
                          f"{r['name']}: {why}")
        return 1 if failed else 0

    l1 = sections["layer1"]
    for line in l1["unsuppressed"]:
        print(line)
    if not quiet:
        for line in l1["stale_suppressions"]:
            print(f"stale baseline entry: {line}")
        for key in ("vmem", "comm_budgets", "comm_time", "stream_time",
                    "stream_dp", "serve_slo", "ckpt", "freshness",
                    "sweep", "screen", "budget_anchors",
                    "launch_budgets", "recompile"):
            for r in sections.get(key, ()):
                mark = "ok" if r["ok"] else "FAIL"
                detail = (f"{r['estimated_mb']}/{r['budget_mb']} MB"
                          if key == "vmem" else
                          f"{r['measured']} B ({r['drop_x']}x vs psum, "
                          f"floor {r['min_drop_x']}x)"
                          if key == "comm_budgets" else
                          f"{r['measured']*100:.0f}% hidden "
                          f"({r['exposed_ms']:.3f} ms exposed of "
                          f"{r['comm_ms']:.3f} ms, floor "
                          f"{r['budget']*100:.0f}%)"
                          if key in ("comm_time", "stream_time") else
                          f"{r['path']}" + (f" ({r['why']})"
                                            if r["why"] else "")
                          if key == "budget_anchors" else
                          f"{r.get('measured', r.get('compiles'))}"
                          f"/{r.get('budget', r.get('max_compiles'))}")
                print(f"[{mark}] {key}:{r['name']} {detail}")
        n_unsup = len(l1["unsuppressed"])
        layers = (["vmem"] if vmem else []) + (
            ["launch budgets", "recompile sweeps"] if budgets else [])
        print(f"graftlint: {l1['files_checked']} files, {n_unsup} "
              f"finding(s), {len(l1['suppressed'])} baselined"
              + (f"; {' + '.join(layers)} "
                 + ("FAILED" if failed and not n_unsup else "ok")
                 if layers else ""))
    return 1 if failed else 0

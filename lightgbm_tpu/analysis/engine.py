"""graftlint driver: walk the package, run Layer 1, apply the baseline.

The engine is deliberately import-free with respect to JAX — Layer 1 is
pure ``ast`` so ``lint`` stays fast (and runnable on machines with no
accelerator stack at all).  Layer 2 (budgets/vmem) lives in
``analysis.budgets`` / ``analysis.vmem`` and is pulled in by the CLI only
when asked.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Iterable, List, Optional

from .baseline import (BaselineResult, Suppression, apply_baseline,
                       parse_baseline)
from .program import Program, fault_site_findings, parity_anchor_findings
from .rules import Finding, analyze_source

# Directories never linted: fixtures are deliberately-broken snippets,
# __pycache__ is noise.
_SKIP_DIRS = {"__pycache__", "fixtures", ".git"}

_HERE = os.path.dirname(os.path.abspath(__file__))
DEFAULT_BASELINE = os.path.join(_HERE, "baseline.toml")
PACKAGE_ROOT = os.path.dirname(_HERE)          # lightgbm_tpu/
REPO_ROOT = os.path.dirname(PACKAGE_ROOT)


def iter_py_files(roots: Iterable[str]) -> List[str]:
    out: List[str] = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def rel_path(path: str) -> str:
    """Repo-relative posix path — the canonical anchor form findings and
    baseline entries use, so the baseline is machine-independent."""
    ap = os.path.abspath(path)
    if ap.startswith(REPO_ROOT + os.sep):
        ap = ap[len(REPO_ROOT) + 1:]
    return ap.replace(os.sep, "/")


@dataclass
class LintReport:
    files_checked: int = 0
    findings: List[Finding] = field(default_factory=list)
    unsuppressed: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    stale: List[Suppression] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.unsuppressed


def _read_sources(paths: Iterable[str]) -> List[tuple]:
    out = []
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as f:
            out.append((rel_path(path), f.read()))
    return out


def run_lint(paths: Optional[Iterable[str]] = None,
             baseline_path: Optional[str] = DEFAULT_BASELINE) -> LintReport:
    """Lint ``paths`` and fold in the baseline.

    With no explicit ``paths`` (the default pass) the whole package is
    analyzed as one :class:`~.program.Program`: traced/kernel closure
    (and the r20 mesh-axis closure) crosses module boundaries, GL010
    checks the fault-site registry against every consultation site and
    the chaos-test tree, and GL014 pins PARITY.md's bit-identical/
    tolerance contracts to live (file, symbol) anchors.  Explicit
    paths keep the r8 per-file behavior (fixtures, CLI-on-a-file) —
    cross-module rules need the whole program and are skipped there.

    ``baseline_path=None`` disables suppression.  GL000 parse failures
    are never baselined and never waived: a tree that does not parse
    fails the gate, full stop.
    """
    report = LintReport()
    if paths is None:
        modules = _read_sources([PACKAGE_ROOT])
        program = Program(modules)
        report.findings.extend(program.run_rules())
        tests_dir = os.path.join(REPO_ROOT, "tests")
        test_sources = (_read_sources([tests_dir])
                        if os.path.isdir(tests_dir) else [])
        report.findings.extend(fault_site_findings(program, test_sources))
        # GL014: PARITY.md contracts pinned to live (file, symbol) pairs
        report.findings.extend(parity_anchor_findings(REPO_ROOT))
        report.files_checked = len(modules)
    else:
        for rel, src in _read_sources(paths):
            report.findings.extend(analyze_source(rel, src))
            report.files_checked += 1
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule))

    suppressions: List[Suppression] = []
    if baseline_path and os.path.exists(baseline_path):
        with open(baseline_path, encoding="utf-8") as f:
            suppressions = parse_baseline(f.read())
    parse_failures = [f for f in report.findings if f.rule == "GL000"]
    rest = [f for f in report.findings if f.rule != "GL000"]
    res: BaselineResult = apply_baseline(rest, suppressions)
    report.unsuppressed = parse_failures + res.unsuppressed
    report.suppressed = res.suppressed
    report.stale = res.stale
    return report

"""graftlint Layer 2 — VMEM footprint estimates for the Pallas kernels.

Every Pallas kernel in the workbench keeps its accumulator resident in
VMEM; a v5e core has ~16 MB of it.  The r3/r4 OOMs (criteo efb_off 54 MB
accumulator, int8 relayout blowup) were all of the same species: a buffer
sized from NOMINAL dims when the hardware pads to (8, 128) tiles.  These
estimators therefore model the PADDED bytes of every VMEM-resident block
at representative production shapes (Higgs F=28, MSLR F=136, B=256) and
assert headroom against the 16 MB budget.

The hist-fused estimate calls the kernel's own ``_vmem_blocking`` so the
check can never drift from what the kernel actually allocates: if someone
retunes the blocking, the estimate follows automatically and this gate
re-validates the result.

Pure math — no compilation, no device; runs in the default ``lint`` pass.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Tuple

VMEM_BUDGET_BYTES = 16 * 1024 * 1024          # v5e per-core VMEM
LANE = 128                                    # minor-dim tile
SUBLANE = 8                                   # second-minor tile (32-bit)


def padded_bytes(shape: Tuple[int, ...], itemsize: int = 4) -> int:
    """Bytes a buffer occupies in VMEM after (8, 128) tiling.

    The minor dim pads to 128 lanes; the second-minor to 8 sublanes (the
    32-bit sublane count — bf16/int8 pack denser, but estimating with 8
    over-counts, which is the safe direction for a budget check)."""
    dims = list(shape)
    if not dims:
        return itemsize
    dims[-1] = -(-dims[-1] // LANE) * LANE
    if len(dims) >= 2:
        dims[-2] = -(-dims[-2] // SUBLANE) * SUBLANE
    total = itemsize
    for d in dims:
        total *= int(d)
    return total


def hist_fused_bytes(num_features: int, num_bins: int, k: int) -> int:
    """Estimated peak VMEM of one ``hist_fused_pallas`` grid step.

    Accumulator block [f_blk, B, k] (lane-padded k) + the per-chunk tile
    model the kernel's own blocking enforces (one-hot, folded stats,
    staged bins, masks, double-buffered inputs)."""
    from ..ops.histogram_pallas import _vmem_blocking

    f_blk, _, _, chunk = _vmem_blocking(num_features, num_bins, k)
    out_bytes = padded_bytes((f_blk, num_bins, k))
    # per-row tile model, same accounting _vmem_blocking budgets against
    per_row = 2 * num_bins + 10 * k + 8 * f_blk + 128
    return out_bytes + chunk * per_row


def split_iter_bytes(num_features: int, num_bins: int,
                     capacity: int, nc: int = 24) -> int:
    """Estimated peak VMEM of one ``split_iter_pallas`` call: whole-array
    blocks (no grid) for 5 inputs + 2 outputs, plus 2x headroom for the
    kernel's in-VMEM intermediates (per-feature gain scan rows, cumsum
    temporaries)."""
    hist2_t = padded_bytes((2, num_features, 3, num_bins))
    table = padded_bytes((capacity, nc))
    fmask = padded_bytes((1, num_features))
    aux = padded_bytes((1, 8))
    scal = padded_bytes((1, 16))
    io = hist2_t + table + fmask + aux + scal + table + aux
    return 2 * io


@dataclass(frozen=True)
class VmemSpec:
    """One kernel at one representative shape vs the 16 MB budget."""

    name: str
    estimator: Callable[[], int]
    note: str = ""

    def check(self) -> Dict[str, object]:
        est = int(self.estimator())
        return {"name": self.name, "estimated_bytes": est,
                "estimated_mb": round(est / (1024 * 1024), 2),
                "budget_mb": VMEM_BUDGET_BYTES // (1024 * 1024),
                "ok": est <= VMEM_BUDGET_BYTES, "note": self.note}


# k = num_segments * S (S=3 grad/hess/count); wave-regime kernels run 42
# segments per wave (fused-CV production shape), the root pass runs 1.
VMEM_SPECS: Tuple[VmemSpec, ...] = (
    VmemSpec("hist_fused_higgs_root",
             lambda: hist_fused_bytes(28, 256, 3),
             note="Higgs F=28 B=256, root pass (k=3, lane-pads to 128)"),
    VmemSpec("hist_fused_higgs_wave",
             lambda: hist_fused_bytes(28, 256, 126),
             note="Higgs F=28 B=256, 42-segment wave (k=126)"),
    VmemSpec("hist_fused_mslr_wave",
             lambda: hist_fused_bytes(136, 256, 126),
             note="MSLR F=136 B=256 — the shape that forced feature "
                  "blocking (18 MB unblocked)"),
    VmemSpec("split_iter_cv31",
             lambda: split_iter_bytes(28, 256, capacity=61),
             note="r7 mega-kernel, num_leaves=31 (capacity 61), Higgs"),
    VmemSpec("split_iter_mslr",
             lambda: split_iter_bytes(136, 256, capacity=61),
             note="r7 mega-kernel at the MSLR feature width"),
)


def check_vmem_specs() -> List[Dict[str, object]]:
    return [s.check() for s in VMEM_SPECS]

"""graftlint — static + trace-level enforcement of the workbench's
compile-time invariants.

Layer 1 (:mod:`.rules`, :mod:`.engine`): pure-AST detection of JAX
footguns (traced-value branching, host syncs in traced code, f64 traps,
static_argnames misuse, in-place mutation, donated-buffer reuse, kernel
dots without an accumulation dtype), with accepted debt ledgered in
``baseline.toml`` (:mod:`.baseline`).

Layer 2 (:mod:`.budgets`, :mod:`.vmem`): declarative per-entry-point HLO
launch budgets, zero-recompile guarantees for the serving bucket ladder
and the fused train step, and padded VMEM footprints vs the 16 MB v5e
scope.

Front ends: ``python -m lightgbm_tpu lint`` (:mod:`.cli`),
``tests/test_graftlint.py`` (tier-1 bridge), ``tools/check.sh``.
"""

from .engine import LintReport, run_lint          # noqa: F401
from .rules import RULE_IDS, Finding, analyze_source  # noqa: F401

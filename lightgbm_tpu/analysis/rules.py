"""graftlint Layer 1 — AST rules for the workbench's JAX footguns.

Every rule answers one question the type system cannot: "does this code
keep the invariants rounds 6-7 paid for?"  The detectors are deliberately
HIGH-PRECISION heuristics: a finding should be either a real bug or a
deliberate decision worth a baseline entry — a linter the tree cannot keep
green gets deleted, not obeyed.

Traced-context model
--------------------
A function is *traced* when JAX (not Python) runs its body:

* decorated with ``jax.jit`` / ``jax.vmap`` / ``functools.partial(jax.jit,
  ...)`` / ``pl.when(...)`` and friends;
* its name appears inside the arguments of a tracing call
  (``jax.jit(f)``, ``lax.scan(f, ...)``, ``pl.pallas_call(partial(f,
  ...), ...)``, ``jax.vmap(f)(x)``, ...);
* it is lexically nested in a traced function; or
* it is called from a traced function — in the same module, or (r16,
  whole-program mode) from a traced function in ANOTHER module through
  the cross-module call graph in :mod:`.program` (tracing is transitive
  through plain Python calls, and Python calls cross file boundaries).

A function is additionally a *kernel* when it reaches ``pl.pallas_call``
or takes ``*_ref`` parameters — kernels get the dtype-discipline rules.

r16 adds four production-loop families on the same chassis: GL008
determinism (wall-clock / unseeded RNG outside the injectable-clock
contract), GL009 lock discipline (attributes mutated both inside and
outside ``with self._lock``), GL010 fault-site registry drift (lives in
:mod:`.program` — it is whole-program by nature), and GL011 typed-error
discipline (bare ``except:``, ``raise Exception``, swallowed handlers).

See analysis/RULES.md for one bad/good example per rule.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

# call targets (final attribute name) that trace their function arguments
TRACING_CALLS = {
    "jit", "pjit", "vmap", "pmap", "grad", "value_and_grad", "hessian",
    "scan", "while_loop", "fori_loop", "cond", "switch", "associative_scan",
    "pallas_call", "custom_jvp", "custom_vjp", "checkpoint", "remat",
    "shard_map", "xmap", "named_call", "when",
}

# decorators (final attribute name) that make the decorated def traced
TRACING_DECORATORS = TRACING_CALLS - {"scan", "while_loop", "fori_loop",
                                      "cond", "switch"}

# attribute calls that force a device->host synchronization
HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready", "copy_to_host"}

# numpy-namespace roots — numpy ops on tracers either crash or silently
# concretize
NUMPY_ROOTS = {"np", "numpy", "onp"}

JAX_EXPR_ROOTS = {"jnp", "jax", "lax", "pl", "pltpu"}

# jax-namespace calls that return HOST constants (fixed at trace time) —
# branching on these is fine
HOST_CONSTANT_JAX_CALLS = {
    "default_backend", "devices", "local_devices", "device_count",
    "local_device_count", "process_index", "process_count",
}

KERNEL_DOT_CALLS = {"dot_general", "dot", "matmul", "einsum"}

# -- GL008: determinism --------------------------------------------------
# ``time`` module calls that read (or stall on) the wall clock.  A bare
# REFERENCE (``clock=time.monotonic`` as a default) is the sanctioned
# injection idiom and never matches — only calls do.
WALL_CLOCK_CALLS = {
    "time", "sleep", "monotonic", "perf_counter", "process_time",
    "time_ns", "monotonic_ns", "perf_counter_ns", "process_time_ns",
}
DATETIME_NOW_CALLS = {"now", "utcnow", "today"}
# ``random`` module functions that consume the process-global RNG
PY_RANDOM_FNS = {
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "betavariate",
    "expovariate", "getrandbits", "seed",
}
# np.random constructors that are deterministic WHEN SEEDED
NP_RNG_CONSTRUCTORS = {"default_rng", "RandomState", "Generator",
                       "SeedSequence", "PCG64", "Philox"}

# -- GL009: lock discipline ----------------------------------------------
LOCK_FACTORIES = {"Lock", "RLock"}
# container methods that mutate their receiver in place
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "remove",
    "discard", "clear",
}
HEAPQ_MUTATORS = {"heappush", "heappop", "heappushpop", "heapreplace"}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    @property
    def anchor(self) -> str:
        return f"{self.path}:{self.line}"

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message}")


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------
def _attr_chain(node: ast.AST) -> List[str]:
    """['jax', 'numpy', 'asarray'] for jax.numpy.asarray; [] if not a
    plain dotted name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def _root_name(node: ast.AST) -> Optional[str]:
    """The base Name an expression is built on (x for x[0].T.foo())."""
    while True:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            return None


def _call_target(call: ast.Call) -> Tuple[Optional[str], List[str]]:
    """(final attr name, full dotted chain) of a call's callee."""
    chain = _attr_chain(call.func)
    if chain:
        return chain[-1], chain
    if isinstance(call.func, ast.Name):
        return call.func.id, [call.func.id]
    return None, []


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _ordered_walk(node: ast.AST, skip_funcs: bool = True) -> Iterator[ast.AST]:
    """Pre-order, source-order walk that (optionally) does not descend
    into nested function definitions."""
    for child in ast.iter_child_nodes(node):
        if skip_funcs and isinstance(child, _FUNC_NODES):
            continue
        yield child
        yield from _ordered_walk(child, skip_funcs)


def _static_names_from_call(call: ast.Call) -> Set[str]:
    """Parameter names a jit call marks static (literal forms only)."""
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.add(e.value)
    return out


def _is_jit_chain(node: ast.AST) -> bool:
    chain = _attr_chain(node)
    return bool(chain) and chain[-1] in ("jit", "pjit")


# ---------------------------------------------------------------------------
# scope collection
# ---------------------------------------------------------------------------
@dataclass
class _FuncInfo:
    node: ast.AST
    name: str                       # '' for lambdas
    parent: Optional["_FuncInfo"]
    params: Set[str] = field(default_factory=set)
    traced: bool = False
    kernel: bool = False
    static_params: Set[str] = field(default_factory=set)
    jit_decorated: bool = False
    calls: Set[str] = field(default_factory=set)   # bare local names called
    # dotted callees (('mod', 'f') for mod.f(...)) — resolved across
    # module boundaries by analysis.program in whole-program mode
    attr_calls: Set[Tuple[str, ...]] = field(default_factory=set)

    def body_stmts(self) -> List[ast.AST]:
        if isinstance(self.node, ast.Lambda):
            return [self.node.body]
        return list(self.node.body)

    def own_nodes(self) -> Iterator[ast.AST]:
        """Every node of this function's body, nested defs excluded."""
        for stmt in self.body_stmts():
            yield stmt
            yield from _ordered_walk(stmt)


class _Scoper(ast.NodeVisitor):
    """Collect every function-like node with parent links + local calls."""

    def __init__(self) -> None:
        self.funcs: List[_FuncInfo] = []
        self._stack: List[_FuncInfo] = []
        self.by_name: Dict[str, List[_FuncInfo]] = {}

    @staticmethod
    def _params_of(node) -> Set[str]:
        a = node.args
        names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return set(names)

    def _enter(self, node, name: str) -> None:
        info = _FuncInfo(node=node, name=name,
                         parent=self._stack[-1] if self._stack else None,
                         params=self._params_of(node))
        self.funcs.append(info)
        if name:
            self.by_name.setdefault(name, []).append(info)
        self._stack.append(info)

    def visit_FunctionDef(self, node):
        self._enter(node, node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._enter(node, "")
        self.generic_visit(node)
        self._stack.pop()

    def visit_Call(self, node):
        if self._stack:
            tgt, chain = _call_target(node)
            if tgt and len(chain) == 1:
                self._stack[-1].calls.add(tgt)
            elif chain and len(chain) <= 4:
                self._stack[-1].attr_calls.add(tuple(chain))
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# per-module analysis
# ---------------------------------------------------------------------------
class _ModuleAnalysis:
    """Traced/kernel closure + rule dispatch for one module."""

    def __init__(self, path: str, tree: ast.Module,
                 kernel_file: bool) -> None:
        self.path = path
        self.tree = tree
        self.kernel_file = kernel_file
        self.findings: List[Finding] = []
        # dotted names referenced inside tracing-call arguments that did
        # not resolve to a local def — candidates for cross-module
        # traced roots, resolved by analysis.program
        self.external_traced_refs: List[Tuple[Tuple[str, ...], bool]] = []
        # local binding -> imported module ('np' -> 'numpy'); and
        # local binding -> (module, symbol) for from-imports
        self.import_aliases: Dict[str, str] = {}
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.import_aliases[a.asname] = a.name
                    else:
                        top = a.name.split(".")[0]
                        self.import_aliases[top] = top
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = (node.module,
                                                             a.name)
        scoper = _Scoper()
        scoper.visit(tree)
        self.funcs = scoper.funcs
        self.by_name = scoper.by_name
        self._mark_roots()

    def _module_of(self, root: str) -> str:
        """Resolve a name root through import aliases (np -> numpy)."""
        return self.import_aliases.get(root, root)

    def seed_traced(self, name: str, kernel: bool = False) -> bool:
        """Mark every local def called ``name`` traced (cross-module
        propagation entry point).  Returns whether anything changed."""
        changed = False
        for info in self.by_name.get(name, []):
            if not info.traced or (kernel and not info.kernel):
                info.traced = True
                info.kernel = info.kernel or kernel
                changed = True
        return changed

    # -- traced/kernel closure ----------------------------------------------
    def _decorator_names(self, dec: ast.AST) -> Set[str]:
        """All dotted-name components a decorator expression mentions."""
        names = set(_attr_chain(dec))
        if isinstance(dec, ast.Call):
            tgt, chain = _call_target(dec)
            names |= set(chain)
            if tgt:
                names.add(tgt)
            for a in dec.args:
                names |= set(_attr_chain(a))
        return names

    def _mark_roots(self) -> None:
        for info in self.funcs:
            if isinstance(info.node, ast.Lambda):
                continue
            for dec in info.node.decorator_list:
                names = self._decorator_names(dec)
                if not (names & TRACING_DECORATORS):
                    continue
                info.traced = True
                if names & {"jit", "pjit"}:
                    info.jit_decorated = True
                    if isinstance(dec, ast.Call):
                        info.static_params |= _static_names_from_call(dec)
                if names & {"when", "pallas_call"}:
                    info.kernel = True
            # *_ref params are the Pallas kernel calling convention
            if sum(p.endswith("_ref") for p in info.params) >= 2:
                info.kernel = True
                info.traced = True
        # names referenced inside the arguments of tracing calls
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            tgt, _ = _call_target(call)
            if tgt not in TRACING_CALLS:
                continue
            referenced: Set[str] = set()
            for a in list(call.args) + [kw.value for kw in call.keywords]:
                referenced |= _names_in(a)
            statics = (_static_names_from_call(call)
                       if tgt in ("jit", "pjit") else set())
            for name in referenced:
                infos = self.by_name.get(name, [])
                if not infos:
                    self.external_traced_refs.append(
                        ((name,), tgt == "pallas_call"))
                for info in infos:
                    info.traced = True
                    if tgt == "pallas_call":
                        info.kernel = True
                    if tgt in ("jit", "pjit"):
                        info.jit_decorated = True
                        info.static_params |= statics
            # dotted references (mod.helper) never resolve locally —
            # hand them to the whole-program resolver
            for a in list(call.args) + [kw.value for kw in call.keywords]:
                for sub in ast.walk(a):
                    if isinstance(sub, ast.Attribute):
                        ch = _attr_chain(sub)
                        if 2 <= len(ch) <= 4:
                            self.external_traced_refs.append(
                                (tuple(ch), tgt == "pallas_call"))

    def close_local(self) -> bool:
        """Lexical nesting + intra-module call graph, to a local fixed
        point.  Returns whether anything changed — analysis.program
        re-runs this after each cross-module seeding round, so the
        global closure is a fixed point over all modules."""
        any_change = False
        changed = True
        while changed:
            changed = False
            for info in self.funcs:
                if not info.traced and info.parent is not None \
                        and info.parent.traced:
                    info.traced = True
                    info.kernel = info.kernel or info.parent.kernel
                    changed = True
                if info.traced:
                    for callee in info.calls:
                        for ci in self.by_name.get(callee, []):
                            if not ci.traced:
                                ci.traced = True
                                ci.kernel = ci.kernel or info.kernel
                                changed = True
            any_change = any_change or changed
        return any_change

    # -- helpers -------------------------------------------------------------
    def traced_param_roots(self, info: _FuncInfo) -> Set[str]:
        """Formal params of this + enclosing traced functions — the names
        that carry tracers."""
        roots: Set[str] = set()
        cur: Optional[_FuncInfo] = info
        while cur is not None:
            if cur.traced:
                roots |= cur.params
            cur = cur.parent
        return roots

    def emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(rule, self.path, node.lineno,
                                     node.col_offset, message))

    # -- rule dispatch -------------------------------------------------------
    def run(self) -> List[Finding]:
        for info in self.funcs:
            if info.traced:
                self._rule_traced_branch(info)
                self._rule_host_sync(info)
            if info.kernel:
                self._rule_kernel_dot(info)
            self._rule_static_args(info)
            self._rule_inplace_mutation(info)
            self._rule_donate_reuse(info)
        self._rule_static_args_callsites()
        self._rule_host_sync_global()
        self._rule_f64()
        self._rule_determinism()
        self._rule_lock_discipline()
        self._rule_typed_errors()
        return self.findings

    # -- GL001: Python control flow on traced values -------------------------
    def _rule_traced_branch(self, info: _FuncInfo) -> None:
        for node in info.own_nodes():
            if not isinstance(node, (ast.If, ast.While, ast.IfExp,
                                     ast.Assert)):
                continue
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Call):
                    tgt, chain = _call_target(sub)
                    if tgt in HOST_CONSTANT_JAX_CALLS:
                        continue
                    if chain and chain[0] in JAX_EXPR_ROOTS:
                        kind = ("while" if isinstance(node, ast.While)
                                else "assert" if isinstance(node, ast.Assert)
                                else "if")
                        self.emit(
                            "GL001", node,
                            f"Python `{kind}` branches on a traced value "
                            f"({'.'.join(chain)}(...)) inside traced code "
                            f"— use lax.cond/lax.select/jnp.where, or "
                            f"hoist the decision to trace time")
                        break

    # -- GL002: host syncs inside traced code --------------------------------
    def _rule_host_sync(self, info: _FuncInfo) -> None:
        tracer_roots = self.traced_param_roots(info)
        for node in info.own_nodes():
            if not isinstance(node, ast.Call):
                continue
            tgt, chain = _call_target(node)
            if tgt in HOST_SYNC_METHODS and tgt != "block_until_ready" \
                    and isinstance(node.func, ast.Attribute):
                self.emit("GL002", node,
                          f"`.{tgt}()` forces a device sync inside traced "
                          f"code — return the value and sync at the host "
                          f"boundary")
                continue
            if chain in (["jax", "device_get"], ["device_get"]):
                self.emit("GL002", node,
                          "jax.device_get inside traced code is a host "
                          "sync — keep data on device until dispatch "
                          "returns")
                continue
            if not node.args:
                continue
            arg_root = _root_name(node.args[0])
            if arg_root not in tracer_roots:
                continue
            if chain and chain[0] in NUMPY_ROOTS and tgt in (
                    "asarray", "array", "copy", "ascontiguousarray",
                    "savetxt"):
                self.emit("GL002", node,
                          f"np.{tgt} on traced value `{arg_root}` "
                          f"materializes it on host — use the jnp "
                          f"equivalent or keep the op in XLA")
            elif len(chain) == 1 and tgt in ("float", "int", "bool"):
                self.emit("GL002", node,
                          f"`{tgt}()` on traced value `{arg_root}` "
                          f"concretizes the tracer (host sync or trace "
                          f"error) — use .astype or keep it symbolic")

    # -- GL002 (module scope): syncs that matter anywhere --------------------
    def _rule_host_sync_global(self) -> None:
        """Two sync forms flagged regardless of traced context: they only
        appear on dispatch/warm/benchmark paths, where each use is either
        a bug or a deliberate boundary worth a baseline line."""
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            tgt, chain = _call_target(node)
            if tgt == "block_until_ready":
                self.emit(
                    "GL002", node,
                    "block_until_ready stalls the host until the device "
                    "drains — only warm-up / timing code should do this, "
                    "and it should be ledgered in the baseline")
            elif chain and chain[0] in NUMPY_ROOTS and \
                    tgt in ("asarray", "array") and node.args:
                for sub in ast.walk(node.args[0]):
                    if isinstance(sub, ast.Call):
                        _, sc = _call_target(sub)
                        if sc and sc[0] in ("jnp", "lax"):
                            self.emit(
                                "GL002", node,
                                f"np.{tgt} over a device expression "
                                f"materializes it on host (blocking "
                                f"dispatch) — sync only at the API "
                                f"boundary, and ledger that boundary in "
                                f"the baseline")
                            break

    # -- GL003: float64 traps in accelerator code ----------------------------
    def _rule_f64(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                chain = _attr_chain(node)
                is_jnp = bool(chain) and chain[0] in JAX_EXPR_ROOTS
                if self.kernel_file or is_jnp:
                    self.emit(
                        "GL003", node,
                        f"{'.'.join(chain) or 'float64'} in accelerator "
                        f"code: TPUs have no f64 ALU — under default "
                        f"config this silently truncates to f32, under "
                        f"x64 it breaks the kernel dtype contract; name "
                        f"an explicit f32/bf16 width")
            elif isinstance(node, ast.Call):
                tgt, chain = _call_target(node)
                if chain[-2:] == ["config", "update"] and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        node.args[0].value == "jax_enable_x64":
                    self.emit(
                        "GL003", node,
                        "jax_enable_x64 flips every default dtype to f64 "
                        "process-wide — the workbench's kernels and "
                        "packed formats are f32-only")
                elif tgt == "astype" and self.kernel_file and node.args \
                        and isinstance(node.args[0], ast.Name) and \
                        node.args[0].id == "float":
                    self.emit(
                        "GL003", node,
                        ".astype(float) means f64 under numpy semantics "
                        "— name the width (jnp.float32)")

    # -- GL004: static_argnames discipline -----------------------------------
    def _rule_static_args(self, info: _FuncInfo) -> None:
        if isinstance(info.node, ast.Lambda):
            return
        # (a) static_argnames naming a parameter the function doesn't have
        for dec in info.node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            tgt, _ = _call_target(dec)
            is_partial_jit = (tgt == "partial"
                              and any(_is_jit_chain(a) for a in dec.args))
            if tgt in ("jit", "pjit") or is_partial_jit:
                for name in sorted(_static_names_from_call(dec)):
                    if name not in info.params:
                        self.emit(
                            "GL004", dec,
                            f"static_argnames names `{name}` but "
                            f"`{info.name}` has no such parameter — jit "
                            f"raises (or silently ignores it) at call "
                            f"time")
        # (b) jitted def consuming a param where Python needs a concrete
        # value, without marking it static
        if not info.jit_decorated:
            return
        dynamic = info.params - info.static_params - {"self"}
        for node in info.own_nodes():
            if isinstance(node, ast.Call):
                tgt, chain = _call_target(node)
                if tgt == "range" and len(chain) == 1:
                    for a in node.args:
                        root = _root_name(a)
                        if root in dynamic:
                            self.emit(
                                "GL004", node,
                                f"`range({root})` inside jitted "
                                f"`{info.name}` needs a concrete value — "
                                f"add `{root}` to static_argnames or use "
                                f"lax.fori_loop")

    def _rule_static_args_callsites(self) -> None:
        """jax.jit(f, static_argnames=...) where f is a visible local def
        lacking that parameter."""
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call) or not _is_jit_chain(
                    call.func):
                continue
            statics = _static_names_from_call(call)
            if not statics or not call.args or not isinstance(
                    call.args[0], ast.Name):
                continue
            for target in self.by_name.get(call.args[0].id, []):
                for name in sorted(statics):
                    if name not in target.params:
                        self.emit(
                            "GL004", call,
                            f"static_argnames names `{name}` but "
                            f"`{target.name}` has no such parameter — jit "
                            f"raises (or silently ignores it) at call "
                            f"time")

    # -- GL005: in-place numpy mutation of jax arrays ------------------------
    def _rule_inplace_mutation(self, info: _FuncInfo) -> None:
        jax_names: Set[str] = set()
        for node in info.own_nodes():
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tname = node.targets[0].id
                if isinstance(node.value, ast.Call):
                    _, chain = _call_target(node.value)
                    if chain and chain[0] in ("jnp", "jax", "lax"):
                        jax_names.add(tname)
                        continue
                jax_names.discard(tname)
                continue
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Subscript):
                target = node.targets[0]
            elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Subscript):
                target = node.target
            if target is not None:
                root = _root_name(target)
                if root in jax_names and not root.endswith("_ref"):
                    self.emit(
                        "GL005", node,
                        f"in-place `{root}[...] = ...` on a jax array — "
                        f"jax arrays are immutable (this raises at "
                        f"runtime); use `.at[...].set(...)`")

    # -- GL006: donated buffers reused after dispatch ------------------------
    def _rule_donate_reuse(self, info: _FuncInfo) -> None:
        if isinstance(info.node, ast.Lambda):
            return
        donating: Dict[str, Tuple[int, ...]] = {}
        donated: Dict[str, int] = {}            # var -> donation line
        skip_nodes: Set[int] = set()            # Name nodes of the donation
        for node in info.own_nodes():
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and _is_jit_chain(node.value.func):
                nums: Tuple[int, ...] = ()
                for kw in node.value.keywords:
                    if kw.arg == "donate_argnums":
                        v = kw.value
                        if isinstance(v, ast.Constant) and isinstance(
                                v.value, int):
                            nums = (v.value,)
                        elif isinstance(v, (ast.Tuple, ast.List)):
                            nums = tuple(
                                e.value for e in v.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, int))
                if nums:
                    donating[node.targets[0].id] = nums
            elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Name) and node.func.id in donating:
                for pos in donating[node.func.id]:
                    if pos < len(node.args):
                        root = _root_name(node.args[pos])
                        if root is not None:
                            donated.setdefault(root, node.lineno)
                            for sub in ast.walk(node.args[pos]):
                                skip_nodes.add(id(sub))
            elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load) and node.id in donated \
                    and id(node) not in skip_nodes:
                self.emit(
                    "GL006", node,
                    f"`{node.id}` was donated to a jitted call (line "
                    f"{donated[node.id]}) and is read again — the buffer "
                    f"may already be aliased to the output (garbage on "
                    f"TPU)")
                del donated[node.id]

    # -- GL007: kernel dots without explicit accumulation dtype --------------
    def _rule_kernel_dot(self, info: _FuncInfo) -> None:
        for node in info.own_nodes():
            if not isinstance(node, ast.Call):
                continue
            tgt, chain = _call_target(node)
            if tgt in KERNEL_DOT_CALLS and chain and \
                    chain[0] in ("lax", "jnp", "jax"):
                if not any(kw.arg == "preferred_element_type"
                           for kw in node.keywords):
                    self.emit(
                        "GL007", node,
                        f"{'.'.join(chain)} in kernel code without "
                        f"preferred_element_type — the accumulation "
                        f"dtype follows operand promotion (bf16 operands "
                        f"accumulate in bf16: silent precision loss on "
                        f"the MXU)")

    # -- GL008: determinism (injectable-clock / seeded-RNG contract) ---------
    def _rule_determinism(self) -> None:
        """Direct wall-clock reads and global-RNG draws.  Only *calls*
        match: ``clock=time.monotonic`` as a default argument is the
        sanctioned injection idiom and is a bare reference, never a
        call.  The one legitimate boundary (pipeline/staleness.py's
        ``wall_clock``) carries an inline waiver."""
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            tgt, chain = _call_target(node)
            if not chain or tgt is None:
                continue
            mod = self._module_of(chain[0])
            if len(chain) == 2 and mod == "time" and \
                    tgt in WALL_CLOCK_CALLS:
                self.emit(
                    "GL008", node,
                    f"direct `{chain[0]}.{tgt}()` — r12-r15 subsystems "
                    f"promise an injectable clock; accept "
                    f"`clock=time.monotonic` as a parameter and call "
                    f"`clock()` so SimClock tests stay deterministic")
            elif mod == "datetime" and tgt in DATETIME_NOW_CALLS and \
                    2 <= len(chain) <= 3:
                self.emit(
                    "GL008", node,
                    f"`{'.'.join(chain)}()` reads the wall clock — "
                    f"thread a clock parameter (or a timestamp argument) "
                    f"instead of sampling ambient time")
            elif len(chain) == 2 and mod == "random" and \
                    tgt in PY_RANDOM_FNS:
                self.emit(
                    "GL008", node,
                    f"`{chain[0]}.{tgt}()` draws from the process-global "
                    f"RNG — construct `random.Random(seed)` (or accept "
                    f"an rng parameter) so runs replay bit-identically")
            elif mod == "numpy" and len(chain) == 3 and \
                    chain[1] == "random":
                if tgt in NP_RNG_CONSTRUCTORS:
                    if not node.args and not node.keywords:
                        self.emit(
                            "GL008", node,
                            f"`{'.'.join(chain)}()` without a seed pulls "
                            f"OS entropy — pass an explicit seed (the "
                            f"workbench's runs must replay "
                            f"bit-identically)")
                else:
                    self.emit(
                        "GL008", node,
                        f"`{'.'.join(chain)}()` uses numpy's legacy "
                        f"global RNG — use a seeded "
                        f"np.random.default_rng(seed) generator")
            elif len(chain) == 1:
                fi = self.from_imports.get(tgt)
                if fi is None:
                    continue
                fmod, fsym = fi
                if fmod == "time" and fsym in WALL_CLOCK_CALLS:
                    self.emit(
                        "GL008", node,
                        f"direct `{tgt}()` (time.{fsym}) — accept an "
                        f"injectable clock parameter instead")
                elif fmod == "random" and fsym in PY_RANDOM_FNS:
                    self.emit(
                        "GL008", node,
                        f"`{tgt}()` (random.{fsym}) draws from the "
                        f"process-global RNG — use a seeded instance")

    # -- GL009: lock discipline ---------------------------------------------
    def _rule_lock_discipline(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self._lock_check_class(node)

    @staticmethod
    def _self_attr(node: ast.AST, selfname: str) -> Optional[str]:
        """First attribute on a self.<attr>[...]... chain, else None."""
        attrs: List[str] = []
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            if isinstance(node, ast.Attribute):
                attrs.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name) and node.id == selfname and attrs:
            return attrs[-1]
        return None

    def _lock_check_class(self, cls: ast.ClassDef) -> None:
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

        def self_name(m) -> str:
            return m.args.args[0].arg if m.args.args else "self"

        # 1. which attrs hold threading locks?
        locks: Set[str] = set()
        for m in methods:
            sn = self_name(m)
            for node in ast.walk(m):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                tgt, chain = _call_target(node.value)
                if tgt not in LOCK_FACTORIES:
                    continue
                from_threading = (
                    (len(chain) >= 2
                     and self._module_of(chain[0]) == "threading")
                    or (len(chain) == 1 and self.from_imports.get(
                        tgt, ("", ""))[0] == "threading"))
                if not from_threading:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == sn:
                        locks.add(t.attr)
        if not locks:
            return

        # 2. classify every self-attr mutation site as locked/unlocked
        locked: Dict[str, List[ast.AST]] = {}
        unlocked: Dict[str, List[ast.AST]] = {}

        def is_lock_expr(expr: ast.AST, sn: str) -> bool:
            a = self._self_attr(expr, sn)
            return a in locks

        def record(stmt: ast.AST, sn: str, in_lock: bool) -> None:
            sites = locked if in_lock else unlocked
            for node in ast.walk(stmt):
                attr = None
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        a = self._self_attr(t, sn)
                        if a:
                            sites.setdefault(a, []).append(node)
                    continue
                if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    attr = self._self_attr(node.target, sn)
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        a = self._self_attr(t, sn)
                        if a:
                            sites.setdefault(a, []).append(node)
                    continue
                elif isinstance(node, ast.Call):
                    tgt, chain = _call_target(node)
                    if tgt in MUTATOR_METHODS and isinstance(
                            node.func, ast.Attribute):
                        attr = self._self_attr(node.func.value, sn)
                    elif tgt in HEAPQ_MUTATORS and node.args:
                        attr = self._self_attr(node.args[0], sn)
                if attr:
                    sites.setdefault(attr, []).append(node)

        def scan(body: List[ast.stmt], sn: str, in_lock: bool) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                if isinstance(stmt, ast.With):
                    inner = in_lock or any(
                        is_lock_expr(i.context_expr, sn)
                        for i in stmt.items)
                    scan(stmt.body, sn, inner)
                elif isinstance(stmt, (ast.If, ast.For, ast.While)):
                    head = (stmt.iter if isinstance(stmt, ast.For)
                            else stmt.test)
                    record(head, sn, in_lock)
                    scan(stmt.body, sn, in_lock)
                    scan(stmt.orelse, sn, in_lock)
                elif isinstance(stmt, ast.Try):
                    scan(stmt.body, sn, in_lock)
                    for h in stmt.handlers:
                        scan(h.body, sn, in_lock)
                    scan(stmt.orelse, sn, in_lock)
                    scan(stmt.finalbody, sn, in_lock)
                else:
                    record(stmt, sn, in_lock)

        for m in methods:
            if m.name in ("__init__", "__new__"):
                continue            # construction precedes sharing
            scan(list(m.body), self_name(m), in_lock=False)

        for attr in sorted(set(locked) & set(unlocked)):
            if attr in locks:
                continue
            for node in unlocked[attr]:
                self.emit(
                    "GL009", node,
                    f"`self.{attr}` is mutated under the lock elsewhere "
                    f"in `{cls.name}` but not here — every write to a "
                    f"lock-guarded attribute must sit inside `with "
                    f"self._lock:` (use RLock if helpers re-enter)")

    # -- GL011: typed-error discipline ---------------------------------------
    def _rule_typed_errors(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ExceptHandler):
                if node.type is None:
                    self.emit(
                        "GL011", node,
                        "bare `except:` catches SystemExit/Keyboard"
                        "Interrupt too — name the typed fault "
                        "(SwapRejected, OOCBlockError, FaultError, ...) "
                        "or `except Exception` at an outermost boundary")
                elif len(node.body) == 1 and isinstance(node.body[0],
                                                        ast.Pass):
                    self.emit(
                        "GL011", node,
                        "swallowed exception (`except ...: pass`) — "
                        "record, re-raise, or degrade explicitly; silent "
                        "drops hide chaos-matrix regressions")
            elif isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                name = None
                if isinstance(exc, ast.Call) and isinstance(exc.func,
                                                            ast.Name):
                    name = exc.func.id
                elif isinstance(exc, ast.Name):
                    name = exc.id
                if name in ("Exception", "BaseException"):
                    self.emit(
                        "GL011", node,
                        f"`raise {name}(...)` defeats the typed-error "
                        f"contract — raise one of the workbench's typed "
                        f"faults so callers can catch precisely")


RULE_IDS = ("GL001", "GL002", "GL003", "GL004", "GL005", "GL006", "GL007",
            "GL008", "GL009", "GL010", "GL011")


_KERNEL_FILE_RE = re.compile(
    r"pallas_call\(|from jax\.experimental import pallas|"
    r"import pallas_tpu|jax\.experimental\.pallas")


def is_kernel_file(src: str) -> bool:
    """A module that DEFINES Pallas kernels (not one that merely calls a
    wrapper from a kernel module) gets the dtype-discipline rules."""
    return bool(_KERNEL_FILE_RE.search(src))


def apply_waivers(findings: List[Finding], src: str) -> List[Finding]:
    """Drop findings waived inline: `# graftlint: GLxxx — reason` on the
    finding's line.  GL000 (parse failure) is never waivable — a file
    that does not parse cannot carry a trustworthy comment."""
    lines = src.splitlines()
    kept = []
    for f in findings:
        if f.rule != "GL000":
            line = lines[f.line - 1] if f.line - 1 < len(lines) else ""
            if "graftlint:" in line:
                waiver = line.split("graftlint:", 1)[1]
                if f.rule in waiver or "off" in waiver:
                    continue
        kept.append(f)
    return sorted(kept, key=lambda f: (f.path, f.line, f.rule))


def analyze_source(path: str, src: str) -> List[Finding]:
    """Run every Layer-1 rule over one module's source (standalone
    per-file mode; whole-program mode lives in analysis.program)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("GL000", path, e.lineno or 1, 0,
                        f"syntax error: {e.msg}")]
    analysis = _ModuleAnalysis(path, tree, is_kernel_file(src))
    analysis.close_local()
    findings = analysis.run()
    return apply_waivers(findings, src)

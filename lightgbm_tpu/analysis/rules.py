"""graftlint Layer 1 — AST rules for the workbench's JAX footguns.

Every rule answers one question the type system cannot: "does this code
keep the invariants rounds 6-7 paid for?"  The detectors are deliberately
HIGH-PRECISION heuristics: a finding should be either a real bug or a
deliberate decision worth a baseline entry — a linter the tree cannot keep
green gets deleted, not obeyed.

Traced-context model
--------------------
A function is *traced* when JAX (not Python) runs its body:

* decorated with ``jax.jit`` / ``jax.vmap`` / ``functools.partial(jax.jit,
  ...)`` / ``pl.when(...)`` and friends;
* its name appears inside the arguments of a tracing call
  (``jax.jit(f)``, ``lax.scan(f, ...)``, ``pl.pallas_call(partial(f,
  ...), ...)``, ``jax.vmap(f)(x)``, ...);
* it is lexically nested in a traced function; or
* it is called from a traced function — in the same module, or (r16,
  whole-program mode) from a traced function in ANOTHER module through
  the cross-module call graph in :mod:`.program` (tracing is transitive
  through plain Python calls, and Python calls cross file boundaries).

A function is additionally a *kernel* when it reaches ``pl.pallas_call``
or takes ``*_ref`` parameters — kernels get the dtype-discipline rules.

r16 adds four production-loop families on the same chassis: GL008
determinism (wall-clock / unseeded RNG outside the injectable-clock
contract), GL009 lock discipline (attributes mutated both inside and
outside ``with self._lock``), GL010 fault-site registry drift (lives in
:mod:`.program` — it is whole-program by nature), and GL011 typed-error
discipline (bare ``except:``, ``raise Exception``, swallowed handlers).

Mesh-context model (r20)
------------------------
GL012 runs a second closure in parallel with the traced one: a function
is *meshed* when a ``shard_map``/``pmap`` entry point reaches it — its
name appears in a mesh entry call's arguments, it is lexically nested in
a meshed function, or a meshed function calls it (cross-module through
:mod:`.program`, exactly like tracing).  Each meshed function carries
the union of axis names its seeding sites establish (string literals in
``P(...)``/``PartitionSpec(...)`` specs and ``axis_name=`` kwargs,
resolved through module string constants and, whole-program, through
imports); sites whose axes cannot be statically resolved mark the
context *incomplete*, which disables the axis-agreement check but keeps
the membership fact.  A collective whose axis argument is a function
PARAMETER is never an outside-mesh finding — the axis flows from the
caller and the mesh closure checks the caller instead.

Quantized-space lattice (r20)
-----------------------------
GL013 runs a per-function abstract-space inference over three value
spaces the r14/r18/r19 rounds made load-bearing: ``bin`` (u8 bin codes
— ordinal, compared but never measured), ``int8``/``bf16`` (quantized
wire payloads), and ``stat`` (f32 statistics / dequantized values).
Spaces seed from explicit casts (``.astype(jnp.uint8)`` -> bin,
``.astype(jnp.int8/bfloat16)`` -> wire, ``.astype(jnp.float32)`` ->
stat, i.e. a dequantize) and from the ``ForestSoA``/``PackedForest``/
``QuantizedForestArrays`` layout-contract fields (``.split_bin`` ->
bin, ``.leaf_q`` -> wire), and propagate through assignment, slicing,
shape ops and ``jnp.where``.  Unknown stays unknown — every GL013
sub-rule fires only on proven mixes.

See analysis/RULES.md for one bad/good example per rule.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

# call targets (final attribute name) that trace their function arguments
TRACING_CALLS = {
    "jit", "pjit", "vmap", "pmap", "grad", "value_and_grad", "hessian",
    "scan", "while_loop", "fori_loop", "cond", "switch", "associative_scan",
    "pallas_call", "custom_jvp", "custom_vjp", "checkpoint", "remat",
    "shard_map", "xmap", "named_call", "when",
}

# decorators (final attribute name) that make the decorated def traced
TRACING_DECORATORS = TRACING_CALLS - {"scan", "while_loop", "fori_loop",
                                      "cond", "switch"}

# attribute calls that force a device->host synchronization
HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready", "copy_to_host"}

# numpy-namespace roots — numpy ops on tracers either crash or silently
# concretize
NUMPY_ROOTS = {"np", "numpy", "onp"}

JAX_EXPR_ROOTS = {"jnp", "jax", "lax", "pl", "pltpu"}

# jax-namespace calls that return HOST constants (fixed at trace time) —
# branching on these is fine
HOST_CONSTANT_JAX_CALLS = {
    "default_backend", "devices", "local_devices", "device_count",
    "local_device_count", "process_index", "process_count",
}

KERNEL_DOT_CALLS = {"dot_general", "dot", "matmul", "einsum"}

# -- GL012: collective/mesh discipline -------------------------------------
# Cross-replica collectives: every one of these requires a bound mesh
# axis at trace time.  ``lax.axis_index`` is deliberately EXCLUDED — it
# needs the axis too, but every workbench use sits next to a collective
# that already carries the finding, and flagging both doubles the noise
# for one bug.
COLLECTIVE_CALLS = {
    "psum", "psum_scatter", "ppermute", "all_gather", "all_to_all",
    "pmean", "pmax", "pmin", "pshuffle", "pswapaxes",
}
# tracing calls that ESTABLISH a mesh-axis context for their function
# argument (vmap/scan etc. trace but bind no axis)
MESH_ENTRY_CALLS = {"shard_map", "pmap", "xmap"}
PARTITION_SPEC_NAMES = {"P", "PartitionSpec"}

# -- GL013: quantized-space lattice -----------------------------------------
# Layout-contract fields whose space is part of the serving/wire ABI
# (ForestSoA / PackedForest / QuantizedForestArrays — see PARITY.md).
BIN_CODE_FIELDS = {"split_bin"}          # u8 bin codes: ordinal, not metric
WIRE_FIELDS = {"leaf_q"}                 # quantized wire payloads
_CAST_SPACE = {
    "uint8": "bin",
    "int8": "int8",
    "bfloat16": "bf16",
    "float32": "stat",                   # an f32 cast IS the dequantize
    "float64": "stat",
}
WIRE_SPACES = {"int8", "bf16"}
# methods that change shape/residency but never the value space
_SPACE_PRESERVING_METHODS = {
    "reshape", "ravel", "flatten", "copy", "transpose", "squeeze",
    "block_until_ready",
}
# the int8 histogram accumulator overflows int32 past this many rows
# (all-ones gradient column: 127 * n  >  2^31 - 1)
INT8_ACC_ROW_LIMIT = (1 << 31) // 127    # = 16_909_320
# the ONE sanctioned raw-wire boundary: ops/quantize.py's per-hop
# requantize helper (and its leading-underscore alias in older call
# sites) may ppermute int8/bf16 payloads — everything else must route
# hops through it
SANCTIONED_HOP_FUNCS = {"wire_transfer", "_wire_transfer"}

# -- GL008: determinism --------------------------------------------------
# ``time`` module calls that read (or stall on) the wall clock.  A bare
# REFERENCE (``clock=time.monotonic`` as a default) is the sanctioned
# injection idiom and never matches — only calls do.
WALL_CLOCK_CALLS = {
    "time", "sleep", "monotonic", "perf_counter", "process_time",
    "time_ns", "monotonic_ns", "perf_counter_ns", "process_time_ns",
}
DATETIME_NOW_CALLS = {"now", "utcnow", "today"}
# ``random`` module functions that consume the process-global RNG
PY_RANDOM_FNS = {
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "betavariate",
    "expovariate", "getrandbits", "seed",
}
# np.random constructors that are deterministic WHEN SEEDED
NP_RNG_CONSTRUCTORS = {"default_rng", "RandomState", "Generator",
                       "SeedSequence", "PCG64", "Philox"}

# -- GL009: lock discipline ----------------------------------------------
LOCK_FACTORIES = {"Lock", "RLock"}
# container methods that mutate their receiver in place
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "remove",
    "discard", "clear",
}
HEAPQ_MUTATORS = {"heappush", "heappop", "heappushpop", "heapreplace"}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    @property
    def anchor(self) -> str:
        return f"{self.path}:{self.line}"

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message}")


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------
def _attr_chain(node: ast.AST) -> List[str]:
    """['jax', 'numpy', 'asarray'] for jax.numpy.asarray; [] if not a
    plain dotted name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def _root_name(node: ast.AST) -> Optional[str]:
    """The base Name an expression is built on (x for x[0].T.foo())."""
    while True:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            return None


def _call_target(call: ast.Call) -> Tuple[Optional[str], List[str]]:
    """(final attr name, full dotted chain) of a call's callee."""
    chain = _attr_chain(call.func)
    if chain:
        return chain[-1], chain
    if isinstance(call.func, ast.Name):
        return call.func.id, [call.func.id]
    return None, []


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _ordered_walk(node: ast.AST, skip_funcs: bool = True) -> Iterator[ast.AST]:
    """Pre-order, source-order walk that (optionally) does not descend
    into nested function definitions."""
    for child in ast.iter_child_nodes(node):
        if skip_funcs and isinstance(child, _FUNC_NODES):
            continue
        yield child
        yield from _ordered_walk(child, skip_funcs)


def _static_names_from_call(call: ast.Call) -> Set[str]:
    """Parameter names a jit call marks static (literal forms only)."""
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.add(e.value)
    return out


def _is_jit_chain(node: ast.AST) -> bool:
    chain = _attr_chain(node)
    return bool(chain) and chain[-1] in ("jit", "pjit")


# ---------------------------------------------------------------------------
# scope collection
# ---------------------------------------------------------------------------
@dataclass
class _FuncInfo:
    node: ast.AST
    name: str                       # '' for lambdas
    parent: Optional["_FuncInfo"]
    params: Set[str] = field(default_factory=set)
    traced: bool = False
    kernel: bool = False
    static_params: Set[str] = field(default_factory=set)
    jit_decorated: bool = False
    calls: Set[str] = field(default_factory=set)   # bare local names called
    # dotted callees (('mod', 'f') for mod.f(...)) — resolved across
    # module boundaries by analysis.program in whole-program mode
    attr_calls: Set[Tuple[str, ...]] = field(default_factory=set)
    # -- GL012 mesh-context closure (parallel to traced) --
    meshed: bool = False                # reachable from a mesh entry point
    mesh_axes: Set[str] = field(default_factory=set)
    # True when ANY seeding site's axes could not be statically resolved
    # — membership holds, but the axis-agreement check is disabled
    mesh_unknown: bool = False

    def body_stmts(self) -> List[ast.AST]:
        if isinstance(self.node, ast.Lambda):
            return [self.node.body]
        return list(self.node.body)

    def own_nodes(self) -> Iterator[ast.AST]:
        """Every node of this function's body, nested defs excluded."""
        for stmt in self.body_stmts():
            yield stmt
            yield from _ordered_walk(stmt)

    def strict_own_nodes(self) -> Iterator[ast.AST]:
        """Like own_nodes, but DIRECTLY-nested def statements are skipped
        too (own_nodes yields them and walks their bodies).  The r20
        rules need true per-function ownership: a collective inside a
        nested shard_map body belongs to the nested function's info —
        attributing it to the enclosing (unmeshed) function would turn
        the standard closure idiom into a false positive."""
        for stmt in self.body_stmts():
            if isinstance(stmt, _FUNC_NODES):
                continue
            yield stmt
            yield from _ordered_walk(stmt)


@dataclass
class _MeshSite:
    """One ``shard_map``/``pmap`` call: the names/chains it references and
    the axes its specs establish.  Seeding is deferred to ``close_local``
    so whole-program mode can install an ``axis_resolver`` first."""
    call: ast.Call
    names: Set[str]                     # bare names in the call's args
    chains: Set[Tuple[str, ...]]        # dotted refs for cross-module seeds
    axes: Set[str]                      # statically-resolved axis names
    deferred: Set[str]                  # axis NAMES awaiting the resolver
    has_specs: bool                     # any P(...)/axis_name= seen at all


class _Scoper(ast.NodeVisitor):
    """Collect every function-like node with parent links + local calls."""

    def __init__(self) -> None:
        self.funcs: List[_FuncInfo] = []
        self._stack: List[_FuncInfo] = []
        self.by_name: Dict[str, List[_FuncInfo]] = {}

    @staticmethod
    def _params_of(node) -> Set[str]:
        a = node.args
        names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return set(names)

    def _enter(self, node, name: str) -> None:
        info = _FuncInfo(node=node, name=name,
                         parent=self._stack[-1] if self._stack else None,
                         params=self._params_of(node))
        self.funcs.append(info)
        if name:
            self.by_name.setdefault(name, []).append(info)
        self._stack.append(info)

    def visit_FunctionDef(self, node):
        self._enter(node, node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._enter(node, "")
        self.generic_visit(node)
        self._stack.pop()

    def visit_Call(self, node):
        if self._stack:
            tgt, chain = _call_target(node)
            if tgt and len(chain) == 1:
                self._stack[-1].calls.add(tgt)
            elif chain and len(chain) <= 4:
                self._stack[-1].attr_calls.add(tuple(chain))
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# per-module analysis
# ---------------------------------------------------------------------------
class _ModuleAnalysis:
    """Traced/kernel closure + rule dispatch for one module."""

    def __init__(self, path: str, tree: ast.Module,
                 kernel_file: bool) -> None:
        self.path = path
        self.tree = tree
        self.kernel_file = kernel_file
        self.findings: List[Finding] = []
        # dotted names referenced inside tracing-call arguments that did
        # not resolve to a local def — candidates for cross-module
        # traced roots, resolved by analysis.program
        self.external_traced_refs: List[Tuple[Tuple[str, ...], bool]] = []
        # -- GL012 state --
        # module-level  NAME = "string"  constants (DATA_AXIS = "data")
        self.str_constants: Dict[str, str] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                self.str_constants[node.targets[0].id] = node.value.value
        # mesh entry sites awaiting seeding (see _MeshSite)
        self.mesh_sites: List[_MeshSite] = []
        # (chain, axes, complete) mesh refs that did not resolve locally
        self.external_mesh_refs: List[
            Tuple[Tuple[str, ...], frozenset, bool]] = []
        # whole-program mode installs a callable(name)->Optional[str]
        # that resolves imported axis constants; None = per-file mode
        self.axis_resolver = None
        self._mesh_seeded = False
        self._int8_guard: Optional[bool] = None
        # local binding -> imported module ('np' -> 'numpy'); and
        # local binding -> (module, symbol) for from-imports
        self.import_aliases: Dict[str, str] = {}
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.import_aliases[a.asname] = a.name
                    else:
                        top = a.name.split(".")[0]
                        self.import_aliases[top] = top
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = (node.module,
                                                             a.name)
        scoper = _Scoper()
        scoper.visit(tree)
        self.funcs = scoper.funcs
        self.by_name = scoper.by_name
        self._mark_roots()

    def _module_of(self, root: str) -> str:
        """Resolve a name root through import aliases (np -> numpy)."""
        return self.import_aliases.get(root, root)

    def seed_traced(self, name: str, kernel: bool = False) -> bool:
        """Mark every local def called ``name`` traced (cross-module
        propagation entry point).  Returns whether anything changed."""
        changed = False
        for info in self.by_name.get(name, []):
            if not info.traced or (kernel and not info.kernel):
                info.traced = True
                info.kernel = info.kernel or kernel
                changed = True
        return changed

    @staticmethod
    def _merge_mesh(info: _FuncInfo, axes, complete: bool) -> bool:
        """Union a mesh context into one function; True if it grew."""
        changed = False
        if not info.meshed:
            info.meshed = True
            changed = True
        new = set(axes) - info.mesh_axes
        if new:
            info.mesh_axes |= new
            changed = True
        if not complete and not info.mesh_unknown:
            info.mesh_unknown = True
            changed = True
        return changed

    def seed_meshed(self, name: str, axes, complete: bool = True) -> bool:
        """Mark every local def called ``name`` mesh-reachable with the
        given axes (cross-module propagation entry point)."""
        changed = False
        for info in self.by_name.get(name, []):
            changed |= self._merge_mesh(info, axes, complete)
        return changed

    # -- traced/kernel closure ----------------------------------------------
    def _decorator_names(self, dec: ast.AST) -> Set[str]:
        """All dotted-name components a decorator expression mentions."""
        names = set(_attr_chain(dec))
        if isinstance(dec, ast.Call):
            tgt, chain = _call_target(dec)
            names |= set(chain)
            if tgt:
                names.add(tgt)
            for a in dec.args:
                names |= set(_attr_chain(a))
        return names

    def _mark_roots(self) -> None:
        for info in self.funcs:
            if isinstance(info.node, ast.Lambda):
                continue
            for dec in info.node.decorator_list:
                names = self._decorator_names(dec)
                if not (names & TRACING_DECORATORS):
                    continue
                info.traced = True
                if names & {"jit", "pjit"}:
                    info.jit_decorated = True
                    if isinstance(dec, ast.Call):
                        info.static_params |= _static_names_from_call(dec)
                if names & {"when", "pallas_call"}:
                    info.kernel = True
            # *_ref params are the Pallas kernel calling convention
            if sum(p.endswith("_ref") for p in info.params) >= 2:
                info.kernel = True
                info.traced = True
        # names referenced inside the arguments of tracing calls
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            tgt, _ = _call_target(call)
            if tgt not in TRACING_CALLS:
                continue
            referenced: Set[str] = set()
            for a in list(call.args) + [kw.value for kw in call.keywords]:
                referenced |= _names_in(a)
            statics = (_static_names_from_call(call)
                       if tgt in ("jit", "pjit") else set())
            for name in referenced:
                infos = self.by_name.get(name, [])
                if not infos:
                    self.external_traced_refs.append(
                        ((name,), tgt == "pallas_call"))
                for info in infos:
                    info.traced = True
                    if tgt == "pallas_call":
                        info.kernel = True
                    if tgt in ("jit", "pjit"):
                        info.jit_decorated = True
                        info.static_params |= statics
            # dotted references (mod.helper) never resolve locally —
            # hand them to the whole-program resolver
            chains: Set[Tuple[str, ...]] = set()
            for a in list(call.args) + [kw.value for kw in call.keywords]:
                for sub in ast.walk(a):
                    if isinstance(sub, ast.Attribute):
                        ch = _attr_chain(sub)
                        if 2 <= len(ch) <= 4:
                            chains.add(tuple(ch))
                            self.external_traced_refs.append(
                                (tuple(ch), tgt == "pallas_call"))
            # mesh entry points additionally establish an axis context
            # for everything they reference (GL012) — recorded now,
            # seeded in close_local once the axis_resolver is in place
            if tgt in MESH_ENTRY_CALLS:
                axes, deferred, has_specs = self._mesh_axes_of(call)
                self.mesh_sites.append(_MeshSite(
                    call=call, names=set(referenced), chains=chains,
                    axes=axes, deferred=deferred, has_specs=has_specs))

    # -- GL012: mesh-axis extraction ------------------------------------------
    def _collect_axis(self, node: ast.AST, axes: Set[str],
                      deferred: Set[str]) -> None:
        if isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                self._collect_axis(e, axes, deferred)
        elif isinstance(node, ast.Constant):
            if isinstance(node.value, str):
                axes.add(node.value)
            # P(None) / P() placeholders carry no axis
        elif isinstance(node, ast.Name):
            if node.id in self.str_constants:
                axes.add(self.str_constants[node.id])
            else:
                deferred.add(node.id)
        else:
            # smesh.axis_name, f-strings, ... — not statically resolvable
            deferred.add("?")

    def _mesh_axes_of(self, call: ast.Call
                      ) -> Tuple[Set[str], Set[str], bool]:
        """Axis names a mesh entry call establishes: string literals (or
        resolvable module constants) inside P(...)/PartitionSpec(...)
        specs and axis_name= kwargs.  ``deferred`` holds names the
        whole-program resolver may still supply; the marker '?' means an
        expression form no resolver can recover."""
        axes: Set[str] = set()
        deferred: Set[str] = set()
        has_specs = False
        for kw in call.keywords:
            if kw.arg in ("axis_name", "axis_names"):
                has_specs = True
                self._collect_axis(kw.value, axes, deferred)
        for node in ast.walk(call):
            if isinstance(node, ast.Call) and node is not call:
                t2, _ = _call_target(node)
                if t2 in PARTITION_SPEC_NAMES:
                    has_specs = True
                    for a in node.args:
                        self._collect_axis(a, axes, deferred)
        return axes, deferred, has_specs

    def seed_mesh_sites(self) -> None:
        """Turn recorded mesh entry sites into meshed functions, resolving
        deferred axis names through ``axis_resolver`` when whole-program
        mode installed one.  Idempotent; runs at the top of close_local."""
        if self._mesh_seeded:
            return
        self._mesh_seeded = True
        for site in self.mesh_sites:
            axes = set(site.axes)
            unresolved: Set[str] = set()
            for name in site.deferred:
                val = (self.axis_resolver(name)
                       if self.axis_resolver and name != "?" else None)
                if val is not None:
                    axes.add(val)
                else:
                    unresolved.add(name)
            complete = site.has_specs and not unresolved
            for name in site.names:
                if name in self.by_name:
                    self.seed_meshed(name, axes, complete)
                else:
                    self.external_mesh_refs.append(
                        ((name,), frozenset(axes), complete))
            for ch in site.chains:
                self.external_mesh_refs.append(
                    (ch, frozenset(axes), complete))
            # inline lambdas (shard_map(lambda x: ..., ...)) have no
            # name to seed through — mesh them by node identity
            lambda_nodes = {id(sub)
                            for a in list(site.call.args)
                            + [kw.value for kw in site.call.keywords]
                            for sub in ast.walk(a)
                            if isinstance(sub, ast.Lambda)}
            if lambda_nodes:
                for info in self.funcs:
                    if id(info.node) in lambda_nodes:
                        self._merge_mesh(info, axes, complete)

    def close_local(self) -> bool:
        """Lexical nesting + intra-module call graph, to a local fixed
        point.  Returns whether anything changed — analysis.program
        re-runs this after each cross-module seeding round, so the
        global closure is a fixed point over all modules."""
        self.seed_mesh_sites()
        any_change = False
        changed = True
        while changed:
            changed = False
            for info in self.funcs:
                if not info.traced and info.parent is not None \
                        and info.parent.traced:
                    info.traced = True
                    info.kernel = info.kernel or info.parent.kernel
                    changed = True
                if info.traced:
                    for callee in info.calls:
                        for ci in self.by_name.get(callee, []):
                            if not ci.traced:
                                ci.traced = True
                                ci.kernel = ci.kernel or info.kernel
                                changed = True
                # GL012: the mesh context flows exactly like tracing —
                # lexical nesting and plain Python calls
                if info.parent is not None and info.parent.meshed:
                    changed |= self._merge_mesh(
                        info, info.parent.mesh_axes,
                        not info.parent.mesh_unknown)
                if info.meshed:
                    for callee in info.calls:
                        for ci in self.by_name.get(callee, []):
                            changed |= self._merge_mesh(
                                ci, info.mesh_axes, not info.mesh_unknown)
            any_change = any_change or changed
        return any_change

    # -- helpers -------------------------------------------------------------
    def traced_param_roots(self, info: _FuncInfo) -> Set[str]:
        """Formal params of this + enclosing traced functions — the names
        that carry tracers."""
        roots: Set[str] = set()
        cur: Optional[_FuncInfo] = info
        while cur is not None:
            if cur.traced:
                roots |= cur.params
            cur = cur.parent
        return roots

    def emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(rule, self.path, node.lineno,
                                     node.col_offset, message))

    # -- rule dispatch -------------------------------------------------------
    def run(self) -> List[Finding]:
        for info in self.funcs:
            if info.traced:
                self._rule_traced_branch(info)
                self._rule_host_sync(info)
            if info.kernel:
                self._rule_kernel_dot(info)
            if info.traced or info.meshed:
                self._rule_collective_balance(info)
            self._rule_static_args(info)
            self._rule_inplace_mutation(info)
            self._rule_donate_reuse(info)
            self._rule_mesh_collectives(info)
            self._rule_quantized_space(info)
        self._rule_static_args_callsites()
        self._rule_host_sync_global()
        self._rule_f64()
        self._rule_determinism()
        self._rule_lock_discipline()
        self._rule_typed_errors()
        return self.findings

    # -- GL001: Python control flow on traced values -------------------------
    def _rule_traced_branch(self, info: _FuncInfo) -> None:
        for node in info.own_nodes():
            if not isinstance(node, (ast.If, ast.While, ast.IfExp,
                                     ast.Assert)):
                continue
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Call):
                    tgt, chain = _call_target(sub)
                    if tgt in HOST_CONSTANT_JAX_CALLS:
                        continue
                    if chain and chain[0] in JAX_EXPR_ROOTS:
                        kind = ("while" if isinstance(node, ast.While)
                                else "assert" if isinstance(node, ast.Assert)
                                else "if")
                        self.emit(
                            "GL001", node,
                            f"Python `{kind}` branches on a traced value "
                            f"({'.'.join(chain)}(...)) inside traced code "
                            f"— use lax.cond/lax.select/jnp.where, or "
                            f"hoist the decision to trace time")
                        break

    # -- GL002: host syncs inside traced code --------------------------------
    def _rule_host_sync(self, info: _FuncInfo) -> None:
        tracer_roots = self.traced_param_roots(info)
        for node in info.own_nodes():
            if not isinstance(node, ast.Call):
                continue
            tgt, chain = _call_target(node)
            if tgt in HOST_SYNC_METHODS and tgt != "block_until_ready" \
                    and isinstance(node.func, ast.Attribute):
                self.emit("GL002", node,
                          f"`.{tgt}()` forces a device sync inside traced "
                          f"code — return the value and sync at the host "
                          f"boundary")
                continue
            if chain in (["jax", "device_get"], ["device_get"]):
                self.emit("GL002", node,
                          "jax.device_get inside traced code is a host "
                          "sync — keep data on device until dispatch "
                          "returns")
                continue
            if not node.args:
                continue
            arg_root = _root_name(node.args[0])
            if arg_root not in tracer_roots:
                continue
            if chain and chain[0] in NUMPY_ROOTS and tgt in (
                    "asarray", "array", "copy", "ascontiguousarray",
                    "savetxt"):
                self.emit("GL002", node,
                          f"np.{tgt} on traced value `{arg_root}` "
                          f"materializes it on host — use the jnp "
                          f"equivalent or keep the op in XLA")
            elif len(chain) == 1 and tgt in ("float", "int", "bool"):
                self.emit("GL002", node,
                          f"`{tgt}()` on traced value `{arg_root}` "
                          f"concretizes the tracer (host sync or trace "
                          f"error) — use .astype or keep it symbolic")

    # -- GL002 (module scope): syncs that matter anywhere --------------------
    def _rule_host_sync_global(self) -> None:
        """Two sync forms flagged regardless of traced context: they only
        appear on dispatch/warm/benchmark paths, where each use is either
        a bug or a deliberate boundary worth a baseline line."""
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            tgt, chain = _call_target(node)
            if tgt == "block_until_ready":
                self.emit(
                    "GL002", node,
                    "block_until_ready stalls the host until the device "
                    "drains — only warm-up / timing code should do this, "
                    "and it should be ledgered in the baseline")
            elif chain and chain[0] in NUMPY_ROOTS and \
                    tgt in ("asarray", "array") and node.args:
                for sub in ast.walk(node.args[0]):
                    if isinstance(sub, ast.Call):
                        _, sc = _call_target(sub)
                        if sc and sc[0] in ("jnp", "lax"):
                            self.emit(
                                "GL002", node,
                                f"np.{tgt} over a device expression "
                                f"materializes it on host (blocking "
                                f"dispatch) — sync only at the API "
                                f"boundary, and ledger that boundary in "
                                f"the baseline")
                            break

    # -- GL003: float64 traps in accelerator code ----------------------------
    def _rule_f64(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                chain = _attr_chain(node)
                is_jnp = bool(chain) and chain[0] in JAX_EXPR_ROOTS
                if self.kernel_file or is_jnp:
                    self.emit(
                        "GL003", node,
                        f"{'.'.join(chain) or 'float64'} in accelerator "
                        f"code: TPUs have no f64 ALU — under default "
                        f"config this silently truncates to f32, under "
                        f"x64 it breaks the kernel dtype contract; name "
                        f"an explicit f32/bf16 width")
            elif isinstance(node, ast.Call):
                tgt, chain = _call_target(node)
                if chain[-2:] == ["config", "update"] and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        node.args[0].value == "jax_enable_x64":
                    self.emit(
                        "GL003", node,
                        "jax_enable_x64 flips every default dtype to f64 "
                        "process-wide — the workbench's kernels and "
                        "packed formats are f32-only")
                elif tgt == "astype" and self.kernel_file and node.args \
                        and isinstance(node.args[0], ast.Name) and \
                        node.args[0].id == "float":
                    self.emit(
                        "GL003", node,
                        ".astype(float) means f64 under numpy semantics "
                        "— name the width (jnp.float32)")

    # -- GL004: static_argnames discipline -----------------------------------
    def _rule_static_args(self, info: _FuncInfo) -> None:
        if isinstance(info.node, ast.Lambda):
            return
        # (a) static_argnames naming a parameter the function doesn't have
        for dec in info.node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            tgt, _ = _call_target(dec)
            is_partial_jit = (tgt == "partial"
                              and any(_is_jit_chain(a) for a in dec.args))
            if tgt in ("jit", "pjit") or is_partial_jit:
                for name in sorted(_static_names_from_call(dec)):
                    if name not in info.params:
                        self.emit(
                            "GL004", dec,
                            f"static_argnames names `{name}` but "
                            f"`{info.name}` has no such parameter — jit "
                            f"raises (or silently ignores it) at call "
                            f"time")
        # (b) jitted def consuming a param where Python needs a concrete
        # value, without marking it static
        if not info.jit_decorated:
            return
        dynamic = info.params - info.static_params - {"self"}
        for node in info.own_nodes():
            if isinstance(node, ast.Call):
                tgt, chain = _call_target(node)
                if tgt == "range" and len(chain) == 1:
                    for a in node.args:
                        root = _root_name(a)
                        if root in dynamic:
                            self.emit(
                                "GL004", node,
                                f"`range({root})` inside jitted "
                                f"`{info.name}` needs a concrete value — "
                                f"add `{root}` to static_argnames or use "
                                f"lax.fori_loop")

    def _rule_static_args_callsites(self) -> None:
        """jax.jit(f, static_argnames=...) where f is a visible local def
        lacking that parameter."""
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call) or not _is_jit_chain(
                    call.func):
                continue
            statics = _static_names_from_call(call)
            if not statics or not call.args or not isinstance(
                    call.args[0], ast.Name):
                continue
            for target in self.by_name.get(call.args[0].id, []):
                for name in sorted(statics):
                    if name not in target.params:
                        self.emit(
                            "GL004", call,
                            f"static_argnames names `{name}` but "
                            f"`{target.name}` has no such parameter — jit "
                            f"raises (or silently ignores it) at call "
                            f"time")

    # -- GL005: in-place numpy mutation of jax arrays ------------------------
    def _rule_inplace_mutation(self, info: _FuncInfo) -> None:
        jax_names: Set[str] = set()
        for node in info.own_nodes():
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tname = node.targets[0].id
                if isinstance(node.value, ast.Call):
                    _, chain = _call_target(node.value)
                    if chain and chain[0] in ("jnp", "jax", "lax"):
                        jax_names.add(tname)
                        continue
                jax_names.discard(tname)
                continue
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Subscript):
                target = node.targets[0]
            elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Subscript):
                target = node.target
            if target is not None:
                root = _root_name(target)
                if root in jax_names and not root.endswith("_ref"):
                    self.emit(
                        "GL005", node,
                        f"in-place `{root}[...] = ...` on a jax array — "
                        f"jax arrays are immutable (this raises at "
                        f"runtime); use `.at[...].set(...)`")

    # -- GL006: donated buffers reused after dispatch ------------------------
    def _rule_donate_reuse(self, info: _FuncInfo) -> None:
        if isinstance(info.node, ast.Lambda):
            return
        donating: Dict[str, Tuple[int, ...]] = {}
        donated: Dict[str, int] = {}            # var -> donation line
        skip_nodes: Set[int] = set()            # Name nodes of the donation
        for node in info.own_nodes():
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and _is_jit_chain(node.value.func):
                nums: Tuple[int, ...] = ()
                for kw in node.value.keywords:
                    if kw.arg == "donate_argnums":
                        v = kw.value
                        if isinstance(v, ast.Constant) and isinstance(
                                v.value, int):
                            nums = (v.value,)
                        elif isinstance(v, (ast.Tuple, ast.List)):
                            nums = tuple(
                                e.value for e in v.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, int))
                if nums:
                    donating[node.targets[0].id] = nums
            elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Name) and node.func.id in donating:
                for pos in donating[node.func.id]:
                    if pos < len(node.args):
                        root = _root_name(node.args[pos])
                        if root is not None:
                            donated.setdefault(root, node.lineno)
                            for sub in ast.walk(node.args[pos]):
                                skip_nodes.add(id(sub))
            elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load) and node.id in donated \
                    and id(node) not in skip_nodes:
                self.emit(
                    "GL006", node,
                    f"`{node.id}` was donated to a jitted call (line "
                    f"{donated[node.id]}) and is read again — the buffer "
                    f"may already be aliased to the output (garbage on "
                    f"TPU)")
                del donated[node.id]

    # -- GL007: kernel dots without explicit accumulation dtype --------------
    def _rule_kernel_dot(self, info: _FuncInfo) -> None:
        for node in info.own_nodes():
            if not isinstance(node, ast.Call):
                continue
            tgt, chain = _call_target(node)
            if tgt in KERNEL_DOT_CALLS and chain and \
                    chain[0] in ("lax", "jnp", "jax"):
                if not any(kw.arg == "preferred_element_type"
                           for kw in node.keywords):
                    self.emit(
                        "GL007", node,
                        f"{'.'.join(chain)} in kernel code without "
                        f"preferred_element_type — the accumulation "
                        f"dtype follows operand promotion (bf16 operands "
                        f"accumulate in bf16: silent precision loss on "
                        f"the MXU)")

    # -- GL008: determinism (injectable-clock / seeded-RNG contract) ---------
    def _rule_determinism(self) -> None:
        """Direct wall-clock reads and global-RNG draws.  Only *calls*
        match: ``clock=time.monotonic`` as a default argument is the
        sanctioned injection idiom and is a bare reference, never a
        call.  The one legitimate boundary (pipeline/staleness.py's
        ``wall_clock``) carries an inline waiver."""
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            tgt, chain = _call_target(node)
            if not chain or tgt is None:
                continue
            mod = self._module_of(chain[0])
            if len(chain) == 2 and mod == "time" and \
                    tgt in WALL_CLOCK_CALLS:
                self.emit(
                    "GL008", node,
                    f"direct `{chain[0]}.{tgt}()` — r12-r15 subsystems "
                    f"promise an injectable clock; accept "
                    f"`clock=time.monotonic` as a parameter and call "
                    f"`clock()` so SimClock tests stay deterministic")
            elif mod == "datetime" and tgt in DATETIME_NOW_CALLS and \
                    2 <= len(chain) <= 3:
                self.emit(
                    "GL008", node,
                    f"`{'.'.join(chain)}()` reads the wall clock — "
                    f"thread a clock parameter (or a timestamp argument) "
                    f"instead of sampling ambient time")
            elif len(chain) == 2 and mod == "random" and \
                    tgt in PY_RANDOM_FNS:
                self.emit(
                    "GL008", node,
                    f"`{chain[0]}.{tgt}()` draws from the process-global "
                    f"RNG — construct `random.Random(seed)` (or accept "
                    f"an rng parameter) so runs replay bit-identically")
            elif mod == "numpy" and len(chain) == 3 and \
                    chain[1] == "random":
                if tgt in NP_RNG_CONSTRUCTORS:
                    if not node.args and not node.keywords:
                        self.emit(
                            "GL008", node,
                            f"`{'.'.join(chain)}()` without a seed pulls "
                            f"OS entropy — pass an explicit seed (the "
                            f"workbench's runs must replay "
                            f"bit-identically)")
                else:
                    self.emit(
                        "GL008", node,
                        f"`{'.'.join(chain)}()` uses numpy's legacy "
                        f"global RNG — use a seeded "
                        f"np.random.default_rng(seed) generator")
            elif len(chain) == 1:
                fi = self.from_imports.get(tgt)
                if fi is None:
                    continue
                fmod, fsym = fi
                if fmod == "time" and fsym in WALL_CLOCK_CALLS:
                    self.emit(
                        "GL008", node,
                        f"direct `{tgt}()` (time.{fsym}) — accept an "
                        f"injectable clock parameter instead")
                elif fmod == "random" and fsym in PY_RANDOM_FNS:
                    self.emit(
                        "GL008", node,
                        f"`{tgt}()` (random.{fsym}) draws from the "
                        f"process-global RNG — use a seeded instance")

    # -- GL009: lock discipline ---------------------------------------------
    def _rule_lock_discipline(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self._lock_check_class(node)

    @staticmethod
    def _self_attr(node: ast.AST, selfname: str) -> Optional[str]:
        """First attribute on a self.<attr>[...]... chain, else None."""
        attrs: List[str] = []
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            if isinstance(node, ast.Attribute):
                attrs.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name) and node.id == selfname and attrs:
            return attrs[-1]
        return None

    def _lock_check_class(self, cls: ast.ClassDef) -> None:
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

        def self_name(m) -> str:
            return m.args.args[0].arg if m.args.args else "self"

        # 1. which attrs hold threading locks?
        locks: Set[str] = set()
        for m in methods:
            sn = self_name(m)
            for node in ast.walk(m):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                tgt, chain = _call_target(node.value)
                if tgt not in LOCK_FACTORIES:
                    continue
                from_threading = (
                    (len(chain) >= 2
                     and self._module_of(chain[0]) == "threading")
                    or (len(chain) == 1 and self.from_imports.get(
                        tgt, ("", ""))[0] == "threading"))
                if not from_threading:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == sn:
                        locks.add(t.attr)
        if not locks:
            return

        # 2. classify every self-attr mutation site as locked/unlocked
        locked: Dict[str, List[ast.AST]] = {}
        unlocked: Dict[str, List[ast.AST]] = {}

        def is_lock_expr(expr: ast.AST, sn: str) -> bool:
            a = self._self_attr(expr, sn)
            return a in locks

        def record(stmt: ast.AST, sn: str, in_lock: bool) -> None:
            sites = locked if in_lock else unlocked
            for node in ast.walk(stmt):
                attr = None
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        a = self._self_attr(t, sn)
                        if a:
                            sites.setdefault(a, []).append(node)
                    continue
                if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    attr = self._self_attr(node.target, sn)
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        a = self._self_attr(t, sn)
                        if a:
                            sites.setdefault(a, []).append(node)
                    continue
                elif isinstance(node, ast.Call):
                    tgt, chain = _call_target(node)
                    if tgt in MUTATOR_METHODS and isinstance(
                            node.func, ast.Attribute):
                        attr = self._self_attr(node.func.value, sn)
                    elif tgt in HEAPQ_MUTATORS and node.args:
                        attr = self._self_attr(node.args[0], sn)
                if attr:
                    sites.setdefault(attr, []).append(node)

        def scan(body: List[ast.stmt], sn: str, in_lock: bool) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                if isinstance(stmt, ast.With):
                    inner = in_lock or any(
                        is_lock_expr(i.context_expr, sn)
                        for i in stmt.items)
                    scan(stmt.body, sn, inner)
                elif isinstance(stmt, (ast.If, ast.For, ast.While)):
                    head = (stmt.iter if isinstance(stmt, ast.For)
                            else stmt.test)
                    record(head, sn, in_lock)
                    scan(stmt.body, sn, in_lock)
                    scan(stmt.orelse, sn, in_lock)
                elif isinstance(stmt, ast.Try):
                    scan(stmt.body, sn, in_lock)
                    for h in stmt.handlers:
                        scan(h.body, sn, in_lock)
                    scan(stmt.orelse, sn, in_lock)
                    scan(stmt.finalbody, sn, in_lock)
                else:
                    record(stmt, sn, in_lock)

        for m in methods:
            if m.name in ("__init__", "__new__"):
                continue            # construction precedes sharing
            scan(list(m.body), self_name(m), in_lock=False)

        for attr in sorted(set(locked) & set(unlocked)):
            if attr in locks:
                continue
            for node in unlocked[attr]:
                self.emit(
                    "GL009", node,
                    f"`self.{attr}` is mutated under the lock elsewhere "
                    f"in `{cls.name}` but not here — every write to a "
                    f"lock-guarded attribute must sit inside `with "
                    f"self._lock:` (use RLock if helpers re-enter)")

    # -- GL011: typed-error discipline ---------------------------------------
    def _rule_typed_errors(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ExceptHandler):
                if node.type is None:
                    self.emit(
                        "GL011", node,
                        "bare `except:` catches SystemExit/Keyboard"
                        "Interrupt too — name the typed fault "
                        "(SwapRejected, OOCBlockError, FaultError, ...) "
                        "or `except Exception` at an outermost boundary")
                elif len(node.body) == 1 and isinstance(node.body[0],
                                                        ast.Pass):
                    self.emit(
                        "GL011", node,
                        "swallowed exception (`except ...: pass`) — "
                        "record, re-raise, or degrade explicitly; silent "
                        "drops hide chaos-matrix regressions")
            elif isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                name = None
                if isinstance(exc, ast.Call) and isinstance(exc.func,
                                                            ast.Name):
                    name = exc.func.id
                elif isinstance(exc, ast.Name):
                    name = exc.id
                if name in ("Exception", "BaseException"):
                    self.emit(
                        "GL011", node,
                        f"`raise {name}(...)` defeats the typed-error "
                        f"contract — raise one of the workbench's typed "
                        f"faults so callers can catch precisely")

    # -- GL012: collective/mesh discipline ------------------------------------
    def _collective_call(self, call: ast.Call) -> Optional[str]:
        """The collective's name when this call is a jax.lax collective,
        else None.  Requires a jax-rooted callee — a method named `psum`
        on some service object never matches."""
        tgt, chain = _call_target(call)
        if tgt not in COLLECTIVE_CALLS:
            return None
        if len(chain) == 1:
            mod = self.from_imports.get(tgt, ("", ""))[0]
            return tgt if mod in ("jax.lax", "lax") else None
        root = chain[0]
        if root in ("lax", "jax"):
            return tgt
        if self.from_imports.get(root) == ("jax", "lax"):
            return tgt
        if self.import_aliases.get(root, "").split(".")[0] == "jax":
            return tgt
        return None

    def _collective_axis(self, call: ast.Call, info: _FuncInfo
                         ) -> Tuple[str, Optional[str]]:
        """Classify a collective's axis argument:
        ('const', name)   — string literal / module string constant
        ('param', name)   — a formal parameter of this or an enclosing
                            function (the caller owns the binding)
        ('unknown', None) — any other expression form"""
        axis_node: Optional[ast.AST] = None
        for kw in call.keywords:
            if kw.arg == "axis_name":
                axis_node = kw.value
        if axis_node is None and len(call.args) >= 2:
            axis_node = call.args[1]
        if axis_node is None:
            return "unknown", None
        if isinstance(axis_node, ast.Constant) and \
                isinstance(axis_node.value, str):
            return "const", axis_node.value
        if isinstance(axis_node, ast.Name):
            name = axis_node.id
            if name in self.str_constants:
                return "const", self.str_constants[name]
            if self.axis_resolver is not None:
                val = self.axis_resolver(name)
                if val is not None:
                    return "const", val
            cur: Optional[_FuncInfo] = info
            while cur is not None:
                if name in cur.params:
                    return "param", name
                cur = cur.parent
        return "unknown", None

    def _rule_mesh_collectives(self, info: _FuncInfo) -> None:
        for node in info.strict_own_nodes():
            if not isinstance(node, ast.Call):
                continue
            coll = self._collective_call(node)
            if coll is None:
                continue
            kind, axis = self._collective_axis(node, info)
            if not info.meshed:
                # a parameter axis flows from the caller — the closure
                # checks the caller instead, so only LITERAL axes can be
                # proven unbound here
                if kind == "const":
                    where = f"`{info.name}`" if info.name else "a lambda"
                    self.emit(
                        "GL012", node,
                        f"lax.{coll} over axis {axis!r} in {where}, which "
                        f"no shard_map/pmap entry point reaches — the "
                        f"axis is unbound at trace time (tracing raises, "
                        f"or a stubbed mesh silently no-ops the "
                        f"reduction); establish the mesh context or "
                        f"accept axis_name from the caller")
                continue
            if kind == "const" and not info.mesh_unknown and \
                    info.mesh_axes and axis not in info.mesh_axes:
                known = ", ".join(repr(a) for a in sorted(info.mesh_axes))
                self.emit(
                    "GL012", node,
                    f"lax.{coll} names axis {axis!r} but the enclosing "
                    f"mesh context binds only {known} — the collective "
                    f"raises an unbound-axis error at trace time (or "
                    f"reduces over the wrong replica group if {axis!r} "
                    f"exists on an outer mesh)")

    def _count_collectives(self, nodes) -> int:
        return sum(1 for n in nodes
                   if isinstance(n, ast.Call)
                   and self._collective_call(n) is not None)

    def _branch_collective_count(self, branch: ast.AST) -> Optional[int]:
        """Collectives a lax.cond/switch branch performs; None when the
        branch cannot be resolved statically (partial(...), methods,
        multiply-defined names)."""
        if isinstance(branch, ast.Lambda):
            return self._count_collectives(ast.walk(branch.body))
        if isinstance(branch, ast.Name):
            infos = self.by_name.get(branch.id, [])
            if len(infos) == 1:
                return self._count_collectives(infos[0].strict_own_nodes())
        return None

    def _stmt_collective_count(self, stmts) -> int:
        c = 0
        for s in stmts:
            c += self._count_collectives([s, *_ordered_walk(s)])
        return c

    def _rule_collective_balance(self, info: _FuncInfo) -> None:
        """The SPMD deadlock shape: under a traced/meshed program, one
        branch of a conditional performs a collective the other doesn't.
        Replicas that disagree on the predicate (or a traced predicate
        lowered per-shard) leave some devices waiting in the collective
        forever.  Host-static Python `if`s (config flags, `axis_name is
        None` dispatch) are exempt — only traced-value tests count."""
        for node in info.strict_own_nodes():
            if isinstance(node, ast.Call):
                tgt, chain = _call_target(node)
                if not chain or chain[0] not in ("lax", "jax"):
                    continue
                branches: List[ast.AST] = []
                if tgt == "cond" and len(node.args) >= 3:
                    branches = list(node.args[1:3])
                elif tgt == "switch" and len(node.args) >= 2 and \
                        isinstance(node.args[1], (ast.List, ast.Tuple)):
                    branches = list(node.args[1].elts)
                if len(branches) < 2:
                    continue
                counts = [self._branch_collective_count(b)
                          for b in branches]
                if any(c is None for c in counts):
                    continue
                if any(c > 0 for c in counts) and \
                        any(c == 0 for c in counts):
                    self.emit(
                        "GL012", node,
                        f"lax.{tgt} where one branch performs a "
                        f"collective and another performs none — under "
                        f"SPMD every replica must reach the same "
                        f"collective sequence, so the no-collective "
                        f"branch deadlocks the mesh; hoist the "
                        f"collective out of the conditional or make "
                        f"every branch participate (psum of zeros)")
            elif isinstance(node, ast.If) and node.orelse:
                if not self._tests_traced_value(node.test):
                    continue
                nb = self._stmt_collective_count(node.body)
                ne = self._stmt_collective_count(node.orelse)
                if (nb > 0) != (ne > 0):
                    self.emit(
                        "GL012", node,
                        "`if` on a traced value where only one arm "
                        "performs a collective — per-shard divergence "
                        "deadlocks the mesh (the arm without the "
                        "collective never posts the matching reduction); "
                        "make both arms participate or hoist the "
                        "predicate to trace time")

    def _tests_traced_value(self, test: ast.AST) -> bool:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call):
                tgt, chain = _call_target(sub)
                if tgt in HOST_CONSTANT_JAX_CALLS:
                    continue
                if chain and chain[0] in JAX_EXPR_ROOTS:
                    return True
        return False

    # -- GL013: quantized-space discipline -------------------------------------
    def _dtype_name_of(self, node: ast.AST) -> Optional[str]:
        chain = _attr_chain(node)
        if chain and chain[-1] in _CAST_SPACE:
            return chain[-1]
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value if node.value in _CAST_SPACE else None
        return None

    def _space_of(self, expr: ast.AST,
                  env: Dict[str, Optional[str]]) -> Optional[str]:
        """Abstract value space of an expression: 'bin' | 'int8' | 'bf16'
        | 'stat' | None (unknown).  Deliberately conservative — unknown
        propagates, so every GL013 finding rests on a PROVEN mix."""
        if isinstance(expr, ast.Name):
            return env.get(expr.id)
        if isinstance(expr, (ast.Subscript, ast.Starred)):
            return self._space_of(expr.value, env)
        if isinstance(expr, ast.Attribute):
            if expr.attr in BIN_CODE_FIELDS:
                return "bin"
            if expr.attr in WIRE_FIELDS:
                return "int8"
            if expr.attr == "T":
                return self._space_of(expr.value, env)
            return None
        if isinstance(expr, ast.Call):
            tgt, chain = _call_target(expr)
            if isinstance(expr.func, ast.Attribute):
                recv = expr.func.value
                if tgt == "astype" and expr.args:
                    d = self._dtype_name_of(expr.args[0])
                    if d is not None:
                        return _CAST_SPACE[d]
                    return self._space_of(recv, env)  # width-only change
                if tgt in _SPACE_PRESERVING_METHODS:
                    return self._space_of(recv, env)
            if tgt == "where" and chain and chain[0] in JAX_EXPR_ROOTS \
                    and len(expr.args) == 3:
                a = self._space_of(expr.args[1], env)
                b = self._space_of(expr.args[2], env)
                return a if a == b else None
            return None
        if isinstance(expr, ast.BinOp):
            left = self._space_of(expr.left, env)
            right = self._space_of(expr.right, env)
            if left == right:
                return left
            # f32 is absorbing under JAX promotion: stat * scale -> stat
            if "stat" in (left, right):
                return "stat"
            return None
        if isinstance(expr, ast.UnaryOp):
            return self._space_of(expr.operand, env)
        return None

    @staticmethod
    def _is_float_literal(node: ast.AST) -> bool:
        if isinstance(node, ast.UnaryOp):
            node = node.operand
        return isinstance(node, ast.Constant) and \
            isinstance(node.value, float)

    def _module_has_int8_guard(self) -> bool:
        """Any comparison in this MODULE against the 2^31/127 bound —
        a literal 16_909_320, a name like INT8_ACC_ROW_LIMIT, or the
        expression (1 << 31) // 127 — counts as the row-count guard."""
        if self._int8_guard is None:
            self._int8_guard = any(
                isinstance(node, ast.Compare)
                and any(self._is_int8_bound(op)
                        for op in [node.left, *node.comparators])
                for node in ast.walk(self.tree))
        return self._int8_guard

    @staticmethod
    def _is_int8_bound(node: ast.AST) -> bool:
        if isinstance(node, ast.Constant):
            return node.value == INT8_ACC_ROW_LIMIT
        chain = _attr_chain(node)
        if chain:
            leaf = chain[-1].upper()
            return "INT8" in leaf and ("LIMIT" in leaf or "BOUND" in leaf)
        if isinstance(node, ast.BinOp) and \
                isinstance(node.op, ast.FloorDiv) and \
                isinstance(node.right, ast.Constant) and \
                node.right.value == 127:
            lhs = node.left
            return (isinstance(lhs, ast.BinOp)
                    and ((isinstance(lhs.op, ast.LShift)
                          and isinstance(lhs.right, ast.Constant)
                          and lhs.right.value == 31)
                         or (isinstance(lhs.op, ast.Pow)
                             and isinstance(lhs.right, ast.Constant)
                             and lhs.right.value == 31)))
        return False

    def _assigns_int32(self, info: _FuncInfo, name: str) -> bool:
        """Does any assignment in this function bind `name` to an int32
        dtype?  Handles tuple unpacking (`oh_t, acc_t = jnp.int8,
        jnp.int32`)."""
        for node in info.strict_own_nodes():
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                pairs = []
                if isinstance(target, ast.Name):
                    pairs = [(target, node.value)]
                elif isinstance(target, (ast.Tuple, ast.List)) and \
                        isinstance(node.value, (ast.Tuple, ast.List)) and \
                        len(target.elts) == len(node.value.elts):
                    pairs = list(zip(target.elts, node.value.elts))
                for t, v in pairs:
                    if isinstance(t, ast.Name) and t.id == name:
                        ch = _attr_chain(v)
                        if ch and ch[-1] == "int32":
                            return True
        return False

    def _int8_accumulation(self, call: ast.Call, info: _FuncInfo,
                           env: Dict[str, Optional[str]]) -> bool:
        tgt, chain = _call_target(call)
        if tgt in KERNEL_DOT_CALLS and chain and \
                chain[0] in ("lax", "jnp", "jax"):
            for kw in call.keywords:
                if kw.arg != "preferred_element_type":
                    continue
                ch = _attr_chain(kw.value)
                if ch and ch[-1] == "int32":
                    return True
                if isinstance(kw.value, ast.Name) and \
                        self._assigns_int32(info, kw.value.id):
                    return True
            return False
        if tgt == "sum" and chain and chain[0] == "jnp" and call.args:
            return self._space_of(call.args[0], env) == "int8"
        return False

    def _in_sanctioned_hop(self, info: _FuncInfo) -> bool:
        cur: Optional[_FuncInfo] = info
        while cur is not None:
            if cur.name in SANCTIONED_HOP_FUNCS:
                return True
            cur = cur.parent
        return False

    def _rule_quantized_space(self, info: _FuncInfo) -> None:
        env: Dict[str, Optional[str]] = {}
        for node in info.strict_own_nodes():
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                env[node.targets[0].id] = self._space_of(node.value, env)
            operands: List[ast.AST] = []
            if isinstance(node, ast.Compare):
                if any(isinstance(op, (ast.Is, ast.IsNot, ast.In,
                                       ast.NotIn)) for op in node.ops):
                    operands = []
                else:
                    operands = [node.left, *node.comparators]
            elif isinstance(node, ast.BinOp):
                operands = [node.left, node.right]
            if operands:
                spaces = [self._space_of(o, env) for o in operands]
                has_bin = "bin" in spaces
                has_stat = "stat" in spaces or any(
                    self._is_float_literal(o) for o in operands)
                if has_bin and has_stat:
                    what = ("comparison" if isinstance(node, ast.Compare)
                            else "arithmetic")
                    self.emit(
                        "GL013", node,
                        f"{what} mixes u8 bin codes with dequantized "
                        f"f32 values — bin codes are ordinal, not "
                        f"magnitudes (PARITY.md: the quantized space IS "
                        f"the compute space); route in bin space or "
                        f"dequantize BOTH sides first")
                    continue
            if not isinstance(node, ast.Call):
                continue
            if self._collective_call(node) == "ppermute" and node.args \
                    and not self._in_sanctioned_hop(info):
                if self._space_of(node.args[0], env) in WIRE_SPACES:
                    self.emit(
                        "GL013", node,
                        "lax.ppermute of a quantized (int8/bf16) payload "
                        "outside wire_transfer — each ring hop must "
                        "requantize against the CURRENT partial's scale "
                        "(ops/quantize.wire_transfer), or D-1 hops "
                        "compound the quantization error unbounded")
            elif self._int8_accumulation(node, info, env):
                if not self._module_has_int8_guard():
                    self.emit(
                        "GL013", node,
                        f"int8 accumulation into int32 without a "
                        f"row-count guard in this module — past "
                        f"{INT8_ACC_ROW_LIMIT:,} rows a (segment, bin) "
                        f"cell can exceed 2^31-1 and wrap silently; "
                        f"compare rows against INT8_ACC_ROW_LIMIT "
                        f"(= (1 << 31) // 127) and raise before "
                        f"dispatch")


# ---------------------------------------------------------------------------
# GL012 probe — the tools/hlo_counts.py shim re-exports this
# ---------------------------------------------------------------------------
def mesh_probe(path: str, src: Optional[str] = None) -> List[dict]:
    """Per-function mesh-context report for one module (per-file mode:
    cross-module seeds and imported axis constants are not visible —
    `axes_complete` is False for contexts that need them).

    Returns one dict per named function that is meshed or performs a
    collective: ``{"function", "line", "meshed", "axes",
    "axes_complete", "collectives": [{"op", "line", "axis"}...]}``.
    """
    if src is None:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
    tree = ast.parse(src)
    analysis = _ModuleAnalysis(path, tree, is_kernel_file(src))
    analysis.close_local()
    out: List[dict] = []
    for info in analysis.funcs:
        if not info.name:
            continue
        collectives = []
        for node in info.strict_own_nodes():
            if isinstance(node, ast.Call):
                coll = analysis._collective_call(node)
                if coll is not None:
                    _, axis = analysis._collective_axis(node, info)
                    collectives.append({"op": coll, "line": node.lineno,
                                        "axis": axis})
        if info.meshed or collectives:
            out.append({
                "function": info.name,
                "line": info.node.lineno,
                "meshed": info.meshed,
                "axes": sorted(info.mesh_axes),
                "axes_complete": info.meshed and not info.mesh_unknown,
                "collectives": collectives,
            })
    return out


RULE_IDS = ("GL001", "GL002", "GL003", "GL004", "GL005", "GL006", "GL007",
            "GL008", "GL009", "GL010", "GL011", "GL012", "GL013", "GL014")


_KERNEL_FILE_RE = re.compile(
    r"pallas_call\(|from jax\.experimental import pallas|"
    r"import pallas_tpu|jax\.experimental\.pallas")


def is_kernel_file(src: str) -> bool:
    """A module that DEFINES Pallas kernels (not one that merely calls a
    wrapper from a kernel module) gets the dtype-discipline rules."""
    return bool(_KERNEL_FILE_RE.search(src))


def apply_waivers(findings: List[Finding], src: str) -> List[Finding]:
    """Drop findings waived inline: `# graftlint: GLxxx — reason` on the
    finding's line.  GL000 (parse failure) is never waivable — a file
    that does not parse cannot carry a trustworthy comment."""
    lines = src.splitlines()
    kept = []
    for f in findings:
        if f.rule != "GL000":
            line = lines[f.line - 1] if f.line - 1 < len(lines) else ""
            if "graftlint:" in line:
                waiver = line.split("graftlint:", 1)[1]
                if f.rule in waiver or "off" in waiver:
                    continue
        kept.append(f)
    return sorted(kept, key=lambda f: (f.path, f.line, f.rule))


def analyze_source(path: str, src: str) -> List[Finding]:
    """Run every Layer-1 rule over one module's source (standalone
    per-file mode; whole-program mode lives in analysis.program)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("GL000", path, e.lineno or 1, 0,
                        f"syntax error: {e.msg}")]
    analysis = _ModuleAnalysis(path, tree, is_kernel_file(src))
    analysis.close_local()
    findings = analysis.run()
    return apply_waivers(findings, src)

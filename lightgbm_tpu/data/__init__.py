"""Out-of-core data subsystem (ISSUE 7).

Streaming BinMapper construction (GK-style mergeable quantile sketches),
host-resident binned block storage with an async double-buffered
host->HBM prefetcher, and the streamed per-block training drivers.
"""

from .block_store import BlockStore, OOCBlockError
from .sketch import GKSummary, StreamingBinMapperBuilder, schema_digest
from .stream_grow import (
    stream_goss_round,
    stream_grow_tree,
    stream_plain_round,
)

__all__ = [
    "BlockStore",
    "OOCBlockError",
    "GKSummary",
    "schema_digest",
    "StreamingBinMapperBuilder",
    "stream_goss_round",
    "stream_grow_tree",
    "stream_plain_round",
]

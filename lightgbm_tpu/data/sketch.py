"""One-pass mergeable streaming quantile sketch for out-of-core binning.

The in-memory :meth:`BinMapper.fit` needs the whole column resident to run
``np.unique`` / ``np.quantile``; under out-of-core training (ISSUE 7) the
dataset arrives as row blocks and is never materialized.  This module
builds the SAME BinMapper from a single pass over the blocks via a
per-feature adaptive sketch with three regimes:

* **exact** — raw finite values buffered while the stream is small
  (``capacity`` rows, default 200k = the in-memory fit's own sampling
  threshold).  Finalizing from here calls the SHARED
  :func:`~lightgbm_tpu.dataset.numeric_bin_bounds` on the concatenated
  buffer — bit-identical to the in-memory fit whenever total rows stay
  within ``min(capacity, 200_000)`` (beyond 200k the in-memory fit
  subsamples; the stream does not).
* **distinct** — past capacity, columns with a bounded value vocabulary
  (``max_distinct``) collapse to exact ``(distinct, counts)`` tallies.
  Both fit paths stay EXACT from here at any n: the few-distinct "mids"
  path reads only distinct/counts, and the quantile path goes through
  :func:`~lightgbm_tpu.dataset._weighted_quantile`, a bit-exact
  reformulation of ``np.quantile(method="linear")`` on the expanded
  column.
* **gk** — genuinely continuous columns degrade to a Greenwald–Khanna
  summary: tuples ``(v, g, Δ)`` where ``cumsum(g)[i] <= rank(v_i) <=
  cumsum(g)[i] + Δ_i``.  Each incoming block is first reduced to its own
  EXACT ``eps/2``-rank summary (the block is fully known, so this is a
  lossless-within-eps/2 "merge" of a per-block sketch — what makes the
  sketch mergeable), then the surviving ~2/eps tuples are inserted and
  compressed under the classic ``g_i + Δ_i <= floor(2·eps_gk·n)``
  invariant.  Quantile queries are then rank-accurate to ``eps·n``
  (documented ε; tests/test_sketch.py checks the realized rank error).

NaN handling is exact in every regime (per-feature NaN counters), so the
nan-bin layout always matches the in-memory fit.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..dataset import BinMapper, numeric_bin_bounds

_DEFAULT_CAPACITY = 200_000   # == BinMapper.fit's sample_cnt threshold
_DEFAULT_MAX_DISTINCT = 4096


def _merge_distinct(av, ac, bv, bc):
    """Merge two (distinct values, counts) tallies into one."""
    v = np.concatenate([av, bv])
    c = np.concatenate([ac, bc])
    order = np.argsort(v, kind="stable")
    v, c = v[order], c[order]
    new = np.r_[True, v[1:] != v[:-1]]
    idx = np.cumsum(new) - 1
    out_v = v[new]
    out_c = np.zeros(len(out_v), np.int64)
    np.add.at(out_c, idx, c)
    return out_v, out_c


class GKSummary:
    """Greenwald–Khanna quantile summary over a weighted value stream.

    Invariant: with ``rmin_i = cumsum(g)[i]``, the true rank of ``v_i``
    (count of stream values <= v_i) lies in ``[rmin_i, rmin_i + d_i]``.
    Compression merges neighbors while ``g_i + g_{i+1} + d_{i+1}`` stays
    under ``floor(2·eps·n)``; the first/last tuples are never merged away
    (exact min/max).
    """

    def __init__(self, eps: float):
        self.eps = float(eps)
        self.n = 0
        self.v = np.empty(0, np.float64)
        self.g = np.empty(0, np.int64)
        self.d = np.empty(0, np.int64)

    def insert_distinct(self, dv: np.ndarray, dc: np.ndarray) -> None:
        """Insert a sorted (distinct, counts) batch (a block's exact
        summary — within-batch ranks carry no uncertainty, so new tuples
        only inherit the OLD successor's interval).

        A batch tuple with ``dc > 1`` is a collapsed BAND: up to ``dc - 1``
        of its mass sits at values strictly below ``dv`` (the band's
        interior, discarded by :meth:`_FeatureSketch._block_summary`).
        Placing all of it at ``dv`` under-counts the true rank of any OLD
        tuple the band straddles, so those tuples' Δ is widened by the
        band's below-mass — keeping every interval HONEST (rank really is
        in ``[rmin, rmin + Δ]``; tests/test_sketch.py checks it), at the
        price that banding debt accumulates into Δ instead of silently
        into the answer."""
        dv = np.asarray(dv, np.float64)
        dc = np.asarray(dc, np.int64)
        if len(dv) == 0:
            return
        n1 = self.n + int(dc.sum())
        if self.n == 0:
            self.v, self.g = dv.copy(), dc.copy()
            self.d = np.zeros(len(dv), np.int64)
            self.n = n1
            self._compress()
            return
        pos = np.searchsorted(self.v, dv)
        match = (pos < len(self.v)) & (self.v[np.minimum(pos, len(self.v) - 1)]
                                       == dv)
        # new tuples inherit the PRE-widening successor interval (their own
        # old-stream uncertainty is the old summary's, not this batch's)
        nv, nc = dv[~match], dc[~match]
        nd = np.empty(0, np.int64)
        if len(nv):
            pos2 = np.searchsorted(self.v, nv)
            # below-min is NOT exact here (banding hides mass under the
            # first tuple's value), so it inherits tuple 0's interval like
            # any interior insert; above-max stays exact (block summaries
            # always keep the true block max)
            interior = pos2 < len(self.v)
            succ = np.minimum(pos2, len(self.v) - 1)
            nd = np.where(interior, self.g[succ] + self.d[succ] - 1,
                          0).astype(np.int64)
        # widen old tuples strictly inside a band: band i covers
        # (dv[i-1], dv[i]] and hides up to dc[i]-1 of mass below the old
        # tuple's value (an old tuple AT dv[i] is exact: all band mass
        # really is <= it)
        band = np.searchsorted(dv, self.v, side="left")
        inside = (band < len(dv)) & (dv[np.minimum(band, len(dv) - 1)]
                                     != self.v)
        self.d += np.where(inside,
                           dc[np.minimum(band, len(dv) - 1)] - 1, 0)
        if match.any():
            # exact value collision: fold the mass into the existing tuple
            # (its rank interval just shifts with the added mass)
            self.g[pos[match]] += dc[match]
        if len(nv):
            v = np.concatenate([self.v, nv])
            g = np.concatenate([self.g, nc])
            d = np.concatenate([self.d, nd])
            order = np.argsort(v, kind="stable")
            self.v, self.g, self.d = v[order], g[order], d[order]
        self.n = n1
        self._compress()

    def merge(self, other: "GKSummary") -> None:
        """Merge another summary into this one (tuples re-inserted as
        weighted values; the other's within-tuple uncertainty Δ is
        surrendered, adding up to its ``eps·n_other`` to the rank error —
        the documented merged bound is ``eps·n_self + eps·n_other``)."""
        if other.n == 0:
            return
        self.insert_distinct(other.v, other.g)

    def _compress(self) -> None:
        t = int(np.floor(2.0 * self.eps * self.n))
        m = len(self.v)
        if m <= 2 or t <= 0:
            return
        v, g, d = list(self.v), list(self.g), list(self.d)
        i = m - 2
        while i >= 1:
            if g[i] + g[i + 1] + d[i + 1] <= t:
                g[i + 1] += g[i]
                del v[i], g[i], d[i]
            i -= 1
        self.v = np.asarray(v, np.float64)
        self.g = np.asarray(g, np.int64)
        self.d = np.asarray(d, np.int64)

    def query(self, qs: np.ndarray) -> np.ndarray:
        """Values whose rank is near ``q·n``: picks the tuple whose honest
        rank interval ``[rmin, rmax]`` minimizes the worst-case distance
        ``max(r - rmin, rmax - r)`` — optimal given the intervals, and
        since consecutive intervals overlap within the compression
        threshold the realized error stays within the sketch ε
        (vectorized over ``qs``)."""
        if self.n == 0:
            return np.full(np.shape(qs), np.nan)
        r = np.asarray(qs, np.float64).reshape(-1) * self.n
        rmin = np.cumsum(self.g)
        rmax = rmin + self.d
        cost = np.maximum(r[:, None] - rmin[None, :],
                          rmax[None, :] - r[:, None])
        return self.v[np.argmin(cost, axis=1)].reshape(np.shape(qs))


class _FeatureSketch:
    """Adaptive per-feature sketch: exact buffer -> distinct tally -> GK."""

    def __init__(self, capacity: int, eps: float, max_distinct: int):
        self.capacity = int(capacity)
        self.eps = float(eps)
        self.max_distinct = int(max_distinct)
        self.mode = "exact"
        self.buffer: List[np.ndarray] = []
        self.n = 0                       # finite values seen
        self.nan_count = 0               # exact (nan-bin layout must match)
        self.distinct: Optional[np.ndarray] = None
        self.counts: Optional[np.ndarray] = None
        self.gk: Optional[GKSummary] = None

    def update(self, col: np.ndarray) -> None:
        col = np.asarray(col, np.float64)
        finite_mask = ~np.isnan(col)
        self.nan_count += int(len(col) - finite_mask.sum())
        vals = col[finite_mask]
        if len(vals) == 0:
            return
        self.n += len(vals)
        if self.mode == "exact":
            self.buffer.append(vals)
            if self.n > self.capacity:
                self._spill()
            return
        dv, dc = np.unique(vals, return_counts=True)
        if self.mode == "distinct":
            self.distinct, self.counts = _merge_distinct(
                self.distinct, self.counts, dv, dc.astype(np.int64))
            if len(self.distinct) > self.max_distinct:
                self._degrade_to_gk()
        else:
            self.gk.insert_distinct(*self._block_summary(dv, dc))

    def _spill(self) -> None:
        """exact -> distinct (bounded vocabulary) or GK (continuous)."""
        vals = np.concatenate(self.buffer)
        self.buffer = []
        dv, dc = np.unique(vals, return_counts=True)
        if len(dv) <= self.max_distinct:
            self.mode = "distinct"
            self.distinct, self.counts = dv, dc.astype(np.int64)
        else:
            self.mode = "gk"
            self.gk = GKSummary(self.eps / 2.0)
            self.gk.insert_distinct(*self._block_summary(dv, dc))

    def _degrade_to_gk(self) -> None:
        self.mode = "gk"
        self.gk = GKSummary(self.eps / 2.0)
        self.gk.insert_distinct(*self._block_summary(self.distinct,
                                                     self.counts))
        self.distinct = self.counts = None

    def _block_summary(self, dv: np.ndarray, dc: np.ndarray):
        """Exact eps/2-rank summary of one block's (distinct, counts):
        keep the last value of every ``floor(eps/2 · block_n)``-wide rank
        band (merged mass rides as that tuple's g; its own rank stays
        exact).  Bounds per-block insert work at ~2/eps tuples regardless
        of block cardinality — this is the mergeable-sketch step."""
        tot = int(dc.sum())
        band_w = max(1, int(np.floor(0.5 * self.eps * tot)))
        cum = np.cumsum(dc)
        band = (cum - 1) // band_w
        keep = np.r_[band[:-1] != band[1:], True]
        kv = dv[keep]
        kc = np.diff(np.r_[0, cum[keep]])
        return kv, kc.astype(np.int64)

    # -- finalize ----------------------------------------------------------
    def bounds(self, budget: int, min_data_in_bin: int) -> np.ndarray:
        if self.n == 0:
            return np.zeros(0)
        if self.mode == "exact":
            return numeric_bin_bounds(budget, min_data_in_bin,
                                      vals=np.concatenate(self.buffer))
        if self.mode == "distinct":
            return numeric_bin_bounds(budget, min_data_in_bin,
                                      distinct=self.distinct,
                                      counts=self.counts)
        # GK: the vocabulary is unbounded, so the few-distinct "mids" path
        # cannot apply — quantile bounds straight from the summary, rank-
        # accurate to eps·n (the documented streaming ε)
        budget_eff = budget
        if min_data_in_bin > 1:
            budget_eff = max(1, min(budget, self.n // min_data_in_bin))
        qs = np.linspace(0.0, 1.0, budget_eff + 1)[1:-1]
        ub = np.unique(self.gk.query(qs))
        if len(ub) > 1:
            ub = ub[np.concatenate(([True], np.diff(ub) > 0))]
        return np.asarray(ub, np.float64)


class StreamingBinMapperBuilder:
    """One-pass BinMapper construction from row blocks.

    >>> b = StreamingBinMapperBuilder(num_features=F)
    >>> for X_block in stream:
    ...     b.update(X_block)
    >>> mapper = b.finalize(max_bin=255, min_data_in_bin=3)

    Exactness contract (tests/test_sketch.py): bit-identical to
    ``BinMapper.fit(X_full)`` when total rows <= ``min(capacity,
    200_000)``; bit-identical at ANY n for bounded-vocabulary columns
    (vs the unsampled fit); otherwise bin edges are quantiles with rank
    error <= ``eps``·n.
    """

    def __init__(self, num_features: int, capacity: int = _DEFAULT_CAPACITY,
                 eps: float = 1e-3, max_distinct: int = _DEFAULT_MAX_DISTINCT):
        if num_features <= 0:
            raise ValueError(f"num_features must be positive, got "
                             f"{num_features}")
        if not (0.0 < eps < 0.5):
            raise ValueError(f"eps must be in (0, 0.5), got {eps}")
        self.num_features = int(num_features)
        self.num_rows = 0
        self._sketches = [_FeatureSketch(capacity, eps, max_distinct)
                          for _ in range(self.num_features)]

    def update(self, X_block: np.ndarray) -> "StreamingBinMapperBuilder":
        X_block = np.asarray(X_block)
        if X_block.ndim == 1:
            X_block = X_block[:, None]
        if X_block.ndim != 2:
            raise ValueError(
                f"blocks must be 2-D [rows, F], got shape {X_block.shape}")
        if X_block.shape[1] != self.num_features:
            raise ValueError(
                f"ragged feature counts across blocks: expected "
                f"{self.num_features} features, got {X_block.shape[1]}")
        X_block = np.asarray(X_block, np.float64)
        for f in range(self.num_features):
            self._sketches[f].update(X_block[:, f])
        self.num_rows += X_block.shape[0]
        return self

    def finalize(self, max_bin: int = 255,
                 min_data_in_bin: int = 3) -> BinMapper:
        if self.num_rows == 0:
            raise ValueError("finalize() before any update() — the sketch "
                             "has seen no rows")
        bounds: List[np.ndarray] = []
        nan_bin = np.full(self.num_features, -1, dtype=np.int32)
        n_bins = np.ones(self.num_features, dtype=np.int32)
        for f, sk in enumerate(self._sketches):
            has_nan = sk.nan_count > 0
            budget = max_bin - (1 if has_nan else 0)
            ub = sk.bounds(budget, min_data_in_bin)
            nb = len(ub) + 1
            if has_nan:
                nan_bin[f] = nb
                nb += 1
            bounds.append(ub)
            n_bins[f] = nb
        return BinMapper(bounds, nan_bin, n_bins,
                         np.zeros(self.num_features, dtype=bool))


def schema_digest(mapper: BinMapper) -> str:
    """Stable fingerprint of a binning schema (checkpoint compatibility).

    A saved forest's ``split_bin`` thresholds and ``split_feature``
    indices only mean anything under the exact binning they were trained
    with — the SAME invariant :meth:`Booster.ingest_init_model` enforces
    structurally.  Checkpoints store this digest instead of the full
    mapper: resume recomputes it from the offered Dataset and a mismatch
    is an *incompatible schema*, not corruption.  Covers the per-feature
    bound arrays bit-for-bit, the nan-bin layout, categorical flags, and
    the EFB bundling (which remaps the training column space without
    touching ``upper_bounds``).
    """
    import hashlib

    h = hashlib.sha256()
    h.update(np.int64(mapper.num_features).tobytes())
    for ub in mapper.upper_bounds:
        h.update(np.int64(len(ub)).tobytes())
        h.update(np.ascontiguousarray(ub, np.float64).tobytes())
    h.update(np.ascontiguousarray(mapper.nan_bin, np.int32).tobytes())
    h.update(np.ascontiguousarray(mapper.n_bins, np.int32).tobytes())
    h.update(np.ascontiguousarray(mapper.is_categorical, bool).tobytes())
    b = getattr(mapper, "bundler", None)
    if b is not None:
        h.update(repr(b.groups).encode())
        h.update(np.ascontiguousarray(b.default_bins).tobytes())
    return h.hexdigest()

"""Host-resident binned row blocks + async host->HBM prefetch.

The out-of-core regime (ISSUE 7): the ``[n, F]`` binned code matrix no
longer lives in HBM — it lives here, as packed uint8/uint16 host blocks,
and the training loop walks them through a DOUBLE-BUFFERED
``jax.device_put`` pipeline: block ``k+1``'s transfer is issued before
block ``k``'s histogram pass is consumed, so (dispatch being async) the
PCIe copy overlaps the compute and the accumulation loop never waits on
the wire (``analysis.budgets.stream_prefetch_time`` budgets this overlap
at the reference shape).

Block layout rules — these are load-bearing for BIT-IDENTITY with the
in-memory grower (tests/test_streaming.py), because f32 accumulation is
non-associative and the streamed per-block partial sums must replicate
the in-memory ``_hist_from_segstats`` chunking exactly:

* ``block_rows`` must be a multiple of ``ROW_PAD_MULTIPLE`` (256) and is
  pinned to the histogram op's ``row_chunk`` by the streamed round;
* single-block stores (``ceil256(n) <= block_rows``) keep the block at
  ``ceil256(n)`` rows — matching the in-memory single-chunk dot's
  contraction length, with NO zero-init accumulate;
* multi-block stores pad the tail block to EXACTLY ``block_rows`` —
  matching the in-memory scan's zero-padded chunks — and the consumer
  accumulates ``acc = zeros; acc += h_k`` for every block in order,
  matching the scan's zero-init.
"""

from __future__ import annotations

import time
import zlib
from typing import Iterator, List, Tuple

import numpy as np

from ..dataset import ROW_PAD_MULTIPLE


class OOCBlockError(RuntimeError):
    """A block-store read failed — always carries WHICH block.

    ``kind`` classifies the quarantine reason:

    * ``"corrupt"`` — the block's bytes no longer match the checksum
      recorded at construction (host memory / file corruption);
    * ``"short"`` — the block's shape mutated away from the layout
      rules (rows/features no longer what the store was built with);
    * ``"read"`` — a transient read or transfer error persisted past
      the bounded retry.

    Bare upstream exceptions (an injected :class:`FaultError`, a jax
    transfer error) are chained as ``__cause__`` so the block index is
    never lost on the way up (ISSUE r13 satellite).
    """

    def __init__(self, message: str, block: int, kind: str = "read",
                 attempts: int = 1):
        super().__init__(message)
        self.block = int(block)
        self.kind = kind
        self.attempts = int(attempts)


def _check_block_rows(block_rows: int) -> int:
    block_rows = int(block_rows)
    if block_rows <= 0 or block_rows % ROW_PAD_MULTIPLE:
        raise ValueError(
            f"block_rows={block_rows} must be a positive multiple of "
            f"{ROW_PAD_MULTIPLE}")
    return block_rows


class BlockStore:
    """Immutable host store of binned row blocks (see module docstring)."""

    def __init__(self, blocks: List[np.ndarray], num_rows: int,
                 block_rows: int):
        if not blocks:
            raise ValueError("BlockStore needs at least one block")
        self.blocks = blocks
        self.num_rows = int(num_rows)
        self.block_rows = _check_block_rows(block_rows)
        self.bytes_streamed = 0    # PCIe byte odometer (bench/budget hooks)
        self.prefetch_blocks = 1   # host->HBM lookahead depth (r19:
        #   ``stream_prefetch_blocks`` — how many blocks ahead of the
        #   consumer the device_put pipeline runs; budgeted by
        #   ``analysis.budgets.stream_prefetch_time``)
        if len(blocks) > 1:
            for k, b in enumerate(blocks):
                if b.shape[0] != self.block_rows:
                    raise ValueError(
                        f"multi-block store: block {k} has {b.shape[0]} "
                        f"rows, expected exactly block_rows="
                        f"{self.block_rows}")
        # -- r13 hardening state ------------------------------------------
        # blocks are trusted AT CONSTRUCTION (the writer just built them);
        # the per-read verify catches anything that mutates them afterwards
        # (host memory corruption, a bad mmap page, a buggy mutation).
        self.checksums = [zlib.crc32(np.ascontiguousarray(b).data)
                          for b in blocks]
        self._shapes = [b.shape for b in blocks]
        self.verify_checksums = True
        self.fault_injector = None     # lightgbm_tpu.faults.FaultInjector
        self.max_read_retries = 3      # transient-read attempts per block
        self.retry_backoff_s = 0.05    # base of the exponential backoff
        self._sleep = time.sleep       # injectable (tests pin to no-op)
        self.read_retries = 0          # absorbed-transient odometer
        self.quarantined: set = set()  # block indices that failed verify
        self.device = None             # pinned target device (streamed-dp:
        #   each per-shard store transfers onto its OWN mesh device so D
        #   PCIe pipelines run concurrently; None = default device)

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    @property
    def num_features(self) -> int:
        return int(self.blocks[0].shape[1])

    @property
    def padded_rows(self) -> int:
        """Total padded row extent (the streamed analogue of n_pad)."""
        return int(sum(b.shape[0] for b in self.blocks))

    @property
    def nbytes(self) -> int:
        return int(sum(b.nbytes for b in self.blocks))

    @property
    def dtype(self):
        return self.blocks[0].dtype

    def _verify_block(self, k: int) -> np.ndarray:
        """Integrity screen for block ``k`` (shape then checksum); a
        failure quarantines the block — no retry can help, the bytes are
        gone — and raises the typed error immediately."""
        b = self.blocks[k]
        if b.shape != self._shapes[k]:
            self.quarantined.add(k)
            raise OOCBlockError(
                f"block {k} is short/misshapen: {b.shape} vs the "
                f"{self._shapes[k]} it was built with", block=k,
                kind="short")
        if self.verify_checksums and \
                zlib.crc32(np.ascontiguousarray(b).data) \
                != self.checksums[k]:
            self.quarantined.add(k)
            raise OOCBlockError(
                f"block {k} failed its checksum (host-side corruption "
                "after construction)", block=k, kind="corrupt")
        return b

    def _fetch_device(self, k: int, col_ids=None):
        """Read + transfer block ``k`` with the bounded retry: transient
        errors (injected ``block_read``/``device_put`` faults, runtime
        transfer hiccups) back off exponentially and retry up to
        ``max_read_retries`` times; integrity failures never retry.

        ``col_ids`` (r20 feature screening) slices the block to the
        active columns on the HOST, after the integrity verify (the
        checksum covers the full block as written) and before the
        device_put — so only ``F_active`` columns ever cross PCIe."""
        import jax

        from ..faults import FaultError

        last = None
        for attempt in range(self.max_read_retries + 1):
            if attempt:
                self.read_retries += 1
                self._sleep(self.retry_backoff_s * (2 ** (attempt - 1)))
            try:
                if self.fault_injector is not None:
                    self.fault_injector.check("block_read")
                b = self._verify_block(k)
                if col_ids is not None:
                    b = np.ascontiguousarray(b[:, col_ids])
                if self.fault_injector is not None:
                    self.fault_injector.check("device_put")
                return (jax.device_put(b) if self.device is None
                        else jax.device_put(b, self.device))
            except OOCBlockError:
                raise                      # quarantined: not transient
            except (FaultError, RuntimeError, OSError) as e:
                last = e
        raise OOCBlockError(
            f"block {k} read failed after "
            f"{self.max_read_retries + 1} attempts: {last}", block=k,
            kind="read",
            attempts=self.max_read_retries + 1) from last

    def device_blocks(self, prefetch_blocks: int = None, col_ids=None
                      ) -> Iterator[Tuple[int, "object"]]:
        """Yield ``(row_offset, device_block)`` with ``prefetch_blocks``
        lookahead: blocks k+1..k+P have their ``jax.device_put`` issued
        BEFORE block k is handed to the consumer, so their host->HBM
        copies run while the consumer's histogram kernel chews on block k
        (async dispatch).  Depth defaults to the store's configured
        ``prefetch_blocks`` (the ``stream_prefetch_blocks`` param); depth
        1 is the classic double buffer.  ``col_ids`` streams only the
        active columns (r20 screening) — the odometer counts the SLICED
        bytes, since that is what actually crossed PCIe."""
        depth = self.prefetch_blocks if prefetch_blocks is None \
            else int(prefetch_blocks)
        if depth < 1:
            raise ValueError(
                f"prefetch_blocks={depth} must be >= 1 (1 = double "
                "buffer)")
        from collections import deque

        window: deque = deque()
        n = len(self.blocks)
        for k in range(min(depth, n)):
            window.append(self._fetch_device(k, col_ids))
        for k in range(n):
            cur = window.popleft()
            if k + depth < n:
                window.append(self._fetch_device(k + depth, col_ids))
            blk = self.blocks[k]
            self.bytes_streamed += (
                blk.nbytes if col_ids is None
                else blk.shape[0] * len(col_ids) * blk.itemsize)
            yield k * self.block_rows, cur

    def gather_rows(self, idx: np.ndarray, col_ids=None) -> np.ndarray:
        """Host-side row gather (GOSS-at-the-source: only the sampled rows
        cross PCIe, so transferred bytes shrink with the sampling rate;
        ``col_ids`` additionally restricts the gather to the active
        columns — the r20 hot-feature prior compounding on top)."""
        idx = np.asarray(idx, np.int64)
        n_cols = self.num_features if col_ids is None else len(col_ids)
        out = np.empty((len(idx), n_cols), self.dtype)
        b = idx // self.block_rows
        r = idx - b * self.block_rows
        for k in range(len(self.blocks)):
            m = b == k
            if m.any():
                rows = self.blocks[k][r[m]]
                out[m] = rows if col_ids is None else rows[:, col_ids]
        return out

    @staticmethod
    def from_binned(codes: np.ndarray, block_rows: int) -> "BlockStore":
        """Chunk an already-binned [n, F] code matrix per the layout rules
        (tests and the GOSS full-matrix fallback)."""
        w = BlockStore.writer(block_rows)
        w.append(np.asarray(codes))
        return w.finish()

    @staticmethod
    def writer(block_rows: int) -> "_BlockWriter":
        return _BlockWriter(block_rows)


def shard_block_store(store: BlockStore, n_shards: int
                      ) -> List[BlockStore]:
    """Split a multi-block store into ``n_shards`` per-shard stores over
    CONTIGUOUS block ranges (the streamed × dp composition, ISSUE r19).

    Each shard is a real :class:`BlockStore` — same blocks by reference
    (no copy), its own ``bytes_streamed`` PCIe odometer, the parent's
    fault-injection / verify config — so shard ``s`` can run the full
    prefetch pipeline against its own device while shard ``s+1`` does the
    same.  Contiguity keeps the global row order ``shard-major``, which
    is exactly ``shard_rows``'s layout for the resident vectors: row
    ``i`` of shard ``s`` is global row ``s*rows_per_shard + i``.

    Requires ``num_blocks % n_shards == 0`` (the Booster picks the
    device count as a divisor of the block count, so per-shard block
    walks stay in lockstep and every block-round is a full-mesh
    collective).
    """
    n_shards = int(n_shards)
    if n_shards < 1:
        raise ValueError(f"n_shards={n_shards} must be >= 1")
    if store.num_blocks % n_shards:
        raise ValueError(
            f"cannot shard {store.num_blocks} blocks across "
            f"{n_shards} devices: block count must be divisible so "
            "per-shard block walks stay in lockstep")
    per = store.num_blocks // n_shards
    rows_per_shard = per * store.block_rows
    shards: List[BlockStore] = []
    for s in range(n_shards):
        lo = s * rows_per_shard
        real = max(0, min(store.num_rows - lo, rows_per_shard))
        sh = BlockStore(store.blocks[s * per:(s + 1) * per],
                        max(real, 1), store.block_rows)
        sh.num_rows = real          # may be 0 for all-padding tail shards
        sh.verify_checksums = store.verify_checksums
        sh.fault_injector = store.fault_injector
        sh.max_read_retries = store.max_read_retries
        sh.retry_backoff_s = store.retry_backoff_s
        sh._sleep = store._sleep
        sh.prefetch_blocks = store.prefetch_blocks
        shards.append(sh)
    return shards


class ColumnViewStore:
    """A column-restricted VIEW of a BlockStore (r20 feature screening).

    Wraps a store and a sorted global column-id vector; ``device_blocks``
    and ``gather_rows`` yield ``[rows, F_active]`` slices (sliced on the
    host, BEFORE device_put — the PCIe saving is real, not cosmetic),
    while every other attribute — retry config, fault injector, device
    pin, quarantine set, the ``bytes_streamed`` odometer — delegates to
    the parent, so a view composes transparently with the streamed
    round functions, ``shard_block_store`` shards, and
    ``drain_shard_odometers`` (which must keep draining the REAL
    shards).  Trees grown against a view live in compacted feature
    space; the caller remaps winners to global ids
    (``models.feature_mask.remap_split_features``).
    """

    def __init__(self, store, col_ids):
        object.__setattr__(self, "_store", store)
        object.__setattr__(
            self, "col_ids", np.asarray(col_ids, np.int64))
        if self.col_ids.ndim != 1 or len(self.col_ids) == 0:
            raise ValueError("col_ids must be a non-empty 1-D id vector")
        if self.col_ids.min() < 0 or \
                self.col_ids.max() >= store.num_features:
            raise ValueError(
                f"col_ids out of range for a {store.num_features}-feature "
                "store")

    def __getattr__(self, name):
        # anything not overridden (block_rows, num_blocks, padded_rows,
        # num_rows, dtype, prefetch_blocks, device, quarantined, ...)
        # reads through to the parent store
        return getattr(self._store, name)

    def __setattr__(self, name, value):
        # writes (the GOSS rounds' ``bytes_streamed +=``, test knobs)
        # also go to the parent — the view carries NO state of its own
        setattr(self._store, name, value)

    @property
    def num_features(self) -> int:
        return int(len(self.col_ids))

    def device_blocks(self, prefetch_blocks: int = None):
        return self._store.device_blocks(prefetch_blocks,
                                         col_ids=self.col_ids)

    def gather_rows(self, idx: np.ndarray) -> np.ndarray:
        return self._store.gather_rows(idx, col_ids=self.col_ids)


class _BlockWriter:
    """Incremental BlockStore builder: appends arbitrary-length code
    chunks, emits fixed ``block_rows`` blocks, applies the single-block /
    padded-tail finalize rules."""

    def __init__(self, block_rows: int):
        self.block_rows = _check_block_rows(block_rows)
        self._blocks: List[np.ndarray] = []
        self._carry: List[np.ndarray] = []
        self._carry_rows = 0
        self._num_rows = 0
        self._dtype = None
        self._num_features = None

    def append(self, codes: np.ndarray) -> "_BlockWriter":
        codes = np.asarray(codes)
        if codes.ndim != 2:
            raise ValueError(f"code chunks must be 2-D, got {codes.shape}")
        if self._dtype is None:
            self._dtype = codes.dtype
            self._num_features = int(codes.shape[1])
        elif codes.dtype != self._dtype:
            raise ValueError(
                f"code dtype {codes.dtype} != first chunk's {self._dtype}")
        elif int(codes.shape[1]) != self._num_features:
            raise ValueError(
                f"ragged feature counts: {codes.shape[1]} vs "
                f"{self._num_features}")
        self._num_rows += int(codes.shape[0])
        self._carry.append(codes)
        self._carry_rows += int(codes.shape[0])
        while self._carry_rows >= self.block_rows:
            buf = np.concatenate(self._carry, axis=0)
            self._blocks.append(np.ascontiguousarray(buf[:self.block_rows]))
            rest = buf[self.block_rows:]
            self._carry = [rest] if rest.shape[0] else []
            self._carry_rows = int(rest.shape[0])
        return self

    def finish(self) -> BlockStore:
        if self._num_rows == 0:
            raise ValueError("no rows appended")
        n = self._num_rows
        n_pad = -(-n // ROW_PAD_MULTIPLE) * ROW_PAD_MULTIPLE
        carry = (np.concatenate(self._carry, axis=0) if self._carry
                 else np.zeros((0, self._num_features), self._dtype))
        if not self._blocks:
            # single block: pad to ceil256(n) ONLY (no zero-init add on the
            # consumer side — mirrors the in-memory single-chunk dot)
            blk = np.zeros((n_pad, self._num_features), self._dtype)
            blk[:carry.shape[0]] = carry
            blocks = [np.ascontiguousarray(blk)]
        else:
            blocks = self._blocks
            if carry.shape[0]:
                tail = np.zeros((self.block_rows, self._num_features),
                                self._dtype)
                tail[:carry.shape[0]] = carry
                blocks = blocks + [np.ascontiguousarray(tail)]
        return BlockStore(blocks, n, self.block_rows)

"""Streamed × data-parallel training: per-shard BlockStores on the dp
mesh with per-block-round pipelined merges (ISSUE r19 tentpole).

Composition of the two scale axes that previously only worked alone:

* **r11 out-of-core**: the [n, F] code matrix lives in host blocks and
  every histogram pass is a host loop over prefetched ``device_put``
  transfers;
* **r9/r10 multi-chip**: rows shard over a 1-D ``Mesh(('data',))`` and
  per-shard histogram partials merge through
  ``ops.histogram.histogram_merge`` (psum / reduce-scatter ring /
  pipelined sub-chunk ring with optional bf16/int8 wire).

Here the parent :class:`~.block_store.BlockStore` splits into D
per-shard stores over contiguous block ranges
(:func:`~.block_store.shard_block_store`) — shard ``s`` streams ONLY its
own row range onto its own device, so D PCIe pipelines run concurrently
and per-device ingest bytes drop by D.  Each **block-round** is one
``shard_map``-ed program: every device runs the UNCHANGED serial
per-block kernel (``models.tree._stream_*_block_fn``) on its local
block, then the r10 merge runs **per block-round**, so the inter-chip
transfer of block ``j``'s partial flies while block ``j+1``'s PCIe
prefetch and histogram compute proceed (``analysis.budgets.
stream_dp_time_model`` budgets this overlap at the reference shape).

Under the reduce-scatter modes the merged partial stays FEATURE-SHARDED
across block-rounds — each shard accumulates only its F/D slice — and
the full histogram is gathered ONCE per split iteration when the
replicated update consumes it, so per-iteration ICI bytes are
``K·(D-1)/D·H`` (ring, wire-compressible) plus one ``(D-1)/D·H`` gather
instead of ``K·2(D-1)/D·H`` for per-block psums.

GOSS-at-the-source multiplies with the int8 wire format: each shard
samples its OWN rows on host (top-|g| + seeded uniform rest, upstream's
per-machine sampling) so PCIe bytes shrink by the sampling rate, while
the compacted shards' histograms merge over int8 ring hops so ICI bytes
shrink 4× — multiplicative, modeled in ``STREAM_DP_BUDGETS`` and
measured in tools/bench_stream_dp.py.

Parity contract (PARITY.md): with f32 wire the grown trees match
in-memory single-chip training on the established dp bar — split
structure and row partitions ``np.array_equal``, leaf values / preds to
f32 rounding — and are FULLY bit-identical where every histogram sum is
exact (single-round dyadic data pins this in tests/test_stream_dp.py).
int8/bf16 wire is tolerance-gated, never bit-claimed.

Feature screening (r20) stacks on BOTH byte reductions orthogonally:
on screened rounds the Booster wraps each per-shard store in a
:class:`~.block_store.ColumnViewStore` before handing it to the round
drivers below, so PCIe ingest shrinks by ``F / F_active`` per shard
(on top of GOSS's row sampling) and every per-block-round merge moves
``F_active``-width histograms over the ring (on top of the wire
dtype).  The drivers themselves are screening-blind — the view store
and the compacted kernel shapes carry the whole change.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.tree import (
    _stream_root_block_fn,
    _stream_strict_block_fn,
    _stream_wave_block_fn,
    _stream_wave_fns,
    _tree_from_packed,
    decode_wave_width,
    stream_exact_prune,
    stream_strict_init,
    stream_strict_update,
    stream_wave_init,
)
from ..ops.histogram import histogram_merge
from ..parallel.data_parallel import DATA_AXIS, shard_rows
from ..utils.compat import shard_map
from .stream_grow import _grad_stats_fn, _pred_update_fn

_RS_MODES = ("reduce_scatter", "reduce_scatter_ring",
             "reduce_scatter_pipelined")


def choose_stream_dp_devices(num_blocks: int, n_devices: int) -> int:
    """Largest device count <= ``n_devices`` dividing ``num_blocks``.

    Divisibility keeps the per-shard block walks in lockstep (every
    block-round is a full-mesh collective) and — because every block in
    a multi-block store is exactly ``block_rows`` — automatically makes
    the padded row extent shard-divisible too.
    """
    d = max(int(n_devices), 1)
    while d > 1 and num_blocks % d:
        d -= 1
    return d


def setup_stream_shards(store, mesh):
    """Shard ``store`` across ``mesh`` and pin each shard's transfers to
    its own device -> list of per-shard BlockStores (with independent
    ``bytes_streamed`` PCIe odometers, surfaced by the bench)."""
    from .block_store import shard_block_store

    devices = list(mesh.devices.flat)
    shards = shard_block_store(store, len(devices))
    for sh, dev in zip(shards, devices):
        sh.device = dev
    return shards


def drain_shard_odometers(store, shards) -> None:
    """Fold the per-shard PCIe odometers into the parent store's global
    ``bytes_streamed`` (keeping the r11 global odometer contract) while
    leaving per-shard counters intact for the per-device byte model."""
    store.bytes_streamed = sum(sh.bytes_streamed for sh in shards)


def dp_block_rounds(shards, mesh):
    """Yield ``(local_offset, bins_global)`` per block-round.

    Every shard's generator advances in lockstep: round ``j`` assembles
    shard ``s``'s local block ``j`` (already on device ``s`` via the
    per-shard prefetch pipeline) into ONE row-sharded global array —
    zero-copy, ``jax.make_array_from_single_device_arrays`` — whose
    local offset ``j * block_rows`` is the SAME replicated scalar on
    every shard, so the serial per-block kernels run verbatim on local
    slices.
    """
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    n_shards = len(shards)
    block_rows = shards[0].block_rows
    num_features = shards[0].num_features
    gens = [sh.device_blocks() for sh in shards]
    for rounds in zip(*gens):
        blks = [r[1] for r in rounds]
        bins_g = jax.make_array_from_single_device_arrays(
            (n_shards * block_rows, num_features), sharding, blks)
        yield rounds[0][0], bins_g


def _hist_out_spec(merge_mode: str):
    # reduce-scatter modes leave the merged histogram FEATURE-sharded
    # ([S, F_pad/D, B, 3] per shard -> global [S, F_pad, B, 3]); psum
    # replicates it
    return P(None, DATA_AXIS) if merge_mode in _RS_MODES else P()


@functools.lru_cache(maxsize=None)
def _dp_root_block_step(mesh, num_bins: int, block_rows: int,
                        hist_impl: str, hist_dtype: str, merge_mode: str,
                        wire_dtype: str, merge_chunks: int):
    """One root block-round: the serial root block kernel on each local
    block + the per-block-round mesh merge."""
    n_shards = int(mesh.shape[DATA_AXIS])
    blk = _stream_root_block_fn(num_bins, block_rows, hist_impl,
                                hist_dtype)

    def body(bins_b, stats, off):
        h = blk(bins_b, stats, off)
        return histogram_merge(h, DATA_AXIS, merge_mode, n_shards,
                               wire_dtype, merge_chunks)

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=_hist_out_spec(merge_mode),
        check_vma=False))


@functools.lru_cache(maxsize=None)
def _dp_strict_block_step(mesh, num_bins: int, block_rows: int,
                          hist_impl: str, hist_dtype: str,
                          merge_mode: str, wire_dtype: str,
                          merge_chunks: int):
    """One strict split-iteration block-round: local partition +
    {left, right, other} histogram partial (the serial kernel verbatim),
    then the r10 merge — per block-round, so the ring hops of block
    ``j`` overlap block ``j+1``'s prefetch + compute."""
    n_shards = int(mesh.shape[DATA_AXIS])
    blk = _stream_strict_block_fn(num_bins, block_rows, hist_impl,
                                  hist_dtype)

    def body(bins_b, stats, row_leaf, off, aux, n_nodes):
        rl2, h = blk(bins_b, stats, row_leaf, off, aux, n_nodes)
        hm = histogram_merge(h, DATA_AXIS, merge_mode, n_shards,
                             wire_dtype, merge_chunks)
        return rl2, hm

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(), P(),
                  P()),
        out_specs=(P(DATA_AXIS), _hist_out_spec(merge_mode)),
        check_vma=False))


@functools.lru_cache(maxsize=None)
def _dp_wave_block_step(mesh, w_width: int, num_bins: int,
                        num_features: int, block_rows: int,
                        hist_impl: str, hist_dtype: str, merge_mode: str,
                        wire_dtype: str, merge_chunks: int):
    """One wave block-round: table-lookup routing + W-segment histogram
    partial on each local block, then the per-block-round merge."""
    n_shards = int(mesh.shape[DATA_AXIS])
    blk = _stream_wave_block_fn(w_width, num_bins, num_features,
                                block_rows, hist_impl, hist_dtype)

    def body(bins_b, stats, row_leaf, off, tbl, n_nodes):
        rl2, h = blk(bins_b, stats, row_leaf, off, tbl, n_nodes)
        hm = histogram_merge(h, DATA_AXIS, merge_mode, n_shards,
                             wire_dtype, merge_chunks)
        return rl2, hm

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(), P(),
                  P()),
        out_specs=(P(DATA_AXIS), _hist_out_spec(merge_mode)),
        check_vma=False))


@functools.lru_cache(maxsize=None)
def _dp_strict_update_fn(num_features: int):
    """Replicated strict table update consuming the accumulated merged
    histogram.  Under the reduce-scatter modes the accumulator is
    feature-sharded with zero padding — THIS is the once-per-iteration
    gather: slicing back to F makes jit insert one all-gather, the only
    full-histogram transfer per split iteration."""

    @jax.jit
    def fn(acc, Ptbl, aux, feature_mask, ctx, max_depth, n_nodes,
           n_leaves):
        hist = acc[:, :num_features]
        return stream_strict_update(hist, Ptbl, aux, feature_mask, ctx,
                                    max_depth, n_nodes, n_leaves)

    return fn


@functools.lru_cache(maxsize=None)
def _dp_wave_update_fn(capacity: int, w_width: int, grow_leaves: int,
                       num_features: int, num_bins: int, wave_tail: str):
    """Replicated wave update over the accumulated merged histogram
    (same once-per-wave gather note as :func:`_dp_strict_update_fn`)."""
    _, update, _ = _stream_wave_fns(capacity, w_width, grow_leaves,
                                    num_features, num_bins, wave_tail)

    @jax.jit
    def fn(Ptbl, cache, node_slot, n_nodes, n_leaves, acc, feature_mask,
           ctx, max_depth):
        return update(Ptbl, cache, node_slot, n_nodes, n_leaves,
                      acc[:, :num_features], feature_mask, ctx,
                      max_depth)

    return fn


def _accumulate(acc, h, multi: bool):
    """The serial streamed accumulator contract, verbatim: zero-init +
    ordered adds for multi-block, direct handoff for a single local
    block (0 + h is exact in f32, so the merged values are unchanged)."""
    if acc is None:
        return (jnp.zeros_like(h) + h) if multi else h
    return acc + h


def stream_dp_grow_tree(shards, mesh, stats, feature_mask, ctx,
                        num_leaves: int, num_bins: int, max_depth,
                        wave_width: int, hist_impl: str, hist_dtype: str,
                        merge_mode: str, wire_dtype: str,
                        merge_chunks: int):
    """Grow one tree streamed across the dp mesh; returns
    ``(tree [replicated], row_leaf [row-sharded])``."""
    width, tail, overgrow = decode_wave_width(wave_width)
    args = (shards, mesh, stats, feature_mask, ctx, num_leaves, num_bins,
            max_depth, hist_impl, hist_dtype, merge_mode, wire_dtype,
            merge_chunks)
    if width <= 1:
        return _grow_strict_dp(*args)
    return _grow_wave_dp(*args[:5], num_leaves, num_bins, max_depth,
                         width, tail, overgrow, hist_impl, hist_dtype,
                         merge_mode, wire_dtype, merge_chunks)


def _dp_root_hist(shards, mesh, stats, num_bins, hist_impl, hist_dtype,
                  merge_mode, wire_dtype, merge_chunks):
    block_rows = shards[0].block_rows
    step = _dp_root_block_step(mesh, num_bins, block_rows, hist_impl,
                               hist_dtype, merge_mode, wire_dtype,
                               merge_chunks)
    multi = shards[0].num_blocks > 1
    acc = None
    for off, bins_g in dp_block_rounds(shards, mesh):
        h = step(bins_g, stats, jnp.int32(off))
        acc = _accumulate(acc, h, multi)
    return acc


def _sharded_zeros_i32(mesh, n: int):
    return jax.device_put(jnp.zeros(n, jnp.int32),
                          NamedSharding(mesh, P(DATA_AXIS)))


def _grow_strict_dp(shards, mesh, stats, feature_mask, ctx, num_leaves,
                    num_bins, max_depth, hist_impl, hist_dtype,
                    merge_mode, wire_dtype, merge_chunks):
    capacity = 2 * num_leaves - 1
    num_features = shards[0].num_features
    block_rows = shards[0].block_rows
    acc = _dp_root_hist(shards, mesh, stats, num_bins, hist_impl,
                        hist_dtype, merge_mode, wire_dtype, merge_chunks)
    Ptbl, aux = stream_strict_init(acc[0, :num_features], ctx,
                                   feature_mask, capacity)
    padded = sum(sh.padded_rows for sh in shards)
    row_leaf = _sharded_zeros_i32(mesh, padded)
    n_nodes = jnp.int32(1)
    n_leaves = jnp.int32(1)
    step = _dp_strict_block_step(mesh, num_bins, block_rows, hist_impl,
                                 hist_dtype, merge_mode, wire_dtype,
                                 merge_chunks)
    upd = _dp_strict_update_fn(num_features)
    multi = shards[0].num_blocks > 1
    for _ in range(num_leaves - 1):
        acc = None
        for off, bins_g in dp_block_rounds(shards, mesh):
            row_leaf, h = step(bins_g, stats, row_leaf, jnp.int32(off),
                               aux, n_nodes)
            acc = _accumulate(acc, h, multi)
        Ptbl, aux, n_nodes, n_leaves = upd(acc, Ptbl, aux, feature_mask,
                                           ctx, max_depth, n_nodes,
                                           n_leaves)
    return _tree_from_packed(Ptbl, n_leaves, None, None), row_leaf


def _grow_wave_dp(shards, mesh, stats, feature_mask, ctx, num_leaves,
                  num_bins, max_depth, width, tail, overgrow, hist_impl,
                  hist_dtype, merge_mode, wire_dtype, merge_chunks):
    exact = tail == "exact"
    grow_leaves = (max(num_leaves + 1, int(overgrow or 0)) if exact
                   else num_leaves)
    capacity = 2 * grow_leaves - 1
    w_width = min(int(width), grow_leaves - 1)
    num_features = shards[0].num_features
    block_rows = shards[0].block_rows
    acc = _dp_root_hist(shards, mesh, stats, num_bins, hist_impl,
                        hist_dtype, merge_mode, wire_dtype, merge_chunks)
    Ptbl, cache, node_slot = stream_wave_init(
        acc[0, :num_features], ctx, feature_mask, capacity, grow_leaves)
    padded = sum(sh.padded_rows for sh in shards)
    row_leaf = _sharded_zeros_i32(mesh, padded)
    n_nodes = jnp.int32(1)
    n_leaves = jnp.int32(1)
    plan, _, cond = _stream_wave_fns(capacity, w_width, grow_leaves,
                                     num_features, num_bins, tail)
    upd = _dp_wave_update_fn(capacity, w_width, grow_leaves,
                             num_features, num_bins, tail)
    step = _dp_wave_block_step(mesh, w_width, num_bins, num_features,
                               block_rows, hist_impl, hist_dtype,
                               merge_mode, wire_dtype, merge_chunks)
    multi = shards[0].num_blocks > 1
    # host sync once per wave, same GL002-baselined predicate as the
    # serial streamed driver (the block loop is a host loop)
    while bool(cond(Ptbl, n_leaves)):
        tbl = plan(Ptbl, n_leaves)
        acc = None
        for off, bins_g in dp_block_rounds(shards, mesh):
            row_leaf, h = step(bins_g, stats, row_leaf, jnp.int32(off),
                               tbl, n_nodes)
            acc = _accumulate(acc, h, multi)
        Ptbl, cache, node_slot, n_nodes, n_leaves = upd(
            Ptbl, cache, node_slot, n_nodes, n_leaves, acc, feature_mask,
            ctx, max_depth)
    if exact:
        newP, row_leaf, n_leaves_f = stream_exact_prune(Ptbl, row_leaf,
                                                        num_leaves)
        return _tree_from_packed(newP, n_leaves_f, None, None), row_leaf
    return _tree_from_packed(Ptbl, n_leaves, None, None), row_leaf


# ---------------------------------------------------------------------------
# Boosting-round drivers (wired from models.gbdt.Booster.update)
# ---------------------------------------------------------------------------


def stream_dp_plain_round(shards, mesh, obj_key: tuple, y, w, bag, pred,
                          fmask, hyper, num_leaves: int, num_bins: int,
                          hist_impl: str, hist_dtype: str,
                          wave_width: int, is_rf: bool, merge_mode: str,
                          wire_dtype: str, merge_chunks: int):
    """One plain gbdt/rf round streamed across the dp mesh — the
    streamed-dp restatement of ``stream_grow.stream_plain_round`` with
    the SAME jitted gradient/update functions (row-sharded residents
    partition elementwise, so per-row arithmetic is unchanged)."""
    _, _, stats = _grad_stats_fn(obj_key)(pred, y, w, bag)
    tree, row_leaf = stream_dp_grow_tree(
        shards, mesh, stats, fmask, hyper.ctx(), num_leaves, num_bins,
        hyper.max_depth, wave_width, hist_impl, hist_dtype, merge_mode,
        wire_dtype, merge_chunks)
    new_pred = _pred_update_fn(is_rf)(pred, hyper.learning_rate,
                                      row_leaf, tree.leaf_value)
    return tree, new_pred


@functools.lru_cache(maxsize=None)
def _dp_goss_pred_block_step(mesh, block_rows: int):
    """Sharded per-block train-score update for the streamed-dp GOSS
    round: each device traverses its own block and FMA-updates its local
    prediction slice (same contraction as the serial streamed pass)."""
    from ..ops.predict import predict_tree_binned

    def body(pred, bins_b, off, lr, tree):
        nb = bins_b.shape[0]
        delta = predict_tree_binned(tree, bins_b, None)
        p_b = lax.dynamic_slice(pred, (off,), (nb,))
        return lax.dynamic_update_slice(pred, p_b + lr * delta, (off,))

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P(), P()),
        out_specs=P(DATA_AXIS),
        check_vma=False))


def stream_dp_goss_round(shards, mesh, obj_key: tuple, y, w, bag, pred,
                         fmask, hyper, key, goss_k_shard,
                         top_rate: float, other_rate: float, seed: int,
                         num_leaves: int, num_bins: int, hist_impl: str,
                         hist_dtype: str, wave_width: int,
                         merge_mode: str, wire_dtype: str,
                         merge_chunks: int):
    """One GOSS round with PER-SHARD host sampling before transfer —
    the GOSS×wire compounding round.

    Each shard samples its OWN row range on host (exact top-|g| + seeded
    uniform rest, upstream's per-machine data-parallel GOSS) and gathers
    only those rows across PCIe — per-shard ingest bytes shrink by the
    sampling rate, counted on each shard's own odometer.  The compacted
    shards then grow one tree through the unchanged in-memory dp step
    (``parallel.data_parallel.make_dp_grow_step``), whose ring merges
    carry the int8/bf16 wire — so PCIe and ICI bytes shrink in the SAME
    round, multiplicatively.  Like serial streamed GOSS, the sampling
    RNG stream deliberately differs from device GOSS: statistically
    equivalent, tolerance-gated, never bit-claimed.
    """
    from ..parallel.data_parallel import make_dp_grow_step

    k_top_s, k_other_s = goss_k_shard
    k_shard = k_top_s + k_other_s
    g, h, _ = _grad_stats_fn(obj_key)(pred, y, w, bag)
    g_abs = np.asarray(jnp.abs(g))          # host sync: sampling source
    bag_h = np.asarray(bag)                 # host sync: validity mask
    g_h = np.asarray(g)
    h_h = np.asarray(h)
    w_h = np.asarray(w)
    n_shards = len(shards)
    rows_ps = g_abs.shape[0] // n_shards
    amp = np.float32((1.0 - top_rate) / max(other_rate, 1e-12))

    bins_parts, stats_parts = [], []
    idx_parts, wt_parts = [], []
    for s, sh in enumerate(shards):
        lo = s * rows_ps
        valid = bag_h[lo:lo + rows_ps] > 0
        score = np.where(valid, g_abs[lo:lo + rows_ps], -1.0)
        k_top_eff = min(k_top_s, int(valid.sum()))
        if k_top_eff > 0:
            top_idx = np.sort(np.argpartition(-score, k_top_eff - 1)
                              [:k_top_eff].astype(np.int64))
        else:
            top_idx = np.empty(0, np.int64)
        is_top = np.zeros(rows_ps, bool)
        is_top[top_idx] = True
        rest_idx = np.flatnonzero(valid & ~is_top)
        rng = np.random.default_rng((int(seed), s))
        k_other_eff = min(k_other_s, len(rest_idx))
        other_idx = np.sort(rng.choice(rest_idx, size=k_other_eff,
                                       replace=False))

        def pad_fill(idx, k):
            out = np.zeros(k, np.int64)
            out[:len(idx)] = idx
            fill = (np.arange(k) < len(idx)).astype(np.float32)
            return out, fill

        top_idx, top_fill = pad_fill(top_idx, k_top_s)
        other_idx, other_fill = pad_fill(other_idx, k_other_s)
        idx_local = np.concatenate([top_idx, other_idx])
        wt_local = np.concatenate([top_fill, other_fill * amp])

        # GOSS-at-the-source, per shard: only this shard's sampled rows
        # cross ITS PCIe lane (per-shard odometer)
        bins_s = sh.gather_rows(idx_local)
        sh.bytes_streamed += bins_s.nbytes
        bins_parts.append(bins_s)
        idx_g = lo + idx_local
        live = ((bag_h[idx_g] > 0) & (wt_local > 0)).astype(np.float32)
        wt_local = wt_local * live
        stats_parts.append(np.stack(
            [g_h[idx_g] * wt_local, h_h[idx_g] * wt_local, live],
            axis=-1).astype(np.float32))
        idx_parts.append(idx_g)
        wt_parts.append(wt_local)

    bins_g = shard_rows(mesh, jnp.asarray(np.concatenate(bins_parts)))
    stats_g = shard_rows(mesh, jnp.asarray(np.concatenate(stats_parts)))
    grow = make_dp_grow_step(
        mesh, num_leaves, num_bins, hist_impl, shards[0].block_rows,
        wave_width, hist_dtype, merge_mode, 0, wire_dtype, merge_chunks)
    tree, _ = grow(bins_g, stats_g, fmask, hyper, key)

    # train-score update: one full streamed sharded traversal pass
    pred_step = _dp_goss_pred_block_step(mesh, shards[0].block_rows)
    lr = jnp.float32(hyper.learning_rate)
    for off, bins_b in dp_block_rounds(shards, mesh):
        pred = pred_step(pred, bins_b, jnp.int32(off), lr, tree)
    del idx_parts, wt_parts, w_h, k_shard
    return tree, pred

"""Host drivers for out-of-core (streamed) tree growth — ISSUE 7.

The in-memory growers are single device programs over a resident [n, F]
matrix.  Here the matrix lives in a :class:`~.block_store.BlockStore` and
every histogram pass becomes a host loop over double-buffered prefetched
blocks: per-block jitted kernels (``models.tree._stream_*_block_fn``) do
the row-axis partition + histogram work, their partials are summed with
the in-memory op's exact chunk semantics, and per-iteration jitted
updates run the unchanged split machinery on the accumulated histogram.
On the plain numeric path the resulting trees are BIT-IDENTICAL to
``grow_tree(..., row_chunk=block_rows)`` (tests/test_streaming.py).

Resident O(n) state: ``stats``/``row_leaf``/``pred``/``y``/``w``/``bag``
vectors stay in device memory — the HBM ceiling this subsystem breaks is
the [n, F] code matrix (F bytes/row vs ~24 bytes/row of vector state).

GOSS-at-the-source: under ``boosting=goss`` rows are sampled ON HOST
(top-|g| + uniform rest) and only the sampled subset is gathered and
shipped, so per-round histogram PCIe bytes shrink to ``(top_rate +
other_rate) * n * F`` plus one full streaming pass for train-score
updates.  The host sampler is a deliberately different RNG stream from
the device GOSS path (exact host top-k vs approx_top_mask), so GOSS
under streaming is statistically equivalent but not bit-identical to
in-memory GOSS — documented in README.

Feature screening (r20) composes here for free: on screened rounds the
Booster hands these drivers a
:class:`~.block_store.ColumnViewStore` — the EMA screener acting as a
hot-feature prior over the column axis, exactly dual to GOSS over the
row axis — so every per-block gather, kernel, and odometer count below
sees the compacted ``F_active`` width with no screened branch in this
module.  Both F in the GOSS byte formula above and the per-block
histograms shrink together.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..models.tree import (
    _stream_root_block_fn,
    _stream_strict_block_fn,
    _stream_wave_block_fn,
    _stream_wave_fns,
    _tree_from_packed,
    decode_wave_width,
    grow_tree,
    renew_leaf_values,
    stream_exact_prune,
    stream_strict_init,
    stream_strict_update,
    stream_wave_init,
)
from ..ops.lookup import lookup_values
from ..ops.predict import predict_tree_binned


def _root_hist(store, stats, num_bins, hist_impl, hist_dtype):
    """Accumulate the [1, F, B, 3] root histogram over streamed blocks,
    replicating the in-memory chunk-scan's zero-init + ordered adds."""
    blk = _stream_root_block_fn(num_bins, store.block_rows, hist_impl,
                                hist_dtype)
    multi = store.num_blocks > 1
    acc = None
    for off, bins_b in store.device_blocks():
        h = blk(bins_b, stats, jnp.int32(off))
        if acc is None:
            acc = (jnp.zeros_like(h) + h) if multi else h
        else:
            acc = acc + h
    return acc[0]                                        # [F, B, 3]


def stream_grow_tree(store, stats, feature_mask, ctx, num_leaves: int,
                     num_bins: int, max_depth, wave_width: int,
                     hist_impl: str = "auto", hist_dtype: str = "f32"):
    """Grow one tree from a BlockStore (plain numeric path).

    Mirrors ``grow_tree``'s strict/wave dispatch on the encoded
    ``wave_width``; returns ``(tree, row_leaf)`` like the in-memory
    grower, with ``row_leaf`` sized ``store.padded_rows``.
    """
    width, tail, overgrow = decode_wave_width(wave_width)
    if width <= 1:
        return _grow_strict(store, stats, feature_mask, ctx, num_leaves,
                            num_bins, max_depth, hist_impl, hist_dtype)
    return _grow_wave(store, stats, feature_mask, ctx, num_leaves,
                      num_bins, max_depth, width, tail, overgrow,
                      hist_impl, hist_dtype)


def _grow_strict(store, stats, feature_mask, ctx, num_leaves, num_bins,
                 max_depth, hist_impl, hist_dtype):
    capacity = 2 * num_leaves - 1
    root_hist = _root_hist(store, stats, num_bins, hist_impl, hist_dtype)
    P, aux = stream_strict_init(root_hist, ctx, feature_mask, capacity)
    row_leaf = jnp.zeros(store.padded_rows, jnp.int32)
    n_nodes = jnp.int32(1)
    n_leaves = jnp.int32(1)
    blk = _stream_strict_block_fn(num_bins, store.block_rows, hist_impl,
                                  hist_dtype)
    multi = store.num_blocks > 1
    for _ in range(num_leaves - 1):
        acc = None
        for off, bins_b in store.device_blocks():
            row_leaf, h = blk(bins_b, stats, row_leaf, jnp.int32(off),
                              aux, n_nodes)
            if acc is None:
                acc = (jnp.zeros_like(h) + h) if multi else h
            else:
                acc = acc + h
        P, aux, n_nodes, n_leaves = stream_strict_update(
            acc, P, aux, feature_mask, ctx, max_depth, n_nodes, n_leaves)
    return _tree_from_packed(P, n_leaves, None, None), row_leaf


def _grow_wave(store, stats, feature_mask, ctx, num_leaves, num_bins,
               max_depth, width, tail, overgrow, hist_impl, hist_dtype):
    exact = tail == "exact"
    grow_leaves = (max(num_leaves + 1, int(overgrow or 0)) if exact
                   else num_leaves)
    capacity = 2 * grow_leaves - 1
    w_width = min(int(width), grow_leaves - 1)
    num_features = store.num_features
    root_hist = _root_hist(store, stats, num_bins, hist_impl, hist_dtype)
    P, cache, node_slot = stream_wave_init(root_hist, ctx, feature_mask,
                                           capacity, grow_leaves)
    row_leaf = jnp.zeros(store.padded_rows, jnp.int32)
    n_nodes = jnp.int32(1)
    n_leaves = jnp.int32(1)
    plan, update, cond = _stream_wave_fns(capacity, w_width, grow_leaves,
                                          num_features, num_bins, tail)
    blk = _stream_wave_block_fn(w_width, num_bins, num_features,
                                store.block_rows, hist_impl, hist_dtype)
    multi = store.num_blocks > 1
    # host sync once per wave: the wave count is data-dependent and the
    # block loop is a host loop, so the while predicate must come back to
    # the host (graftlint GL002 — baselined with this justification)
    while bool(cond(P, n_leaves)):
        tbl = plan(P, n_leaves)
        acc = None
        for off, bins_b in store.device_blocks():
            row_leaf, h = blk(bins_b, stats, row_leaf, jnp.int32(off),
                              tbl, n_nodes)
            if acc is None:
                acc = (jnp.zeros_like(h) + h) if multi else h
            else:
                acc = acc + h
        P, cache, node_slot, n_nodes, n_leaves = update(
            P, cache, node_slot, n_nodes, n_leaves, acc, feature_mask,
            ctx, max_depth)
    if exact:
        newP, row_leaf, n_leaves_f = stream_exact_prune(P, row_leaf,
                                                        num_leaves)
        return _tree_from_packed(newP, n_leaves_f, None, None), row_leaf
    return _tree_from_packed(P, n_leaves, None, None), row_leaf


# ---------------------------------------------------------------------------
# Boosting-round drivers (wired from models.gbdt.Booster.update)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _grad_stats_fn(obj_key: tuple):
    """Jitted grad/hess + per-row stat stack, keyed like gbdt's round
    functions so repeated rounds reuse one compile."""
    from ..models.gbdt import _rebuild_objective

    obj = _rebuild_objective(obj_key)

    @jax.jit
    def fn(pred, y, w, bag):
        g, h = obj.grad_hess(pred, y, w)
        stats = jnp.stack([g * bag, h * bag,
                           (bag > 0).astype(jnp.float32)], axis=-1)
        return g, h, stats

    return fn


@functools.lru_cache(maxsize=None)
def _goss_grow_fn(num_leaves: int, num_bins: int, hist_impl: str,
                  row_chunk: int, hist_dtype: str, wave_width: int):
    """Jitted in-memory grower over the GOSS-compacted [k, F] matrix."""

    @jax.jit
    def fn(bins_c, stats, fmask, ctx, max_depth, key):
        return grow_tree(bins_c, stats, fmask, ctx, num_leaves, num_bins,
                         max_depth, ff_bynode=None, key=key,
                         hist_impl=hist_impl, row_chunk=row_chunk,
                         hist_dtype=hist_dtype, wave_width=wave_width,
                         fuse_partition=True)

    return fn


@functools.lru_cache(maxsize=None)
def _block_pred_fn():
    @jax.jit
    def fn(tree, bins_b):
        return predict_tree_binned(tree, bins_b, None)

    return fn


@functools.lru_cache(maxsize=None)
def _replay_add_fn():
    """Jitted ``pred + shrink * delta`` used when a loaded forest is
    replayed onto a streamed Dataset (model-file continuation, r15).
    Jitted for the same FMA-contraction reason as
    :func:`_pred_update_fn` — the replayed predictions must be
    bit-identical to the ones the uninterrupted run carried."""

    @jax.jit
    def fn(pred, shrink, delta):
        return pred + shrink * delta

    return fn


@functools.lru_cache(maxsize=None)
def _pred_update_fn(is_rf: bool):
    """Jitted train-score update.  MUST be jitted, not eager: under jit
    XLA:CPU contracts ``pred + shrink * leaf`` into an FMA exactly like
    the in-memory round program does — computed eagerly the mul and add
    round separately and tree k+1 sees 1-ulp-different gradients."""

    @jax.jit
    def fn(pred, lr, row_leaf, leaf_value):
        shrink = jnp.where(is_rf, 1.0, lr)
        return pred + shrink * lookup_values(row_leaf, leaf_value)

    return fn


def stream_plain_round(store, obj_key: tuple, y, w, bag, pred, fmask,
                       hyper, num_leaves: int, num_bins: int,
                       hist_impl: str, hist_dtype: str, wave_width: int,
                       is_rf: bool, renew_alpha=None, renew_scale=None):
    """One plain gbdt/rf boosting round over a BlockStore — the streamed
    restatement of gbdt's serial ``round_fn``."""
    _, _, stats = _grad_stats_fn(obj_key)(pred, y, w, bag)
    tree, row_leaf = stream_grow_tree(
        store, stats, fmask, hyper.ctx(), num_leaves, num_bins,
        hyper.max_depth, wave_width, hist_impl, hist_dtype)
    if renew_alpha is not None:
        rw = w * bag if renew_scale is None else w * bag * renew_scale(y)
        tree = renew_leaf_values(tree, row_leaf, y - pred, rw, renew_alpha)
    new_pred = _pred_update_fn(is_rf)(pred, hyper.learning_rate, row_leaf,
                                      tree.leaf_value)
    return tree, new_pred


def stream_goss_round(store, obj_key: tuple, y, w, bag, pred, fmask,
                      hyper, key, goss_k, top_rate: float,
                      other_rate: float, seed: int, num_leaves: int,
                      num_bins: int, hist_impl: str, hist_dtype: str,
                      wave_width: int, renew_alpha=None,
                      renew_scale=None):
    """One GOSS round with host-side sampling before transfer.

    Selection runs on host copies of |g| and the bag (deliberate host
    syncs — graftlint GL002, baselined): exact top-``k_top`` by |g|, then
    a seeded uniform draw of ``k_other`` from the rest, then ONE host
    gather of just those rows crosses PCIe.  Weighting matches the device
    GOSS path (amplified other-weights, live masking); the selection RNG
    stream intentionally does not.
    """
    k_top, k_other = goss_k
    g, h, _ = _grad_stats_fn(obj_key)(pred, y, w, bag)
    g_abs = np.asarray(jnp.abs(g))          # host sync: sampling source
    bag_h = np.asarray(bag)                 # host sync: validity mask
    valid = bag_h > 0
    score = np.where(valid, g_abs, -1.0)
    k_top_eff = min(k_top, int(valid.sum()))
    if k_top_eff > 0:
        top_idx = np.sort(np.argpartition(-score, k_top_eff - 1)
                          [:k_top_eff].astype(np.int64))
    else:
        top_idx = np.empty(0, np.int64)
    is_top = np.zeros(score.shape[0], bool)
    is_top[top_idx] = True
    rest_idx = np.flatnonzero(valid & ~is_top)
    rng = np.random.default_rng(seed)
    k_other_eff = min(k_other, len(rest_idx))
    other_idx = np.sort(rng.choice(rest_idx, size=k_other_eff,
                                   replace=False))

    def pad_fill(idx, k):
        out = np.zeros(k, np.int64)
        out[:len(idx)] = idx
        fill = (np.arange(k) < len(idx)).astype(np.float32)
        return out, fill

    top_idx, top_fill = pad_fill(top_idx, k_top)
    other_idx, other_fill = pad_fill(other_idx, k_other)
    idx_h = np.concatenate([top_idx, other_idx])
    amp = np.float32((1.0 - top_rate) / max(other_rate, 1e-12))
    wt_h = np.concatenate([top_fill, other_fill * amp])

    # GOSS-at-the-source: only the k sampled rows cross PCIe
    bins_h = store.gather_rows(idx_h)
    store.bytes_streamed += bins_h.nbytes
    bins_c = jax.device_put(bins_h)
    idx = jnp.asarray(idx_h, jnp.int32)
    wt = jnp.asarray(wt_h)
    live = (bag[idx] > 0).astype(jnp.float32) * (wt > 0)
    wt = wt * live
    stats = jnp.stack([g[idx] * wt, h[idx] * wt, live], axis=-1)
    grow = _goss_grow_fn(num_leaves, num_bins, hist_impl,
                         store.block_rows, hist_dtype, wave_width)
    tree, rl_c = grow(bins_c, stats, fmask, hyper.ctx(), hyper.max_depth,
                      key)
    if renew_alpha is not None:
        rw = w[idx] * wt
        if renew_scale is not None:
            rw = rw * renew_scale(y[idx])
        tree = renew_leaf_values(tree, rl_c, y[idx] - pred[idx], rw,
                                 renew_alpha)
    # train-score update: one full streaming pass of traversal per round
    pred_fn = _block_pred_fn()
    deltas = [pred_fn(tree, bins_b) for _, bins_b in store.device_blocks()]
    delta = deltas[0] if len(deltas) == 1 else jnp.concatenate(deltas)
    new_pred = jax.jit(lambda p_, lr, d: p_ + lr * d)(
        pred, hyper.learning_rate, delta)
    return tree, new_pred

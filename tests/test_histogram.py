"""Histogram op: matmul formulation vs numpy oracle, segments, chunking."""

import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import compute_histograms


def _numpy_hist(bins, stats, seg, K, B):
    n, F = bins.shape
    S = stats.shape[1]
    out = np.zeros((K, F, B, S), np.float64)
    for i in range(n):
        if 0 <= seg[i] < K:
            for f in range(F):
                out[seg[i], f, bins[i, f]] += stats[i]
    return out


@pytest.mark.parametrize("n,F,B,K", [(100, 3, 8, 1), (257, 2, 16, 2),
                                     (1000, 4, 32, 3)])
def test_histogram_matches_numpy(rng, n, F, B, K):
    bins = rng.integers(0, B, (n, F)).astype(np.uint8)
    stats = rng.normal(0, 1, (n, 3)).astype(np.float32)
    seg = rng.integers(0, K + 1, n).astype(np.int32)  # includes dropped seg K
    got = compute_histograms(jnp.asarray(bins), jnp.asarray(stats),
                             jnp.asarray(seg), K, B)
    want = _numpy_hist(bins, stats, seg, K, B)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_histogram_row_chunking_equivalent(rng):
    n, F, B, K = 700, 3, 16, 2
    bins = rng.integers(0, B, (n, F)).astype(np.uint8)
    stats = rng.normal(0, 1, (n, 2)).astype(np.float32)
    seg = rng.integers(0, K, n).astype(np.int32)
    full = compute_histograms(jnp.asarray(bins), jnp.asarray(stats),
                              jnp.asarray(seg), K, B, row_chunk=10_000)
    chunked = compute_histograms(jnp.asarray(bins), jnp.asarray(stats),
                                 jnp.asarray(seg), K, B, row_chunk=128)
    np.testing.assert_allclose(np.asarray(full), np.asarray(chunked),
                               rtol=1e-5, atol=1e-5)


def test_histogram_zero_stats_rows_contribute_nothing(rng):
    n, F, B = 50, 2, 8
    bins = rng.integers(0, B, (n, F)).astype(np.uint8)
    stats = np.ones((n, 1), np.float32)
    stats[25:] = 0.0
    seg = np.zeros(n, np.int32)
    got = compute_histograms(jnp.asarray(bins), jnp.asarray(stats),
                             jnp.asarray(seg), 1, B)
    # every feature's histogram accumulates all contributing rows once
    assert float(np.asarray(got).sum()) == 25.0 * F


@pytest.mark.parametrize("mode", ["f32", "bf16"])
def test_fused_pallas_matches_numpy(rng, mode):
    from lightgbm_tpu.ops.histogram_pallas import hist_fused_pallas

    n, F, B, K = 1500, 4, 32, 5
    bins = rng.integers(0, B, (n, F)).astype(np.uint8)
    stats = rng.normal(0, 1, (n, 3)).astype(np.float32)
    seg = rng.integers(-1, K + 1, n).astype(np.int32)  # out-of-range dropped
    got = hist_fused_pallas(jnp.asarray(bins), jnp.asarray(stats),
                            jnp.asarray(seg), K, B, hist_dtype=mode)
    want = _numpy_hist(bins, stats, seg, K, B)
    tol = 2e-2 if mode == "bf16" else 1e-3
    np.testing.assert_allclose(np.asarray(got), want, rtol=tol, atol=tol)


def test_fused_pallas_feature_blocking(rng):
    """Wide-feature shapes split the feature axis into grid blocks (the
    [F, B, K] accumulator must fit VMEM — MSLR has 136 features)."""
    from lightgbm_tpu.ops.histogram_pallas import hist_fused_pallas

    # F=136, B=256, K=42*3 -> a ~17.5 MB accumulator: must split into
    # (at least) two feature blocks to fit the 16 MB VMEM scope
    n, F, B, K = 700, 136, 256, 42
    bins = rng.integers(0, B, (n, F)).astype(np.uint8)
    stats = rng.normal(0, 1, (n, 3)).astype(np.float32)
    seg = rng.integers(0, K, n).astype(np.int32)
    got = hist_fused_pallas(jnp.asarray(bins), jnp.asarray(stats),
                            jnp.asarray(seg), K, B, hist_dtype="f32")
    want = _numpy_hist(bins, stats, seg, K, B)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-3, atol=1e-3)


def test_fused_pallas_int8_quantized():
    """int8 quantized-gradient mode (use_quantized_grad analogue): unbiased
    stochastic rounding, exact int32 accumulation — histogram within ~1%
    of exact, count channel near-exact.  Uses its OWN rng: the stochastic
    tolerance is calibrated to this exact draw (the shared session rng
    makes the bound order-dependent)."""
    from lightgbm_tpu.ops.histogram_pallas import hist_fused_pallas

    rng = np.random.default_rng(1234)
    n, F, B, K = 4000, 4, 32, 5
    bins = rng.integers(0, B, (n, F)).astype(np.uint8)
    stats = np.column_stack([
        rng.normal(0, 1, n), np.abs(rng.normal(0, 1, n)),
        np.ones(n)]).astype(np.float32)
    seg = rng.integers(0, K, n).astype(np.int32)
    got = np.asarray(hist_fused_pallas(
        jnp.asarray(bins), jnp.asarray(stats), jnp.asarray(seg), K, B,
        hist_dtype="int8"))
    want = _numpy_hist(bins, stats, seg, K, B)
    scale = np.abs(stats).max(axis=0) / 127.0
    # per-cell error bound: each row contributes <= scale/... stochastic
    # rounding error < 1 quantum per row; cells hold ~n/(K*B) rows
    tol = scale * 4 * np.sqrt(n / (K * B) + 9)
    err = np.abs(got - want).max(axis=(0, 1, 2))
    assert np.all(err < tol), (err, tol)
    # totals per (segment, channel): each row's rounding error repeats in
    # ALL F feature histograms, so the f-summed error has sigma
    # F * sqrt(rows_per_seg / 12) quanta; allow 4 sigma
    tg, wg = got.sum(axis=(1, 2)), want.sum(axis=(1, 2))
    sigma_q = F * np.sqrt(n / K / 12.0)
    np.testing.assert_allclose(tg, wg, rtol=5e-3,
                               atol=float(scale.max()) * 4 * sigma_q)

"""Split-gain scan: hand-computable cases + constraint handling."""

import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.ops.split import (
    SplitContext,
    find_best_split,
    leaf_objective,
    leaf_output,
    threshold_l1,
)


def make_ctx(l1=0.0, l2=0.0, min_data=0.0, min_hess=0.0, min_gain=0.0):
    return SplitContext(
        lambda_l1=jnp.float32(l1), lambda_l2=jnp.float32(l2),
        min_data_in_leaf=jnp.float32(min_data),
        min_sum_hessian=jnp.float32(min_hess),
        min_gain_to_split=jnp.float32(min_gain))


def test_perfect_split_found():
    # feature 0: bins 0,1 have grad -1 each (4 rows), bins 2,3 grad +1 (4 rows)
    # splitting at bin 1 separates negative from positive grads perfectly.
    B = 4
    hist = np.zeros((2, B, 3), np.float32)
    hist[0, 0] = [-2.0, 2.0, 2.0]
    hist[0, 1] = [-2.0, 2.0, 2.0]
    hist[0, 2] = [2.0, 2.0, 2.0]
    hist[0, 3] = [2.0, 2.0, 2.0]
    # feature 1: uninformative, everything in one bin
    hist[1, 0] = [0.0, 8.0, 8.0]
    bs = find_best_split(jnp.asarray(hist), make_ctx(),
                         jnp.ones(2), jnp.bool_(True))
    assert int(bs.feature) == 0
    assert int(bs.bin) == 1
    # gain = GL^2/HL + GR^2/HR - G^2/H = 16/4 + 16/4 - 0 = 8
    assert float(bs.gain) == pytest.approx(8.0, rel=1e-5)
    assert float(bs.left_g) == pytest.approx(-4.0)
    assert float(bs.right_g) == pytest.approx(4.0)
    assert float(bs.left_c) == pytest.approx(4.0)


def test_min_data_constraint_blocks_small_children():
    B = 4
    hist = np.zeros((1, B, 3), np.float32)
    hist[0, 0] = [-5.0, 1.0, 1.0]   # one row with big grad
    hist[0, 1] = [0.1, 1.0, 1.0]
    hist[0, 2] = [0.1, 1.0, 1.0]
    hist[0, 3] = [4.8, 1.0, 1.0]
    bs_free = find_best_split(jnp.asarray(hist), make_ctx(),
                              jnp.ones(1), jnp.bool_(True))
    assert np.isfinite(float(bs_free.gain))
    bs_blocked = find_best_split(jnp.asarray(hist), make_ctx(min_data=2),
                                 jnp.ones(1), jnp.bool_(True))
    # only the middle split (2 vs 2) remains legal
    assert int(bs_blocked.bin) == 1


def test_feature_mask_disables_feature():
    B = 2
    hist = np.zeros((2, B, 3), np.float32)
    hist[0, 0] = [-3.0, 2.0, 2.0]
    hist[0, 1] = [3.0, 2.0, 2.0]
    hist[1, 0] = [-1.0, 2.0, 2.0]
    hist[1, 1] = [1.0, 2.0, 2.0]
    mask = jnp.asarray([0.0, 1.0])
    bs = find_best_split(jnp.asarray(hist), make_ctx(), mask, jnp.bool_(True))
    assert int(bs.feature) == 1


def test_depth_not_ok_blocks_everything():
    hist = np.zeros((1, 2, 3), np.float32)
    hist[0, 0] = [-3.0, 2.0, 2.0]
    hist[0, 1] = [3.0, 2.0, 2.0]
    bs = find_best_split(jnp.asarray(hist), make_ctx(),
                         jnp.ones(1), jnp.bool_(False))
    assert not np.isfinite(float(bs.gain))


def test_lambda_l2_shrinks_gain_and_output():
    g, h = jnp.float32(-6.0), jnp.float32(3.0)
    ctx0 = make_ctx(l2=0.0)
    ctx2 = make_ctx(l2=3.0)
    assert float(leaf_output(g, h, ctx0)) == pytest.approx(2.0)
    assert float(leaf_output(g, h, ctx2)) == pytest.approx(1.0)
    assert float(leaf_objective(g, h, ctx0)) > float(leaf_objective(g, h, ctx2))


def test_threshold_l1():
    assert float(threshold_l1(jnp.float32(5.0), jnp.float32(2.0))) == 3.0
    assert float(threshold_l1(jnp.float32(-5.0), jnp.float32(2.0))) == -3.0
    assert float(threshold_l1(jnp.float32(1.0), jnp.float32(2.0))) == 0.0


def test_last_bin_never_selected():
    # all mass in last bin -> right side of any split empty except bin<last;
    # splitting exactly at the last bin would give an empty right child.
    hist = np.zeros((1, 4, 3), np.float32)
    hist[0, 3] = [3.0, 2.0, 2.0]
    bs = find_best_split(jnp.asarray(hist), make_ctx(min_data=1),
                         jnp.ones(1), jnp.bool_(True))
    assert not np.isfinite(float(bs.gain))

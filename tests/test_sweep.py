"""Sweep-as-a-service tests (ISSUE r17 tentpole + satellites).

The distributed, preemptible hyperparameter sweep subsystem: the
scheduler's configs x devices mesh plan, the crash-safe resumable
ledger (atomic saves, sentinel-proof leaderboard, RData/JSON codecs,
``_merge_existing`` drift handling), the SweepService's fused
hyper-batch engine with kill-anywhere checkpoint parity (fault
injection at ``sweep_segment``/``sweep_record`` plus SIGTERM drain,
FILE-level byte comparison on both codecs), the RefreshDaemon's
sweep -> canary -> flip retune loop with chaos at ``sweep_promote``,
the ``task=sweep`` CLI contract, and the analytic SWEEP_BUDGETS.
"""

import gzip
import hashlib
import io
import json
import os
import signal

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.__main__ import _sweep, main as cli_main
from lightgbm_tpu.analysis.budgets import (BUDGET_ANCHORS, SWEEP_BUDGETS,
                                           check_budget_anchors,
                                           check_sweep_budgets,
                                           sweep_budget_by_name,
                                           sweep_staleness_model,
                                           sweep_time_model)
from lightgbm_tpu.config import parse_params
from lightgbm_tpu.faults import SITES, SWEEP_SITES, FaultInjector
from lightgbm_tpu.pipeline.daemon import ArrivalFeed, RefreshDaemon
from lightgbm_tpu.pipeline.staleness import SimClock
from lightgbm_tpu.sweep import (SENTINEL, SweepLedger, SweepScheduler,
                                SweepService, expand_grid, fused_bucket_key)
from lightgbm_tpu.sweep.ledger import grid_digest
from lightgbm_tpu.utils.rdata import read_rdata, write_rdata
from lightgbm_tpu.utils.sweep import run_grid_search

GRID = expand_grid(learning_rate=[0.3, 0.1], num_leaves=[7, 15])
BASE = {"objective": "regression", "metric": "l2", "verbose": -1,
        "min_data_in_leaf": 5}
# small segments force mid-unit checkpoints in the chaos tests
SEGMENTED = dict(BASE, cv_segment_rounds=5)
FROZEN_CLOCK = lambda: 0.0  # noqa: E731 — pins saved_at for byte parity


def _problem(n=400, f=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2
         + rng.normal(0, 0.1, n)).astype(np.float32)
    return X, y


def _dataset(seed=0):
    X, y = _problem(seed=seed)
    return lgb.Dataset(X, label=y)


def _digest(path):
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _service(ds, *, base=BASE, rounds=20, es=5, **kw):
    return SweepService(GRID, ds, base_params=base, num_boost_round=rounds,
                        nfold=3, early_stopping_rounds=es, seed=0, **kw)


# -- scheduler: grid -> hyper-batches -> device groups -------------------


def _parsed(grid, extra=()):
    return [parse_params({**BASE, **dict(extra), **cfg},
                         warn_unknown=False) for cfg in grid]


class _TS:
    num_bins = 32


def test_scheduler_buckets_by_fused_statics():
    grid = expand_grid(learning_rate=[0.3, 0.1], num_leaves=[7, 15, 31])
    plan = SweepScheduler().plan(_parsed(grid), _TS())
    # 3 num_leaves x 2 learning_rate -> 6 buckets (lr buckets too: a
    # bucket runs to its slowest config's early stop)
    assert len(plan.units) == 6
    assert plan.n_configs() == len(grid)
    keys = {u.bucket_key for u in plan.units}
    assert len(keys) == 6
    covered = sorted(i for u in plan.units for i in u.config_indices)
    assert covered == list(range(len(grid)))


def test_scheduler_hyper_batch_chunking_and_lpt_balance():
    grid = [{"num_leaves": 7}] * 10  # one bucket, hyper_batch=4 -> 4+4+2
    plan = SweepScheduler(hyper_batch=4).plan(_parsed(grid), _TS(),
                                             n_devices=2)
    sizes = sorted(len(u.config_indices) for u in plan.units)
    assert sizes == [2, 4, 4]
    assert plan.n_groups == 2
    loads = [sum(len(u.config_indices) for u in plan.units_for_group(g))
             for g in range(2)]
    assert sorted(loads) == [4, 6]  # greedy LPT: 4 | 4+2


def test_scheduler_skips_done_and_is_deterministic():
    parsed = _parsed(GRID)
    p1 = SweepScheduler().plan(parsed, _TS(), done=[0, 2], n_devices=4)
    assert p1.n_configs() == 2
    assert all(0 not in u.config_indices and 2 not in u.config_indices
               for u in p1.units)
    p2 = SweepScheduler().plan(parsed, _TS(), done=[0, 2], n_devices=4)
    assert p1 == p2  # same pending set -> same units, uids, groups


def test_scheduler_validation():
    with pytest.raises(ValueError, match="hyper_batch"):
        SweepScheduler(hyper_batch=0)
    with pytest.raises(ValueError, match="n_devices"):
        SweepScheduler().plan(_parsed(GRID), _TS(), n_devices=0)
    with pytest.raises(ValueError, match="divide"):
        SweepScheduler().plan(_parsed(GRID), _TS(), n_devices=4,
                              group_size=3)


def test_bucket_key_separates_objective_scalars():
    a = parse_params(dict(BASE, objective="quantile", alpha=0.5,
                          num_leaves=7), warn_unknown=False)
    b = parse_params(dict(BASE, objective="quantile", alpha=0.9,
                          num_leaves=7), warn_unknown=False)
    assert fused_bucket_key(a, _TS()) != fused_bucket_key(b, _TS())


# -- ledger: expand_grid, atomic save, sentinel leaderboard --------------


def test_expand_grid_first_axis_fastest():
    rows = expand_grid(a=[1, 2], b=["x", "y"])
    assert rows == [{"a": 1, "b": "x"}, {"a": 2, "b": "x"},
                    {"a": 1, "b": "y"}, {"a": 2, "b": "y"}]


def test_ledger_save_is_atomic_no_tmp_left(tmp_path):
    for name in ("led.json", "led.RData"):
        path = str(tmp_path / name)
        led = SweepLedger(GRID, path, clock=FROZEN_CLOCK)
        led.record(1, 12, -0.5)
        assert os.path.exists(path)
        assert not [f for f in os.listdir(tmp_path)
                    if f.startswith(".tmp-")], name
        led2 = SweepLedger(GRID, path, clock=FROZEN_CLOCK)
        assert led2.done(1) and not led2.done(0)
        assert led2.rows[1]["iteration"] == 12


def test_leaderboard_excludes_sentinel_rows(tmp_path):
    led = SweepLedger(GRID)
    led.rows[0]["iteration"] = 10          # score still SENTINEL: excluded
    led.rows[1].update(iteration=20, score=-0.25)
    led.rows[2].update(iteration=30, score=-0.125)
    board = led.leaderboard()
    assert [r["iteration"] for r in board] == [30, 20]  # best first
    assert all(r["score"] != SENTINEL for r in board)
    # a half-recorded row ranks nowhere even though done() counts it
    assert led.done(0) and led.rows[0] not in board
    assert led.pending() == [3]


def test_grid_digest_covers_rows_and_statics():
    d0 = grid_digest(GRID, nfold=3, seed=0)
    assert d0 == grid_digest(list(GRID), nfold=3, seed=0)
    assert d0 != grid_digest(GRID, nfold=5, seed=0)
    assert d0 != grid_digest(GRID[:3], nfold=3, seed=0)


# -- satellite 4: _merge_existing edge cases ----------------------------


def test_merge_existing_resumes_done_rows(tmp_path):
    path = str(tmp_path / "led.json")
    led = SweepLedger(GRID, path, clock=FROZEN_CLOCK)
    led.record(0, 11, -0.5)
    led.record(2, 13, -0.25)
    led2 = SweepLedger(GRID, path, clock=FROZEN_CLOCK)
    assert led2.pending() == [1, 3]
    assert led2.rows[0]["iteration"] == 11
    assert led2.rows[2]["score"] == -0.25


def test_merge_existing_grid_shape_drift(tmp_path):
    # saved ledger longer than the new grid: extra rows ignored
    path = str(tmp_path / "led.json")
    big = GRID + [{"learning_rate": 0.05, "num_leaves": 63}]
    led = SweepLedger(big, path, clock=FROZEN_CLOCK)
    led.record(4, 40, -0.1)
    led.record(1, 41, -0.2)
    led2 = SweepLedger(GRID, path, clock=FROZEN_CLOCK)
    assert len(led2.rows) == len(GRID)
    assert led2.done(1) and led2.pending() == [0, 2, 3]
    # drifted axis VALUES at the same index: results must NOT transfer
    other = expand_grid(learning_rate=[0.2, 0.05], num_leaves=[7, 15])
    led3 = SweepLedger(other, path, clock=FROZEN_CLOCK)
    assert not led3.done(1)
    assert led3.pending() == [0, 1, 2, 3]


def test_merge_existing_float_tolerance():
    # R numerics round-trip as floats: 7 vs 7.0 must still match
    assert SweepLedger._cfg_equal({"num_leaves": 7, "lr": 0.1},
                                  {"num_leaves": 7.0, "lr": 0.1})
    assert SweepLedger._cfg_equal({"lr": 0.1},
                                  {"lr": 0.1 + 1e-12})
    assert not SweepLedger._cfg_equal({"lr": 0.1}, {"lr": 0.1001})
    assert not SweepLedger._cfg_equal({"lr": 0.1}, {"lr": 0.1, "x": 1})
    assert not SweepLedger._cfg_equal({"s": "goss"}, {"s": "gbdt"})


def test_merge_existing_rdata_json_round_trip(tmp_path):
    jp, rp = str(tmp_path / "led.json"), str(tmp_path / "led.RData")
    led = SweepLedger(GRID, jp, clock=FROZEN_CLOCK)
    led.record(0, 17, -0.5)
    led.record(3, 19, -0.75)
    # re-save the same rows through the RData codec, then resume from it
    led.path = rp
    led.save()
    led2 = SweepLedger(GRID, rp, clock=FROZEN_CLOCK)
    assert led2.pending() == [1, 2]
    assert led2.rows[0]["iteration"] == 17  # int restored from R numeric
    assert led2.rows[3]["score"] == -0.75
    df = read_rdata(rp)["paramGrid"]
    assert list(df.keys())[:2] == ["iteration", "score"]


def test_merge_existing_reference_paramgrid_rdata():
    # the repo-root reference ledger (108 configs, the R script's own
    # checkpoint format) must load as a resumable ledger
    path = os.path.join(os.path.dirname(__file__), "..",
                        "paramGrid_tpu.RData")
    df = read_rdata(path)["paramGrid"]
    n = len(df["iteration"])
    grid = [{k: df[k][i] for k in df if k not in ("iteration", "score")}
            for i in range(n)]
    led = SweepLedger(grid, path, clock=FROZEN_CLOCK)
    assert len(led.rows) == n == 108
    done = [i for i in range(n) if led.done(i)]
    assert done == [i for i in range(n)
                    if df["iteration"][i] != SENTINEL]


# -- service: fused engine, parity, kill-anywhere resume -----------------


def test_service_fused_matches_host_and_compat_wrapper(tmp_path):
    ds = _dataset()
    lp = str(tmp_path / "a.json")
    res = _service(ds, ledger_path=lp,
                   checkpoint_dir=str(tmp_path / "ck")).run()
    assert res.completed and res.engine == "fused"
    assert res.units_done == res.units_total
    rows_fused = [dict(r) for r in res.ledger.rows]

    # the host loop (engine.cv) draws its own fold partition and
    # aggregates per fold, so scores only agree loosely — exact parity
    # is asserted against the compat wrapper below, which shares the
    # fused path
    host = _service(ds, engine="host").run()
    assert host.completed and host.engine == "host"
    rows_host = [dict(r) for r in host.ledger.rows]
    for a, b in zip(rows_fused, rows_host):
        assert a["score"] == pytest.approx(b["score"], rel=0.25)

    lg = run_grid_search(GRID, ds, base_params=BASE, num_boost_round=20,
                         nfold=3, early_stopping_rounds=5, seed=0,
                         verbose=False)
    assert [dict(r) for r in lg.rows] == rows_fused
    assert lg.sweep_stats["rounds_total"] > 0
    assert lg.sweep_stats["plan"]["units"] == res.units_total


def test_service_resume_skips_done_configs(tmp_path):
    ds = _dataset()
    lp = str(tmp_path / "led.json")
    led = SweepLedger(GRID, lp, clock=FROZEN_CLOCK)
    led.record(0, 5, -9.0)   # pre-recorded: must survive untouched
    led.record(2, 6, -8.0)
    res = _service(ds, ledger_path=lp).run()
    assert res.completed
    assert res.ledger.rows[0]["iteration"] == 5  # not re-run
    assert res.ledger.rows[2]["iteration"] == 6
    assert res.ledger.rows[1]["iteration"] not in (SENTINEL, 5, 6)


@pytest.mark.parametrize("suffix", ["json", "RData"])
def test_kill_anywhere_file_level_parity(tmp_path, suffix):
    """Fault mid-sweep at a segment boundary, resume from the hyper-batch
    checkpoint: the final ledger FILE is byte-identical to an
    uninterrupted run's, on both codecs."""
    ds = _dataset()
    clean = str(tmp_path / f"clean.{suffix}")
    _service(ds, base=SEGMENTED, rounds=30, es=30, ledger_path=clean,
             clock=FROZEN_CLOCK).run()

    chaos = str(tmp_path / f"chaos.{suffix}")
    ck = str(tmp_path / f"ck_{suffix}")
    inj = FaultInjector()
    inj.arm("sweep_segment", after=2)
    r = _service(ds, base=SEGMENTED, rounds=30, es=30, ledger_path=chaos,
                 checkpoint_dir=ck, injector=inj, clock=FROZEN_CLOCK).run()
    assert r.preempted and "sweep_segment" in r.error
    assert os.path.isdir(ck)  # mid-unit carry checkpoints exist

    r2 = _service(ds, base=SEGMENTED, rounds=30, es=30, ledger_path=chaos,
                  checkpoint_dir=ck, clock=FROZEN_CLOCK).run()
    assert r2.completed and r2.resumed_units >= 1
    assert _digest(chaos) == _digest(clean)
    assert not os.path.exists(ck)  # spent checkpoints pruned


def test_sweep_record_fault_leaves_ledger_untouched(tmp_path):
    ds = _dataset()
    clean = str(tmp_path / "clean.json")
    _service(ds, base=SEGMENTED, rounds=30, es=30, ledger_path=clean,
             clock=FROZEN_CLOCK).run()
    lp = str(tmp_path / "rec.json")
    ck = str(tmp_path / "ck")
    inj = FaultInjector()
    inj.arm("sweep_record")
    r = _service(ds, base=SEGMENTED, rounds=30, es=30, ledger_path=lp,
                 checkpoint_dir=ck, injector=inj, clock=FROZEN_CLOCK).run()
    assert r.preempted and "sweep_record" in r.error
    # the fault fired BEFORE any row mutation: all rows still sentinels
    assert SweepLedger(GRID, lp, clock=FROZEN_CLOCK).pending() \
        == list(range(len(GRID)))
    r2 = _service(ds, base=SEGMENTED, rounds=30, es=30, ledger_path=lp,
                  checkpoint_dir=ck, clock=FROZEN_CLOCK).run()
    assert r2.completed and _digest(lp) == _digest(clean)


def test_sigterm_drain_mid_sweep_resumes(tmp_path):
    # real SIGTERM delivered mid-run (the bench_chaos trick): the guard
    # drains at the next poll, the rerun completes with parity
    ds = _dataset()
    clean = str(tmp_path / "clean.json")
    _service(ds, engine="host", ledger_path=clean,
             clock=FROZEN_CLOCK).run()

    from lightgbm_tpu.engine import cv as real_cv
    fired = []

    def killing_cv(*a, **kw):
        fit = real_cv(*a, **kw)
        if not fired:
            fired.append(True)
            os.kill(os.getpid(), signal.SIGTERM)
        return fit

    lp = str(tmp_path / "drain.json")
    r = _service(ds, engine="host", ledger_path=lp, cv_fn=killing_cv,
                 clock=FROZEN_CLOCK).run()
    assert r.preempted and "SIGTERM" in r.error
    assert 0 < r.units_done < len(GRID)
    r2 = _service(ds, engine="host", ledger_path=lp,
                  clock=FROZEN_CLOCK).run()
    assert r2.completed and _digest(lp) == _digest(clean)


def test_corrupt_unit_checkpoint_falls_back_to_restart(tmp_path):
    ds = _dataset()
    clean = str(tmp_path / "clean.json")
    _service(ds, base=SEGMENTED, rounds=30, es=30, ledger_path=clean,
             clock=FROZEN_CLOCK).run()
    lp = str(tmp_path / "c.json")
    ck = str(tmp_path / "ck")
    inj = FaultInjector()
    inj.arm("sweep_segment", after=2)
    _service(ds, base=SEGMENTED, rounds=30, es=30, ledger_path=lp,
             checkpoint_dir=ck, injector=inj, clock=FROZEN_CLOCK).run()
    # torch every checkpoint payload byte
    for root, _, files in os.walk(ck):
        for f in files:
            with open(os.path.join(root, f), "r+b") as fh:
                fh.write(b"\x00garbage\x00")
    r2 = _service(ds, base=SEGMENTED, rounds=30, es=30, ledger_path=lp,
                  checkpoint_dir=ck, clock=FROZEN_CLOCK).run()
    assert r2.completed and r2.resumed_units == 0  # clean restart
    assert _digest(lp) == _digest(clean)


def test_stale_grid_digest_rejects_foreign_checkpoint(tmp_path):
    ds = _dataset()
    lp = str(tmp_path / "led.json")
    ck = str(tmp_path / "ck")
    inj = FaultInjector()
    inj.arm("sweep_segment", after=2)
    _service(ds, base=SEGMENTED, rounds=30, es=30, ledger_path=lp,
             checkpoint_dir=ck, injector=inj, clock=FROZEN_CLOCK).run()
    # same units (uid keys on bucket+indices), different sweep statics:
    # the grid_digest in the checkpoint meta must reject the restore
    if os.path.exists(lp):  # fault may land before the first commit
        os.unlink(lp)
    r2 = SweepService(GRID, ds, base_params=SEGMENTED,
                      num_boost_round=30, nfold=3,
                      early_stopping_rounds=30, seed=1, ledger_path=lp,
                      checkpoint_dir=ck, clock=FROZEN_CLOCK).run()
    assert r2.completed and r2.resumed_units == 0


def test_service_validation():
    ds = _dataset()
    with pytest.raises(ValueError, match="engine"):
        _service(ds, engine="gpu")
    with pytest.raises(ValueError, match="nfold"):
        SweepService(GRID, ds, base_params=BASE, nfold=1)
    with pytest.raises(ValueError, match="grid"):
        SweepService([], ds, base_params=BASE)


def test_rdata_ledger_bytes_are_filename_independent(tmp_path):
    # the gzip wrapper must pin mtime AND FNAME: ledgers written through
    # differently-named tmp siblings still compare byte-equal
    cols = {"iteration": [1.0], "score": [-0.5], "num_leaves": [7.0]}
    a, b = str(tmp_path / "one.RData"), str(tmp_path / "two.RData")
    write_rdata(a, "paramGrid", cols)
    write_rdata(b, "paramGrid", cols)
    with open(a, "rb") as f:
        ba = f.read()
    with open(b, "rb") as f:
        bb = f.read()
    assert ba == bb
    assert gzip.decompress(ba) == gzip.decompress(bb)


# -- daemon: sweep -> canary -> flip retune loop -------------------------

DPARAMS = {"objective": "regression", "metric": "l2", "num_leaves": 7,
           "learning_rate": 0.3, "verbose": -1, "min_data_in_leaf": 5}


def _push_block(feed, rng, n=200):
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2
         + rng.normal(0, 0.1, n)).astype(np.float32)
    feed.push(X, y)


def _sweep_daemon(state_dir, clk, feed, *, sweep_every=2, injector=None):
    return RefreshDaemon(DPARAMS, str(state_dir), feed=feed, clock=clk,
                         refresh_rounds=5, initial_rounds=10,
                         sweep_grid=GRID, sweep_every=sweep_every,
                         sweep_rounds=15, sweep_nfold=3,
                         sweep_early_stopping=15, injector=injector)


def test_daemon_retunes_every_n_flips(tmp_path):
    rng = np.random.default_rng(0)
    clk = SimClock()
    feed = ArrivalFeed(clock=clk)
    d = _sweep_daemon(tmp_path, clk, feed)
    evs = []
    for _ in range(4):
        _push_block(feed, rng)
        clk.advance(1.0)
        evs.extend(d.run_until_idle())
    names = [e["event"] for e in evs]
    assert names == ["flipped", "flipped", "retuned", "flipped"]
    ret = next(e for e in evs if e["event"] == "retuned")
    assert ret["winner"] in [dict(c) for c in GRID]
    assert ret["sweep_units"] >= 1 and ret["tune_s"] >= 0
    # the promoted config is live: subsequent refreshes train with it
    assert d.params["num_leaves"] == ret["winner"]["num_leaves"]
    snap = d.snapshot()
    assert snap["flips_since_sweep"] == 1  # one flip after the retune
    dec = d.tracker.record(ret["generation"]).decomposition()
    assert "tune" in dec and dec["tune"] >= 0
    assert "tune" not in d.tracker.record(1).decomposition()


def test_daemon_sweep_promote_fault_retries_to_retuned(tmp_path):
    rng = np.random.default_rng(1)
    clk = SimClock()
    feed = ArrivalFeed(clock=clk)
    inj = FaultInjector()
    inj.arm("sweep_promote")
    d = _sweep_daemon(tmp_path, clk, feed, sweep_every=1, injector=inj)
    _push_block(feed, rng)
    e1 = d.run_until_idle()
    assert [e["event"] for e in e1] == ["flipped"]
    _push_block(feed, rng)
    e2 = d.run_until_idle()
    names = [e["event"] for e in e2]
    assert "preempted" in names and names[-1] == "retuned"
    pre = next(e for e in e2 if e["event"] == "preempted")
    assert pre["phase"] == "sweep_promote"


def test_daemon_retune_hook_and_validation(tmp_path):
    rng = np.random.default_rng(2)
    clk = SimClock()
    feed = ArrivalFeed(clock=clk)
    bare = RefreshDaemon(DPARAMS, str(tmp_path / "bare"), feed=feed,
                         clock=clk, refresh_rounds=5, initial_rounds=10)
    with pytest.raises(ValueError, match="sweep_grid"):
        bare.retune()
    with pytest.raises(ValueError, match="sweep_grid"):
        RefreshDaemon(DPARAMS, str(tmp_path / "bad"), feed=feed,
                      clock=clk, sweep_every=2)

    d = _sweep_daemon(tmp_path / "d", clk, feed, sweep_every=0)
    _push_block(feed, rng)
    assert [e["event"] for e in d.run_until_idle()] == ["flipped"]
    _push_block(feed, rng)
    ev = d.retune()  # operator-forced sweep, no cadence needed
    assert ev["event"] == "retuned"
    assert d.snapshot()["flips_since_sweep"] == 0


# -- task=sweep CLI contract ---------------------------------------------


@pytest.fixture
def cli_env(tmp_path):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(300, 4))
    y = X[:, 0] + 0.3 * X[:, 1] + rng.normal(0, 0.1, 300)
    data = str(tmp_path / "train.csv")
    np.savetxt(data, np.column_stack([y, X]), delimiter=",", fmt="%.6g")
    grid = str(tmp_path / "grid.json")
    with open(grid, "w") as f:
        json.dump({"axes": {"learning_rate": [0.3, 0.1],
                            "num_leaves": [7, 15]}}, f)
    return tmp_path, data, grid


def test_sweep_cli_end_to_end(cli_env):
    tmp_path, data, grid = cli_env
    cfg = {"sweep_grid": grid, "ledger": str(tmp_path / "led.json"),
           "sweep_checkpoint_dir": str(tmp_path / "ck"),
           "num_trees": "20", "nfold": "3", "objective": "regression",
           "metric": "l2", "verbose": "-1"}
    out, err = io.StringIO(), io.StringIO()
    assert _sweep(cfg, data, False, "0", stdout=out, stderr=err) == 0
    board = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert len(board) == 4
    assert board[0]["score"] == max(r["score"] for r in board)
    summary = json.loads(err.getvalue().splitlines()[-1])
    assert summary["configs"] == 4 and summary["engine"] == "fused"


def test_sweep_cli_typed_errors(cli_env):
    tmp_path, data, grid = cli_env

    def check(match, **over):
        cfg = {"sweep_grid": grid}
        cfg.update(over)
        dp = cfg.pop("data", data)
        with pytest.raises(SystemExit, match=match):
            _sweep(cfg, dp, False, "0", stdout=io.StringIO(),
                   stderr=io.StringIO())

    check("requires data", data=None)
    check("requires sweep_grid", sweep_grid=None)
    check("unreadable", sweep_grid=str(tmp_path / "missing.json"))
    check("must be an integer", sweep_devices="x")
    check(">= 1", sweep_devices="0")
    check("divide", sweep_devices="4", sweep_group_size="3")
    check("auto|fused|host", engine="gpu")
    check("unknown key", bogus_key="1")
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        f.write("{not json")
    check("not valid JSON", sweep_grid=bad)
    empty = str(tmp_path / "empty.json")
    with open(empty, "w") as f:
        json.dump({"axes": {"learning_rate": []}}, f)
    check("non-empty lists", sweep_grid=empty)
    # refresh-side validation: cadence without a grid is a typed error
    with pytest.raises(SystemExit, match="requires sweep_grid"):
        cli_main(["task=refresh", f"watch_dir={tmp_path}",
                  f"state_dir={tmp_path / 's'}", "sweep_every=2"])


# -- budgets + registry --------------------------------------------------


def test_sweep_sites_registered_and_in_union():
    assert SWEEP_SITES == ("sweep_segment", "sweep_record",
                          "sweep_promote")
    assert set(SWEEP_SITES) <= set(SITES)


def test_sweep_budgets_all_green():
    results = check_sweep_budgets()
    assert len(results) == len(SWEEP_BUDGETS) == 5
    assert all(r["ok"] for r in results), results
    by = {r["name"]: r for r in results}
    # the mesh beats serial by >= 2x; batching alone by >= 1.5x
    assert by["sweep_speedup_d8"]["measured"] >= 2.0
    assert by["sweep_fused_gain_d1"]["measured"] >= 1.5
    # closed-loop: fused D=8 inside the tune->serve SLO, serial outside
    assert by["sweep_tune_serve_slo"]["measured"] <= 300.0
    assert by["sweep_serial_blows_tune_slo"]["cmp"] == "ge"
    assert by["sweep_serial_blows_tune_slo"]["measured"] > 300.0
    with pytest.raises(KeyError):
        sweep_budget_by_name("nope")


def test_sweep_time_model_shape():
    t1 = sweep_time_model(n_devices=1)
    t8 = sweep_time_model(n_devices=8)
    assert t8["makespan_s"] < t1["makespan_s"] < t1["serial_s"]
    assert t8["chain_buckets"] == 2  # ceil(9 buckets / 8 groups)
    s = sweep_staleness_model(n_devices=8)
    assert s["tune_serve_s"] == pytest.approx(
        s["sweep_s"] + s["winner_train_s"] + s["publish_s"]
        + s["warm_s"] + s["canary_s"] + s["flip_s"])
    assert sweep_staleness_model(serial=True)["sweep_s"] \
        == pytest.approx(t1["serial_s"])


def test_budget_anchors_cover_sweep_package():
    assert "sweep" in BUDGET_ANCHORS
    res = [r for r in check_budget_anchors()
           if r["name"].startswith("sweep:")]
    assert res and all(r["ok"] for r in res)

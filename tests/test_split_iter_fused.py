"""Split-iteration mega-kernel parity (r7 tentpole).

The fused strict grower replaces the XLA ``find_best_split`` + packed
node-table update with one Pallas call per split iteration
(``split_iter_pallas``).  These tests pin the kernel to the XLA
semantics:

* kernel-level: identical histogram + table inputs -> bitwise-identical
  new packed table and next-leaf pick vs an XLA reference built from
  ``find_best_split`` (regression fixture);
* tree-level: ``fuse_split=True`` vs ``False`` trees are bitwise equal
  on structure, thresholds, leaf values, counts and row routing —
  unbatched, under the multiclass class-vmap, and under the
  hyperparameter-batched E-sweep.  The stored ``split_gain`` diagnostic
  alone is compared to ~2 ulp: the two programs compile ``hist_fn`` in
  different fusion contexts (the fused path feeds a transpose into the
  Pallas operand) and XLA:CPU's accumulation order is not bitwise
  stable across contexts.  Given identical histogram bits the kernel
  matches exactly (first test);
* categorical fixtures gate the fusion off and must stay on the byte-
  identical XLA path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.models.tree import _PK, _packed_root_table, grow_tree
from lightgbm_tpu.ops.histogram_pallas import split_iter_pallas
from lightgbm_tpu.ops.split import (CatInfo, SplitContext,
                                    constrained_leaf_output, find_best_split)


def make_ctx(l1=0.1, l2=1.0, min_data=3.0, min_hess=1e-3, min_gain=0.0,
             mds=0.5, ps=1.5):
    return SplitContext(
        lambda_l1=jnp.float32(l1), lambda_l2=jnp.float32(l2),
        min_data_in_leaf=jnp.float32(min_data),
        min_sum_hessian=jnp.float32(min_hess),
        min_gain_to_split=jnp.float32(min_gain),
        max_delta_step=jnp.float32(mds), path_smooth=jnp.float32(ps))


def reg_fixture(seed=3, n=300, num_features=7, num_bins=16):
    rng = np.random.RandomState(seed)
    bins = jnp.asarray(rng.randint(0, num_bins, size=(n, num_features)),
                       jnp.int32)
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    stats = jnp.stack([g, jnp.ones(n, jnp.float32),
                       jnp.ones(n, jnp.float32)], -1)
    return bins, stats, jnp.ones(num_features, jnp.float32)


def assert_trees_equal(t1, t0, r1, r0, gain_ulp=False):
    for f in t1._fields:
        a, b = getattr(t1, f), getattr(t0, f)
        if a is None:
            assert b is None
            continue
        a, b = np.asarray(a), np.asarray(b)
        if gain_ulp and f == "split_gain":
            np.testing.assert_allclose(a, b, rtol=5e-7, atol=0.0,
                                       err_msg=f)
        else:
            np.testing.assert_array_equal(a, b, err_msg=f)
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r0))


def _xla_split_iter_ref(P, hist2, ctx, fmask, max_depth, n_nodes, capacity):
    """XLA reference for one split iteration: pick the best expandable
    leaf, score both children with ``find_best_split`` and apply the
    one-row-gather / three-row-scatter table update — same code shape as
    the pre-r7 strict grower body."""
    K = _PK
    neg_inf = jnp.float32(-jnp.inf)
    gains = jnp.where(P[:, K.IS_LEAF] > 0.5, P[:, K.CAND_GAIN], neg_inf)
    leaf = jnp.argmax(gains).astype(jnp.int32)
    active = jnp.isfinite(gains[leaf])
    nl, nr = n_nodes, n_nodes + 1
    row = P[leaf]
    feat = row[K.CAND_FEAT]
    thr = row[K.CAND_BIN]
    gain = row[K.CAND_GAIN]
    wl_v, wr_v = row[K.CAND_WL], row[K.CAND_WR]
    lo, hi = row[K.BOUND_LO], row[K.BOUND_HI]
    child_depth = row[K.DEPTH] + 1.0
    depth_ok = (max_depth <= 0) | (child_depth < max_depth.astype(jnp.float32))

    def score(h, lo_, hi_, po):
        return find_best_split(h, ctx, fmask, depth_ok, None, None,
                               lo_, hi_, po)

    bs = jax.vmap(score)(hist2, jnp.stack([lo, lo]), jnp.stack([hi, hi]),
                         jnp.stack([wl_v, wr_v]))
    leaf_row = row.at[jnp.array([K.SPLIT_FEAT, K.SPLIT_BIN, K.LEFT, K.RIGHT,
                                 K.IS_LEAF, K.SPLIT_GAIN])].set(
        jnp.stack([feat, thr, nl.astype(jnp.float32),
                   nr.astype(jnp.float32), jnp.float32(0.0), gain]))
    two = lambda a, b: jnp.stack([a, b])
    child_rows = jnp.stack([
        jnp.full((2,), -1.0), jnp.zeros((2,)), jnp.full((2,), -1.0),
        jnp.full((2,), -1.0), two(wl_v, wr_v), jnp.ones((2,)),
        two(row[K.CAND_LC], row[K.CAND_RC]), jnp.zeros((2,)),
        jnp.full((2,), child_depth), bs.gain, bs.feature.astype(jnp.float32),
        bs.bin.astype(jnp.float32), bs.left_g, bs.left_h, bs.left_c,
        bs.right_g, bs.right_h, bs.right_c, bs.left_out, bs.right_out,
        two(lo, lo), two(hi, hi), jnp.zeros((2,)),
        jnp.minimum(row[K.PM], bs.gain)], axis=-1)
    oob = jnp.int32(capacity)
    P = P.at[jnp.where(active, leaf, oob)].set(leaf_row, mode="drop")
    P = P.at[jnp.where(active, jnp.stack([nl, nr]), oob)].set(
        child_rows, mode="drop")
    return P


def test_kernel_bitmatches_xla_one_iteration():
    rng = np.random.RandomState(7)
    F, B, num_leaves = 9, 32, 15
    cap = 2 * num_leaves - 1
    ctx = make_ctx()
    fmask = jnp.ones(F, jnp.float32)
    hist2 = jnp.asarray((rng.randn(2, F, B, 3).astype(np.float32)) ** 2)
    root_hist = hist2[0] + hist2[1]
    root_tot = jnp.sum(root_hist.sum(0), axis=0)
    root_out = constrained_leaf_output(
        root_tot[0], root_tot[1], root_tot[2],
        ctx._replace(path_smooth=jnp.float32(0.0)),
        jnp.float32(-jnp.inf), jnp.float32(jnp.inf), jnp.float32(0.0))
    root_best = find_best_split(root_hist, ctx, fmask, jnp.bool_(True), None,
                                parent_out=root_out)
    tab = _packed_root_table(cap, root_out, root_tot, root_best, None)
    aux = jnp.stack([jnp.float32(0), root_best.feature.astype(jnp.float32),
                     root_best.bin.astype(jnp.float32),
                     jnp.isfinite(root_best.gain).astype(jnp.float32),
                     jnp.float32(0), jnp.float32(0), jnp.float32(0),
                     jnp.float32(0)]).reshape(1, 8)
    md = jnp.int32(0)
    n_nodes = jnp.int32(1)

    def both():
        scal = jnp.concatenate([jnp.stack([
            ctx.lambda_l1, ctx.lambda_l2, ctx.min_data_in_leaf,
            ctx.min_sum_hessian, ctx.min_gain_to_split, ctx.max_delta_step,
            ctx.path_smooth, md.astype(jnp.float32),
            n_nodes.astype(jnp.float32)]), jnp.zeros(7)]).reshape(1, 16)
        Pk, auxk = split_iter_pallas(hist2.transpose(0, 1, 3, 2), tab,
                                     fmask.reshape(1, F), aux, scal, pk=_PK)
        Px = _xla_split_iter_ref(tab, hist2, ctx, fmask, md, n_nodes, cap)
        return Pk, Px, auxk

    Pk, Px, auxk = jax.jit(both)()
    np.testing.assert_array_equal(np.asarray(Pk), np.asarray(Px))
    # next-pick aux mirrors the XLA leaf selection on the updated table
    K = _PK
    Px_np = np.asarray(Px)
    gains = np.where(Px_np[:, K.IS_LEAF] > 0.5, Px_np[:, K.CAND_GAIN],
                     -np.inf)
    leaf_n = int(np.argmax(gains))
    a = np.asarray(auxk)[0]
    assert int(a[0]) == leaf_n
    assert a[1] == Px_np[leaf_n, K.CAND_FEAT]
    assert a[2] == Px_np[leaf_n, K.CAND_BIN]
    assert bool(a[3]) == bool(np.isfinite(gains[leaf_n]))


def test_tree_parity_regression_unbatched():
    bins, stats, fmask = reg_fixture()
    ctx = make_ctx()
    t1, r1 = jax.jit(lambda: grow_tree(bins, stats, fmask, ctx, 31, 16, 0,
                                       fuse_split=True))()
    t0, r0 = jax.jit(lambda: grow_tree(bins, stats, fmask, ctx, 31, 16, 0,
                                       fuse_split=False))()
    assert_trees_equal(t1, t0, r1, r0, gain_ulp=True)


def test_tree_parity_early_stop():
    # min_data_in_leaf so large growth stalls before the leaf budget:
    # the active flag must kill all remaining iterations identically.
    bins, stats, fmask = reg_fixture()
    ctx = make_ctx(min_data=120.0)
    t1, r1 = jax.jit(lambda: grow_tree(bins, stats, fmask, ctx, 63, 16, 0,
                                       fuse_split=True))()
    t0, r0 = jax.jit(lambda: grow_tree(bins, stats, fmask, ctx, 63, 16, 0,
                                       fuse_split=False))()
    assert_trees_equal(t1, t0, r1, r0, gain_ulp=True)
    assert int(t1.num_leaves) < 63


def test_tree_parity_multiclass_vmap():
    rng = np.random.RandomState(11)
    n, F, B = 400, 7, 16
    bins = jnp.asarray(rng.randint(0, B, size=(n, F)), jnp.int32)
    gm = jnp.asarray(rng.randn(3, n).astype(np.float32))
    sm = jnp.stack([gm, jnp.ones((3, n), jnp.float32),
                    jnp.ones((3, n), jnp.float32)], axis=-1)
    fmask = jnp.ones(F, jnp.float32)
    ctx = make_ctx()

    def grow(fs):
        return jax.vmap(lambda s: grow_tree(bins, s, fmask, ctx, 15, B, 0,
                                            fuse_split=fs))(sm)

    t1, r1 = jax.jit(lambda: grow(True))()
    t0, r0 = jax.jit(lambda: grow(False))()
    assert_trees_equal(t1, t0, r1, r0, gain_ulp=True)


def test_tree_parity_hyper_vmap_sweep():
    # fused-CV-style E-batch: hyperparameters vary across the batch axis.
    bins, stats, fmask = reg_fixture()
    E = 5
    l1s = jnp.asarray(np.linspace(0.0, 0.4, E), jnp.float32)
    mds = jnp.asarray([0, 4, 6, 0, 5], jnp.int32)

    def grow(l1, md, fs):
        ctx = make_ctx(l1=l1)
        return grow_tree(bins, stats, fmask, ctx, 31, 16, md, fuse_split=fs)

    t1, r1 = jax.jit(jax.vmap(lambda a, b: grow(a, b, True)))(l1s, mds)
    t0, r0 = jax.jit(jax.vmap(lambda a, b: grow(a, b, False)))(l1s, mds)
    assert_trees_equal(t1, t0, r1, r0, gain_ulp=True)


def test_categorical_fixture_gates_off_identically():
    # cat_info forces the XLA path; fuse_split=True must be a no-op.
    rng = np.random.RandomState(5)
    n, F, B = 500, 4, 24
    bins = jnp.asarray(rng.randint(0, B, size=(n, F)), jnp.int32)
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    stats = jnp.stack([g, jnp.ones(n, jnp.float32),
                       jnp.ones(n, jnp.float32)], -1)
    fmask = jnp.ones(F, jnp.float32)
    cat = CatInfo(is_cat=jnp.zeros(F, bool).at[0].set(True),
                  cat_smooth=jnp.float32(10.0), cat_l2=jnp.float32(10.0),
                  max_cat_threshold=8)
    ctx = make_ctx(ps=0.0, mds=0.0)
    t1, r1 = jax.jit(lambda: grow_tree(bins, stats, fmask, ctx, 15, B, 0,
                                       cat_info=cat, fuse_split=True))()
    t0, r0 = jax.jit(lambda: grow_tree(bins, stats, fmask, ctx, 15, B, 0,
                                       cat_info=cat, fuse_split=False))()
    assert_trees_equal(t1, t0, r1, r0)
    assert bool(np.asarray(t1.is_cat_split).any())

"""Deliberately-broken graftlint fixture for the check.sh v3 lane.

tools/check.sh lints THIS file with ``--format github`` and asserts the
run exits 1 with ``::error`` annotations carrying the expected rule ids
— proving the v3 families run and the CI annotation format holds.

The default lint pass never sees this file: ``fixtures`` is in the
engine's ``_SKIP_DIRS`` and pytest doesn't collect it (no ``test_``
prefix).  Only explicit-path invocations lint it.
"""

import jax.numpy as jnp
from jax import lax


def merge_without_mesh(hist):
    # GL012: literal axis, no shard_map/pmap reaches this function
    return lax.psum(hist, "data")


def route_in_mixed_space(rows, thresholds, scale):
    # GL013: u8 bin codes compared against dequantized f32 thresholds
    codes = rows.astype(jnp.uint8)
    deq = thresholds.astype(jnp.float32) * scale
    return codes <= deq

"""Data-parallel training over a virtual 8-device CPU mesh.

Validates the psum histogram merge path (SURVEY.md §4: "test the psum path
with multi-device simulation"): a row-sharded training step must produce
bit-identical trees to the single-device grower, because split decisions are
computed from the psum-merged histograms on every shard.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.config import Params
from lightgbm_tpu.models.gbdt import HyperScalars
from lightgbm_tpu.models.tree import grow_tree
from lightgbm_tpu.ops.split import SplitContext
from lightgbm_tpu.parallel.data_parallel import (
    make_dp_train_step,
    make_mesh,
    shard_rows,
)

OBJ_KEY = ("regression", 1.0, 1.0, 0.9, 1.0, 0.7, 30, True, 1)


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(0)
    n, f, b = 1024, 5, 16
    bins = rng.integers(0, b, (n, f)).astype(np.uint8)
    y = (bins[:, 0] * 0.5 + np.sin(bins[:, 1].astype(float))
         + rng.normal(0, 0.1, n)).astype(np.float32)
    return bins, y, b


def _run_dp(problem, n_devices, num_leaves=15):
    bins_np, y_np, num_bins = problem
    n = len(y_np)
    mesh = make_mesh(n_devices)
    step = make_dp_train_step(mesh, OBJ_KEY, num_leaves, num_bins)
    bins, y, w, bag, pred = shard_rows(
        mesh, jnp.asarray(bins_np), jnp.asarray(y_np),
        jnp.ones(n, jnp.float32), jnp.ones(n, jnp.float32),
        jnp.zeros(n, jnp.float32))
    fmask = jnp.ones(bins_np.shape[1], jnp.float32)
    hyper = HyperScalars.from_params(Params())
    tree, new_pred = step(bins, y, w, bag, pred, fmask, hyper,
                          jax.random.PRNGKey(0))
    return jax.device_get(tree), np.asarray(new_pred)


def test_eight_device_matches_single_device(problem):
    assert len(jax.devices()) >= 8, "conftest must provide 8 CPU devices"
    tree1, pred1 = _run_dp(problem, 1)
    tree8, pred8 = _run_dp(problem, 8)
    np.testing.assert_array_equal(tree1.split_feature, tree8.split_feature)
    np.testing.assert_array_equal(tree1.split_bin, tree8.split_bin)
    np.testing.assert_allclose(tree1.leaf_value, tree8.leaf_value,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(pred1, pred8, rtol=1e-5, atol=1e-6)


def test_dp_matches_unsharded_grower(problem):
    bins_np, y_np, num_bins = problem
    n = len(y_np)
    tree8, _ = _run_dp(problem, 8)
    stats = jnp.stack([jnp.asarray(-y_np), jnp.ones(n), jnp.ones(n)],
                      axis=-1)
    ctx = SplitContext(
        lambda_l1=jnp.float32(0.0), lambda_l2=jnp.float32(0.0),
        min_data_in_leaf=jnp.float32(20.0), min_sum_hessian=jnp.float32(1e-3),
        min_gain_to_split=jnp.float32(0.0))
    tree_ref, _ = grow_tree(jnp.asarray(bins_np), stats,
                            jnp.ones(bins_np.shape[1]), ctx, 15, num_bins,
                            max_depth=-1)
    tree_ref = jax.device_get(tree_ref)
    np.testing.assert_array_equal(tree_ref.split_feature, tree8.split_feature)
    np.testing.assert_array_equal(tree_ref.split_bin, tree8.split_bin)


def test_dryrun_multichip_entrypoint():
    import sys

    sys.path.insert(0, ".")
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_train_api_tree_learner_data_matches_serial():
    """lgb.train(tree_learner='data') on the 8-device mesh must produce the
    same model as serial training (VERDICT r1 item 6: user-reachable DP)."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(7)
    n = 3000
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 3) + X[:, 2] * X[:, 3]
         + rng.normal(0, 0.1, n)).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.2, "verbosity": -1}

    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=12)
    dp = lgb.train(dict(params, tree_learner="data"),
                   lgb.Dataset(X, label=y), num_boost_round=12)
    assert dp._dp_mesh is not None, "DP path must engage on the 8-dev mesh"

    for ts, td in zip(serial.trees, dp.trees):
        np.testing.assert_array_equal(np.asarray(ts.split_feature),
                                      np.asarray(td.split_feature))
        np.testing.assert_array_equal(np.asarray(ts.split_bin),
                                      np.asarray(td.split_bin))
    np.testing.assert_allclose(serial.predict(X), dp.predict(X),
                               rtol=1e-5, atol=1e-5)


def test_train_api_tree_learner_data_with_bagging():
    """DP training composes with bagging + feature_fraction (the sweep's
    stochastic knobs, r/gridsearchCV.R:97-99)."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(11)
    n = 2000
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] ** 2 + rng.normal(0, 0.1, n)).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 15,
              "bagging_fraction": 0.7, "bagging_freq": 2,
              "feature_fraction": 0.8, "verbosity": -1}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=10)
    dp = lgb.train(dict(params, tree_learner="data"),
                   lgb.Dataset(X, label=y), num_boost_round=10)
    assert dp._dp_mesh is not None
    np.testing.assert_allclose(serial.predict(X), dp.predict(X),
                               rtol=1e-4, atol=1e-4)


def test_train_api_tree_learner_feature_matches_serial():
    """lgb.train(tree_learner='feature') on the 8-device mesh: feature-
    sharded histograms + all_gather split exchange must reproduce the
    serial model (SURVEY.md §2C feature-parallel row)."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(23)
    n = 2500
    X = rng.normal(size=(n, 10)).astype(np.float32)  # 10 cols over 8 shards
    y = (X[:, 0] * 2 - X[:, 3] ** 2 + np.sin(X[:, 7] * 2)
         + rng.normal(0, 0.1, n)).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.2, "verbosity": -1,
              "grow_policy": "leafwise"}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=10)
    fp = lgb.train(dict(params, tree_learner="feature"),
                   lgb.Dataset(X, label=y), num_boost_round=10)
    assert fp._fp_mesh is not None, "FP path must engage on the 8-dev mesh"
    for ts, tf in zip(serial.trees, fp.trees):
        np.testing.assert_array_equal(np.asarray(ts.split_feature),
                                      np.asarray(tf.split_feature))
        np.testing.assert_array_equal(np.asarray(ts.split_bin),
                                      np.asarray(tf.split_bin))
    np.testing.assert_allclose(serial.predict(X), fp.predict(X),
                               rtol=1e-5, atol=1e-5)


def test_train_api_tree_learner_data_with_goss():
    """GOSS under the data-parallel mesh: per-shard compaction (upstream's
    per-machine sampling), psum-merged histograms; quality must be close
    to serial GOSS."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(31)
    n = 4000
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 3)
         + rng.normal(0, 0.1, n)).astype(np.float32)
    params = {"boosting": "goss", "objective": "regression",
              "num_leaves": 15, "learning_rate": 0.2, "verbosity": -1,
              "top_rate": 0.3, "other_rate": 0.2}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=15)
    dp = lgb.train(dict(params, tree_learner="data"),
                   lgb.Dataset(X, label=y), num_boost_round=15)
    assert dp._dp_mesh is not None
    r_serial = float(np.sqrt(np.mean((serial.predict(X) - y) ** 2)))
    r_dp = float(np.sqrt(np.mean((dp.predict(X) - y) ** 2)))
    # different sampling streams (per-shard), so compare quality bands
    assert r_dp < r_serial * 1.3, (r_dp, r_serial)


def test_dp_goss_tree_is_replicated_and_padding_free():
    """The DP GOSS regression pair: (a) per-node feature sampling must not
    desync shards (tree truly replicated — stored trees reproduce the
    booster's own train scores); (b) shards whose live rows < the static
    per-shard k must not inject padding rows into the histograms."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(41)
    n = 260  # pads to 512 -> shards 5-7 of the 8-dev mesh hold no live rows
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = (X[:, 0] * 2 + rng.normal(0, 0.1, n)).astype(np.float32)
    params = {"boosting": "goss", "objective": "regression",
              "num_leaves": 7, "learning_rate": 0.2, "verbosity": -1,
              "top_rate": 0.3, "other_rate": 0.2,
              "feature_fraction_bynode": 0.5, "tree_learner": "data"}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    assert b._dp_mesh is not None
    # (a) replication: replaying the stored trees equals the train scores
    import jax.numpy as jnp
    pred = np.full(n, b.init_score_, np.float32)
    for t in b.trees:
        from lightgbm_tpu.ops.predict import predict_tree_binned
        codes = jnp.asarray(
            b.train_set.bin_mapper.transform(X.astype(np.float64)))
        pred = pred + 0.2 * np.asarray(
            predict_tree_binned(t, codes, b.params.num_leaves))
    np.testing.assert_allclose(pred, np.asarray(b._pred_train)[:n],
                               rtol=1e-4, atol=1e-4)
    # (b) no fabricated counts: the root count equals the live row count
    root_count = float(np.asarray(b.trees[0].count)[0])
    assert root_count <= n + 1e-3, root_count


def test_dp_multiclass_matches_serial():
    """tree_learner='data' with multiclass: the class axis vmaps INSIDE the
    shard_map (per-class histogram psums batch into one collective) and the
    result must be bit-identical to serial training."""
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(13)
    n, F, K = 1024, 5, 3
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (np.argmax(X[:, :K] + 0.3 * rng.normal(size=(n, K)), axis=1)
         .astype(np.float32))
    params = {"objective": "multiclass", "num_class": K, "num_leaves": 7,
              "verbosity": -1, "min_data_in_leaf": 5}
    b_serial = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    b_dp = lgb.train({**params, "tree_learner": "data"},
                     lgb.Dataset(X, label=y), num_boost_round=5)
    np.testing.assert_allclose(b_serial.predict(X[:100]),
                               b_dp.predict(X[:100]), rtol=1e-5, atol=1e-6)


def test_dp_multiclass_goss_trains():
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(14)
    n, F, K = 2048, 4, 3
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = rng.integers(0, K, n).astype(np.float32)
    b = lgb.train({"objective": "multiclass", "num_class": K,
                   "boosting": "goss", "tree_learner": "data",
                   "num_leaves": 7, "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=4)
    p = b.predict(X[:50])
    assert p.shape == (50, K)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)


def test_dp_lambdarank_matches_serial():
    """tree_learner='data' with lambdarank: lambdas computed replicated
    (whole queries), growth sharded with psum-merged histograms — must
    match serial training."""
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(23)
    n_q, g_sz = 64, 16
    n = n_q * g_sz
    X = rng.normal(size=(n, 5)).astype(np.float32)
    rel = np.clip((X[:, 0] + 0.5 * X[:, 1]
                   + 0.3 * rng.normal(size=n)) * 1.2 + 1.5, 0, 4)
    y = np.floor(rel).astype(np.float32)
    group = np.full(n_q, g_sz)
    params = {"objective": "lambdarank", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 5}
    b_s = lgb.train(params, lgb.Dataset(X, label=y, group=group),
                    num_boost_round=5)
    b_d = lgb.train({**params, "tree_learner": "data"},
                    lgb.Dataset(X, label=y, group=group),
                    num_boost_round=5)
    np.testing.assert_allclose(b_s.predict(X[:100]), b_d.predict(X[:100]),
                               rtol=1e-4, atol=1e-5)


def test_train_api_tree_learner_data_with_categorical():
    """Categorical subset splits under the 8-device dp mesh must be
    bit-identical to serial (VERDICT r2 next-round item 6): the k-vs-rest
    scan runs on psum-merged histograms, so every shard commits the same
    subset masks."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(23)
    n, k = 4000, 24
    cat = rng.integers(0, k, n)
    # distinct per-category effects: symmetric patterns create exact gain
    # ties whose argmax depends on f32 summation order (psum vs serial)
    per_cat = rng.normal(0, 1.5, k)
    effect = per_cat[cat]
    dense = rng.normal(size=(n, 3)).astype(np.float32)
    y = (effect + 0.5 * dense[:, 0] + rng.normal(0, 0.1, n)).astype(np.float32)
    X = np.column_stack([cat.astype(np.float32), dense])
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.2, "verbosity": -1, "min_data_in_leaf": 5}

    serial = lgb.train(dict(params),
                       lgb.Dataset(X, label=y, categorical_feature=[0]),
                       num_boost_round=10)
    dp = lgb.train(dict(params, tree_learner="data"),
                   lgb.Dataset(X, label=y, categorical_feature=[0]),
                   num_boost_round=10)
    assert dp._dp_mesh is not None, "DP path must engage with categoricals"
    assert any(bool(np.asarray(t.is_cat_split).any()) for t in dp.trees)

    # The cat scan ranks categories by a g/h ratio sort; psum merges shard
    # histograms in a different f32 summation order than serial
    # accumulation, so near-tie subset boundaries and leaf-gain rankings
    # can flip (upstream's machine-allreduce has the same property).
    # Require the models to be equivalent in QUALITY, not bitwise.
    ps, pd = serial.predict(X), dp.predict(X)
    rmse_s = float(np.sqrt(np.mean((ps - y) ** 2)))
    rmse_d = float(np.sqrt(np.mean((pd - y) ** 2)))
    assert abs(rmse_s - rmse_d) < 0.02 * rmse_s, (rmse_s, rmse_d)
    assert float(np.mean(np.abs(ps - pd))) < 0.05


def test_2d_mesh_dp_fp_composition_matches_serial():
    """Stretch (VERDICT r2 item 9): rows x features 2-D mesh — histograms
    psum over 'data', split exchange over 'feature' — must reproduce the
    serial strict grower's model."""
    import jax
    import jax.numpy as jnp
    import lightgbm_tpu as lgb
    from lightgbm_tpu.models.gbdt import (HyperScalars,
                                          _objective_static_key)
    from lightgbm_tpu.config import parse_params
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.parallel.feature_parallel import (
        make_dp_fp_train_step, make_mesh_2d, pad_features)
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.default_rng(11)
    n, f = 2048, 6
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 3) + X[:, 2] * X[:, 3]
         + rng.normal(0, 0.1, n)).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.2, "verbosity": -1,
              "grow_policy": "leafwise"}

    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=5)

    ds = lgb.Dataset(X, label=y)
    ds.construct()
    p = parse_params(params)
    obj = create_objective(p)
    mesh = make_mesh_2d(4, 2)
    codes = pad_features(np.asarray(ds.X_binned), 2)
    fmask = np.zeros(codes.shape[1], np.float32)
    fmask[:f] = 1.0

    step = make_dp_fp_train_step(
        mesh, _objective_static_key(obj, p), p.num_leaves, ds.num_bins)
    bins_b = jax.device_put(jnp.asarray(codes),
                            NamedSharding(mesh, P("data", "feature")))
    fmask_d = jax.device_put(jnp.asarray(fmask),
                             NamedSharding(mesh, P("feature")))
    row = NamedSharding(mesh, P("data"))
    yd = jax.device_put(ds.y, row)
    wd = jax.device_put(ds.w, row)
    bag = jax.device_put(ds.row_mask, row)
    init = float(obj.init_score(np.asarray(ds.get_label()),
                                np.ones(ds.num_data())))
    pred = jax.device_put(jnp.full(ds.row_mask.shape, init, jnp.float32),
                          row)
    hyper = HyperScalars.from_params(p)
    trees = []
    for r in range(5):
        key = jax.random.fold_in(jax.random.PRNGKey(p.seed), r)
        tree, pred = step(bins_b, yd, wd, bag, pred, fmask_d, hyper, key)
        trees.append(tree)

    for ts, td in zip(serial.trees, trees):
        np.testing.assert_array_equal(np.asarray(ts.split_feature),
                                      np.asarray(td.split_feature))
        np.testing.assert_array_equal(np.asarray(ts.split_bin),
                                      np.asarray(td.split_bin))
        np.testing.assert_allclose(np.asarray(ts.leaf_value),
                                   np.asarray(td.leaf_value),
                                   rtol=2e-4, atol=2e-4)


def test_fp_multiclass_matches_serial():
    """tree_learner='feature' with multiclass (fp-supported since r4): the
    class axis vmaps inside the shard_map — per-class split-exchange
    all_gathers batch into one collective — and must match serial."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(31)
    n, F, K = 1024, 10, 3
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (np.argmax(X[:, :K] + 0.3 * rng.normal(size=(n, K)), axis=1)
         .astype(np.float32))
    params = {"objective": "multiclass", "num_class": K, "num_leaves": 7,
              "verbosity": -1, "min_data_in_leaf": 5,
              "grow_policy": "leafwise"}
    b_serial = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    b_fp = lgb.train({**params, "tree_learner": "feature"},
                     lgb.Dataset(X, label=y), num_boost_round=5)
    assert b_fp._fp_mesh is not None, "FP path must engage on the 8-dev mesh"
    np.testing.assert_allclose(b_serial.predict(X[:100]),
                               b_fp.predict(X[:100]), rtol=1e-5, atol=1e-6)


def test_fp_categorical_matches_serial():
    """tree_learner='feature' with categorical k-vs-rest splits
    (fp-supported since r4): the static is_cat mask slices per shard and
    the winning subset mask rides the split exchange; must match serial."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(37)
    n = 2000
    cat = rng.integers(0, 12, n).astype(np.float32)
    Xnum = rng.normal(size=(n, 9)).astype(np.float32)
    X = np.column_stack([cat, Xnum])
    effect = np.array([1.5, -2.0, 0.3, 2.2, -0.7, 0.0, 1.0, -1.2, 0.5,
                       -0.2, 0.8, -1.6])
    y = (effect[cat.astype(int)] + Xnum[:, 0]
         + rng.normal(0, 0.1, n)).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.2, "verbosity": -1,
              "grow_policy": "leafwise"}
    serial = lgb.train(dict(params),
                       lgb.Dataset(X, label=y, categorical_feature=[0]),
                       num_boost_round=8)
    fp = lgb.train(dict(params, tree_learner="feature"),
                   lgb.Dataset(X, label=y, categorical_feature=[0]),
                   num_boost_round=8)
    assert fp._fp_mesh is not None, "FP path must engage on the 8-dev mesh"
    for ts, tf in zip(serial.trees, fp.trees):
        np.testing.assert_array_equal(np.asarray(ts.split_feature),
                                      np.asarray(tf.split_feature))
    np.testing.assert_allclose(serial.predict(X), fp.predict(X),
                               rtol=1e-5, atol=1e-5)


def test_fp_wave_growth_matches_serial():
    """tree_learner='feature' with WAVE growth (r5): per-wave split
    exchange (one batched all_gather for all 2W children) + psum'd
    partition columns must reproduce the serial frontier grower's model,
    including the exact tail's overgrow + replay + prune."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(17)
    n, F = 8192, 10
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 3) + X[:, 2] * X[:, 3]
         + rng.normal(0, 0.1, n)).astype(np.float32)
    for tail in ("exact", "greedy"):
        params = {"objective": "regression", "num_leaves": 31,
                  "learning_rate": 0.2, "verbosity": -1,
                  "grow_policy": "frontier", "wave_tail": tail}
        b_serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                             num_boost_round=5)
        b_fp = lgb.train({**params, "tree_learner": "feature"},
                         lgb.Dataset(X, label=y), num_boost_round=5)
        assert b_fp._fp_mesh is not None, "FP path must engage"
        np.testing.assert_allclose(b_serial.predict(X[:512]),
                                   b_fp.predict(X[:512]),
                                   rtol=1e-5, atol=1e-6, err_msg=tail)


def test_dp_linear_tree_matches_serial():
    """linear_tree under tree_learner='data' (r5): constant-leaf growth
    shards rows with psum'd histograms, the per-leaf ridge systems merge
    with one psum of the Gram tensors, and the result must match serial
    linear-tree training."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(23)
    n, F = 2048, 6
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (1.5 * X[:, 0] + np.where(X[:, 1] > 0, 2 * X[:, 2], -X[:, 2])
         + 0.05 * rng.normal(size=n)).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.2, "verbosity": -1, "linear_tree": True,
              "linear_lambda": 0.01, "grow_policy": "leafwise"}
    b_serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                         num_boost_round=5)
    b_dp = lgb.train({**params, "tree_learner": "data"},
                     lgb.Dataset(X, label=y), num_boost_round=5)
    assert b_dp._dp_mesh is not None, "DP path must engage"
    ps, pd = b_serial.predict(X[:256]), b_dp.predict(X[:256])
    np.testing.assert_allclose(ps, pd, rtol=5e-4, atol=5e-5)

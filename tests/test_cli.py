"""Config-file CLI (__main__.py) — upstream ``lightgbm config=train.conf``."""

import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.__main__ import main, parse_argv, parse_config_text


@pytest.fixture(scope="module")
def csv_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli")
    rng = np.random.default_rng(0)
    n = 1200
    X = rng.normal(size=(n, 4))
    y = 2 * X[:, 0] - X[:, 1] + 0.1 * rng.normal(size=n)
    tr = np.column_stack([y[:1000], X[:1000]])
    va = np.column_stack([y[1000:], X[1000:]])
    trp, vap = str(d / "train.csv"), str(d / "valid.csv")
    np.savetxt(trp, tr, delimiter=",", fmt="%.8g")
    np.savetxt(vap, va, delimiter=",", fmt="%.8g")
    return d, trp, vap, X, y


def test_config_parsing():
    cfg = parse_config_text(
        "task = train\n# comment\nnum_leaves=15\nmetric = l2  # tail\n")
    assert cfg == {"task": "train", "num_leaves": "15", "metric": "l2"}
    with pytest.raises(ValueError):
        parse_argv(["notakeyvalue"])


def test_cli_train_and_predict(csv_files):
    d, trp, vap, X, y = csv_files
    model = str(d / "model.txt")
    conf = d / "train.conf"
    conf.write_text(
        f"task = train\ndata = {trp}\nvalid = {vap}\n"
        f"objective = regression\nnum_trees = 30\nnum_leaves = 15\n"
        f"verbosity = -1\noutput_model = {model}\n")
    assert main([f"config={conf}"]) == 0

    out = str(d / "preds.txt")
    assert main([f"config={conf}", "task=predict", f"data={vap}",
                 f"input_model={model}", f"output_result={out}"]) == 0
    pred = np.loadtxt(out)
    rmse = float(np.sqrt(np.mean((pred - y[1000:]) ** 2)))
    assert rmse < np.std(y) * 0.5, rmse
    # CLI overrides beat the config file (upstream precedence)
    b = lgb.Booster(model_file=model)
    assert b.num_trees() == 30


def test_cli_module_invocation(csv_files):
    """python -m lightgbm_tpu works end to end in a fresh process."""
    d, trp, vap, X, y = csv_files
    model = str(d / "model2.txt")
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "task=train", f"data={trp}",
         "objective=regression", "num_trees=5", "verbosity=-1",
         f"output_model={model}"],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-500:]
    assert "finished training" in r.stdout

"""Round-2 fixes: init_model continuation, leaf renewal, ADVICE items."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def reg_data():
    rng = np.random.default_rng(42)
    n = 2000
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 3) + 0.5 * X[:, 2] * X[:, 3]
         + rng.normal(0, 0.1, n)).astype(np.float32)
    return X, y


def _rmse(a, b):
    return float(np.sqrt(np.mean((a - b) ** 2)))


def test_init_model_continuation_matches_single_run(reg_data):
    """20 rounds == 10 rounds + init_model continuation of 10 more
    (same params, same data => identical trees)."""
    X, y = reg_data
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.2, "verbosity": -1}
    full = lgb.train(dict(params), lgb.Dataset(X, label=y),
                     num_boost_round=20)
    part = lgb.train(dict(params), lgb.Dataset(X, label=y),
                     num_boost_round=10)
    cont = lgb.train(dict(params), lgb.Dataset(X, label=y),
                     num_boost_round=10, init_model=part)
    assert cont.num_trees() == 20
    np.testing.assert_allclose(full.predict(X), cont.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_init_model_from_file_and_different_lr(reg_data):
    X, y = reg_data
    p1 = {"objective": "regression", "num_leaves": 15,
          "learning_rate": 0.3, "verbosity": -1}
    first = lgb.train(p1, lgb.Dataset(X, label=y), num_boost_round=8)
    pred_first = first.predict(X)
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.json")
        first.save_model(path)
        # continue with different lr AND different num_leaves
        p2 = {"objective": "regression", "num_leaves": 7,
              "learning_rate": 0.05, "verbosity": -1}
        cont = lgb.train(p2, lgb.Dataset(X, label=y), num_boost_round=5,
                         init_model=path)
    assert cont.num_trees() == 13
    # first 8 trees' contribution preserved exactly
    np.testing.assert_allclose(cont.predict(X, num_iteration=8), pred_first,
                               rtol=1e-4, atol=1e-5)
    # continuation improves training loss
    assert _rmse(cont.predict(X), y) < _rmse(pred_first, y)


def test_l1_leaf_renewal_beats_plain_surrogate(reg_data):
    """Median leaf renewal must improve MAE on a skewed-noise target."""
    X, _ = reg_data
    rng = np.random.default_rng(1)
    # heavy-tailed asymmetric noise: renewal matters here
    y = (X[:, 0] * 2 + rng.exponential(1.0, len(X)).astype(np.float32))
    params = {"objective": "l1", "num_leaves": 31, "learning_rate": 0.2,
              "verbosity": -1}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=60)
    mae = float(np.mean(np.abs(b.predict(X) - y)))
    # oracle check: sklearn LAD GBDT
    from sklearn.ensemble import HistGradientBoostingRegressor
    orc = HistGradientBoostingRegressor(
        loss="absolute_error", max_iter=60, learning_rate=0.2,
        max_leaf_nodes=31).fit(X, y)
    mae_orc = float(np.mean(np.abs(orc.predict(X) - y)))
    assert mae < mae_orc * 1.2, (mae, mae_orc)


def test_quantile_init_score_and_renewal(reg_data):
    """Quantile objective: init at the alpha-quantile + quantile renewal;
    the empirical coverage of predictions must approximate alpha."""
    X, _ = reg_data
    rng = np.random.default_rng(2)
    y = (X[:, 0] + rng.normal(0, 1.0, len(X))).astype(np.float32)
    for alpha in (0.1, 0.9):
        params = {"objective": "quantile", "alpha": alpha,
                  "num_leaves": 31, "learning_rate": 0.1, "verbosity": -1}
        b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=80)
        cover = float(np.mean(y <= b.predict(X)))
        assert abs(cover - alpha) < 0.06, (alpha, cover)


def test_pred_leaf_returns_leaf_ordinals(reg_data):
    X, y = reg_data
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    leaves = b.predict(X[:100], pred_leaf=True)
    assert leaves.shape == (100, 5)
    assert leaves.min() >= 0
    assert leaves.max() < 15  # ordinals in [0, num_leaves)


def test_feature_importance_explicit_iteration(reg_data):
    X, y = reg_data
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10)
    all_imp = b.feature_importance()
    assert all_imp.sum() == sum(
        int(np.sum(~np.asarray(t.is_leaf) & (np.asarray(t.left) >= 0)))
        for t in b.trees)
    half = b.feature_importance(iteration=5)
    assert half.sum() < all_imp.sum()
    gains = b.feature_importance(importance_type="gain")
    assert gains.dtype == np.float64 and gains.sum() > 0
    # informative feature 0 must dominate
    assert np.argmax(gains) == 0


def test_nan_at_predict_maps_to_zero_bin(reg_data):
    """Feature with no NaN at fit time: NaN at predict falls in the bin
    containing 0.0 (LightGBM missing->zero convention)."""
    X, y = reg_data
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10)
    Xq = X[:10].copy()
    Xz = Xq.copy(); Xz[:, 0] = 0.0
    Xn = Xq.copy(); Xn[:, 0] = np.nan
    np.testing.assert_allclose(b.predict(Xn), b.predict(Xz),
                               rtol=1e-5, atol=1e-6)


def test_dump_model_structure(reg_data):
    """dump_model(): traversable nested dict with raw-value thresholds."""
    X, y = reg_data
    params = {"objective": "regression", "num_leaves": 7, "verbosity": -1}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)
    d = b.dump_model()
    assert d["num_class"] == 1
    assert len(d["tree_info"]) == 3
    assert d["max_feature_idx"] == X.shape[1] - 1

    def walk(node, depth=0):
        if "leaf_value" in node:
            return 1
        assert node["decision_type"] == "<="
        assert isinstance(node["threshold"], float)
        return walk(node["left_child"]) + walk(node["right_child"])

    leaves = walk(d["tree_info"][0]["tree_structure"])
    assert leaves == d["tree_info"][0]["num_leaves"]
    # manual traversal of the dumped dict must reproduce predict()
    def traverse(node, row):
        while "leaf_value" not in node:
            node = (node["left_child"]
                    if row[node["split_feature"]] <= node["threshold"]
                    else node["right_child"])
        return node["leaf_value"]

    lr = 0.1
    manual = np.array([
        b.init_score_ + lr * sum(
            traverse(t["tree_structure"], X[i]) for t in d["tree_info"])
        for i in range(20)])
    np.testing.assert_allclose(manual, b.predict(X[:20]), rtol=1e-4,
                               atol=1e-5)

"""Gain-informed feature screening tests (ISSUE 20): EMA-FS.

Three contracts:

* EXACTNESS OFF — ``feature_screen="off"`` (and the degenerate
  ``keep_ratio=1.0`` screener) routes through the unified mask layer
  with a ``None``/all-ones base, so whole trained models are
  BIT-IDENTICAL (``np.array_equal``) to the pre-screening paths —
  strict and wave growers, in-memory and streamed.
* COMPACTION PARITY — with screening ON, the in-memory and streamed
  paths plan the same active sets and grow the same trees (histogram
  ``row_chunk`` pinned to the block size, the r7 accumulation-order
  rule), and winner ids are always GLOBAL feature ids.
* FRESHNESS — refresh rounds run the full feature set and observe
  gains, so a feature whose gain only emerges late re-enters the
  active set; without refreshes it provably never does.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.dataset import Dataset
from lightgbm_tpu.faults import ScreenScopeError


def _problem(n, f, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, f)).astype(np.float32)
    w = rng.normal(0, 1, f)
    logits = (X @ w) * 0.7 + 0.6 * np.sin(X[:, 0] * 2)
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    return X, y


def _trees_equal(a, b):
    for ta, tb in zip(a.trees, b.trees):
        for field in ("split_feature", "split_bin", "left", "right",
                      "leaf_value", "is_leaf"):
            if not np.array_equal(np.asarray(getattr(ta, field)),
                                  np.asarray(getattr(tb, field))):
                return False
    return len(a.trees) == len(b.trees)


def _train(X, y, extra, rounds=4):
    p = dict(objective="binary", num_leaves=15, learning_rate=0.1,
             max_bin=63, min_data_in_leaf=5, verbose=-1, seed=7)
    p.update(extra)
    bst = lgb.Booster(p, Dataset(X, label=y, params=dict(p)))
    for _ in range(rounds):
        bst.update()
    return bst


def _split_feature_set(bst):
    out = set()
    for t in bst.trees:
        sf = np.asarray(t.split_feature)
        out |= set(sf[sf >= 0].tolist())
    return out


# ---------------------------------------------------------------------------
# exactness off: the unified mask layer is bit-identical when not screening
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("grower", [{"wave_width": 1}, {"wave_width": 4}],
                         ids=["strict", "wave"])
@pytest.mark.parametrize("n,f", [(900, 5), (700, 13), (640, 136)])
def test_screen_off_bit_identical_strict_and_wave(grower, n, f):
    X, y = _problem(n, f)
    base = _train(X, y, grower)
    off = _train(X, y, dict(grower, feature_screen="off"))
    # keep_ratio=1.0 keeps every feature: the screener exists but can
    # never compact, so the full pipeline (plan/observe included) must
    # still be bit-identical to the unscreened program
    keep_all = _train(X, y, dict(grower, feature_screen="ema",
                                 screen_keep_ratio=1.0))
    for other in (off, keep_all):
        assert _trees_equal(base, other)
        assert np.array_equal(np.asarray(base._pred_train),
                              np.asarray(other._pred_train))


def test_screen_off_bit_identical_streamed():
    n, f, block_rows = 1800, 13, 512
    X, y = _problem(n, f)
    blocks = [(X[lo:lo + block_rows], y[lo:lo + block_rows])
              for lo in range(0, n, block_rows)]
    trained = []
    for extra in ({}, {"feature_screen": "off"}):
        p = dict(objective="binary", num_leaves=15, learning_rate=0.1,
                 max_bin=63, min_data_in_leaf=5, verbose=-1, seed=7,
                 stream_block_rows=block_rows, **extra)
        bst = lgb.Booster(p, Dataset.from_blocks(blocks,
                                                 params=dict(p)))
        for _ in range(4):
            bst.update()
        trained.append(bst)
    assert trained[0]._streamed and trained[1]._streamed
    assert _trees_equal(trained[0], trained[1])


# ---------------------------------------------------------------------------
# compaction parity: screened in-memory == screened streamed, global ids
# ---------------------------------------------------------------------------

SCREEN = dict(feature_screen="ema", screen_keep_ratio=0.3,
              screen_refresh_rounds=4, screen_ema_decay=0.9)


@pytest.mark.parametrize("grower", [{"wave_width": 1}, {"wave_width": 4}],
                         ids=["strict", "wave"])
def test_screened_in_memory_matches_streamed(grower):
    n, f, block_rows, rounds = 1800, 13, 512, 6
    X, y = _problem(n, f)
    base = dict(objective="binary", num_leaves=15, learning_rate=0.1,
                max_bin=63, min_data_in_leaf=5, verbose=-1, seed=7,
                **SCREEN, **grower)
    # accumulation-order rule (r7): pin the in-memory histogram chunking
    # to the streamed block size so partial sums add in the same order
    p_mem = dict(base, row_chunk=block_rows)
    p_st = dict(base, stream_block_rows=block_rows)
    mem = lgb.Booster(p_mem, Dataset(X, label=y, params=dict(p_mem)))
    blocks = [(X[lo:lo + block_rows], y[lo:lo + block_rows])
              for lo in range(0, n, block_rows)]
    st = lgb.Booster(p_st, Dataset.from_blocks(blocks, params=dict(p_st)))
    for _ in range(rounds):
        mem.update()
        st.update()
    assert st._streamed and mem._screener is not None
    assert _trees_equal(mem, st)
    assert np.array_equal(np.asarray(mem._pred_train),
                          np.asarray(st._pred_train))
    # compaction actually happened (keep=4 of 13) AND winners are global
    assert mem._screener.keep == 4
    for bst in (mem, st):
        feats = _split_feature_set(bst)
        assert feats and all(0 <= fid < f for fid in feats)


def test_screened_stream_moves_fewer_bytes():
    n, f, block_rows = 2048, 20, 512
    X, y = _problem(n, f, seed=3)
    blocks = [(X[lo:lo + block_rows], y[lo:lo + block_rows])
              for lo in range(0, n, block_rows)]
    streamed_bytes = []
    for extra in ({}, dict(SCREEN, screen_keep_ratio=0.25,
                           screen_refresh_rounds=3)):
        p = dict(objective="binary", num_leaves=15, learning_rate=0.1,
                 max_bin=63, min_data_in_leaf=5, verbose=-1, seed=7,
                 stream_block_rows=block_rows, **extra)
        bst = lgb.Booster(p, Dataset.from_blocks(blocks,
                                                 params=dict(p)))
        for _ in range(6):
            bst.update()
        streamed_bytes.append(bst.train_set.block_store.bytes_streamed)
    full, screened = streamed_bytes
    # ColumnViewStore slices host-side BEFORE device_put: 4 of 6 rounds
    # stream 5/20 columns, so PCIe bytes must drop well below full width
    assert screened < 0.6 * full, (screened, full)


# ---------------------------------------------------------------------------
# composition: screening x feature_fraction x bynode x EFB, one mask path
# ---------------------------------------------------------------------------

def _onehot_problem(n=2000, k=40, seed=5):
    rng = np.random.default_rng(seed)
    cat = rng.integers(0, k, n)
    onehot = np.zeros((n, k), np.float32)
    onehot[np.arange(n), cat] = 1.0
    dense = rng.normal(size=(n, 3)).astype(np.float32)
    X = np.concatenate([dense, onehot], axis=1)
    effect = rng.normal(0, 1.0, k)
    y = (dense[:, 0] + effect[cat]
         + rng.normal(0, 0.1, n)).astype(np.float32)
    return X, y


def test_screening_composes_with_ff_bynode_and_efb():
    X, y = _onehot_problem()
    ff = dict(feature_fraction=0.8, feature_fraction_bynode=0.7,
              objective="regression")
    on = _train(X, y, dict(ff, **dict(SCREEN, screen_refresh_rounds=3)),
                rounds=6)
    ds = on.train_set
    fb = int(ds.num_feature_)              # post-EFB training width
    assert fb < X.shape[1]                 # bundling really engaged
    assert on._screener is not None and on._screener.keep < fb
    feats = _split_feature_set(on)
    assert feats and all(0 <= fid < fb for fid in feats)
    # no double-masking: the degenerate keeper composes with BOTH
    # fraction draws bit-identically to the unscreened program (the
    # base-mask routing must not perturb either RNG stream)
    plain = _train(X, y, ff, rounds=6)
    keep_all = _train(X, y,
                      dict(ff, **dict(SCREEN, screen_keep_ratio=1.0)),
                      rounds=6)
    assert _trees_equal(plain, keep_all)


# ---------------------------------------------------------------------------
# freshness: refresh rounds rediscover late-gain features
# ---------------------------------------------------------------------------

def _late_gain_problem(n=2000, f=6, seed=11):
    """Feature 0 carries a big step, feature 5 a smaller one: stumps fit
    feature 0 first, and only once its residual has shrunk below the
    feature-5 step does feature 5's gain emerge — strictly later than
    round 0's EWMA snapshot."""
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, f)).astype(np.float32)
    y = (2.0 * (X[:, 0] > 0) + 0.6 * (X[:, 5] > 0)
         + rng.normal(0, 0.01, n)).astype(np.float32)
    return X, y


def test_refresh_rediscovers_late_gain_feature():
    X, y = _late_gain_problem()
    base = dict(objective="regression", num_leaves=2, learning_rate=0.5,
                max_bin=63, min_data_in_leaf=5, verbose=-1, seed=7,
                feature_screen="ema", screen_keep_ratio=0.15,  # keep=1
                screen_ema_decay=0.9)
    fresh = _train(X, y, dict(base, screen_refresh_rounds=3), rounds=12)
    assert fresh._screener.keep == 1
    # refreshes at rounds 3/6/9 rerun the full set; by then feature 0's
    # residual step (2.0 * 0.5^k) is below feature 5's 0.6 -> rediscovered
    assert 5 in _split_feature_set(fresh)
    # guard: with refreshes effectively disabled, the screened rounds
    # only ever see the round-0 winner — feature 5 can never re-enter
    stale = _train(X, y, dict(base, screen_refresh_rounds=1000),
                   rounds=12)
    assert 5 not in _split_feature_set(stale)
    assert 0 in _split_feature_set(stale)


# ---------------------------------------------------------------------------
# unit: the global-id remap and the scope fences
# ---------------------------------------------------------------------------

def test_remap_split_features_passes_sentinels_through():
    import collections

    import jax.numpy as jnp

    from lightgbm_tpu.models.feature_mask import remap_split_features

    T = collections.namedtuple("T", ["split_feature"])
    tree = T(split_feature=jnp.asarray([2, -1, 0, 1, -1], jnp.int32))
    out = remap_split_features(tree, np.asarray([4, 9, 130], np.int32))
    assert np.array_equal(np.asarray(out.split_feature),
                          [130, -1, 4, 9, -1])


@pytest.mark.parametrize("extra,key", [
    (dict(objective="multiclass", num_class=3), "num_class"),
    (dict(linear_tree=True), "linear_tree"),
    (dict(boosting="dart"), "boosting"),
    (dict(extra_trees=True), "extra_trees"),
    (dict(monotone_constraints=[1, 0, 0, 0, 0]), "monotone_constraints"),
    (dict(interaction_constraints=[[0, 1], [2, 3, 4]]),
     "interaction_constraints"),
    (dict(tree_learner="feature"), "tree_learner"),
])
def test_screen_scope_fences(extra, key):
    X, y = _problem(300, 5, seed=2)
    if extra.get("objective") == "multiclass":
        y = (np.abs(X[:, 0]) * 2).astype(np.int32) % 3
    p = dict(objective="binary", num_leaves=7, verbose=-1,
             feature_screen="ema")
    p.update(extra)
    with pytest.raises(ScreenScopeError) as ei:
        lgb.Booster(p, Dataset(X, label=y, params=dict(p)))
    assert ei.value.key == key


def test_screen_budget_lines_all_green():
    from lightgbm_tpu.analysis.budgets import (check_screen_budgets,
                                               feature_screen_time_model)

    res = check_screen_budgets()
    assert res and all(r["ok"] for r in res), res
    t = feature_screen_time_model()
    assert t["speedup_x"] >= 1.5 and t["f_active"] == 34.0
    # the exactness guards: both degenerate operating points collapse
    # to a 1x factor — the model never charges an unearned discount
    assert feature_screen_time_model(keep_ratio=1.0)["speedup_x"] == 1.0
    assert feature_screen_time_model(
        refresh_rounds=1)["avg_round_factor"] == 1.0

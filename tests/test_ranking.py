"""LambdaRank + NDCG (MSLR north-star config — VERDICT r1 item 4).

Synthetic ranked data: each query has docs with hidden utility; graded
relevance labels are a noisy discretization.  LambdaRank's NDCG@5 must
clearly beat a pointwise-regression baseline trained on the same features.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.ranking import (
    LambdaRank,
    RankEvalContext,
    _pack_groups,
    eval_ranking,
    ndcg_at_k,
)


def make_ranked(n_queries=120, docs_lo=8, docs_hi=24, f=6, seed=0):
    rng = np.random.default_rng(seed)
    sizes = rng.integers(docs_lo, docs_hi + 1, n_queries)
    n = int(sizes.sum())
    X = rng.normal(0, 1, (n, f))
    # hidden utility: nonlinear in the first three features
    u = (1.2 * X[:, 0] + np.sin(2 * X[:, 1]) + 0.6 * X[:, 2] ** 2
         + 0.3 * rng.normal(0, 1, n))
    # graded relevance 0..4 by within-query quantile
    y = np.zeros(n, np.float64)
    start = 0
    for s in sizes:
        q = u[start:start + s]
        ranks = q.argsort().argsort()
        y[start:start + s] = np.minimum(4, (5 * ranks) // s)
        start += s
    return X, y, sizes


def ndcg_of_scores(scores, y, sizes, k=5):
    doc_idx, valid = _pack_groups(sizes)
    gains = np.where(valid, (2.0 ** y[doc_idx] - 1) * valid, 0.0)
    s = jnp.asarray(np.where(valid, scores[doc_idx], -np.inf), jnp.float32)
    per_q = ndcg_at_k(s, jnp.asarray(gains, jnp.float32),
                      jnp.asarray(valid), k)
    return float(np.mean(np.asarray(per_q)))


def test_ndcg_metric_sanity():
    # perfect ordering -> 1.0; inverted ordering is worse
    sizes = np.array([5, 7])
    y = np.array([0, 1, 2, 3, 4, 0, 0, 1, 2, 3, 4, 4], np.float64)
    perfect = ndcg_of_scores(y.astype(np.float64), y, sizes)
    inverted = ndcg_of_scores(-y.astype(np.float64), y, sizes)
    assert perfect == pytest.approx(1.0, abs=1e-6)
    assert inverted < 0.8


def test_lambdarank_beats_pointwise():
    X, y, sizes = make_ranked()
    params = dict(objective="lambdarank", num_leaves=15, learning_rate=0.1,
                  min_data_in_leaf=5, verbosity=-1)
    ds = lgb.Dataset(X, label=y, group=sizes)
    rk = lgb.train(params, ds, num_boost_round=60)
    scores_rk = rk.predict(X)

    reg = lgb.train(dict(objective="regression", num_leaves=15,
                         learning_rate=0.1, min_data_in_leaf=5,
                         verbosity=-1),
                    lgb.Dataset(X, label=y), num_boost_round=60)
    scores_reg = reg.predict(X)

    n5_rk = ndcg_of_scores(scores_rk, y, sizes)
    n5_reg = ndcg_of_scores(scores_reg, y, sizes)
    assert n5_rk > 0.8
    assert n5_rk >= n5_reg - 0.005  # at least parity, usually clearly better

    # and it must clearly beat random ordering
    rng = np.random.default_rng(0)
    n5_rand = ndcg_of_scores(rng.normal(0, 1, len(y)), y, sizes)
    assert n5_rk > n5_rand + 0.1


def test_lambdarank_requires_group():
    X, y, _ = make_ranked(n_queries=10)
    with pytest.raises(ValueError, match="group"):
        lgb.train(dict(objective="lambdarank", verbosity=-1),
                  lgb.Dataset(X, label=y), num_boost_round=2)


def test_ndcg_eval_during_training():
    X, y, sizes = make_ranked(n_queries=60, seed=3)
    Xv, yv, sv = make_ranked(n_queries=20, seed=4)
    ds = lgb.Dataset(X, label=y, group=sizes)
    dv = lgb.Dataset(Xv, label=yv, group=sv)
    booster = lgb.train(dict(objective="lambdarank", num_leaves=15,
                             min_data_in_leaf=5, verbosity=-1,
                             eval_at=[3, 5]),
                        ds, num_boost_round=10, valid_sets=[dv],
                        valid_names=["va"])
    res = booster.eval_valid()
    names = {r[1] for r in res}
    assert names == {"ndcg@3", "ndcg@5"}
    assert all(r[3] for r in res)  # higher_better
    assert all(0.0 <= r[2] <= 1.0 for r in res)


def test_lambdarank_cv_group_aware():
    X, y, sizes = make_ranked(n_queries=40, seed=5)
    res = lgb.cv(dict(objective="lambdarank", num_leaves=7,
                      min_data_in_leaf=5, verbosity=-1, eval_at=[5]),
                 lgb.Dataset(X, label=y, group=sizes),
                 num_boost_round=8, nfold=3,
                 early_stopping_rounds=5)
    key = [k for k in res if k.endswith("-mean")]
    assert key, res.keys()
    assert res.best_iter >= 1
    # ndcg is higher-better: best_score must be positive (no sign flip)
    assert 0.0 < res.best_score <= 1.0


def test_lgbm_ranker_sklearn():
    X, y, sizes = make_ranked(n_queries=50, seed=7)
    from lightgbm_tpu.sklearn import LGBMRanker
    r = LGBMRanker(n_estimators=20, num_leaves=15, min_child_samples=5)
    r.fit(X, y, group=sizes)
    s = r.predict(X)
    assert s.shape == (len(y),)
    assert ndcg_of_scores(s, y, sizes) > 0.75


def test_truncation_level_changes_gradients():
    X, y, sizes = make_ranked(n_queries=30, seed=9)
    import jax
    from lightgbm_tpu.config import parse_params

    n = len(y)
    pred = jnp.asarray(np.random.default_rng(0).normal(0, 1, n), jnp.float32)
    w = jnp.ones(n, jnp.float32)

    def grads(trunc):
        p = parse_params(dict(objective="lambdarank",
                              lambdarank_truncation_level=trunc))
        obj = LambdaRank(p)
        obj.set_group(sizes, y, n)
        g, h = obj.grad_hess(pred, jnp.asarray(y, jnp.float32), w)
        return np.asarray(g)

    g_full = grads(30)
    g_t1 = grads(1)
    assert not np.allclose(g_full, g_t1)
    # gradients sum to ~0 per query (pairwise antisymmetry)
    assert abs(g_full.sum()) < 1e-2


def test_lambdarank_refit_with_group():
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(41)
    n_q, g_sz = 48, 12
    n = n_q * g_sz
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.clip(np.floor(X[:, 0] + 0.3 * rng.normal(size=n)) + 2,
                0, 4).astype(np.float32)
    group = np.full(n_q, g_sz)
    b = lgb.train({"objective": "lambdarank", "num_leaves": 7,
                   "verbosity": -1},
                  lgb.Dataset(X, label=y, group=group), num_boost_round=6)
    # refit on the second half (regrouped)
    half = n // 2
    ref = b.refit(X[half:], y[half:], group=np.full(n_q // 2, g_sz),
                  decay_rate=0.5)
    for t0, t1 in zip(b.trees, ref.trees):
        np.testing.assert_array_equal(np.asarray(t0.split_feature),
                                      np.asarray(t1.split_feature))
    assert not np.allclose(np.asarray(b.trees[0].leaf_value),
                           np.asarray(ref.trees[0].leaf_value))
    import pytest
    with pytest.raises(ValueError, match="group="):
        b.refit(X[half:], y[half:])


def _map_oracle(scores, y, sizes, k):
    """Numpy AP@k per query: binary relevance label>0, denominator
    min(num_relevant, k); empty-relevance queries count 1."""
    out = []
    start = 0
    for s in sizes:
        sc, yy = scores[start:start + s], y[start:start + s]
        start += s
        order = np.argsort(-sc, kind="stable")
        rel = (yy[order] > 0).astype(np.float64)
        npos = rel.sum()
        if npos == 0:
            out.append(1.0)
            continue
        hits = np.cumsum(rel)[:k]
        r = rel[:k]
        ap = np.sum(r * hits / (1.0 + np.arange(len(r)))) / min(npos, k)
        out.append(ap)
    return float(np.mean(out))


def test_map_matches_numpy_oracle():
    X, y, sizes = make_ranked(n_queries=50, seed=7)
    rng = np.random.default_rng(1)
    scores = rng.normal(0, 1, len(y))
    ds = lgb.Dataset(X, label=y, group=sizes)
    ds.construct()
    for k in (1, 3, 5, 10):
        got = eval_ranking(jnp.asarray(scores, jnp.float32), ds, [k],
                           metrics=("map",))
        assert got[0][0] == f"map@{k}"
        assert got[0][1] == pytest.approx(
            _map_oracle(scores, y, sizes, k), abs=1e-5)


def test_map_eval_and_early_stopping():
    X, y, sizes = make_ranked(n_queries=60, seed=3)
    Xv, yv, sv = make_ranked(n_queries=20, seed=4)
    ds = lgb.Dataset(X, label=y, group=sizes)
    dv = lgb.Dataset(Xv, label=yv, group=sv)
    booster = lgb.train(dict(objective="lambdarank", num_leaves=15,
                             min_data_in_leaf=5, verbosity=-1,
                             metric=["map"], eval_at=[5]),
                        ds, num_boost_round=8, valid_sets=[dv],
                        valid_names=["va"])
    res = booster.eval_valid()
    assert {r[1] for r in res} == {"map@5"}
    assert all(0.0 <= r[2] <= 1.0 for r in res)

    # early stopping driven by map must engage (higher_better respected)
    evals = {}
    booster2 = lgb.train(dict(objective="lambdarank", num_leaves=15,
                              min_data_in_leaf=5, verbosity=-1,
                              metric=["map"], eval_at=[5],
                              early_stopping_rounds=3),
                         ds, num_boost_round=200, valid_sets=[dv],
                         valid_names=["va"],
                         callbacks=[lgb.record_evaluation(evals)])
    assert booster2.best_iteration >= 1
    assert len(evals["va"]["map@5"]) < 200  # stopped early

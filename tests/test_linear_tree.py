"""linear_tree (ridge models in leaves) — models/tree.py fit_linear_leaves.

Upstream contract (LightGBM linear_tree): leaves predict
``const + coef . x_pathfeats`` fit by ridge-regularized Newton; constant
leaves remain the fallback for degenerate solves.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def pw_linear():
    rng = np.random.default_rng(0)
    n = 2500
    X = rng.normal(size=(n, 3)).astype(np.float32)
    y = (np.where(X[:, 0] > 0, 3.0 * X[:, 0], -1.0 * X[:, 0])
         + 0.5 * X[:, 1] + 0.05 * rng.normal(size=n)).astype(np.float32)
    return X, y


def test_linear_beats_constant_on_piecewise_linear(pw_linear):
    X, y = pw_linear
    ds = lgb.Dataset(X, label=y)
    base = {"objective": "regression", "verbosity": -1, "num_leaves": 4,
            "learning_rate": 0.5}
    b_lin = lgb.train({**base, "linear_tree": True}, ds, num_boost_round=8)
    b_con = lgb.train(base, ds, num_boost_round=8)
    r_lin = float(np.sqrt(np.mean((b_lin.predict(X) - y) ** 2)))
    r_con = float(np.sqrt(np.mean((b_con.predict(X) - y) ** 2)))
    assert r_lin < 0.5 * r_con, (r_lin, r_con)


def test_predict_matches_train_preds(pw_linear):
    X, y = pw_linear
    ds = lgb.Dataset(X, label=y)
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "num_leaves": 7, "linear_tree": True}, ds,
                  num_boost_round=5)
    tp = np.asarray(b._pred_train)[: len(y)]
    np.testing.assert_allclose(tp, b.predict(X, raw_score=True),
                               rtol=1e-5, atol=1e-5)
    # truncation works through the linear path
    p2 = b.predict(X[:50], num_iteration=2)
    p5 = b.predict(X[:50])
    assert not np.allclose(p2, p5)


def test_linear_tree_save_load_roundtrip(pw_linear, tmp_path):
    X, y = pw_linear
    ds = lgb.Dataset(X, label=y)
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "num_leaves": 5, "linear_tree": True}, ds,
                  num_boost_round=4)
    path = str(tmp_path / "lin.json")
    b.save_model(path)
    loaded = lgb.Booster(model_file=path)
    assert loaded.trees[0].linear_feat is not None
    np.testing.assert_allclose(b.predict(X[:100]), loaded.predict(X[:100]),
                               rtol=1e-5, atol=1e-5)


def test_linear_tree_early_stopping_valid(pw_linear):
    X, y = pw_linear
    dtrain = lgb.Dataset(X[:2000], label=y[:2000])
    dvalid = dtrain.create_valid(X[2000:], label=y[2000:])
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "num_leaves": 5, "linear_tree": True},
                  dtrain, num_boost_round=100, valid_sets=[dvalid],
                  early_stopping_rounds=5)
    assert 0 < b.best_iteration <= 100
    # valid-set eval used the LINEAR leaf values: the recorded best score
    # matches an explicit predict at best_iteration
    pred = b.predict(X[2000:], num_iteration=b.best_iteration)
    mse = float(np.mean((y[2000:] - pred) ** 2))
    np.testing.assert_allclose(mse, b.best_score["valid_0"]["l2"],
                               rtol=1e-4)


def test_linear_tree_nan_and_guardrails(pw_linear):
    X, y = pw_linear
    Xn = X.copy()
    Xn[::7, 0] = np.nan
    ds = lgb.Dataset(Xn, label=y)
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "linear_tree": True}, ds, num_boost_round=3)
    p = b.predict(Xn[:100])
    assert np.all(np.isfinite(p))
    with pytest.raises(NotImplementedError, match="gbdt"):
        lgb.train({"objective": "regression", "boosting": "dart",
                   "linear_tree": True}, ds, 2)
    with pytest.raises(NotImplementedError):
        b.predict(Xn[:10], pred_contrib=True)
    with pytest.raises(NotImplementedError):
        b.refit(X, y)


def test_chunked_fit_matches_single_pass(pw_linear):
    """The chunked normal-equations accumulation (row_chunk) must agree
    with a single-pass fit (code-review r2: a clamped tail chunk silently
    double-counted rows)."""
    X, y = pw_linear
    ds = lgb.Dataset(X, label=y)
    base = {"objective": "regression", "verbosity": -1, "num_leaves": 4,
            "linear_tree": True}
    b_one = lgb.train(base, ds, num_boost_round=3)
    # row_chunk smaller than n forces the multi-chunk path on same data
    b_chunk = lgb.train({**base, "row_chunk": 1024}, ds, num_boost_round=3)
    np.testing.assert_allclose(b_one.predict(X[:200]),
                               b_chunk.predict(X[:200]),
                               rtol=1e-4, atol=1e-5)


def test_rollback_with_linear_tree(pw_linear):
    X, y = pw_linear
    ds = lgb.Dataset(X, label=y)
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "num_leaves": 4, "linear_tree": True}, ds,
                  num_boost_round=4)
    b.rollback_one_iter()
    # train preds must equal an explicit 3-tree predict (the rolled-back
    # tree's coef.x part must be gone too)
    tp = np.asarray(b._pred_train)[: len(y)]
    np.testing.assert_allclose(tp, b.predict(X, raw_score=True,
                                             num_iteration=3),
                               rtol=1e-4, atol=1e-4)


def test_linear_lambda_regularizes(pw_linear):
    X, y = pw_linear
    ds = lgb.Dataset(X, label=y)
    base = {"objective": "regression", "verbosity": -1, "num_leaves": 4,
            "linear_tree": True}
    b0 = lgb.train(base, ds, num_boost_round=2)
    b9 = lgb.train({**base, "linear_lambda": 1e4}, ds, num_boost_round=2)

    def coef_norm(b):
        return float(sum(np.abs(np.asarray(t.linear_coef)).sum()
                         for t in b.trees))

    assert coef_norm(b9) < coef_norm(b0)

"""r9 histogram-merge topologies on the virtual 8-device CPU mesh.

The reduce-scatter split finding must be SERIAL-PARITY-IDENTICAL: each
shard receives only its F/D feature slice of the merged histogram, runs
the split iteration over the slice, and the per-shard BestSplit
candidates combine through an O(D) argmax all-gather — so the winning
(feature, bin) must match the single-chip grower exactly, including when
the feature axis pads unevenly (F=13 over 8 shards leaves shards 6-7
holding ONLY padding columns).  Voting mode is approximate by contract,
but its exact-union case (2k >= F: every feature is a candidate) must
also reproduce serial trees bit-for-bit.

These are the tier-1-visible merge-mode scenarios (ISSUE r9 satellite:
fast virtual-mesh subset); the full Booster-level chains live in
test_parallel.py and __graft_entry__.dryrun_multichip.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.config import Params
from lightgbm_tpu.models.gbdt import HyperScalars
from lightgbm_tpu.models.tree import grow_tree
from lightgbm_tpu.ops.split import SplitContext
from lightgbm_tpu.parallel.data_parallel import (
    make_dp_grow_step,
    make_dp_train_step,
    make_mesh,
    shard_rows,
)

OBJ_KEY = ("regression", 1.0, 1.0, 0.9, 1.0, 0.7, 30, True, 1)
N_DEV = 8


def _ctx():
    return SplitContext(
        lambda_l1=jnp.float32(0.0), lambda_l2=jnp.float32(1.0),
        min_data_in_leaf=jnp.float32(20.0),
        min_sum_hessian=jnp.float32(1e-3),
        min_gain_to_split=jnp.float32(0.0))


def _make_problem(f, n=1024, num_bins=16, seed=0):
    rng = np.random.RandomState(seed)
    bins = rng.randint(0, num_bins, size=(n, f)).astype(np.uint8)
    y = (np.sin(bins[:, 0].astype(np.float32))
         + 0.5 * bins[:, min(1, f - 1)].astype(np.float32)
         + rng.normal(0, 0.1, n)).astype(np.float32)
    stats = np.stack([(0.0 - y).astype(np.float32),
                      np.ones(n, np.float32),
                      np.ones(n, np.float32)], axis=1)
    return bins, y, stats


def _grow_pair(f, merge, voting_k=0, wave_width=1, num_leaves=15,
               num_bins=16):
    """(serial tree/rows, distributed tree/rows) for one merge mode."""
    from jax.sharding import Mesh, PartitionSpec as P

    from lightgbm_tpu.utils.compat import shard_map

    bins, _y, stats = _make_problem(f, num_bins=num_bins)
    fmask = jnp.ones(f, jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("data",))
    ctx = _ctx()

    tree_s, rows_s = jax.jit(lambda: grow_tree(
        jnp.asarray(bins), jnp.asarray(stats), fmask, ctx, num_leaves,
        num_bins, jnp.int32(-1), wave_width=wave_width))()

    def step(b, s):
        return grow_tree(b, s, fmask, ctx, num_leaves, num_bins,
                         jnp.int32(-1), axis_name="data",
                         wave_width=wave_width, hist_merge=merge,
                         n_shards=N_DEV, voting_k=voting_k)

    tree_d, rows_d = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=(P(), P("data")), check_vma=False))(
        jnp.asarray(bins), jnp.asarray(stats))
    return ((jax.device_get(tree_s), np.asarray(rows_s)),
            (jax.device_get(tree_d), np.asarray(rows_d)))


def _assert_tree_parity(serial, dist):
    (ts, rs), (td, rd) = serial, dist
    np.testing.assert_array_equal(ts.split_feature, td.split_feature)
    np.testing.assert_array_equal(ts.split_bin, td.split_bin)
    np.testing.assert_allclose(ts.leaf_value, td.leaf_value,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(rs, rd)


def test_reduce_scatter_parity_ragged_tail():
    """F=13 over 8 shards: features pad to 16, shards 6-7 hold ONLY
    padding columns — the masked-out slice must never win a split."""
    assert len(jax.devices()) >= N_DEV
    _assert_tree_parity(*_grow_pair(13, "reduce_scatter"))


def test_reduce_scatter_parity_f136_wave():
    """The MSLR feature width (F=136, 17/shard) under the frontier
    (wave) grower with the reduce-scatter-sliced histogram cache."""
    _assert_tree_parity(*_grow_pair(136, "reduce_scatter", wave_width=4))


def test_reduce_scatter_parity_fewer_features_than_shards():
    """F=5 < D=8: most shards are pure padding; still exact."""
    _assert_tree_parity(*_grow_pair(5, "reduce_scatter"))


def test_ring_reduce_scatter_parity():
    """The ppermute ring realization must agree with psum_scatter."""
    _assert_tree_parity(*_grow_pair(13, "reduce_scatter_ring",
                                    wave_width=4))


def test_voting_exact_union_parity():
    """2k >= F short-circuits the ballot to the full feature set; the
    candidate reduce-scatter must then reproduce serial trees exactly."""
    _assert_tree_parity(*_grow_pair(13, "voting", voting_k=7,
                                    wave_width=4))


def test_voting_approximate_grows_valid_tree():
    """k << F voting is approximate by contract: it must still grow a
    tree whose splits all come from real (non-padding) features."""
    (ts, _), (td, _) = _grow_pair(136, "voting", voting_k=5)
    assert int(np.sum(td.split_feature >= 0)) > 0
    live = td.split_feature[td.split_feature >= 0]
    assert live.max() < 136


@pytest.mark.parametrize("f", [5, 13, 136])
@pytest.mark.parametrize("wave_width", [1, 4])
def test_pipelined_parity_strict_and_wave(f, wave_width):
    """r10 tentpole exactness bar: the chunked pipelined ring (C=4,
    f32 wire) grows SERIAL-PARITY-IDENTICAL trees across ragged widths
    — F=5 < D, F=13 (pads 32 with chunking vs 16 without: different
    column ownership than plain reduce-scatter, same trees), and the
    MSLR width F=136 — under both the strict and the wave grower."""
    assert len(jax.devices()) >= N_DEV
    _assert_tree_parity(*_grow_pair(f, "reduce_scatter_pipelined",
                                    wave_width=wave_width))


def test_pipelined_multiclass_matches_psum():
    """Class axis vmapped inside the shard_map over the pipelined merge:
    per-class chunked rings batch, trees match psum's."""
    k = 3
    obj_mc = ("multiclass", 1.0, 1.0, 0.9, 1.0, 0.7, 30, True, k)
    bins_np, _y, _ = _make_problem(5, n=1024)
    n = bins_np.shape[0]
    y_mc = (bins_np[:, 0] % k).astype(np.float32)
    mesh = make_mesh(N_DEV)

    def run(merge_mode):
        step = make_dp_train_step(mesh, obj_mc, 7, 16, num_class=k,
                                  merge_mode=merge_mode)
        bins, y, w, bag = shard_rows(
            mesh, jnp.asarray(bins_np), jnp.asarray(y_mc),
            jnp.ones(n, jnp.float32), jnp.ones(n, jnp.float32))
        pred = shard_rows(mesh, jnp.zeros((n, k), jnp.float32))
        fmask = jnp.ones(bins_np.shape[1], jnp.float32)
        trees, new_pred = step(bins, y, w, bag, pred, fmask,
                               HyperScalars.from_params(Params()),
                               jax.random.PRNGKey(1))
        return jax.device_get(trees), np.asarray(new_pred)

    t_ps, p_ps = run("psum")
    t_pl, p_pl = run("reduce_scatter_pipelined")
    np.testing.assert_array_equal(t_ps.split_feature, t_pl.split_feature)
    np.testing.assert_array_equal(t_ps.split_bin, t_pl.split_bin)
    np.testing.assert_allclose(p_ps, p_pl, rtol=1e-5, atol=1e-6)


def test_pipelined_ranking_stats():
    """The stats-only dp grow step (ranking path) under the pipelined
    merge vs serial."""
    bins_np, _y, stats_np = _make_problem(13, n=1024)
    mesh = make_mesh(N_DEV)
    grow = make_dp_grow_step(mesh, 15, 16,
                             merge_mode="reduce_scatter_pipelined")
    bins, stats = shard_rows(mesh, jnp.asarray(bins_np),
                             jnp.asarray(stats_np))
    fmask = jnp.ones(bins_np.shape[1], jnp.float32)
    hyper = HyperScalars.from_params(Params())
    tree_d, _ = grow(bins, stats, fmask, hyper, jax.random.PRNGKey(2))

    tree_s, _ = grow_tree(jnp.asarray(bins_np), jnp.asarray(stats_np),
                          fmask, hyper.ctx(), 15, 16, hyper.max_depth)
    np.testing.assert_array_equal(np.asarray(tree_s.split_feature),
                                  np.asarray(tree_d.split_feature))
    np.testing.assert_array_equal(np.asarray(tree_s.split_bin),
                                  np.asarray(tree_d.split_bin))


def test_wire_dtypes_close_and_guarded():
    """bf16/int8 wire formats: merged histograms stay within the
    documented tolerance of the exact merge, and non-f32 wire refuses
    the fused collectives (no hop boundary to compress at)."""
    from jax.sharding import Mesh, PartitionSpec as P

    from lightgbm_tpu.ops.histogram import histogram_merge
    from lightgbm_tpu.utils.compat import shard_map

    s, f, b = 2, 13, 8
    rng = np.random.RandomState(5)
    counts = rng.poisson(16, (N_DEV, s, f, b)).astype(np.float32)
    hist = jnp.asarray(np.stack(
        [counts * rng.randn(N_DEV, s, f, b).astype(np.float32),
         counts * 0.25, counts], axis=-1))
    mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("data",))

    def run(mode, wire):
        def body(h):
            return histogram_merge(h[0], "data", mode=mode,
                                   n_shards=N_DEV, wire_dtype=wire)
        return np.asarray(jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("data"),),
            out_specs=P("data"), check_vma=False))(hist))

    exact = run("reduce_scatter_ring", "f32")
    scale = np.abs(exact).max()
    for wire in ("bf16", "int8"):
        got = run("reduce_scatter_ring", wire)
        rel = np.abs(got - exact).max() / scale
        assert rel < 0.03, (wire, rel)      # documented ring-hop tolerance
        got_p = run("reduce_scatter_pipelined", wire)
        assert np.abs(got_p).max() > 0
    with pytest.raises(ValueError, match="ring merge mode"):
        run("psum", "int8")
    with pytest.raises(ValueError, match="ring merge mode"):
        run("reduce_scatter", "bf16")
    with pytest.raises(ValueError, match="wire dtype"):
        run("reduce_scatter_ring", "fp8")


def test_mesh_shape_routing():
    """r10 satellite: 2-D rows x features mesh is the default topology
    at D>=8, F>=64 (bit-identical predictions to serial); mesh_shape
    overrides pin or disable it; malformed values die early."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(23)
    n, f = 1024, 64
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(X[:, 5] * 3)
         + rng.normal(0, 0.1, n)).astype(np.float32)
    base = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
            "learning_rate": 0.2, "tree_learner": "data"}

    b_ser = lgb.train({k: v for k, v in base.items()
                       if k != "tree_learner"},
                      lgb.Dataset(X, label=y), num_boost_round=3)
    p_ser = b_ser.predict(X)

    b_auto = lgb.train(dict(base), lgb.Dataset(X, label=y),
                       num_boost_round=3)
    assert getattr(b_auto, "_dp2", False)
    assert dict(b_auto._dp_mesh.shape) == {"data": 4, "feature": 2}
    np.testing.assert_allclose(b_auto.predict(X), p_ser,
                               rtol=1e-5, atol=1e-6)

    b_1d = lgb.train(dict(base, mesh_shape="1d"),
                     lgb.Dataset(X, label=y), num_boost_round=3)
    assert not getattr(b_1d, "_dp2", False)
    np.testing.assert_allclose(b_1d.predict(X), p_ser,
                               rtol=1e-5, atol=1e-6)

    b_2x4 = lgb.train(dict(base, mesh_shape="2x4"),
                      lgb.Dataset(X, label=y), num_boost_round=3)
    assert dict(b_2x4._dp_mesh.shape) == {"data": 2, "feature": 4}
    np.testing.assert_allclose(b_2x4.predict(X), p_ser,
                               rtol=1e-5, atol=1e-6)

    # narrow data stays 1-D under auto (halving the slice buys nothing)
    b_narrow = lgb.train(dict(base), lgb.Dataset(X[:, :8], label=y),
                         num_boost_round=2)
    assert not getattr(b_narrow, "_dp2", False)

    # explicit ring merge keeps the 1-D topology (grow_tree rejects
    # ring merges composed with a feature axis)
    b_ring = lgb.train(dict(base, histogram_merge="reduce_scatter"),
                       lgb.Dataset(X, label=y), num_boost_round=2)
    assert not getattr(b_ring, "_dp2", False)

    with pytest.raises(ValueError, match="mesh_shape"):
        lgb.train(dict(base, mesh_shape="coil"),
                  lgb.Dataset(X, label=y), num_boost_round=1)


def test_histogram_wire_override_param():
    """params={'histogram_wire': ...}: routes through _dp_wire, rejects
    fused-collective merges, trains within the documented tolerance."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(31)
    n = 1500
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] + rng.normal(0, 0.1, n)).astype(np.float32)
    base = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
            "tree_learner": "data"}
    b_f32 = lgb.train(dict(base), lgb.Dataset(X, label=y),
                      num_boost_round=4)
    b_q = lgb.train(dict(base, histogram_wire="int8"),
                    lgb.Dataset(X, label=y), num_boost_round=4)
    assert b_q._dp_wire("reduce_scatter_pipelined", n) == ("int8", 4)
    # quality, not parity: quantized wire tracks the f32 model loosely
    mse_f = float(np.mean((b_f32.predict(X) - y) ** 2))
    mse_q = float(np.mean((b_q.predict(X) - y) ** 2))
    assert mse_q < 1.5 * mse_f + 1e-3, (mse_f, mse_q)
    with pytest.raises(ValueError, match="histogram_wire"):
        lgb.train(dict(base, histogram_wire="fp8"),
                  lgb.Dataset(X, label=y), num_boost_round=1)
    with pytest.raises(ValueError, match="reduce_scatter_ring"):
        lgb.train(dict(base, histogram_merge="psum",
                       histogram_wire="int8"),
                  lgb.Dataset(X, label=y), num_boost_round=1)
    b_c2 = lgb.train(dict(base, merge_chunks=2),
                     lgb.Dataset(X, label=y), num_boost_round=4)
    assert b_c2._dp_wire("reduce_scatter_pipelined", n) == ("f32", 2)
    np.testing.assert_allclose(b_c2.predict(X), b_f32.predict(X),
                               rtol=1e-5, atol=1e-6)
    with pytest.raises(ValueError, match="merge_chunks"):
        lgb.train(dict(base, merge_chunks=0),
                  lgb.Dataset(X, label=y), num_boost_round=1)


def test_histogram_merge_slices_match_psum():
    """Unit check: each shard's reduce-scatter output equals its feature
    slice of the full psum merge, for both realizations."""
    from jax.sharding import Mesh, PartitionSpec as P

    from lightgbm_tpu.ops.histogram import histogram_merge
    from lightgbm_tpu.utils.compat import shard_map

    s, f, b = 2, 13, 8
    rng = np.random.RandomState(3)
    hist = jnp.asarray(rng.randn(N_DEV, s, f, b, 3).astype(np.float32))
    mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("data",))

    def run(mode):
        def body(h):
            return histogram_merge(h[0], "data", mode=mode,
                                   n_shards=N_DEV)
        return np.asarray(jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("data"),),
            out_specs=P("data"), check_vma=False))(hist))

    full = np.asarray(hist.sum(axis=0))                      # [S, F, B, 3]
    f_loc = -(-f // N_DEV)                                   # 2, padded 16
    padded = np.concatenate(
        [full, np.zeros((s, N_DEV * f_loc - f, b, 3), np.float32)], axis=1)
    want = padded.reshape(s, N_DEV, f_loc, b, 3).transpose(1, 0, 2, 3, 4)
    want = want.reshape(N_DEV * s, f_loc, b, 3)
    for mode in ("reduce_scatter", "reduce_scatter_ring"):
        got = run(mode).reshape(N_DEV * s, f_loc, b, 3)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError, match="merge mode"):
        run("allgatherify")


def test_dp_train_step_merge_modes_match_psum():
    """The full dp train step (objective grad -> grow -> score update)
    over each r9 merge mode reproduces the psum step's tree; psum's own
    serial parity is pinned by test_parallel.py."""
    bins_np, y_np, _ = _make_problem(6, n=1024)
    n = len(y_np)
    mesh = make_mesh(N_DEV)

    def run(merge_mode, voting_k=0):
        step = make_dp_train_step(mesh, OBJ_KEY, 15, 16,
                                  merge_mode=merge_mode,
                                  voting_k=voting_k)
        bins, y, w, bag, pred = shard_rows(
            mesh, jnp.asarray(bins_np), jnp.asarray(y_np),
            jnp.ones(n, jnp.float32), jnp.ones(n, jnp.float32),
            jnp.zeros(n, jnp.float32))
        fmask = jnp.ones(bins_np.shape[1], jnp.float32)
        tree, new_pred = step(bins, y, w, bag, pred, fmask,
                              HyperScalars.from_params(Params()),
                              jax.random.PRNGKey(0))
        return jax.device_get(tree), np.asarray(new_pred)

    tree_ps, pred_ps = run("psum")
    for mode, vk in (("reduce_scatter", 0), ("voting", 6)):
        tree_m, pred_m = run(mode, vk)        # vk=6 -> exact union (F=6)
        np.testing.assert_array_equal(tree_ps.split_feature,
                                      tree_m.split_feature)
        np.testing.assert_array_equal(tree_ps.split_bin, tree_m.split_bin)
        np.testing.assert_allclose(pred_ps, pred_m, rtol=1e-5, atol=1e-6)


def test_dp_grow_step_reduce_scatter_ranking_stats():
    """The stats-only dp grow step (the ranking path: lambdas computed
    replicated, growth sharded) under reduce_scatter vs serial."""
    bins_np, _y, stats_np = _make_problem(13, n=1024)
    n = stats_np.shape[0]
    mesh = make_mesh(N_DEV)
    grow = make_dp_grow_step(mesh, 15, 16, merge_mode="reduce_scatter")
    bins, stats = shard_rows(mesh, jnp.asarray(bins_np),
                             jnp.asarray(stats_np))
    fmask = jnp.ones(bins_np.shape[1], jnp.float32)
    hyper = HyperScalars.from_params(Params())
    tree_d, _ = grow(bins, stats, fmask, hyper, jax.random.PRNGKey(2))

    tree_s, _ = grow_tree(jnp.asarray(bins_np), jnp.asarray(stats_np),
                          fmask, hyper.ctx(), 15, 16, hyper.max_depth)
    np.testing.assert_array_equal(np.asarray(tree_s.split_feature),
                                  np.asarray(tree_d.split_feature))
    np.testing.assert_array_equal(np.asarray(tree_s.split_bin),
                                  np.asarray(tree_d.split_bin))


def test_dp_multiclass_reduce_scatter_matches_psum():
    """Class axis vmapped inside the shard_map: per-class histograms
    reduce-scatter as one batched collective; trees match psum's."""
    k = 3
    obj_mc = ("multiclass", 1.0, 1.0, 0.9, 1.0, 0.7, 30, True, k)
    bins_np, _y, _ = _make_problem(5, n=1024)
    n = bins_np.shape[0]
    y_mc = (bins_np[:, 0] % k).astype(np.float32)
    mesh = make_mesh(N_DEV)

    def run(merge_mode):
        step = make_dp_train_step(mesh, obj_mc, 7, 16, num_class=k,
                                  merge_mode=merge_mode)
        bins, y, w, bag = shard_rows(
            mesh, jnp.asarray(bins_np), jnp.asarray(y_mc),
            jnp.ones(n, jnp.float32), jnp.ones(n, jnp.float32))
        pred = shard_rows(mesh, jnp.zeros((n, k), jnp.float32))
        fmask = jnp.ones(bins_np.shape[1], jnp.float32)
        trees, new_pred = step(bins, y, w, bag, pred, fmask,
                               HyperScalars.from_params(Params()),
                               jax.random.PRNGKey(1))
        return jax.device_get(trees), np.asarray(new_pred)

    t_ps, p_ps = run("psum")
    t_rs, p_rs = run("reduce_scatter")
    np.testing.assert_array_equal(t_ps.split_feature, t_rs.split_feature)
    np.testing.assert_array_equal(t_ps.split_bin, t_rs.split_bin)
    np.testing.assert_allclose(p_ps, p_rs, rtol=1e-5, atol=1e-6)


def test_booster_tree_learner_voting_routes_and_trains():
    """tree_learner='voting' must engage the dp mesh, route the voting
    merge, and (top_k small) still learn the target."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(17)
    n = 2000
    X = rng.normal(size=(n, 12)).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(X[:, 5] * 3)
         + rng.normal(0, 0.1, n)).astype(np.float32)
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "learning_rate": 0.2, "verbosity": -1,
                   "tree_learner": "voting", "top_k": 3},
                  lgb.Dataset(X, label=y), num_boost_round=8)
    assert b._dp_mesh is not None
    mode, k = b._dp_merge_mode()
    assert (mode, k) == ("voting", 3)
    rmse = float(np.sqrt(np.mean((b.predict(X) - y) ** 2)))
    assert rmse < float(np.std(y)) * 0.6, rmse


def test_comm_budget_model_and_gate():
    """The declarative comm budgets: reduce-scatter receives exactly the
    F/D slice at the r9 reference shape (D=8, F=136, B=256, S=2) — an
    8x drop vs psum against the >=4x acceptance floor."""
    from lightgbm_tpu.analysis.budgets import (check_comm_budgets,
                                               hist_merge_comm_bytes)

    ps = hist_merge_comm_bytes("psum", 8, 136, 256, 2)
    rs = hist_merge_comm_bytes("reduce_scatter", 8, 136, 256, 2)
    bestsplit = 8 * 16 * 4
    assert ps["received_bytes_per_shard"] == 2 * 136 * 256 * 3 * 4 \
        + bestsplit
    assert rs["received_bytes_per_shard"] == 2 * 17 * 256 * 3 * 4 \
        + bestsplit
    results = check_comm_budgets()
    assert all(r["ok"] for r in results), results
    assert {r["mode"] for r in results} == {
        "reduce_scatter", "reduce_scatter_ring",
        "reduce_scatter_pipelined", "voting"}
    with pytest.raises(ValueError):
        hist_merge_comm_bytes("gather", 8, 136, 256, 2)


def test_comm_time_model_and_pipelined_budgets():
    """r10: the comm *time* model.  At the D=8/F=136/B=256 reference the
    wave's histogram matmul (~2.7 ms) dwarfs ring comm (~50 us), so the
    pipelined schedule hides all but the first chunk's wire time:
    hidden_frac = 1 - 1/C = 0.75 at C=4, over the 60% acceptance floor.
    int8 wire must cut modeled ring bytes >=2x vs r9's 104,960 B/shard."""
    from lightgbm_tpu.analysis.budgets import (
        check_comm_time_budgets, comm_budget_by_name,
        hist_merge_comm_bytes, hist_merge_comm_time)

    # pipelined C=4 pads F=136 -> 160: the slice widens to 20 features
    pipe = hist_merge_comm_bytes("reduce_scatter_pipelined", 8, 136,
                                 256, 2)
    bestsplit = 8 * 16 * 4
    assert pipe["received_bytes_per_shard"] == 2 * 20 * 256 * 3 * 4 \
        + bestsplit
    # int8 wire: 1 B cells + a 12 B per-feature scale sidecar on each of
    # the (d-1)*chunks hop messages (5 features per message at C=4)
    q = hist_merge_comm_bytes("reduce_scatter_pipelined", 8, 136, 256, 2,
                              wire_dtype="int8")
    assert q["received_bytes_per_shard"] == 2 * 20 * 256 * 3 * 1 \
        + 7 * 20 * 12 + bestsplit
    assert 104_960 / q["received_bytes_per_shard"] >= 2.0
    assert comm_budget_by_name("hist_wire_int8_d8").check()["ok"]

    # wire compression only makes sense where per-hop messages exist
    with pytest.raises(ValueError, match="ring"):
        hist_merge_comm_bytes("psum", 8, 136, 256, 2, wire_dtype="int8")
    with pytest.raises(ValueError, match="wire"):
        hist_merge_comm_bytes("reduce_scatter_ring", 8, 136, 256, 2,
                              wire_dtype="fp8")

    t = hist_merge_comm_time("reduce_scatter_pipelined", 8, 136, 256, 2)
    assert t["compute_bound"]
    assert abs(t["hidden_frac"] - 0.75) < 1e-9   # 1 - 1/C at C=4
    assert abs(t["hidden_ms"] + t["exposed_ms"] - t["comm_ms"]) < 1e-9
    # serial modes expose their full comm time
    ser = hist_merge_comm_time("reduce_scatter", 8, 136, 256, 2)
    assert ser["hidden_frac"] == 0.0
    assert ser["exposed_ms"] == ser["comm_ms"]
    # comm-bound regime: tiny compute -> makespan is comm-dominated and
    # only the chunk-0 compute bubble is hidden
    cb = hist_merge_comm_time("reduce_scatter_pipelined", 8, 136, 256, 2,
                              rows_per_shard=1)
    assert not cb["compute_bound"]
    assert 0.0 < cb["hidden_frac"] < 0.25

    results = check_comm_time_budgets()
    assert all(r["ok"] for r in results), results
    assert {r["name"] for r in results} == {
        "merge_hidden_pipelined_d8", "merge_hidden_pipelined_int8_d8"}


def test_int8_overflow_guards():
    """The int8 accumulation cliff (2^31/127 rows per (segment, bin)
    cell) must raise at every layer instead of silently wrapping."""
    from lightgbm_tpu.config import parse_params
    from lightgbm_tpu.models.gbdt import check_int8_row_limit
    from lightgbm_tpu.ops.histogram_pallas import (
        INT8_ACC_ROW_LIMIT, hist_from_segstats_pallas)

    assert INT8_ACC_ROW_LIMIT == (1 << 31) // 127
    p = parse_params({"objective": "regression", "hist_dtype": "int8"},
                     warn_unknown=False)
    check_int8_row_limit(p, INT8_ACC_ROW_LIMIT, 1)          # at the bound
    with pytest.raises(ValueError, match="int8"):
        check_int8_row_limit(p, INT8_ACC_ROW_LIMIT + 1, 1)
    check_int8_row_limit(p, INT8_ACC_ROW_LIMIT + 1, 8)      # sharded: fine
    p_f32 = parse_params({"objective": "regression"}, warn_unknown=False)
    check_int8_row_limit(p_f32, 10 ** 9, 1)                 # non-int8

    with pytest.raises(ValueError, match="int8"):
        hist_from_segstats_pallas(jnp.zeros((8, 2), jnp.int32),
                                  jnp.ones((8, 4)), 4, hist_dtype="int8")


def test_tree_learner_and_top_k_validation():
    from lightgbm_tpu.config import parse_params

    p = parse_params({"objective": "regression",
                      "tree_learner": "voting", "topk": 11},
                     warn_unknown=False)
    assert p.tree_learner == "voting" and p.top_k == 11
    with pytest.raises(ValueError):
        parse_params({"objective": "regression", "tree_learner": "ring"},
                     warn_unknown=False)
    with pytest.raises(ValueError):
        parse_params({"objective": "regression", "top_k": 0},
                     warn_unknown=False)


def test_histogram_merge_override_param():
    """params={'histogram_merge': ...} forces the topology; bad values
    die in _dp_merge_mode before any tracing."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(29)
    n = 1500
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] + rng.normal(0, 0.1, n)).astype(np.float32)
    base = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
            "tree_learner": "data"}
    b_ps = lgb.train(dict(base, histogram_merge="psum"),
                     lgb.Dataset(X, label=y), num_boost_round=4)
    assert b_ps._dp_merge_mode()[0] == "psum"
    b_rs = lgb.train(dict(base), lgb.Dataset(X, label=y),
                     num_boost_round=4)
    # r10: the data learner's default is the pipelined chunked ring
    assert b_rs._dp_merge_mode()[0] == "reduce_scatter_pipelined"
    np.testing.assert_allclose(b_ps.predict(X), b_rs.predict(X),
                               rtol=1e-5, atol=1e-5)
    b_plain = lgb.train(dict(base, histogram_merge="reduce_scatter"),
                        lgb.Dataset(X, label=y), num_boost_round=4)
    assert b_plain._dp_merge_mode()[0] == "reduce_scatter"
    np.testing.assert_allclose(b_ps.predict(X), b_plain.predict(X),
                               rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError, match="histogram_merge"):
        lgb.train(dict(base, histogram_merge="gather"),
                  lgb.Dataset(X, label=y), num_boost_round=1)

"""Monotone constraints, max_delta_step, extra_trees, path_smooth.

Coverage model (SURVEY.md §4): behavioral assertions against the parameter
semantics LightGBM documents — monotonicity holds pointwise on a prediction
grid, max_delta_step caps leaf outputs exactly, extra_trees still learns,
path_smooth shrinks leaf spread — plus config validation errors.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def mono_data():
    rng = np.random.default_rng(7)
    n = 4000
    X = rng.normal(size=(n, 4)).astype(np.float32)
    # true effect of x0 is increasing, x1 decreasing, x2/x3 free
    y = (1.5 * X[:, 0] - 2.0 * X[:, 1] + np.sin(3 * X[:, 2])
         + 0.3 * rng.normal(size=n)).astype(np.float32)
    return X, y


def _monotonicity_violations(booster, X, feature, sign, n_grid=25,
                             n_rows=40):
    """Count grid-adjacent prediction pairs moving AGAINST the constraint."""
    lo, hi = X[:, feature].min(), X[:, feature].max()
    base = X[:n_rows].copy()
    prev, viol = None, 0
    for v in np.linspace(lo, hi, n_grid):
        Xg = base.copy()
        Xg[:, feature] = v
        p = booster.predict(Xg)
        if prev is not None:
            viol += int(np.sum((p - prev) * sign < -1e-6))
        prev = p
    return viol


def test_monotone_constraints_hold(mono_data):
    X, y = mono_data
    ds = lgb.Dataset(X, label=y)
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "num_leaves": 31,
                   "monotone_constraints": [1, -1, 0, 0]},
                  ds, num_boost_round=30)
    assert _monotonicity_violations(b, X, 0, +1) == 0
    assert _monotonicity_violations(b, X, 1, -1) == 0
    # the constrained model must still fit (constraints match the truth)
    rmse = float(np.sqrt(np.mean((b.predict(X) - y) ** 2)))
    assert rmse < np.std(y) * 0.6, rmse


def test_monotone_unconstrained_model_violates():
    """Sanity: an unconstrained overfit on noisy data DOES violate
    (otherwise the zero-violation assertions above are vacuous) while the
    constrained fit on the SAME data does not."""
    rng = np.random.default_rng(11)
    n = 800
    X = rng.normal(size=(n, 3)).astype(np.float32)
    y = (0.5 * X[:, 0] + 2.0 * rng.normal(size=n)).astype(np.float32)
    ds = lgb.Dataset(X, label=y)
    base = {"objective": "regression", "verbosity": -1, "num_leaves": 63,
            "min_data_in_leaf": 2}
    b = lgb.train(base, ds, num_boost_round=30)
    assert _monotonicity_violations(b, X, 0, +1) > 0
    b_c = lgb.train({**base, "monotone_constraints": [1, 0, 0]}, ds,
                    num_boost_round=30)
    assert _monotonicity_violations(b_c, X, 0, +1) == 0


def test_monotone_constraints_frontier_and_strict(mono_data):
    """Both growers enforce the constraint (wave growth propagates bounds
    through the histogram-subtraction path)."""
    X, y = mono_data
    ds = lgb.Dataset(X, label=y)
    for policy in ("leafwise", "frontier"):
        b = lgb.train({"objective": "regression", "verbosity": -1,
                       "grow_policy": policy,
                       "monotone_constraints": [1, -1, 0, 0]},
                      ds, num_boost_round=15)
        assert _monotonicity_violations(b, X, 0, +1) == 0, policy
        assert _monotonicity_violations(b, X, 1, -1) == 0, policy


def test_monotone_string_form_and_validation(mono_data):
    X, y = mono_data
    ds = lgb.Dataset(X, label=y)
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "monotone_constraints": "1,-1,0,0"},
                  ds, num_boost_round=5)
    assert _monotonicity_violations(b, X, 0, +1) == 0
    with pytest.raises(ValueError, match="-1, 0, or 1"):
        lgb.train({"objective": "regression",
                   "monotone_constraints": [2, 0, 0, 0]}, ds, 2)
    with pytest.raises(ValueError, match="entries for"):
        lgb.train({"objective": "regression", "verbosity": -1,
                   "monotone_constraints": [1, 0]}, ds, 2)


def test_monotone_on_categorical_rejected():
    rng = np.random.default_rng(3)
    X = np.column_stack([rng.integers(0, 5, 500),
                         rng.normal(size=500)]).astype(np.float32)
    y = rng.normal(size=500).astype(np.float32)
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    with pytest.raises(ValueError, match="categorical"):
        lgb.train({"objective": "regression", "verbosity": -1,
                   "monotone_constraints": [1, 0]}, ds, 2)


def test_max_delta_step_caps_leaf_values(mono_data):
    X, y = mono_data
    ds = lgb.Dataset(X, label=y)
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "max_delta_step": 0.05}, ds, num_boost_round=8)
    for t in b.trees:
        vals = np.asarray(t.leaf_value)[np.asarray(t.is_leaf)]
        assert np.all(np.abs(vals) <= 0.05 + 1e-6)


def test_extra_trees_learns_and_differs(mono_data):
    X, y = mono_data
    ds = lgb.Dataset(X, label=y)
    base = {"objective": "regression", "verbosity": -1, "num_leaves": 31}
    b_plain = lgb.train(base, ds, num_boost_round=40)
    b_extra = lgb.train({**base, "extra_trees": True}, ds,
                        num_boost_round=40)
    p_plain = b_plain.predict(X)
    p_extra = b_extra.predict(X)
    # randomized thresholds -> a different model ...
    assert not np.allclose(p_plain, p_extra)
    # ... that still learns far better than the mean predictor
    rmse = float(np.sqrt(np.mean((p_extra - y) ** 2)))
    assert rmse < np.std(y) * 0.7, rmse


def test_extra_trees_splits_low_cardinality_feature():
    """The random threshold draws within each feature's OWN bin range
    (code-review r2): a binary feature must still get picked, not starve
    because the draw ranges over the continuous features' 255 bins."""
    rng = np.random.default_rng(21)
    n = 3000
    xb = rng.integers(0, 2, n).astype(np.float32)     # binary, 2 bins
    xc = rng.normal(size=(n, 2)).astype(np.float32)   # continuous
    X = np.column_stack([xb, xc])
    y = (3.0 * xb + 0.1 * rng.normal(size=n)).astype(np.float32)
    ds = lgb.Dataset(X, label=y)
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "extra_trees": True, "num_leaves": 7},
                  ds, num_boost_round=20)
    imp = b.feature_importance()
    assert imp[0] > 0, imp       # the binary driver feature gets split
    rmse = float(np.sqrt(np.mean((b.predict(X) - y) ** 2)))
    assert rmse < 0.5, rmse      # and the signal is actually captured


def test_path_smooth_shrinks_leaf_spread(mono_data):
    X, y = mono_data
    ds = lgb.Dataset(X, label=y)
    base = {"objective": "regression", "verbosity": -1, "num_leaves": 63,
            "min_data_in_leaf": 2}
    b0 = lgb.train(base, ds, num_boost_round=3)
    b1 = lgb.train({**base, "path_smooth": 100.0}, ds, num_boost_round=3)

    def leaf_std(b):
        vals = [np.asarray(t.leaf_value)[np.asarray(t.is_leaf)]
                for t in b.trees]
        return float(np.concatenate(vals).std())

    assert leaf_std(b1) < leaf_std(b0)
    with pytest.raises(ValueError, match="path_smooth"):
        lgb.train({"objective": "regression", "path_smooth": -1.0}, ds, 2)


def test_monotone_with_goss_and_dp_mesh(mono_data):
    """Constraints hold under GOSS sampling and under the data-parallel
    mesh learner (mono plumbed through _goss_compact_round and
    make_dp_train_step)."""
    X, y = mono_data
    ds = lgb.Dataset(X, label=y)
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "boosting": "goss",
                   "monotone_constraints": [1, -1, 0, 0]},
                  ds, num_boost_round=15)
    assert _monotonicity_violations(b, X, 0, +1) == 0
    import jax
    if len(jax.devices()) > 1:
        b2 = lgb.train({"objective": "regression", "verbosity": -1,
                        "tree_learner": "data",
                        "monotone_constraints": [1, -1, 0, 0]},
                       ds, num_boost_round=10)
        assert _monotonicity_violations(b2, X, 0, +1) == 0


def _branch_feature_sets(booster):
    """Per-leaf sets of ORIGINAL features used on the root path."""
    sets = []
    for info in booster.dump_model()["tree_info"]:
        def rec(node, used):
            if "leaf_value" in node:
                if used:
                    sets.append(frozenset(used))
                return
            u2 = used | {node["split_feature"]}
            rec(node["left_child"], u2)
            rec(node["right_child"], u2)
        rec(info["tree_structure"], set())
    return sets


def test_interaction_constraints_respected():
    rng = np.random.default_rng(17)
    n = 4000
    X = rng.normal(size=(n, 4)).astype(np.float32)
    # truth mixes (x0,x2) and (x1,x3) — the constraint forbids exactly that
    y = (X[:, 0] * X[:, 2] + X[:, 1] * X[:, 3]
         + 0.1 * rng.normal(size=n)).astype(np.float32)
    groups = [[0, 1], [2, 3]]
    ds = lgb.Dataset(X, label=y)
    for policy in ("leafwise", "frontier"):
        b = lgb.train({"objective": "regression", "verbosity": -1,
                       "grow_policy": policy, "num_leaves": 15,
                       "interaction_constraints": groups},
                      ds, num_boost_round=15)
        for used in _branch_feature_sets(b):
            assert (used <= {0, 1}) or (used <= {2, 3}), (policy, used)
    # sanity: unconstrained DOES mix groups on this data
    b0 = lgb.train({"objective": "regression", "verbosity": -1,
                    "num_leaves": 15}, ds, num_boost_round=15)
    assert any(not (u <= {0, 1}) and not (u <= {2, 3})
               for u in _branch_feature_sets(b0))


def test_interaction_constraints_singletons_and_string():
    rng = np.random.default_rng(18)
    n = 2000
    X = rng.normal(size=(n, 3)).astype(np.float32)
    y = (X[:, 0] + X[:, 2] + 0.1 * rng.normal(size=n)).astype(np.float32)
    # only [0,1] listed: feature 2 becomes a singleton group (sklearn
    # convention) — usable alone, never together with others
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "num_leaves": 7,
                   "interaction_constraints": "[0,1]"},
                  ds := lgb.Dataset(X, label=y), num_boost_round=10)
    for used in _branch_feature_sets(b):
        assert used <= {0, 1} or used == {2}, used
    # feature 2 is still used somewhere (it carries signal)
    assert b.feature_importance()[2] > 0

"""Checkpoint/resume tests (ISSUE r13 tentpole a+b).

The contract is BIT-IDENTITY, not tolerance: a run killed at ANY round
and resumed from its checkpoint must produce the same forest — every
tree buffer ``np.array_equal`` — and the same train predictions as the
run that was never interrupted.  Pinned across strict and wave growers,
in-memory and streamed (single- and multi-block, ragged tail) datasets,
and the dryrun multi-chip mesh, plus the durability half: torn and
corrupt checkpoint files are rejected naming the damaged field, and
``load_latest`` falls back past them.
"""

import hashlib
import io
import json
import os
import signal

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.dataset import Dataset
from lightgbm_tpu.training import (
    CKPT_FORMAT_VERSION,
    CorruptCheckpointError,
    IncompatibleCheckpointError,
    latest_checkpoint,
    list_checkpoints,
    load_checkpoint,
    load_latest,
    resume_booster,
    save_checkpoint,
    train_resumable,
)
from lightgbm_tpu.training.checkpoint import _HEADER_LEN, CKPT_MAGIC


def _problem(n=700, f=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, f)).astype(np.float32)
    w = rng.normal(0, 1, f)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float32)
    return X, y


def _trees_equal(a, b):
    if len(a.trees) != len(b.trees):
        return False
    for ta, tb in zip(a.trees, b.trees):
        for field in ("split_feature", "split_bin", "left", "right",
                      "leaf_value", "is_leaf"):
            if not np.array_equal(np.asarray(getattr(ta, field)),
                                  np.asarray(getattr(tb, field))):
                return False
    return True


def _assert_same_run(ref, got):
    assert _trees_equal(ref, got)
    assert np.array_equal(np.asarray(ref._pred_train),
                          np.asarray(got._pred_train))


# layout -> (params extra, dataset factory kind)
#   memory        in-memory Dataset
#   stream_one    single padded block (ceil256(700) = 768 <= 768)
#   stream_multi  3 blocks of 256 with a ragged 188-row tail
_LAYOUTS = {
    "memory": None,
    "stream_one": 768,
    "stream_multi": 256,
}

_GROWERS = {"strict": {}, "wave": {"wave_width": 4}}


def _make(layout, grower, seed=0, bagging=False):
    """(params, fresh-Dataset factory) for one layout x grower cell."""
    X, y = _problem(seed=seed)
    p = dict(objective="binary", num_leaves=7, learning_rate=0.2,
             max_bin=31, min_data_in_leaf=5, verbose=-1, seed=7)
    p.update(_GROWERS[grower])
    if bagging:
        p.update(bagging_fraction=0.8, bagging_freq=1, feature_fraction=0.8)
    block_rows = _LAYOUTS[layout]
    if block_rows is None:
        def make_ds():
            return Dataset(X, label=y, params=dict(p))
    else:
        p["stream_block_rows"] = block_rows
        blocks = [(X[lo:lo + block_rows], y[lo:lo + block_rows])
                  for lo in range(0, len(X), block_rows)]
        def make_ds():
            return Dataset.from_blocks(blocks, params=dict(p))
    return p, make_ds


def _reference(p, make_ds, rounds):
    b = lgb.Booster(dict(p), make_ds())
    for _ in range(rounds):
        b.update()
    return b


ROUNDS = 4


@pytest.mark.parametrize("layout", list(_LAYOUTS))
@pytest.mark.parametrize("grower", list(_GROWERS))
def test_kill_at_every_round_resumes_bit_identical(tmp_path, grower, layout):
    """Checkpoint every round, then resume from EVERY generation k and
    train the remaining rounds: each resumed forest must equal the
    uninterrupted one bit for bit."""
    p, make_ds = _make(layout, grower, bagging=(layout == "memory"))
    ref = _reference(p, make_ds, ROUNDS)

    d = str(tmp_path / "ckpts")
    res = train_resumable(dict(p), make_ds(), ROUNDS, checkpoint_dir=d,
                          checkpoint_rounds=1, keep_last=ROUNDS + 1,
                          resume=False)
    assert res.completed and not res.preempted
    assert res.rounds_done == ROUNDS
    _assert_same_run(ref, res.booster)

    paths = list_checkpoints(d)
    assert [load_checkpoint(q)[1]["iter"] for q in paths] \
        == list(range(1, ROUNDS + 1))
    for k, path in zip(range(1, ROUNDS), paths):
        b = resume_booster(path, make_ds())
        assert b._iter == k
        for _ in range(ROUNDS - k):
            b.update()
        _assert_same_run(ref, b)


def test_sigterm_drains_checkpoints_and_resumes(tmp_path):
    """A real SIGTERM mid-run: the in-flight round completes, a
    checkpoint lands, and a second invocation resumes to the same
    forest as the uninterrupted run."""
    p, make_ds = _make("memory", "strict", bagging=True)
    ref = _reference(p, make_ds, 6)
    d = str(tmp_path / "ckpts")

    def kill_at(booster, i):
        if i == 2:
            os.kill(os.getpid(), signal.SIGTERM)

    res = train_resumable(dict(p), make_ds(), 6, checkpoint_dir=d,
                          checkpoint_rounds=10, resume=False,
                          round_callbacks=[kill_at])
    assert res.preempted and not res.completed
    assert res.rounds_done == 3          # round index 2 finished
    assert res.last_checkpoint is not None
    assert load_checkpoint(res.last_checkpoint)[1]["iter"] == 3

    res2 = train_resumable(dict(p), make_ds(), 6, checkpoint_dir=d,
                           checkpoint_rounds=10, resume=True)
    assert res2.completed and res2.resumed_from == res.last_checkpoint
    _assert_same_run(ref, res2.booster)


def test_screened_kill_resume_bit_identical(tmp_path):
    """r20: the EMA screener's state (EWMA vector + rounds-since-refresh
    counter) rides the checkpoint, so a kill mid-screening-cycle resumes
    to the SAME active-set plans and the same forest bit for bit."""
    X, y = _problem(n=900, f=13, seed=4)
    p = dict(objective="binary", num_leaves=15, learning_rate=0.2,
             max_bin=31, min_data_in_leaf=5, verbose=-1, seed=7,
             feature_screen="ema", screen_keep_ratio=0.3,
             screen_refresh_rounds=3)

    def make_ds():
        return Dataset(X, label=y, params=dict(p))

    rounds = 8
    ref = _reference(p, make_ds, rounds)
    d = str(tmp_path / "ckpts")
    b = lgb.Booster(dict(p), make_ds())
    for _ in range(5):                       # kill between refreshes
        b.update()
    save_checkpoint(b, d)
    ema5, since5 = b._screener.state()
    assert since5 != 0                       # genuinely mid-cycle

    r = resume_booster(latest_checkpoint(d), make_ds())
    got_ema, got_since = r._screener.state()
    assert np.array_equal(got_ema, ema5) and got_since == since5
    for _ in range(rounds - 5):
        r.update()
    _assert_same_run(ref, r)
    assert np.array_equal(r._screener.state()[0],
                          ref._screener.state()[0])


def test_dp_mesh_resume_bit_identical(tmp_path):
    """Dryrun multi-chip (8 virtual CPU devices): the checkpoint carries
    the merge-mode config and resume stays bit-identical."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    p, make_ds = _make("memory", "strict")
    p.update(tree_learner="data", histogram_merge="reduce_scatter")
    ref = _reference(p, make_ds, 3)

    d = str(tmp_path / "ckpts")
    b = lgb.Booster(dict(p), make_ds())
    b.update()
    save_checkpoint(b, d)
    meta = load_checkpoint(latest_checkpoint(d))[1]
    assert meta["parallel"]["tree_learner"] == "data"
    assert meta["parallel"]["merge_mode"] == "reduce_scatter"

    r = resume_booster(latest_checkpoint(d), make_ds())
    for _ in range(2):
        r.update()
    _assert_same_run(ref, r)


# -- durability: torn / corrupt artifacts --------------------------------


def _one_checkpoint(tmp_path, rounds=2):
    p, make_ds = _make("memory", "strict")
    b = lgb.Booster(dict(p), make_ds())
    for _ in range(rounds):
        b.update()
    d = str(tmp_path / "ckpts")
    return save_checkpoint(b, d), make_ds


def _rewrite_payload(path, mutate):
    """Re-serialize a checkpoint with one array mutated and the OUTER
    sha256 recomputed — so only the per-field crc can catch it."""
    blob = open(path, "rb").read()
    with np.load(io.BytesIO(blob[_HEADER_LEN:])) as z:
        arrays = {k: np.array(z[k]) for k in z.files}
    mutate(arrays)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    payload = buf.getvalue()
    header = (CKPT_MAGIC + np.uint32(CKPT_FORMAT_VERSION).tobytes()
              + hashlib.sha256(payload).digest())
    with open(path, "wb") as f:
        f.write(header + payload)


@pytest.mark.parametrize("field", ["pred_train", "key",
                                   "tree00000/leaf_value",
                                   "tree00001/split_bin"])
def test_per_field_corruption_rejected_naming_field(tmp_path, field):
    path, _ = _one_checkpoint(tmp_path)

    def flip(arrays):
        a = arrays[field]
        view = a.view(np.uint8).reshape(-1)
        view[0] ^= 0xFF
    _rewrite_payload(path, flip)
    with pytest.raises(CorruptCheckpointError) as ei:
        load_checkpoint(path)
    assert ei.value.field == field
    assert field in str(ei.value)


def test_torn_write_truncation_rejected(tmp_path):
    path, _ = _one_checkpoint(tmp_path)
    blob = open(path, "rb").read()
    for cut in (0, _HEADER_LEN - 5, _HEADER_LEN + 10, len(blob) - 1):
        open(path, "wb").write(blob[:cut])
        with pytest.raises(CorruptCheckpointError):
            load_checkpoint(path)


def test_payload_bitrot_caught_by_sha256(tmp_path):
    path, _ = _one_checkpoint(tmp_path)
    blob = bytearray(open(path, "rb").read())
    blob[_HEADER_LEN + 100] ^= 0x01
    open(path, "wb").write(bytes(blob))
    with pytest.raises(CorruptCheckpointError, match="sha256"):
        load_checkpoint(path)


def test_bad_magic_and_version_rejected(tmp_path):
    path, _ = _one_checkpoint(tmp_path)
    blob = bytearray(open(path, "rb").read())
    wrong = bytes(blob).replace(CKPT_MAGIC, b"NOTLGBTP", 1)
    open(path, "wb").write(wrong)
    with pytest.raises(CorruptCheckpointError, match="magic"):
        load_checkpoint(path)
    blob[len(CKPT_MAGIC):len(CKPT_MAGIC) + 4] = \
        np.uint32(CKPT_FORMAT_VERSION + 9).tobytes()
    open(path, "wb").write(bytes(blob))
    with pytest.raises(IncompatibleCheckpointError, match="format"):
        load_checkpoint(path)


def test_schema_drift_rejected(tmp_path):
    path, _ = _one_checkpoint(tmp_path)
    X2, y2 = _problem(seed=99)
    other = Dataset(X2 * 3.0 + 1.0, label=y2)
    with pytest.raises(IncompatibleCheckpointError, match="binning"):
        resume_booster(path, other)


def test_load_latest_falls_back_past_corrupt_newest(tmp_path):
    p, make_ds = _make("memory", "strict")
    d = str(tmp_path / "ckpts")
    b = lgb.Booster(dict(p), make_ds())
    b.update()
    save_checkpoint(b, d)
    b.update()
    newest = save_checkpoint(b, d)
    blob = bytearray(open(newest, "rb").read())
    blob[-1] ^= 0xFF
    open(newest, "wb").write(bytes(blob))

    path, found = load_latest(d)
    assert path is not None and path != newest
    assert found["meta"]["iter"] == 1
    assert [q for q, _ in found["rejected"]] == [newest]

    # and the resumable loop rides the fallback to the same forest
    ref = _reference(p, make_ds, 4)
    with pytest.warns(UserWarning, match="corrupt checkpoint"):
        res = train_resumable(dict(p), make_ds(), 4, checkpoint_dir=d,
                              checkpoint_rounds=10, resume=True)
    assert res.completed and res.resumed_from == path
    _assert_same_run(ref, res.booster)


def test_keep_last_prunes_old_generations(tmp_path):
    p, make_ds = _make("memory", "strict")
    d = str(tmp_path / "ckpts")
    res = train_resumable(dict(p), make_ds(), 5, checkpoint_dir=d,
                          checkpoint_rounds=1, keep_last=2, resume=False)
    assert res.completed
    paths = list_checkpoints(d)
    assert len(paths) == 2
    assert load_checkpoint(paths[-1])[1]["iter"] == 5
    assert not [n for n in os.listdir(d) if n.startswith(".tmp-")]

"""Streaming quantile sketch + from_blocks construction tests (ISSUE 7).

Pins the exactness contract documented in data/sketch.py: bit-identical
BinMapper on the exact fast path, exact at any n for bounded-vocabulary
columns, eps-rank-bounded edges on the GK path — plus the from_blocks
input validation surface.
"""

import numpy as np
import pytest

from lightgbm_tpu.data.sketch import (GKSummary, StreamingBinMapperBuilder,
                                      _FeatureSketch)
from lightgbm_tpu.dataset import (BinMapper, Dataset, _weighted_quantile,
                                  numeric_bin_bounds)


def _mapper_equal(a: BinMapper, b: BinMapper) -> bool:
    if not np.array_equal(a.n_bins, b.n_bins):
        return False
    if not np.array_equal(a.nan_bin, b.nan_bin):
        return False
    return all(np.array_equal(ua, ub)
               for ua, ub in zip(a.upper_bounds, b.upper_bounds))


def _mixed_matrix(n, seed=0):
    """Continuous + low-cardinality + constant + NaN-bearing columns."""
    rng = np.random.default_rng(seed)
    cont = rng.normal(0, 1, n)
    lowcard = rng.integers(0, 7, n).astype(np.float64)
    const = np.full(n, 3.25)
    withnan = rng.normal(2, 5, n)
    withnan[rng.random(n) < 0.1] = np.nan
    return np.column_stack([cont, lowcard, const, withnan])


# ---------------------------------------------------------------- exact path

def test_exact_fast_path_bit_identical():
    X = _mixed_matrix(3000)
    ref = BinMapper.fit(X, max_bin=63, min_data_in_bin=3)
    b = StreamingBinMapperBuilder(num_features=X.shape[1])
    for lo in range(0, len(X), 700):          # ragged last block on purpose
        b.update(X[lo:lo + 700])
    assert _mapper_equal(b.finalize(max_bin=63, min_data_in_bin=3), ref)


@pytest.mark.parametrize("max_bin", [15, 63, 255])
def test_exact_path_max_bin_aware(max_bin):
    X = _mixed_matrix(2500, seed=1)
    ref = BinMapper.fit(X, max_bin=max_bin, min_data_in_bin=3)
    b = StreamingBinMapperBuilder(num_features=X.shape[1]).update(X)
    got = b.finalize(max_bin=max_bin, min_data_in_bin=3)
    assert _mapper_equal(got, ref)
    assert int(got.n_bins.max()) <= max_bin + 1   # +1 for the nan bin


def test_exact_path_single_vs_many_blocks_identical():
    X = _mixed_matrix(2048, seed=2)
    one = StreamingBinMapperBuilder(4).update(X).finalize(63, 3)
    b = StreamingBinMapperBuilder(4)
    for lo in range(0, 2048, 256):
        b.update(X[lo:lo + 256])
    assert _mapper_equal(b.finalize(63, 3), one)


# ------------------------------------------------------------- distinct path

def test_distinct_path_exact_past_capacity():
    # bounded vocabulary: past the exact buffer the tally path must still
    # reproduce the UNSAMPLED in-memory fit bit-for-bit at any n
    rng = np.random.default_rng(3)
    X = rng.integers(0, 40, (6000, 1)).astype(np.float64) / 7.0
    ref = BinMapper.fit(X, max_bin=25, min_data_in_bin=3)
    b = StreamingBinMapperBuilder(1, capacity=500)
    for lo in range(0, 6000, 900):
        b.update(X[lo:lo + 900])
    assert b._sketches[0].mode == "distinct"
    assert _mapper_equal(b.finalize(max_bin=25, min_data_in_bin=3), ref)


def test_weighted_quantile_matches_numpy_linear():
    rng = np.random.default_rng(4)
    distinct = np.unique(rng.normal(0, 3, 200))
    counts = rng.integers(1, 9, len(distinct)).astype(np.int64)
    expanded = np.repeat(distinct, counts)
    qs = np.linspace(0.0, 1.0, 41)[1:-1]
    got = _weighted_quantile(distinct, counts, qs)
    want = np.quantile(expanded, qs, method="linear")
    assert np.array_equal(got, want)          # bitwise, incl. _lerp branch


# ------------------------------------------------------------------- GK path

def _gk_rank_errors(summary, vals, qs):
    srt = np.sort(vals)
    n = len(vals)
    errs = []
    for q, v in zip(qs, summary.query(qs)):
        rank = np.searchsorted(srt, v, side="right")
        errs.append(abs(rank - q * n) / n)
    return np.asarray(errs)


def test_gk_intervals_stay_honest():
    # the load-bearing property: every tuple's TRUE rank sits inside its
    # claimed [rmin, rmin + d] (banding debt is widened into d, never
    # silently dropped) — the query error bound rests on this
    rng = np.random.default_rng(12)
    vals = rng.lognormal(0, 1, 40_000)
    sk = _FeatureSketch(capacity=1000, eps=5e-3, max_distinct=128)
    for lo in range(0, len(vals), 3000):
        sk.update(vals[lo:lo + 3000])
    assert sk.mode == "gk"
    srt = np.sort(vals)
    rmin = np.cumsum(sk.gk.g)
    for i, v in enumerate(sk.gk.v):
        rank = np.searchsorted(srt, v, side="right")
        assert rmin[i] <= rank <= rmin[i] + sk.gk.d[i]


def test_gk_path_rank_error_within_eps():
    rng = np.random.default_rng(5)
    vals = rng.normal(0, 1, 50_000)
    eps = 1e-2
    sk = _FeatureSketch(capacity=1000, eps=eps, max_distinct=256)
    for lo in range(0, len(vals), 4096):
        sk.update(vals[lo:lo + 4096])
    assert sk.mode == "gk"
    qs = np.linspace(0.0, 1.0, 101)[1:-1]
    errs = _gk_rank_errors(sk.gk, vals, qs)
    assert errs.max() <= eps
    # the summary stays compact: O(1/eps) tuples, not O(n)
    assert len(sk.gk.v) < 20 / eps


def test_gk_merge_bound():
    rng = np.random.default_rng(6)
    a_vals = rng.normal(0, 1, 20_000)
    b_vals = rng.normal(2, 1, 20_000)
    eps = 1e-2
    a, b = GKSummary(eps), GKSummary(eps)
    for s, vals in ((a, a_vals), (b, b_vals)):
        for lo in range(0, len(vals), 4096):
            dv, dc = np.unique(vals[lo:lo + 4096], return_counts=True)
            s.insert_distinct(dv, dc.astype(np.int64))
    a.merge(b)
    assert a.n == 40_000
    qs = np.linspace(0.0, 1.0, 51)[1:-1]
    # documented merged bound: eps·n_a + eps·n_b = 2·eps·n
    errs = _gk_rank_errors(a, np.concatenate([a_vals, b_vals]), qs)
    assert errs.max() <= 2 * eps


def test_gk_bounds_close_to_exact():
    rng = np.random.default_rng(7)
    vals = rng.normal(0, 1, 30_000)
    sk = _FeatureSketch(capacity=1000, eps=1e-3, max_distinct=64)
    sk.update(vals)
    ub = sk.bounds(budget=63, min_data_in_bin=3)
    exact = numeric_bin_bounds(63, 3, vals=vals)
    assert len(ub) == len(exact)
    # edges are quantiles of a smooth CDF: eps-rank error -> small value gap
    assert np.max(np.abs(ub - exact)) < 0.05


# ------------------------------------------------------- builder validation

def test_builder_validation():
    with pytest.raises(ValueError, match="num_features"):
        StreamingBinMapperBuilder(0)
    with pytest.raises(ValueError, match="eps"):
        StreamingBinMapperBuilder(3, eps=0.9)
    b = StreamingBinMapperBuilder(3)
    with pytest.raises(ValueError, match="ragged"):
        b.update(np.zeros((10, 4)))
    with pytest.raises(ValueError, match="2-D"):
        b.update(np.zeros((2, 3, 4)))
    with pytest.raises(ValueError, match="no rows"):
        StreamingBinMapperBuilder(3).finalize()


# ---------------------------------------------------- from_blocks validation

def _blocks(n=1024, f=5, nb=4, seed=0, with_y=True):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, f)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    step = n // nb
    out = []
    for lo in range(0, n, step):
        if with_y:
            out.append((X[lo:lo + step], y[lo:lo + step]))
        else:
            out.append(X[lo:lo + step])
    return out


def test_from_blocks_rejects_one_shot_generator():
    gen = (b for b in _blocks())
    with pytest.raises(ValueError, match="one-shot generator"):
        Dataset.from_blocks(gen, params={"stream_block_rows": 256})


def test_from_blocks_rejects_ragged_features():
    blocks = _blocks(with_y=False)
    blocks[2] = blocks[2][:, :3]
    with pytest.raises(ValueError, match="feature"):
        Dataset.from_blocks(blocks,
                            params={"stream_block_rows": 256}).construct()


def test_from_blocks_rejects_dtype_mismatch():
    blocks = _blocks(with_y=False)
    blocks[1] = blocks[1].astype(np.float64)
    with pytest.raises(ValueError, match="dtype"):
        Dataset.from_blocks(blocks,
                            params={"stream_block_rows": 256}).construct()


def test_from_blocks_rejects_bad_tuple_and_double_label():
    blocks = _blocks()
    bad = blocks[:1] + [(blocks[1][0], blocks[1][1], None, None)]
    with pytest.raises(ValueError, match=r"\(X, y\)"):
        Dataset.from_blocks(bad, params={"stream_block_rows": 256})
    with pytest.raises(ValueError, match="label"):
        Dataset.from_blocks(_blocks(),
                            label=np.zeros(1024, np.float32),
                            params={"stream_block_rows": 256})


def test_from_blocks_rejects_empty_and_bad_block_rows():
    with pytest.raises(ValueError, match="no rows|empty"):
        Dataset.from_blocks([], params={"stream_block_rows": 256})
    with pytest.raises(ValueError, match="multiple"):
        Dataset.from_blocks(_blocks(), params={"stream_block_rows": 100})


def test_from_blocks_binned_codes_match_in_memory():
    rng = np.random.default_rng(11)
    X = rng.normal(0, 1, (1500, 6)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    params = {"max_bin": 63, "stream_block_rows": 512}
    ref = Dataset(X, label=y, params=dict(params)).construct()
    blocks = [(X[lo:lo + 512], y[lo:lo + 512]) for lo in range(0, 1500, 512)]
    ds = Dataset.from_blocks(blocks, params=dict(params)).construct()
    assert ds.is_streamed and ds.block_store is not None
    got = ds.block_store.gather_rows(np.arange(1500))
    want = np.asarray(ref.X_binned)[:1500]
    assert np.array_equal(got, want.astype(got.dtype))
    assert np.array_equal(np.asarray(ds.y)[:1500], y)   # y pads to 256-mult

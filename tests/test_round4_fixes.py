"""Round-4 regression tests: ADVICE r3 fixes + lazy tree store."""

import numpy as np
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb


def test_approx_top_mask_outlier_robust():
    """ADVICE r3 (medium): a single huge |gradient| must not collapse the
    bucketed threshold to first-k-by-index — iterative refinement keeps
    the selection a true top-k up to final-bucket tie-breaking."""
    from lightgbm_tpu.ops.sampling import approx_top_mask

    rng = np.random.default_rng(0)
    n, k = 100_000, 20_000
    x = np.abs(rng.normal(0, 0.01, n)).astype(np.float32)
    x[12345] = 50.0                       # the outlier
    sel = np.asarray(approx_top_mask(jnp.asarray(x),
                                     jnp.ones(n, bool), k))
    true_top = np.zeros(n, bool)
    true_top[np.argsort(-x)[:k]] = True
    assert sel.sum() == k
    assert sel[12345]
    assert (sel & true_top).sum() / k > 0.97


def test_approx_top_mask_exact_count_edges():
    from lightgbm_tpu.ops.sampling import approx_top_mask

    ones = jnp.ones(1000, jnp.float32)
    v = jnp.ones(1000, bool)
    assert np.asarray(approx_top_mask(ones, v, 100)).sum() == 100  # ties
    assert np.asarray(approx_top_mask(ones, v, 5000)).sum() == 1000
    assert np.asarray(approx_top_mask(ones, v, 0)).sum() == 0
    half = jnp.asarray(np.arange(1000) % 2 == 0)
    s = np.asarray(approx_top_mask(ones, half, 300))
    assert s.sum() == 300 and not (s & ~np.asarray(half)).any()


@pytest.fixture(scope="module")
def small_reg():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(1500, 6)).astype(np.float32)
    y = (X[:, 0] + np.sin(X[:, 1]) + 0.1 * rng.normal(size=1500)
         ).astype(np.float32)
    return X, y


def test_tree_store_segments_match_host_loop(small_reg):
    """Fused segments stored stacked must predict identically to the
    per-round host loop, including staged prefixes and save/load."""
    X, y = small_reg
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    p = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "fused_segment_rounds": 7}
    b = lgb.train(p, ds, num_boost_round=20)     # 7+7+6 stacked segments
    ref = lgb.Booster(p, ds)
    for _ in range(20):
        ref.update()                             # per-round singles
    np.testing.assert_allclose(b.predict(X), ref.predict(X),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(b.predict(X, num_iteration=9),
                               ref.predict(X, num_iteration=9),
                               rtol=1e-5, atol=1e-5)
    # per-tree views materialize lazily and round-trip through save/load
    b2 = lgb.Booster(model_str=b.model_to_string())
    np.testing.assert_allclose(b2.predict(X), b.predict(X),
                               rtol=1e-6, atol=1e-6)


def test_batched_fused_kernel_parity():
    """The element-grid batched histogram kernel (wide-segment vmap path)
    must match the per-element reference, including feature blocking."""
    from lightgbm_tpu.ops.histogram import compute_histograms
    from lightgbm_tpu.ops.histogram_pallas import hist_fused_pallas_batched

    rng = np.random.default_rng(7)
    n, F, B, K, S, E = 3000, 6, 32, 24, 3, 4
    bins = jnp.asarray(rng.integers(0, B, (n, F)).astype(np.uint8))
    stats = jnp.asarray(rng.normal(0, 1, (E, n, S)).astype(np.float32))
    seg = jnp.asarray(rng.integers(-1, K + 1, (E, n)).astype(np.int32))
    got = hist_fused_pallas_batched(bins, stats, seg, K, B,
                                    hist_dtype="f32")
    assert got.shape == (E, K, F, B, S)
    for ei in range(E):
        ref = compute_histograms(bins, stats[ei], seg[ei], K, B,
                                 impl="jnp")
        np.testing.assert_allclose(np.asarray(got[ei]), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)
    # feature-blocked path (52*256*126*4 = 6.7 MB accumulator exceeds the
    # 6 MB VMEM budget, forcing the f_blk-halving + pad/trim branch)
    F2, K2 = 52, 42
    bins2 = jnp.asarray(rng.integers(0, 256, (1024, F2)).astype(np.uint8))
    stats2 = jnp.asarray(rng.normal(0, 1, (2, 1024, 3)).astype(np.float32))
    seg2 = jnp.asarray(rng.integers(0, K2, (2, 1024)).astype(np.int32))
    g2 = hist_fused_pallas_batched(bins2, stats2, seg2, K2, 256,
                                   hist_dtype="f32")
    for ei in range(2):
        ref = compute_histograms(bins2, stats2[ei], seg2[ei], K2, 256,
                                 impl="jnp")
        np.testing.assert_allclose(np.asarray(g2[ei]), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


def test_parity_preset_expands_to_quality_config():
    import warnings as _w

    from lightgbm_tpu.config import parse_params

    p = parse_params({"objective": "binary", "preset": "parity"})
    # TRUE-STRICT order + EXACT f32 histograms on the XLA path (strict on
    # jnp is clean on this worker — the intermittent fault follows
    # strict+pallas; PERF.md "AUC parity — NORTH STAR MET")
    assert p.grow_policy == "leafwise"
    assert p.extra.get("hist_dtype") == "f32"
    assert p.extra.get("hist_impl") == "jnp"
    # explicit user keys still win over the preset
    p2 = parse_params({"objective": "binary", "preset": "parity",
                       "grow_policy": "frontier"})
    assert p2.grow_policy == "frontier"
    # unknown preset names warn instead of vanishing silently
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        parse_params({"objective": "binary", "preset": "partiy"})
    assert any("preset" in str(r.message) for r in rec)


def test_fused_cv_multiclass_matches_host_loop():
    """VERDICT r3 #8: the fused configs-x-folds program now vmaps the
    class axis; its cv curve must track the host loop.  (Tolerance is
    looser than the single-output test: the fused path uses global class
    priors as init while the host loop re-derives them per fold — same
    known init difference the l2 fused test carries.)"""
    rng = np.random.default_rng(7)
    n = 1200
    X = rng.normal(size=(n, 6)).astype(np.float32)
    logits = np.stack([X[:, 0] + 0.5 * X[:, 1], X[:, 2] - X[:, 0],
                       0.8 * X[:, 3]], 1)
    y = logits.argmax(1).astype(np.float32)
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    p = {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
         "verbosity": -1, "learning_rate": 0.1}
    from lightgbm_tpu.config import parse_params
    from lightgbm_tpu.models.fused import fused_cv_eligible
    assert fused_cv_eligible(parse_params(p), None, None, ds)
    fused = lgb.cv(p, ds, num_boost_round=30, nfold=3, stratified=False,
                   early_stopping_rounds=5, seed=11)
    # eval_train_metric forces the host loop without changing training
    host = lgb.cv(p, ds, num_boost_round=30, nfold=3, stratified=False,
                  early_stopping_rounds=5, seed=11, eval_train_metric=True)
    fm = np.asarray(fused["valid multi_logloss-mean"])
    hm = np.asarray(host["valid multi_logloss-mean"])
    k = min(len(fm), len(hm))
    np.testing.assert_allclose(fm[:k], hm[:k], rtol=3e-2, atol=1e-3)
    assert fused.best_score == pytest.approx(host.best_score, rel=2e-2)


def test_tree_store_mutation_paths(small_reg):
    """pop / setitem / mixed update() + update_many on the lazy store."""
    X, y = small_reg
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    p = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "fused_segment_rounds": 5}
    b = lgb.Booster(p, ds)
    b.update_many(10)
    b.update()                                   # single after segments
    assert b.num_trees() == 11
    b.rollback_one_iter()                        # pop
    assert b.num_trees() == 10
    before = b.predict(X)
    t3 = b.trees[3]                              # materialize mid-segment
    b.trees[3] = t3                              # setitem round-trip
    np.testing.assert_allclose(b.predict(X), before, rtol=0, atol=0)
    leaves = b.predict(X[:8], pred_leaf=True)
    assert leaves.shape == (8, 10)


def test_auto_wave_tail_regimes():
    """The auto tail rule (r5): greedy only for mid-size pointwise tasks
    far from leaf-budget saturation (measured quality-neutral at the
    diamonds shape); EXACT — strict order via overgrow + replay — for
    large data (the AUC-parity north star), budget-saturating small
    data, and ranking objectives at any size (greedy costs ~6e-2 NDCG@10
    on the MSLR bench)."""
    from lightgbm_tpu.config import parse_params
    from lightgbm_tpu.models.gbdt import resolve_wave_width

    diamonds = parse_params({"objective": "regression", "num_leaves": 31})
    assert resolve_wave_width(diamonds, 46_080) < 0          # greedy
    tiny = parse_params({"objective": "regression", "num_leaves": 31})
    assert resolve_wave_width(tiny, 8_192) >= 1024           # exact
    rank = parse_params({"objective": "lambdarank", "num_leaves": 63})
    assert resolve_wave_width(rank, 100_096) >= 1024         # exact
    assert resolve_wave_width(rank, 1 << 22) >= 1024         # exact, any n
    big = parse_params({"objective": "binary", "num_leaves": 127})
    assert resolve_wave_width(big, 1 << 20) >= 1024          # exact

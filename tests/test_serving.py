"""Serving subsystem: packed forest format, runtime, micro-batching, CLI.

Covers the r6 acceptance criteria: packed round-trip parity vs
Booster.predict (incl. multiclass + categorical), ingest validation
rejecting cyclic/dangling trees, bucket rounding + padding-mask
correctness at batch sizes 1/7/128/1000, LRU eviction, the
compile-counter bound for mixed-batch workloads, and micro-batch
coalescing/timeout behavior with a mocked clock (zero sleeps).
"""

import io
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import (
    MicroBatcher,
    PACKED_FORMAT_VERSION,
    PackedForest,
    PackedForestError,
    PendingPrediction,
    PredictorRuntime,
    RequestTimeout,
    ServingStats,
    bucket_for,
    pack_booster,
)

TOL = 1e-6


# ---------------------------------------------------------------------------
# model fixtures (kept tiny: CPU compiles dominate this suite's wall time)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def reg_booster(small_regression):
    X, y = small_regression
    return X, lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=12)


@pytest.fixture(scope="module")
def mc_booster():
    rng = np.random.default_rng(7)
    n, f = 900, 4
    X = rng.normal(size=(n, f))
    y = ((X[:, 0] + X[:, 1] > 0).astype(int)
         + (X[:, 2] > 0.5).astype(int)).astype(np.float64)
    b = lgb.train(
        {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
         "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=5)
    return X, b


@pytest.fixture(scope="module")
def cat_booster():
    rng = np.random.default_rng(11)
    n = 900
    cat = rng.integers(0, 12, n).astype(float)
    X = np.column_stack([cat, rng.normal(size=(n, 2))])
    y = (np.where(cat % 3 == 0, 2.0, -1.0) + 0.3 * X[:, 1]
         + 0.05 * rng.normal(size=n))
    b = lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbosity": -1,
         "min_data_in_leaf": 5},
        lgb.Dataset(X, label=y, categorical_feature=[0]), num_boost_round=6)
    return X, b


def _roundtrip(booster, tmp_path, name="m.npz", **kw):
    path = os.path.join(str(tmp_path), name)
    pack_booster(booster, **kw).save(path)
    return PackedForest.load(path)


# ---------------------------------------------------------------------------
# packed round-trip parity
# ---------------------------------------------------------------------------
def test_packed_roundtrip_regression(reg_booster, tmp_path):
    X, b = reg_booster
    rt = PredictorRuntime(_roundtrip(b, tmp_path))
    got = rt.predict(X[:300])
    assert np.abs(got - b.predict(X[:300])).max() <= TOL
    # raw_score and staged truncation share the parity bound
    raw = rt.predict(X[:100], raw_score=True)
    assert np.abs(raw - b.predict(X[:100], raw_score=True)).max() <= TOL
    st = rt.predict(X[:100], num_iteration=5)
    assert np.abs(st - b.predict(X[:100], num_iteration=5)).max() <= TOL


def test_packed_roundtrip_multiclass(mc_booster, tmp_path):
    X, b = mc_booster
    pf = _roundtrip(b, tmp_path)
    assert pf.num_class == 3
    rt = PredictorRuntime(pf)
    got = rt.predict(X[:200])
    ref = b.predict(X[:200])
    assert got.shape == ref.shape == (200, 3)
    assert np.abs(got - ref).max() <= TOL
    assert np.abs(got.sum(axis=1) - 1.0).max() < 1e-5


def test_packed_roundtrip_categorical(cat_booster, tmp_path):
    X, b = cat_booster
    pf = _roundtrip(b, tmp_path)
    assert pf.is_cat_split is not None and pf.is_cat_split.any()
    rt = PredictorRuntime(pf)
    assert np.abs(rt.predict(X[:200]) - b.predict(X[:200])).max() <= TOL


def test_predict_numpy_oracle_parity(mc_booster, tmp_path):
    X, b = mc_booster
    pf = _roundtrip(b, tmp_path)
    codes = pf.bin_mapper.transform(X[:64])
    got = pf.predict_numpy(codes, raw_score=False)
    assert np.abs(got - b.predict(X[:64])).max() <= TOL


def test_booster_save_model_npz_roundtrip(reg_booster, tmp_path):
    """.npz routing through save_model/Booster(model_file=...)."""
    X, b = reg_booster
    path = os.path.join(str(tmp_path), "model.npz")
    b.save_model(path)
    b2 = lgb.Booster(model_file=path)
    assert np.abs(b2.predict(X[:200]) - b.predict(X[:200])).max() <= TOL
    assert b2.num_trees() == b.num_trees()
    assert b2.feature_name() == b.feature_name()


def test_pack_truncation_semantics(reg_booster, tmp_path):
    X, b = reg_booster
    pf = _roundtrip(b, tmp_path, name="trunc.npz", num_iteration=4)
    assert pf.num_trees == 4
    assert pf.best_iteration == -1          # stored best no longer indexes
    rt = PredictorRuntime(pf)
    assert np.abs(rt.predict(X[:50])
                  - b.predict(X[:50], num_iteration=4)).max() <= TOL
    with pytest.raises(ValueError):
        pack_booster(b, start_iteration=b.num_trees())


# ---------------------------------------------------------------------------
# ingest validation
# ---------------------------------------------------------------------------
def _tamper_and_reload(pf, tmp_path, name, mutate):
    mutate(pf)
    path = os.path.join(str(tmp_path), name)
    pf.save(path)                            # save() does not re-validate
    return path


def test_ingest_rejects_cycle(reg_booster, tmp_path):
    X, b = reg_booster
    pf = _roundtrip(b, tmp_path, name="c0.npz")

    def mk_cycle(p):
        p.left[0, 0] = 0                     # root's left child is the root

    path = _tamper_and_reload(pf, tmp_path, "cyc.npz", mk_cycle)
    with pytest.raises(PackedForestError, match="reachable twice"):
        PackedForest.load(path)
    # validate=False loads without raising; traversal still terminates
    # because the convergence loop is bounded by node capacity
    pf_raw = PackedForest.load(path, validate=False)
    out = pf_raw.to_tree()
    from lightgbm_tpu.ops.predict import predict_tree_binned
    import jax.tree_util as jtu
    one = jtu.tree_map(lambda a: a[0], out)
    codes = pf_raw.bin_mapper.transform(X[:8])
    vals = predict_tree_binned(one, np.asarray(codes), max_depth_cap=None)
    assert np.asarray(vals).shape == (8,)    # terminated, no hang


def test_ingest_rejects_dangling_child(reg_booster, tmp_path):
    pf = _roundtrip(reg_booster[1], tmp_path, name="d0.npz")

    def dangle(p):
        internal = np.argwhere(~p.is_leaf[0]
                               & (p.left[0] >= 0)).ravel()
        p.left[0, internal[0]] = -1

    path = _tamper_and_reload(pf, tmp_path, "dang.npz", dangle)
    with pytest.raises(PackedForestError, match="dangling"):
        PackedForest.load(path)


def test_ingest_rejects_out_of_range_child(reg_booster, tmp_path):
    pf = _roundtrip(reg_booster[1], tmp_path, name="o0.npz")

    def oob(p):
        internal = np.argwhere(~p.is_leaf[0] & (p.left[0] >= 0)).ravel()
        p.right[0, internal[0]] = p.capacity + 5

    path = _tamper_and_reload(pf, tmp_path, "oob.npz", oob)
    with pytest.raises(PackedForestError, match="out of range"):
        PackedForest.load(path)


def test_ingest_rejects_bad_feature_and_nonfinite_leaf(reg_booster,
                                                      tmp_path):
    pf = _roundtrip(reg_booster[1], tmp_path, name="f0.npz")

    def badfeat(p):
        internal = np.argwhere(~p.is_leaf[0] & (p.left[0] >= 0)).ravel()
        p.split_feature[0, internal[0]] = 999

    path = _tamper_and_reload(pf, tmp_path, "feat.npz", badfeat)
    with pytest.raises(PackedForestError, match="feature"):
        PackedForest.load(path)

    pf2 = _roundtrip(reg_booster[1], tmp_path, name="n0.npz")

    def nanleaf(p):
        leaf = np.argwhere(p.is_leaf[0]).ravel()
        p.leaf_value[0, leaf[0]] = np.nan

    path2 = _tamper_and_reload(pf2, tmp_path, "nan.npz", nanleaf)
    with pytest.raises(PackedForestError, match="non-finite"):
        PackedForest.load(path2)


def test_ingest_rejects_foreign_and_future_files(reg_booster, tmp_path):
    foreign = os.path.join(str(tmp_path), "foreign.npz")
    np.savez(foreign, stuff=np.arange(4))
    with pytest.raises(PackedForestError, match="missing meta_json"):
        PackedForest.load(foreign)

    pf = _roundtrip(reg_booster[1], tmp_path, name="v0.npz")
    path = os.path.join(str(tmp_path), "future.npz")
    pf.save(path)
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files}
    meta = json.loads(bytes(arrays["meta_json"]).decode())
    meta["format_version"] = PACKED_FORMAT_VERSION + 1
    arrays["meta_json"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    np.savez(path, **arrays)
    with pytest.raises(PackedForestError, match="newer than supported"):
        PackedForest.load(path)


def test_validate_recomputes_depth_cap(reg_booster, tmp_path):
    pf = _roundtrip(reg_booster[1], tmp_path, name="dc.npz")
    stored = pf.depth_cap
    pf.depth_cap = 1                         # lie, as a hostile file could
    assert pf.validate().depth_cap == stored


# ---------------------------------------------------------------------------
# runtime: buckets, padding, compile cache
# ---------------------------------------------------------------------------
def test_bucket_for_rounding():
    cases = {1: 1, 2: 2, 3: 4, 7: 8, 8: 8, 128: 128, 129: 256,
             1000: 1024, 16384: 16384}
    for n, want in cases.items():
        assert bucket_for(n, 16384) == want
    assert bucket_for(1000, 256) == 256      # capped at max_bucket
    assert bucket_for(0, 16384) == 1


@pytest.mark.parametrize("n", [1, 7, 128, 1000])
def test_bucket_padding_parity(reg_booster, tmp_path, n):
    """Padded rows never leak into real outputs, at every bucket shape."""
    X, b = reg_booster
    rt = PredictorRuntime(_roundtrip(b, tmp_path), max_bucket=256)
    Xn = np.resize(X, (n, X.shape[1]))
    got = rt.predict(Xn)
    assert got.shape == (n,)
    assert np.abs(got - b.predict(Xn)).max() <= TOL


def test_compile_counter_mixed_batches(reg_booster, tmp_path):
    """Acceptance: a mixed-size workload compiles at most len(buckets)
    programs — sizes from {1..1000} collapse onto power-of-two buckets."""
    X, b = reg_booster
    rt = PredictorRuntime(_roundtrip(b, tmp_path), max_bucket=1024)
    rng = np.random.default_rng(3)
    sizes = [1, 7, 128, 1000] + list(rng.integers(1, 1001, size=12))
    for n in sizes:
        Xn = np.resize(X, (int(n), X.shape[1]))
        got = rt.predict(Xn)
        assert np.abs(got - b.predict(Xn)).max() <= TOL
    assert rt.num_compiles <= len(rt.buckets)
    info = rt.cache_info()
    assert info["num_compiles"] == rt.num_compiles
    # repeating the workload is all cache hits
    before = rt.num_compiles
    for n in sizes[:6]:
        rt.predict(np.resize(X, (int(n), X.shape[1])))
    assert rt.num_compiles == before


def test_chunking_beyond_max_bucket(reg_booster, tmp_path):
    X, b = reg_booster
    rt = PredictorRuntime(_roundtrip(b, tmp_path), max_bucket=64)
    got = rt.predict(X[:300])                # 4 full chunks + remainder
    assert np.abs(got - b.predict(X[:300])).max() <= TOL
    assert max(k[0] for k in rt._cache) <= 64


def test_lru_eviction_recompiles(reg_booster, tmp_path):
    X, b = reg_booster
    rt = PredictorRuntime(_roundtrip(b, tmp_path), max_bucket=1024,
                          max_cache_entries=2)
    for n in (1, 2, 4):                      # 3 buckets through a 2-slot LRU
        rt.predict(X[:n])
    assert len(rt._cache) == 2
    assert (1, False) not in rt._cache       # oldest evicted
    c = rt.num_compiles
    rt.predict(X[:1])                        # evicted bucket recompiles
    assert rt.num_compiles == c + 1
    rt.predict(X[:4])                        # survivor still cached
    assert rt.num_compiles == c + 1


def test_empty_batch_and_bad_max_bucket(reg_booster, tmp_path):
    X, b = reg_booster
    pf = _roundtrip(b, tmp_path)
    rt = PredictorRuntime(pf)
    assert rt.predict(X[:0]).shape == (0,)
    with pytest.raises(ValueError, match="power of two"):
        PredictorRuntime(pf, max_bucket=300)


def test_stats_snapshot_counters(reg_booster, tmp_path):
    X, b = reg_booster
    rt = PredictorRuntime(_roundtrip(b, tmp_path), stats=ServingStats())
    rt.predict(X[:7])
    rt.predict(X[:7])
    snap = rt.stats.snapshot()
    bk = {e["bucket"]: e for e in snap["buckets"]}[8]
    assert bk["dispatches"] == 2 and bk["rows"] == 14
    assert bk["cache_hits"] == 1 and bk["cache_misses"] == 1
    assert bk["padded_rows"] == 2
    assert 0.0 < bk["padding_waste"] < 1.0
    assert bk["latency_p50_ms"] >= 0.0
    json.dumps(snap)                         # snapshot is JSON-able


def test_warm_buckets_precompiles_ladder(reg_booster, tmp_path):
    """warm() builds the whole ladder up front; subsequent traffic of any
    size class is pure cache hits (r7 satellite)."""
    X, b = reg_booster
    rt = PredictorRuntime(_roundtrip(b, tmp_path), max_bucket=64)
    n = rt.warm()
    assert n == len(rt.buckets) == rt.warmed_buckets        # 1..64 fits
    c = rt.num_compiles
    for sz in (1, 2, 5, 33, 64):
        got = rt.predict(np.resize(X, (sz, X.shape[1])))
        assert got.shape == (sz,)
    assert rt.num_compiles == c              # zero compiles on traffic
    # ladder larger than the LRU: warm only the LARGEST entries that fit
    # (warming all would evict programs it just built)
    rt2 = PredictorRuntime(_roundtrip(b, tmp_path, name="m2.npz"),
                           max_bucket=1024, max_cache_entries=3)
    assert rt2.warm() == 3
    assert sorted(k[0] for k in rt2._cache) == [256, 512, 1024]


def test_snapshot_folds_compile_cache(reg_booster, tmp_path):
    X, b = reg_booster
    rt = PredictorRuntime(_roundtrip(b, tmp_path), max_bucket=256,
                          stats=ServingStats())
    rt.predict(X[:5])
    snap = rt.stats.snapshot()
    cc = snap["compile_cache"]
    assert cc["num_compiles"] == rt.num_compiles == 1
    assert cc["buckets_live"] == [8]
    assert cc["warmed_buckets"] == 0
    json.dumps(snap)


# ---------------------------------------------------------------------------
# micro-batching queue (mocked clock, no sleeps)
# ---------------------------------------------------------------------------
class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


@pytest.fixture()
def reg_runtime(reg_booster, tmp_path):
    return PredictorRuntime(_roundtrip(reg_booster[1], tmp_path))


def test_microbatch_coalesces_on_delay(reg_booster, reg_runtime):
    X, b = reg_booster
    clk = _Clock()
    mb = MicroBatcher(reg_runtime, max_batch=8, max_delay_ms=10.0,
                      clock=clk)
    handles = [mb.submit(X[i]) for i in range(3)]
    assert mb.pump() == 0                    # below batch AND below delay
    assert not handles[0].done and mb.pending_count() == 3
    clk.t = 0.011                            # oldest passes max_delay
    assert mb.pump() == 1                    # ONE coalesced dispatch
    got = np.array([h.result() for h in handles])
    assert np.abs(got - b.predict(X[:3])).max() <= TOL
    assert reg_runtime.stats.batched_dispatches == 1


def test_microbatch_full_batch_dispatches_immediately(reg_booster,
                                                      reg_runtime):
    X, b = reg_booster
    mb = MicroBatcher(reg_runtime, max_batch=4, max_delay_ms=1e6,
                      clock=_Clock())
    handles = [mb.submit(X[i]) for i in range(9)]
    assert mb.pump() == 2                    # two full batches, 1 leftover
    assert mb.pending_count() == 1
    assert mb.flush() == 1
    got = np.array([h.result() for h in handles])
    assert np.abs(got - b.predict(X[:9])).max() <= TOL


def test_microbatch_timeout_expires_requests(reg_booster, reg_runtime):
    X, _ = reg_booster
    clk = _Clock()
    mb = MicroBatcher(reg_runtime, max_batch=8, max_delay_ms=1e6,
                      timeout_ms=5.0, clock=clk)
    h_expire = mb.submit(X[0])
    h_live = mb.submit(X[1], timeout_ms=1e6)
    clk.t = 0.006                            # past default deadline
    mb.pump()
    with pytest.raises(RequestTimeout):
        h_expire.result()
    assert not h_live.done                   # own deadline still far
    mb.flush()
    assert h_live.done and h_live.error is None
    assert reg_runtime.stats.timeouts == 1


def test_microbatch_fallback_on_device_error(reg_booster, tmp_path):
    X, b = reg_booster
    rt = PredictorRuntime(_roundtrip(b, tmp_path))
    rt.predict = None                        # simulate a dead device path

    def boom(*a, **k):
        raise RuntimeError("device gone")

    rt.predict = boom
    mb = MicroBatcher(rt, max_batch=2, max_delay_ms=0.0, clock=_Clock())
    h1, h2 = mb.submit(X[0]), mb.submit(X[1])
    mb.pump()
    got = np.array([h1.result(), h2.result()])
    assert np.abs(got - b.predict(X[:2])).max() <= TOL
    assert rt.stats.fallbacks == 2

    mb2 = MicroBatcher(rt, max_batch=1, max_delay_ms=0.0, clock=_Clock(),
                       fallback_unbatched=False)
    h3 = mb2.submit(X[0])
    mb2.pump()
    with pytest.raises(RuntimeError, match="fallback is disabled"):
        h3.result()


def test_microbatch_rejects_bad_row_and_unready_result(reg_booster,
                                                       reg_runtime):
    X, _ = reg_booster
    mb = MicroBatcher(reg_runtime, clock=_Clock())
    h = mb.submit(X[0, :3])                  # wrong feature count
    assert h.done
    with pytest.raises(ValueError, match="features"):
        h.result()
    h2 = mb.submit(X[0])
    with pytest.raises(RuntimeError, match="not ready"):
        h2.result()
    mb.flush()
    assert h2.done
    assert isinstance(h2, PendingPrediction)


def test_microbatch_mixed_truncation_groups(reg_booster, reg_runtime):
    X, b = reg_booster
    mb = MicroBatcher(reg_runtime, max_batch=16, max_delay_ms=0.0,
                      clock=_Clock())
    ha = mb.submit(X[0], num_iteration=3)
    hb = mb.submit(X[1])
    mb.pump()
    assert abs(ha.result() - b.predict(X[:1], num_iteration=3)[0]) <= TOL
    assert abs(hb.result() - b.predict(X[1:2])[0]) <= TOL


# ---------------------------------------------------------------------------
# CLI: lightgbm_tpu serve over stdio (in-process, injected streams)
# ---------------------------------------------------------------------------
def test_cli_serve_inprocess(cat_booster, tmp_path):
    from lightgbm_tpu.__main__ import _serve

    X, b = cat_booster
    path = os.path.join(str(tmp_path), "serve.npz")
    pack_booster(b).save(path)
    lines = "\n".join(",".join(f"{v:.6f}" for v in X[i]) for i in range(7))
    out, err = io.StringIO(), io.StringIO()
    rc = _serve(path, {"max_batch": "4", "show_stats": "true"},
                stdin=io.StringIO(lines + "\n"), stdout=out, stderr=err)
    assert rc == 0
    preds = np.array([float(x) for x in out.getvalue().split()])
    assert np.abs(preds - b.predict(X[:7])).max() <= TOL
    snap = json.loads(err.getvalue())
    assert snap["requests"] == 7


def test_cli_serve_warm_buckets(cat_booster, tmp_path):
    from lightgbm_tpu.__main__ import _serve

    X, b = cat_booster
    path = os.path.join(str(tmp_path), "serve_warm.npz")
    pack_booster(b).save(path)
    lines = "\n".join(",".join(f"{v:.6f}" for v in X[i]) for i in range(3))
    out, err = io.StringIO(), io.StringIO()
    rc = _serve(path, {"warm_buckets": "true", "max_bucket": "8",
                       "show_stats": "true"},
                stdin=io.StringIO(lines + "\n"), stdout=out, stderr=err)
    assert rc == 0
    preds = np.array([float(x) for x in out.getvalue().split()])
    assert np.abs(preds - b.predict(X[:3])).max() <= TOL
    err_lines = err.getvalue().strip().splitlines()
    assert "warmed 4" in err_lines[0]        # ladder 1,2,4,8
    snap = json.loads(err_lines[-1])
    assert snap["compile_cache"]["warmed_buckets"] == 4
    # the request traffic itself compiled nothing new
    assert snap["compile_cache"]["num_compiles"] == 4


def test_cli_serve_json_and_error_lines(mc_booster, tmp_path):
    from lightgbm_tpu.__main__ import _serve

    X, b = mc_booster
    path = os.path.join(str(tmp_path), "serve_mc.npz")
    pack_booster(b).save(path)
    rows = [json.dumps(list(X[i])) for i in range(3)]
    rows.insert(1, "not,a,number,row")       # malformed request mid-stream
    out = io.StringIO()
    rc = _serve(path, {"output_format": "json"},
                stdin=io.StringIO("\n".join(rows) + "\n"),
                stdout=out, stderr=io.StringIO())
    assert rc == 0
    emitted = out.getvalue().strip().splitlines()
    assert len(emitted) == 4
    assert emitted[1].startswith("ERROR:")   # order preserved, stream lives
    ok = np.array([json.loads(emitted[i]) for i in (0, 2, 3)])
    assert np.abs(ok - b.predict(X[:3])).max() <= TOL


# ---------------------------------------------------------------------------
# satellite regressions
# ---------------------------------------------------------------------------
def test_convergence_loop_bounded_on_malformed_tree():
    """predict_tree_binned(max_depth_cap=None) terminates on a cyclic
    tree instead of spinning the while_loop forever."""
    import jax.numpy as jnp

    from lightgbm_tpu.models.tree import Tree
    from lightgbm_tpu.ops.predict import predict_tree_binned

    m = 5
    tree = Tree(
        split_feature=jnp.zeros(m, jnp.int32),
        split_bin=jnp.zeros(m, jnp.int32),
        left=jnp.zeros(m, jnp.int32),        # every child edge -> root
        right=jnp.zeros(m, jnp.int32),
        leaf_value=jnp.zeros(m, jnp.float32),
        is_leaf=jnp.zeros(m, bool),          # no leaf ever closes the path
        count=jnp.zeros(m, jnp.float32),
        split_gain=jnp.zeros(m, jnp.float32),
        num_leaves=jnp.int32(0),
    )
    bins = jnp.zeros((4, 2), jnp.int32)
    vals = predict_tree_binned(tree, bins, max_depth_cap=None)
    assert np.asarray(vals).shape == (4,)    # returned: bounded by capacity


def test_grow_tree_rejects_raw_wave_width_ge_1024():
    """Raw widths >= 1024 collide with resolve_wave_width's exact-tail
    encoding and must be rejected, not silently misrouted."""
    from lightgbm_tpu.models.tree import grow_tree

    with pytest.raises(ValueError, match="resolve_wave_width"):
        grow_tree(None, None, None, None, num_leaves=31, num_bins=256,
                  max_depth=-1, wave_width=2000)
    # a "valid-looking" exact encoding whose overgrow target does not
    # exceed num_leaves is equally meaningless
    with pytest.raises(ValueError, match="resolve_wave_width"):
        grow_tree(None, None, None, None, num_leaves=31, num_bins=256,
                  max_depth=-1, wave_width=31 * 1024 + 42)


def test_fused_part_kernel_has_no_hist_dtype_param():
    import inspect

    from lightgbm_tpu.ops import histogram_pallas as hp

    sig = inspect.signature(hp._fused_part_kernel)
    assert "hist_dtype" not in sig.parameters

"""Regression tests for the round-1 code-review findings."""

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def tiny_reg():
    rng = np.random.default_rng(3)
    X = rng.normal(0, 1, (800, 4))
    y = X[:, 0] + np.sin(2 * X[:, 1]) + 0.05 * rng.normal(0, 1, 800)
    return X, y


def test_custom_fobj_objective_trains(tiny_reg):
    X, y = tiny_reg

    def my_l2(pred, y_true):
        return pred - y_true, jnp.ones_like(pred)

    booster = lgb.train({"objective": my_l2, "verbosity": 0},
                        lgb.Dataset(X, label=y), num_boost_round=10)
    pred = booster.predict(X)
    assert np.sqrt(np.mean((pred - y) ** 2)) < np.std(y)


def test_max_depth_zero_means_unlimited(tiny_reg):
    X, y = tiny_reg
    b0 = lgb.train({"objective": "regression", "max_depth": 0,
                    "verbosity": 0}, lgb.Dataset(X, label=y),
                   num_boost_round=3)
    # must actually split (not constant stumps)
    assert int(b0.trees[0].num_leaves) > 1


def test_max_depth_one_gives_stumps(tiny_reg):
    X, y = tiny_reg
    b = lgb.train({"objective": "regression", "max_depth": 1,
                   "min_data_in_leaf": 1, "verbosity": 0},
                  lgb.Dataset(X, label=y), num_boost_round=3)
    for t in b.trees:
        assert int(t.num_leaves) <= 2


def test_feature_fraction_bynode_samples_per_split():
    rng = np.random.default_rng(5)
    n = 2000
    X = rng.normal(0, 1, (n, 8))
    # every feature matters a bit, feature 0 dominates
    y = 3.0 * X[:, 0] + X[:, 1:].sum(axis=1) * 0.3
    params = {"objective": "regression", "feature_fraction_bynode": 0.25,
              "num_leaves": 31, "verbosity": 0, "seed": 1}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    used = set()
    for t in b.trees:
        feats = np.asarray(t.split_feature)
        internal = np.asarray(~t.is_leaf) & (feats >= 0)
        used.update(feats[internal].tolist())
    # with per-node sampling, splits cannot all be on the dominant feature
    assert len(used) > 1


def test_num_boost_round_zero_clean(tiny_reg):
    X, y = tiny_reg
    dtrain = lgb.Dataset(X, label=y)
    dvalid = lgb.Dataset(X[:100], label=y[:100], reference=dtrain)
    booster = lgb.train({"objective": "regression", "verbosity": 0},
                        dtrain, num_boost_round=0, valid_sets=[dvalid])
    assert booster.num_trees() == 0


def test_subset_clears_stale_group():
    rng = np.random.default_rng(6)
    X = rng.normal(0, 1, (100, 2))
    y = rng.normal(0, 1, 100)
    ds = lgb.Dataset(X, label=y, group=[50, 50])
    ds.construct()
    assert ds.group_id is not None
    sub = ds.subset(np.arange(10))
    assert sub.group_id is None


def test_categorical_overflow_bin_shared():
    from lightgbm_tpu.dataset import BinMapper

    # budget forces keeping only the 3 most frequent of 6 categories
    vals = np.array([0.0] * 50 + [1.0] * 40 + [2.0] * 30 + [3.0] * 2
                    + [4.0] * 2 + [5.0] * 2).reshape(-1, 1)
    bm = BinMapper.fit(vals, max_bin=4, categorical=[0])
    codes = bm.transform(np.array([[0.0], [1.0], [2.0], [3.0], [4.0], [5.0]]))
    kept = codes[:3, 0]
    rare = codes[3:, 0]
    assert len(set(kept.tolist())) == 3
    # all rare categories share ONE overflow bin, distinct from kept bins
    assert len(set(rare.tolist())) == 1
    assert rare[0] not in kept


def test_predict_start_iteration(tiny_reg):
    X, y = tiny_reg
    b = lgb.train({"objective": "regression", "verbosity": 0},
                  lgb.Dataset(X, label=y), num_boost_round=10)
    full = b.predict(X, num_iteration=10, raw_score=True)
    head = b.predict(X, num_iteration=4, raw_score=True)
    tail = b.predict(X, start_iteration=4, num_iteration=6, raw_score=True)
    # init_score appears in both pieces; subtract one copy when recombining
    np.testing.assert_allclose(head + tail - b.init_score_, full,
                               rtol=1e-5, atol=1e-5)


def test_rf_max_features_actually_samples():
    from lightgbm_tpu.sklearn import LGBMRandomForestRegressor

    rng = np.random.default_rng(9)
    n = 1500
    X = rng.normal(0, 1, (n, 6))
    y = 3.0 * X[:, 0] + 0.2 * X[:, 1:].sum(axis=1)
    rf = LGBMRandomForestRegressor(n_estimators=8, max_leaf_nodes=8,
                                   max_features=1, random_state=0,
                                   min_samples_leaf=5)
    rf.fit(X, y)
    used = set()
    for t in rf.booster_.trees:
        feats = np.asarray(t.split_feature)
        internal = np.asarray(~t.is_leaf) & (feats >= 0)
        used.update(feats[internal].tolist())
    # mtry=1 of 6: the dominant feature cannot monopolize every split
    assert len(used) >= 3, used


def test_rollback_restores_valid_eval(tiny_reg):
    X, y = tiny_reg
    dtrain = lgb.Dataset(X[:600], label=y[:600])
    dvalid = lgb.Dataset(X[600:], label=y[600:], reference=dtrain)
    b = lgb.Booster({"objective": "regression", "verbosity": 0,
                     "metric": "l2"}, dtrain)
    b.add_valid(dvalid, "va")
    b.update()
    before = b.eval_valid()[0][2]
    b.update()
    b.rollback_one_iter()
    after = b.eval_valid()[0][2]
    assert abs(before - after) < 1e-6


def test_pallas_histogram_parity():
    import jax

    from lightgbm_tpu.ops.histogram import compute_histograms
    from lightgbm_tpu.ops.histogram_pallas import compute_histograms_pallas

    rng = np.random.default_rng(7)
    n, F, B, K, S = 3000, 4, 32, 2, 3
    bins = jnp.asarray(rng.integers(0, B, (n, F)).astype(np.uint8))
    stats = jnp.asarray(rng.normal(0, 1, (n, S)).astype(np.float32))
    seg = jnp.asarray(rng.integers(0, K + 1, n).astype(np.int32))
    ref = compute_histograms(bins, stats, seg, K, B)
    got = compute_histograms_pallas(bins, stats, seg, K, B, chunk=512)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)

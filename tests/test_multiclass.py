"""Multiclass softmax objective: K trees per round, sklearn parity."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.sklearn import LGBMClassifier


@pytest.fixture(scope="module")
def blobs():
    rng = np.random.default_rng(31)
    n_per = 600
    centers = np.array([[0, 0], [3, 0.5], [1, 3]])
    X = np.concatenate([
        rng.normal(0, 0.9, (n_per, 2)) + c for c in centers])
    y = np.repeat(np.arange(3), n_per).astype(np.float64)
    perm = rng.permutation(len(y))
    return X[perm], y[perm]


def test_multiclass_train_predicts_probabilities(blobs):
    X, y = blobs
    dtrain = lgb.Dataset(X[:1400], label=y[:1400])
    booster = lgb.train({"objective": "multiclass", "num_class": 3,
                         "num_leaves": 15, "verbosity": 0},
                        dtrain, num_boost_round=30)
    p = booster.predict(X[1400:])
    assert p.shape == (len(X) - 1400, 3)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)
    acc = float(np.mean(np.argmax(p, axis=1) == y[1400:]))
    assert acc > 0.85, acc


def test_multiclass_close_to_sklearn_oracle(blobs):
    X, y = blobs
    from sklearn.ensemble import HistGradientBoostingClassifier

    sk = HistGradientBoostingClassifier(
        max_iter=30, learning_rate=0.1, max_leaf_nodes=15,
        early_stopping=False).fit(X[:1400], y[:1400])
    sk_acc = sk.score(X[1400:], y[1400:])

    clf = LGBMClassifier(n_estimators=30, num_leaves=15)
    clf.fit(X[:1400], y[:1400])
    assert clf.n_classes_ == 3
    our_acc = clf.score(X[1400:], y[1400:])
    assert our_acc > sk_acc - 0.05, (our_acc, sk_acc)
    proba = clf.predict_proba(X[1400:])
    assert proba.shape == (len(X) - 1400, 3)


def test_multiclass_early_stopping_and_metric(blobs):
    X, y = blobs
    dtrain = lgb.Dataset(X[:1200], label=y[:1200])
    dvalid = lgb.Dataset(X[1200:1500], label=y[1200:1500], reference=dtrain)
    booster = lgb.train({"objective": "multiclass", "num_class": 3,
                         "learning_rate": 0.4, "num_leaves": 31,
                         "verbosity": 0},
                        dtrain, num_boost_round=200, valid_sets=[dvalid],
                        early_stopping_rounds=5)
    assert 0 < booster.best_iteration <= 200
    assert "multi_logloss" in booster.best_score["valid_0"]


def test_multiclass_save_load_roundtrip(tmp_path, blobs):
    X, y = blobs
    dtrain = lgb.Dataset(X[:900], label=y[:900])
    booster = lgb.train({"objective": "multiclass", "num_class": 3,
                         "num_leaves": 7, "verbosity": 0},
                        dtrain, num_boost_round=8)
    path = str(tmp_path / "mc.json")
    booster.save_model(path)
    loaded = lgb.Booster(model_file=path)
    np.testing.assert_allclose(booster.predict(X[900:950]),
                               loaded.predict(X[900:950]), rtol=1e-5)


def test_multiclass_pred_leaf(blobs):
    X, y = blobs
    dtrain = lgb.Dataset(X[:900], label=y[:900])
    booster = lgb.train({"objective": "multiclass", "num_class": 3,
                         "num_leaves": 7, "verbosity": 0},
                        dtrain, num_boost_round=4)
    leaves = booster.predict(X[:50], pred_leaf=True)
    # LightGBM contract: [n, num_iteration * num_class], leaf ordinals
    assert leaves.shape == (50, 4 * 3)
    assert leaves.min() >= 0 and leaves.max() < 7
    # rows landing in the same leaf get the same class scores
    l2 = booster.predict(X[:50], pred_leaf=True, num_iteration=2)
    assert l2.shape == (50, 2 * 3)
    np.testing.assert_array_equal(l2, leaves[:, :6])


def test_multiclass_refit(blobs):
    X, y = blobs
    dtrain = lgb.Dataset(X[:900], label=y[:900])
    booster = lgb.train({"objective": "multiclass", "num_class": 3,
                         "num_leaves": 7, "verbosity": 0},
                        dtrain, num_boost_round=6)
    ref = booster.refit(X[900:1400], y[900:1400], decay_rate=0.5)
    # structure unchanged, values moved
    for t0, t1 in zip(booster.trees, ref.trees):
        np.testing.assert_array_equal(np.asarray(t0.split_feature),
                                      np.asarray(t1.split_feature))
        assert not np.allclose(np.asarray(t0.leaf_value),
                               np.asarray(t1.leaf_value))
    # refit on the training slice itself keeps accuracy in range
    acc = np.mean(np.argmax(ref.predict(X[1400:]), axis=1) == y[1400:])
    assert acc > 0.8, acc


def test_multiclass_random_forest(blobs):
    """boosting='rf' with multiclass: per-class forests averaged (upstream
    supports rf for any objective); probabilities stay normalized."""
    X, y = blobs
    booster = lgb.train({"objective": "multiclass", "num_class": 3,
                         "boosting": "rf", "bagging_fraction": 0.7,
                         "bagging_freq": 1, "num_leaves": 15,
                         "verbosity": -1},
                        lgb.Dataset(X[:1200], label=y[:1200]),
                        num_boost_round=20)
    proba = booster.predict(X[1200:1500])
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)
    acc = float(np.mean(np.argmax(proba, axis=1) == y[1200:1500]))
    assert acc > 0.8, acc
    # staged predict still averages over the PREFIX forest
    p5 = booster.predict(X[1200:1210], num_iteration=5)
    np.testing.assert_allclose(p5.sum(axis=1), 1.0, rtol=1e-5)
    assert not np.allclose(p5, proba[:10])

"""API-parity surface: refit, save_binary, plotting helpers."""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    n = 3000
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 3)
         + rng.normal(0, 0.1, n)).astype(np.float32)
    return X, y


def test_refit_on_shifted_data(data):
    """refit keeps structure, adapts leaf values toward the new targets."""
    X, y = data
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.2, "verbosity": -1}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=20)
    y_shift = y + 3.0
    b2 = b.refit(X, y_shift, decay_rate=0.5)
    # structures identical
    for t1, t2 in zip(b.trees, b2.trees):
        np.testing.assert_array_equal(np.asarray(t1.split_feature),
                                      np.asarray(t2.split_feature))
    # original untouched; refit moves toward the shifted target
    e_old = float(np.mean(np.abs(b.predict(X) - y_shift)))
    e_new = float(np.mean(np.abs(b2.predict(X) - y_shift)))
    assert e_new < e_old, (e_new, e_old)


def test_save_binary_roundtrip(data, tmp_path):
    X, y = data
    d1 = lgb.Dataset(X, label=y)
    d1.construct()
    path = str(tmp_path / "train.bin.npz")
    d1.save_binary(path)

    d2 = lgb.Dataset(path)
    d2.construct()
    np.testing.assert_array_equal(np.asarray(d1.X_binned),
                                  np.asarray(d2.X_binned))
    np.testing.assert_allclose(d1.get_label(), d2.get_label())
    # training from the reloaded binary matches training from raw
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1}
    b1 = lgb.train(dict(params), d1, num_boost_round=5)
    b2 = lgb.train(dict(params), lgb.Dataset(path), num_boost_round=5)
    np.testing.assert_allclose(b1.predict(X), b2.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_plot_importance_and_metric(data, tmp_path):
    X, y = data
    from lightgbm_tpu.plotting import plot_importance, plot_metric

    evals = {}
    dtrain = lgb.Dataset(X[:2500], label=y[:2500])
    dvalid = dtrain.create_valid(X[2500:], label=y[2500:])
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbosity": -1}, dtrain, num_boost_round=10,
                  valid_sets=[dvalid], evals_result=evals)
    ax = plot_importance(b)
    assert len(ax.patches) > 0
    ax2 = plot_metric(evals)
    assert len(ax2.lines) >= 1


def test_create_tree_digraph(data):
    X, y = data
    from lightgbm_tpu.plotting import create_tree_digraph

    b = lgb.train({"objective": "regression", "num_leaves": 7,
                   "verbosity": -1}, lgb.Dataset(X, label=y),
                  num_boost_round=3)
    dot = create_tree_digraph(b, tree_index=1)
    assert dot.startswith("digraph Tree {") and dot.endswith("}")
    assert dot.count("->") == 2 * 6  # 6 internal nodes, yes+no edges
    assert "leaf" in dot


def test_save_binary_bin_suffix_roundtrip(data, tmp_path):
    """The LightGBM Dataset('train.bin') contract: save_binary normalizes
    the numpy .npz suffix so the SAME path string reloads."""
    X, y = data
    d1 = lgb.Dataset(X, label=y)
    path = str(tmp_path / "train.bin")
    d1.save_binary(path)
    d2 = lgb.Dataset(path)
    d2.construct()
    np.testing.assert_allclose(d1.get_label(), d2.get_label())
    # constructor label overrides the stored one
    y2 = y + 1.0
    d3 = lgb.Dataset(path, label=y2)
    d3.construct()
    np.testing.assert_allclose(d3.get_label(), y2)


def test_refit_weight_and_guardrails(data):
    X, y = data
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    w = np.ones(len(y), np.float32)
    w[: len(y) // 2] = 10.0
    b_w = b.refit(X, y + 1.0, weight=w)
    b_u = b.refit(X, y + 1.0)
    assert not np.allclose(b_w.predict(X[:50]), b_u.predict(X[:50]))
    with pytest.raises(TypeError):
        b.refit(X, y, bogus_arg=1)
    # refit boosters are predict-only
    assert b_w.train_set is None


def test_trees_to_dataframe():
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(400, 3)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] + 0.2 * rng.normal(size=400)).astype(np.float32)
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "num_leaves": 7}, lgb.Dataset(X, label=y),
                  num_boost_round=4)
    df = b.trees_to_dataframe()
    assert set(df.columns) >= {"tree_index", "node_depth", "node_index",
                               "left_child", "right_child", "parent_index",
                               "split_feature", "split_gain", "threshold",
                               "decision_type", "value", "count"}
    assert df.tree_index.nunique() == 4
    # internal rows reference children that exist
    ids = set(df.node_index)
    internal = df[df.split_feature.notna()]
    assert set(internal.left_child).issubset(ids)
    assert set(internal.right_child).issubset(ids)
    # leaves carry values, internals carry gains
    assert df[df.value.notna()].left_child.isna().all()
    assert (internal.split_gain >= 0).all()


def test_reset_parameter_callback():
    """lgb.reset_parameter: learning-rate decay actually changes per-round
    shrinkage (smaller later trees) without recompiling."""
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(3)
    X = rng.normal(size=(600, 3)).astype(np.float32)
    y = (X[:, 0] + 0.2 * rng.normal(size=600)).astype(np.float32)
    ds = lgb.Dataset(X, label=y)
    lrs = [0.3 * (0.5 ** i) for i in range(6)]
    b = lgb.train({"objective": "regression", "verbosity": -1},
                  ds, num_boost_round=6,
                  callbacks=[lgb.reset_parameter(learning_rate=lrs)])
    assert abs(b.params.learning_rate - lrs[-1]) < 1e-9
    # callable form matches the list form exactly
    b2 = lgb.train({"objective": "regression", "verbosity": -1},
                   ds, num_boost_round=6,
                   callbacks=[lgb.reset_parameter(
                       learning_rate=lambda i: 0.3 * (0.5 ** i))])
    np.testing.assert_allclose(b.predict(X[:40]), b2.predict(X[:40]),
                               rtol=1e-6)
    # schedule produced a different model than constant lr
    b3 = lgb.train({"objective": "regression", "verbosity": -1,
                    "learning_rate": 0.3}, ds, num_boost_round=6)
    assert not np.allclose(b.predict(X[:40]), b3.predict(X[:40]))
    # static params refuse to reset
    import pytest
    with pytest.raises(ValueError, match="shape-static"):
        b.reset_parameter({"num_leaves": 63})
    # predict reproduces the per-round schedule exactly: the maintained
    # train predictions (built with each round's OWN lr) must equal
    # predict() (stored trees are normalized to the base lr)
    n_real = 600
    np.testing.assert_allclose(
        np.asarray(b._pred_train)[:n_real],
        b.predict(X, raw_score=True), rtol=1e-5, atol=1e-5)


def test_reset_parameter_in_cv():
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(6)
    X = rng.normal(size=(500, 3)).astype(np.float32)
    y = (X[:, 0] + 0.2 * rng.normal(size=500)).astype(np.float32)
    ds = lgb.Dataset(X, label=y)
    res = lgb.cv({"objective": "regression", "verbosity": -1}, ds,
                 num_boost_round=5, nfold=3, seed=7,
                 callbacks=[lgb.reset_parameter(
                     learning_rate=lambda i: 0.2 * 0.8 ** i)])
    assert len(res["valid l2-mean"]) == 5


def test_early_stopping_min_delta_param():
    """early_stopping_min_delta: a huge delta stops almost immediately,
    while delta=0 keeps improving (LightGBM 4.x parameter)."""
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(4)
    X = rng.normal(size=(1500, 4)).astype(np.float32)
    y = (X[:, 0] + 0.5 * rng.normal(size=1500)).astype(np.float32)
    dtrain = lgb.Dataset(X[:1000], label=y[:1000])
    dvalid = dtrain.create_valid(X[1000:], label=y[1000:])
    b_strict = lgb.train({"objective": "regression", "verbosity": -1,
                          "early_stopping_round": 3,
                          "early_stopping_min_delta": 1e9},
                         dtrain, num_boost_round=100, valid_sets=[dvalid])
    b_loose = lgb.train({"objective": "regression", "verbosity": -1,
                         "early_stopping_round": 3},
                        dtrain, num_boost_round=100, valid_sets=[dvalid])
    assert b_strict.best_iteration <= 4
    assert b_loose.best_iteration > b_strict.best_iteration


def test_dataset_feature_num_bin():
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(5)
    X = np.column_stack([rng.integers(0, 3, 800),
                         rng.normal(size=800)]).astype(np.float32)
    ds = lgb.Dataset(X, label=rng.normal(size=800).astype(np.float32))
    assert ds.feature_num_bin(0) <= 4        # 3 distinct values
    assert ds.feature_num_bin(1) > 50        # continuous
    assert len(ds.get_feature_name()) == 2

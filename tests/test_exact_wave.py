"""Exact-order wave growth (wave_tail="exact"): overgrow + strict replay.

The claim under test (models/tree.py _exact_prune): priority-first
extraction order over the realized gain tree equals descending pathmin
order, so pruning an overgrown wave tree to the top-(num_leaves-1)
expandable nodes by (pathmin desc, id asc) reproduces the STRICT grower's
tree exactly — the r4 gap decomposition showed split ORDER was the entire
residual quality gap of wave growth (PERF.md), so exactness here is the
north-star AUC-parity mechanism.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.models.tree import grow_tree
from lightgbm_tpu.ops.lookup import lookup_values
from lightgbm_tpu.ops.split import SplitContext


def _ctx(min_data=20.0):
    return SplitContext(
        lambda_l1=jnp.float32(0.0), lambda_l2=jnp.float32(0.0),
        min_data_in_leaf=jnp.float32(min_data),
        min_sum_hessian=jnp.float32(1e-3),
        min_gain_to_split=jnp.float32(0.0), max_delta_step=jnp.float32(0.0),
        path_smooth=jnp.float32(0.0))


def _make(seed, n=20000, F=10, B=64):
    rng = np.random.default_rng(seed)
    X = rng.integers(0, B, (n, F)).astype(np.uint8)
    ylat = (X[:, 0] * 0.1 + np.sin(X[:, 1] * 0.3) + X[:, 2] * X[:, 3] * 0.01
            + rng.normal(0, 0.5, n))
    g = (0.0 - ylat).astype(np.float32)
    stats = jnp.stack([jnp.asarray(g), jnp.ones(n), jnp.ones(n)], axis=-1)
    return jnp.asarray(X), stats


def _splits(t):
    m = np.asarray(~t.is_leaf & (t.left >= 0))
    return sorted(zip(np.asarray(t.split_feature)[m].tolist(),
                      np.asarray(t.split_bin)[m].tolist()))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_exact_replay_matches_strict_grower(seed):
    """With full coverage (overgrow to 4x), the exact-mode tree is
    IDENTICAL to the strict grower's: same split multiset, same leaf
    count, same per-row leaf values."""
    nl, B = 31, 64
    bins, stats = _make(seed)
    fmask = jnp.ones(bins.shape[1], jnp.float32)
    t_s, rl_s = grow_tree(bins, stats, fmask, _ctx(), nl, B, -1,
                          wave_width=1, hist_impl="jnp")
    enc = (4 * nl) * 1024 + 16          # overgrow_leaves=124, width=16
    t_e, rl_e = grow_tree(bins, stats, fmask, _ctx(), nl, B, -1,
                          wave_width=enc, hist_impl="jnp")
    assert int(t_s.num_leaves) == int(t_e.num_leaves) == nl
    assert _splits(t_s) == _splits(t_e)
    v_s = np.asarray(lookup_values(rl_s, t_s.leaf_value))
    v_e = np.asarray(lookup_values(rl_e, t_e.leaf_value))
    np.testing.assert_allclose(v_s, v_e, rtol=2e-4, atol=2e-6)


def test_exact_default_overgrow_near_strict():
    """At moderate (1.5x) overgrowth, coverage misses are rare: the
    split multiset differs from strict in at most a few tail splits.
    (The production default is 2.0x — gap-converged on-chip, PERF.md r5.)"""
    from lightgbm_tpu.models.gbdt import _exact_overgrow_target

    nl, B = 31, 64
    bins, stats = _make(0)
    fmask = jnp.ones(bins.shape[1], jnp.float32)
    t_s, _ = grow_tree(bins, stats, fmask, _ctx(), nl, B, -1,
                       wave_width=1, hist_impl="jnp")
    l_over = _exact_overgrow_target(nl, 16, 1.5)
    t_e, _ = grow_tree(bins, stats, fmask, _ctx(), nl, B, -1,
                       wave_width=l_over * 1024 + 16, hist_impl="jnp")
    from collections import Counter

    s_s, s_e = _splits(t_s), _splits(t_e)
    common = sum((Counter(s_s) & Counter(s_e)).values())
    assert int(t_e.num_leaves) == nl
    assert common >= len(s_s) - 3, (s_s, s_e)


def test_exact_row_leaf_consistent():
    """row_leaf returned by exact mode routes every row to the leaf the
    pruned tree structure itself routes it to (remap through the
    overgrown frontier is coherent)."""
    nl, B = 31, 64
    bins, stats = _make(3)
    fmask = jnp.ones(bins.shape[1], jnp.float32)
    enc = 47 * 1024 + 16
    t, rl = grow_tree(bins, stats, fmask, _ctx(), nl, B, -1,
                      wave_width=enc, hist_impl="jnp")
    via_rl = np.asarray(lookup_values(rl, t.leaf_value))
    # traverse the tree directly for every row
    sf = np.asarray(t.split_feature)
    sb = np.asarray(t.split_bin)
    lt = np.asarray(t.left)
    rt = np.asarray(t.right)
    lv = np.asarray(t.leaf_value)
    isl = np.asarray(t.is_leaf)
    Xb = np.asarray(bins)
    out = np.zeros(Xb.shape[0], np.float32)
    for i in range(Xb.shape[0]):
        nd = 0
        while not isl[nd]:
            nd = lt[nd] if Xb[i, sf[nd]] <= sb[nd] else rt[nd]
        out[i] = lv[nd]
    np.testing.assert_allclose(via_rl, out, rtol=1e-5, atol=1e-6)


def test_exact_respects_num_leaves_budget():
    """Exact mode never exceeds the leaf budget and its final capacity is
    the standard 2*num_leaves-1 (stackable into the forest)."""
    nl, B = 16, 32
    bins, stats = _make(5, n=5000, F=6, B=32)
    fmask = jnp.ones(bins.shape[1], jnp.float32)
    t, rl = grow_tree(bins, stats, fmask, _ctx(), nl, B, -1,
                      wave_width=40 * 1024 + 8, hist_impl="jnp")
    assert t.capacity == 2 * nl - 1
    assert int(t.num_leaves) <= nl
    assert int(np.asarray(rl).max()) < t.capacity


def test_resolve_wave_width_exact_encoding():
    """Default tails: exact for large/rank/small-saturating shapes, greedy
    only for mid-size pointwise; encoding decodes to a wave-aligned
    overgrowth target."""
    from lightgbm_tpu.config import parse_params
    from lightgbm_tpu.models.gbdt import resolve_wave_width

    p = parse_params({"objective": "binary", "num_leaves": 127})
    ww = resolve_wave_width(p, 1 << 20)          # large data -> exact
    assert ww >= 1024
    l_over, width = ww // 1024, ww % 1024
    assert 127 < l_over <= 2 * 127 + 64
    assert width == 42
    p2 = parse_params({"objective": "regression", "num_leaves": 31})
    assert resolve_wave_width(p2, 46000) < 0     # mid-size pointwise greedy
    p3 = parse_params({"objective": "lambdarank", "num_leaves": 63})
    assert resolve_wave_width(p3, 100000) >= 1024   # ranking -> exact
    p4 = parse_params({"objective": "binary", "num_leaves": 127,
                       "wave_tail": "greedy"})
    assert resolve_wave_width(p4, 1 << 20) < 0   # explicit override wins


def test_exact_stalled_growth_no_ghost_leaves():
    """When splittable structure exhausts below num_leaves, unused table
    slots must NOT masquerade as leaves (their default parent is the
    root): leaf count, is_leaf sum, and reachability must stay coherent
    (code review r5)."""
    rng = np.random.default_rng(9)
    n = 4096
    # one informative binary feature -> the tree stalls after ~3 splits
    X = rng.integers(0, 2, (n, 3)).astype(np.uint8)
    g = (X[:, 0] * 2.0 - 1.0 + 0.01 * rng.normal(size=n)).astype(np.float32)
    stats = jnp.stack([jnp.asarray(g), jnp.ones(n), jnp.ones(n)], axis=-1)
    fmask = jnp.ones(3, jnp.float32)
    t, rl = grow_tree(jnp.asarray(X), stats, fmask, _ctx(min_data=1),
                      31, 4, -1, wave_width=62 * 1024 + 16,
                      hist_impl="jnp")
    n_leaves = int(t.num_leaves)
    isl = np.asarray(t.is_leaf)
    assert isl.sum() == n_leaves, (isl.sum(), n_leaves)
    # every is_leaf slot must be reachable from the root
    lt, rt = np.asarray(t.left), np.asarray(t.right)
    reach = {0}
    stack = [0]
    while stack:
        i = stack.pop()
        if lt[i] >= 0:
            reach.update((lt[i], rt[i]))
            stack.extend((lt[i], rt[i]))
    assert set(np.flatnonzero(isl)) <= reach
    assert set(np.unique(np.asarray(rl))) <= set(np.flatnonzero(isl))


def test_partition_fused_kernel_matches_unfused():
    """The partition-fused wave kernel (histogram + row routing in one
    pallas call, r5) must produce the same tree as the unfused path —
    same splits, same row routing — in every wave tail mode."""
    nl, B = 31, 64
    bins, stats = _make(4, n=12000, F=8)
    fmask = jnp.ones(bins.shape[1], jnp.float32)
    for enc in (16, -16, 48 * 1024 + 16):        # half, greedy, exact
        t_u, rl_u = grow_tree(bins, stats, fmask, _ctx(), nl, B, -1,
                              wave_width=enc, hist_impl="pallas",
                              hist_dtype="bf16", fuse_partition=False)
        t_f, rl_f = grow_tree(bins, stats, fmask, _ctx(), nl, B, -1,
                              wave_width=enc, hist_impl="pallas",
                              hist_dtype="bf16", fuse_partition=True)
        assert _splits(t_u) == _splits(t_f), enc
        np.testing.assert_array_equal(np.asarray(rl_u), np.asarray(rl_f),
                                      err_msg=str(enc))
        np.testing.assert_allclose(np.asarray(t_u.leaf_value),
                                   np.asarray(t_f.leaf_value),
                                   rtol=1e-5, atol=1e-6)

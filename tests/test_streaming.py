"""Out-of-core training tests (ISSUE 7): streamed-vs-in-memory parity.

The contract under test is BIT-IDENTITY, not tolerance: with the
streamed histogram row_chunk pinned to the block size (see
data/stream_grow.py's layout rules), every per-round arithmetic step is
the same jitted computation the in-memory path runs, so whole trained
models must compare equal with ``np.array_equal`` — strict and wave
growers, single- and multi-block stores, ragged tails included.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis.budgets import (check_stream_budgets,
                                           stream_prefetch_time)
from lightgbm_tpu.data import BlockStore
from lightgbm_tpu.dataset import Dataset


def _problem(n, f, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, f)).astype(np.float32)
    w = rng.normal(0, 1, f)
    logits = (X @ w) * 0.7 + 0.6 * np.sin(X[:, 0] * 2)
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    return X, y


def _trees_equal(a, b):
    for ta, tb in zip(a.trees, b.trees):
        for field in ("split_feature", "split_bin", "left", "right",
                      "leaf_value", "is_leaf"):
            if not np.array_equal(np.asarray(getattr(ta, field)),
                                  np.asarray(getattr(tb, field))):
                return False
    return len(a.trees) == len(b.trees)


def _train_pair(n, f, block_rows, extra, rounds=3, seed=0):
    X, y = _problem(n, f, seed)
    base = dict(objective="binary", num_leaves=15, learning_rate=0.1,
                max_bin=63, min_data_in_leaf=5, verbose=-1, seed=7)
    base.update(extra)
    # binning params ride on the Dataset (LightGBM convention); the
    # in-memory histogram row_chunk is pinned to the streamed block size
    # so both sides accumulate partial sums in the same order
    p_mem = dict(base, row_chunk=block_rows)
    p_st = dict(base, stream_block_rows=block_rows)
    mem = lgb.Booster(p_mem, Dataset(X, label=y, params=dict(p_mem)))
    blocks = [(X[lo:lo + block_rows], y[lo:lo + block_rows])
              for lo in range(0, n, block_rows)]
    st = lgb.Booster(p_st, Dataset.from_blocks(blocks, params=dict(p_st)))
    for _ in range(rounds):
        mem.update()
        st.update()
    return mem, st


GROWERS = [("strict", {"wave_width": 1}),
           ("wave_half", {"wave_width": 4}),
           ("wave_exact", {"wave_width": 4, "wave_tail": "exact"})]


@pytest.mark.parametrize("name,extra", GROWERS, ids=[g[0] for g in GROWERS])
@pytest.mark.parametrize("n,f,block_rows", [
    (1800, 5, 512),      # multi-block, ragged 264-row tail
    (500, 13, 512),      # single block, padded
    (2048, 136, 512),    # wide (the Higgs/MSLR feature regime), 4 blocks
])
def test_streamed_trees_bit_identical(name, extra, n, f, block_rows):
    mem, st = _train_pair(n, f, block_rows, extra)
    assert st._streamed and not getattr(mem, "_streamed", False)
    assert _trees_equal(mem, st)
    assert np.array_equal(np.asarray(mem._pred_train),
                          np.asarray(st._pred_train))


def test_streamed_bagging_and_feature_fraction_bit_identical():
    mem, st = _train_pair(1800, 8, 512,
                          {"bagging_fraction": 0.7, "bagging_freq": 1,
                           "feature_fraction": 0.6}, rounds=4)
    assert _trees_equal(mem, st)


def test_streamed_predictions_match_in_memory():
    mem, st = _train_pair(1500, 6, 512, {"wave_width": 4}, rounds=3)
    Xq, _ = _problem(300, 6, seed=99)
    assert np.array_equal(mem.predict(Xq), st.predict(Xq))


# ------------------------------------------------------------- block store

def test_block_store_prefetch_and_odometer():
    rng = np.random.default_rng(1)
    codes = rng.integers(0, 50, (1200, 4)).astype(np.uint8)
    store = BlockStore.from_binned(codes, block_rows=512)
    assert store.num_blocks == 3 and store.num_rows == 1200
    assert store.bytes_streamed == 0
    seen = []
    for off, dev in store.device_blocks():
        seen.append((off, np.asarray(dev)))
    assert [off for off, _ in seen] == [0, 512, 1024]   # row offsets
    got = np.concatenate([b for _, b in seen])[:1200]
    assert np.array_equal(got, codes)
    # every block crossed the (simulated) PCIe once
    assert store.bytes_streamed == sum(b.nbytes for b in store.blocks)
    assert np.array_equal(store.gather_rows(np.array([0, 700, 1199])),
                          codes[[0, 700, 1199]])


def test_block_store_layout_validation():
    with pytest.raises(ValueError, match="multiple"):
        BlockStore.from_binned(np.zeros((600, 2), np.uint8), block_rows=100)
    w = BlockStore.writer(block_rows=256)
    w.append(np.zeros((300, 3), np.uint8))
    with pytest.raises(ValueError, match="feature"):
        w.append(np.zeros((10, 4), np.uint8))
    with pytest.raises(ValueError, match="dtype"):
        w.append(np.zeros((10, 3), np.uint16))


# ------------------------------------------------------------ time budgets

def test_stream_prefetch_budget_passes():
    for r in check_stream_budgets():
        assert r["ok"], r
    t = stream_prefetch_time()
    # double-buffering hides all but the first transfer: 1 - 1/K at the
    # compute-bound reference shape
    assert t["hidden_frac"] >= 0.60
    assert t["compute_bound"]


# ------------------------------------------------------------------- GOSS

def test_streamed_goss_trains_and_shrinks_transfer():
    n, f = 4096, 10
    X, y = _problem(n, f, seed=3)
    params = dict(objective="binary", num_leaves=15, learning_rate=0.15,
                  max_bin=63, verbose=-1, seed=7, boosting="goss",
                  top_rate=0.2, other_rate=0.1, stream_block_rows=512)
    ds = Dataset.from_blocks(
        [(X[lo:lo + 512], y[lo:lo + 512]) for lo in range(0, n, 512)],
        params=dict(params))
    bst = lgb.Booster(params, ds)
    for _ in range(5):
        bst.update()
    streamed = ds.block_store.bytes_streamed
    # GOSS-at-the-source: only the sampled rows cross PCIe for TRAINING.
    # Each round still streams the store once for the whole-dataset pred
    # update (unavoidable — every row's score moves); the tree-growing
    # gather on top of that must be the sampled ~0.3n rows, not another
    # full pass (a strict grower would re-stream the store per split).
    store_bytes = sum(b.nbytes for b in ds.block_store.blocks)
    gather_bytes = streamed - 5 * store_bytes
    assert 0 < gather_bytes < 5 * 0.35 * store_bytes
    p = bst.predict(X)
    auc_rank = np.argsort(np.argsort(p))
    auc = ((auc_rank[y > 0].sum() - (y > 0).sum() * ((y > 0).sum() - 1) / 2)
           / max(1, (y > 0).sum() * (y == 0).sum()))
    assert auc > 0.65


# ------------------------------------------------------------ scope guards

def _make_streamed(n=1024, f=5, **params):
    X, y = _problem(n, f)
    blocks = [(X[lo:lo + 512], y[lo:lo + 512]) for lo in range(0, n, 512)]
    p = dict(objective="binary", verbose=-1, stream_block_rows=512)
    p.update(params)
    return lgb.Booster(p, Dataset.from_blocks(blocks, params=dict(p)))


@pytest.mark.parametrize("params", [
    {"linear_tree": True},
    {"extra_trees": True},
    {"monotone_constraints": [1, 0, 0, 0, 0]},
    {"boosting": "dart"},
    {"feature_fraction_bynode": 0.5},
], ids=["linear_tree", "extra_trees", "mono", "dart", "ff_bynode"])
def test_streamed_scope_rejections(params):
    with pytest.raises(ValueError, match="streamed"):
        _make_streamed(**params)


def test_streamed_tree_learner_falls_back_to_serial():
    # r19: 'data' now routes to the real streamed-dp composition — only
    # the learners the block loop can't express fall back (with a
    # warning), and the dp route carries no warning at all
    with pytest.warns(UserWarning, match="serial"):
        bst = _make_streamed(tree_learner="feature")
    bst.update()     # trains fine on the serial path
    assert len(bst.trees) == 1
    assert not getattr(bst, "_stream_dp", False)


def test_streamed_data_learner_routes_to_dp():
    bst = _make_streamed(tree_learner="data")
    assert getattr(bst, "_stream_dp", False)
    bst.update()
    assert len(bst.trees) == 1


def test_streamed_valid_set_rejected():
    bst = _make_streamed()
    X, y = _problem(600, 5, seed=5)
    blocks = [(X[:512], y[:512]), (X[512:], y[512:])]
    vs = Dataset.from_blocks(blocks, params={"stream_block_rows": 512})
    with pytest.raises(ValueError, match="streamed"):
        bst.add_valid(vs, "v0")

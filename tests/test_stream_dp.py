"""Streamed × data-parallel composition tests (ISSUE r19).

Parity contract (PARITY.md): merged histogram MULTISETS are identical
across device counts, but f32 summation GROUPING changes with D and the
merge topology, so

* where every histogram sum is exact in f32 — the dyadic tier below:
  L2 objective, labels on the half-integer grid with an exact mean —
  streamed-dp training is **bit-identical** (``np.array_equal`` on trees
  AND predictions) to in-memory single-chip f32, any merge mode, any D;
* on general data, streamed-dp matches the established dp bar: split
  structure and row routing ``np.array_equal``, leaf values / preds to
  f32 rounding (rtol 1e-5 / atol 1e-6).  int8/bf16 wire is
  tolerance-gated by contract and never bit-claimed.

Elastic resume (r13 × r19): a checkpoint written at D=8 restores
bit-identically at any divisor/multiple D (reshard-on-load nests shard
boundaries); incompatible topologies reject with a typed
``IncompatibleCheckpointError`` naming the field, never a shape error
mid-round.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis.budgets import (check_stream_dp_budgets,
                                           stream_dp_bytes_model,
                                           stream_dp_time_model,
                                           stream_prefetch_time)
from lightgbm_tpu.data.block_store import BlockStore, shard_block_store
from lightgbm_tpu.dataset import Dataset
from lightgbm_tpu.faults import StreamScopeError
from lightgbm_tpu.training.checkpoint import (IncompatibleCheckpointError,
                                              resume_booster)

BASE = dict(objective="l2", num_leaves=15, learning_rate=0.5,
            min_data_in_leaf=5, max_bin=63, verbose=-1, seed=7,
            deterministic=True)


def _dyadic_problem(n, f, seed=0):
    """Labels whose per-leaf gradient sums are EXACT in f32: y in {0,1}
    with exactly n/2 ones, so init=0.5 and round-1 gradients are ±0.5 —
    every histogram partial sum is exact regardless of summation order,
    making round-1 trees bit-identical across D and merge topology."""
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, f)).astype(np.float32)
    w = rng.normal(0, 1, f)
    logits = (X @ w) + 0.6 * np.sin(X[:, 0] * 2)
    order = np.argsort(logits)
    y = np.zeros(n, np.float32)
    y[order[n // 2:]] = 1.0          # exactly n//2 ones (n is even)
    return X, y


def _general_problem(n, f, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, f)).astype(np.float32)
    w = rng.normal(0, 1, f)
    y = ((X @ w) * 0.7 + 0.3 * np.sin(X[:, 0] * 2)
         + 0.1 * rng.normal(size=n)).astype(np.float32)
    return X, y


def _blocks(X, y, block_rows):
    return [(X[lo:lo + block_rows], y[lo:lo + block_rows])
            for lo in range(0, len(X), block_rows)]


def _trees_equal(a, b):
    if len(a.trees) != len(b.trees):
        return False
    for ta, tb in zip(a.trees, b.trees):
        for field in ("split_feature", "split_bin", "left", "right",
                      "leaf_value", "is_leaf"):
            if not np.array_equal(np.asarray(getattr(ta, field)),
                                  np.asarray(getattr(tb, field))):
                return False
    return True


def _trees_structure_close(a, b, rtol=1e-5, atol=1e-6):
    assert len(a.trees) == len(b.trees)
    for k, (ta, tb) in enumerate(zip(a.trees, b.trees)):
        for field in ("split_feature", "split_bin", "left", "right",
                      "is_leaf"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ta, field)),
                np.asarray(getattr(tb, field)),
                err_msg=f"tree {k} field {field}")
        np.testing.assert_allclose(
            np.asarray(ta.leaf_value), np.asarray(tb.leaf_value),
            rtol=rtol, atol=atol, err_msg=f"tree {k} leaf_value")


def _train_pair(X, y, block_rows, extra, rounds):
    """In-memory single-chip vs streamed-dp boosters on the same data."""
    p_mem = dict(BASE, row_chunk=block_rows, **extra)
    p_mem.pop("histogram_merge", None)
    p_mem.pop("histogram_wire", None)
    mem = lgb.Booster(p_mem, Dataset(X, label=y, params=dict(p_mem)))
    p_dp = dict(BASE, tree_learner="data", stream_block_rows=block_rows,
                **extra)
    dp = lgb.Booster(
        p_dp, Dataset.from_blocks(_blocks(X, y, block_rows),
                                  params=dict(p_dp)))
    assert getattr(dp, "_stream_dp", False), "dp routing did not engage"
    for _ in range(rounds):
        mem.update()
        dp.update()
    return mem, dp


# -- the composition parity matrix (acceptance #3) -----------------------

MATRIX = [
    # (features, n, block_rows) — 8-block stores shard 1 block/device,
    # 16-block stores 2 blocks/device; ragged n exercises tail padding
    (5, 1800, 256),       # 8 blocks (ragged 24-row tail), K_local=1
    (13, 3996, 256),      # 16 blocks (ragged 156-row tail), K_local=2
    (136, 2048, 256),     # wide Higgs/MSLR regime, 8 blocks, K_local=1
]
GROWERS = [("strict", {}), ("wave", {"wave_width": 4})]


@pytest.mark.parametrize("gname,gextra", GROWERS,
                         ids=[g[0] for g in GROWERS])
@pytest.mark.parametrize("f,n,block_rows",
                         MATRIX, ids=["f5", "f13x2blk", "f136"])
def test_stream_dp_bit_identical_where_exact(gname, gextra, f, n,
                                             block_rows):
    """Dyadic tier: one round, every histogram sum exact -> full
    bitwise parity (trees AND predictions) vs in-memory single chip."""
    X, y = _dyadic_problem(n, f)
    mem, dp = _train_pair(X, y, block_rows, gextra, rounds=1)
    assert _trees_equal(mem, dp)
    assert np.array_equal(np.asarray(mem.predict(X)),
                          np.asarray(dp.predict(X)))


@pytest.fixture(scope="module")
def _general_mem():
    """One in-memory reference training shared by both merge modes."""
    X, y = _general_problem(3996, 13)
    p = dict(BASE, row_chunk=256)
    mem = lgb.Booster(p, Dataset(X, label=y, params=dict(p)))
    for _ in range(3):
        mem.update()
    return X, y, mem


@pytest.mark.parametrize("merge", ["psum", "reduce_scatter_pipelined"])
def test_stream_dp_general_data_dp_parity_bar(merge, _general_mem):
    """General data, multi-round: structure/routing exact, leaves to f32
    rounding — the same bar the in-memory dp learners hold."""
    X, y, mem = _general_mem
    p = dict(BASE, tree_learner="data", stream_block_rows=256,
             histogram_merge=merge)
    dp = lgb.Booster(p, Dataset.from_blocks(_blocks(X, y, 256),
                                            params=dict(p)))
    assert dp._stream_dp
    for _ in range(3):
        dp.update()
    _trees_structure_close(mem, dp)
    np.testing.assert_allclose(np.asarray(mem.predict(X)),
                               np.asarray(dp.predict(X)),
                               rtol=1e-5, atol=1e-6)


def test_stream_dp_shards_and_odometers():
    """Per-shard stores split the block walk; each shard's PCIe odometer
    counts only its own range and the parent rolls them up.  (Same
    n/F/block shape as the f13 matrix entry — reuses its compiles.)"""
    X, y = _general_problem(3996, 13)
    p = dict(BASE, tree_learner="data", stream_block_rows=256)
    ds = Dataset.from_blocks(_blocks(X, y, 256), params=dict(p))
    b = lgb.Booster(p, ds)
    shards = b._stream_shards
    assert len(shards) == 8 and all(s.num_blocks == 2 for s in shards)
    b.update()
    per_shard = [s.bytes_streamed for s in shards]
    assert all(v > 0 for v in per_shard)
    assert len(set(per_shard)) == 1          # equal ranges, equal bytes
    assert ds.block_store.bytes_streamed == sum(per_shard)


def test_stream_dp_goss_int8_compounds():
    """GOSS-at-the-source × int8 wire: sampled per-shard gathers move
    far fewer PCIe bytes than a full pass, in the same round the ring
    hops carry int8 — and the trained model stays sane."""
    X, y = _general_problem(3996, 13)
    p = dict(BASE, tree_learner="data", stream_block_rows=256,
             boosting="goss", top_rate=0.1, other_rate=0.1,
             histogram_wire="int8", learning_rate=0.1)
    ds = Dataset.from_blocks(_blocks(X, y, 256), params=dict(p))
    b = lgb.Booster(p, ds)
    assert b._stream_dp
    full_pass = sum(blk.nbytes for s in b._stream_shards
                    for blk in s.blocks)
    before = [s.bytes_streamed for s in b._stream_shards]
    b.update()
    after = [s.bytes_streamed for s in b._stream_shards]
    # per round each shard moves: one full-store predict pass (every
    # row's score moves) + the sampled gather, which must be the ~20%
    # sampled rows rather than a second full pass
    gather = [a - bb for a, bb in zip(after, before)]
    assert all(full_pass / 8 < g < 1.5 * full_pass / 8 for g in gather)
    pred = np.asarray(b.predict(X))
    assert np.isfinite(pred).all() and pred.std() > 0


# -- elastic resume (acceptance #4) --------------------------------------


def _ckpt_run(rounds_pre=2, rounds_post=3, n=3996, f=13):
    X, y = _dyadic_problem(n, f)
    p = dict(BASE, tree_learner="data", stream_block_rows=256,
             learning_rate=0.5)
    ds = Dataset.from_blocks(_blocks(X, y, 256), params=dict(p))
    b = lgb.Booster(p, ds)
    assert b._dp_mesh.devices.size == 8
    for _ in range(rounds_pre):
        b.update()
    arrays, meta = b.checkpoint_state()
    for _ in range(rounds_post):
        b.update()
    return X, y, p, b, arrays, meta


def test_elastic_resume_same_d_bit_identical():
    X, y, p, b8, arrays, meta = _ckpt_run()
    ds = Dataset.from_blocks(_blocks(X, y, 256), params=dict(p))
    br = resume_booster((arrays, meta), ds)
    assert br._dp_mesh.devices.size == 8
    for _ in range(3):
        br.update()
    assert _trees_equal(b8, br)
    assert np.array_equal(np.asarray(b8.predict(X)),
                          np.asarray(br.predict(X)))


def test_elastic_resume_d8_to_d4():
    """Kill at D=8, resume on a 4-device fleet: restored state and the
    first post-resume tree (dyadic-exact sums) are bit-identical to the
    D=8 continuation; the full continued run holds the dp parity bar."""
    X, y, p, b8, arrays, meta = _ckpt_run(rounds_pre=2, rounds_post=1)
    meta4 = dict(meta, params=dict(meta["params"], stream_dp_devices=4))
    ds = Dataset.from_blocks(_blocks(X, y, 256), params=dict(p))
    b4 = resume_booster((arrays, meta4), ds)
    assert b4._dp_mesh.devices.size == 4
    # restored forest is the writer's, bit for bit
    assert len(b4.trees) == 2
    for ta, tb in zip(b4.trees, b8.trees):
        assert np.array_equal(np.asarray(ta.leaf_value),
                              np.asarray(tb.leaf_value))
    b4.update()
    # round 3's gradients are NOT on the dyadic grid (leaf quotients),
    # so cross-D equality holds on structure + f32-rounded leaves
    _trees_structure_close(b8, b4)
    np.testing.assert_allclose(np.asarray(b8.predict(X)),
                               np.asarray(b4.predict(X)),
                               rtol=1e-5, atol=1e-6)


def test_elastic_resume_first_round_bit_identical_across_d():
    """Checkpoint BEFORE any round, resume at D=8 and at D=4: round 1's
    histogram sums are dyadic-exact, so the two continuations grow a
    bit-identical first tree — the 'bit-identical where comparable'
    elastic guarantee."""
    X, y = _dyadic_problem(3996, 13)
    p = dict(BASE, tree_learner="data", stream_block_rows=256)
    ds = Dataset.from_blocks(_blocks(X, y, 256), params=dict(p))
    b = lgb.Booster(p, ds)
    arrays, meta = b.checkpoint_state()
    outs = []
    for d in (8, 4):
        m = dict(meta, params=dict(meta["params"], stream_dp_devices=d))
        dsr = Dataset.from_blocks(_blocks(X, y, 256), params=dict(p))
        br = resume_booster((arrays, m), dsr)
        assert br._dp_mesh.devices.size == d
        br.update()
        outs.append(br)
    assert _trees_equal(outs[0], outs[1])
    assert np.array_equal(np.asarray(outs[0].predict(X)),
                          np.asarray(outs[1].predict(X)))


@pytest.fixture(scope="module")
def _reject_ckpt():
    """One 1-round D=8 checkpoint shared by the typed-rejection tests
    (each doctors its own copy of the meta; arrays are read-only)."""
    return _ckpt_run(rounds_pre=1, rounds_post=0)


def test_elastic_resume_rejects_foreign_device_count(_reject_ckpt):
    X, y, p, _, arrays, meta = _reject_ckpt
    meta_f = dict(meta, parallel=dict(meta["parallel"], n_devices=3))
    ds = Dataset.from_blocks(_blocks(X, y, 256), params=dict(p))
    with pytest.raises(IncompatibleCheckpointError) as ei:
        resume_booster((arrays, meta_f), ds)
    assert ei.value.field == "n_devices"
    assert "n_devices" in str(ei.value)


def test_elastic_resume_rejects_non_divisible_reshard(_reject_ckpt):
    X, y, p, _, arrays, meta = _reject_ckpt
    # resume run resolves D=8 from the mesh; a writer at D=6 neither
    # divides nor is divided by it
    meta_nd = dict(meta, parallel=dict(meta["parallel"], n_devices=6))
    ds = Dataset.from_blocks(_blocks(X, y, 256), params=dict(p))
    with pytest.raises(IncompatibleCheckpointError) as ei:
        resume_booster((arrays, meta_nd), ds)
    assert ei.value.field == "n_devices"


def test_elastic_resume_rejects_merge_mode_mismatch(_reject_ckpt):
    X, y, p, _, arrays, meta = _reject_ckpt
    assert meta["parallel"]["merge_mode"] == "reduce_scatter_pipelined"
    ds = Dataset.from_blocks(_blocks(X, y, 256), params=dict(p))
    with pytest.raises(IncompatibleCheckpointError) as ei:
        resume_booster((arrays, meta), ds,
                       params=dict(p, histogram_merge="psum"))
    assert ei.value.field == "merge_mode"


# -- typed scope fences (satellite) --------------------------------------


@pytest.mark.parametrize("extra,key", [
    (dict(boosting="dart"), "boosting"),
    (dict(extra_trees=True), "extra_trees"),
    (dict(feature_fraction_bynode=0.5), "feature_fraction_bynode"),
    (dict(linear_tree=True), "linear_tree"),
])
def test_streamed_scope_errors_name_the_key(extra, key):
    X, y = _general_problem(600, 5)
    p = dict(BASE, stream_block_rows=256, **extra)
    ds = Dataset.from_blocks(_blocks(X, y, 256), params=dict(p))
    with pytest.raises(StreamScopeError) as ei:
        lgb.Booster(p, ds)
    assert ei.value.key == key
    assert key in str(ei.value)


def test_stream_dp_rejects_voting_merge_typed():
    X, y = _general_problem(2048, 5)
    p = dict(BASE, tree_learner="data", stream_block_rows=256,
             histogram_merge="voting")
    ds = Dataset.from_blocks(_blocks(X, y, 256), params=dict(p))
    with pytest.raises(StreamScopeError) as ei:
        lgb.Booster(p, ds)
    assert ei.value.key == "histogram_merge"


def test_stream_dp_single_block_falls_back_serial():
    # serial-path trainability is test_streaming.py's job; here we pin
    # only the routing: 1 block admits no >1-device lockstep split
    X, y = _general_problem(500, 5)
    p = dict(BASE, tree_learner="data", stream_block_rows=512)
    ds = Dataset.from_blocks(_blocks(X, y, 512), params=dict(p))
    with pytest.warns(UserWarning, match="lockstep"):
        b = lgb.Booster(p, ds)
    assert not getattr(b, "_stream_dp", False)
    assert b._streamed


# -- shard_block_store / prefetch depth (satellites) ---------------------


def test_shard_block_store_contract():
    codes = np.arange(8 * 256 * 3, dtype=np.uint8).reshape(-1, 3) % 250
    store = BlockStore.from_binned(codes, 256)
    shards = shard_block_store(store, 4)
    assert [s.num_blocks for s in shards] == [2, 2, 2, 2]
    assert sum(s.num_rows for s in shards) == store.num_rows
    got = np.concatenate([np.asarray(b) for s in shards
                          for _, b in s.device_blocks()])
    assert np.array_equal(got, np.concatenate(
        [np.asarray(b) for b in store.blocks]))
    with pytest.raises(ValueError, match="shard"):
        shard_block_store(store, 3)


def test_block_store_prefetch_depth():
    codes = np.arange(6 * 256 * 2, dtype=np.uint8).reshape(-1, 2) % 250
    store = BlockStore.from_binned(codes, 256)
    with pytest.raises(ValueError, match="prefetch"):
        list(store.device_blocks(prefetch_blocks=0))
    store.prefetch_blocks = 3
    offs = [off for off, _ in store.device_blocks()]
    assert offs == [0, 256, 512, 768, 1024, 1280]
    assert store.bytes_streamed == sum(b.nbytes for b in store.blocks)


def test_stream_prefetch_blocks_param_threads_to_store():
    X, y = _general_problem(600, 5)
    p = dict(BASE, stream_block_rows=256, stream_prefetch_blocks=2)
    ds = Dataset.from_blocks(_blocks(X, y, 256), params=dict(p))
    lgb.Booster(p, ds)
    assert ds.block_store.prefetch_blocks == 2


# -- budget models (satellite) -------------------------------------------


def test_stream_dp_budgets_green():
    res = check_stream_dp_budgets()
    assert {r["name"] for r in res} >= {
        "stream_dp_merge_hidden_ref", "stream_dp_goss_int8_bytes_ref"}
    for r in res:
        assert r["ok"], r


def test_stream_dp_time_model_reference_point():
    t = stream_dp_time_model()
    assert t["merge_hidden_frac"] >= 0.60
    assert t["compute_bound"]
    # deeper prefetch never hurts the composed model either
    deep = stream_dp_time_model(prefetch_blocks=2)
    assert deep["merge_hidden_frac"] >= 0.60


def test_stream_dp_bytes_model_compounds():
    m = stream_dp_bytes_model()
    assert m["reduction_factor"] >= 4.0
    # the reductions act on different links: each factor alone is
    # smaller than their compound
    assert m["reduction_factor"] > min(m["pcie_factor"], m["ici_factor"])
    f32 = stream_dp_bytes_model(wire_dtype="f32", top_rate=1.0,
                                other_rate=0.0)
    assert abs(f32["reduction_factor"] - 1.0) < 1e-9


def test_stream_prefetch_depth_model_monotone():
    shallow = stream_prefetch_time(prefetch_blocks=1)
    deep = stream_prefetch_time(prefetch_blocks=2)
    assert deep["hidden_frac"] >= 0.60
    assert deep["transfer_ms"] <= shallow["transfer_ms"]

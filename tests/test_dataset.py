"""Binner + Dataset container tests."""

import numpy as np
import pytest

from lightgbm_tpu.dataset import BinMapper, Dataset, ROW_PAD_MULTIPLE


def test_binner_few_distinct_values_get_own_bins():
    X = np.array([[1.0], [2.0], [2.0], [3.0], [1.0]])
    bm = BinMapper.fit(X, max_bin=255, min_data_in_bin=1)
    codes = bm.transform(X)
    assert codes[:, 0].tolist() == [0, 1, 1, 2, 0]
    assert bm.n_bins[0] == 3


def test_binner_min_data_in_bin_merges_sparse_values():
    # 3 distinct values with counts 5/1/5: the middle singleton cannot hold
    # its own bin at min_data_in_bin=3 (LightGBM GreedyFindBin behavior)
    X = np.array([[1.0]] * 5 + [[2.0]] + [[3.0]] * 5)
    bm = BinMapper.fit(X, max_bin=255, min_data_in_bin=3)
    codes = bm.transform(X)
    assert bm.n_bins[0] == 2
    assert codes[0, 0] != codes[-1, 0]


def test_binner_quantile_mode_monotone():
    rng = np.random.default_rng(0)
    X = rng.normal(0, 1, (10000, 1))
    bm = BinMapper.fit(X, max_bin=16)
    codes = bm.transform(X)
    assert codes.max() <= 15
    # monotone: larger raw value -> bin code >= smaller's
    order = np.argsort(X[:, 0])
    assert (np.diff(codes[order, 0].astype(int)) >= 0).all()
    # roughly equal-frequency bins
    counts = np.bincount(codes[:, 0], minlength=16)
    assert counts.min() > 10000 / 16 * 0.5


def test_binner_nan_gets_dedicated_bin():
    X = np.array([[1.0], [np.nan], [2.0], [3.0]])
    bm = BinMapper.fit(X, max_bin=255, min_data_in_bin=1)
    codes = bm.transform(X)
    assert codes[1, 0] == bm.nan_bin[0]
    assert codes[1, 0] == bm.n_bins[0] - 1


def test_binner_reused_for_valid_data():
    rng = np.random.default_rng(1)
    X = rng.normal(0, 1, (5000, 3))
    bm = BinMapper.fit(X, max_bin=64)
    X2 = rng.normal(0, 1, (100, 3))
    codes = bm.transform(X2)
    # out-of-range values clamp to edge bins
    lo = np.full((1, 3), -100.0)
    hi = np.full((1, 3), 100.0)
    assert (bm.transform(lo) == 0).all()
    assert (bm.transform(hi) == bm.n_bins - 1 - (bm.nan_bin >= 0)).all()


def test_dataset_construct_pads_rows():
    rng = np.random.default_rng(2)
    X = rng.normal(0, 1, (1000, 4))
    y = rng.normal(0, 1, 1000)
    ds = Dataset(X, label=y).construct()
    assert ds.num_data() == 1000
    assert ds.X_binned.shape[0] % ROW_PAD_MULTIPLE == 0
    assert float(ds.row_mask.sum()) == 1000
    assert float(ds.w[1000:].sum()) == 0.0


def test_dataset_reference_shares_bin_mapper():
    rng = np.random.default_rng(3)
    X = rng.normal(0, 1, (500, 2))
    y = rng.normal(0, 1, 500)
    dtrain = Dataset(X, label=y).construct()
    dvalid = Dataset(rng.normal(0, 1, (100, 2)), label=rng.normal(0, 1, 100),
                     reference=dtrain).construct()
    assert dvalid.bin_mapper is dtrain.bin_mapper


def test_dataset_subset():
    rng = np.random.default_rng(4)
    X = rng.normal(0, 1, (800, 3))
    y = rng.normal(0, 1, 800)
    ds = Dataset(X, label=y).construct()
    sub = ds.subset(np.arange(100))
    assert sub.num_data() == 100
    assert sub.bin_mapper is ds.bin_mapper
    np.testing.assert_allclose(sub.get_label(), y[:100])


def test_dataset_pandas_feature_names():
    pd = pytest.importorskip("pandas")
    df = pd.DataFrame({"a": [1.0, 2, 3, 4], "b": [4.0, 3, 2, 1]})
    ds = Dataset(df, label=[1.0, 2, 3, 4]).construct()
    assert ds.feature_names == ["a", "b"]


def test_categorical_binning():
    X = np.array([[0.0], [1.0], [2.0], [2.0], [7.0]])
    bm = BinMapper.fit(X, max_bin=255, categorical=[0])
    codes = bm.transform(X)
    assert codes[:, 0].tolist() == [0, 1, 2, 2, 3]
    assert bm.is_categorical[0]

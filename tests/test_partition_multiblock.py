"""Multi-feature-block partition fusion (r7): interpret-mode parity of
routing codes and histograms vs the unfused semantics at F=136-style
shapes — the MSLR class the r5 single-block kernel gated off.

Stats are small integers so the kernel's bf16 operand rounding is exact
and the reference histogram can be computed in plain f32.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.models.tree import grow_tree
from lightgbm_tpu.ops.histogram_pallas import (_vmem_blocking,
                                               hist_partition_fused_pallas,
                                               prepare_wave_operands)
from lightgbm_tpu.ops.split import SplitContext

F, B, W = 136, 256, 4
S = 3


def _wave_case(rng, n, wfeat, wthr=None, wdl=None):
    """Synthetic wave state: rows live in leaves 0..W+1; leaves 0..W-1
    split this wave (wave rank == leaf id), the rest stay put."""
    bins = rng.randint(0, B, size=(n, F)).astype(np.int32)
    g = rng.randint(-4, 5, size=n).astype(np.float32)
    stats = np.stack([g, np.ones(n, np.float32), np.ones(n, np.float32)], -1)
    leaf = rng.randint(0, W + 2, size=n)
    wthr = rng.randint(0, B, size=W) if wthr is None else wthr
    wdl = rng.randint(0, 2, size=W).astype(bool) if wdl is None else wdl
    sel = leaf < W
    lf = np.where(sel, leaf, 0)
    pv = np.stack([
        sel.astype(np.float32),
        np.where(sel, wfeat[lf], 0).astype(np.float32),
        np.where(sel, wthr[lf], 0).astype(np.float32),
        np.where(sel, 2 * leaf, 0).astype(np.float32),
        np.where(sel, wdl[lf], 0).astype(np.float32),
        np.zeros(n, np.float32), np.zeros(n, np.float32),
        np.zeros(n, np.float32)])                       # [8, n]
    return bins, stats, leaf, pv, wthr, wdl


def _reference(bins, stats, leaf, wfeat, wthr, wdl):
    """Unfused-path semantics: XLA-side routing + per-direct-child
    histogram accumulation in f32."""
    n = bins.shape[0]
    sel = leaf < W
    lf = np.where(sel, leaf, 0)
    v = bins[np.arange(n), wfeat[lf]]
    go_left = v <= wthr[lf]
    enc = np.where(sel, 2 * leaf + np.where(go_left, 0, 1) + 1, 0)
    to_direct = sel & (go_left == wdl[lf])
    seg = np.where(to_direct, leaf, W)
    hist = np.zeros((W, F, B, S), np.float32)
    for w in range(W):
        rows = np.flatnonzero(seg == w)
        for f in range(F):
            np.add.at(hist[w, f], (bins[rows, f],), stats[rows])
    return hist, enc


def run_fused(bins, stats, pv, wfeat):
    bins_t, stats_t, chunk = prepare_wave_operands(
        jnp.asarray(bins), jnp.asarray(stats), B, W)
    n_pad = bins_t.shape[1]
    pv_t = jnp.asarray(np.pad(pv, ((0, 0), (0, n_pad - pv.shape[1]))))
    hist, enc = jax.jit(lambda: hist_partition_fused_pallas(
        bins_t, stats_t, pv_t, W, B, chunk, hist_dtype="bf16",
        wfeat=jnp.asarray(wfeat, jnp.int32), num_features=F))()
    return np.asarray(hist), np.asarray(enc)[:bins.shape[0]]


def test_shape_actually_blocks():
    # the whole point: this shape must need >1 VMEM feature block
    f_blk, n_fblk, f_pad, _ = _vmem_blocking(F, B, W * S, chunk_align=512)
    assert n_fblk > 1
    assert f_pad > 0          # padded tail block is exercised


def test_hist_and_routing_parity_multiblock():
    rng = np.random.RandomState(0)
    # one split feature inside each of the feature blocks incl. the
    # padded tail block (f_blk=32: blocks are [0,32), ... [128,136)+pad)
    wfeat = np.array([3, 40, 101, 135])
    bins, stats, leaf, pv, wthr, wdl = _wave_case(rng, n=5000, wfeat=wfeat)
    hist_ref, enc_ref = _reference(bins, stats, leaf, wfeat, wthr, wdl)
    hist, enc = run_fused(bins, stats, pv, wfeat)
    np.testing.assert_array_equal(enc, enc_ref)
    np.testing.assert_array_equal(hist, hist_ref)


def test_split_feature_in_every_block_position():
    # routing keyed on wave rank must find the split value no matter
    # which block owns the feature — first/last column of each block
    rng = np.random.RandomState(1)
    for base in (0, 31, 32, 64, 96, 128):
        wfeat = np.minimum(np.array([base, base + 1, base + 2, base + 3]),
                           F - 1)
        bins, stats, leaf, pv, wthr, wdl = _wave_case(rng, n=3000,
                                                      wfeat=wfeat)
        _, enc_ref = _reference(bins, stats, leaf, wfeat, wthr, wdl)
        _, enc = run_fused(bins, stats, pv, wfeat)
        np.testing.assert_array_equal(enc, enc_ref, err_msg=str(base))


def test_tree_parity_f136():
    """End-to-end: the fused frontier grower engages at F=136 and grows
    the same tree as the unfused path."""
    rng = np.random.RandomState(2)
    n = 4000
    bins = jnp.asarray(rng.randint(0, B, size=(n, F)).astype(np.int32))
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    stats = jnp.stack([g, jnp.ones(n, jnp.float32),
                       jnp.ones(n, jnp.float32)], -1)
    fmask = jnp.ones(F, jnp.float32)
    ctx = SplitContext(jnp.float32(0.0), jnp.float32(1.0), jnp.float32(20.0),
                       jnp.float32(1e-3), jnp.float32(0.0))

    def grow(fp):
        return grow_tree(bins, stats, fmask, ctx, 15, B, -1, wave_width=8,
                         hist_impl="pallas", hist_dtype="bf16",
                         fuse_partition=fp)

    tu, ru = jax.jit(lambda: grow(False))()
    tf, rf = jax.jit(lambda: grow(True))()
    np.testing.assert_array_equal(np.asarray(tu.split_feature),
                                  np.asarray(tf.split_feature))
    np.testing.assert_array_equal(np.asarray(tu.split_bin),
                                  np.asarray(tf.split_bin))
    np.testing.assert_array_equal(np.asarray(ru), np.asarray(rf))
    np.testing.assert_allclose(np.asarray(tu.leaf_value),
                               np.asarray(tf.leaf_value),
                               rtol=1e-5, atol=1e-6)
